"""Public entry points — the interface_quda.cpp analog.

Mirrors the C API surface (include/quda.h): init_quda / load_gauge_quda /
invert_quda / invert_multishift_quda / eigensolve_quda / dslash_quda /
mat_quda / plaq_quda / gauss_gauge_quda / perform_gauge_smear_quda /
perform_wflow_quda / compute_gauge_fixing_* / compute_ks_link_quda /
compute_gauge_force_quda / update_gauge_field_quda / mom_action_quda /
contract_quda, with resident-field state (make_resident_gauge) kept in a
module-level context the way interface_quda.cpp keeps gaugePrecise etc.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..fields.geometry import EVEN, ODD, LatticeGeometry
from ..fields.spinor import even_odd_join, even_odd_split
from ..ops import blas
from ..utils import logging as qlog
from ..utils.precision import complex_dtype
from .params import EigParamAPI, GaugeParam, InvertParam, MultigridParamAPI

_ctx = {
    "initialized": False,
    "geom": None,
    "gauge": None,          # resident gauge (4,T,Z,Y,X,3,3)
    "gauge_param": None,
    "fat": None,
    "long": None,
    "mg": None,
    "mg_epoch": -1,         # gauge_epoch the resident MG was built against
    "gauge_epoch": 0,       # bumped whenever the resident gauge changes
}


def init_quda(device: int = 0):
    """initQuda analog (device selection is PJRT's job on TPU)."""
    from ..obs import metrics as omet
    from ..obs import trace as otr
    from ..utils import config as qconf
    from ..utils import monitor as qmon
    from ..utils import tune as qtune
    qconf.check_environment()  # warn on typoed / CUDA-era env knobs
    qmon.start_default()       # QUDA_TPU_ENABLE_MONITOR sampling thread
    otr.maybe_start()          # QUDA_TPU_TRACE span/event session
    omet.maybe_start()         # QUDA_TPU_METRICS counter/gauge registry
    from ..obs import comms as ocomms
    ocomms.maybe_start()       # ICI comms ledger (rides both knobs)
    from ..obs import flight as ofl
    from ..obs import live as olive
    from ..obs import postmortem as opm
    ofl.maybe_start()          # QUDA_TPU_FLIGHT black-box ring buffer
    olive.maybe_start()        # QUDA_TPU_LIVE telemetry HTTP plane
    opm.reset_session()        # fresh postmortem bundle index
    # warm-start the chip-keyed tuner cache (tune.cpp persistent-cache
    # behavior): a fresh worker with a shared QUDA_TPU_RESOURCE_PATH
    # serves its first solve from already-raced (platform, volume,
    # form) winners — zero re-races, and the load is mirrored as a
    # tune_cache_loaded trace event (after maybe_start, so it lands in
    # the session)
    usable = qtune.warm_start()
    if usable:
        qlog.printq(f"tuner warm cache: {usable} entries usable on "
                    f"{qtune.platform_key()}", qlog.VERBOSE)
    _ctx["initialized"] = True
    qlog.printq("initialized", qlog.VERBOSE)


def _packed_enabled(on_tpu: bool) -> bool:
    """QUDA_TPU_PACKED override, else the platform default (packed
    device order on TPU)."""
    from ..utils import config as qconf
    v = qconf.get("QUDA_TPU_PACKED", fresh=True)
    return on_tpu if v == "" else v == "1"


def _pallas_enabled(on_tpu: bool) -> bool:
    """QUDA_TPU_PALLAS override, else pallas on real TPU."""
    from ..utils import config as qconf
    v = qconf.get("QUDA_TPU_PALLAS", fresh=True)
    return on_tpu if v == "" else v == "1"


def _pallas_interpret(on_tpu: bool) -> bool:
    """Interpret-mode pallas off-TPU: forcing QUDA_TPU_PALLAS=1 on a CPU
    host (CI, the kernel-in-solver routing tests) runs the SAME kernels
    through the pallas interpreter instead of failing to lower."""
    return not on_tpu


def end_quda():
    # gauge_epoch stays MONOTONE across re-initialisation: resident
    # caches elsewhere (interfaces/milc.py) key on it, and a reset would
    # let a post-reinit epoch collide with a pre-reset one, reviving
    # stale operators built against the old gauge.
    keep_epoch = _ctx["gauge_epoch"]
    for k in list(_ctx):
        _ctx[k] = None if k != "initialized" else False
    _ctx["gauge_epoch"] = keep_epoch
    _ctx["mg_epoch"] = -1
    # shutdown telemetry flush (endQuda summary semantics): the timer
    # summary + profile.tsv, the tuner's profiler half (profile_0.tsv),
    # the roofline rows, the metrics export + fleet report, the flight
    # recorder's black-box tail, and the trace session artifacts.
    # Every step runs even when an earlier one raises (a broken
    # profile writer must not eat the trace of the crashed session it
    # would explain) — the first error is re-raised AFTER the epilogue
    # completes.  Everything flushed is indexed (name -> path + size +
    # the session knob snapshot) into artifacts_manifest.json — the
    # ONE file an operator or CI collects to find every artifact,
    # postmortem bundles included.
    from ..obs import comms as ocomms
    from ..obs import costmodel as ocost
    from ..obs import flight as ofl
    from ..obs import live as olive
    from ..obs import memory as omem
    from ..obs import metrics as omet
    from ..obs import postmortem as opm
    from ..obs import roofline as orf
    from ..obs import trace as otr
    from ..utils import monitor as qmon
    from ..utils import tune as qtune
    from ..utils.timer import print_summary

    artifacts: dict = {}

    def _flush_metrics():
        try:
            paths = omet.stop()
            if paths:
                artifacts["metrics.prom"] = paths["prom"]
                artifacts["metrics.tsv"] = paths["tsv"]
                artifacts["fleet_report.txt"] = paths["report"]
                qlog.printq(f"metrics artifacts: {paths['prom']} / "
                            f"{paths['report']}", qlog.SUMMARIZE)
        finally:
            # the ledger follows the resident fields _ctx drops — even
            # when the flush raised (unwritable path), or the next
            # session would report this one's fields as still resident
            omem.reset()

    def _flush_flight():
        # before the trace flush: a wrapped ring emits flight_dropped,
        # which must land in the trace artifact it explains
        paths = ofl.stop()
        if paths:
            artifacts["flight.jsonl"] = paths["flight"]
            qlog.printq(f"flight recorder: {paths['flight']} "
                        f"({paths['events']} events, "
                        f"{paths['dropped']} dropped)", qlog.SUMMARIZE)

    def _flush_trace():
        paths = otr.stop()
        if paths:
            artifacts["trace.json"] = paths["chrome"]
            artifacts["trace_events.jsonl"] = paths["jsonl"]
            qlog.printq(f"trace artifacts: {paths['chrome']} / "
                        f"{paths['jsonl']}", qlog.SUMMARIZE)

    def _save_tune_profile():
        artifacts["profile_0.tsv"] = qtune.save_profile()

    def _save_roofline():
        # dumps the ICI ledger rows alongside
        artifacts["roofline.tsv"] = orf.save()

    def _save_cost_report():
        # cost_drift.tsv for noted compiles
        artifacts["cost_drift.tsv"] = ocost.save_report()

    errors = []
    # olive.stop FIRST: the scrape plane reads every other leg's live
    # session — it must be down before those sessions close, or a
    # mid-teardown scrape races the flushes below
    for step in (olive.stop,
                 qmon.stop_default, print_summary, _save_tune_profile,
                 _save_roofline,
                 orf.reset,  # a later init/end must not re-dump rows
                 _save_cost_report,
                 ocost.reset,
                 ocomms.stop,    # ledger follows the session it served
                 _flush_metrics, _flush_flight, _flush_trace):
        try:
            step()
        except Exception as e:   # noqa: BLE001 — epilogue must finish
            errors.append(e)
    try:
        mpath = opm.write_artifacts_manifest(artifacts)
        if mpath:
            qlog.printq(f"artifacts manifest: {mpath}", qlog.SUMMARIZE)
    except Exception as e:       # noqa: BLE001 — epilogue must finish
        errors.append(e)
    opm.reset_session()
    if errors:
        raise errors[0]


def _require_init():
    if not _ctx["initialized"]:
        qlog.errorq("initQuda has not been called")


def _serve_rid_attrs() -> dict:
    """Request-id span/flight attributes when this API call executes a
    solve-service batch (obs/postmortem.serve_requests scope): the
    comma-joined ticket ids, {} outside the service so non-serve spans
    stay unchanged."""
    from ..obs import postmortem as opm
    rids = opm.current_request_ids()
    return {"request_ids": ",".join(rids)} if rids else {}


def _pm_api(api: str, payload: Optional[str] = None):
    """API-boundary postmortem guard (obs/postmortem.py).

    When failure capture is enabled, enters a solve scope carrying the
    caller's payload field (source/gauge), the param, and the knob
    snapshot as of API entry, and captures any uncaught exception
    crossing this boundary as an ``exception:<type>`` bundle before
    re-raising — unless a more specific trigger (breakdown, verify
    mismatch, gauge rejection, ladder exhaustion) already captured
    inside the call: one failure, one bundle.  Capture disabled = one
    knob read, then the undecorated call — no scope, no try frame
    semantics change, no bundle I/O (the raising-stub pin in
    tests/test_flight.py).  tests/test_flight_lint.py pins that every
    inverting entry point carries this guard and that its except-to-
    status site calls the capture hook."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            from ..obs import postmortem as opm
            if not opm.enabled():
                return fn(*args, **kwargs)
            src = None
            if payload is not None:
                # positional or keyword spelling of the payload (the
                # entry points name it source / sources / gauge) — a
                # keyword-style call must still dump a replayable field
                src = args[0] if args else next(
                    (kwargs[k] for k in ("source", "sources", "gauge")
                     if k in kwargs), None)
            param = (args[1] if len(args) > 1 else
                     kwargs.get("param", kwargs.get("invert_param")))
            with opm.solve_scope(api, param=param, source=src,
                                 source_name=payload or "source"):
                try:
                    return fn(*args, **kwargs)
                except Exception as e:
                    opm.capture_exception(api, e)
                    raise
        return wrapper
    return deco


def _set_resident_gauge(g):
    """Every resident-gauge mutation goes through here so the MG
    staleness guard (gauge_epoch) can never miss one — and so the HBM
    ledger re-tracks the resident bytes on every mutation (smear, HMC
    update, gauss) with one row, not a leak."""
    _ctx["gauge"] = g
    _ctx["gauge_epoch"] += 1
    from ..obs import memory as omem
    omem.track("gauge", "resident_gauge", g)


@_pm_api("load_gauge_quda", payload="gauge")
def load_gauge_quda(gauge, param: GaugeParam):
    """loadGaugeQuda: host layout (4,T,Z,Y,X,3,3) -> resident device gauge."""
    _require_init()
    param.validate()
    geom = LatticeGeometry(tuple(param.X))
    dtype = complex_dtype(param.cuda_prec)
    if param.gauge_order != "canonical":
        from ..utils import host_order as ho
        conv = {"qdp": ho.gauge_from_qdp, "milc": ho.gauge_from_milc}
        if param.gauge_order == "cps":
            gauge = ho.gauge_from_cps(gauge, geom, param.anisotropy)
        else:
            gauge = conv[param.gauge_order](gauge, geom)
    g = jnp.asarray(gauge, dtype)
    if g.shape != (4,) + geom.lattice_shape + (3, 3):
        qlog.errorq(f"gauge shape {g.shape} != expected for {param.X}")
    # gauge validation (robust/): a NaN link poisons every subsequent
    # solve on this configuration, so reject non-finite input LOUDLY at
    # the boundary; the fault site lets tests drill the rejection.
    # Runs BEFORE the anisotropy fold — the unitarity screen must see
    # the links as the user supplied them (folded spatial links are
    # legitimately non-unitary)
    from ..obs import trace as otr
    from ..robust import faultinject as finj
    g = finj.maybe_poison_gauge(g)
    if not bool(jnp.all(jnp.isfinite(g))):
        otr.event("gauge_rejected", cat="robust", reason="nonfinite",
                  X=list(param.X))
        # failure capture BEFORE the raise: the bundle dumps the gauge
        # AS REJECTED (fault-poisoned links included) so a replay of
        # the bundle reproduces the rejection from the dump alone
        from ..obs import postmortem as opm
        opm.capture("gauge_rejected", api="load_gauge_quda",
                    fields={"gauge": g},
                    note=f"non-finite links rejected at load, "
                         f"X={list(param.X)}")
        qlog.errorq(
            "load_gauge_quda: non-finite link values in the input "
            "gauge field — rejected (a NaN link silently poisons every "
            "subsequent solve); check the file/transfer and reload")
    from ..utils import config as qconf
    utol = float(qconf.get("QUDA_TPU_GAUGE_UNITARITY_TOL", fresh=True))
    if utol > 0.0:
        from ..ops.su3 import unitarity_deviation
        dev = float(unitarity_deviation(g))
        if dev > utol:
            otr.event("gauge_unitarity", cat="robust", deviation=dev,
                      tol=utol)
            qlog.warningq(
                f"load_gauge_quda: max unitarity deviation {dev:.2e} "
                f"exceeds QUDA_TPU_GAUGE_UNITARITY_TOL={utol:g}; "
                "repair with update_gauge_field_quda's reunitarize "
                "(ops.su3.project_su3) or reload a clean configuration")
    if param.anisotropy != 1.0:
        # QUDA folds the Wilson anisotropy into the links at load time:
        # spatial links are divided by xi (GaugeFieldParam anisotropy)
        scale = jnp.ones((4, 1, 1, 1, 1, 1, 1), g.real.dtype)
        scale = scale.at[:3].set(1.0 / param.anisotropy)
        g = g * scale.astype(dtype)
    _install_resident_gauge(g, param, geom)


def _install_resident_gauge(g, param: GaugeParam, geom: LatticeGeometry):
    """Install an ALREADY converted/validated device gauge as the
    resident one: geometry + param + epoch bump + ledger re-track —
    the residency-manager seam (serve/residency.py) that generalises
    the single ``_ctx['gauge']`` slot to multiple cached gauges
    without re-running load_gauge_quda's host-order conversion and
    input screens on every activation.  ``load_gauge_quda`` itself
    ends here, so single-slot callers (MILC interface included) see
    exactly the pre-round-15 behavior."""
    _ctx["geom"] = geom
    _set_resident_gauge(g)
    _ctx["gauge_param"] = param


def resident_gauge_state():
    """(gauge, gauge_param, geom) of the currently resident gauge —
    how serve/residency adopts a gauge just loaded through
    ``load_gauge_quda`` into its multi-gauge table."""
    return _ctx["gauge"], _ctx["gauge_param"], _ctx["geom"]


def resident_mg_state():
    """The resident MG hierarchy, or None when there is none or it was
    built for a gauge other than the resident one (stale hierarchies
    are never handed to the residency manager — they would be restored
    as 'valid' later).  The serve layer stashes this next to its cached
    gauge so a multi-tenant worker keeps one warm hierarchy PER gauge
    instead of rebuilding on every activation."""
    mg = _ctx.get("mg")
    if mg is None or _ctx.get("mg_epoch") != _ctx.get("gauge_epoch"):
        return None
    return mg


def _install_resident_mg(mg):
    """Adopt a hierarchy known to match the CURRENTLY resident gauge
    (the residency manager's table pairs them): epoch pinned to the
    live gauge epoch + ledger re-track — the MG sibling of
    ``_install_resident_gauge``.  ``mg=None`` clears the slot (the
    ledger row is the caller's to move)."""
    _ctx["mg"] = mg
    if mg is None:
        return
    _ctx["mg_epoch"] = _ctx["gauge_epoch"]
    from ..obs import memory as omem
    omem.track("mg", "hierarchy", mg)


def free_gauge_quda():
    _ctx["gauge"] = None
    from ..obs import memory as omem
    omem.release("gauge", "resident_gauge")


def _antiperiodic():
    return _ctx["gauge_param"].t_boundary == "antiperiodic"


def _build_dirac(p: InvertParam, pc: bool):
    from ..models import clover as mclover
    from ..models import domain_wall as mdw
    from ..models import staggered as mstag
    from ..models import twisted as mtw
    from ..models import wilson as mwil

    geom = _ctx["geom"]
    g = _ctx["gauge"]
    ap = _antiperiodic()
    matpc = EVEN if p.matpc_type == "even-even" else ODD
    t = p.dslash_type
    if t == "wilson":
        return (mwil.DiracWilsonPC(g, geom, p.kappa, ap, matpc) if pc
                else mwil.DiracWilson(g, geom, p.kappa, ap))
    if t == "clover":
        return (mclover.DiracCloverPC(g, geom, p.kappa, p.csw, ap, matpc)
                if pc else mclover.DiracClover(g, geom, p.kappa, p.csw, ap))
    if t == "twisted-mass":
        return (mtw.DiracTwistedMassPC(g, geom, p.kappa, p.mu, ap, matpc)
                if pc else mtw.DiracTwistedMass(g, geom, p.kappa, p.mu, ap))
    if t == "twisted-clover":
        return (mtw.DiracTwistedCloverPC(g, geom, p.kappa, p.mu, p.csw, ap,
                                         matpc) if pc
                else mtw.DiracTwistedClover(g, geom, p.kappa, p.mu, p.csw,
                                            ap))
    if t == "ndeg-twisted-mass":
        return (mtw.DiracNdegTwistedMassPC(g, geom, p.kappa, p.mu,
                                           p.epsilon, ap, matpc)
                if pc else
                mtw.DiracNdegTwistedMass(g, geom, p.kappa, p.mu, p.epsilon,
                                         ap))
    if t == "ndeg-twisted-clover":
        return (mtw.DiracNdegTwistedCloverPC(g, geom, p.kappa, p.mu,
                                             p.epsilon, p.csw, ap, matpc)
                if pc else
                mtw.DiracNdegTwistedClover(g, geom, p.kappa, p.mu,
                                           p.epsilon, p.csw, ap))
    if t in ("staggered", "asqtad", "hisq"):
        improved = t != "staggered"
        fat = _ctx["fat"] if improved else g
        lng = _ctx["long"] if improved else None
        if improved and fat is None:
            qlog.errorq("asqtad/hisq invert requires compute_ks_link_quda "
                        "or load_fat_long_quda first")
        return (mstag.DiracStaggeredPC(fat, geom, p.mass, improved, lng,
                                       matpc, antiperiodic_t=ap) if pc
                else mstag.DiracStaggered(fat, geom, p.mass, improved, lng,
                                          antiperiodic_t=ap))
    if t in ("domain-wall", "domain-wall-4d", "mobius"):
        b5, c5 = (1.0, 0.0) if t != "mobius" else (p.b5, p.c5)
        m5 = -p.m5  # QUDA passes m5 negative
        if pc:
            if t == "domain-wall":
                # QUDA convention: plain "domain-wall" preconditions with
                # the 5-d checkerboard (lib/dirac_domain_wall.cpp:124)
                return mdw.DiracDomainWall5DPC(g, geom, p.Ls, m5, p.mass,
                                               ap, matpc)
            return mdw.DiracMobiusPC(g, geom, p.Ls, m5, p.mass, b5, c5, ap,
                                     matpc)
        return mdw.DiracMobius(g, geom, p.Ls, m5, p.mass, b5, c5, ap)
    if t == "mobius-eofa":
        m5 = -p.m5
        kw = dict(mq1=p.eofa_mq1, mq2=p.eofa_mq2, mq3=p.eofa_mq3,
                  eofa_pm=p.eofa_pm, eofa_shift=p.eofa_shift)
        if pc:
            return mdw.DiracMobiusEofaPC(g, geom, p.Ls, m5, p.mass, p.b5,
                                         p.c5, antiperiodic_t=ap,
                                         matpc=matpc, **kw)
        return mdw.DiracMobiusEofa(g, geom, p.Ls, m5, p.mass, p.b5, p.c5,
                                   antiperiodic_t=ap, **kw)
    if t == "laplace":
        from ..ops.laplace import laplace

        class _Lap:
            def M(self, psi):
                return laplace(g, psi, ndim=p.laplace3D, mass=p.mass)

            Mdag = M

            def MdagM(self, psi):
                return self.M(self.M(psi))

        return _Lap()
    qlog.errorq(f"dslash_type {t} not wired into invert yet")


_DWF_TYPES = ("domain-wall", "domain-wall-4d", "mobius", "mobius-eofa")

# BiCGStab(L) ladder depth — ONE constant shared by the solver call and
# the flops accounting so the two can never desynchronise.
_BICGSTAB_L = 4


def _split(b, p, d=None):
    geom = _ctx["geom"]
    if d is not None and hasattr(d, "split5"):
        return d.split5(b)      # 5d checkerboard (slice-aligned layout)
    if p.dslash_type in _DWF_TYPES:
        be = jax.vmap(lambda v: even_odd_split(v, geom)[0])(b)
        bo = jax.vmap(lambda v: even_odd_split(v, geom)[1])(b)
        return be, bo
    return even_odd_split(b, geom)


def _join(xe, xo, p, d=None):
    geom = _ctx["geom"]
    if d is not None and hasattr(d, "join5"):
        return d.join5(xe, xo)
    if p.dslash_type in _DWF_TYPES:
        return jax.vmap(lambda e, o: even_odd_join(e, o, geom))(xe, xo)
    return even_odd_join(xe, xo, geom)


def _resolve_sloppy(param: InvertParam) -> str:
    """Resolve cuda_prec_sloppy="auto": bf16 ("half") on TPU — where
    "single/single" would never mix and the bf16 HBM/MXU path would go
    unused — and = cuda_prec elsewhere.  Any explicitly pinned value
    (including sloppy == prec for a pure-precision solve) is honored."""
    if param.cuda_prec_sloppy != "auto":
        return param.cuda_prec_sloppy
    from ..utils import config as qconf
    env = qconf.get("QUDA_TPU_SLOPPY_PRECISION", fresh=True)
    if env:
        qlog.printq(f"cuda_prec_sloppy=auto -> {env} "
                    "(QUDA_TPU_SLOPPY_PRECISION)", qlog.VERBOSE)
        return env
    if jax.default_backend() == "tpu":
        qlog.printq("cuda_prec_sloppy=auto -> half (bf16) on TPU",
                    qlog.VERBOSE)
        return "half"
    return param.cuda_prec


def _pair_refined_solve(mv, sys_rhs, dtype, param, inner_solver,
                        max_cycles: int = 10):
    """Shared defect-correction harness for the pair-sloppy bicgstab/gcr
    paths: run the sloppy inner solver per cycle, track TOTAL inner
    iterations (so param.iter_count/gflops reflect real work, not cycle
    count)."""
    from .. import solvers
    inner_iters = []

    def inner(r):
        ri = inner_solver(r)
        inner_iters.append(int(ri.iters))
        return ri.x

    res = solvers.solve_refined(mv, inner, sys_rhs, dtype, tol=param.tol,
                                max_cycles=max_cycles)
    return res._replace(iters=jnp.int32(sum(inner_iters)))


class _StaggeredPairsSolve:
    """Solve-loop adapter presenting DiracStaggeredPCPairs through the
    generic invert flow (prepare/M/reconstruct), so every Krylov iterate
    stays complex-free (pair representation), with the pallas eo stencil
    on real TPU.  The mixed-precision hooks (sloppy/codec) hand back a
    bf16 pair operator + plain-cast codec on the SAME layout."""

    hermitian = True

    def __init__(self, dpc, use_pallas: bool,
                 pallas_interpret: bool = False):
        self._dpc = dpc
        self._pallas_interpret = pallas_interpret
        self.op = dpc.pairs(jnp.float32, use_pallas=use_pallas,
                            pallas_interpret=pallas_interpret)

    def prepare(self, b_even, b_odd):
        return self.op.prepare_pairs(b_even, b_odd)

    def M(self, x_pp):
        return self.op.M_pairs(x_pp)

    Mdag = M

    def MdagM(self, x_pp):
        return self.op.M_pairs(self.op.M_pairs(x_pp))

    def reconstruct(self, x_pp, b_even, b_odd):
        return self.op.reconstruct_pairs(x_pp, b_even, b_odd)

    def sloppy(self, prec: str = "half"):
        return self._dpc.pairs(jnp.bfloat16,
                               use_pallas=self.op.use_pallas,
                               pallas_interpret=self._pallas_interpret)

    def codec(self, precise_dtype, store_dtype):
        from ..solvers.mixed import pair_inplace_codec
        return pair_inplace_codec(store_dtype)

    def flops_per_site_M(self) -> int:
        return getattr(self._dpc, "flops_per_site_M", lambda: 0)()


class _PairOpSolve(_StaggeredPairsSolve):
    """Solve-loop adapter presenting a non-Hermitian pair operator
    (DiracMobiusPCPairs incl. EOFA, DiracCloverPCPairs) through the
    generic invert flow.  Same shape as the staggered adapter (which it
    subclasses) except Mdag is the genuine adjoint and cg routes
    through the normal equations, whose coefficients are real (norms
    and real dots are representation-exact on pair arrays)."""

    hermitian = False

    def Mdag(self, x_pp):
        return self.op.Mdag_pairs(x_pp)

    def MdagM(self, x_pp):
        return self.op.MdagM_pairs(x_pp)

    def __getattr__(self, name):
        # 5d-PC split/join hooks pass through when the wrapped pair
        # operator provides them (hasattr stays False otherwise, so the
        # generic DWF vmap split applies to the 4d-PC families)
        if name in ("split5", "join5"):
            return getattr(self.op, name)
        raise AttributeError(name)


class _WilsonPairsSolve:
    """Pallas-dslash-in-solver routing for the Wilson PC family: the
    whole Krylov loop (prepare, MdagM, reconstruct) runs on the packed
    pair representation with the measured-winner pallas eo stencil
    (QUDA_TPU_PALLAS_VERSION, default v2 by the round-5 chip verdict) —
    so the 5,673-GFLOPS kernel actually executes INSIDE the compiled
    solve instead of only in standalone benchmarks (the solver/kernel
    chasm, VERDICT round 5 weak #1; QUDA analog: the policy-tuned dslash
    inside the CG hot loop, lib/inv_cg_quda.cpp + dslash_policy.hpp).

    CG routes through the normal equations (coefficients real — exact
    on pairs), mirroring _PairOpSolve; the mixed-precision hooks hand
    back the bf16 pair operator + the in-place pair codec on the SAME
    layout, so reliable updates stay complex-free too."""

    hermitian = False

    def __init__(self, dpk, pallas_interpret: bool = False,
                 pallas_version: Optional[int] = None):
        self._dpk = dpk
        self._pallas_interpret = pallas_interpret
        self.op = dpk.pairs(jnp.float32, use_pallas=True,
                            pallas_interpret=pallas_interpret,
                            pallas_version=pallas_version)

    def prepare(self, b_even, b_odd):
        return self.op.prepare_pairs(b_even, b_odd)

    def M(self, x_pp):
        return self.op.M_pairs(x_pp)

    def Mdag(self, x_pp):
        return self.op.Mdag_pairs(x_pp)

    def MdagM(self, x_pp):
        return self.op.MdagM_pairs(x_pp)

    def reconstruct(self, x_pp, b_even, b_odd):
        return self.op.reconstruct_pairs(x_pp, b_even, b_odd)

    def sloppy(self, prec: str = "half"):
        store = jnp.bfloat16 if prec in ("half", "quarter") \
            else jnp.float32
        return self._dpk.pairs(store, use_pallas=True,
                               pallas_interpret=self._pallas_interpret,
                               pallas_version=self.op._pallas_version)

    def codec(self, precise_dtype, store_dtype):
        from ..solvers.mixed import pair_inplace_codec
        return pair_inplace_codec(store_dtype)

    def flops_per_site_M(self) -> int:
        return getattr(self._dpk, "flops_per_site_M", lambda: 0)()


def _invert_wilson_df64(b, param: InvertParam, d, sloppy_prec: str,
                        on_tpu: bool, t0: float):
    """Deep-tolerance Wilson PC CG with a df64 (float32-pair) precise
    side — reaches 1e-10-class true residuals with no f64 and no complex
    execution (reference contract: fp64 matPrecise lib/inv_cg_quda.cpp:63
    + dbldbl reductions include/dbldbl.h; see ops/wilson_df64.py).

    Returns the f32-rounded solution; the lo word of the full-lattice
    solution is published as ``param.x_df64_lo`` (x + x_df64_lo is the
    full-precision solution — the analog of QUDA returning fp64 x)."""
    import numpy as np

    from .. import solvers
    from ..models.wilson import DiracWilsonPCPacked
    from ..obs import convergence as oconv
    from ..obs import trace as otr
    from ..ops import df64 as dfm
    from ..ops import wilson_df64 as wdf

    recording = otr.enabled()
    with otr.phase("setup", "invert_quda"):
        dpk = d if isinstance(d, DiracWilsonPCPacked) else d.packed()
        op = wdf.WilsonPCDF64(dpk)
        be, bo = _split(b, param)
        rhs_df = op.prepare_df(be, bo)

        # 'quarter' sloppy: int8 block-float LINKS under the df64
        # reliable-update correction (the QUDA quarter-precision gauge
        # bet — int8 mantissas + per-link f32 scales, decompressed at
        # link load; spinor iterates stay bf16, there is no int8 pair
        # codec).  The df64 precise side re-anchors the residual every
        # reliable-update cycle, so the quantisation error never
        # accumulates into the true residual (benched at 1e-10 —
        # tests/test_blockfloat.py acceptance drill).
        store = jnp.bfloat16 if sloppy_prec in ("half", "quarter") \
            else jnp.float32
        sl = dpk.pairs(store, use_pallas=_pallas_enabled(on_tpu),
                       pallas_interpret=_pallas_interpret(on_tpu),
                       precision_form=("int8"
                                       if sloppy_prec == "quarter"
                                       else None))
        codec = solvers.pair_inplace_codec(store)
    t_solve0 = time.perf_counter()
    with otr.phase("compute", "invert_quda"), \
            otr.span("solve:cg_reliable_df64", cat="solver",
                     tol=param.tol):
        res = solvers.cg_reliable_df(
            op, sl.MdagM_pairs, rhs_df, codec, tol=param.tol,
            maxiter=param.maxiter, delta=param.reliable_delta,
            record=recording)
    t_solve = time.perf_counter() - t_solve0

    xe_df, xo_df = op.reconstruct_df(res.x, be, bo)
    fr2 = float(dfm.to_f32(op.full_residual_norm2(xe_df, xo_df, be, bo)))
    b2 = float(blas.norm2_comp(b))
    param.true_res = float(np.sqrt(fr2 / b2))

    xe_hi, xe_lo = op.from_df(xe_df, b.dtype)
    xo_hi, xo_lo = op.from_df(xo_df, b.dtype)
    x_full = _join(xe_hi, xo_hi, param)
    param.x_df64_lo = _join(xe_lo, xo_lo, param)
    param.iter_count = int(res.iters)
    param.secs = time.perf_counter() - t0
    _record_solve_metrics("invert_quda", "wilson_df64",
                          "cg-reliable-df64", t_solve,
                          param.dslash_type, param.cuda_prec)
    flops = getattr(dpk, "flops_per_site_M", lambda: 0)()
    # PC operator: flops_per_site_M counts per UPDATED site, and a PC
    # operator updates one parity — volume/2 sites (see invert_quda's
    # accounting note)
    sites = _ctx["geom"].volume // 2
    param.gflops = (param.iter_count * 2.0 * flops * sites) / 1e9
    # param.true_res above is the df64 full-lattice residual — the
    # deepest-precision verification this route can state
    _solve_supervision(param, "invert_quda", res.converged,
                       getattr(res, "breakdown", None))
    if recording:
        # the recorded curve is the normal-equation residual and the
        # solver ships its own |Mdag b|^2 in the history dict, which
        # harvest prefers over this direct-system fallback
        b2_sys = float(dfm.to_f32(dfm.norm2(rhs_df)))
        oconv.publish(oconv.harvest("cg-reliable-df64", res,
                                    tol=param.tol, b2=b2_sys), param)
    qlog.printq(
        f"invert_quda[wilson/cg/df64]: {param.iter_count} iters, "
        f"true_res {param.true_res:.2e}, {param.secs:.2f} s")
    return x_full


def _solve_supervision(param, api: str, converged=None, breakdown=None,
                       converged_multi=None):
    """The verified-exit epilogue shared by every API solve.

    ALWAYS (robust on or off): maintain ``param.converged`` (and
    ``converged_multi``) from the solver's own convergence claim — a
    solve that exits at maxiter without meeting tol is flagged and
    warned about ONCE per (api, solver), never silently returned
    (reference: invert_test reports per-solve convergence; a serving
    fleet treats silence as success).  No new device ops: the flags are
    host conversions of results every solver already computes.

    With QUDA_TPU_ROBUST != off additionally record ``verified_res``
    (the caller has already recomputed param.true_res against the
    hi-precision reference operator at the API boundary — this is that
    number, plus the fault-injection seam) and classify
    ``solve_status``; breakdown/verification events land in the trace
    stream (breakdown_detected / verify_mismatch)."""
    import math

    import numpy as np

    from ..obs import metrics as omet
    from ..obs import trace as otr
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    from ..utils import config as qconf

    def _count_solve():
        # fleet solve accounting (metrics off -> single-global-load
        # no-ops): one solves_total increment per supervised attempt,
        # labeled by the FINAL status (solve_status when robust
        # classified the exit, the convergence claim otherwise)
        status = (getattr(param, "solve_status", None)
                  or ("converged" if param.converged else "unconverged"))
        omet.inc("solves_total", api=api, family=param.dslash_type,
                 status=status)
        omet.inc("solve_iterations_total",
                 float(getattr(param, "iter_count", 0) or 0),
                 api=api, family=param.dslash_type)

    if converged_multi is not None:
        param.converged_multi = [bool(c) for c in
                                 np.asarray(converged_multi).reshape(-1)]
        conv = all(param.converged_multi)
    else:
        conv = bool(np.asarray(jax.device_get(converged)).all())
    param.converged = conv
    bk = 0 if breakdown is None else int(np.asarray(breakdown))
    if not conv and not bk:
        qlog.warn_once(
            f"unconverged:{api}:{param.inv_type}",
            f"{api}[{param.dslash_type}/{param.inv_type}]: solve "
            f"exited without meeting tol {param.tol:g} (achieved "
            f"true_res {param.true_res:.2e}); InvertParam.converged="
            "False — further occurrences are flagged silently on the "
            "param")
    if not rsent.active():
        _count_solve()
        return
    vres = finj.inflated_residual(float(param.true_res))
    param.verified_res = vres
    margin = float(qconf.get("QUDA_TPU_ROBUST_VERIFY_MARGIN",
                             fresh=True))
    from ..obs import postmortem as opm
    if bk:
        param.solve_status = f"breakdown:{rsent.reason(bk)}"
        param.converged = False
        otr.event("breakdown_detected", cat="robust", api=api,
                  reason=rsent.reason(bk), solver=param.inv_type,
                  iters=param.iter_count)
        omet.inc("breakdowns_total", api=api, reason=rsent.reason(bk))
        # failure capture AFTER classification: the bundle records the
        # attempt param with its final solve_status, so a replay's
        # status comparison is against the classified exit
        opm.capture(f"breakdown:{rsent.reason(bk)}", api=api,
                    param=param)
        qlog.warn_once(
            f"breakdown:{api}:{rsent.reason(bk)}",
            f"{api}: breakdown sentinel tripped "
            f"({rsent.reason(bk)}) after {param.iter_count} "
            "iterations — clean exit, no NaN spin; see "
            "InvertParam.solve_status")
    elif not conv:
        param.solve_status = "unconverged"
    elif not (math.isfinite(vres) and vres <= margin * param.tol):
        param.solve_status = "unverified"
        param.converged = False
        otr.event("verify_mismatch", cat="robust", api=api,
                  verified_res=vres, tol=param.tol, margin=margin)
        opm.capture("verify_mismatch", api=api, param=param)
        qlog.warn_once(
            f"unverified:{api}",
            f"{api}: solver claimed convergence but the recomputed "
            f"true residual {vres:.2e} exceeds "
            f"{margin:g} * tol — status 'unverified'")
    else:
        param.solve_status = "converged"
    _count_solve()


def _solve_form(d) -> str:
    """Kernel-form label for roofline attribution (obs/roofline.py):
    conservative — only forms whose PERF.md traffic model provably
    matches the executing kernel get a specific label; everything else
    is 'generic' (flop attribution only, no bandwidth claim)."""
    op = getattr(d, "op", d)
    name = type(op).__name__.lower()
    if "wilson" in name and getattr(op, "use_pallas", False):
        v = getattr(op, "_pallas_version", None)
        # reconstruct-12 storage is visible in the resident link shape
        # (rows kept: 2 instead of 3 — models/wilson.to_recon12), which
        # is authoritative even if QUDA_TPU_RECONSTRUCT changed after
        # operator construction; it shrinks the gauge traffic the
        # roofline model charges, so the label must carry it
        gpp = getattr(op, "gauge_eo_pp", None)
        r12 = (gpp is not None and len(gpp) > 0
               and gpp[0].shape[1] == 2)
        suffix = "_r12" if r12 else ""
        if getattr(op, "_mesh", None) is not None and v in (2, 3):
            return f"wilson_sharded_v{v}{suffix}"
        # precision storage forms (PERF.md round 16) carry their own
        # traffic models; the label is read off the authoritative
        # operator attribute, with bf16 storage distinguished where the
        # tile economics differ (full-tile fold / bz=Z admission exist
        # BECAUSE of the bf16 (16,128) tile shape)
        form = getattr(op, "_precision_form", None)
        bf16 = (getattr(op, "store_dtype", None) is not None
                and jnp.dtype(op.store_dtype) == jnp.dtype(jnp.bfloat16))
        if form == "int8":
            return "wilson_v2_int8"
        if form == "r12f":
            return "wilson_v2_r12f"
        if form == "fold":
            return f"wilson_v2{'_bf16' if bf16 else ''}_fold"
        if form == "bzfull" and bf16:
            return "wilson_v2_bf16_bzfull"
        # f32 bzfull moves the same bytes as the baseline v2 block
        # schedule — same model row, no separate label
        if v in (2, 3):
            return f"wilson_v{v}{suffix}"
    if "wilson" in name:
        return "wilson_xla"
    if "staggered" in name:
        # base traffic model keyed on the hop-set count: 'fat' = plain
        # staggered (one hop set), 'fat_naik' = improved (fat + Naik)
        base = ("fat_naik" if getattr(op, "long_eo_pp", None) is not None
                else "fat")
        if getattr(op, "use_pallas", False):
            form = getattr(op, "_pallas_form", None)
            if getattr(op, "_mesh", None) is not None:
                # mesh pins the two-pass interior today (see
                # models/staggered.py); the halo transport is
                # policy-dependent O(surface) and lives in the trace
                return f"staggered_sharded_{base}"
            if form == "fused":
                pf = getattr(op, "_precision_form", "full")
                if pf in ("r12", "fold"):
                    return f"staggered_{base}_fused_{pf}"
                return f"staggered_{base}_fused"
            if form == "v3":
                return f"staggered_{base}_v3"
            if form == "two_pass":
                # the PERF.md round-8 model name predates the form knob
                return ("staggered_fat_naik" if base == "fat_naik"
                        else "staggered_fat")
        return "staggered_xla"
    # operator zoo (PERF.md round 18).  The fused/staged split is read
    # off the authoritative construction-time attribute (_op_form,
    # models/formsel resolution); r12 off the resident link shape as in
    # the wilson branch.  Order matters: 'ndeg' before 'twisted'
    # (doublet classes contain 'twisted'), 'twisted' before 'clover'
    # (DiracTwistedCloverPCPairs contains both).
    gpp = getattr(op, "gauge_eo_pp", None)
    r12 = (gpp is not None and len(gpp) > 0 and gpp[0].shape[1] == 2)
    suffix = "_r12" if r12 else ""
    fused = getattr(op, "_op_form", None) == "pallas"
    if "ndeg" in name:
        # doublet operators keep the staged composition permanently
        # (flavor mixing is not an epilogue term) — flops-only label
        return "twisted_xla"
    if "twistedclover" in name:
        return (f"twisted_clover_pallas{suffix}" if fused
                else "twisted_clover_xla")
    if "twisted" in name:
        return (f"twisted_mass_pallas{suffix}" if fused
                else "twisted_xla")
    if "clover" in name:
        return f"clover_pallas{suffix}" if fused else "clover_xla"
    if "mobius" in name or "domainwall" in name:
        if fused:
            ls = getattr(op, "ls", None)
            # only Ls in {4, 8} carry traffic models (roofline.py);
            # other Ls report honest flops-only via 'dwf_pallas'
            return (f"dwf_ls{ls}_pallas" if ls in (4, 8)
                    else "dwf_pallas")
        return "dwf_xla"
    return "generic"


@_pm_api("invert_quda", payload="source")
def invert_quda(source, param: InvertParam):
    """invertQuda: solve M x = b per param; returns x, mutates param
    result fields (true_res, iter_count, secs, gflops, converged; with
    QUDA_TPU_TRACE also res_history/events — obs/convergence.py; with
    QUDA_TPU_ROBUST also verified_res/solve_status/solve_attempts —
    quda_tpu/robust)."""
    _require_init()
    param.validate()
    from ..obs import trace as otr
    from ..robust import escalate as resc
    with otr.api_span("invert_quda", dslash=param.dslash_type,
                      inv=param.inv_type, tol=param.tol,
                      **_serve_rid_attrs()), \
            _hbm_sampled("invert_quda"):
        if resc.enabled():
            # QUDA_TPU_ROBUST=escalate: drive the attempt through the
            # bounded retry ladder (robust/escalate.py) — breakdown,
            # verification mismatch, or operator-construction failure
            # escalates pallas -> XLA -> df64/BiCGStab
            return resc.run_ladder(_invert_quda_body, source, param,
                                   api="invert_quda")
        return _invert_quda_body(source, param)


import contextlib

# ledger families whose fields live only for the duration of one API
# call (clover terms rebuilt per _build_dirac; eig workspaces handed to
# the caller at return) — released when the call exits so "resident
# now" stays honest while the family HIGH-WATER keeps the peak signal.
# gauge/fat_naik/mg are genuinely resident (_ctx) and are NOT listed.
_TRANSIENT_FAMILIES = ("clover", "eig")


@contextlib.contextmanager
def _hbm_sampled(api: str):
    """HBM sampling around an API solve (metrics-gated: zero work when
    QUDA_TPU_METRICS is off): all-local-device memory_stats snapshots
    on entry and exit feed the per-device gauges and the session
    high-water marks of the memory ledger (obs/memory.py).  Transient
    per-call ledger families are released on exit."""
    from ..obs import memory as omem
    from ..obs import metrics as omet
    if omet.enabled():
        omem.sample(f"{api}:enter")
    try:
        yield
    finally:
        for fam in _TRANSIENT_FAMILIES:
            omem.release_family(fam)
        if omet.enabled():
            omem.sample(f"{api}:exit")


def _op_mesh(d):
    """The jax.sharding.Mesh a solve operator runs on, walked through
    the adapter wrappers (_WilsonPairsSolve and friends hold the pairs
    op on ``.op``); None for single-device operators.  Drives the
    per-device trace tracks and the ICI solve attribution."""
    seen = set()
    o = d
    while o is not None and id(o) not in seen:
        seen.add(id(o))
        m = getattr(o, "_mesh", None)
        if m is not None:
            return m
        o = getattr(o, "op", None) or getattr(o, "dirac", None)
    return None


def _record_solve_metrics(api: str, form: str, solver: str,
                          secs: float, family: str, prec: str):
    """The ONE home for per-route compile/execution accounting: first
    execution of a distinct (api, form, shape, prec, solver) key
    counts a compile (obs/metrics.record_execution), every execution
    lands a solve_seconds sample.  INVARIANT carried here so no route
    can drift: ``secs`` is the COMPUTE-PHASE time of the route (never
    the full API wall incl. setup), or cross-form histogram
    comparisons — the compile/race-storm instrument — are skewed.
    No-op when QUDA_TPU_METRICS is off."""
    from ..obs import metrics as omet
    if not omet.enabled():
        return
    geom = _ctx["geom"]
    shape = geom.lattice_shape if geom is not None else ()
    omet.record_execution(api, form, shape, prec, solver, secs)
    omet.observe("solve_seconds", secs, api=api, family=family)


def _invert_quda_body(source, param: InvertParam):
    from .. import solvers
    from ..obs import convergence as oconv
    from ..obs import trace as otr

    recording = otr.enabled()
    dtype = complex_dtype(param.cuda_prec)
    b = jnp.asarray(source, dtype)
    t0 = time.perf_counter()
    pc = param.solve_type.endswith("-pc")
    inv = param.inv_type
    with otr.phase("setup", "invert_quda"):
        d = _build_dirac(param, pc)
        d_full = _build_dirac(param, False)

        # Mixed-precision gate (computed early: the layout choice below
        # must not apply to representation combinations it cannot serve).
        # QUDA threads matSloppy through every solver
        # (include/invert_quda.h:369); the TPU ladder
        # (utils/precision.py) has two genuinely distinct sloppy levels:
        # a lower complex dtype (double->single, CPU only) and bf16/int8
        # pair storage ("half"/"quarter" — ops/pair.py).
        sloppy_prec = _resolve_sloppy(param)
        on_tpu = jax.default_backend() == "tpu"
        # complex-free staggered pair adapter: CG-family solves only (its
        # coefficients are real on the Hermitian PC operator, so the pair
        # representation is exact; bicgstab/gcr would feed pair residuals
        # into the complex wrappers), and never silently degrade an f64
        # solve to the f32 pair representation (on TPU f64 does not
        # exist, so the adapter is the only executable path there)
        # shared pair-adapter gate: CG-family solves only (their
        # coefficients are real — exact on the pair representation),
        # never silently degrading an f64 solve to f32 pairs
        pairs_ok = (pc
                    and param.inv_type in ("cg", "pcg", "cg3", "cgne",
                                           "cgnr")
                    and (param.cuda_prec == "single" or on_tpu)
                    and _packed_enabled(on_tpu))
        stag_pairs = pairs_ok and param.dslash_type in ("staggered",
                                                        "asqtad", "hisq")
        # complex-free adapter for the non-Hermitian PC families (cg
        # routes through the normal equations, whose coefficients are
        # real)
        pair_op = pairs_ok and param.dslash_type in (
            "domain-wall", "domain-wall-4d", "mobius", "mobius-eofa",
            "clover", "twisted-mass", "twisted-clover",
            "ndeg-twisted-mass", "ndeg-twisted-clover")
        # pallas-dslash-in-solver routing for Wilson PC (kernel-form
        # selection threaded from utils/config.py: QUDA_TPU_PALLAS gates
        # it on/off, QUDA_TPU_PALLAS_VERSION picks the kernel generation
        # — v2 by chip measurement).  'quarter' keeps the canonical
        # int8-codec path.
        wil_pairs = (pairs_ok and param.dslash_type == "wilson"
                     and _pallas_enabled(on_tpu)
                     and sloppy_prec != "quarter")
        pair_sloppy = (sloppy_prec in ("half", "quarter")
                       and ((param.dslash_type == "wilson" and pc)
                            or stag_pairs or pair_op))
        dtype_sloppy = (sloppy_prec != param.cuda_prec
                        and complex_dtype(sloppy_prec) != complex_dtype(
                            param.cuda_prec))
        mixed = (param.inv_type == "cg" and (pair_sloppy or dtype_sloppy))
        # a canonical dtype-sloppy operator cannot consume pair iterates
        # (same exclusion as the wilson packed gate below)
        pair_excluded = mixed and dtype_sloppy and not pair_sloppy
        stag_pairs = stag_pairs and not pair_excluded
        pair_op = pair_op and not pair_excluded
        wil_pairs = wil_pairs and not pair_excluded

        # TPU-native packed device order for the Wilson PC solve path
        # (QUDA keeps solver fields in native FloatN order the same way);
        # default on TPU, opt-in/out anywhere via QUDA_TPU_PACKED=1/0.
        # Skipped for the dtype-sloppy mixed path (its canonical sloppy
        # operator cannot consume packed iterates) and for 'quarter'
        # (the int8 gauge codec lives on the canonical layout).
        if (param.dslash_type == "wilson" and pc
                and _packed_enabled(on_tpu)
                and not (mixed and dtype_sloppy and not pair_sloppy)
                and sloppy_prec != "quarter"):
            d = d.packed()

        # Extended-precision (df64) route: deep-tolerance Wilson CG where
        # no f64 backend serves (TPU always; CPU when the precise dtype
        # is f32).  The fp64-matPrecise + dbldbl-reduction analog
        # (lib/inv_cg_quda.cpp:63, include/dbldbl.h): precise side in
        # float32-pair arithmetic, sloppy loop unchanged.
        # QUDA_TPU_DF64: '' auto / '1' force / '0' off.
        from ..utils import config as qconf
        df64_mode = str(qconf.get("QUDA_TPU_DF64", fresh=True))
        # precision guard even when forced: the route certifies the
        # residual of the f32-valued system, so an f64 source (CPU double
        # path, which the native f64 solve already serves) must never be
        # silently rounded into a false 1e-10 certificate; packed opt-out
        # honored because the df64 stencil lives on the packed layout
        df64_able = (param.dslash_type == "wilson" and pc
                     and param.inv_type == "cg" and not param.num_offset
                     and (on_tpu or param.cuda_prec == "single")
                     and _packed_enabled(on_tpu))
        df64_route = df64_able and df64_mode != "0" and (
            df64_mode == "1" or param.tol < 5e-8)
        if not df64_route:
            if stag_pairs:
                # complex-free staggered solve loop (pair representation
                # end to end; the pallas eo stencil on real TPU).
                # 'quarter' storage has no staggered int8 codec — the
                # sloppy op falls back to bf16.
                d = _StaggeredPairsSolve(d, _pallas_enabled(on_tpu),
                                         _pallas_interpret(on_tpu))
            elif pair_op:
                d = _PairOpSolve(d, _pallas_enabled(on_tpu),
                                 _pallas_interpret(on_tpu))
            elif wil_pairs:
                from ..models.wilson import DiracWilsonPCPacked
                if isinstance(d, DiracWilsonPCPacked):
                    # the hand-tuned eo kernel runs inside the compiled
                    # Krylov loop (interpret-mode off TPU so the routing
                    # is testable on CPU hosts)
                    d = _WilsonPairsSolve(d, _pallas_interpret(on_tpu))

            if pc:
                be, bo = _split(b, param, d)
                rhs = d.prepare(be, bo)
            else:
                rhs = b

            normop = param.solve_type.startswith("normop")
            hermitian_pc = getattr(d, "hermitian", False)

            if param.num_offset:
                qlog.errorq("use invert_multishift_quda for shifted "
                            "solves")

            if hermitian_pc:   # staggered PC: already the normal operator
                mv = d.M
                sys_rhs = rhs
                back = lambda x: x
                mv_applies = 1.0
            elif normop:
                mv = lambda v: d.Mdag(d.M(v))
                sys_rhs = d.Mdag(rhs)
                back = lambda x: x
                mv_applies = 2.0
            else:
                mv = d.M
                sys_rhs = rhs
                back = lambda x: x
                mv_applies = 1.0

            if inv == "cg" and not (hermitian_pc or normop):
                # QUDA's solve-type matrix (lib/solve.cpp:180): CG +
                # direct solve is routed through the normal RESIDUAL
                # equations (CGNR).  Users wanting the normal-ERROR form
                # should pick inv_type="cgne".
                qlog.warningq("cg on a non-normal system; using CGNR "
                              "(normal-residual) semantics")
                mv = lambda v: d.Mdag(d.M(v))
                sys_rhs = d.Mdag(rhs)
                mv_applies = 2.0

            # direct-route solvers that internally apply the operator
            # more than once per counted iteration (cgne/cgnr compose
            # Mdag themselves, BiCGStab does two mat-vecs per iteration).
            # Hermitian-PC systems run these as plain one-apply CG — no
            # bump.  cg3's recursion is one apply per counted iteration.
            if (mv_applies == 1.0 and not hermitian_pc
                    and inv in ("cgne", "cgnr", "bicgstab")):
                mv_applies = 2.0
            # BiCGStab(L) needs NO bump: solvers/bicgstab.bicgstab_l
            # counts MATVEC APPLICATIONS as iterations (k += 2L per cycle
            # = exactly the 2L operator applies the cycle performs), so
            # each counted iteration is already one mv apply.  The old
            # flat 2.0 treated the count as cycles and over-reported its
            # gflops 2x; charging L+1 per counted iteration would
            # over-report (L+1)x.

    if df64_route:
        return _invert_wilson_df64(b, param, d, sloppy_prec, on_tpu, t0)

    t_solve0 = time.perf_counter()
    with otr.phase("compute", "invert_quda"), \
            otr.span(f"solve:{inv}", cat="solver", mesh=_op_mesh(d),
                     tol=param.tol, maxiter=param.maxiter):
        # keyword-only at the call site: four adjacent bools among 17
        # parameters — a positional transposition would type-check and
        # silently pick the wrong solve route
        res = _invert_dispatch(param=param, d=d, d_full=d_full, b=b,
                               rhs=rhs, sys_rhs=sys_rhs, mv=mv,
                               mv_applies=mv_applies, inv=inv,
                               mixed=mixed, pair_sloppy=pair_sloppy,
                               hermitian_pc=hermitian_pc, normop=normop,
                               sloppy_prec=sloppy_prec, dtype=dtype,
                               pc=pc, t0=t0, recording=recording)
    if not isinstance(res, tuple):
        return res             # gcr-mg handled everything itself
    res, publish_sys_rhs = res
    t_solve = time.perf_counter() - t_solve0

    # compile/executable-cache accounting: the first compute phase of a
    # distinct (form, shape, prec, solver) key paid the XLA compile
    # inside t_solve
    _record_solve_metrics("invert_quda", _solve_form(d), inv, t_solve,
                          param.dslash_type, param.cuda_prec)

    with otr.phase("epilogue", "invert_quda"):
        x_sys = back(res.x)
        if pc:
            xe, xo = d.reconstruct(x_sys, be, bo)
            x_full = _join(xe, xo, param, d)
        else:
            x_full = x_sys

        param.iter_count = int(res.iters)
        param.secs = time.perf_counter() - t0
        r = b - d_full.M(x_full)
        param.true_res = float(jnp.sqrt(blas.norm2(r) / blas.norm2(b)))
        flops = getattr(d, "flops_per_site_M", lambda: 0)()
        # GFLOPS convention: flops_per_site_M counts flops per site the
        # operator UPDATES, and an even/odd-preconditioned operator
        # updates one parity — volume/2 sites (the reference's
        # Dirac*PC::flops are per-parity counts, include/dslash.h:475).
        # Charging the FULL volume overstated every PC gflops ~2x
        # (round-5 logs predate this fix).  mv_applies follows the SOLVE
        # ROUTE (1 for direct/Hermitian-PC operators AND BiCGStab(L),
        # whose iteration counter already counts matvec applications;
        # 2 for normal-equation forms), set where mv is built.
        sites = _ctx["geom"].volume // 2 if pc else _ctx["geom"].volume
        param.gflops = (param.iter_count * mv_applies * flops
                        * sites) / 1e9
        # verified exit: param.true_res above IS the hi-precision XLA
        # reference recomputation (d_full.M on the full lattice) — the
        # supervision epilogue records it as verified_res and
        # classifies the exit (robust/), and ALWAYS maintains
        # param.converged + the one-time unconverged warning
        _solve_supervision(param, "invert_quda", res.converged,
                           getattr(res, "breakdown", None))

    from ..utils import timer as qtimer
    qtimer.add_flops(param.gflops * 1e9)
    if recording:
        # convergence history -> InvertParam.res_history/events + trace
        # residual events; roofline attribution of the compute phase
        rec = oconv.harvest(inv, res, tol=param.tol,
                            b2=float(blas.norm2(publish_sys_rhs)))
        oconv.publish(rec, param)
        from ..obs import roofline as orf
        # applies counts M applications; a PC M runs TWO dslash
        # invocations per apply, and the KERNEL_MODELS traffic side is
        # per invocation — dslash_per_apply keeps the BW column honest
        orf.record(_solve_form(d), sites,
                   param.iter_count * mv_applies, t_solve,
                   flops_per_site=flops,
                   dslash_per_apply=2.0 if pc else 1.0,
                   label=f"invert_quda:{param.dslash_type}/{inv}")
    from ..obs import comms as ocomms
    if ocomms.enabled() and _op_mesh(d) is not None:
        # ICI attribution: the comms ledger's per-invocation halo model
        # x this solve's measured applies, emitted as the roofline.tsv
        # sibling row.  Gated on the LEDGER (which rides either
        # trace or metrics knob), not on `recording` — a metrics-only
        # session must still see ici_bytes_total.  The site prefix
        # confines the model to this operator's family so another
        # form's stencils traced earlier in the session cannot leak in.
        form = _solve_form(d)
        ocomms.attribute_solve(
            form, param.iter_count * mv_applies, 2.0 if pc else 1.0,
            t_solve, label=f"invert_quda:{param.dslash_type}/{inv}",
            site_prefix=form.split("_")[0])
    qlog.printq(
        f"invert_quda[{param.dslash_type}/{inv}]: {param.iter_count} "
        f"iters, true_res {param.true_res:.2e}, {param.secs:.2f} s")
    return x_full


def _invert_dispatch(param, d, d_full, b, rhs, sys_rhs, mv, mv_applies,
                     inv, mixed, pair_sloppy, hermitian_pc, normop,
                     sloppy_prec, dtype, pc, t0, recording):
    """The solver dispatch chain of invert_quda.  Returns
    ``(SolverResult, system_rhs_for_history)`` — or the finished
    solution array for the gcr-mg route, which completes its own
    epilogue/accounting."""
    from .. import solvers

    if mixed and inv == "cg":
        if pair_sloppy:
            sl = d.sloppy(sloppy_prec)
            # each operator representation (canonical / packed) supplies
            # the codec matching its sloppy storage layout; the storage
            # dtype comes from the BUILT sloppy operator so the two can
            # never desynchronise
            codec = (d.codec(dtype, sl.store_dtype)
                     if hasattr(d, "codec")
                     else solvers.pair_codec(sl.store_dtype, dtype))
            # staggered PC is already the (Hermitian) normal operator
            mv_lo = sl.M_pairs if hermitian_pc else sl.MdagM_pairs
            res = solvers.cg_reliable(
                mv, mv_lo, sys_rhs, tol=param.tol,
                maxiter=param.maxiter, delta=param.reliable_delta,
                codec=codec, record=recording)
        else:
            sl = _build_sloppy(param, pc, sloppy_prec)
            if hermitian_pc:
                mv_lo = sl.M
            else:
                mv_lo = lambda v: sl.Mdag(sl.M(v))
            res = solvers.cg_reliable(
                mv, mv_lo, sys_rhs, complex_dtype(sloppy_prec),
                tol=param.tol, maxiter=param.maxiter,
                delta=param.reliable_delta, record=recording)
    elif inv in ("cg", "pcg", "cg3"):
        fn = solvers.create(inv)
        kw = {"tol_hq": param.tol_hq} if inv == "cg" else {}
        if inv in ("cg", "pcg"):
            kw["record"] = recording
        res = fn(mv, sys_rhs, tol=param.tol, maxiter=param.maxiter, **kw)
    elif inv in ("cgne", "cgnr"):
        # explicit normal-error / normal-residual solves on the DIRECT
        # system (lib/solve.cpp CGNE/CGNR rows): cgne solves M Mdag y = b
        # then x = Mdag y (error-norm minimising); cgnr solves
        # Mdag M x = Mdag b (residual-norm minimising)
        if hermitian_pc:
            res = solvers.cg(d.M, rhs, tol=param.tol,
                             maxiter=param.maxiter, record=recording)
        else:
            fn = solvers.cgne if inv == "cgne" else solvers.cgnr
            res = fn(d.M, d.Mdag, rhs, tol=param.tol, maxiter=param.maxiter)
    elif inv == "bicgstab":
        if pair_sloppy:
            # defect-correction outer at precise, bf16-internal BiCGStab
            # inner (QUDA's sloppy-solve + reliable-residual pattern for
            # non-Hermitian systems).  The inner operator must match the
            # OUTER system: MdagM when solving the normal equations.
            sl = d.sloppy(sloppy_prec)
            mv_in = sl.MdagM if normop else sl.M
            res = _pair_refined_solve(
                mv, sys_rhs, dtype, param,
                jax.jit(lambda r: solvers.bicgstab(
                    mv_in, r, tol=1e-3, maxiter=param.maxiter)))
        else:
            res = solvers.bicgstab(mv, sys_rhs, tol=param.tol,
                                   maxiter=param.maxiter,
                                   record=recording)
    elif inv == "bicgstab-l":
        res = solvers.bicgstab_l(mv, sys_rhs, L=_BICGSTAB_L,
                                 tol=param.tol, maxiter=param.maxiter,
                                 record=recording)
    elif inv == "gcr":
        if pair_sloppy:
            sl = d.sloppy(sloppy_prec)
            mv_in = sl.MdagM if normop else sl.M
            # NOTE: gcr is a host-driven restart loop (it jits its own
            # cycles internally) — wrapping it in jax.jit would trace the
            # float() convergence checks.  The inner budget honors
            # param.maxiter across the refinement cycles.
            cycles = 10
            inner_budget = max(1, param.maxiter
                               // (cycles * param.gcrNkrylov))
            res = _pair_refined_solve(
                mv, sys_rhs, dtype, param,
                lambda r: solvers.gcr(
                    mv_in, r, tol=1e-3, nkrylov=param.gcrNkrylov,
                    max_restarts=inner_budget),
                max_cycles=cycles)
        else:
            res = solvers.gcr(mv, sys_rhs, tol=param.tol,
                              nkrylov=param.gcrNkrylov,
                              max_restarts=max(1, param.maxiter
                                               // param.gcrNkrylov))
    elif inv in ("ca-cg", "ca-gcr"):
        fn = solvers.create(inv)
        res = fn(mv, sys_rhs, tol=param.tol,
                 max_cycles=max(1, param.maxiter // 8))
    elif inv == "gcr-mg":
        t_mg0 = time.perf_counter()
        res, pair_true_res = _solve_mg(d_full, b, param)
        t_mg = time.perf_counter() - t_mg0
        x_full = res.x
        param.iter_count = int(res.iters)
        param.secs = time.perf_counter() - t0
        # this route returns before _invert_quda_body's shared
        # accounting call — record here or MG (the costliest compile in
        # the system) stays invisible to the compile/race-storm
        # instrument; t_mg is the setup+solve call only
        _record_solve_metrics("invert_quda", "gcr_mg", inv, t_mg,
                              param.dslash_type, param.cuda_prec)
        # fine-operator work only (V-cycle smoother/coarse flops not
        # charged — same convention as QUDA's outer-solver gflops)
        param.gflops = (param.iter_count
                        * getattr(d_full, "flops_per_site_M", lambda: 0)()
                        * _ctx["geom"].volume) / 1e9
        if pair_true_res is not None:
            # the pair route already measured it complex-free; re-deriving
            # it here with d_full.M would put a complex op on the device
            param.true_res = pair_true_res
        else:
            r = b - d_full.M(x_full)
            param.true_res = float(jnp.sqrt(blas.norm2(r) / blas.norm2(b)))
        _solve_supervision(param, "invert_quda", res.converged,
                           getattr(res, "breakdown", None))
        return x_full
    else:
        qlog.errorq(f"inv_type {inv} not wired")

    # the cgne/cgnr branch solves against the DIRECT rhs; everything
    # else iterated on sys_rhs — the history relres must normalise
    # against the system the recorded residuals belong to
    return res, (rhs if inv in ("cgne", "cgnr") else sys_rhs)


@_pm_api("invert_multi_src_quda", payload="source")
def invert_multi_src_quda(sources, param: InvertParam):
    """invertMultiSrcQuda analog: solve M x_i = b_i for a batch of
    sources (lib/interface_quda.cpp:3064 callMultiSrcQuda).

    sources: (n_src, T, Z, Y, X, 4, 3) host/device batch.  Returns the
    (n_src, ...) solution batch and mutates param: ``true_res_multi`` /
    ``iter_count_multi`` hold per-RHS results, ``iter_count`` their sum,
    and ``gflops`` charges each RHS its own converged iterations at the
    round-6 PC convention (flops per UPDATED site x volume/2).

    Routing (QUDA's split_key decision re-derived for one-process TPU):

    * >1 device and the batch divides the device count -> SPLIT GRID
      (parallel/split.py): sources sharded over the mesh src axis,
      gauge replicated, one independent PC solve per sub-grid.
    * otherwise, Wilson PC or staggered/HISQ PC + CG family on the
      packed representation -> the BATCHED PAIRS pipeline: every Krylov
      iterate is a packed pair batch ((n_src, 4, 3, 2, T, Z, Y*Xh)
      Wilson / (n_src, 3, 2, T, Z, Y*Xh) staggered) and the stencil is
      the MRHS pallas eo kernel (link tiles loaded once per
      (t, z-block), all RHS streamed through them) or its vmapped XLA
      form off-TPU.  The staggered PC operator is Hermitian, so its
      batch runs direct CG (one M per iteration); Wilson runs CGNR.
      QUDA_TPU_MULTI_SRC_BLOCK=1 swaps the independent per-RHS lanes
      for true block CG (shared Krylov space, real Gram matmuls).
    * anything else falls back to a per-source invert_quda loop (same
      results, no amortisation) so the entry point serves every
      operator the single-source API serves.

    QUDA_TPU_MULTI_SRC_SPLIT forces ('1') or forbids ('0') the
    split-grid route.
    """
    _require_init()
    param.validate()
    from ..obs import trace as otr
    from ..robust import escalate as resc
    with otr.api_span("invert_multi_src_quda", dslash=param.dslash_type,
                      inv=param.inv_type, n_src=len(sources),
                      **_serve_rid_attrs()), \
            _hbm_sampled("invert_multi_src_quda"):
        if resc.enabled():
            return resc.run_ladder(_invert_multi_src_body, sources,
                                   param, api="invert_multi_src_quda")
        return _invert_multi_src_body(sources, param)


def _invert_multi_src_body(sources, param: InvertParam):
    import numpy as np

    from ..obs import convergence as oconv
    from ..obs import trace as otr
    from ..utils import config as qconf
    from ..solvers.block import _check_nrhs

    recording = otr.enabled()
    dtype = complex_dtype(param.cuda_prec)
    B = jnp.asarray(sources, dtype)
    n_src = B.shape[0]
    _check_nrhs(n_src)
    t0 = time.perf_counter()
    pc = param.solve_type.endswith("-pc")
    on_tpu = jax.default_backend() == "tpu"
    geom = _ctx["geom"]

    if param.num_offset:
        qlog.errorq("invert_multi_src_quda does not serve multishift; "
                    "use invert_multishift_quda per source")

    cg_family = param.inv_type in ("cg", "pcg", "cgnr", "cgne")
    # f32 pair storage cannot certify tolerances below the f32 floor —
    # deep-tol batches take the per-source fallback, whose invert_quda
    # engages the df64 route (same 5e-8 threshold it uses)
    tol_ok = param.tol >= 5e-8
    stag_family = param.dslash_type in ("staggered", "asqtad", "hisq")
    # Wilson AND the staggered/HISQ family ride the batched pairs
    # pipeline (round 10: MILC-interface HISQ workloads no longer run
    # the slow per-source path end to end); checked against ``mesh is
    # None`` at the route decision below, AFTER the split-grid gate may
    # have released an unusable mesh back to this route
    # operator-zoo Schur families (round 18): clover/twisted-mass/
    # twisted-clover ride the same batched-pairs pipeline via the
    # _SchurPairOpBase MRHS suite.  Doublet (ndeg) and DWF operators
    # stay per-source: the doublet flavor axis and the Ls axis already
    # occupy the batch dimension their kernels lead with.
    zoo_family = param.dslash_type in ("clover", "twisted-mass",
                                       "twisted-clover")
    batched_able = (pc
                    and (param.dslash_type == "wilson" or stag_family
                         or zoo_family)
                    and cg_family and tol_ok
                    and (param.cuda_prec == "single" or on_tpu)
                    and _packed_enabled(on_tpu))
    # per-UPDATED-site flops of one PC M apply (round-6 convention)
    if stag_family:
        flops_m = 2 * (1146 if param.dslash_type != "staggered"
                       else 570) + 24
    elif param.dslash_type in ("clover", "twisted-clover"):
        flops_m = 2 * 1320 + 2 * 504 + 48
    elif param.dslash_type == "twisted-mass":
        flops_m = 2 * 1320 + 192
    else:
        flops_m = 2 * 1320 + 48

    # split-vs-batched dispatch, resolved in its one home
    # (parallel/split.multi_src_route — the serve/ batcher consults the
    # same function to label coalesced batches with their route)
    from ..parallel.split import multi_src_route
    split_mode = str(qconf.get("QUDA_TPU_MULTI_SRC_SPLIT", fresh=True))
    try:
        route, mesh, split_gated = multi_src_route(
            n_src, split_mode=split_mode,
            split_gate=(pc and param.dslash_type == "wilson"
                        and cg_family and tol_ok),
            batched_gate=batched_able)
    except ValueError as e:
        qlog.errorq(str(e))

    def _finish(x_full, iters_rhs, res_rhs, mv_applies,
                converged_rhs=None, breakdown=None):
        import math
        param.iter_count_multi = [int(i) for i in iters_rhs]
        param.true_res_multi = [float(r) for r in res_rhs]
        param.iter_count = int(sum(param.iter_count_multi))
        # np.max propagates a NaN lane into the headline (python max
        # would silently skip it when NaN is not the last element)
        param.true_res = float(np.max(np.asarray(param.true_res_multi)))
        param.secs = time.perf_counter() - t0
        if converged_rhs is None:
            # the route surfaced no per-lane convergence claim: the
            # honest maxiter criterion (a lockstep solve that ran out
            # of budget did NOT converge), plus a finiteness screen on
            # the recomputed per-lane residual
            converged_rhs = [int(i) < param.maxiter
                             and math.isfinite(float(r))
                             for i, r in zip(iters_rhs, res_rhs)]
        # the per-RHS res_rhs above are recomputed with the full
        # hi-precision operator (d_chk.M) — the verified exit
        _solve_supervision(param, "invert_multi_src_quda",
                           breakdown=breakdown,
                           converged_multi=converged_rhs)
        flops = flops_m              # PC M cost (per updated site)
        sites = geom.volume // 2 if pc else geom.volume
        # per-RHS accounting, QUDA's per-source gflops convention.  The
        # batched route records each lane's OWN converged iteration
        # count (its extra lockstep applies past convergence are idle-
        # lane work, not charged); the split route's vmapped while_loop
        # runs every sub-grid to the slowest lane's stop, so its
        # per-RHS counts are the executed lockstep iterations — equal
        # across lanes by construction
        param.gflops = (param.iter_count * mv_applies * flops
                        * sites) / 1e9
        qlog.printq(
            f"invert_multi_src_quda[{param.dslash_type}/"
            f"{param.inv_type}]: {n_src} sources, "
            f"iters {param.iter_count_multi}, worst true_res "
            f"{param.true_res:.2e}, {param.secs:.2f} s")
        return x_full

    if split_gated:
        # a usable src mesh exists but this operator/solver/tolerance
        # is outside the split route's CG-family Wilson-PC gate: say so
        # (an env knob or auto decision must never lose effect without
        # a trace — the round-6 wilson.py notice rule) and fall through
        # to a route that honors the request
        qlog.printq(
            f"invert_multi_src_quda: split-grid route serves Wilson PC "
            f"CG-family solves at tol >= 5e-8 only; "
            f"{param.dslash_type}/{param.inv_type} (tol {param.tol:g}) "
            "falls back to the batched-pairs/per-source routes",
            qlog.SUMMARIZE)

    if route == "split":
        # split grid: shard sources over the src mesh axis, replicate
        # the gauge, one full PC solve per sub-grid (complex arithmetic
        # — this route serves multi-device hosts, where complex
        # executes; the axon single-chip runtime takes the pair route)
        from ..models.wilson import DiracWilsonPC
        from ..parallel.split import split_grid_solve
        from ..solvers.fused_iter import fused_cg
        ap = _antiperiodic()
        matpc = EVEN if param.matpc_type == "even-even" else ODD
        kappa, tol, maxiter = param.kappa, param.tol, param.maxiter

        def solve_one(g_raw, b):
            d1 = DiracWilsonPC(g_raw, geom, kappa, ap, matpc)
            be, bo = even_odd_split(b, geom)
            rhs = d1.prepare(be, bo)
            nrm = d1.Mdag(rhs)
            res = fused_cg(lambda v: d1.Mdag(d1.M(v)), nrm, tol=tol,
                           maxiter=maxiter)
            xe, xo = d1.reconstruct(res.x, be, bo)
            # thread the solver's OWN convergence claim (and sentinel
            # code) out of the vmapped lane: the maxiter heuristic
            # cannot see a mid-solve breakdown exit, whose iters <
            # maxiter would otherwise read as converged
            return (even_odd_join(xe, xo, geom), res.iters,
                    res.converged, res.breakdown)

        # pass the RAW resident gauge; each sub-grid folds the boundary
        # phase inside its own trace (DiracWilsonPC does it)
        t_solve0 = time.perf_counter()
        with otr.phase("compute", "invert_multi_src_quda", mesh=mesh,
                       route="split_grid"):
            x_full, iters, conv_l, bk_l = split_grid_solve(
                solve_one, _ctx["gauge"], B, mesh)
        _record_solve_metrics("invert_multi_src_quda",
                              "wilson_split_grid", param.inv_type,
                              time.perf_counter() - t_solve0,
                              param.dslash_type, param.cuda_prec)
        with otr.phase("epilogue", "invert_multi_src_quda"):
            d_chk = _build_dirac(param, False)
            res_rhs = [float(jnp.sqrt(blas.norm2(B[i]
                                                 - d_chk.M(x_full[i]))
                                      / blas.norm2(B[i])))
                       for i in range(n_src)]
            bk = (None if bk_l is None
                  else int(np.max(np.asarray(bk_l))))
            return _finish(x_full, np.asarray(iters), res_rhs, 2.0,
                           converged_rhs=np.asarray(conv_l),
                           breakdown=bk)

    if route == "batched":
        from ..solvers.block import (_per_rhs_dot, batched_cg_pairs,
                                     block_cg_pairs)
        with otr.phase("setup", "invert_multi_src_quda"):
            d = _build_dirac(param, True)
            if param.dslash_type == "wilson":
                d = d.packed()
            # staggered: pin the two_pass form — this route only ever
            # runs the gather MRHS kernel (_d_to_mrhs), so 'auto' would
            # race single-RHS kernels whose winner is never used
            kw = ({"form": "two_pass"} if stag_family else {})
            op = d.pairs(jnp.float32,
                         use_pallas=_pallas_enabled(on_tpu),
                         pallas_interpret=_pallas_interpret(on_tpu),
                         **kw)
            halves = [even_odd_split(B[i], geom) for i in range(n_src)]
            be = jnp.stack([h[0] for h in halves])
            bo = jnp.stack([h[1] for h in halves])
            rhs_b = op.prepare_pairs_mrhs(be, bo)
            if stag_family:
                # the staggered PC operator is already the (Hermitian
                # positive definite) normal operator — the batched CG
                # runs it directly, one M apply per counted iteration
                nrm_b = rhs_b
                mv_b = op.M_pairs_mrhs
                mv_applies = 1.0
            else:
                # CGNR on the batched normal equations (coefficients
                # real — exact on pairs; same route as the
                # single-source wil_pairs cg)
                nrm_b = op.Mdag_pairs_mrhs(rhs_b)
                mv_b = op.MdagM_pairs_mrhs
                mv_applies = 2.0
            use_block = str(qconf.get("QUDA_TPU_MULTI_SRC_BLOCK",
                                      fresh=True)) == "1"
        solver_name = "block-cg-pairs" if use_block else \
            "batched-cg-pairs"
        t_solve0 = time.perf_counter()
        with otr.phase("compute", "invert_multi_src_quda"), \
                otr.span(f"solve:{solver_name}", cat="solver",
                         nrhs=n_src, tol=param.tol):
            if use_block:
                res = block_cg_pairs(mv_b, nrm_b,
                                     tol=param.tol,
                                     maxiter=param.maxiter,
                                     record=recording)
                iters_rhs = np.full(n_src, int(res.iters))
            else:
                res = batched_cg_pairs(mv_b, nrm_b,
                                       tol=param.tol,
                                       maxiter=param.maxiter,
                                       record=recording)
                iters_rhs = np.asarray(res.iters)
        t_solve = time.perf_counter() - t_solve0
        _record_solve_metrics(
            "invert_multi_src_quda",
            ("staggered" if stag_family
             else param.dslash_type.replace("-", "_") if zoo_family
             else "wilson") + "_batched_pairs",
            solver_name, t_solve, param.dslash_type, param.cuda_prec)
        conv = np.asarray(res.converged)
        if not conv.all():
            qlog.warningq(
                f"invert_multi_src_quda: {int((~conv).sum())} of "
                f"{n_src} sources did not reach tol {param.tol:g} "
                f"within {param.maxiter} iterations (block-CG Gram "
                "breakdown reports lanes unconverged too); per-RHS "
                "true_res_multi holds the achieved residuals")
        with otr.phase("epilogue", "invert_multi_src_quda"):
            xe_b, xo_b = op.reconstruct_pairs_mrhs(res.x, be, bo)
            x_full = jax.vmap(
                lambda e, o: even_odd_join(e, o, geom))(xe_b, xo_b)
            d_chk = _build_dirac(param, False)
            res_rhs = [float(jnp.sqrt(blas.norm2(B[i]
                                                 - d_chk.M(x_full[i]))
                                      / blas.norm2(B[i])))
                       for i in range(n_src)]
            x_out = _finish(x_full, iters_rhs, res_rhs, mv_applies,
                            converged_rhs=conv,
                            breakdown=getattr(res, "breakdown", None))
        if recording:
            # per-lane convergence histories (worst relative lane is
            # the headline; each lane normalized against its OWN b2)
            # + MRHS roofline attribution of the batch solve
            b2_rhs = np.asarray(_per_rhs_dot(nrm_b, nrm_b))
            rec = oconv.harvest(solver_name, res, tol=param.tol,
                                b2=b2_rhs)
            oconv.publish(rec, param)
            from ..obs import roofline as orf
            zoo_fused = getattr(op, "_op_form", None) == "pallas"
            if not getattr(op, "use_pallas", False):
                form = "generic"
            elif param.dslash_type == "clover":
                form = ("clover_pallas_mrhs" if zoo_fused
                        else "clover_xla")
            elif param.dslash_type == "twisted-mass":
                form = ("twisted_mass_pallas_mrhs" if zoo_fused
                        else "twisted_xla")
            elif param.dslash_type == "twisted-clover":
                form = ("twisted_clover_pallas_mrhs" if zoo_fused
                        else "twisted_clover_xla")
            elif not stag_family:
                form = "wilson_mrhs"
            else:
                form = ("staggered_mrhs"
                        if getattr(op, "long_eo_pp", None) is not None
                        else "staggered_fat_mrhs")
            orf.record(form, geom.volume // 2,
                       float(np.max(iters_rhs)) * mv_applies, t_solve,
                       nrhs=n_src, flops_per_site=flops_m,
                       dslash_per_apply=2.0,
                       label=f"invert_multi_src_quda:{solver_name}")
        return x_out

    # generic fallback: per-source invert_quda loop (correct everywhere,
    # no gauge amortisation) — keeps the multi-source surface total
    import copy
    xs, iters_rhs, res_rhs, gflops, conv_rhs = [], [], [], 0.0, []
    for i in range(n_src):
        p_i = copy.copy(param)
        xs.append(invert_quda(B[i], p_i))
        iters_rhs.append(p_i.iter_count)
        res_rhs.append(p_i.true_res)
        gflops += p_i.gflops
        conv_rhs.append(p_i.converged)
    x_full = jnp.stack(xs)
    param.iter_count_multi = list(iters_rhs)
    param.true_res_multi = [float(r) for r in res_rhs]
    param.iter_count = int(sum(iters_rhs))
    param.true_res = float(np.max(np.asarray(param.true_res_multi)))
    param.secs = time.perf_counter() - t0
    param.gflops = gflops
    # the inner invert_quda calls already ran their own supervision
    # (and, under 'escalate', their own ladders) — roll their verdicts
    # up onto the batch param
    _solve_supervision(param, "invert_multi_src_quda",
                       converged_multi=conv_rhs)
    qlog.printq(
        f"invert_multi_src_quda[{param.dslash_type}/{param.inv_type}] "
        f"(per-source fallback): {n_src} sources, iters "
        f"{param.iter_count_multi}, worst true_res "
        f"{param.true_res:.2e}, {param.secs:.2f} s")
    return x_full


def _build_sloppy(p: InvertParam, pc: bool, sloppy_prec: str = None):
    import copy
    sloppy_prec = sloppy_prec or _resolve_sloppy(p)
    sl = copy.copy(p)
    sl.cuda_prec = sloppy_prec
    dt = complex_dtype(sloppy_prec)
    saved = {k: _ctx[k] for k in ("gauge", "fat", "long")}
    for k, v in saved.items():
        if v is not None:
            _ctx[k] = v.astype(dt)
    try:
        d = _build_dirac(sl, pc)
    finally:
        _ctx.update(saved)
    return d


def _mg_level_params(mp: "MultigridParamAPI"):
    """MultigridParamAPI -> per-level MGLevelParam list (one mapping for
    both the resident-setup and the solve path, so user smoothing knobs
    are never silently dropped)."""
    from ..mg.mg import MGLevelParam
    return [MGLevelParam(block=tuple(mp.geo_block_size[i]),
                         n_vec=mp.n_vec[i],
                         setup_iters=mp.setup_iters[i]
                         if i < len(mp.setup_iters) else 150,
                         setup_tol=mp.setup_tol[i]
                         if i < len(mp.setup_tol) else 5e-6,
                         pre_smooth=mp.nu_pre[i] if i < len(mp.nu_pre)
                         else 0,
                         post_smooth=mp.nu_post[i] if i < len(mp.nu_post)
                         else 4,
                         smoother_omega=mp.smoother_omega,
                         coarse_solver_iters=mp.coarse_solver_iters)
            for i in range(mp.n_level - 1)]


def _mg_pairs_enabled(d, param: InvertParam, on_tpu: bool) -> bool:
    """Pair-hierarchy gate: Wilson or staggered — including IMPROVED
    staggered, where the hierarchy is fat-only and mg_solve_pairs runs
    the outer Krylov on the full fat+Naik operator (defect correction;
    mg/pair.PairStaggeredLevelOp.M_std_full) — and, like every other
    pair gate in this file, never silently degrade an f64 solve to f32
    pairs."""
    family_ok = type(d).__name__ in ("DiracWilson", "DiracStaggered")
    return (_packed_enabled(on_tpu) and family_ok
            and (param.cuda_prec == "single" or on_tpu))


def _solve_mg(d_full, b, param: InvertParam, mg_param=None):
    """Returns (SolverResult, true_res or None): the pair route computes
    the true residual complex-free itself (the caller's complex check
    cannot execute on runtimes without complex support)."""
    from ..mg.mg import MG, mg_solve
    mp = mg_param or MultigridParamAPI()
    params = _mg_level_params(mp)
    mg = _ctx["mg"]
    if mg is not None and _ctx["mg_epoch"] != _ctx["gauge_epoch"]:
        # resident hierarchy was built for a different gauge — rebuild
        # (updateMultigridQuda semantics, interface_quda.cpp:2789; a stale
        # hierarchy silently degrades to a wrong preconditioner)
        qlog.printq("gauge changed since MG setup; rebuilding hierarchy",
                    qlog.VERBOSE)
        mg = None
    on_tpu = jax.default_backend() == "tpu"
    from ..mg.pair import PairMG
    if _mg_pairs_enabled(d_full, param, on_tpu):
        # complex-free hierarchy (mg/pair.py): the only MG that can
        # execute on TPU runtimes without complex64 support.  Boundary
        # conversions run host-side in numpy so no complex op ever
        # reaches the device.
        import numpy as np
        from ..mg.pair import mg_solve_pairs
        if mg is not None and not isinstance(mg, PairMG):
            qlog.printq("resident MG is complex; rebuilding as pair "
                        "hierarchy for the packed path", qlog.VERBOSE)
            mg = None
        b_np = np.asarray(b)
        b_pairs = jnp.asarray(
            np.stack([b_np.real, b_np.imag], -1).astype(np.float32))
        res, mg = mg_solve_pairs(d_full, _ctx["geom"], b_pairs, params,
                                 tol=param.tol, nkrylov=param.gcrNkrylov,
                                 mg=mg)
        _ctx["mg"] = mg
        _ctx["mg_epoch"] = _ctx["gauge_epoch"]
        from ..obs import memory as omem
        omem.track("mg", "hierarchy", mg)
        # true residual in pair arithmetic (no complex op on device) —
        # measured against the operator the outer solve targeted
        # (M_std_full = fat+Naik for improved staggered)
        outer_m = getattr(mg.adapter, "M_std_full", mg.adapter.M_std)
        r_pairs = b_pairs - outer_m(res.x)
        true_res = float(jnp.sqrt(blas.norm2(r_pairs)
                                  / blas.norm2(b_pairs)))
        x_np = np.asarray(res.x)
        return res._replace(x=jnp.asarray(
            (x_np[..., 0] + 1j * x_np[..., 1]).astype(b_np.dtype))), \
            true_res
    if isinstance(mg, PairMG):
        mg = None
    res, mg = mg_solve(d_full, _ctx["geom"], b, params, tol=param.tol,
                       nkrylov=param.gcrNkrylov, mg=mg)
    _ctx["mg"] = mg
    _ctx["mg_epoch"] = _ctx["gauge_epoch"]
    from ..obs import memory as omem
    omem.track("mg", "hierarchy", mg)
    return res, None


def new_multigrid_quda(mg_param: MultigridParamAPI, invert_param: InvertParam):
    """newMultigridQuda: run setup, keep hierarchy resident."""
    _require_init()
    mg_param.validate()
    from ..mg.mg import MG
    d = _build_dirac(invert_param, False)
    params = _mg_level_params(mg_param)
    on_tpu = jax.default_backend() == "tpu"
    if _mg_pairs_enabled(d, invert_param, on_tpu):
        # resident hierarchy in the complex-free representation so the
        # subsequent packed invert_quda reuses it (mg/pair.py)
        from ..mg.pair import PairMG
        _ctx["mg"] = PairMG(d, _ctx["geom"], params)
    else:
        _ctx["mg"] = MG(d, _ctx["geom"], params)
    _ctx["mg_epoch"] = _ctx["gauge_epoch"]
    from ..obs import memory as omem
    omem.track("mg", "hierarchy", _ctx["mg"])
    return _ctx["mg"]


def update_multigrid_quda(mg_param: MultigridParamAPI,
                          invert_param: InvertParam):
    """updateMultigridQuda (interface_quda.cpp:2789): refresh the resident
    hierarchy against the CURRENT resident gauge (after an HMC update or
    a new configuration load)."""
    _require_init()
    _ctx["mg"] = None
    return new_multigrid_quda(mg_param, invert_param)


def destroy_multigrid_quda():
    _ctx["mg"] = None
    from ..obs import memory as omem
    omem.release("mg", "hierarchy")


@_pm_api("invert_multishift_quda", payload="source")
def invert_multishift_quda(source, param: InvertParam):
    """invertMultiShiftQuda: (A + offset_i) x_i = b on the PC normal op."""
    _require_init()
    param.validate()
    from ..obs import trace as otr
    from ..robust import escalate as resc
    with otr.api_span("invert_multishift_quda",
                      dslash=param.dslash_type,
                      n_shifts=len(param.offset),
                      **_serve_rid_attrs()), \
            _hbm_sampled("invert_multishift_quda"):
        if resc.enabled():
            return resc.run_ladder(_invert_multishift_body, source,
                                   param, api="invert_multishift_quda")
        return _invert_multishift_body(source, param)


def _publish_multishift(res, rhs, param, tol=None, stage_note=None):
    """Convergence history for a multishift route: base-system residuals
    + per-shift lanes/converged-at events (obs/convergence.py).

    ``tol`` is the tolerance the RECORDED stage actually ran at (the
    dtype-sloppy route clamps to 1e-4; labeling that history with
    param.tol would produce a record that looks 6 orders short of a
    tolerance nothing was judged against).  ``stage_note`` marks a
    record that covers only part of the route (e.g. unrecorded
    per-shift refinement CGs follow)."""
    from ..obs import convergence as oconv
    if getattr(res, "history", None) is None:
        return
    rec = oconv.harvest("multi-shift-cg", res,
                        tol=param.tol if tol is None else tol,
                        b2=float(blas.norm2(rhs)))
    if rec is not None and stage_note is not None:
        rec.events.insert(0, {"type": "stage", "note": stage_note})
    oconv.publish(rec, param)


def _invert_multishift_body(source, param: InvertParam):
    from ..obs import trace as otr
    from ..solvers.multishift import multishift_cg
    recording = otr.enabled()
    b = jnp.asarray(source, complex_dtype(param.cuda_prec))
    d = _build_dirac(param, True)
    be, bo = _split(b, param, d)

    def _account(n_extra_mv: int = 0):
        """Populate param.gflops like invert_quda does (monitor parity,
        lib/monitor.cpp solver fields).  Hermitian PC (staggered): the
        shifted solves apply M once per iteration; otherwise the normal
        equations cost MdagM = 2 applies.  Polish solves add their own.
        PC convention: flops_per_site_M is per UPDATED site, so the PC
        operator charges volume/2 (see invert_quda's accounting note)."""
        flops = getattr(d, "flops_per_site_M", lambda: 0)()
        sites = _ctx["geom"].volume // 2
        mv_per_iter = 1.0 if getattr(d, "hermitian", False) else 2.0
        param.gflops = ((param.iter_count * mv_per_iter + n_extra_mv)
                        * flops * sites) / 1e9
        _record_solve_metrics("invert_multishift_quda", _solve_form(d),
                              "multishift-cg", param.secs,
                              param.dslash_type, param.cuda_prec)

    on_tpu = jax.default_backend() == "tpu"
    if (param.dslash_type in ("staggered", "asqtad", "hisq")
            and (param.cuda_prec == "single" or on_tpu)
            and _packed_enabled(on_tpu)):
        # complex-free multishift (the RHMC rational-force hot path):
        # shared-Krylov solve entirely on pair arrays (CG coefficients
        # on the Hermitian PC operator are real, so the pair
        # representation is exact), pallas eo stencil on real TPU
        t0 = time.perf_counter()
        ad = _StaggeredPairsSolve(d, _pallas_enabled(on_tpu),
                                  _pallas_interpret(on_tpu))
        rhs_pp = ad.prepare(be, bo)
        with otr.phase("compute", "invert_multishift_quda"):
            res = multishift_cg(ad.M, rhs_pp, tuple(param.offset),
                                tol=param.tol, maxiter=param.maxiter,
                                record=recording)
        param.iter_count = int(res.iters)
        param.secs = time.perf_counter() - t0
        _account()
        _publish_multishift(res, rhs_pp, param)
        r0 = rhs_pp - (ad.M(res.x[0])
                       + param.offset[0] * res.x[0].astype(jnp.float32))
        param.true_res = float(jnp.sqrt(blas.norm2(r0)
                                        / blas.norm2(rhs_pp)))
        _solve_supervision(param, "invert_multishift_quda",
                           breakdown=getattr(res, "breakdown", None),
                           converged_multi=res.converged)
        return jnp.stack([ad.op._from_pairs(res.x[i], b.dtype)
                          for i in range(len(param.offset))])

    if (param.dslash_type == "wilson"
            and (param.cuda_prec == "single" or on_tpu)
            and _packed_enabled(on_tpu)):
        # complex-free Wilson multishift: shared-Krylov CGNR on the
        # packed pair representation end to end (coefficients of the
        # shifted normal-equation solves are real — exact on pairs)
        if param.cuda_prec_sloppy in ("half", "quarter"):
            # EXPLICIT sloppy request (not an 'auto' resolution): served
            # at f32 pairs (>= requested quality) — say so instead of
            # silently ignoring it
            qlog.printq(
                f"multishift: cuda_prec_sloppy="
                f"'{param.cuda_prec_sloppy}' served at f32 pair storage "
                "on the complex-free route", qlog.VERBOSE)
        t0 = time.perf_counter()
        sl = d.packed().pairs(jnp.float32,
                              use_pallas=_pallas_enabled(on_tpu),
                              pallas_interpret=_pallas_interpret(on_tpu))
        rhs_pp = sl.prepare_pairs(be, bo)
        nrm_rhs = sl.Mdag_pairs(rhs_pp)
        with otr.phase("compute", "invert_multishift_quda"):
            res = multishift_cg(sl.MdagM_pairs, nrm_rhs,
                                tuple(param.offset), tol=param.tol,
                                maxiter=param.maxiter, record=recording)
        param.iter_count = int(res.iters)
        param.secs = time.perf_counter() - t0
        _account()
        _publish_multishift(res, nrm_rhs, param)
        r0 = nrm_rhs - (sl.MdagM_pairs(res.x[0])
                        + param.offset[0] * res.x[0].astype(jnp.float32))
        param.true_res = float(jnp.sqrt(blas.norm2(r0)
                                        / blas.norm2(nrm_rhs)))
        _solve_supervision(param, "invert_multishift_quda",
                           breakdown=getattr(res, "breakdown", None),
                           converged_multi=res.converged)
        return jnp.stack([sl.solution_from_pairs(res.x[i], b.dtype)
                          for i in range(len(param.offset))])

    rhs = d.prepare(be, bo)
    if getattr(d, "hermitian", False):
        mv = d.M
    else:
        mv = lambda v: d.Mdag(d.M(v))
        rhs = d.Mdag(rhs)
    t0 = time.perf_counter()
    shifts = tuple(param.offset)
    sloppy_prec = _resolve_sloppy(param)
    pair_sloppy = (sloppy_prec in ("half", "quarter")
                   and param.dslash_type == "wilson")
    if pair_sloppy:
        # QUDA's multi-shift strategy (lib/inv_multi_cg_quda.cpp final
        # phase): run the shared-Krylov solve at sloppy precision, then
        # polish each shift with a short precise-level CG seeded by the
        # sloppy solution.
        from ..solvers.cg import cg as cg_solve
        sl = d.sloppy(sloppy_prec)
        with otr.phase("compute", "invert_multishift_quda"):
            res = multishift_cg(sl.MdagM, rhs.astype(jnp.complex64),
                                shifts, tol=max(param.tol, 1e-4),
                                maxiter=param.maxiter, record=recording)
        _publish_multishift(
            res, rhs, param, tol=max(param.tol, 1e-4),
            stage_note="sloppy shared-Krylov stage (tol clamped to "
                       "1e-4); per-shift precise refinement CGs follow "
                       "and are not recorded, so param.iter_count "
                       "exceeds this history's length")
        xs, iters, conv_s = [], int(res.iters), []
        for i, s in enumerate(shifts):
            mv_s = (lambda sig: lambda v: mv(v) + sig * v)(s)
            ref = cg_solve(mv_s, rhs, x0=res.x[i].astype(rhs.dtype),
                           tol=param.tol, maxiter=param.maxiter)
            xs.append(ref.x)
            iters += int(ref.iters)
            conv_s.append(bool(ref.converged))
        param.iter_count = iters
        param.secs = time.perf_counter() - t0
        _account()
        r0 = rhs - (mv(xs[0]) + shifts[0] * xs[0])
        param.true_res = float(jnp.sqrt(blas.norm2(r0) / blas.norm2(rhs)))
        # convergence judged on the precise-level per-shift polish CGs
        _solve_supervision(param, "invert_multishift_quda",
                           converged_multi=conv_s)
        return jnp.stack(xs)
    with otr.phase("compute", "invert_multishift_quda"):
        res = multishift_cg(mv, rhs, shifts, tol=param.tol,
                            maxiter=param.maxiter, record=recording)
    param.iter_count = int(res.iters)
    param.secs = time.perf_counter() - t0
    _account()
    _publish_multishift(res, rhs, param)
    r0 = rhs - (mv(res.x[0]) + shifts[0] * res.x[0])
    param.true_res = float(jnp.sqrt(blas.norm2(r0) / blas.norm2(rhs)))
    _solve_supervision(param, "invert_multishift_quda",
                       breakdown=getattr(res, "breakdown", None),
                       converged_multi=res.converged)
    return res.x


def dslash_quda(psi, param: InvertParam, parity: int):
    """dslashQuda: apply the PC hop D_{parity, 1-parity}."""
    _require_init()
    d = _build_dirac(param, True)
    return d.D_to(jnp.asarray(psi, complex_dtype(param.cuda_prec)), parity)


def mat_quda(psi, param: InvertParam):
    """MatQuda: full operator application."""
    _require_init()
    d = _build_dirac(param, False)
    return d.M(jnp.asarray(psi, complex_dtype(param.cuda_prec)))


def mat_dag_mat_quda(psi, param: InvertParam):
    _require_init()
    d = _build_dirac(param, False)
    return d.MdagM(jnp.asarray(psi, complex_dtype(param.cuda_prec)))


@_pm_api("eigensolve_quda")
def eigensolve_quda(eig_param: EigParamAPI, invert_param: InvertParam):
    """eigensolveQuda: returns (evals, evecs)."""
    _require_init()
    eig_param.validate()
    from ..obs import trace as otr
    with otr.api_span("eigensolve_quda", eig_type=eig_param.eig_type,
                      n_ev=eig_param.n_ev,
                      dslash=invert_param.dslash_type), \
            _hbm_sampled("eigensolve_quda"):
        return _eigensolve_body(eig_param, invert_param)


def _eigensolve_body(eig_param: EigParamAPI, invert_param: InvertParam):
    from ..eig.iram import iram
    from ..eig.lanczos import EigParam, trlm
    from ..obs import trace as otr
    with otr.phase("setup", "eigensolve_quda"):
        pc = invert_param.solve_type.endswith("-pc")
        d = _build_dirac(invert_param, pc)
    geom = _ctx["geom"]
    dtype = complex_dtype(invert_param.cuda_prec)
    shape = (geom.half_lattice_shape if pc else geom.lattice_shape) + (4, 3)
    if invert_param.dslash_type in ("staggered", "asqtad", "hisq"):
        shape = shape[:-2] + (1, 3)
    if invert_param.dslash_type in ("ndeg-twisted-mass",
                                    "ndeg-twisted-clover"):
        shape = shape[:-2] + (2, 4, 3)   # flavor doublet axis
    if invert_param.dslash_type in _DWF_TYPES:
        shape = (invert_param.Ls,) + shape
    p = EigParam(n_ev=eig_param.n_ev, n_kr=eig_param.n_kr,
                 tol=eig_param.tol, max_restarts=eig_param.max_restarts,
                 use_poly_acc=eig_param.use_poly_acc,
                 poly_deg=eig_param.poly_deg, a_min=eig_param.a_min,
                 a_max=eig_param.a_max, spectrum=eig_param.spectrum)
    on_tpu = jax.default_backend() == "tpu"
    if (eig_param.eig_type == "trlm" and eig_param.use_norm_op and pc
            and _packed_enabled(on_tpu)
            and (invert_param.cuda_prec == "single" or on_tpu)
            and invert_param.dslash_type in ("wilson", "staggered",
                                             "asqtad", "hisq")):
        # complex-free TRLM (eig/pair_eig.py): the only eigensolve that
        # executes on TPU runtimes without complex64.  Realified
        # Hermitian Lanczos on the pair operator; kept vectors convert
        # to complex at the host boundary.  Dispatched BEFORE the
        # complex example/operator construction below so no complex
        # device array is materialised on this path.
        import numpy as np
        from ..eig.pair_eig import trlm_pairs
        T, Z, Y, X = geom.lattice_shape
        if invert_param.dslash_type == "wilson":
            sl = d.packed().pairs(
                jnp.float32, use_pallas=_pallas_enabled(on_tpu),
                pallas_interpret=_pallas_interpret(on_tpu))
            mv = sl.MdagM_pairs
            ex_pp = jnp.zeros((4, 3, 2, T, Z, Y * X // 2), jnp.float32)
            pair_axis = 2
            conv = sl.solution_from_pairs
        else:
            ad = _StaggeredPairsSolve(d, _pallas_enabled(on_tpu),
                                      _pallas_interpret(on_tpu))
            mv = ad.M
            ex_pp = jnp.zeros((3, 2, T, Z, Y * X // 2), jnp.float32)
            pair_axis = 1
            conv = ad.op._from_pairs
        t_eig0 = time.perf_counter()
        with otr.phase("compute", "eigensolve_quda",
                       solver="trlm_pairs"):
            res = trlm_pairs(mv, ex_pp, p, pair_axis)
        from ..obs import memory as omem
        from ..obs import metrics as omet
        _record_solve_metrics("eigensolve_quda", "trlm_pairs",
                              eig_param.eig_type,
                              time.perf_counter() - t_eig0,
                              invert_param.dslash_type,
                              invert_param.cuda_prec)
        omet.inc("eigensolves_total", family=invert_param.dslash_type,
                 eig_type=eig_param.eig_type)
        omem.track("eig", "evecs_trlm_pairs", res.evecs)
        if res.evecs.shape[0] < eig_param.n_ev:
            qlog.printq(
                f"eigensolve (pair route): only {res.evecs.shape[0]} of "
                f"{eig_param.n_ev} eigenpairs converged/deduplicated — "
                "raise n_kr/max_restarts or loosen tol",
                qlog.SUMMARIZE)
        evecs_h = np.stack([np.asarray(conv(res.evecs[i], dtype))
                            for i in range(res.evecs.shape[0])])
        # host-side modified Gram-Schmidt: converged non-degenerate
        # vectors are already orthonormal (the rotation is ~identity);
        # within DEGENERATE eigenspaces the realified dedup only
        # guarantees |overlap| < 0.5, and deflation consumers assume an
        # orthonormal basis
        for i in range(evecs_h.shape[0]):
            for k in range(i):
                ov = np.vdot(evecs_h[k], evecs_h[i])
                evecs_h[i] = evecs_h[i] - ov * evecs_h[k]
            evecs_h[i] /= np.sqrt(np.vdot(evecs_h[i],
                                          evecs_h[i]).real)
        evecs = jnp.asarray(evecs_h)
        if eig_param.vec_outfile:
            from ..utils.io import save_vectors
            save_vectors(eig_param.vec_outfile, evecs, res.evals)
        return res.evals, evecs
    example = jnp.zeros(shape, dtype)
    if eig_param.use_norm_op:
        # staggered PC: M already IS the (Hermitian) normal operator
        op = d.M if getattr(d, "hermitian", False) else d.MdagM
    else:
        op = d.M
    t_eig0 = time.perf_counter()
    with otr.phase("compute", "eigensolve_quda",
                   solver=eig_param.eig_type):
        if eig_param.eig_type == "trlm":
            res = trlm(op, example, p)
        elif eig_param.eig_type == "arpack":
            # host ARPACK bridge (lib/arpack_interface.cpp analog)
            from ..eig.arpack_bridge import arpack_solve
            res = arpack_solve(op, example, p,
                               hermitian=eig_param.use_norm_op)
        else:
            res = iram(op, example, p)
    from ..obs import memory as omem
    from ..obs import metrics as omet
    _record_solve_metrics("eigensolve_quda", eig_param.eig_type,
                          eig_param.eig_type,
                          time.perf_counter() - t_eig0,
                          invert_param.dslash_type,
                          invert_param.cuda_prec)
    omet.inc("eigensolves_total", family=invert_param.dslash_type,
             eig_type=eig_param.eig_type)
    omem.track("eig", f"evecs_{eig_param.eig_type}", res.evecs)
    if eig_param.vec_outfile:
        from ..utils.io import save_vectors
        save_vectors(eig_param.vec_outfile, res.evecs, res.evals)
    return res.evals, res.evecs


# -- gauge utilities -------------------------------------------------------

def plaq_quda():
    from ..gauge.observables import plaquette
    _require_init()
    m, s, t = plaquette(_ctx["gauge"])
    return float(m), float(s), float(t)


def gauge_observables_quda():
    from ..gauge.observables import energy, plaquette, polyakov_loop, qcharge
    _require_init()
    g = _ctx["gauge"]
    return {
        "plaquette": tuple(float(x) for x in plaquette(g)),
        "polyakov_loop": complex(polyakov_loop(g)),
        "qcharge": float(qcharge(g)),
        "energy": tuple(float(x) for x in energy(g)),
    }


def gauss_gauge_quda(seed: int, sigma: float):
    """gaussGaugeQuda: randomise the resident gauge field."""
    from ..ops.su3 import random_su3
    _require_init()
    key = jax.random.PRNGKey(seed)
    _set_resident_gauge(random_su3(key, (4,) + _ctx["geom"].lattice_shape,
                                   _ctx["gauge"].dtype, scale=sigma))


def perform_gauge_smear_quda(smear_type: str, n_steps: int, **kw):
    """performGaugeSmearQuda: ape|stout|ovrimp-stout|hyp on resident gauge."""
    from ..gauge import smear as gsm
    _require_init()
    g = _ctx["gauge"]
    if smear_type == "ape":
        g = gsm.ape_smear(g, kw.get("alpha", 0.6), n_steps=n_steps)
    elif smear_type == "stout":
        g = gsm.stout_smear(g, kw.get("rho", 0.1), n_steps=n_steps)
    elif smear_type == "ovrimp-stout":
        g = gsm.stout_smear(g, kw.get("rho", 0.08), n_steps=n_steps,
                            epsilon=kw.get("epsilon", -0.25))
    elif smear_type == "hyp":
        g = gsm.hyp_smear(g, n_steps=n_steps)
    else:
        qlog.errorq(f"unknown smear type {smear_type}")
    _set_resident_gauge(g)


def perform_wflow_quda(n_steps: int, eps: float, smear_type="wilson",
                       measure=None):
    from ..gauge.smear import symanzik_flow_step, wilson_flow_step
    _require_init()
    step = wilson_flow_step if smear_type == "wilson" else symanzik_flow_step
    hist = []
    g = _ctx["gauge"]
    for i in range(n_steps):
        g = step(g, eps)
        if measure:
            hist.append(measure(g, (i + 1) * eps))
    _set_resident_gauge(g)
    return hist


def compute_gauge_fixing_ovr_quda(gauge_dirs: int = 4, **kw):
    from ..gauge.fix import gaugefix_ovr
    _require_init()
    g, iters, theta = gaugefix_ovr(_ctx["gauge"], _ctx["geom"],
                                   gauge_dirs=gauge_dirs, **kw)
    _set_resident_gauge(g)
    return iters, theta


def compute_gauge_fixing_fft_quda(gauge_dirs: int = 4, **kw):
    from ..gauge.fix import gaugefix_fft
    _require_init()
    g, iters, theta = gaugefix_fft(_ctx["gauge"], _ctx["geom"],
                                   gauge_dirs=gauge_dirs, **kw)
    _set_resident_gauge(g)
    return iters, theta


def compute_ks_link_quda(naik_eps: float = 0.0):
    """computeKSLinkQuda: HISQ fatten the resident gauge; keep fat/long
    resident for staggered inverts."""
    from ..gauge.hisq import hisq_fattening
    from ..obs import memory as omem
    _require_init()
    links = hisq_fattening(_ctx["gauge"], naik_eps)
    _ctx["fat"] = links.fat
    _ctx["long"] = links.long
    omem.track("fat_naik", "fat_links", links.fat)
    omem.track("fat_naik", "long_links", links.long)
    return links


def load_fat_long_quda(fat, long_links):
    from ..obs import memory as omem
    _require_init()
    dtype = _ctx["gauge"].dtype if _ctx["gauge"] is not None else None
    _ctx["fat"] = jnp.asarray(fat, dtype)
    _ctx["long"] = jnp.asarray(long_links, dtype)
    omem.track("fat_naik", "fat_links", _ctx["fat"])
    omem.track("fat_naik", "long_links", _ctx["long"])


def save_gauge_field_quda(path: str, precision: int = 64):
    """Write the resident gauge as a SciDAC/ILDG lime file
    (lib/qio_field.cpp write path analog).  The anisotropy folded in at
    load time is UNDONE so the file holds the original links (QUDA
    saveGaugeQuda semantics)."""
    from ..utils.lime import save_gauge_lime
    _require_init()
    if _ctx["gauge"] is None:
        qlog.errorq("no resident gauge to save")
    g = _ctx["gauge"]
    gp = _ctx["gauge_param"]
    if gp is not None and gp.anisotropy != 1.0:
        scale = jnp.ones((4, 1, 1, 1, 1, 1, 1), g.real.dtype)
        scale = scale.at[:3].set(gp.anisotropy)
        g = g * scale.astype(g.dtype)
    save_gauge_lime(path, g, _ctx["geom"], precision=precision)


def load_gauge_field_quda(path: str, param: GaugeParam = None):
    """Read a SciDAC/ILDG lime file and make it the resident gauge
    (lib/qio_field.cpp read path analog).  Returns the gauge array.

    The caller's param is copied, its X replaced by the file geometry,
    and gauge_order forced canonical (file data is always canonical)."""
    import dataclasses

    from ..utils.lime import load_gauge_lime
    _require_init()
    gauge, meta = load_gauge_lime(path)
    gp = dataclasses.replace(param or GaugeParam(), X=meta["dims"],
                             gauge_order="canonical")
    load_gauge_quda(gauge, gp)
    return gauge


def compute_gauge_force_quda(beta: float, c1: float = 0.0):
    from ..gauge.action import gauge_force, improved_action, wilson_action
    _require_init()
    act = (lambda u: wilson_action(u, beta)) if c1 == 0.0 else \
        (lambda u: improved_action(u, beta, c1))
    return gauge_force(act, _ctx["gauge"])


def compute_gauge_force_paths_quda(mom, input_path_buf, loop_coeff,
                                   dt: float):
    """computeGaugeForceQuda (quda.h:1393): arbitrary user path tables.

    input_path_buf[mu][i] = i-th path (MILC encoding, backward = 7-mu)
    completing a loop with the initial U_mu; loop_coeff the per-path
    coefficients.  Returns mom - dt * F with F the su(3)-projected force
    of the path action (AD; staple math of gauge_force.cuh subsumed).
    """
    from ..gauge.paths import gauge_path_force
    _require_init()
    f = gauge_path_force(_ctx["gauge"], input_path_buf, loop_coeff)
    return jnp.asarray(mom) - dt * f


def gauge_loop_trace_quda(paths, coeffs, factor: float = 1.0):
    """gaugeLoopTraceQuda (quda.h:1420, lib/gauge_loop_trace.cu:74):
    returns one complex trace per loop, factor * c_i * sum_x tr W_i(x),
    as a (num_paths,) array (matching the C API's traces[] output)."""
    from ..gauge.paths import gauge_loop_trace
    _require_init()
    return factor * gauge_loop_trace(_ctx["gauge"], paths, coeffs)


def update_gauge_field_quda(mom, dt: float, reunitarize: bool = True):
    from ..gauge.action import update_gauge
    from ..ops.su3 import project_su3
    _require_init()
    g = update_gauge(_ctx["gauge"], mom, dt)
    if reunitarize:
        g = project_su3(g)
    _set_resident_gauge(g)


def mom_action_quda(mom):
    from ..gauge.action import mom_action
    return float(mom_action(mom))


def perform_wuppertal_n_step(psi, n_steps: int, alpha: float = 3.0):
    """performWuppertalnStep (interface_quda.cpp:4935)."""
    from ..gauge.quark_smear import wuppertal_smear
    _require_init()
    return wuppertal_smear(_ctx["gauge"], jnp.asarray(psi), alpha, n_steps)


def perform_two_link_gaussian_smear(psi, n_steps: int, omega: float = 2.0):
    """performTwoLinkGaussianSmearNStep: two-link staggered smearing."""
    from ..gauge.hisq import two_link
    from ..gauge.quark_smear import gaussian_smear
    _require_init()
    tl = two_link(_ctx["gauge"])
    return gaussian_smear(_ctx["gauge"], jnp.asarray(psi), omega, n_steps,
                          two_link_gauge=tl)


def laph_sink_project_quda(evecs, psi):
    """laphSinkProject (quda.h:1859)."""
    from ..ops.contract import laph_sink_project
    return laph_sink_project(jnp.asarray(evecs), jnp.asarray(psi))


def perform_gflow_quda(phi, n_steps: int, eps: float):
    """performGFlowQuda: joint gauge+fermion gradient flow; updates the
    resident gauge and returns the flowed fermion."""
    from ..gauge.smear import fermion_flow
    _require_init()
    g, p = fermion_flow(_ctx["gauge"], jnp.asarray(phi), eps, n_steps)
    _set_resident_gauge(g)
    return p


def contract_quda(x, y, contract_type: str = "open", momenta=None):
    from ..ops.contract import contract_dr, contract_ft, contract_open_spin
    if contract_type == "open":
        return contract_open_spin(jnp.asarray(x), jnp.asarray(y))
    if contract_type == "dr":
        return contract_dr(jnp.asarray(x), jnp.asarray(y))
    if contract_type == "ft":
        return contract_ft(jnp.asarray(x), jnp.asarray(y),
                           momenta or [(0, 0, 0)])
    qlog.errorq(f"unknown contract type {contract_type}")
