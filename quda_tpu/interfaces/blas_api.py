"""blasGEMMQuda / blasLUInvQuda analogs — the public batched-BLAS entry
points.

Reference behavior: `include/quda.h:1779-1788` (blasGEMMQuda,
blasLUInvQuda) with QudaBLASParam (`include/quda.h:871-902`), dispatched
in `lib/interface/blas_interface.cpp` to strided-batch GEMM / batched
LU-inverse backends (cuBLAS or Eigen).  Semantics implemented here:

- flat host arrays addressed by (offset, leading dimension, stride),
  where strides are in units of matrices and stride == 0 means densely
  packed (`lib/targets/generic/blas_lapack_eigen.cpp`: effective element
  stride = batch_matrix_size * max(stride, 1));
- op(A)/op(B) in {n, t, c} (none / transpose / conjugate-transpose);
- row- or column-major storage (the reference swaps A<->B and re-labels
  dims to feed column-major cuBLAS; here the order just selects the
  reshape);
- alpha/beta complex scalars, C = alpha op(A) op(B) + beta C;
- data types S/C/D/Z.  S/C run batched on the accelerator via jnp
  einsum / jnp.linalg.inv (XLA batched GEMM / LU are MXU-native);
  D/Z have no TPU hardware path and run on the host via numpy —
  same split the reference makes between native and generic backends.

The flat-array entry points exist for API parity with host applications
that call QUDA as a BLAS utility; :func:`gemm_batched` is their traced
in-framework sibling — a jit-safe strided-batched GEMM on device arrays
(no host roundtrip, no flat addressing) that the MG coarse-stencil
construction (mg/gemm.py) contracts through, the way the reference's
calculateY leans on the cuBLAS strided-batch backend.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from .params import _check

BLAS_DTYPES = {"S": np.float32, "D": np.float64,
               "C": np.complex64, "Z": np.complex128}


@dataclasses.dataclass
class BLASParam:
    """QudaBLASParam (quda.h:871).  Defaults follow newQudaBLASParam."""
    blas_type: str = "gemm"        # gemm | lu-inv
    trans_a: str = "n"             # n | t | c
    trans_b: str = "n"
    m: int = 0
    n: int = 0
    k: int = 0
    lda: int = 0
    ldb: int = 0
    ldc: int = 0
    a_offset: int = 0
    b_offset: int = 0
    c_offset: int = 0
    a_stride: int = 1              # units of matrices; 0 = packed
    b_stride: int = 1
    c_stride: int = 1
    alpha: complex = 1.0
    beta: complex = 0.0
    inv_mat_size: int = 0          # rank of the square matrix for lu-inv
    batch_count: int = 1
    data_type: str = "C"           # S | D | C | Z
    data_order: str = "col"        # row | col

    def validate(self):
        _check(self.blas_type in ("gemm", "lu-inv"),
               f"bad blas_type {self.blas_type}")
        _check(self.data_type in BLAS_DTYPES,
               f"bad data_type {self.data_type}")
        _check(self.data_order in ("row", "col"),
               f"bad data_order {self.data_order}")
        _check(self.batch_count > 0, "batch_count must be positive")
        if self.blas_type == "gemm":
            if self.data_type in ("S", "D"):
                _check(np.imag(self.alpha) == 0 and np.imag(self.beta) == 0,
                       "complex alpha/beta with real data_type "
                       f"{self.data_type}")
            _check(self.trans_a in ("n", "t", "c"), "bad trans_a")
            _check(self.trans_b in ("n", "t", "c"), "bad trans_b")
            _check(self.m > 0 and self.n > 0 and self.k > 0,
                   f"bad gemm dims m={self.m} n={self.n} k={self.k}")
            _check(min(self.a_stride, self.b_stride, self.c_stride) >= 0,
                   "BLAS strides must be positive or zero")
            # leading-dimension consistency (checkBLASParam analog)
            if self.data_order == "col":
                _check(self.lda >= (self.m if self.trans_a == "n" else
                                    self.k), "lda too small")
                _check(self.ldb >= (self.k if self.trans_b == "n" else
                                    self.n), "ldb too small")
                _check(self.ldc >= self.m, "ldc too small")
            else:
                _check(self.lda >= (self.k if self.trans_a == "n" else
                                    self.m), "lda too small")
                _check(self.ldb >= (self.n if self.trans_b == "n" else
                                    self.k), "ldb too small")
                _check(self.ldc >= self.n, "ldc too small")
        else:
            _check(self.inv_mat_size > 0, "inv_mat_size must be positive")
        return self

    def describe(self) -> str:
        return "\n".join(f"{f.name} = {getattr(self, f.name)}"
                         for f in dataclasses.fields(self))


def _op_traced(mats: jnp.ndarray, trans: str) -> jnp.ndarray:
    """op(X) on a (..., r, c) device array, trans in {n, t, c}."""
    _check(trans in ("n", "t", "c"), f"bad trans {trans!r}")
    if trans == "n":
        return mats
    out = jnp.swapaxes(mats, -1, -2)
    return jnp.conjugate(out) if trans == "c" else out


def gemm_batched(a: jnp.ndarray, b: jnp.ndarray, trans_a: str = "n",
                 trans_b: str = "n", alpha=1.0, c: jnp.ndarray = None,
                 beta=0.0) -> jnp.ndarray:
    """Traced strided-batched GEMM: alpha op(A) op(B) [+ beta C] over
    arbitrary leading batch axes — the in-framework (jit-safe, no host
    roundtrip) sibling of :func:`blas_gemm_quda`, dispatching to XLA's
    batched dot (the MXU-native path the flat entry point reshapes
    into).  ``op`` is applied to the STORED arrays (the flat API's
    convention): op(A) must come out (..., m, k) and op(B) (..., k, n)
    — i.e. pass A stored as (..., k, m) when trans_a is 't'/'c'.
    Leading axes broadcast.  Used by the MG coarse-link construction
    (mg/gemm.py) so the Galerkin contraction is one batched GEMM per
    hop direction instead of a per-column probe loop."""
    out = jnp.matmul(_op_traced(a, trans_a), _op_traced(b, trans_b),
                     preferred_element_type=None)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    if c is not None and beta != 0.0:
        out = out + jnp.asarray(beta, out.dtype) * c
    return out


def _stored_dims(rows_op, cols_op, trans):
    """(stored_rows, stored_cols) of the array holding op(X)."""
    return (rows_op, cols_op) if trans == "n" else (cols_op, rows_op)


def _gather_batch(flat, offset, ld, rows, cols, stride, order, nbatch):
    """Slice nbatch (rows, cols) matrices out of a flat array.

    Column-major: element (i, j) of batch b lives at
    offset + b*elem_stride + j*ld + i; row-major swaps i/j roles.
    elem_stride = matrix_size * max(stride, 1)  (stride in matrices,
    0 = packed, matching blas_lapack's batch addressing).
    """
    if order == "col":
        mat_elems = ld * cols
        elem_stride = mat_elems * max(stride, 1)
        need = offset + (nbatch - 1) * elem_stride + mat_elems
        _check(flat.size >= need,
               f"array too small: have {flat.size}, need {need}")
        idx = (offset + np.arange(nbatch)[:, None, None] * elem_stride
               + np.arange(cols)[None, :, None] * ld
               + np.arange(rows)[None, None, :])
        return flat[idx].transpose(0, 2, 1)      # -> (b, rows, cols)
    mat_elems = rows * ld
    elem_stride = mat_elems * max(stride, 1)
    need = offset + (nbatch - 1) * elem_stride + mat_elems
    _check(flat.size >= need,
           f"array too small: have {flat.size}, need {need}")
    idx = (offset + np.arange(nbatch)[:, None, None] * elem_stride
           + np.arange(rows)[None, :, None] * ld
           + np.arange(cols)[None, None, :])
    return flat[idx]                             # (b, rows, cols)


def _scatter_batch(flat, mats, offset, ld, rows, cols, stride, order):
    """Inverse of _gather_batch: write (b, rows, cols) back into flat."""
    nbatch = mats.shape[0]
    if order == "col":
        mat_elems = ld * cols
        elem_stride = mat_elems * max(stride, 1)
        idx = (offset + np.arange(nbatch)[:, None, None] * elem_stride
               + np.arange(cols)[None, :, None] * ld
               + np.arange(rows)[None, None, :])
        flat[idx] = mats.transpose(0, 2, 1)
    else:
        mat_elems = rows * ld
        elem_stride = mat_elems * max(stride, 1)
        idx = (offset + np.arange(nbatch)[:, None, None] * elem_stride
               + np.arange(rows)[None, :, None] * ld
               + np.arange(cols)[None, None, :])
        flat[idx] = mats


def _apply_op(mats, trans):
    if trans == "n":
        return mats
    if trans == "t":
        return mats.transpose(0, 2, 1)
    return np.conj(mats.transpose(0, 2, 1))


def blas_gemm_quda(array_a, array_b, array_c, param: BLASParam,
                   use_native: bool = True):
    """C = alpha op(A) op(B) + beta C, strided-batched over flat arrays.

    Returns a new flat array with the updated C (the C analog mutates
    arrayC in place; a functional return fits the JAX world).  With
    ``use_native`` and an S/C data type the batched product runs on the
    accelerator; otherwise numpy on the host (the generic backend).
    """
    param.validate()
    _check(param.blas_type == "gemm", "blas_gemm_quda needs blas_type=gemm")
    dt = BLAS_DTYPES[param.data_type]
    a = np.asarray(array_a).ravel().astype(dt, copy=False)
    b = np.asarray(array_b).ravel().astype(dt, copy=False)
    c = np.array(array_c).ravel().astype(dt)     # owning copy, mutated

    ar, ac = _stored_dims(param.m, param.k, param.trans_a)
    br, bc = _stored_dims(param.k, param.n, param.trans_b)
    order = param.data_order
    amats = _gather_batch(a, param.a_offset, param.lda, ar, ac,
                          param.a_stride, order, param.batch_count)
    bmats = _gather_batch(b, param.b_offset, param.ldb, br, bc,
                          param.b_stride, order, param.batch_count)
    cmats = _gather_batch(c, param.c_offset, param.ldc, param.m, param.n,
                          param.c_stride, order, param.batch_count)

    opa = _apply_op(amats, param.trans_a)        # (b, m, k)
    opb = _apply_op(bmats, param.trans_b)        # (b, k, n)
    alpha = dt(param.alpha) if param.data_type in ("C", "Z") else \
        dt(np.real(param.alpha))
    beta = dt(param.beta) if param.data_type in ("C", "Z") else \
        dt(np.real(param.beta))

    if use_native and param.data_type in ("S", "C"):
        prod = np.asarray(jnp.einsum("bij,bjk->bik",
                                     jnp.asarray(opa), jnp.asarray(opb)))
    else:
        prod = np.einsum("bij,bjk->bik", opa, opb)
    out = (alpha * prod.astype(dt) + beta * cmats).astype(dt)

    _scatter_batch(c, out, param.c_offset, param.ldc, param.m, param.n,
                   param.c_stride, param.data_order)
    return c


def blas_lu_inv_quda(array_a, param: BLASParam, use_native: bool = True):
    """Batched LU-based inverse of batch_count square matrices.

    Reference: blasLUInvQuda (`include/quda.h:1788`), which ignores
    leading dims / offsets / strides for inversions
    (`lib/interface/blas_interface.cpp`: "Leading dims, strides, and
    offsets are irrelevant for LU inversions") — matrices are densely
    packed (batch, n, n) in the data order given.  Returns the packed
    inverses as a flat array.
    """
    param.validate()
    _check(param.blas_type == "lu-inv",
           "blas_lu_inv_quda needs blas_type=lu-inv")
    n = param.inv_mat_size
    dt = BLAS_DTYPES[param.data_type]
    a = np.asarray(array_a).ravel().astype(dt, copy=False)
    _check(a.size >= param.batch_count * n * n,
           f"array too small for {param.batch_count} {n}x{n} matrices")
    # inv(A^T) = inv(A)^T, so the packed blocks invert identically in
    # either data order — no transposes needed.
    mats = a[:param.batch_count * n * n].reshape(param.batch_count, n, n)
    if use_native and param.data_type in ("S", "C"):
        inv = np.asarray(jnp.linalg.inv(jnp.asarray(mats)))
    else:
        inv = np.linalg.inv(mats)
    return inv.astype(dt).reshape(-1)
