"""MG transfer operators: geometric block aggregation with spin-chirality
blocking and block orthonormalisation.

Reference behavior: lib/transfer.cpp (Transfer::P :340 / ::R :414),
lib/block_orthogonalize.in.cu, lib/prolongator.in.cu, lib/restrictor.in.cu.

TPU-native design: aggregation is a reshape/transpose onto a blocked
layout, and block orthonormalisation is ONE batched QR over
(coarse sites x chirality) — `jnp.linalg.qr` on a
(..., block_dof, n_vec) tensor — replacing QUDA's 307-line block-Gram-
Schmidt kernel.  Prolong/restrict are single einsums (MXU matmuls).

Canonical chiral layout: any field enters as (lat..., 2, K) where 2 is the
gamma5 chirality (fine fermions: spin 4 -> (chir 2, spin-in-chir 2), K=6;
coarse fields: K = n_vec of the level below).  Spin-chirality blocking
(QUDA spin_bs=2) preserves gamma5 = diag(+1,-1) on every level.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ops import blas


def to_chiral(psi: jnp.ndarray) -> jnp.ndarray:
    """(lat..., 4, 3) -> (lat..., 2, 6): spins (0,1)->chir 0, (2,3)->chir 1."""
    lat = psi.shape[:-2]
    return psi.reshape(lat + (2, 6))


def from_chiral(psi: jnp.ndarray) -> jnp.ndarray:
    lat = psi.shape[:-2]
    return psi.reshape(lat + (4, 3))


@dataclasses.dataclass
class Transfer:
    """Block transfer between a (T,Z,Y,X) fine level and its coarse level.

    v: (Tc,Zc,Yc,Xc, 2, D, N) orthonormal aggregates
       (D = prod(block) * K_fine, N = n_vec).
    """

    v: jnp.ndarray
    block: Tuple[int, int, int, int]   # (bt,bz,by,bx)
    fine_shape: Tuple[int, int, int, int]
    k_fine: int
    n_vec: int

    @classmethod
    def from_null_vectors(cls, null_vecs: jnp.ndarray,
                          block: Tuple[int, int, int, int]) -> "Transfer":
        """null_vecs: (N, T,Z,Y,X, 2, K) in chiral layout."""
        n, T, Z, Y, X, two, K = null_vecs.shape
        bt, bz, by, bx = block
        assert T % bt == 0 and Z % bz == 0 and Y % by == 0 and X % bx == 0, \
            (null_vecs.shape, block)
        blocked = _block_fields(null_vecs, block)   # (N, Tc,Zc,Yc,Xc, 2, D)
        # batched QR over (coarse site, chirality): columns = null vectors
        cols = jnp.moveaxis(blocked, 0, -1)         # (Tc,..,2, D, N)
        q, r = jnp.linalg.qr(cols)
        return cls(q, block, (T, Z, Y, X), K, n)

    @property
    def coarse_shape(self):
        T, Z, Y, X = self.fine_shape
        bt, bz, by, bx = self.block
        return (T // bt, Z // bz, Y // by, X // bx)

    def restrict(self, fine: jnp.ndarray) -> jnp.ndarray:
        """(T,Z,Y,X,2,K) -> (Tc,Zc,Yc,Xc,2,N): R = V^dag aggregate."""
        blocked = _block_fields(fine[None], self.block)[0]  # (Tc,..,2,D)
        return jnp.einsum("...dn,...d->...n", jnp.conjugate(self.v), blocked)

    def prolong(self, coarse: jnp.ndarray) -> jnp.ndarray:
        """(Tc,Zc,Yc,Xc,2,N) -> (T,Z,Y,X,2,K)."""
        blocked = jnp.einsum("...dn,...n->...d", self.v, coarse)
        return _unblock_fields(blocked[None], self.block, self.fine_shape,
                               self.k_fine)[0]


def _block_fields(fields: jnp.ndarray, block):
    """(B, T,Z,Y,X, 2, K) -> (B, Tc,Zc,Yc,Xc, 2, D) with
    D = bt*bz*by*bx*K; chirality stays outside the aggregate."""
    Bn, T, Z, Y, X, two, K = fields.shape
    bt, bz, by, bx = block
    r = fields.reshape(Bn, T // bt, bt, Z // bz, bz, Y // by, by,
                       X // bx, bx, two, K)
    r = r.transpose(0, 1, 3, 5, 7, 9, 2, 4, 6, 8, 10)
    return r.reshape(Bn, T // bt, Z // bz, Y // by, X // bx, two,
                     bt * bz * by * bx * K)


def _unblock_fields(blocked: jnp.ndarray, block, fine_shape, K):
    Bn = blocked.shape[0]
    T, Z, Y, X = fine_shape
    bt, bz, by, bx = block
    r = blocked.reshape(Bn, T // bt, Z // bz, Y // by, X // bx, 2,
                        bt, bz, by, bx, K)
    r = r.transpose(0, 1, 6, 2, 7, 3, 8, 4, 9, 5, 10)
    return r.reshape(Bn, T, Z, Y, X, 2, K)
