"""GEMM-built Galerkin coarse stencil: calculateY as batched contractions.

Reference behavior: lib/coarse_op.in.cu calculateY computes the coarse
link field Y and coarse clover X directly from the null-vector
aggregates with batched tensor contractions (the MMA path leans on
strided-batch GEMM).  The probing construction this module replaces
(mg/coarse.build_coarse, mg/pair.build_coarse_pairs) is exact but
dispatch-shaped like a unit test: 2*n_vec coarse unit columns x (1 diag
+ 8 hop directions x 2 parity masks) separately-jitted probes — ~34*n_vec
host-loop dispatches per level, each paying a full prolong AND restrict
GEMM for ONE column (the measured coarse_probe share of the round-5
5652 s setup scandal).

The GEMM form exploits two structural facts the probe loop ignores:

1. **The probe prolongations are free.**  Prolonging the coarse unit
   vector e_{chir,b} replicated over all coarse sites is just the
   null-vector aggregate column V[..., chir, :, b] unblocked — a
   reshape, not a GEMM.  All 2*n_vec probe inputs together are one
   batched reshape of the transfer itself.

2. **One masked application per direction separates link from diagonal.**
   A single-direction hop couples output site x only to source
   x + sign*mu, so the output of hop applied to the FULL column batch
   splits exactly by a static fine-lattice face mask: sites whose
   source crossed an aggregate boundary carry the inter-block link
   column, interior sites the intra-block diagonal contribution.  The
   probe loop needed TWO parity-masked applications per direction to
   make the same separation; the face-mask split is algebraically
   identical (tests/test_mg_gemm_coarse.py pins both layouts against
   the probe loop to fp tolerance) at half the hop applications.

Per level the whole build is then: 1 batched diag + 8 batched hop
applications over the 2*n_vec-column batch, each followed by ONE
strided-batched GEMM restriction (`interfaces/blas_api.gemm_batched`
on the complex layout; the 4-GEMM pair product on pair arrays) — 9
compiled contractions instead of ~34*n_vec dispatches, with zero
prolong work.  `QUDA_TPU_MG_COARSE_CHUNK` caps the resident column
batch for fine lattices where 2*n_vec full fields exceed HBM.

The ext==1 edge case follows the probe loop's convention: when the
coarse extent along mu is 1 the neighbour aggregate IS the aggregate,
the face mask is all-ones and the whole direction output feeds the
link (which then acts diagonally in the coarse apply) — bit-compatible
with the legacy construction.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.geometry import axis_of_mu
from .coarse import DIRS


def _face_mask(fine_shape, block, mu: int, sign: int) -> np.ndarray:
    """(T,Z,Y,X) float mask of fine OUTPUT sites whose hop source
    x + sign*mu lies in the neighbouring aggregate (1.0 on the
    outgoing face, 0.0 interior).  ``block`` is in array-axis order
    (bt,bz,by,bx), matching transfer._block_fields."""
    ax = axis_of_mu(mu)
    b = block[ax]
    coord = np.arange(fine_shape[ax]) % b
    face = (coord == (b - 1)) if sign > 0 else (coord == 0)
    shape = [1, 1, 1, 1]
    shape[ax] = fine_shape[ax]
    return np.broadcast_to(face.reshape(shape),
                           fine_shape).astype(np.float64)


def _chunk(n_cols: int) -> int:
    from ..utils import config as qconf
    c = int(qconf.get("QUDA_TPU_MG_COARSE_CHUNK", fresh=True))
    return n_cols if c <= 0 else min(c, n_cols)


def _mask_for(latc, fine_shape, block, mu, sign, ndim, dtype):
    ext = latc[axis_of_mu(mu)]
    if ext == 1:
        m = np.ones(fine_shape)
    else:
        m = _face_mask(fine_shape, block, mu, sign)
    return jnp.asarray(m, dtype).reshape(
        (1,) + tuple(fine_shape) + (1,) * (ndim - 5))


# -- cached probe programs ---------------------------------------------------
#
# Module-level jits keyed on the opstate restore function (stable
# identity) with every device array an ARGUMENT: compiles are
# constant-free (measured ~5-50x faster to build than the closure
# variants) and the jit cache hits on every same-shaped REBUILD — a
# serve worker or HMC chain re-running setup per gauge pays tracing
# once per process and the coarse_probe phase drops to pure execution.

def _rcols_cx(vv, Hb, block, latc, nc):
    """Batched restriction on the complex layout: (cols, lat, 2, K) ->
    (latc, nc, cols) as ONE strided-batched GEMM per call
    (blasGEMMQuda's traced sibling; the reference's cuBLAS
    strided-batch dispatch)."""
    from ..interfaces.blas_api import gemm_batched
    from .transfer import _block_fields
    blocked = _block_fields(Hb, block)         # (cols, latc, 2, D)
    bmat = jnp.moveaxis(blocked, 0, -1)        # (latc, 2, D, cols)
    out = gemm_batched(vv, bmat, trans_a="c")  # (latc, 2, N, cols)
    return out.reshape(tuple(latc) + (nc, Hb.shape[0]))


def _rcols_pr(vv, Hb, block, latc, nc):
    """Batched restriction on pair arrays: (cols, lat, 2, K, 2) ->
    (latc, nc, cols, 2) — the realified 4-GEMM complex product (the
    MXU-native recipe, same as the apply path)."""
    from .pair import _block_fields_pairs, _pair_ein
    blocked = _block_fields_pairs(Hb, block)   # (cols, latc, 2, D, 2)
    out = _pair_ein("...dn,k...d->...nk", vv, blocked, conj_a=True)
    return out.reshape(tuple(latc) + (nc, Hb.shape[0], 2))


@partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _probe_diag_cx(restore, spec, block, pair, arrays, vv, Wb):
    parts = restore(spec, arrays)
    latc = vv.shape[:4]
    nc = 2 * (vv.shape[-1] if not pair else vv.shape[-2])
    rc = _rcols_pr if pair else _rcols_cx
    return rc(vv, jax.vmap(parts.diag)(Wb), block, latc, nc)


@partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _probe_dir_st(restore, spec, block, pair, mu, sign, arrays, vv, Wb):
    parts = restore(spec, arrays)
    latc = vv.shape[:4]
    nc = 2 * (vv.shape[-1] if not pair else vv.shape[-2])
    fine_shape = Wb.shape[1:5]
    rc = _rcols_pr if pair else _rcols_cx
    H = jax.vmap(lambda w: parts.hop(w, mu, sign))(Wb)
    mdt = jnp.float32 if pair else vv.dtype
    m = _mask_for(latc, fine_shape, block, mu, sign, H.ndim, mdt)
    ycol = rc(vv, H * m, block, latc, nc)
    if latc[axis_of_mu(mu)] == 1:
        return ycol, jnp.zeros_like(ycol)
    return ycol, rc(vv, H * (1.0 - m), block, latc, nc)


def _check_extents(latc):
    for mu in range(4):
        ext = latc[axis_of_mu(mu)]
        if ext != 1 and ext % 2 != 0:
            raise ValueError(
                f"coarse extent {ext} along mu={mu} must be even or 1")


def _make_probes(fine_parts, block, latc, fine_shape, pair, nc, mdt):
    """(probe_diag, probe_dir) for one builder.  Preferred route: the
    opstate seam — module-level cached programs with every array an
    argument (compile once per process per operator class + shapes;
    rebuilds are pure execution).  Fallback: per-build closure jits
    (transfer still a traced argument — embedded-constant compiles
    measured ~50x slower) for operator types without a registered
    state; identical results, pinned in tests/test_mg_gemm_coarse.py."""
    from .opstate import op_state
    st = op_state(fine_parts)
    if st is not None:
        restore, spec, arrays = st

        def probe_diag(vv, Wb):
            return _probe_diag_cx(restore, spec, block, pair, arrays,
                                  vv, Wb)

        def probe_dir(vv, Wb, mu, sign):
            return _probe_dir_st(restore, spec, block, pair, mu, sign,
                                 arrays, vv, Wb)
        return probe_diag, probe_dir

    rc = _rcols_pr if pair else _rcols_cx

    @jax.jit
    def probe_diag(vv, Wb):
        return rc(vv, jax.vmap(fine_parts.diag)(Wb), block, latc, nc)

    @partial(jax.jit, static_argnums=(2, 3))
    def probe_dir(vv, Wb, mu, sign):
        H = jax.vmap(lambda w: fine_parts.hop(w, mu, sign))(Wb)
        m = _mask_for(latc, fine_shape, block, mu, sign, H.ndim, mdt)
        ycol = rc(vv, H * m, block, latc, nc)
        if latc[axis_of_mu(mu)] == 1:
            return ycol, jnp.zeros_like(ycol)
        return ycol, rc(vv, H * (1.0 - m), block, latc, nc)
    return probe_diag, probe_dir


def _build_stencil(v, wb, unblock, probe_diag, probe_dir, nc, n_vec,
                   latc, cat_axis):
    """The shared chunked probe loop: per chunk, UNBLOCK only that
    chunk's probe columns to fine fields (QUDA_TPU_MG_COARSE_CHUNK is
    the peak-HBM valve — at most ``chunk`` fine fields resident), run
    the batched diag + 8 hop probes, accumulate X and the 8 Y links."""
    from ..obs import trace as otr
    chunk = _chunk(nc)
    x_parts, y_parts = [], {d: [] for d in DIRS}
    with otr.span("mg_coarse_gemm_build", cat="mg", n_vec=n_vec,
                  coarse_shape=list(latc), chunk=chunk):
        for c0 in range(0, nc, chunk):
            Wb = unblock(wb[c0:c0 + chunk])
            xacc = probe_diag(v, Wb)
            for d in DIRS:
                ycol, dcol = probe_dir(v, Wb, *d)
                y_parts[d].append(ycol)
                xacc = xacc + dcol
            x_parts.append(xacc)
    cat = (lambda ps: ps[0] if len(ps) == 1
           else jnp.concatenate(ps, axis=cat_axis))
    return cat(x_parts), {d: cat(y_parts[d]) for d in DIRS}


def build_coarse_gemm(fine_parts, transfer, g5_hermitian: bool = True):
    """GEMM-form coarse construction on the COMPLEX layout — drop-in
    for mg/coarse.build_coarse (same CoarseOperator, same X/Y to fp
    tolerance)."""
    from .coarse import CoarseOperator
    from .transfer import _unblock_fields

    latc = transfer.coarse_shape
    fine_shape = transfer.fine_shape
    block = transfer.block
    n = transfer.n_vec
    nc = 2 * n
    v = transfer.v                             # (latc, 2, D, N)
    _check_extents(latc)

    # probe batch: every coarse unit column's prolongation is an
    # aggregate column of V itself (one reshape, no GEMM) — column
    # order chir*n + b, matching the probe loop.  wb stays in the small
    # blocked (coarse) layout; fine-field unblocking happens per chunk.
    sel = jnp.eye(2, dtype=v.dtype)            # (c0, chir)
    cols = jnp.moveaxis(v, -1, 0)              # (N, latc, 2, D)
    wb = cols[None] * sel[:, None, None, None, None, None, :, None]
    wb = wb.reshape((nc,) + v.shape[:4] + v.shape[4:6])

    probe_diag, probe_dir = _make_probes(fine_parts, block, latc,
                                         fine_shape, False, nc, v.dtype)
    x, y = _build_stencil(
        v, wb,
        lambda w: _unblock_fields(w, block, fine_shape, transfer.k_fine),
        probe_diag, probe_dir, nc, n, latc, cat_axis=-1)
    return CoarseOperator(x, y, n, g5_hermitian)


def build_coarse_pairs_gemm(fine_parts, transfer,
                            g5_hermitian: bool = True):
    """GEMM-form coarse construction on PAIR arrays — drop-in for
    mg/pair.build_coarse_pairs (restriction = the realified 4-GEMM
    complex product, same batched-contraction shape)."""
    # lazy: mg/pair.py imports this module for its builder hook
    from .pair import (PairCoarseOperator, _unblock_fields_pairs, F32,
                       resolve_coarse_form)

    latc = transfer.coarse_shape
    fine_shape = transfer.fine_shape
    block = transfer.block
    n = transfer.n_vec
    nc = 2 * n
    v = transfer.v                             # (latc, 2, D, N, 2)
    _check_extents(latc)

    sel = jnp.eye(2, dtype=F32)
    cols = jnp.moveaxis(v, -2, 0)              # (N, latc, 2, D, 2)
    wb = cols[None] * sel[:, None, None, None, None, None, :, None,
                          None]
    wb = wb.reshape((nc,) + v.shape[:4] + (2, v.shape[5], 2))

    probe_diag, probe_dir = _make_probes(fine_parts, block, latc,
                                         fine_shape, True, nc, F32)
    x, y = _build_stencil(
        v, wb,
        lambda w: _unblock_fields_pairs(w, block, fine_shape,
                                        transfer.k_fine),
        probe_diag, probe_dir, nc, n, latc, cat_axis=-2)
    return resolve_coarse_form(
        PairCoarseOperator(x, y, n, g5_hermitian))
