"""Pytree state/restore seam for MG level operators.

The fast MG setup (mg/gemm.py builders, mg/mg.py null-vector block
solve) wants its jitted programs keyed on module-level functions with
the operator's device arrays passed as ARGUMENTS.  Two reasons, both
measured on this container:

* **Compile speed.**  A closure-captured device array is embedded in
  the traced program as an XLA constant; constant-heavy programs
  compiled ~5-50x slower than the identical program taking the array
  as an argument (1.9 s -> 0.04 s for one batched restriction GEMM).
* **Cross-build caching.**  With the gauge an argument and the restore
  function a stable module-level object, jax's jit cache (and the
  persistent compilation cache a serve worker enables) hits on every
  REBUILD of the same-shaped hierarchy — updateMultigridQuda after an
  HMC step or a serve-worker gauge swap pays tracing/compile once per
  process, and setup phases drop to pure execution.

``op_state(level_op)`` returns ``(restore, spec, arrays)`` — a
module-level restore function (stable identity, safe as a jit static),
a hashable spec, and a pytree of device arrays — such that
``restore(spec, arrays)`` rebuilds an adapter equivalent to
``level_op`` inside a traced context; or None for operator types
without a registered state (the builders then fall back to the
closure-jit route: identical results, per-build compiles).

Restores bypass __init__ (object.__new__ + attribute assignment):
constructors fold boundary phases or pre-shift links, which must not
be re-applied to already-prepared arrays.
"""

from __future__ import annotations


# -- restore functions (module-level: their identity IS the cache key) ----

def _restore_levelop_wilson(spec, arrays):
    from ..models.wilson import DiracWilson
    from .mg import _LevelOp
    geom, kappa = spec
    d = object.__new__(DiracWilson)
    d.geom = geom
    d.kappa = kappa
    d.gauge = arrays["gauge"]          # boundary phases already folded
    return _LevelOp(d)


def _restore_pair_wilson(spec, arrays):
    from ..ops.pair import dslash_full_pairs
    from .pair import PairWilsonLevelOp
    kappa, use_pallas, interp, X = spec
    op = object.__new__(PairWilsonLevelOp)
    op.kappa = kappa
    op.gauge_pairs = arrays["gauge_pairs"]
    op._dslash = dslash_full_pairs
    op.use_pallas = use_pallas
    op._interp = interp
    if use_pallas:
        op._X = X
        op.gauge_pl = arrays["gauge_pl"]
        op.gauge_bw = arrays["gauge_bw"]
    return op


def _restore_pair_staggered(spec, arrays):
    from .pair import PairStaggeredLevelOp
    mass, use_pallas, interp, X, lat = spec
    op = object.__new__(PairStaggeredLevelOp)
    op.mass = mass
    op.fat_pairs = arrays["fat_pairs"]
    op.long_pairs = arrays.get("long_pairs")
    op.use_pallas = use_pallas
    op._interp = interp
    if use_pallas:
        op._X = X
        op.fat_pl = arrays["fat_pl"]
        op.fat_bw = arrays["fat_bw"]
    from .mg import parity_eps
    op._eps = parity_eps(lat, 3)
    return op


def _restore_coarse(spec, arrays):
    from .coarse import CoarseOperator
    n_vec, g5 = spec
    x_diag, y = arrays
    return CoarseOperator(x_diag, y, n_vec, g5)


def _restore_pair_coarse(spec, arrays):
    # canonical einsum form: probing and setup solves want the
    # representation-independent diag/hop algebra, not the apply-form
    # embedding/pallas variants
    from .pair import PairCoarseOperator
    n_vec, g5 = spec
    x_diag, y = arrays
    return PairCoarseOperator(x_diag, y, n_vec, g5)


def op_state(level_op):
    """(restore, spec, arrays) for registered operator types; None
    otherwise (callers fall back to closure-jit probes)."""
    from ..models.wilson import DiracWilson
    from .coarse import CoarseOperator
    from .mg import _LevelOp
    from .pair import (PairCoarseOperator, PairStaggeredLevelOp,
                       PairWilsonLevelOp)
    t = type(level_op)
    if t is _LevelOp and type(level_op.dirac) is DiracWilson:
        d = level_op.dirac
        return (_restore_levelop_wilson, (d.geom, d.kappa),
                {"gauge": d.gauge})
    if t is PairWilsonLevelOp:
        arrays = {"gauge_pairs": level_op.gauge_pairs}
        if level_op.use_pallas:
            arrays["gauge_pl"] = level_op.gauge_pl
            arrays["gauge_bw"] = level_op.gauge_bw
        return (_restore_pair_wilson,
                (level_op.kappa, level_op.use_pallas, level_op._interp,
                 getattr(level_op, "_X", 0)), arrays)
    if t is PairStaggeredLevelOp:
        arrays = {"fat_pairs": level_op.fat_pairs}
        if level_op.long_pairs is not None:
            arrays["long_pairs"] = level_op.long_pairs
        if level_op.use_pallas:
            arrays["fat_pl"] = level_op.fat_pl
            arrays["fat_bw"] = level_op.fat_bw
        lat = tuple(int(s) for s in level_op.fat_pairs.shape[1:5])
        return (_restore_pair_staggered,
                (level_op.mass, level_op.use_pallas, level_op._interp,
                 getattr(level_op, "_X", 0), lat), arrays)
    if t is CoarseOperator:
        return (_restore_coarse,
                (level_op.n_vec, level_op.g5_hermitian),
                (level_op.x_diag, dict(level_op.y)))
    if t is PairCoarseOperator:
        if level_op.identity_diag:
            return None                  # Yhat form: not a level op
        return (_restore_pair_coarse,
                (level_op.n_vec, level_op.g5_hermitian),
                (level_op.x_diag, dict(level_op.y)))
    return None
