"""Staggered Kähler-Dirac block preconditioning ("level 0.5" of staggered
multigrid).

Reference behavior: lib/staggered_kd_build_xinv.cu (builds the inverse of
the staggered operator's 2^4-hypercube block-diagonal part, a dense 48x48
per block) and lib/staggered_kd_apply_xinv.cu (applies it), used by
lib/dirac_staggered_kd.cpp as the right preconditioner that converts the
staggered operator's spectrum from a circle through zero into something a
Krylov method loves.

TPU-native construction: the block-diagonal part of M is extracted by
BLOCK-CHECKERBOARD probing — with only even(or odd)-parity 2^4 blocks lit,
a block's output receives no contribution from its (opposite-parity)
neighbours, so 48 dof x 2 block colors = 96 operator applications yield
the exact dense blocks, batched-inverted with one jnp.linalg.inv.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry

BLOCK = (2, 2, 2, 2)
BLOCK_DOF = 16 * 3  # 2^4 sites x 3 colors (staggered: nspin=1)


def _to_blocks(psi: jnp.ndarray):
    """(T,Z,Y,X,1,3) -> (Tb,Zb,Yb,Xb, 48)."""
    T, Z, Y, X = psi.shape[:4]
    r = psi.reshape(T // 2, 2, Z // 2, 2, Y // 2, 2, X // 2, 2, 3)
    r = r.transpose(0, 2, 4, 6, 1, 3, 5, 7, 8)
    return r.reshape(T // 2, Z // 2, Y // 2, X // 2, BLOCK_DOF)


def _from_blocks(b: jnp.ndarray):
    Tb, Zb, Yb, Xb = b.shape[:4]
    r = b.reshape(Tb, Zb, Yb, Xb, 2, 2, 2, 2, 3)
    r = r.transpose(0, 4, 1, 5, 2, 6, 3, 7, 8)
    return r.reshape(Tb * 2, Zb * 2, Yb * 2, Xb * 2, 1, 3)


def _block_parity(geom: LatticeGeometry):
    Tb, Zb, Yb, Xb = (d // 2 for d in geom.lattice_shape)
    t = np.arange(Tb)[:, None, None, None]
    z = np.arange(Zb)[None, :, None, None]
    y = np.arange(Yb)[None, None, :, None]
    x = np.arange(Xb)[None, None, None, :]
    return (t + z + y + x) % 2


def build_kd_xinv(apply_m: Callable, geom: LatticeGeometry,
                  dtype=jnp.complex128) -> jnp.ndarray:
    """Dense inverse of the 2^4-block-diagonal part of apply_m.

    apply_m: full-lattice staggered operator on (T,Z,Y,X,1,3) fields.
    Returns (Tb,Zb,Yb,Xb, 48, 48).
    """
    for d in geom.lattice_shape:
        if d % 4 != 0 and d != 2:
            # block parity masking needs an even number of blocks per dim
            # (or a single pair); d % 4 == 2 with d > 2 gives odd block
            # counts, which breaks the checkerboard at the wrap
            if (d // 2) % 2 != 0:
                raise ValueError(
                    f"extent {d}: need an even number of 2^4 blocks")
    bpar = jnp.asarray(_block_parity(geom))
    blatt = bpar.shape

    mv = jax.jit(apply_m)
    cols = []
    for dof in range(BLOCK_DOF):
        col = jnp.zeros(blatt + (BLOCK_DOF,), dtype)
        for p in (0, 1):
            probe_b = jnp.zeros(blatt + (BLOCK_DOF,), dtype)
            probe_b = probe_b.at[..., dof].set(
                (bpar == p).astype(dtype))
            out = mv(_from_blocks(probe_b))
            out_b = _to_blocks(out)
            col = col + jnp.where((bpar == p)[..., None], out_b, 0)
        cols.append(col)
    x = jnp.stack(cols, axis=-1)          # (blatt, 48, 48)
    return jnp.linalg.inv(x)


def apply_kd_xinv(xinv: jnp.ndarray, psi: jnp.ndarray) -> jnp.ndarray:
    """X^{-1} psi via one batched (48,48) matvec per block."""
    b = _to_blocks(psi)
    out = jnp.einsum("...ab,...b->...a", xinv, b)
    return _from_blocks(out)


def kd_preconditioner(apply_m: Callable, geom: LatticeGeometry,
                      dtype=jnp.complex128) -> Callable:
    """Right-preconditioner closure K(r) = X^{-1} r for GCR/PCG."""
    xinv = build_kd_xinv(apply_m, geom, dtype)
    return lambda r: apply_kd_xinv(xinv, r)
