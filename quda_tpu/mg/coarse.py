"""Coarse-grid operator: Galerkin RAP as an explicit coarse-link stencil.

Reference behavior: lib/coarse_op.in.cu calculateY (+ the 2002-line
include/kernels/coarse_op_kernel.cuh) computes the coarse link field Y and
coarse clover X so the coarse operator is a nearest-neighbour stencil over
(2 x n_vec)-dimensional site vectors; lib/dirac_coarse.cpp applies it.

TPU-native construction — probing instead of a hand-written RAP kernel:
every fine operator here decomposes as  M = diag + sum_{mu,sign} hop_{mu,sign}
with hop_{mu,sign} coupling x only to x + sign*mu.  For a FIXED direction,
R . hop . P applied to a coarse unit vector e_B replicated over ALL coarse
sites yields exactly the column B of that direction's coarse link on every
coarse site at once (no aliasing — each coarse site hears from exactly one
neighbour).  So

    Y_{mu,sign}[:, :, B] = R( hop_{mu,sign}( P(e_B) ) )
    X_diag[:, :, B]      = R( diag( P(e_B) ) )

costs Nc = 2*n_vec applications of each hop — the same asymptotic work as
calculateY, in ~60 lines, and it recurses verbatim onto coarse levels
because CoarseOperator itself exposes diag/hop.  Galerkin exactness
(coarse M == R M P) is asserted in tests rather than trusted.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..fields.geometry import axis_of_mu
from .transfer import Transfer, from_chiral, to_chiral

DIRS = tuple((mu, sign) for mu in range(4) for sign in (+1, -1))


class FineOpParts:
    """Protocol: .diag(psi), .hop(psi, mu, sign), .M(psi) on standard-layout
    full-lattice fields."""


@dataclasses.dataclass
class CoarseOperator:
    """Nearest-neighbour coarse stencil on (Tc,Zc,Yc,Xc, 2, N) fields."""

    x_diag: jnp.ndarray                      # (latc, Nc, Nc)
    y: Dict[Tuple[int, int], jnp.ndarray]    # (mu,sign) -> (latc, Nc, Nc)
    n_vec: int
    g5_hermitian: bool = True

    @property
    def nc(self):
        return 2 * self.n_vec

    def _flat(self, v):
        return v.reshape(v.shape[:4] + (self.nc,))

    def _unflat(self, v):
        return v.reshape(v.shape[:4] + (2, self.n_vec))

    def diag(self, v):
        f = self._flat(v)
        return self._unflat(jnp.einsum("...ab,...b->...a", self.x_diag, f))

    def hop(self, v, mu, sign):
        f = self._flat(v)
        nbr = jnp.roll(f, -sign, axis=axis_of_mu(mu))
        return self._unflat(
            jnp.einsum("...ab,...b->...a", self.y[(mu, sign)], nbr))

    def M(self, v):
        out = self.diag(v)
        for mu, sign in DIRS:
            out = out + self.hop(v, mu, sign)
        return out

    def gamma5(self, v):
        sign = jnp.array([1.0, -1.0], dtype=v.real.dtype)
        return v * sign[:, None].astype(v.dtype)

    def Mdag(self, v):
        if not self.g5_hermitian:
            raise NotImplementedError
        return self.gamma5(self.M(self.gamma5(v)))

    def MdagM(self, v):
        return self.Mdag(self.M(v))


def build_coarse(fine_parts, transfer: Transfer,
                 g5_hermitian: bool = True) -> CoarseOperator:
    """Probe R . (diag|hop) . P to assemble the coarse stencil.

    A fine hop from a site INTERIOR to a block stays inside the block —
    that contribution belongs to the coarse DIAGONAL, not the coarse link.
    A uniform probe cannot separate the two, so each direction is probed
    twice with the coarse sites masked by their parity along mu: the
    output at unlit sites is the pure inter-block link column, the output
    at lit sites the intra-block diagonal contribution.  Coarse extents
    must be even (or 1, where the neighbour IS the site and a single
    unmasked probe feeds the link, which then acts diagonally anyway).
    """
    latc = transfer.coarse_shape
    n = transfer.n_vec
    nc = 2 * n
    import numpy as np

    for mu in range(4):
        ext = latc[axis_of_mu(mu)]
        if ext != 1 and ext % 2 != 0:
            raise ValueError(
                f"coarse extent {ext} along mu={mu} must be even or 1")

    # fine_parts works in the CHIRAL layout (lat, 2, K) — fine Dirac
    # operators are wrapped by _FinePartsAdapter, CoarseOperator is native
    @jax.jit
    def probe_diag(vc):
        fine = transfer.prolong(vc)
        return transfer.restrict(fine_parts.diag(fine))

    from functools import partial

    @partial(jax.jit, static_argnums=(1, 2))
    def probe_hop(vc, mu, sign):
        fine = transfer.prolong(vc)
        return transfer.restrict(fine_parts.hop(fine, mu, sign))

    def coord_parity(mu):
        ax = axis_of_mu(mu)
        shape = [1, 1, 1, 1]
        shape[ax] = latc[ax]
        c = np.arange(latc[ax]).reshape(shape) % 2
        return np.broadcast_to(c, latc)  # (latc,)

    from ..obs import trace as otr

    dtype = transfer.v.dtype
    diag_cols = []
    hop_cols = {d: [] for d in DIRS}
    # the probe loop is the coarse-stencil cost: Nc = 2*n_vec columns x
    # (1 diag + 8 masked-twice hop) probes — spanned so the MG setup
    # breakdown's coarse_probe phase shows its inner structure in the
    # trace (span is the module no-op when tracing is off)
    with otr.span("mg_coarse_probe_loop", cat="mg", n_vec=n,
                  coarse_shape=list(latc)):
        for chir in range(2):
            for b in range(n):
                e = jnp.zeros(latc + (2, n),
                              dtype).at[..., chir, b].set(1.0)
                dcol = probe_diag(e).reshape(latc + (nc,))
                for mu, sign in DIRS:
                    ext = latc[axis_of_mu(mu)]
                    if ext == 1:
                        out = probe_hop(e, mu, sign).reshape(latc + (nc,))
                        hop_cols[(mu, sign)].append(out)
                        continue
                    par = jnp.asarray(coord_parity(mu))[..., None, None]
                    ycol = jnp.zeros(latc + (nc,), dtype)
                    for p in (0, 1):
                        mask = (par == p).astype(dtype)
                        out = probe_hop(e * mask, mu,
                                        sign).reshape(latc + (nc,))
                        lit = (jnp.asarray(coord_parity(mu)) == p)[
                            ..., None]
                        # unlit sites: pure link column; lit: diagonal
                        ycol = jnp.where(lit, ycol, out)
                        dcol = dcol + jnp.where(lit, out, 0.0)
                    hop_cols[(mu, sign)].append(ycol)
                diag_cols.append(dcol)

    x_diag = jnp.stack(diag_cols, axis=-1)           # (latc, Nc, Nc)
    y = {d: jnp.stack(hop_cols[d], axis=-1) for d in DIRS}
    return CoarseOperator(x_diag, y, n, g5_hermitian)
