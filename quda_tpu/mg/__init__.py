"""Adaptive multigrid: transfer, Galerkin coarse ops, V-cycles, KD blocks."""

from .transfer import Transfer, from_chiral, to_chiral  # noqa: F401
from .coarse import CoarseOperator, build_coarse  # noqa: F401
from .mg import MG, MGLevelParam, mg_solve  # noqa: F401
