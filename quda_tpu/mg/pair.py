"""Complex-free multigrid: the MG hierarchy on re/im pair arrays.

Reference behavior: lib/multigrid.cpp (the hierarchy this realifies),
lib/transfer.cpp, lib/coarse_op.in.cu.  QUDA runs MG in complex
arithmetic; the axon TPU runtime cannot execute complex64 at all
(PERF.md), so this module re-poses the identical hierarchy over the
REALIFICATION of every object:

* chiral fields   (lat, 2, K)     complex -> (lat, 2, K, 2)     real
* transfer V      (latc, 2, D, N) complex -> (latc, 2, D, N, 2) real
* coarse links    (latc, Nc, Nc)  complex -> (latc, Nc, Nc, 2)  real

Complex products become explicit 4-einsum pair products (the MXU-native
complex multiply, same recipe as ops/pair.py).  The one genuinely
complex-structured step — block orthonormalisation of the null vectors —
uses Cholesky-QR on the INTERLEAVED real embedding: mapping each complex
entry g to the 2x2 real block [[re,-im],[im,re]] is a ring homomorphism
C -> R^{2x2} that sends Hermitian-positive-definite to symmetric-positive-
definite and lower-triangular (real positive diagonal) to lower-
triangular, so by Cholesky uniqueness the REAL Cholesky of the embedded
Gram matrix IS the embedding of the complex Cholesky.  Two passes
(CholQR2) restore f32 orthonormality to working precision.

Krylov pieces (null-vector CG, MR/GCR smoothers, the outer GCR) run the
existing dtype-generic solvers directly on the pair arrays: a real-
coefficient Krylov method on the realified operator (the eig/pair_eig.py
trick).  The V-cycle, probing construction, and verify() are inherited
from mg/mg.py via its layout hooks — the hierarchy logic is written once.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..fields.geometry import axis_of_mu
from ..ops import blas
from ..ops import gamma as g
from ..ops.pair import (color_mul_pairs, dagger_pairs,
                        deinterleave_mat as _deinterleave,
                        interleave_mat as _interleave, spin_mul_pairs,
                        to_pairs)
from ..ops.shift import shift
from .coarse import DIRS
from .mg import MG, MGLevelParam, parity_eps

F32 = jnp.float32


# -- chiral pair layout -----------------------------------------------------

def to_chiral_pairs(psi: jnp.ndarray) -> jnp.ndarray:
    """(lat..., 4, 3, 2) -> (lat..., 2, 6, 2)."""
    lat = psi.shape[:-3]
    return psi.reshape(lat + (2, 6, 2))


def from_chiral_pairs(psi: jnp.ndarray) -> jnp.ndarray:
    lat = psi.shape[:-3]
    return psi.reshape(lat + (4, 3, 2))


# -- pair linear algebra ----------------------------------------------------

def _pair_ein(spec: str, a: jnp.ndarray, b: jnp.ndarray,
              conj_a: bool = False) -> jnp.ndarray:
    """Complex einsum on (..., 2) pair arrays: one spec, four real
    einsums, f32 accumulation."""
    ar, ai = a[..., 0], a[..., 1]
    if conj_a:
        ai = -ai
    br, bi = b[..., 0], b[..., 1]
    import functools
    ein = functools.partial(jnp.einsum, spec, preferred_element_type=F32)
    re = ein(ar, br) - ein(ai, bi)
    im = ein(ar, bi) + ein(ai, br)
    return jnp.stack([re, im], axis=-1)




def _cholqr_pass(cols: jnp.ndarray) -> jnp.ndarray:
    """One Cholesky-QR pass on (..., D, N, 2) pair columns."""
    n = cols.shape[-2]
    gram = _pair_ein("...dn,...dm->...nm", cols, cols, conj_a=True)
    emb = _interleave(gram)
    chol = jnp.linalg.cholesky(emb)
    eye = jnp.broadcast_to(jnp.eye(2 * n, dtype=chol.dtype), chol.shape)
    linv = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    w = dagger_pairs(_deinterleave(linv))          # (..., N, N, 2): L^-dag
    return _pair_ein("...dn,...nm->...dm", cols, w)


def cholqr2(cols: jnp.ndarray) -> jnp.ndarray:
    """Orthonormalise complex columns given as (..., D, N, 2) pairs.
    Two Cholesky-QR passes (CholQR2) for f32-grade orthonormality."""
    return _cholqr_pass(_cholqr_pass(cols))


# -- transfer ---------------------------------------------------------------

def _block_fields_pairs(fields: jnp.ndarray, block):
    """(B, T,Z,Y,X, 2, K, 2) -> (B, Tc,Zc,Yc,Xc, 2, D, 2)."""
    Bn, T, Z, Y, X, two, K, _ = fields.shape
    bt, bz, by, bx = block
    r = fields.reshape(Bn, T // bt, bt, Z // bz, bz, Y // by, by,
                       X // bx, bx, two, K, 2)
    r = r.transpose(0, 1, 3, 5, 7, 9, 2, 4, 6, 8, 10, 11)
    return r.reshape(Bn, T // bt, Z // bz, Y // by, X // bx, two,
                     bt * bz * by * bx * K, 2)


def _unblock_fields_pairs(blocked: jnp.ndarray, block, fine_shape, K):
    Bn = blocked.shape[0]
    T, Z, Y, X = fine_shape
    bt, bz, by, bx = block
    r = blocked.reshape(Bn, T // bt, Z // bz, Y // by, X // bx, 2,
                        bt, bz, by, bx, K, 2)
    r = r.transpose(0, 1, 6, 2, 7, 3, 8, 4, 9, 5, 10, 11)
    return r.reshape(Bn, T, Z, Y, X, 2, K, 2)


@dataclasses.dataclass
class PairTransfer:
    """Block transfer on pair arrays (realified mg/transfer.Transfer).

    v: (Tc,Zc,Yc,Xc, 2, D, N, 2) orthonormal complex aggregates as pairs.
    """

    v: jnp.ndarray
    block: Tuple[int, int, int, int]
    fine_shape: Tuple[int, int, int, int]
    k_fine: int
    n_vec: int

    @classmethod
    def from_null_vectors(cls, null_vecs: jnp.ndarray,
                          block) -> "PairTransfer":
        """null_vecs: (N, T,Z,Y,X, 2, K, 2) pair chiral fields."""
        n, T, Z, Y, X, two, K, _ = null_vecs.shape
        bt, bz, by, bx = block
        assert T % bt == 0 and Z % bz == 0 and Y % by == 0 and X % bx == 0, \
            (null_vecs.shape, block)
        blocked = _block_fields_pairs(null_vecs, block)
        cols = jnp.moveaxis(blocked, 0, -2)         # (latc, 2, D, N, 2)
        return cls(cholqr2(cols), tuple(block), (T, Z, Y, X), K, n)

    @classmethod
    def from_complex(cls, transfer) -> "PairTransfer":
        """Realify an existing complex Transfer (e.g. CPU-built setup
        migrating to a complex-free runtime)."""
        return cls(to_pairs(transfer.v, F32), tuple(transfer.block),
                   tuple(transfer.fine_shape), transfer.k_fine,
                   transfer.n_vec)

    @property
    def coarse_shape(self):
        T, Z, Y, X = self.fine_shape
        bt, bz, by, bx = self.block
        return (T // bt, Z // bz, Y // by, X // bx)

    def restrict(self, fine: jnp.ndarray) -> jnp.ndarray:
        """(T,Z,Y,X,2,K,2) -> (Tc,Zc,Yc,Xc,2,N,2): R = V^dag aggregate."""
        blocked = _block_fields_pairs(fine[None], self.block)[0]
        return _pair_ein("...dn,...d->...n", self.v, blocked, conj_a=True)

    def prolong(self, coarse: jnp.ndarray) -> jnp.ndarray:
        """(Tc,Zc,Yc,Xc,2,N,2) -> (T,Z,Y,X,2,K,2)."""
        blocked = _pair_ein("...dn,...n->...d", self.v, coarse)
        return _unblock_fields_pairs(blocked[None], self.block,
                                     self.fine_shape, self.k_fine)[0]


# -- coarse operator --------------------------------------------------------

@dataclasses.dataclass
class PairCoarseOperator:
    """Nearest-neighbour coarse stencil on (latc, 2, N, 2) pair fields
    (realified mg/coarse.CoarseOperator).

    ``use_embedding=True`` applies each link as ONE real
    (2Nc, 2Nc) matmul on the interleaved embedding instead of four
    (Nc, Nc) einsums: identical flops (a complex matvec is 4 Nc^2 real
    multiplies either way) but a single, larger MXU contraction per
    link — the shape the systolic array wants.  Embedded links are
    built lazily and cached.
    """

    x_diag: jnp.ndarray                      # (latc, Nc, Nc, 2)
    y: Dict[Tuple[int, int], jnp.ndarray]    # (mu,sign) -> (latc, Nc, Nc, 2)
    n_vec: int
    g5_hermitian: bool = True
    use_embedding: bool = False
    identity_diag: bool = False              # Yhat form (yhat_links)
    # fused single-pass coarse stencil (ops/coarse_pallas.py): diag +
    # all 8 hops in one kernel launch over the embedded links — raced
    # against the einsum/embedding forms via QUDA_TPU_MG_COARSE_FORM
    # (resolve_coarse_form); interpret only drives off-chip tests
    use_pallas: bool = False
    pallas_interpret: bool = False

    @property
    def nc(self):
        return 2 * self.n_vec

    def _flat(self, v):
        return v.reshape(v.shape[:4] + (self.nc, 2))

    def _unflat(self, v):
        return v.reshape(v.shape[:4] + (2, self.n_vec, 2))

    def _emb(self, key):
        cache = self.__dict__.setdefault("_emb_cache", {})
        if key not in cache:
            m = self.x_diag if key == "diag" else self.y[key]
            cache[key] = _interleave(m)      # (latc, 2Nc, 2Nc)
        return cache[key]

    def _apply(self, key, f):
        """One coarse link application on the flat (latc, Nc, 2) field."""
        if self.use_embedding:
            # vector pairs -> interleaved (.., 2Nc): (re0, im0, re1, ..)
            fi = f.reshape(f.shape[:4] + (self.nc * 2,))
            out = jnp.einsum("...ab,...b->...a", self._emb(key), fi)
            return out.reshape(f.shape)
        m = self.x_diag if key == "diag" else self.y[key]
        return _pair_ein("...ab,...b->...a", m, f)

    def diag(self, v):
        if self.identity_diag:
            return v            # Yhat form: M_hat = v + sum(hops)
        return self._unflat(self._apply("diag", self._flat(v)))

    def hop(self, v, mu, sign):
        f = self._flat(v)
        nbr = jnp.roll(f, -sign, axis=axis_of_mu(mu))
        return self._unflat(self._apply((mu, sign), nbr))

    def _pl_links(self):
        """(9, S, E, E) embedded link stack [diag, *DIRS] for the fused
        pallas apply (built lazily, cached like the embeddings).  The
        per-direction embeddings are interleaved directly — NOT via
        ``_emb`` — so the pallas form holds one resident stack, not the
        stack plus 9 dead per-key copies the apply path never reads."""
        cache = self.__dict__.setdefault("_emb_cache", {})
        if "_pl_links" not in cache:
            mats = [_interleave(self.x_diag)] + \
                [_interleave(self.y[d]) for d in DIRS]
            e = 2 * self.nc
            cache["_pl_links"] = jnp.stack(mats).reshape(9, -1, e, e)
        return cache["_pl_links"]

    def _pallas_apply(self, v):
        """Fused single-pass coarse M (ops/coarse_pallas.py): the input
        and its 8 pre-rolled neighbour copies stream once through the
        kernel against the resident embedded link stack."""
        from ..ops.coarse_pallas import coarse_apply_pallas
        f = self._flat(v)
        latc = f.shape[:4]
        e = 2 * self.nc
        fi = f.reshape(latc + (e,))            # interleaved (re0,im0,..)
        rolls = [fi] + [jnp.roll(fi, -sign, axis_of_mu(mu))
                        for mu, sign in DIRS]
        psi9 = jnp.stack(rolls).reshape(9, -1, e)
        out = coarse_apply_pallas(self._pl_links(), psi9,
                                  interpret=self.pallas_interpret)
        return self._unflat(out.reshape(latc + (self.nc, 2)))

    def M(self, v):
        if self.use_pallas and not self.identity_diag:
            return self._pallas_apply(v)
        out = self.diag(v)
        for mu, sign in DIRS:
            out = out + self.hop(v, mu, sign)
        return out

    def gamma5(self, v):
        sign = jnp.array([1.0, -1.0], v.dtype)
        return v * sign[:, None, None]

    def Mdag(self, v):
        if not self.g5_hermitian:
            raise NotImplementedError
        return self.gamma5(self.M(self.gamma5(v)))

    def MdagM(self, v):
        return self.Mdag(self.M(v))

    @classmethod
    def from_complex(cls, coarse) -> "PairCoarseOperator":
        return resolve_coarse_form(cls(
            to_pairs(coarse.x_diag, F32),
            {d: to_pairs(coarse.y[d], F32) for d in DIRS},
            coarse.n_vec, coarse.g5_hermitian))


def yhat_links(coarse: PairCoarseOperator,
               xinv: jnp.ndarray | None = None
               ) -> "PairCoarseOperator":
    """Explicit preconditioned coarse links Yhat = X^{-1} Y (QUDA
    calculateYhat, lib/coarse_op_preconditioned.in.cu:329): returns a
    coarse operator whose diag is the identity and whose links are
    X^{-1}-premultiplied, so M_hat = I + sum X^{-1} Y hops — the
    Jacobi-preconditioned coarse stencil QUDA smooths with.

    COMPONENTS.md §2.7 argues XLA's fusion makes the precompute moot on
    TPU (apply X^{-1} on the fly); this explicit form exists so that
    claim can be MEASURED — bench_suite's mg suite times both.  The
    inverse runs through the interleaved embedding (complex-free).
    """
    if xinv is None:
        xinv = _deinterleave(jnp.linalg.inv(
            _interleave(coarse.x_diag)))             # (latc, Nc, Nc, 2)
    yhat = {d: _pair_ein("...ab,...bc->...ac", xinv, coarse.y[d])
            for d in DIRS}
    # identity_diag: M_hat = v + sum(hops) — no dense identity matmul
    # (charging one would bias the A/B against the explicit form)
    return dataclasses.replace(coarse, y=yhat, g5_hermitian=False,
                               identity_diag=True)


def _embed_default() -> bool:
    """QUDA_TPU_MG_EMBED: apply coarse links as single interleaved-
    embedding matmuls (MXU-shaped) instead of 4-einsum pair products."""
    from ..utils import config as qconf
    return str(qconf.get("QUDA_TPU_MG_EMBED", fresh=True)) == "1"


def _arr_on_tpu(x) -> bool:
    """Whether the array actually LIVES on TPU devices — the pallas
    gates must follow placement, not the global backend: a hierarchy
    built under ``jax.default_device(cpu)`` on a chip host (the bench
    suite's setup discipline) holds CPU arrays, and a non-interpret
    pallas call on them would fail to lower."""
    import jax as _jax
    try:
        devs = x.devices() if callable(getattr(x, "devices", None)) \
            else None
        if devs:
            return all(d.platform == "tpu" for d in devs)
    except Exception:
        pass
    return _jax.default_backend() == "tpu"


def resolve_coarse_form(op: PairCoarseOperator) -> PairCoarseOperator:
    """Pick the coarse-apply form per QUDA_TPU_MG_COARSE_FORM: an
    explicit pin is honored (pallas runs interpret off-chip — test
    territory), 'auto' races einsum vs embedding vs the fused pallas
    kernel via utils.tune on chip (cached per (coarse shape, Nc) like
    every other kernel race) and falls back to the static
    QUDA_TPU_MG_EMBED default off-chip, where interpret-mode timings
    would be meaningless."""
    from ..utils import config as qconf
    form = str(qconf.get("QUDA_TPU_MG_COARSE_FORM", fresh=True)) \
        or "auto"
    on_tpu = _arr_on_tpu(op.x_diag)
    if form == "einsum":
        return dataclasses.replace(op, use_embedding=False,
                                   use_pallas=False)
    if form == "embed":
        return dataclasses.replace(op, use_embedding=True,
                                   use_pallas=False)
    if form == "pallas":
        return dataclasses.replace(op, use_pallas=True,
                                   pallas_interpret=not on_tpu)
    if not on_tpu:
        return dataclasses.replace(op, use_embedding=_embed_default(),
                                   use_pallas=False)
    from ..utils import tune
    latc = tuple(int(s) for s in op.x_diag.shape[:4])
    probe = jax.random.normal(jax.random.PRNGKey(7),
                              latc + (2, op.n_vec, 2), F32)
    cands = {
        "einsum": jax.jit(dataclasses.replace(
            op, use_embedding=False, use_pallas=False).M),
        "embed": jax.jit(dataclasses.replace(
            op, use_embedding=True, use_pallas=False).M),
        "pallas": jax.jit(dataclasses.replace(op, use_pallas=True).M),
    }
    win = tune.tune("mg_coarse_form", latc + (op.nc,), cands, (probe,))
    return dataclasses.replace(
        op, use_embedding=(win == "embed"), use_pallas=(win == "pallas"))


def build_coarse_pairs(fine_parts, transfer: PairTransfer,
                       g5_hermitian: bool = True) -> PairCoarseOperator:
    """Probing construction of the coarse stencil on pair arrays —
    structure identical to mg/coarse.build_coarse (see its docstring for
    the parity-masking argument); probing with REAL unit coarse vectors
    reads off each complex column directly as its (re, im) pair."""
    import numpy as np

    latc = transfer.coarse_shape
    n = transfer.n_vec
    nc = 2 * n

    for mu in range(4):
        ext = latc[axis_of_mu(mu)]
        if ext != 1 and ext % 2 != 0:
            raise ValueError(
                f"coarse extent {ext} along mu={mu} must be even or 1")

    @jax.jit
    def probe_diag(vc):
        return transfer.restrict(fine_parts.diag(transfer.prolong(vc)))

    from functools import partial

    @partial(jax.jit, static_argnums=(1, 2))
    def probe_hop(vc, mu, sign):
        return transfer.restrict(
            fine_parts.hop(transfer.prolong(vc), mu, sign))

    def coord_parity(mu):
        ax = axis_of_mu(mu)
        shape = [1, 1, 1, 1]
        shape[ax] = latc[ax]
        c = np.arange(latc[ax]).reshape(shape) % 2
        return np.broadcast_to(c, latc)

    def as_col(out):                       # (latc, 2, n, 2) -> (latc, nc, 2)
        return out.reshape(latc + (nc, 2))

    from ..obs import trace as otr

    diag_cols = []
    hop_cols = {d: [] for d in DIRS}
    # spanned like mg/coarse.build_coarse: the coarse_probe phase of the
    # MG setup breakdown shows the probe loop in the trace
    with otr.span("mg_coarse_probe_loop", cat="mg", n_vec=n,
                  coarse_shape=list(latc)):
        for chir in range(2):
            for b in range(n):
                e = jnp.zeros(latc + (2, n, 2),
                              F32).at[..., chir, b, 0].set(1.0)
                dcol = as_col(probe_diag(e))
                for mu, sign in DIRS:
                    ext = latc[axis_of_mu(mu)]
                    if ext == 1:
                        hop_cols[(mu, sign)].append(
                            as_col(probe_hop(e, mu, sign)))
                        continue
                    par = jnp.asarray(coord_parity(mu))[..., None, None,
                                                        None]
                    ycol = jnp.zeros(latc + (nc, 2), F32)
                    for p in (0, 1):
                        mask = (par == p).astype(F32)
                        out = as_col(probe_hop(e * mask, mu, sign))
                        lit = (jnp.asarray(coord_parity(mu)) == p)[
                            ..., None, None]
                        ycol = jnp.where(lit, ycol, out)
                        dcol = dcol + jnp.where(lit, out, 0.0)
                    hop_cols[(mu, sign)].append(ycol)
                diag_cols.append(dcol)

    x_diag = jnp.stack(diag_cols, axis=-2)         # (latc, Nc, Nc, 2)
    y = {d: jnp.stack(hop_cols[d], axis=-2) for d in DIRS}
    return PairCoarseOperator(x_diag, y, n, g5_hermitian,
                              use_embedding=_embed_default())


# -- fine-level pair adapters ----------------------------------------------

def wilson_hop_pairs(gauge_pairs, psi, mu, sign, kappa):
    """-kappa * single-direction Wilson hop on (lat,4,3,2) pair arrays
    (pair mirror of models/wilson.DiracWilson.hop)."""
    if sign > 0:
        u = gauge_pairs[mu]
        proj = g.PROJ_MINUS[mu]
        h = color_mul_pairs(u, shift(psi, mu, +1))
    else:
        u = shift(dagger_pairs(gauge_pairs[mu]), mu, -1)
        proj = g.PROJ_PLUS[mu]
        h = color_mul_pairs(u, shift(psi, mu, -1))
    return -kappa * spin_mul_pairs(proj, h)


def _fine_pallas_default(arr) -> bool:
    """Fine-level MG operators ride the pallas kernels when their
    arrays live on chip unless QUDA_TPU_PALLAS forbids them — the same
    gate as the API solvers (placement-checked via ``arr``), so the
    gcr_mg outer solve's smoother/residual applies run on the kernel
    form the fused-iteration solver proved out."""
    from ..utils import config as qconf
    return (_arr_on_tpu(arr)
            and str(qconf.get("QUDA_TPU_PALLAS", fresh=True)) != "0")


class PairWilsonLevelOp:
    """Fine-level adapter for Wilson on pair arrays: the realified
    mg/mg._LevelOp (K = 6 chiral components, gamma5 = chirality sign).

    Standard layout here means canonical pair spinors (T,Z,Y,X,4,3,2);
    the gauge (with t-boundary phases folded in by the wrapped Dirac
    operator) is converted to f32 pairs once at construction.

    On chip the fine dslash rides the v2 pallas kernel with resident
    packed links + pre-shifted backward copy (one layout transpose per
    apply, amortised against the 1,152 B/site kernel traffic), so the
    outer GCR's residuals, the V-cycle smoother, AND the MRHS
    null-vector block solve (``MdagM_mrhs`` -> the MRHS kernel: gauge
    tiles fetched once per (t, z-block) for all n_vec) all run the
    measured-fastest stencil; off-chip the XLA pair stencil serves, as
    everywhere else.
    """

    k_fine = 6
    dtype = F32

    def __init__(self, dirac, use_pallas: Optional[bool] = None,
                 pallas_interpret: bool = False):
        from ..ops.pair import dslash_full_pairs
        self.dirac = dirac
        self.kappa = dirac.kappa
        self.gauge_pairs = to_pairs(dirac.gauge, F32)
        self._dslash = dslash_full_pairs
        self.use_pallas = (_fine_pallas_default(self.gauge_pairs)
                           if use_pallas is None else bool(use_pallas))
        self._interp = bool(pallas_interpret)
        if self.use_pallas:
            from ..ops import wilson_packed as wpk
            from ..ops.wilson_pallas_packed import (backward_gauge,
                                                    to_pallas_layout)
            self._X = int(dirac.geom.lattice_shape[-1])
            self.gauge_pl = to_pallas_layout(wpk.pack_gauge(dirac.gauge))
            self.gauge_bw = backward_gauge(self.gauge_pl, self._X)

    def to_chiral(self, v):
        return to_chiral_pairs(v)

    def from_chiral(self, v):
        return from_chiral_pairs(v)

    # -- pallas-layout shuttles ----------------------------------------
    @staticmethod
    def _pl_of(v):
        """canonical pairs (T,Z,Y,X,4,3,2) -> kernel layout
        (4,3,2,T,Z,YX)."""
        T, Z, Y, X = v.shape[:4]
        return jnp.transpose(v, (4, 5, 6, 0, 1, 2, 3)).reshape(
            4, 3, 2, T, Z, Y * X)

    @staticmethod
    def _pl_back(out, lat):
        T, Z, Y, X = lat
        return jnp.transpose(out.reshape(4, 3, 2, T, Z, Y, X),
                             (3, 4, 5, 6, 0, 1, 2))

    # -- standard (canonical pair) layout ------------------------------
    def _d_std(self, v):
        if self.use_pallas:
            from ..ops.wilson_pallas_packed import dslash_pallas_packed
            d = dslash_pallas_packed(self.gauge_pl, self._pl_of(v),
                                     self._X, interpret=self._interp,
                                     gauge_bw=self.gauge_bw)
            return self._pl_back(d, v.shape[:4])
        return self._dslash(self.gauge_pairs, v, out_dtype=F32)

    def M_std(self, v):
        return v - self.kappa * self._d_std(v)

    def Mdag_std(self, v):
        g5 = jnp.array([1.0, 1.0, -1.0, -1.0], v.dtype)
        sgn = g5[:, None, None]
        return sgn * self.M_std(sgn * v)

    # -- batched MRHS forms (the null-vector block solve's matvec) -----
    def _d_std_mrhs(self, V):
        if self.use_pallas:
            from ..ops.wilson_pallas_packed import \
                dslash_pallas_packed_mrhs
            lat = V.shape[1:5]
            pp = jax.vmap(self._pl_of)(V)
            d = dslash_pallas_packed_mrhs(self.gauge_pl, pp, self._X,
                                          interpret=self._interp,
                                          gauge_bw=self.gauge_bw)
            return jax.vmap(lambda o: self._pl_back(o, lat))(d)
        return jax.vmap(lambda v: self._dslash(self.gauge_pairs, v,
                                               out_dtype=F32))(V)

    def M_mrhs(self, Vc):
        """(N, lat, 2, 6, 2) chiral batch -> M per RHS through ONE
        batched stencil — the null-vector block solve's direct-system
        matvec."""
        s = from_chiral_pairs(Vc)          # reshape works batched
        return to_chiral_pairs(s - self.kappa * self._d_std_mrhs(s))

    def MdagM_mrhs(self, Vc):
        """(N, lat, 2, 6, 2) chiral batch -> MdagM per RHS through ONE
        batched stencil (the MRHS kernel on chip: link tiles read once
        per (t, z-block) and all N RHS streamed through them)."""
        s = from_chiral_pairs(Vc)
        g5 = jnp.array([1.0, 1.0, -1.0, -1.0], s.dtype)[:, None, None]
        ms = s - self.kappa * self._d_std_mrhs(s)
        md = g5 * (g5 * ms - self.kappa * self._d_std_mrhs(g5 * ms))
        return to_chiral_pairs(md)

    # -- chiral layout (the MG hierarchy's view) -----------------------
    def M(self, v):
        return to_chiral_pairs(self.M_std(from_chiral_pairs(v)))

    def MdagM(self, v):
        s = from_chiral_pairs(v)
        return to_chiral_pairs(self.Mdag_std(self.M_std(s)))

    def diag(self, v):
        return v

    def hop(self, v, mu, sign):
        s = from_chiral_pairs(v)
        return to_chiral_pairs(
            wilson_hop_pairs(self.gauge_pairs, s, mu, sign, self.kappa))


class PairStaggeredLevelOp:
    """Fine-level adapter for STAGGERED operators on pair arrays — the
    realified mg/mg._StaggeredLevelOp (direct hierarchy; the KD
    composition is complex-only for now).  Chirality is the site parity
    epsilon(x); K = 3 colors; chiral fields are (lat, 2, 3, 2) with the
    even-site part in component 0.

    The staggered stencil pieces (ops/staggered dslash_full / the hop
    decomposition) are pair-polymorphic, so this adapter only converts
    the (phase-folded) links once and handles the chiral masks."""

    k_fine = 3
    dtype = F32
    nspin = 1

    def __init__(self, dirac, use_pallas: Optional[bool] = None,
                 pallas_interpret: bool = False):
        self.dirac = dirac
        self.geom = dirac.geom
        self.mass = float(dirac.mass)
        self.fat_pairs = to_pairs(dirac.fat, F32)
        self.use_pallas = (_fine_pallas_default(self.fat_pairs)
                           if use_pallas is None else bool(use_pallas))
        self._interp = bool(pallas_interpret)
        if self.use_pallas:
            from ..ops.staggered_pallas import backward_links
            from ..ops.wilson_packed import pack_gauge, to_packed_pairs
            self._X = int(dirac.geom.lattice_shape[-1])
            # the hierarchy represents the FAT-ONLY stencil — only the
            # fat links go resident in kernel layout
            self.fat_pl = to_packed_pairs(pack_gauge(dirac.fat), F32)
            self.fat_bw = backward_links(self.fat_pl, self._X, 1)
        # Improved staggered: the HIERARCHY represents the fat-link
        # stencil (the standard preconditioner simplification, matching
        # mg/mg._StaggeredLevelOp and QUDA's coarse construction,
        # lib/staggered_coarse_op.in.cu), while M_std_full applies the
        # full fat+Naik operator — mg_solve_pairs runs the outer Krylov
        # on M_std_full so the fat-only V-cycle defect-corrects the
        # Naik term implicitly (ref lib/dirac_improved_staggered_kd.cpp).
        self.long_pairs = (to_pairs(dirac.long, F32)
                           if getattr(dirac, "long", None) is not None
                           else None)
        self._eps = parity_eps(self.geom.lattice_shape, 3)

    # -- pallas-layout shuttles ----------------------------------------
    @staticmethod
    def _pl_of(v):
        """canonical pairs (T,Z,Y,X,1,3,2) -> kernel layout
        (3,2,T,Z,YX)."""
        T, Z, Y, X = v.shape[:4]
        return jnp.transpose(v[..., 0, :, :],
                             (4, 5, 0, 1, 2, 3)).reshape(
            3, 2, T, Z, Y * X)

    @staticmethod
    def _pl_back(out, lat):
        T, Z, Y, X = lat
        return jnp.transpose(out.reshape(3, 2, T, Z, Y, X),
                             (2, 3, 4, 5, 0, 1))[..., None, :, :]

    # -- standard (canonical pair, (lat, 1, 3, 2)) layout --------------
    def _d_std(self, v):
        if self.use_pallas:
            from ..ops.staggered_pallas import dslash_staggered_pallas
            d = dslash_staggered_pallas(self.fat_pl, self.fat_bw,
                                        self._pl_of(v), self._X,
                                        interpret=self._interp)
            return self._pl_back(d, v.shape[:4])
        from ..ops import staggered as sops
        return sops.dslash_full(self.fat_pairs, v)

    def _d_std_mrhs(self, V):
        """(N, lat, 1, 3, 2) batched fat-only D through ONE stencil —
        the MRHS kernel on chip (link tiles amortised over all N)."""
        if self.use_pallas:
            from ..ops.staggered_pallas import \
                dslash_staggered_pallas_mrhs
            lat = V.shape[1:5]
            pp = jax.vmap(self._pl_of)(V)
            d = dslash_staggered_pallas_mrhs(self.fat_pl, self.fat_bw,
                                             pp, self._X,
                                             interpret=self._interp)
            return jax.vmap(lambda o: self._pl_back(o, lat))(d)
        from ..ops import staggered as sops
        return jax.vmap(lambda v: sops.dslash_full(self.fat_pairs,
                                                   v))(V)

    def M_mrhs(self, Vc):
        """(N, lat, 2, 3, 2) chiral batch -> M per RHS, one batched
        stencil (null-vector block solve direct matvec)."""
        s = self.from_chiral(Vc)
        return self.to_chiral(2.0 * self.mass * s + self._d_std_mrhs(s))

    def MdagM_mrhs(self, Vc):
        """(N, lat, 2, 3, 2) chiral batch -> MdagM per RHS, one batched
        stencil per application (null-vector block solve matvec)."""
        s = self.from_chiral(Vc)
        ms = 2.0 * self.mass * s + self._d_std_mrhs(s)
        md = 2.0 * self.mass * ms - self._d_std_mrhs(ms)
        return self.to_chiral(md)

    def M_std(self, v):
        return 2.0 * self.mass * v + self._d_std(v)

    def _mdag_std(self, v):
        return 2.0 * self.mass * v - self._d_std(v)

    # -- full improved operator (fat + Naik), standard layout ----------
    def _d_std_full(self, v):
        from ..ops import staggered as sops
        return sops.dslash_full(self.fat_pairs, v, self.long_pairs)

    def M_std_full(self, v):
        """The operator the OUTER solve targets: fat+Naik when long
        links exist, else identical to M_std."""
        if self.long_pairs is None:
            return self.M_std(v)
        return 2.0 * self.mass * v + self._d_std_full(v)

    def Mdag_std_full(self, v):
        if self.long_pairs is None:
            return self._mdag_std(v)
        return 2.0 * self.mass * v - self._d_std_full(v)

    # -- chiral layout --------------------------------------------------
    def to_chiral(self, v):
        eps = jnp.asarray(self._eps)
        even = jnp.where(eps == 0, v, 0)[..., 0, :, :]
        odd = jnp.where(eps == 1, v, 0)[..., 0, :, :]
        return jnp.stack([even, odd], axis=-3)

    def from_chiral(self, vc):
        return (vc[..., 0, :, :] + vc[..., 1, :, :])[..., None, :, :]

    def M(self, v):
        return self.to_chiral(self.M_std(self.from_chiral(v)))

    def MdagM(self, v):
        s = self.from_chiral(v)
        return self.to_chiral(self._mdag_std(self.M_std(s)))

    def diag(self, v):
        # through the chiral roundtrip like the complex adapter: the
        # (lat, 2, 3) chiral space is larger than the image of
        # to_chiral, and M = diag + sum(hop) must hold as CHIRAL-space
        # operators for the probing construction to be consistent
        return self.to_chiral(2.0 * self.mass * self.from_chiral(v))

    def hop(self, v, mu, sign):
        from ..ops import staggered as sops
        return self.to_chiral(sops.hop_term(self.fat_pairs,
                                            self.from_chiral(v), mu,
                                            sign))

    def project_null_source(self, bs):
        """Parity-subspace projection of random chiral sources (the
        complex adapter's project_null_source, pair layout)."""
        return self.to_chiral(self.from_chiral(bs))


# -- the hierarchy ----------------------------------------------------------

class PairMG(MG):
    """Complex-free multigrid hierarchy: same driver as MG (V-cycle,
    probing, verify are inherited), pair-array representation throughout.
    Setup runs real CG on the realified fine operator, CholQR2 block
    orthonormalisation, and real probing — no complex dtype anywhere."""

    _transfer_from_nulls = staticmethod(PairTransfer.from_null_vectors)
    _build_coarse = staticmethod(build_coarse_pairs)     # legacy probe

    @staticmethod
    def _build_coarse_gemm(parts, transfer):
        from .gemm import build_coarse_pairs_gemm
        return build_coarse_pairs_gemm(parts, transfer)

    def _example_field(self, lat_shape, k, dtype):
        rdt = jnp.zeros((), dtype).real.dtype
        return jnp.zeros(lat_shape + (2, k, 2), rdt)

    def _random_like(self, example, key):
        return jax.random.normal(key, example.shape, example.dtype)

    @staticmethod
    def _adapt(fine_dirac, kd: bool = False):
        if getattr(fine_dirac, "nspin", 4) == 1:
            if kd:
                raise NotImplementedError(
                    "pair staggered MG: the Kaehler-Dirac composition "
                    "is complex-only (the direct hierarchy is the "
                    "measured-better configuration; mg/mg.py)")
            return PairStaggeredLevelOp(fine_dirac)
        return PairWilsonLevelOp(fine_dirac)

    @classmethod
    def from_complex(cls, mg: MG, fine_dirac=None) -> "PairMG":
        """Realify an existing complex hierarchy (CPU-built setup ->
        complex-free apply path) without re-running setup."""
        if getattr(mg.adapter, "kd", False):
            raise NotImplementedError(
                "PairMG.from_complex: the source hierarchy composes the "
                "Kaehler-Dirac Xinv, which has no pair fine adapter — "
                "realifying only the transfers would silently break "
                "Galerkin consistency")
        self = object.__new__(cls)
        self.geom = mg.geom
        self.params = list(mg.params)
        self.adapter = cls._adapt(fine_dirac if fine_dirac is not None
                                  else mg.adapter.dirac)
        self.levels = []
        op = self.adapter
        for lv in mg.levels:
            transfer = PairTransfer.from_complex(lv["transfer"])
            coarse = PairCoarseOperator.from_complex(lv["coarse"])
            self.levels.append(dict(op=op, transfer=transfer,
                                    coarse=coarse, param=lv["param"]))
            op = coarse
        return self


def mg_solve_pairs(fine_dirac, geom, b_pairs, params: Sequence[MGLevelParam],
                   tol: float = 1e-6, nkrylov: int = 16,
                   max_restarts: int = 100, key=None,
                   mg: Optional[PairMG] = None):
    """Outer GCR on canonical pair spinors preconditioned by the pair MG
    V-cycle — the complex-free analog of mg/mg.mg_solve AND
    mg/mg.staggered_mg_solve (the adapter supplies the right M_std:
    Wilson (T,Z,Y,X,4,3,2) or staggered (T,Z,Y,X,1,3,2) pair fields).

    For improved staggered (fine_dirac.long is not None) the outer GCR
    applies the FULL fat+Naik operator while the hierarchy preconditions
    with the fat-only stencil — flexible-Krylov defect correction of the
    Naik term (ref lib/dirac_improved_staggered_kd.cpp:1, the production
    improved-staggered MG wiring).

    Returns (SolverResult with pair x, mg).
    """
    from ..solvers.gcr import gcr
    if mg is None:
        mg = PairMG(fine_dirac, geom, params, key)
    a = mg.adapter
    outer = getattr(a, "M_std_full", a.M_std)
    res = gcr(outer, b_pairs, precond=mg.precondition, tol=tol,
              nkrylov=nkrylov, max_restarts=max_restarts)
    return res, mg
