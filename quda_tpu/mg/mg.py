"""Adaptive multigrid driver: setup (null vectors, transfer, coarse op),
recursive V-cycle, and the MG-preconditioned outer solve.

Reference behavior: lib/multigrid.cpp (MG::reset :91, createSmoother :289,
createCoarseDirac :358, createCoarseSolver :581, operator() :1145,
generateNullVectors :1249) and the newMultigridQuda/invertQuda wiring in
lib/interface_quda.cpp.

Setup per level:
  1. generate n_vec near-null vectors of the level operator (loose inverse
     iterations: solve M^dag M v = r_random to low accuracy),
  2. block-orthonormalise them into a Transfer (batched QR),
  3. probe the Galerkin coarse stencil (mg/coarse.py),
  4. recurse until `n_levels`.

Apply (the preconditioner for an outer flexible solver, GCR):
  V-cycle: pre-smooth (fixed-iteration MR) -> restrict residual -> coarse
  solve (recursive V-cycle, or GCR at the bottom) -> prolong-correct ->
  post-smooth.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops import blas
from ..solvers.cg import cg_fixed_iters
from ..solvers.gcr import gcr, gcr_fixed, mr_fixed
from .coarse import CoarseOperator, build_coarse
from .gemm import build_coarse_gemm
from .transfer import Transfer, from_chiral, to_chiral


def parity_eps(lat, trailing):
    """Site-parity mask ``(t+z+y+x) % 2`` with ``trailing`` broadcast
    axes appended — the staggered chiral embedding's epsilon, built in
    ONE place so the level-op constructors (here and mg/pair.py) and
    the opstate restore cannot drift."""
    import numpy as np
    T, Z, Y, X = lat
    t = np.arange(T)[:, None, None, None]
    z = np.arange(Z)[None, :, None, None]
    y = np.arange(Y)[None, None, :, None]
    x = np.arange(X)[None, None, None, :]
    return ((t + z + y + x) % 2).reshape((T, Z, Y, X) + (1,) * trailing)


def _legacy_setup() -> bool:
    """QUDA_TPU_MG_SETUP=legacy selects the pre-round-15 pipeline
    (chunked-vmap fixed-iteration null solves + masked probe loop) —
    kept for the A/B the mg_setup_phase_seconds_total counters own."""
    from ..utils import config as qconf
    return str(qconf.get("QUDA_TPU_MG_SETUP", fresh=True)) == "legacy"


def _normalized_batch(xs):
    from ..ops import blas as _blas
    norms = jax.vmap(_blas.norm2)(xs)
    scale = (1.0 / jnp.sqrt(norms)).astype(xs.dtype)
    return xs * scale.reshape(scale.shape + (1,) * (xs.ndim - 1))


import functools as _functools


def _pick_null_mv(op, use_cg):
    """The level's batched matvec for the null-vector block solve:
    the MRHS stencil when the operator exposes one (link tiles fetched
    once for all lanes), a vmap of the single-RHS form otherwise."""
    if use_cg:
        return getattr(op, "MdagM_mrhs", None) or \
            (lambda V: jax.vmap(op.MdagM)(V))
    return getattr(op, "M_mrhs", None) or \
        (lambda V: jax.vmap(op.M)(V))


def _null_solve_body(mv, bb, tol, maxiter, use_cg, cplx):
    """Tolerance-stopped block solve + normalisation shared by the
    cached (opstate) and closure-jit null-vector routes: ``mv`` is the
    batched matvec in the operator's native dtype (MdagM for cg, M for
    bicgstab); complex systems realify around BiCGStab (its scalar
    lanes are real — the pair-route embedding)."""
    from ..solvers.block import batched_bicgstab_pairs, batched_cg_pairs
    if use_cg:
        return _normalized_batch(
            batched_cg_pairs(mv, bb, tol=tol, maxiter=maxiter).x)
    if cplx:
        def mvp(Vp):
            out = mv(Vp[..., 0] + 1j * Vp[..., 1])
            return jnp.stack([jnp.real(out), jnp.imag(out)], -1)
        bp = jnp.stack([jnp.real(bb), jnp.imag(bb)], -1)
    else:
        mvp, bp = mv, bb
    xs = batched_bicgstab_pairs(mvp, bp, tol=tol, maxiter=maxiter).x
    if cplx:
        xs = (xs[..., 0] + 1j * xs[..., 1]).astype(bb.dtype)
    return _normalized_batch(xs)


@_functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _null_solve_cached(restore, spec, tol, maxiter, use_cg, cplx,
                       arrays, bb):
    """Module-level cached null-vector block solve (see mg/opstate.py:
    arrays as arguments -> constant-free compiles + jit-cache hits on
    every same-shaped rebuild).  Returns the normalised solution
    batch."""
    op = restore(spec, arrays)
    return _null_solve_body(_pick_null_mv(op, use_cg), bb, tol,
                            maxiter, use_cg, cplx)


@dataclasses.dataclass
class MGLevelParam:
    """Per-level knobs (QudaMultigridParam analog)."""
    block: Tuple[int, int, int, int] = (2, 2, 2, 2)
    n_vec: int = 8
    setup_iters: int = 150          # inverse-iteration cap per null vector
    # null-vector solve tolerance (QudaMultigridParam::setup_tol): the
    # fast MRHS setup stops a lane once |r| <= setup_tol * |b| instead
    # of burning the full fixed iteration count — the legacy pipeline
    # has no convergence test and always runs setup_iters
    setup_tol: float = 5e-6
    # fast-setup null-vector solver (QudaMultigridParam::
    # setup_inv_type): 'bicgstab' = batched BiCGStab on the DIRECT
    # system M v = r (the reference's generateNullVectors discipline;
    # ~3-5x fewer dslash than the normal equations near kappa
    # critical), 'cg' = tolerance-stopped inverse iteration on MdagM
    setup_solver: str = "bicgstab"
    pre_smooth: int = 0             # QUDA default: no pre-smoothing
    post_smooth: int = 4
    smoother: str = "mr"            # "mr" | "ca-gcr" (QUDA smoother types)
    smoother_omega: float = 0.85
    coarse_solver_iters: int = 8    # GCR iterations on the bottom level
    coarse_solver_cycles: int = 2
    # Coarse-level latency strategy (SURVEY hard-part #1; QUDA runs
    # coarse levels on subset communicators, lib/multigrid.cpp:358).
    # True = all-gather this level's COARSE rhs and run everything below
    # it REPLICATED on every device (redundant flops, zero collectives
    # below the seam — the ICI-latency trade that wins when the coarse
    # lattice is a handful of sites per device).  Set on the coarsest
    # level's param for the classic bottom-solve gather, or on an
    # INTERMEDIATE level to take whole sub-hierarchies off the mesh —
    # the TPU analog of the reference's subset communicators.
    coarse_replicate: bool = False


class _LevelOp:
    """Fine-level adapter for WILSON-LIKE (nspin=4) operators: chirality
    is the gamma5 spin split, K = 6 (2 spins x 3 colors per chirality).
    Also exposes diag/hop for the coarse probing (FineOpParts face)."""

    k_fine = 6

    def __init__(self, dirac):
        self.dirac = dirac
        self.dtype = dirac.gauge.dtype if hasattr(dirac, "gauge") \
            else jnp.complex128

    def to_chiral(self, v):
        return to_chiral(v)

    def from_chiral(self, v):
        return from_chiral(v)

    def M(self, v):
        return to_chiral(self.dirac.M(from_chiral(v)))

    def MdagM(self, v):
        return to_chiral(self.dirac.MdagM(from_chiral(v)))

    def diag(self, v):
        return to_chiral(self.dirac.diag(from_chiral(v)))

    def hop(self, v, mu, sign):
        return to_chiral(self.dirac.hop(from_chiral(v), mu, sign))


class _StaggeredLevelOp:
    """Fine-level adapter for STAGGERED (nspin=1) operators: chirality is
    the site parity epsilon(x) = (-1)^{x+y+z+t} (the staggered gamma5),
    K = 3 colors; the (lat, 2, 3) chiral field holds the even-site part
    in component 0 and the odd-site part in component 1.

    With ``kd=True`` the adapted operator is the Kaehler-Dirac
    right-preconditioned A = M . Xinv (mg/staggered_kd.py; QUDA
    dirac_staggered_kd.cpp) — the "level 0.5" of staggered MG
    (lib/multigrid.cpp:215 staggered-KD reset).  Xinv is block-local on
    2^4 blocks, so with level-0 aggregates of (2,2,2,2) the composed
    hops still couple only adjacent aggregates and the Galerkin probing
    stays exact.  For improved staggered the stencil uses the fat links
    only (standard preconditioner simplification).
    """

    k_fine = 3

    def __init__(self, dirac, kd: bool = False):
        self.dirac = dirac
        self.geom = dirac.geom
        self.dtype = dirac.fat.dtype
        self._eps = parity_eps(self.geom.lattice_shape, 2)  # (lat,1,1)
        self.kd = kd
        if kd:
            from .staggered_kd import build_kd_xinv
            self.xinv = build_kd_xinv(self._m_fat_std, self.geom,
                                      self.dtype)
            self.xinv_dag = jnp.conjugate(jnp.swapaxes(self.xinv, -1, -2))

    # -- standard-layout operator pieces -------------------------------
    def _m_fat_std(self, v):
        """Fat-link-only M (the stencil the MG hierarchy represents)."""
        return self.dirac.diag(v) + sum(
            self.dirac.hop(v, mu, s) for mu in range(4) for s in (+1, -1))

    def _xinv_std(self, v, dag=False):
        from .staggered_kd import apply_kd_xinv
        return apply_kd_xinv(self.xinv_dag if dag else self.xinv, v)

    def apply_std(self, v):
        """The operator the outer solver sees, standard layout."""
        a = self._xinv_std(v) if self.kd else v
        return self._m_fat_std(a)

    def _mdag_std(self, v):
        # fat-only staggered: Mdag = 2m - D
        out = self.dirac.diag(v) - sum(
            self.dirac.hop(v, mu, s) for mu in range(4) for s in (+1, -1))
        return out

    # -- chiral layout --------------------------------------------------
    def to_chiral(self, v):
        eps = jnp.asarray(self._eps)
        even = jnp.where(eps == 0, v, 0)[..., 0, :]
        odd = jnp.where(eps == 1, v, 0)[..., 0, :]
        return jnp.stack([even, odd], axis=-2)

    def from_chiral(self, vc):
        return (vc[..., 0, :] + vc[..., 1, :])[..., None, :]

    def M(self, v):
        return self.to_chiral(self.apply_std(self.from_chiral(v)))

    def MdagM(self, v):
        s = self.from_chiral(v)
        a = self.apply_std(s)
        ad = self._mdag_std(a)
        if self.kd:
            ad = self._xinv_std(ad, dag=True)
        return self.to_chiral(ad)

    def diag(self, v):
        s = self.from_chiral(v)
        if self.kd:
            s = self._xinv_std(s)
        return self.to_chiral(self.dirac.diag(s))

    def hop(self, v, mu, sign):
        s = self.from_chiral(v)
        if self.kd:
            s = self._xinv_std(s)
        return self.to_chiral(self.dirac.hop(s, mu, sign))

    def project_null_source(self, bs):
        """Project random chiral sources onto the parity-masked
        subspace the staggered chiral embedding actually spans (see
        MG._generate_null_vectors — tolerance-stopped setup solves
        need a consistent system)."""
        return self.to_chiral(self.from_chiral(bs))


def _make_fine_adapter(dirac, kd: bool = False):
    if getattr(dirac, "nspin", 4) == 1:
        return _StaggeredLevelOp(dirac, kd=kd)
    return _LevelOp(dirac)


class MG:
    """Multigrid preconditioner hierarchy.

    Layout hooks (`_example_field`, `_random_like`, `_transfer_from_nulls`,
    `_build_coarse`) isolate the field representation: the base class works
    on complex chiral fields (lat, 2, K); mg/pair.PairMG overrides them to
    run the identical hierarchy on real re/im pair arrays (lat, 2, K, 2)
    for TPU runtimes without complex execution."""

    _transfer_from_nulls = staticmethod(Transfer.from_null_vectors)
    _build_coarse = staticmethod(build_coarse)           # legacy probe
    _build_coarse_gemm = staticmethod(build_coarse_gemm)  # fast default

    def __init__(self, fine_dirac, geom, params: Sequence[MGLevelParam],
                 key=None, verbosity: int = 0, kd: bool = False):
        self.geom = geom
        self.params = list(params)
        if key is None:
            key = jax.random.PRNGKey(2024)
        self.levels: List[dict] = []
        # accept a ready adapter (has k_fine) or a Dirac operator
        self.adapter = (fine_dirac if hasattr(fine_dirac, "k_fine")
                        else self._adapt(fine_dirac, kd=kd))
        self._setup(self.adapter, key, verbosity)

    @staticmethod
    def _adapt(fine_dirac, kd: bool = False):
        return _make_fine_adapter(fine_dirac, kd=kd)

    # -- layout hooks --------------------------------------------------
    def _example_field(self, lat_shape, k, dtype):
        """Zero chiral field of this hierarchy's layout."""
        return jnp.zeros(lat_shape + (2, k), dtype)

    def _random_like(self, example, key):
        """Gaussian field matching `example` (complex here; real in pair
        subclasses)."""
        rdt = jnp.zeros((), example.dtype).real.dtype
        re = jax.random.normal(key, example.shape, rdt)
        im = jax.random.normal(jax.random.fold_in(key, 1), example.shape,
                               rdt)
        return (re + 1j * im).astype(example.dtype)

    # -- setup ---------------------------------------------------------
    def _generate_null_vectors(self, level_op, example, n_vec, p, key):
        """Near-null vectors for one level, normalised.

        Fast path (default): ONE MRHS block solve of M v = r over all
        n_vec random sources at once — QUDA's generateNullVectors
        discipline (lib/multigrid.cpp:1249: the setup solver runs on
        the DIRECT system at setup_tol), through
        ``solvers/block.batched_bicgstab_pairs`` (per-RHS scalar
        lanes, two batched matvecs per iteration).  On kappa-critical
        Wilson drills the direct solve needs ~3-5x fewer dslash
        applications than CG on the squared-condition normal
        equations, and the batch runs the level's MRHS stencil
        (``M_mrhs`` — the MRHS pallas kernel on fine Wilson/staggered
        levels, one link fetch amortised over all n_vec).  Complex
        levels realify into pair arrays around the batched solve
        (real-coefficient Krylov on the realified operator — the
        standard pair-route embedding).  ``p.setup_solver='cg'``
        selects tolerance-stopped inverse iteration on MdagM instead
        (``batched_cg_pairs``, complex-safe lanes).
        QUDA_TPU_MG_NULL_CHUNK caps the batch width (HBM valve).

        Legacy path (QUDA_TPU_MG_SETUP=legacy): the pre-round-15
        chunked-vmap fixed-iteration CG on MdagM — no convergence
        test, always ``setup_iters`` iterations per vector — kept for
        the A/B the phase counters arbitrate."""
        from ..utils import config as qconf
        bs = jnp.stack([
            self._random_like(example, jax.random.fold_in(key, i))
            for i in range(n_vec)])
        chunk = int(qconf.get("QUDA_TPU_MG_NULL_CHUNK", fresh=True))
        iters = p.setup_iters
        proj = getattr(level_op, "project_null_source", None)
        if proj is not None and not _legacy_setup():
            # staggered chiral layouts embed the site fields in a
            # larger space (parity-masked components): a raw random
            # chiral source has a component outside the operator's
            # range, which a TOLERANCE-stopped solve can never
            # converge away (the fixed-iteration legacy never
            # noticed).  Projecting onto the valid subspace makes the
            # system consistent without changing the Krylov span.
            bs = proj(bs)

        if _legacy_setup():
            # chunked vmap: all solves in one compiled computation per
            # chunk, peak memory capped at ~chunk Krylov states
            # (historical hard-coded width: min(n_vec, 4))
            op_MdagM = level_op.MdagM
            chunk = chunk if chunk > 0 else min(n_vec, 4)

            @jax.jit
            def solve_chunk(bb):
                return _normalized_batch(jax.vmap(
                    lambda b: cg_fixed_iters(op_MdagM, b, None,
                                             iters)[0].x)(bb))

            outs = [solve_chunk(bs[i:i + chunk])
                    for i in range(0, n_vec, chunk)]
            return jnp.concatenate(outs) if len(outs) > 1 else outs[0]

        chunk = n_vec if chunk <= 0 else min(chunk, n_vec)
        use_cg = getattr(p, "setup_solver", "bicgstab") == "cg"
        cplx = bool(jnp.iscomplexobj(bs))

        from .opstate import op_state
        st = op_state(level_op)

        def run_solve(cg_flag):
            """Chunked block solve with the (cg?, chunk)-shaped program
            picked per call: the cached constant-free route when the
            level op exposes its opstate (rebuilds of same-shaped
            hierarchies skip tracing AND compiling), a closure jit
            otherwise."""
            if st is not None:
                restore, spec, arrays = st

                def solve_block(bb):
                    return _null_solve_cached(restore, spec,
                                              float(p.setup_tol),
                                              int(iters), cg_flag, cplx,
                                              arrays, bb)
            else:
                mvb = _pick_null_mv(level_op, cg_flag)

                @jax.jit
                def solve_block(bb):
                    return _null_solve_body(mvb, bb, float(p.setup_tol),
                                            int(iters), cg_flag, cplx)
            outs = [solve_block(bs[i:i + chunk])
                    for i in range(0, n_vec, chunk)]
            return jnp.concatenate(outs) if len(outs) > 1 else outs[0]

        nulls = run_solve(use_cg)
        if not use_cg and not bool(jnp.all(jnp.isfinite(nulls))):
            # BiCGStab breakdown (r0-orthogonality collapse near
            # kappa critical): a non-finite lane halts the whole
            # batch, and baking it into the transfer would hand every
            # later gcr_mg solve a garbage hierarchy with nothing
            # pointing at setup.  Fall back to tolerance-stopped CG on
            # the SPD normal equations, which cannot break down.
            from ..utils import logging as qlog
            qlog.warn_once(
                "mg_null_bicgstab_breakdown",
                "MG setup: BiCGStab null-vector solve broke down "
                "(non-finite lanes); falling back to CG on the normal "
                "equations for this level")
            nulls = run_solve(True)
        return nulls

    @staticmethod
    def _await_phase(obj):
        """Block on every device array reachable from a phase's product
        so async dispatch cannot bill one phase's work to the next —
        the breakdown is only worth having if the rows are honest.
        Setup is host-driven; the sync points add nothing hot.  The
        product is either an array/pytree (tree_leaves finds the
        arrays directly — a bare jax Array has an EMPTY __dict__, so
        the object fallback must not shadow this case) or a plain
        object (Transfer/CoarseOperator: an opaque tree leaf, walked
        through its __dict__)."""
        leaves = jax.tree_util.tree_leaves(obj)
        if not any(hasattr(leaf, "block_until_ready")
                   for leaf in leaves):
            leaves = jax.tree_util.tree_leaves(
                getattr(obj, "__dict__", {}))
        for leaf in leaves:
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        return obj

    def _phase(self, level: int, phase: str):
        """One timed MG-setup phase: wall seconds appended to
        ``self.setup_breakdown``, mirrored as a trace span and the
        ``mg_setup_phase_seconds_total`` counter (both single-load
        no-ops when QUDA_TPU_TRACE/QUDA_TPU_METRICS are off) — the
        per-phase attribution the 5652s-setup scandal (ROADMAP item 1)
        never had."""
        import contextlib
        import time as _time

        from ..obs import metrics as omet
        from ..obs import trace as otr

        @contextlib.contextmanager
        def _ctx():
            t0 = _time.perf_counter()
            try:
                with otr.span(f"mg:{phase}", cat="mg", level=level):
                    yield
            finally:
                # record even when the phase raises (a pallas compile
                # failure here is exactly what robust/escalate retries)
                # — the span records its duration unconditionally, and
                # breakdown/metrics must not disagree with it on the
                # error paths
                dt = _time.perf_counter() - t0
                self.setup_breakdown.append(
                    {"level": level, "phase": phase,
                     "seconds": round(dt, 6)})
                omet.inc("mg_setup_phase_seconds_total", dt,
                         level=level, phase=phase)

        return _ctx()

    def _setup(self, adapter, key, verbosity):
        """Hierarchy build with per-phase attribution: [{level, phase,
        seconds}] rows (null_vectors | transfer_build | coarse_probe
        per level) + the total — host bookkeeping, maintained always;
        trace/metrics mirrors activate with their sessions.  The total
        and breakdown record in a finally so a mid-level failure (a
        pallas compile raise the robust ladder retries) still leaves
        honest partial attribution."""
        import time as _time

        from ..obs import metrics as omet
        self.setup_breakdown = []
        self.setup_seconds = 0.0     # set even if setup aborts mid-level
        t_setup0 = _time.perf_counter()
        try:
            self._setup_levels(adapter, key, verbosity)
        finally:
            self.setup_seconds = round(_time.perf_counter() - t_setup0,
                                       6)
            omet.inc("mg_setup_seconds_total", self.setup_seconds,
                     levels=len(self.params))

    def _setup_levels(self, adapter, key, verbosity):
        from ..obs import trace as otr
        level_op = adapter
        lat_shape = self.geom.lattice_shape
        k_fine = adapter.k_fine        # 6 wilson-like, 3 staggered, n_vec coarse
        with otr.span("mg_setup", cat="mg", levels=len(self.params)):
            for li, p in enumerate(self.params):
                dtype = (level_op.dtype if hasattr(level_op, "dtype")
                         else level_op.x_diag.dtype)
                example = self._example_field(lat_shape, k_fine, dtype)
                parts = level_op           # all adapters expose diag/hop
                legacy = _legacy_setup()
                with self._phase(li, "null_vectors"):
                    nulls = self._await_phase(
                        self._generate_null_vectors(
                            level_op, example, p.n_vec, p,
                            jax.random.fold_in(key, li)))
                with self._phase(li, "transfer_build"):
                    transfer = self._await_phase(
                        self._transfer_from_nulls(nulls, p.block))
                with self._phase(li, "coarse_probe"):
                    # phase name kept across pipelines: the counters'
                    # time series IS the A/B record
                    builder = (self._build_coarse if legacy
                               else self._build_coarse_gemm)
                    coarse = self._await_phase(builder(parts, transfer))
                self.levels.append(dict(op=level_op, transfer=transfer,
                                        coarse=coarse, param=p))
                if verbosity:
                    print(f"MG level {li}: lattice {lat_shape} "
                          f"k={k_fine} -> coarse "
                          f"{transfer.coarse_shape} n_vec={p.n_vec}")
                # descend
                level_op = coarse
                lat_shape = transfer.coarse_shape
                k_fine = p.n_vec

    # -- apply ---------------------------------------------------------
    def vcycle(self, level: int, b, x0=None):
        """Approximately solve M_level x = b (chiral layout)."""
        lv = self.levels[level]
        op, tr, coarse, p = lv["op"], lv["transfer"], lv["coarse"], lv["param"]

        def smooth(bb, n, x0):
            if p.smoother == "ca-gcr":
                return gcr_fixed(op.M, bb, nkrylov=n, cycles=1, x0=x0)
            return mr_fixed(op.M, bb, n, p.smoother_omega, x0=x0)

        x = jnp.zeros_like(b) if x0 is None else x0
        if p.pre_smooth:
            x = smooth(b, p.pre_smooth, x)
        r = b - op.M(x)
        rc = tr.restrict(r)
        if p.coarse_replicate:
            # Gather the coarse rhs onto every device BEFORE descending:
            # the level below (and, by GSPMD propagation, everything
            # under it) then runs collective-free and redundantly, and
            # the prolong's input resharding is a single scatter.  On
            # the COARSEST level this is the bottom-solve latency trade;
            # on an INTERMEDIATE level it is the TPU analog of QUDA's
            # subset communicators (lib/multigrid.cpp:185,
            # lib/communicator_stack.cpp:49 — SURVEY §7 hard part #1):
            # small grids whose halo latency dominates their compute run
            # replicated instead of latency-bound on the full mesh.
            rc = self._replicate(rc)
        if level + 1 < len(self.levels):
            ec = self.vcycle(level + 1, rc)
        else:
            ec = gcr_fixed(coarse.M, rc, nkrylov=p.coarse_solver_iters,
                           cycles=p.coarse_solver_cycles)
        x = x + tr.prolong(ec)
        if p.post_smooth:
            x = smooth(b, p.post_smooth, x)
        return x

    def _replicate(self, rc):
        """Constrain ``rc`` to a fully-replicated sharding under the
        active mesh (abstract `jax.sharding.use_mesh` or a concrete
        ``with mesh:`` context); no-op with a one-time warning when no
        mesh is active."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        spec = P(*([None] * rc.ndim))
        amesh = jax.sharding.get_abstract_mesh()
        pmesh = None
        try:
            from jax._src.mesh import thread_resources
            pm = thread_resources.env.physical_mesh
            if pm is not None and not pm.empty:
                pmesh = pm
        except Exception:
            pass
        if amesh is not None and amesh.shape_tuple:
            return jax.lax.with_sharding_constraint(rc, spec)
        if pmesh is not None:
            return jax.lax.with_sharding_constraint(
                rc, NamedSharding(pmesh, spec))
        if not getattr(self, "_warned_replicate", False):
            import warnings
            warnings.warn(
                "coarse_replicate=True has no effect without an "
                "active mesh context (wrap the jit in `with "
                "mesh:` or jax.sharding.use_mesh)", stacklevel=2)
            self._warned_replicate = True
        return rc

    def precondition(self, r_std):
        """K(r) for an outer solver in STANDARD layout (spin for
        wilson-like, (lat,1,3) for staggered)."""
        a = self.adapter
        return a.from_chiral(self.vcycle(0, a.to_chiral(r_std)))

    # -- runtime verification (MG::verify, lib/multigrid.cpp:762) ------
    def verify(self, key=None, galerkin_tol: float = 1e-10,
               pr_tol: float = 1e-10):
        """Check P/R bi-orthonormality and Galerkin consistency on every
        level with a random coarse vector; returns per-level diagnostics
        and raises on violation (QUDA MG::verify analog)."""
        if key is None:
            key = jax.random.PRNGKey(17)
        report = []
        for li, lv in enumerate(self.levels):
            op, tr, coarse = lv["op"], lv["transfer"], lv["coarse"]
            latc = tr.coarse_shape
            k = jax.random.fold_in(key, li)
            dtype = (op.dtype if hasattr(op, "dtype")
                     else op.x_diag.dtype)
            vc = self._random_like(
                self._example_field(latc, tr.n_vec, dtype), k)
            # R P = I on the coarse space
            rp = tr.restrict(tr.prolong(vc))
            e_rp = float(jnp.sqrt(blas.norm2(rp - vc) / blas.norm2(vc)))
            # Galerkin: coarse.M == R M P
            lhs = coarse.M(vc)
            rhs = tr.restrict(op.M(tr.prolong(vc)))
            e_g = float(jnp.sqrt(blas.norm2(lhs - rhs)
                                 / jnp.maximum(blas.norm2(rhs), 1e-30)))
            report.append({"level": li, "rp_identity": e_rp,
                           "galerkin": e_g})
            if e_rp > pr_tol:
                raise RuntimeError(
                    f"MG verify level {li}: R P != I ({e_rp:.2e})")
            if e_g > galerkin_tol:
                raise RuntimeError(
                    f"MG verify level {li}: Galerkin violated ({e_g:.2e})")
        return report


# backwards-compat alias: diag/hop now live on the adapters themselves
_FinePartsAdapter = _LevelOp


def mg_solve(fine_dirac, geom, b_std, params: Sequence[MGLevelParam],
             tol: float = 1e-10, nkrylov: int = 16, max_restarts: int = 100,
             key=None, mg: Optional[MG] = None):
    """Outer GCR preconditioned by the MG V-cycle (QUDA's standard wiring:
    invertQuda with inv_type=GCR, inv_type_precondition=MG)."""
    if mg is None:
        mg = MG(fine_dirac, geom, params, key)
    res = gcr(fine_dirac.M, b_std, precond=mg.precondition, tol=tol,
              nkrylov=nkrylov, max_restarts=max_restarts)
    return res, mg


def staggered_mg_solve(dirac, geom, b_std, params: Sequence[MGLevelParam],
                       tol: float = 1e-10, nkrylov: int = 16,
                       max_restarts: int = 100, key=None, kd: bool = False,
                       mg: Optional[MG] = None):
    """Staggered multigrid solve: outer GCR on M (or, with kd=True, on
    the KD-right-preconditioned A = M Xinv, QUDA's staggered-KD path,
    lib/multigrid.cpp:215), preconditioned by the parity-chirality MG
    hierarchy.  Measured on random gauge at m=0.02 (8^4): the DIRECT
    hierarchy with the ca-gcr smoother contracts ~0.36/cycle while the
    KD-composed one stalls (~0.9) — hence kd defaults to False here; the
    KD machinery remains available and is what QUDA composes on
    physical configurations.

    For improved staggered the hierarchy represents the fat-link stencil
    but the outer GCR applies the FULL fat+Naik M — flexible-Krylov
    defect correction of the Naik term around the fat-only V-cycle (ref
    lib/dirac_improved_staggered_kd.cpp, the production improved-staggered
    MG wiring).  With kd=True the KD composition stays fat-only."""
    if mg is None:
        mg = MG(dirac, geom, params, key, kd=kd)
    a = mg.adapter
    # the adapter knows whether IT composes Xinv — never trust the kd
    # argument when a prebuilt hierarchy is passed in
    kd_active = getattr(a, "kd", False)
    outer = a.apply_std
    if not kd_active and getattr(a.dirac, "long", None) is not None:
        outer = a.dirac.M          # full improved operator (fat + Naik)
    res = gcr(outer, b_std, precond=mg.precondition, tol=tol,
              nkrylov=nkrylov, max_restarts=max_restarts)
    x = a._xinv_std(res.x) if kd_active else res.x
    res = res._replace(x=x)
    return res, mg
