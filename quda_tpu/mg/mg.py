"""Adaptive multigrid driver: setup (null vectors, transfer, coarse op),
recursive V-cycle, and the MG-preconditioned outer solve.

Reference behavior: lib/multigrid.cpp (MG::reset :91, createSmoother :289,
createCoarseDirac :358, createCoarseSolver :581, operator() :1145,
generateNullVectors :1249) and the newMultigridQuda/invertQuda wiring in
lib/interface_quda.cpp.

Setup per level:
  1. generate n_vec near-null vectors of the level operator (loose inverse
     iterations: solve M^dag M v = r_random to low accuracy),
  2. block-orthonormalise them into a Transfer (batched QR),
  3. probe the Galerkin coarse stencil (mg/coarse.py),
  4. recurse until `n_levels`.

Apply (the preconditioner for an outer flexible solver, GCR):
  V-cycle: pre-smooth (fixed-iteration MR) -> restrict residual -> coarse
  solve (recursive V-cycle, or GCR at the bottom) -> prolong-correct ->
  post-smooth.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops import blas
from ..solvers.cg import cg_fixed_iters
from ..solvers.gcr import gcr, gcr_fixed, mr_fixed
from .coarse import CoarseOperator, build_coarse
from .transfer import Transfer, from_chiral, to_chiral


@dataclasses.dataclass
class MGLevelParam:
    """Per-level knobs (QudaMultigridParam analog)."""
    block: Tuple[int, int, int, int] = (2, 2, 2, 2)
    n_vec: int = 8
    setup_iters: int = 150          # inverse-iteration count per null vector
    pre_smooth: int = 0             # QUDA default: no pre-smoothing
    post_smooth: int = 4
    smoother_omega: float = 0.85
    coarse_solver_iters: int = 8    # GCR iterations on the bottom level


class _LevelOp:
    """Adapter giving every level the same face: M/diag/hop in CHIRAL
    layout for fine Dirac operators; CoarseOperator already is."""

    def __init__(self, dirac):
        self.dirac = dirac

    def M(self, v):
        return to_chiral(self.dirac.M(from_chiral(v)))

    def MdagM(self, v):
        return to_chiral(self.dirac.MdagM(from_chiral(v)))


class MG:
    """Multigrid preconditioner hierarchy."""

    def __init__(self, fine_dirac, geom, params: Sequence[MGLevelParam],
                 key=None, verbosity: int = 0):
        self.geom = geom
        self.params = list(params)
        if key is None:
            key = jax.random.PRNGKey(2024)
        self.levels: List[dict] = []
        self._setup(fine_dirac, key, verbosity)

    # -- setup ---------------------------------------------------------
    def _generate_null_vectors(self, op_M, op_MdagM, example, n_vec, iters,
                               key):
        """Inverse iteration: v = (MdagM)^{-1}-ish random, normalised."""
        vecs = []
        solve = jax.jit(
            lambda b: cg_fixed_iters(op_MdagM, b, None, iters)[0].x)
        for i in range(n_vec):
            k = jax.random.fold_in(key, i)
            rdt = jnp.zeros((), example.dtype).real.dtype
            re = jax.random.normal(k, example.shape, rdt)
            im = jax.random.normal(jax.random.fold_in(k, 1), example.shape,
                                   rdt)
            b = (re + 1j * im).astype(example.dtype)
            v = solve(b)
            v = v / jnp.sqrt(blas.norm2(v)).astype(v.dtype)
            vecs.append(v)
        return jnp.stack(vecs)

    def _setup(self, fine_dirac, key, verbosity):
        level_op = _LevelOp(fine_dirac)
        lat_shape = self.geom.lattice_shape
        k_fine = 6
        for li, p in enumerate(self.params):
            example = jnp.zeros(lat_shape + (2, k_fine),
                                fine_dirac.gauge.dtype
                                if hasattr(fine_dirac, "gauge")
                                else jnp.complex128)
            if isinstance(level_op, _LevelOp):
                example = example.astype(level_op.dirac.gauge.dtype)
                MdagM = level_op.MdagM
                parts = _FinePartsAdapter(level_op.dirac)
            else:
                example = example.astype(level_op.x_diag.dtype)
                MdagM = level_op.MdagM
                parts = level_op
            nulls = self._generate_null_vectors(
                level_op.M, MdagM, example, p.n_vec, p.setup_iters,
                jax.random.fold_in(key, li))
            transfer = Transfer.from_null_vectors(nulls, p.block)
            coarse = build_coarse(parts, transfer)
            self.levels.append(dict(op=level_op, transfer=transfer,
                                    coarse=coarse, param=p))
            if verbosity:
                print(f"MG level {li}: lattice {lat_shape} k={k_fine} "
                      f"-> coarse {transfer.coarse_shape} n_vec={p.n_vec}")
            # descend
            level_op = coarse
            lat_shape = transfer.coarse_shape
            k_fine = p.n_vec

    # -- apply ---------------------------------------------------------
    def vcycle(self, level: int, b, x0=None):
        """Approximately solve M_level x = b (chiral layout)."""
        lv = self.levels[level]
        op, tr, coarse, p = lv["op"], lv["transfer"], lv["coarse"], lv["param"]
        x = jnp.zeros_like(b) if x0 is None else x0
        if p.pre_smooth:
            x = mr_fixed(op.M, b, p.pre_smooth, p.smoother_omega, x0=x)
        r = b - op.M(x)
        rc = tr.restrict(r)
        if level + 1 < len(self.levels):
            ec = self.vcycle(level + 1, rc)
        else:
            ec = gcr_fixed(coarse.M, rc, nkrylov=p.coarse_solver_iters,
                           cycles=2)
        x = x + tr.prolong(ec)
        if p.post_smooth:
            x = mr_fixed(op.M, b, p.post_smooth, p.smoother_omega, x0=x)
        return x

    def precondition(self, r_std):
        """K(r) for an outer solver in STANDARD spin layout."""
        return from_chiral(self.vcycle(0, to_chiral(r_std)))


class _FinePartsAdapter:
    """diag/hop of a fine Dirac operator, exposed in the chiral layout."""

    def __init__(self, dirac):
        self.dirac = dirac

    def diag(self, v):
        return to_chiral(self.dirac.diag(from_chiral(v)))

    def hop(self, v, mu, sign):
        return to_chiral(self.dirac.hop(from_chiral(v), mu, sign))


def mg_solve(fine_dirac, geom, b_std, params: Sequence[MGLevelParam],
             tol: float = 1e-10, nkrylov: int = 16, max_restarts: int = 100,
             key=None, mg: Optional[MG] = None):
    """Outer GCR preconditioned by the MG V-cycle (QUDA's standard wiring:
    invertQuda with inv_type=GCR, inv_type_precondition=MG)."""
    if mg is None:
        mg = MG(fine_dirac, geom, params, key)
    res = gcr(fine_dirac.M, b_std, precond=mg.precondition, tol=tol,
              nkrylov=nkrylov, max_restarts=max_restarts)
    return res, mg
