"""Lattice geometry: dimensions, axis conventions, parity decomposition.

Replaces QUDA's LatticeField geometry bookkeeping
(reference: include/lattice_field.h:155, lib/lattice_field.cpp) with a small
static (hashable) descriptor suitable for use as a jit-static argument.

Conventions
-----------
* Array axis order for lattice fields is ``(T, Z, Y, X, *internal)`` —
  X is the fastest-varying lattice axis (matches QUDA's x-fastest site
  ordering, include/index_helper.cuh).
* Directions ``mu = 0,1,2,3`` mean ``x,y,z,t`` (QUDA convention).
  ``axis_of_mu(mu) == 3 - mu`` maps a direction onto the array axis.
* Parity of a site is ``(x+y+z+t) % 2``; 0 = even, 1 = odd
  (QUDA QudaParity, include/enum_quda.h).
* Even/odd (checkerboarded) fields keep full extent in T,Z,Y and half
  extent in X: shape ``(T, Z, Y, X//2, *internal)``.  The physical x of
  element ``(t,z,y,xh)`` on parity ``p`` is ``2*xh + ((t+z+y+p) % 2)``.
"""

from __future__ import annotations

import dataclasses
from functools import cached_property
from typing import Tuple

NDIM = 4

# parity codes (QUDA QudaParity analog)
EVEN = 0
ODD = 1
FULL = 2


def axis_of_mu(mu: int) -> int:
    """Array axis carrying direction mu (mu: 0=x,1=y,2=z,3=t)."""
    return 3 - mu


@dataclasses.dataclass(frozen=True)
class LatticeGeometry:
    """Static description of a 4-D lattice.

    ``dims`` is (X, Y, Z, T) in QUDA order (lib/interface_quda.cpp uses
    param->X[4] with X[0]=x fastest).
    """

    dims: Tuple[int, int, int, int]  # (X, Y, Z, T)

    def __post_init__(self):
        if len(self.dims) != NDIM:
            raise ValueError(f"need 4 dims, got {self.dims}")
        if any(d <= 0 for d in self.dims):
            raise ValueError(f"dims must be positive: {self.dims}")
        if self.dims[0] % 2 != 0:
            raise ValueError(
                f"X extent must be even for even/odd decomposition: {self.dims}")

    # -- basic sizes ---------------------------------------------------
    @property
    def X(self) -> int:
        return self.dims[0]

    @property
    def Y(self) -> int:
        return self.dims[1]

    @property
    def Z(self) -> int:
        return self.dims[2]

    @property
    def T(self) -> int:
        return self.dims[3]

    @cached_property
    def volume(self) -> int:
        v = 1
        for d in self.dims:
            v *= d
        return v

    @property
    def half_volume(self) -> int:
        return self.volume // 2

    @cached_property
    def lattice_shape(self) -> Tuple[int, int, int, int]:
        """Array shape of the lattice axes: (T, Z, Y, X)."""
        return (self.T, self.Z, self.Y, self.X)

    @cached_property
    def half_lattice_shape(self) -> Tuple[int, int, int, int]:
        """Array shape of checkerboarded lattice axes: (T, Z, Y, X//2)."""
        return (self.T, self.Z, self.Y, self.X // 2)

    def extent(self, mu: int) -> int:
        """Extent along direction mu (0=x..3=t)."""
        return self.dims[mu]

    # -- shapes with internal dof --------------------------------------
    def spinor_shape(self, nspin: int = 4, ncolor: int = 3):
        return self.lattice_shape + (nspin, ncolor)

    def half_spinor_shape(self, nspin: int = 4, ncolor: int = 3):
        return self.half_lattice_shape + (nspin, ncolor)

    def gauge_shape(self, ncolor: int = 3):
        """(mu, T, Z, Y, X, c, c) — one SU(N) link per direction per site."""
        return (NDIM,) + self.lattice_shape + (ncolor, ncolor)

    def __str__(self):
        return f"LatticeGeometry(X={self.X},Y={self.Y},Z={self.Z},T={self.T})"
