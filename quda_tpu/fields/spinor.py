"""ColorSpinorField: fermion fields as sharded jax.Arrays.

TPU-native re-design of QUDA's ColorSpinorField
(reference: include/color_spinor_field.h:287, lib/color_spinor_field.cpp).
Instead of layout-polymorphic accessor templates
(include/color_spinor_field_order.h) we keep ONE canonical layout —
``(T, Z, Y, X, spin, color)`` complex, or the checkerboarded half-lattice
variant ``(T, Z, Y, X//2, spin, color)`` — and let XLA pick physical tiling.
Multi-RHS ("composite" fields, color_spinor_field.h:93-120) are a leading
batch axis, not a C++ descriptor.

The class is a registered pytree: `data` is traced, everything else static,
so fields pass through jit/shard_map/scan directly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .geometry import EVEN, FULL, ODD, LatticeGeometry


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ColorSpinorField:
    data: jax.Array  # (..., T, Z, Y, X[/2], spin, color)
    geom: LatticeGeometry = dataclasses.field(metadata=dict(static=True))
    parity: int = dataclasses.field(default=FULL, metadata=dict(static=True))
    nspin: int = dataclasses.field(default=4, metadata=dict(static=True))
    ncolor: int = dataclasses.field(default=3, metadata=dict(static=True))

    # -- construction --------------------------------------------------
    @classmethod
    def zeros(cls, geom: LatticeGeometry, parity: int = FULL, nspin: int = 4,
              ncolor: int = 3, dtype=jnp.complex128, batch: Tuple[int, ...] = ()):
        shape = batch + cls._site_shape(geom, parity) + (nspin, ncolor)
        return cls(jnp.zeros(shape, dtype), geom, parity, nspin, ncolor)

    @classmethod
    def gaussian(cls, key, geom: LatticeGeometry, parity: int = FULL,
                 nspin: int = 4, ncolor: int = 3, dtype=jnp.complex128,
                 batch: Tuple[int, ...] = ()):
        """Gaussian noise source (reference: lib/spinor_noise.in.cu)."""
        shape = batch + cls._site_shape(geom, parity) + (nspin, ncolor)
        rdt = jnp.zeros((), dtype).real.dtype
        k1, k2 = jax.random.split(key)
        re = jax.random.normal(k1, shape, rdt)
        im = jax.random.normal(k2, shape, rdt)
        return cls((re + 1j * im).astype(dtype) / jnp.sqrt(2.0).astype(rdt),
                   geom, parity, nspin, ncolor)

    @classmethod
    def point(cls, geom: LatticeGeometry, site=(0, 0, 0, 0), spin: int = 0,
              color: int = 0, nspin: int = 4, ncolor: int = 3,
              dtype=jnp.complex128):
        """Point source delta_{x,site} delta_{s,spin} delta_{c,color}."""
        x, y, z, t = site
        data = jnp.zeros(geom.spinor_shape(nspin, ncolor), dtype)
        data = data.at[t, z, y, x, spin, color].set(1.0)
        return cls(data, geom, FULL, nspin, ncolor)

    @staticmethod
    def _site_shape(geom: LatticeGeometry, parity: int):
        return geom.lattice_shape if parity == FULL else geom.half_lattice_shape

    # -- views ---------------------------------------------------------
    @property
    def dtype(self):
        return self.data.dtype

    @property
    def is_full(self) -> bool:
        return self.parity == FULL

    def like(self, data: jax.Array) -> "ColorSpinorField":
        return ColorSpinorField(data, self.geom, self.parity, self.nspin,
                                self.ncolor)

    def astype(self, dtype) -> "ColorSpinorField":
        return self.like(self.data.astype(dtype))

    # -- reductions (thin wrappers; solver hot loops use ops.blas) -----
    def norm2(self):
        d = self.data
        return jnp.sum(d.real * d.real + d.imag * d.imag)

    def dot(self, other: "ColorSpinorField"):
        return jnp.sum(jnp.conjugate(self.data) * other.data)


def even_odd_split(full: jax.Array, geom: LatticeGeometry):
    """Split a full-lattice site array into (even, odd) checkerboard halves.

    Layout rule (fields/geometry.py): element (t,z,y,xh) of the parity-p
    half-field holds the physical site x = 2*xh + ((t+z+y+p) % 2).
    Equivalent to QUDA's even/odd subsets (color_spinor_field.h Even()/Odd()).
    Works for any trailing internal shape; the lattice axes must be the
    leading four axes of `full` after optional batch axes are vmapped away.
    """
    T, Z, Y, X = geom.lattice_shape
    lead = full.ndim - 4 - _n_internal(full, geom)
    assert lead == 0, "batch axes: vmap even_odd_split"
    t, z, y = _tzy_grids(geom, full.dtype)
    # shift rows of odd (t+z+y) so that even sites land at even x-slots
    xh = X // 2
    resh = full.reshape((T, Z, Y, xh, 2) + full.shape[4:])
    # site (t,z,y,2*xh+r): parity = (t+z+y+r)%2
    s = ((t + z + y) % 2).astype(jnp.int32)  # (T,Z,Y)
    idx = jnp.broadcast_to(s[..., None], (T, Z, Y, xh))
    mask = _expand(idx == 0, resh[:, :, :, :, 0].ndim)
    even = jnp.where(mask, resh[:, :, :, :, 0], resh[:, :, :, :, 1])
    odd = jnp.where(mask, resh[:, :, :, :, 1], resh[:, :, :, :, 0])
    return even, odd


def even_odd_join(even: jax.Array, odd: jax.Array, geom: LatticeGeometry):
    """Inverse of even_odd_split."""
    T, Z, Y, X = geom.lattice_shape
    t, z, y = _tzy_grids(geom, even.dtype)
    idx = jnp.broadcast_to(((t + z + y) % 2).astype(jnp.int32)[..., None],
                           (T, Z, Y, X // 2))
    mask = _expand(idx == 0, even.ndim)
    slot0 = jnp.where(mask, even, odd)   # physical x even slot content
    slot1 = jnp.where(mask, odd, even)
    full = jnp.stack([slot0, slot1], axis=4)
    return full.reshape((T, Z, Y, X) + even.shape[4:])


def _tzy_grids(geom: LatticeGeometry, dtype):
    T, Z, Y, _ = geom.lattice_shape
    t = jnp.arange(T)[:, None, None]
    z = jnp.arange(Z)[None, :, None]
    y = jnp.arange(Y)[None, None, :]
    return t, z, y


def _expand(mask, ndim):
    while mask.ndim < ndim:
        mask = mask[..., None]
    return mask


def _n_internal(arr, geom):
    # internal axes = everything after the 4 lattice axes
    return arr.ndim - 4
