"""GaugeField: SU(3) link fields as sharded jax.Arrays.

TPU-native re-design of QUDA's GaugeField (reference: include/gauge_field.h:151,
lib/gauge_field.cpp).  Canonical layout is ``(4, T, Z, Y, X, 3, 3)`` complex
with the direction axis leading (mu = 0,1,2,3 = x,y,z,t).  QUDA's
reconstruct-12/8 compression (include/gauge_field_order.h) is deliberately
NOT the default on TPU: the stencils are HBM-bandwidth bound, but XLA prefers
dense tiles and recomputing the third row costs transcendental-free FLOPs we
can spend — a reconstruct-12 storage codec lives in ops/reconstruct.py for
the memory-limited cases instead of being wired through every accessor.

Halos: there is no ghost-buffer machinery here (lattice_field.h:250-440).
Sharded shifts go through parallel/halo.py (collective_permute under
shard_map) or plain jnp.roll on a single device — XLA owns the exchange.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from ..ops import su3
from .geometry import FULL, LatticeGeometry


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class GaugeField:
    data: jax.Array  # (4, T, Z, Y, X, 3, 3)
    geom: LatticeGeometry = dataclasses.field(metadata=dict(static=True))
    ncolor: int = dataclasses.field(default=3, metadata=dict(static=True))

    @classmethod
    def unit(cls, geom: LatticeGeometry, dtype=jnp.complex128):
        data = su3.unit_gauge((4,) + geom.lattice_shape, dtype)
        return cls(data, geom)

    @classmethod
    def random(cls, key, geom: LatticeGeometry, dtype=jnp.complex128,
               scale: float = 0.7):
        """Random SU(3) configuration (tests/utils/host_utils.cpp:1022 analog)."""
        data = su3.random_su3(key, (4,) + geom.lattice_shape, dtype, scale)
        return cls(data, geom)

    @property
    def dtype(self):
        return self.data.dtype

    def mu(self, mu: int) -> jax.Array:
        """Links in direction mu: (T,Z,Y,X,3,3)."""
        return self.data[mu]

    def like(self, data: jax.Array) -> "GaugeField":
        return GaugeField(data, self.geom, self.ncolor)

    def astype(self, dtype) -> "GaugeField":
        return self.like(self.data.astype(dtype))
