"""Link smearing: APE, STOUT, over-improved STOUT, HYP, Wilson/Symanzik flow.

Reference behavior: lib/gauge_ape.cu, lib/gauge_stout.cu (+OvrImp variant),
lib/gauge_hyp.cu, lib/gauge_wilson_flow.cu (Luscher RK3 integrator),
dispatched by performGaugeSmearQuda / performWFlowQuda
(lib/interface_quda.cpp:1677-1693).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from ..ops.shift import shift
from ..ops.su3 import dagger, expm_su3, mat_mul, project_su3, trace
from .action import gauge_force, traceless_hermitian, wilson_action


def staple(gauge, mu: int, nu: int) -> jnp.ndarray:
    """Upper + lower staple of U_mu in the (mu,nu) plane."""
    u_mu, u_nu = gauge[mu], gauge[nu]
    up = mat_mul(mat_mul(u_nu, shift(u_mu, nu, +1)),
                 dagger(shift(u_nu, mu, +1)))
    u_nu_dn = shift(u_nu, nu, -1)
    dn = mat_mul(dagger(u_nu_dn),
                 mat_mul(shift(u_mu, nu, -1), shift(u_nu_dn, mu, +1)))
    return up + dn


def staple_sum(gauge, mu: int, dirs=None) -> jnp.ndarray:
    dirs = [nu for nu in range(4) if nu != mu] if dirs is None else dirs
    s = None
    for nu in dirs:
        t = staple(gauge, mu, nu)
        s = t if s is None else s + t
    return s


def ape_smear(gauge: jnp.ndarray, alpha: float, spatial_only: bool = False,
              n_steps: int = 1) -> jnp.ndarray:
    """U' = proj_SU3((1-alpha) U + alpha/(2(d-1)) * staples)."""
    dirs_all = range(3) if spatial_only else range(4)
    for _ in range(n_steps):
        new = []
        for mu in range(4):
            if spatial_only and mu == 3:
                new.append(gauge[mu])
                continue
            dirs = [nu for nu in dirs_all if nu != mu]
            s = staple_sum(gauge, mu, dirs)
            mixed = (1.0 - alpha) * gauge[mu] + (alpha / (2 * len(dirs))) * s
            new.append(project_su3(mixed, iters=4))
        gauge = jnp.stack(new)
    return gauge


def _stout_q(gauge, mu, rho_staple) -> jnp.ndarray:
    """Hermitian traceless stout generator Q_mu(x)."""
    omega = mat_mul(rho_staple, dagger(gauge[mu]))
    return traceless_hermitian(0.5j * (dagger(omega) - omega))


def stout_smear(gauge: jnp.ndarray, rho: float, n_steps: int = 1,
                epsilon: float = 0.0) -> jnp.ndarray:
    """STOUT: U' = exp(i Q) U, Q from rho * staples (Morningstar-Peardon).

    epsilon != 0 gives over-improved stout (lib/gauge_stout.cu OvrImp
    variant): the staple mixes plaquette and rectangle terms weighted by
    (5 - 2*epsilon)/3 and -(1 - epsilon)/12.
    """
    from .action import rectangle_field  # noqa: F401 (rect staples below)
    for _ in range(n_steps):
        new = []
        for mu in range(4):
            if epsilon == 0.0:
                c = rho * staple_sum(gauge, mu)
            else:
                c = rho * ((5.0 - 2.0 * epsilon) / 3.0 * staple_sum(gauge, mu)
                           - (1.0 - epsilon) / 12.0
                           * _rect_staple_sum(gauge, mu))
            q = _stout_q(gauge, mu, c)
            new.append(mat_mul(expm_su3(q), gauge[mu]))
        gauge = jnp.stack(new)
    return gauge


def _rect_staple_sum(gauge, mu):
    """Sum of the 2x1 rectangle staples of U_mu (for over-improvement)."""
    s = None
    u_mu = gauge[mu]
    for nu in range(4):
        if nu == mu:
            continue
        u_nu = gauge[nu]
        # 2-away in nu (1x2 loops, both orientations), and 2-long in mu
        two_nu = mat_mul(u_nu, shift(u_nu, nu, +1))
        up = mat_mul(mat_mul(two_nu, shift(u_mu, nu, 2)),
                     dagger(shift(two_nu, mu, +1)))
        two_nu_dn = shift(two_nu, nu, -2)
        dn = mat_mul(dagger(two_nu_dn),
                     mat_mul(shift(u_mu, nu, -2), shift(two_nu_dn, mu, +1)))
        # 2-long in mu: U_nu staple around the doubled link, folded back
        u2 = mat_mul(u_mu, shift(u_mu, mu, +1))
        up2 = mat_mul(mat_mul(u_nu, shift(u2, nu, +1)),
                      dagger(shift(u_nu, mu, 2)))
        up2 = mat_mul(up2, dagger(shift(u_mu, mu, +1)))
        u_nu_dn = shift(u_nu, nu, -1)
        dn2 = mat_mul(dagger(u_nu_dn), mat_mul(shift(u2, nu, -1),
                                               shift(u_nu_dn, mu, 2)))
        dn2 = mat_mul(dn2, dagger(shift(u_mu, mu, +1)))
        t = up + dn + up2 + dn2
        s = t if s is None else s + t
    return s


def hyp_smear(gauge: jnp.ndarray, alpha1: float = 0.75, alpha2: float = 0.6,
              alpha3: float = 0.3, n_steps: int = 1) -> jnp.ndarray:
    """HYP smearing (Hasenfratz-Knechtli): three nested levels of
    SU(3)-projected decorated staples confined to the hypercube
    (lib/gauge_hyp.cu)."""
    for _ in range(n_steps):
        # level 1: Vbar_{mu;nu rho} — staples only in the single direction
        # eta not in {mu, nu, rho}
        vbar = {}
        for mu in range(4):
            for nu in range(4):
                for rho in range(4):
                    if len({mu, nu, rho}) != 3:
                        continue
                    (eta,) = [e for e in range(4) if e not in (mu, nu, rho)]
                    s = _staple_of(gauge[mu], gauge[eta], mu, eta)
                    mixed = (1 - alpha3) * gauge[mu] + (alpha3 / 2) * s
                    vbar[(mu, nu, rho)] = project_su3(mixed, iters=4)
        # level 2: Vtilde_{mu;nu} — staples of Vbar in rho not in {mu,nu}
        vtil = {}
        for mu in range(4):
            for nu in range(4):
                if nu == mu:
                    continue
                s = None
                for rho in range(4):
                    if rho in (mu, nu):
                        continue
                    t = _staple_of(vbar[(mu, rho, nu)],
                                   vbar[(rho, mu, nu)], mu, rho)
                    s = t if s is None else s + t
                mixed = (1 - alpha2) * gauge[mu] + (alpha2 / 4) * s
                vtil[(mu, nu)] = project_su3(mixed, iters=4)
        # level 3: full decorated staples
        new = []
        for mu in range(4):
            s = None
            for nu in range(4):
                if nu == mu:
                    continue
                t = _staple_of(vtil[(mu, nu)], vtil[(nu, mu)], mu, nu)
                s = t if s is None else s + t
            mixed = (1 - alpha1) * gauge[mu] + (alpha1 / 6) * s
            new.append(project_su3(mixed, iters=4))
        gauge = jnp.stack(new)
    return gauge


def _staple_of(u_mu, u_nu, mu: int, nu: int):
    """Staples of the field u_mu using u_nu as the orthogonal links."""
    up = mat_mul(mat_mul(u_nu, shift(u_mu, nu, +1)),
                 dagger(shift(u_nu, mu, +1)))
    u_nu_dn = shift(u_nu, nu, -1)
    dn = mat_mul(dagger(u_nu_dn),
                 mat_mul(shift(u_mu, nu, -1), shift(u_nu_dn, mu, +1)))
    return up + dn


# -- gradient flow ---------------------------------------------------------

def _flow_z(gauge, action_fn) -> jnp.ndarray:
    """Hermitian flow generator Z with Vdot = i Z V = -grad S flow."""
    return -2.0 * gauge_force(action_fn, gauge)


def wilson_flow_step(gauge: jnp.ndarray, eps: float,
                     action_fn: Callable = None) -> jnp.ndarray:
    """One Luscher RK3 (2N0901-style W0/W1/W2) gradient-flow step
    (lib/gauge_wilson_flow.cu QUDA_GAUGE_SMEAR_WILSON_FLOW)."""
    act = action_fn or (lambda u: wilson_action(u, 6.0))
    z0 = eps * _flow_z(gauge, act)
    w1 = mat_mul(expm_su3(0.25 * z0), gauge)
    z1 = eps * _flow_z(w1, act)
    w2 = mat_mul(expm_su3((8.0 / 9.0) * z1 - (17.0 / 36.0) * z0), w1)
    z2 = eps * _flow_z(w2, act)
    return mat_mul(expm_su3(0.75 * z2 - (8.0 / 9.0) * z1
                            + (17.0 / 36.0) * z0), w2)


def symanzik_flow_step(gauge: jnp.ndarray, eps: float) -> jnp.ndarray:
    from .action import improved_action
    return wilson_flow_step(gauge, eps,
                            lambda u: improved_action(u, 6.0, -1.0 / 12.0))


def wilson_flow(gauge: jnp.ndarray, eps: float, n_steps: int,
                measure: Callable = None):
    """Integrate the flow; optionally record measure(gauge, t) each step
    (performWFlowQuda's per-step observable printing)."""
    history = []
    for i in range(n_steps):
        gauge = wilson_flow_step(gauge, eps)
        if measure is not None:
            history.append(measure(gauge, (i + 1) * eps))
    return gauge, history


def fermion_flow_step(gauge: jnp.ndarray, phi: jnp.ndarray, eps: float,
                      action_fn: Callable = None):
    """One Luscher RK3 step of the JOINT gauge + fermion gradient flow
    (performGFlowQuda, quda.h:1695): the fermion field flows with the
    4-d covariant Laplacian of the flowing gauge field,

        d phi / dt = Delta(V(t)) phi,

    integrated with the third-order scheme matched to the gauge RK3
    stages (Luscher, arXiv:1302.5246 appendix; QUDA's gflow kernels):
        phi1 = phi0 + (eps/4) D0 phi0
        phi2 = phi0 + (8 eps/9) D1 phi1 - (2 eps/9) D0 phi0
        phi3 = phi1 + (3 eps/4) D2 phi2
    with D_i the Laplacian on the i-th gauge flow stage W_i.

    Returns (flowed gauge, flowed fermion).
    """
    from ..ops.laplace import laplace

    act = action_fn or (lambda u: wilson_action(u, 6.0))

    def lap(w, p):
        return -laplace(w, p, ndim=4, mass=0.0)  # laplace returns -Delta

    w0 = gauge
    d0 = lap(w0, phi)
    phi1 = phi + (eps / 4.0) * d0
    z0 = eps * _flow_z(w0, act)
    w1 = mat_mul(expm_su3(0.25 * z0), w0)

    d1 = lap(w1, phi1)
    phi2 = phi + (8.0 * eps / 9.0) * d1 - (2.0 * eps / 9.0) * d0
    z1 = eps * _flow_z(w1, act)
    w2 = mat_mul(expm_su3((8.0 / 9.0) * z1 - (17.0 / 36.0) * z0), w1)

    d2 = lap(w2, phi2)
    phi3 = phi1 + (3.0 * eps / 4.0) * d2
    z2 = eps * _flow_z(w2, act)
    w3 = mat_mul(expm_su3(0.75 * z2 - (8.0 / 9.0) * z1
                          + (17.0 / 36.0) * z0), w2)
    return w3, phi3


def fermion_flow(gauge: jnp.ndarray, phi: jnp.ndarray, eps: float,
                 n_steps: int):
    """Integrate the joint gauge+fermion flow n_steps (performGFlowQuda)."""
    for _ in range(n_steps):
        gauge, phi = fermion_flow_step(gauge, phi, eps)
    return gauge, phi
