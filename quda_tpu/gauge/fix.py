"""Gauge fixing: Landau/Coulomb by overrelaxation and by Fourier
acceleration.

Reference behavior: lib/gauge_fix_ovr.cu (512 LoC, checkerboarded SU(2)-
subgroup relaxation with halo exchange), lib/gauge_fix_fft.cu (396,
Fourier-accelerated steepest descent), exposed as
computeGaugeFixingOVRQuda / computeGaugeFixingFFTQuda (quda.h:1750,1767).

The OVR update maximises F[g] = sum_mu Re tr[g(x) w(x)],
w(x) = sum_mu (U_mu(x) + U_mu(x-mu)^dag), over one checkerboard parity at
a time via the three SU(2) subgroups; overrelaxation raises the subgroup
rotation to the power omega in quaternion form (angle -> omega * angle) —
a closed-form replacement for QUDA's approximate (omega g + (1-omega))
renormalisation.

The FFT variant preconditions the steepest-descent step with the inverse
lattice Laplacian p^2_max / p^2 in momentum space (jnp.fft over the
lattice axes, batched over color components).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry
from ..ops.shift import shift
from ..ops.su3 import dagger, expm_su3, mat_mul, trace
from .heatbath import SUBGROUPS, _embed_su2, _site_mask, _subgroup_quaternion


def _dirs(gauge_dirs: int):
    return range(gauge_dirs)  # 4 = Landau, 3 = Coulomb


def gaugefix_quality(gauge: jnp.ndarray, gauge_dirs: int = 4):
    """(functional, theta): theta = sum |div A|^2 / (N V) as in QUDA's
    GaugeFixQuality (kernels/gauge_fix_quality.cuh)."""
    vol = int(np.prod(gauge.shape[1:5]))
    f = 0.0
    for mu in _dirs(gauge_dirs):
        f = f + jnp.sum(trace(gauge[mu]).real)
    f = f / (vol * 3 * gauge_dirs)
    div = _div_a(gauge, gauge_dirs)
    theta = jnp.sum(trace(mat_mul(div, dagger(div))).real) / (3 * vol)
    return f, theta


def _ta(m):
    a = 0.5 * (m - dagger(m))
    tr = trace(a) / 3.0
    return a - tr[..., None, None] * jnp.eye(3, dtype=m.dtype)


def _div_a(gauge, gauge_dirs):
    """div A(x) = sum_mu [A_mu(x) - A_mu(x - mu)], A = TA(U)/i."""
    d = None
    for mu in _dirs(gauge_dirs):
        a = _ta(gauge[mu])
        t = a - shift(a, mu, -1)
        d = t if d is None else d + t
    return d


def _apply_transform(gauge, g):
    """U_mu(x) <- g(x) U_mu(x) g(x+mu)^dag."""
    return jnp.stack([
        mat_mul(mat_mul(g, gauge[mu]), dagger(shift(g, mu, +1)))
        for mu in range(4)])


def gaugefix_ovr(gauge: jnp.ndarray, geom: LatticeGeometry,
                 gauge_dirs: int = 4, omega: float = 1.7,
                 tol: float = 1e-10, max_iter: int = 1000,
                 check_interval: int = 10):
    """Overrelaxed gauge fixing; returns (fixed gauge, iterations, theta)."""
    masks = [jnp.asarray(_site_mask(geom, p))[..., None, None]
             for p in (0, 1)]

    @jax.jit
    def one_iter(gauge):
        for parity in (0, 1):
            w = None
            for mu in _dirs(gauge_dirs):
                t = gauge[mu] + dagger(shift(gauge[mu], mu, -1))
                w = t if w is None else w + t
            g_tot = None
            for i, j in SUBGROUPS:
                b0, b1, b2, b3 = _subgroup_quaternion(w, i, j)
                k = jnp.sqrt(b0 ** 2 + b1 ** 2 + b2 ** 2 + b3 ** 2) + 1e-30
                a0, a1, a2, a3 = b0 / k, b1 / k, b2 / k, b3 / k
                # overrelax: rotate by omega * angle in quaternion form
                ang = jnp.arccos(jnp.clip(a0, -1.0, 1.0))
                s = jnp.sin(ang) + 1e-30
                new_ang = omega * ang
                scale = jnp.sin(new_ang) / s
                a0w = jnp.cos(new_ang)
                g = _embed_su2(a0w, a1 * scale, a2 * scale, a3 * scale,
                               i, j, gauge.dtype, w.shape[:-2])
                g = jnp.where(masks[parity], g,
                              jnp.eye(3, dtype=gauge.dtype))
                gauge = _apply_transform(gauge, g)
                w = jnp.where(masks[parity], mat_mul(g, w), w)
        return gauge

    theta = jnp.inf
    it = 0
    while it < max_iter:
        for _ in range(check_interval):
            gauge = one_iter(gauge)
        it += check_interval
        _, theta = gaugefix_quality(gauge, gauge_dirs)
        if float(theta) < tol:
            break
    return gauge, it, float(theta)


def _p2_inv(lat_shape, dtype):
    """p^2_max / p^2 Fourier weights (zero mode weight 0)."""
    ks = [2.0 * np.pi * np.fft.fftfreq(n) for n in lat_shape]
    grids = np.meshgrid(*ks, indexing="ij")
    p2 = sum(4.0 * np.sin(g / 2.0) ** 2 for g in grids)
    p2max = p2.max()
    w = np.where(p2 > 1e-14, p2max / np.maximum(p2, 1e-14), 0.0)
    return jnp.asarray(w, dtype)


def gaugefix_fft(gauge: jnp.ndarray, geom: LatticeGeometry,
                 gauge_dirs: int = 4, alpha: float = 0.08,
                 tol: float = 1e-10, max_iter: int = 2000,
                 check_interval: int = 10):
    """Fourier-accelerated steepest descent: g = exp(alpha F^-1 [w F[div A]])."""
    lat = gauge.shape[1:5]
    w = _p2_inv(lat, gauge.real.dtype)

    @jax.jit
    def one_iter(gauge):
        d = _div_a(gauge, gauge_dirs)           # anti-Hermitian traceless
        # XLA caps FFTs at 3 dimensions, so the 4d lattice transform is
        # factored into a 3d pass + a 1d pass (the DFT is separable —
        # bit-wise this is the same linear map fftn over all four axes
        # computes)
        dk = jnp.fft.fftn(d, axes=(1, 2, 3))
        dk = jnp.fft.fft(dk, axis=0)
        dk = dk * w[..., None, None].astype(dk.dtype)
        d_acc = jnp.fft.ifft(dk, axis=0)
        d_acc = jnp.fft.ifftn(d_acc, axes=(1, 2, 3))
        # g = exp(-alpha * d_acc): d_acc anti-Hermitian -> exp(i * (i d)) ...
        h = -1j * d_acc  # Hermitian generator
        g = expm_su3(-alpha * h, order=8)
        return _apply_transform(gauge, g)

    theta = jnp.inf
    it = 0
    while it < max_iter:
        for _ in range(check_interval):
            gauge = one_iter(gauge)
        it += check_interval
        _, theta = gaugefix_quality(gauge, gauge_dirs)
        if float(theta) < tol:
            break
    return gauge, it, float(theta)
