"""HISQ / asqtad link fattening: fat7 + reunitarisation + asqtad staples,
Naik and Lepage terms, two-link field for staggered smearing.

Reference behavior: lib/llfat_quda.cu (fat7/asqtad staples),
lib/unitarize_links_quda.cu + include/svd_quda.h (U(3) projection),
lib/staggered_two_link_quda.cu, driven by computeKSLinkQuda
(quda.h:1358, lib/interface_quda.cpp).  Path coefficients follow the MILC
convention: (one-link, naik, 3-staple, 5-staple, 7-staple, lepage).

TPU-native notes:
* staples at every level are the same nested `_staple_of` einsum pattern —
  the 5-link and 7-link paths are staples of staples, the Lepage term a
  same-direction double staple;
* reunitarisation is W = V (V^dag V)^{-1/2} via a batched Hermitian
  eigendecomposition — and because `jnp.linalg.eigh` has a JVP rule, the
  HISQ FORCE differentiates straight through it (jax.grad replaces the
  hand-derived SVD differentiation of unitarize_force.cuh / svd_quda.h).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.shift import shift
from ..ops.su3 import dagger, inv_sqrt_herm3_pairs, is_pairs, mat_mul


class HisqCoeffs(NamedTuple):
    one_link: float
    naik: float
    three: float
    five: float
    seven: float
    lepage: float


# MILC fat7 (first HISQ level) and asqtad (second level) coefficient sets
FAT7_COEFFS = HisqCoeffs(1.0 / 8.0, 0.0, 1.0 / 16.0, 1.0 / 64.0,
                         1.0 / 384.0, 0.0)
ASQTAD_COEFFS = HisqCoeffs(1.0 / 8.0 + 3.0 / 8.0 + 1.0 / 8.0, -1.0 / 24.0,
                           1.0 / 16.0, 1.0 / 64.0, 1.0 / 384.0, -1.0 / 8.0)
# second HISQ level includes the Naik correction via eps externally
HISQ_L2_COEFFS = HisqCoeffs(1.0, -1.0 / 24.0, 1.0 / 16.0, 1.0 / 64.0,
                            1.0 / 384.0, -1.0 / 8.0)


def _staple_pair(x_mu: jnp.ndarray, u_nu: jnp.ndarray, mu: int, nu: int):
    """Up+down staple of the link field x_mu decorated by u_nu."""
    up = mat_mul(mat_mul(u_nu, shift(x_mu, nu, +1)),
                 dagger(shift(u_nu, mu, +1)))
    u_dn = shift(u_nu, nu, -1)
    dn = mat_mul(dagger(u_dn), mat_mul(shift(x_mu, nu, -1),
                                       shift(u_dn, mu, +1)))
    return up + dn


@functools.partial(jax.checkpoint, static_argnums=(1,))
def fat_links(gauge: jnp.ndarray, c: HisqCoeffs) -> jnp.ndarray:
    """Generalised fattening for one coefficient set.

    ``jax.checkpoint``: the nested staple sums hold O(100) link-sized
    intermediates alive under AD (the HISQ force differentiates through
    two fattening levels); at 16^4 that peak drove XLA:TPU into its
    compression-remat pass, whose bf16 copies of (…,3,3,2) temps pick a
    (4,128)-tiled layout with 56.9x padding — OOM (measured 2026-07-31).
    Checkpointing stores only (gauge, output) and recomputes staples in
    the backward pass: the same FLOPs-for-HBM trade the reference makes
    by re-deriving staples in hisq_force_quda.cu rather than caching
    every level.

    3-staple: sum_nu staple_nu(U_mu);
    5-staple: sum_{nu != rho} staple_nu(staple_rho(U_mu));
    7-staple: the fully nested three-direction version;
    Lepage:   staple_nu(staple_nu(U_mu)) (same direction twice).
    """
    fat = []
    for mu in range(4):
        acc = c.one_link * gauge[mu]
        for nu in range(4):
            if nu == mu:
                continue
            s3 = _staple_pair(gauge[mu], gauge[nu], mu, nu)
            acc = acc + c.three * s3
            if c.lepage != 0.0:
                acc = acc + c.lepage * _staple_pair(s3, gauge[nu], mu, nu) \
                    * 0.5  # both orientations already in s3; halve double count
            for rho in range(4):
                if rho in (mu, nu):
                    continue
                s5 = _staple_pair(_staple_pair(gauge[mu], gauge[rho],
                                               mu, rho), gauge[nu], mu, nu)
                acc = acc + c.five * s5 * 0.5
                for sg in range(4):
                    if sg in (mu, nu, rho):
                        continue
                    s7 = _staple_pair(
                        _staple_pair(
                            _staple_pair(gauge[mu], gauge[sg], mu, sg),
                            gauge[rho], mu, rho), gauge[nu], mu, nu)
                    acc = acc + c.seven * s7 / 6.0
        fat.append(acc)
    return jnp.stack(fat)


@jax.checkpoint
def naik_links(gauge: jnp.ndarray) -> jnp.ndarray:
    """Straight 3-link (Naik) field: U_mu(x) U_mu(x+mu) U_mu(x+2mu)."""
    out = []
    for mu in range(4):
        u = gauge[mu]
        out.append(mat_mul(mat_mul(u, shift(u, mu, +1)), shift(u, mu, 2)))
    return jnp.stack(out)


def two_link(gauge: jnp.ndarray) -> jnp.ndarray:
    """U_mu(x) U_mu(x+mu) (lib/staggered_two_link_quda.cu, for two-link
    Gaussian quark smearing)."""
    return jnp.stack([mat_mul(gauge[mu], shift(gauge[mu], mu, +1))
                      for mu in range(4)])


@jax.checkpoint
def unitarize_links(v: jnp.ndarray) -> jnp.ndarray:
    """U(3) projection W = V (V^dag V)^{-1/2} via batched eigh.

    Differentiable (eigh JVP) — the HISQ-force path relies on this.
    """
    h = mat_mul(dagger(v), v)                      # Hermitian pos. def.
    if is_pairs(v):
        # complex-free AND differentiable: Cayley-Hamilton + Cardano on
        # the real invariants (the reference's own unitarize recipe).  An
        # eigh of the interleaved 6x6 embedding also computes the value,
        # but its exactly-doubled spectrum makes the eigh JVP 0/0 — the
        # HISQ force would be NaN.
        return mat_mul(v, inv_sqrt_herm3_pairs(h))
    evals, evecs = jnp.linalg.eigh(h)
    inv_sqrt = jnp.einsum(
        "...ab,...b,...cb->...ac", evecs,
        1.0 / jnp.sqrt(jnp.maximum(evals, 1e-18)), jnp.conjugate(evecs))
    return mat_mul(v, inv_sqrt)


class HisqLinks(NamedTuple):
    fat: jnp.ndarray
    long: jnp.ndarray
    w_unitarized: jnp.ndarray


def hisq_fattening(gauge: jnp.ndarray,
                   naik_eps: float = 0.0) -> HisqLinks:
    """Full two-level HISQ construction (computeKSLinkQuda pipeline):
    fat7 -> U(3) reunitarise -> asqtad level-2 (+ Lepage), Naik from W."""
    v = fat_links(gauge, FAT7_COEFFS)
    w = unitarize_links(v)
    fat = fat_links(w, HISQ_L2_COEFFS)
    lng = (1.0 + naik_eps) * (-1.0 / 24.0) * naik_links(w)
    return HisqLinks(fat, lng, w)
