"""Pure-gauge Monte Carlo: SU(3) heatbath + overrelaxation, hot/cold starts.

Reference behavior: lib/pgauge_heatbath.cu (kernels/gauge_heatbath.cuh, 666
LoC), lib/pgauge_init.cu.  Cabibbo-Marinari pseudo-heatbath over the three
SU(2) subgroups with Kennedy-Pendleton sampling, plus microcanonical
overrelaxation, updating one (parity, direction) checkerboard at a time
(staples never touch links being updated).

JAX-native rejection sampling: each site draws a fixed budget of K
candidate (delta, accept) pairs at once and selects the first accepted via
a masked argmax — no data-dependent loops.  At physical couplings
(alpha = beta*k/3 >~ 1) the per-try acceptance is high and K=24 makes the
miss probability negligible; misses keep the old link (exact for K -> inf).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry
from ..ops.su3 import dagger, mat_mul, random_su3, trace, unit_gauge
from .smear import staple_sum

# SU(2) subgroup index pairs within SU(3)
SUBGROUPS = ((0, 1), (0, 2), (1, 2))


def hot_start(key, geom: LatticeGeometry, dtype=jnp.complex128):
    return random_su3(key, (4,) + geom.lattice_shape, dtype, scale=1.0)


def cold_start(geom: LatticeGeometry, dtype=jnp.complex128):
    return unit_gauge((4,) + geom.lattice_shape, dtype)


def _subgroup_quaternion(w, i, j):
    """b-vector of Re tr(g W) = a . b over the (i,j) SU(2) subgroup:
    b0 = Re(Wii + Wjj), b1 = -Im(Wij + Wji), b2 = -Re(Wij - Wji),
    b3 = -Im(Wii - Wjj)."""
    wii, wjj = w[..., i, i], w[..., j, j]
    wij, wji = w[..., i, j], w[..., j, i]
    b0 = (wii + wjj).real
    b1 = -(wij + wji).imag
    b2 = -(wij - wji).real
    b3 = -(wii - wjj).imag
    return b0, b1, b2, b3


def _embed_su2(a0, a1, a2, a3, i, j, dtype, lat_shape):
    """Embed quaternion a into SU(3) as an (i,j)-subgroup rotation."""
    g = jnp.zeros(lat_shape + (3, 3), dtype)
    for k in range(3):
        g = g.at[..., k, k].set(1.0)
    g = g.at[..., i, i].set(a0 + 1j * a3)
    g = g.at[..., i, j].set(a2 + 1j * a1)
    g = g.at[..., j, i].set(-a2 + 1j * a1)
    g = g.at[..., j, j].set(a0 - 1j * a3)
    return g


def _kp_sample(key, alpha, n_tries: int = 24):
    """Kennedy-Pendleton: x0 in [-1,1] with P ~ sqrt(1-x0^2) e^{alpha x0}.

    Returns (x0, ok) — ok=False where all tries rejected.
    """
    shape = alpha.shape
    k1, k2, k3, k4 = jax.random.split(key, 4)
    eps = 1e-12
    r1 = jax.random.uniform(k1, (n_tries,) + shape, minval=eps, maxval=1.0)
    r2 = jax.random.uniform(k2, (n_tries,) + shape)
    r3 = jax.random.uniform(k3, (n_tries,) + shape, minval=eps, maxval=1.0)
    r4 = jax.random.uniform(k4, (n_tries,) + shape)
    a = jnp.maximum(alpha, 1e-10)
    delta = -(jnp.log(r1) + jnp.cos(2 * jnp.pi * r2) ** 2 * jnp.log(r3)) / a
    accept = (r4 ** 2) <= jnp.maximum(1.0 - 0.5 * delta, 0.0)
    # first accepted try per site
    idx = jnp.argmax(accept, axis=0)
    any_ok = jnp.any(accept, axis=0)
    d = jnp.take_along_axis(delta, idx[None], axis=0)[0]
    return 1.0 - d, any_ok


def _site_mask(geom: LatticeGeometry, parity: int):
    T, Z, Y, X = geom.lattice_shape
    t = np.arange(T)[:, None, None, None]
    z = np.arange(Z)[None, :, None, None]
    y = np.arange(Y)[None, None, :, None]
    x = np.arange(X)[None, None, None, :]
    return ((x + y + z + t) % 2 == parity)


def _subgroup_update(key, u_mu, a_staple, beta, sg, heatbath: bool,
                     n_tries: int):
    """One SU(2)-subgroup update of all sites of u_mu (masked outside)."""
    i, j = sg
    w = mat_mul(u_mu, dagger(a_staple))
    b0, b1, b2, b3 = _subgroup_quaternion(w, i, j)
    k = jnp.sqrt(b0 ** 2 + b1 ** 2 + b2 ** 2 + b3 ** 2) + 1e-30
    bh = [b0 / k, b1 / k, b2 / k, b3 / k]
    if heatbath:
        alpha = (beta / 3.0) * k
        kx, kd = jax.random.split(key)
        x0, ok = _kp_sample(kx, alpha, n_tries)
        # uniform direction on the 2-sphere for the perpendicular part
        kn1, kn2 = jax.random.split(kd)
        ct = jax.random.uniform(kn1, k.shape, minval=-1.0, maxval=1.0)
        ph = jax.random.uniform(kn2, k.shape, minval=0.0,
                                maxval=2 * jnp.pi)
        st = jnp.sqrt(jnp.maximum(1.0 - ct ** 2, 0.0))
        n = [ct, st * jnp.cos(ph), st * jnp.sin(ph)]
        xr = jnp.sqrt(jnp.maximum(1.0 - x0 ** 2, 0.0))
        # a = (x0, xr*n) quaternion-multiplied by bhat: right translation on
        # S^3 is an isometry sending e0 -> bhat, so a.bhat = x0 (KP-sampled)
        # with the perpendicular direction uniform.  Quaternion product:
        # (p0,p)(q0,q) = (p0 q0 - p.q, p0 q + q0 p + p x q)
        p0, p1, p2, p3 = x0, xr * n[0], xr * n[1], xr * n[2]
        q0, q1, q2, q3 = bh
        a0 = p0 * q0 - p1 * q1 - p2 * q2 - p3 * q3
        a1 = p0 * q1 + q0 * p1 + p2 * q3 - p3 * q2
        a2 = p0 * q2 + q0 * p2 + p3 * q1 - p1 * q3
        a3 = p0 * q3 + q0 * p3 + p1 * q2 - p2 * q1
        # where rejection failed, keep identity (old link)
        a0 = jnp.where(ok, a0, 1.0)
        a1 = jnp.where(ok, a1, 0.0)
        a2 = jnp.where(ok, a2, 0.0)
        a3 = jnp.where(ok, a3, 0.0)
    else:
        # microcanonical overrelaxation: a = bhat * bhat (quaternion square)
        q0, q1, q2, q3 = bh
        a0 = q0 * q0 - q1 * q1 - q2 * q2 - q3 * q3
        a1, a2, a3 = 2 * q0 * q1, 2 * q0 * q2, 2 * q0 * q3
    g = _embed_su2(a0.astype(u_mu.real.dtype), a1, a2, a3, i, j,
                   u_mu.dtype, u_mu.shape[:-2])
    return mat_mul(g, u_mu)


def sweep(key, gauge: jnp.ndarray, geom: LatticeGeometry, beta: float,
          heatbath: bool = True, n_tries: int = 24) -> jnp.ndarray:
    """One full lattice sweep: 2 parities x 4 directions x 3 subgroups."""
    for parity in (0, 1):
        mask = jnp.asarray(_site_mask(geom, parity))[..., None, None]
        for mu in range(4):
            a = staple_sum(gauge, mu)
            u = gauge[mu]
            for si, sg in enumerate(SUBGROUPS):
                key, sub = jax.random.split(key)
                u_new = _subgroup_update(sub, u, a, beta, sg, heatbath,
                                         n_tries)
                u = jnp.where(mask, u_new, u)
            gauge = gauge.at[mu].set(u)
    return gauge


def heatbath_evolve(key, gauge, geom, beta: float, n_sweeps: int,
                    n_or_per_hb: int = 0):
    """Heatbath sweeps, optionally interleaved with OR sweeps
    (the heatbath_test evolution pattern)."""
    for _ in range(n_sweeps):
        key, k1 = jax.random.split(key)
        gauge = sweep(k1, gauge, geom, beta, heatbath=True)
        for _ in range(n_or_per_hb):
            key, k2 = jax.random.split(key)
            gauge = sweep(k2, gauge, geom, beta, heatbath=False)
    return gauge
