"""Gauge observables: plaquette, Polyakov loop, topological charge, energy.

Reference behavior: lib/gauge_plaq.cu, lib/gauge_polyakov_loop.cu,
lib/gauge_qcharge.cu, lib/gauge_observable.cpp (gaugeObservablesQuda).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..ops.fmunu import PLANES, field_strength
from ..ops.shift import shift
from ..ops.su3 import dagger, is_pairs, mat_mul, re_trace, trace


def plaquette_field(gauge: jnp.ndarray, mu: int, nu: int) -> jnp.ndarray:
    """P_{mu nu}(x) = U_mu(x) U_nu(x+mu) U_mu(x+nu)^dag U_nu(x)^dag."""
    u_mu, u_nu = gauge[mu], gauge[nu]
    return mat_mul(mat_mul(u_mu, shift(u_nu, mu, +1)),
                   dagger(mat_mul(u_nu, shift(u_mu, nu, +1))))


def plaquette(gauge: jnp.ndarray):
    """(mean, spatial, temporal) normalised Re tr P / 3 (plaqQuda order)."""
    sp, tm = [], []
    for mu, nu in PLANES:
        p = jnp.mean(re_trace(plaquette_field(gauge, mu, nu))) / 3.0
        (tm if nu == 3 else sp).append(p)
    s = sum(sp) / len(sp)
    t = sum(tm) / len(tm)
    return (s + t) / 2.0, s, t


def polyakov_loop(gauge: jnp.ndarray):
    """Volume-averaged trace of the temporal Wilson line
    (lib/gauge_polyakov_loop.cu).  Returns complex <tr L>/3."""
    u_t = gauge[3]                    # (T,Z,Y,X,3,3) or (...,3,3,2)
    T = u_t.shape[0]
    line = u_t[0]
    for t in range(1, T):
        line = mat_mul(line, u_t[t])
    tr = trace(line)
    if is_pairs(gauge):               # pair scalar: average the sites only
        return jnp.mean(tr, axis=tuple(range(tr.ndim - 1))) / 3.0
    return jnp.mean(tr) / 3.0


def qcharge_density(gauge: jnp.ndarray) -> jnp.ndarray:
    """Topological charge density q(x) = eps_{mu nu rho sigma}
    tr[F F] / 32 pi^2 from the clover field strength
    (kernels/gauge_qcharge.cuh)."""
    f = field_strength(gauge)   # Hermitian F_h; lattice F = i F_h
    # eps contraction over the 6 planes: (01)(23) - (02)(13) + (03)(12)
    fxy, fxz, fxt, fyz, fyt, fzt = (f[i] for i in range(6))
    dens = (re_trace(mat_mul(fxy, fzt)) - re_trace(mat_mul(fxz, fyt))
            + re_trace(mat_mul(fxt, fyz)))
    # tr(F^latt F^latt) = -tr(F_h F_h); overall factor 8 from eps pairs
    return -8.0 * dens / (32.0 * math.pi ** 2)


def qcharge(gauge: jnp.ndarray):
    return jnp.sum(qcharge_density(gauge))


def energy(gauge: jnp.ndarray):
    """(total, spatial E, temporal B-ish) field-strength energy
    E = sum tr F^2 (gauge_qcharge.cuh qcharge+energy mode)."""
    f = field_strength(gauge)
    e = [jnp.sum(re_trace(mat_mul(f[i], f[i]))) for i in range(6)]
    spatial = e[0] + e[1] + e[3]   # xy, xz, yz
    temporal = e[2] + e[4] + e[5]  # xt, yt, zt
    return spatial + temporal, spatial, temporal
