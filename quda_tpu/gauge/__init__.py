"""Gauge sector: actions/forces/HMC, smearing, flow, heatbath, fixing,
HISQ fattening, observables, quark smearing."""

from .action import (gauge_force, hmc_trajectory, improved_action,  # noqa: F401
                     leapfrog, mom_action, omf2, random_momentum,
                     update_gauge, wilson_action)
from .observables import energy, plaquette, polyakov_loop, qcharge  # noqa: F401
from .fermion_force import pseudofermion_force, rational_force  # noqa: F401
