"""Generic Wilson-line path tables: loop traces, path actions, and forces.

Reference behavior: include/gauge_path_helper.cuh:88 (computeGaugePath —
walk a direction list, forwards d<4 multiplies U_d at the current offset,
backwards d>=4 steps back then multiplies U(7-d)^dag), kernels
gauge_force.cuh:100 / gauge_loop_trace.cuh:84, drivers lib/gauge_force.cu
and lib/gauge_loop_trace.cu, API computeGaugeForceQuda /
computeGaugePathQuda / gaugeLoopTraceQuda (include/quda.h:1393-1420).

TPU-native: the per-thread walk becomes whole-lattice link products with
jnp.roll shifts (one shifted link array per step), and the FORCE comes
from jax.grad of the path action with su(3) (traceless anti-Hermitian)
projection — the hand-derived staple insertions of gauge_force.cuh are
unnecessary, while the API semantics (arbitrary user path tables, MILC /
Chroma style) are preserved.

Path encoding (QUDA/MILC): entries 0,1,2,3 step forward in x,y,z,t; the
backward step along direction mu is encoded as 7 - mu (so 7,6,5,4 =
backward x,y,z,t).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..ops.shift import shift
from ..ops.su3 import dagger, eye_like, is_pairs, mat_mul, re_trace, trace


def _shift_by(arr: jnp.ndarray, disp) -> jnp.ndarray:
    """Shift so result(x) = arr(x + disp), disp in mu units (x,y,z,t)."""
    out = arr
    for mu, n in enumerate(disp):
        if n:
            out = shift(out, mu, +1 if n > 0 else -1, nhop=abs(n))
    return out


def wilson_line(gauge: jnp.ndarray, path: Sequence[int],
                start_disp=(0, 0, 0, 0)):
    """Product of links along ``path`` starting at x + start_disp.

    Returns (W, end_disp): W(x) is the (3,3) product at every site;
    end_disp the net displacement (for closure checks).

    Built tail-first — W_k(x) = L_k(x) @ W_{k+1}(x + step_k) — so each
    step costs ONE whole-lattice shift of the partial product (O(length)
    rolls total, not O(length^2) as origin-relative shifting would).
    """
    disp = [0, 0, 0, 0]
    W = None
    for d in reversed([int(d) for d in path]):
        if d < 4:
            if W is not None:
                W = shift(W, d, +1)
            link = gauge[d]
            disp[d] += 1
        else:
            mu = 7 - d
            if W is not None:
                W = shift(W, mu, -1)
            link = dagger(shift(gauge[mu], mu, -1))
            disp[mu] -= 1
        W = link if W is None else mat_mul(link, W)
    if W is None:
        W = eye_like(gauge[0])
    if any(start_disp):
        W = _shift_by(W, start_disp)
    return W, tuple(disp)


def gauge_loop_trace(gauge: jnp.ndarray, paths: Sequence[Sequence[int]],
                     coeffs: Sequence[float]):
    """Per-path volume-summed traces c_i sum_x tr W_i(x)
    (gaugeLoopTraceQuda, lib/gauge_loop_trace.cu:74, which returns one
    complex trace per loop).  Returns a (num_paths,) complex array."""
    if len(paths) != len(coeffs):
        raise ValueError(f"{len(paths)} paths but {len(coeffs)} coeffs")
    # extents in mu order (x,y,z,t) from (4,T,Z,Y,X,3,3)
    ext = (gauge.shape[4], gauge.shape[3], gauge.shape[2], gauge.shape[1])
    out = []
    for path, c in zip(paths, coeffs):
        W, disp = wilson_line(gauge, path)
        if any(d % e for d, e in zip(disp, ext)):
            # loops may close through the torus (Polyakov lines)
            raise ValueError(f"path {path} does not close: {disp}")
        tr = trace(W)
        if is_pairs(W):          # pair scalar: sum the site axes only
            tr = jnp.sum(tr, axis=tuple(range(tr.ndim - 1)))
        else:
            tr = jnp.sum(tr)
        out.append(c * tr)
    return jnp.stack(out)


def gauge_path_action(gauge: jnp.ndarray,
                      input_path_buf: Sequence[Sequence[Sequence[int]]],
                      coeffs: Sequence[float]):
    """S = sum_mu sum_i c_i sum_x Re tr[U_mu(x) P_i^mu(x + mu)].

    ``input_path_buf[mu][i]`` is the i-th path for direction mu in the
    computeGaugeForceQuda input format (the path starts at x + mu, i.e.
    pre-shifted by the initial link, gauge_force.cuh:76 ``dx[dir]++``).
    """
    s = 0.0
    for mu in range(4):
        if len(input_path_buf[mu]) != len(coeffs):
            raise ValueError(
                f"dir {mu}: {len(input_path_buf[mu])} paths but "
                f"{len(coeffs)} coeffs")
        start = [0, 0, 0, 0]
        start[mu] = 1
        for path, c in zip(input_path_buf[mu], coeffs):
            W, _ = wilson_line(gauge, path, start)
            s = s + c * jnp.sum(re_trace(mat_mul(gauge[mu], W)))
    return s


def gauge_path_force(gauge: jnp.ndarray, input_path_buf, coeffs):
    """su(3)-projected force of the path action (the makeAntiHerm'd
    staple sum of gauge_force.cuh, via AD — see gauge/action.py force
    conventions)."""
    from .action import gauge_force
    return gauge_force(
        lambda g: gauge_path_action(g, input_path_buf, coeffs), gauge)


def plaquette_paths():
    """The 6-staple table of the Wilson action for each direction
    (the standard computeGaugeForceQuda input for beta/3 coefficients)."""
    buf = []
    for mu in range(4):
        paths_mu = []
        for nu in range(4):
            if nu == mu:
                continue
            # forward staple: nu, mu-back, nu-back
            paths_mu.append([nu, 7 - mu, 7 - nu])
            # backward staple: nu-back, mu-back, nu
            paths_mu.append([7 - nu, 7 - mu, nu])
        buf.append(paths_mu)
    return buf
