"""Pseudofermion forces — one AD rule replaces QUDA's force kernel zoo.

Reference behavior: lib/clover_force.cpp + clover_outer_product.cu (+TM
variant), computeStaggeredForceQuda + staggered_oprod.cu, and the HISQ
force chain (lib/hisq_paths_force_quda.cu, unitarize_force.cuh with its
hand-differentiated SVD) — together several thousand lines of per-action
derivative code.

TPU-native design: for S_pf = phi^dag (M^dag M)^{-1} phi, with
X = (M^dag M)^{-1} phi held fixed (computed by CG), the force is

    F = - gauge_force( U -> Re <X, M(U)^dag M(U) X> )

because d S_pf = -X^dag d(M^dag M) X.  jax.grad differentiates through the
ENTIRE operator construction — boundary phases, the clover term's
field-strength leaves, staggered phases, and (for HISQ) the full link
fattening including reunitarisation — so every fermion action gets its
exact force from the same three lines.  Correctness is pinned by
finite-difference tests and HMC dH = O(dt^2) scaling.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..ops import blas
from .action import gauge_force


def pseudofermion_force(make_mdagm: Callable, gauge: jnp.ndarray,
                        x: jnp.ndarray) -> jnp.ndarray:
    """Force of S_pf = phi^dag (MdagM)^{-1} phi at X = (MdagM)^{-1} phi.

    make_mdagm(U) -> callable applying M(U)^dag M(U).
    """
    x = jax.lax.stop_gradient(x)

    def quad(u):
        return -blas.redot(x, make_mdagm(u)(x))

    return gauge_force(quad, gauge)


def pseudofermion_action(make_mdagm: Callable, gauge, phi, solve: Callable):
    """S_pf = phi^dag (MdagM)^{-1} phi and the X it was evaluated at."""
    xsol = solve(make_mdagm(gauge), phi)
    return blas.redot(phi, xsol).real, xsol


def rational_force(make_m: Callable, gauge: jnp.ndarray, x_shifts,
                   residues) -> jnp.ndarray:
    """RHMC rational-approximation force: S = phi^dag r(MdagM) phi with
    r(A) = sum_i c_i (A + s_i)^{-1}; given the multi-shift solutions
    X_i = (A + s_i)^{-1} phi, F = -sum_i c_i gauge_force(<X_i, A X_i>)
    (computeHISQForceQuda's multi-shift consumer path)."""
    total = None
    for xi, ci in zip(x_shifts, residues):
        xi = jax.lax.stop_gradient(xi)

        def quad(u, xi=xi, ci=ci):
            return -ci * blas.redot(xi, make_m(u)(xi))

        f = gauge_force(quad, gauge)
        total = f if total is None else total + f
    return total
