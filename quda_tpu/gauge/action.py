"""Gauge actions, forces via automatic differentiation, and HMC.

Reference behavior: lib/gauge_force.cu + kernels/gauge_force.cuh (staple
evaluation from path tables), lib/gauge_loop_trace.cu, lib/momentum.cu
(momActionQuda, force monitor), lib/gauge_update_quda.cu (U <- exp(i eps p) U),
plus the MILC-driven HMC workflow (lib/milc_interface.cpp).

TPU-native design — THE key departure from the reference: forces are
jax.grad of the action.  QUDA hand-derives every force (generic path
staples, clover force chain rule, HISQ force with SVD differentiation,
2000+ LoC); here ANY differentiable action — plaquette, rectangle,
smeared, or a pseudofermion quadratic form through the whole solver chain
— gets its su(3)-projected force from one `gauge_force` call.  Correctness
is pinned by finite-difference tests and leapfrog energy conservation
(dH = O(dt^2) scaling).

Conventions: momenta P are Hermitian traceless (fields of su(3) coeffs
p_a: P = sum_a p_a T_a); U(t) = exp(i t P) U; H = tr(P^2) + S(U);
force F = sum_a T_a dS/d(theta_a) so that dP/dt = -F conserves H.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.fmunu import PLANES
from ..ops.su3 import (dagger, expm_su3, is_pairs, mat_i, mat_mul,
                       project_su3, random_hermitian_traceless, re_trace,
                       trace)
from .observables import plaquette_field


# -- actions ---------------------------------------------------------------

def wilson_action(gauge: jnp.ndarray, beta: float) -> jnp.ndarray:
    """S = beta sum_{x, mu<nu} (1 - Re tr P_{mu nu} / 3)."""
    s = 0.0
    for mu, nu in PLANES:
        p = re_trace(plaquette_field(gauge, mu, nu)) / 3.0
        s = s + jnp.sum(1.0 - p)
    return beta * s


def rectangle_field(gauge, mu, nu):
    """2x1 loop R_{mu mu nu}(x) (for improved actions)."""
    from ..ops.shift import shift
    u_mu, u_nu = gauge[mu], gauge[nu]
    two = mat_mul(u_mu, shift(u_mu, mu, +1))           # 2-link in mu
    top = mat_mul(two, shift(u_nu, mu, 2))
    bot = mat_mul(u_nu, shift(two, nu, +1))
    return mat_mul(top, dagger(bot))


def improved_action(gauge: jnp.ndarray, beta: float, c1: float):
    """Luscher-Weisz class: c0 * plaq + c1 * rect, c0 = 1 - 8 c1
    (c1 = -1/12: tree-level Symanzik; c1 = -0.331: Iwasaki)."""
    c0 = 1.0 - 8.0 * c1
    s = 0.0
    for mu, nu in PLANES:
        p = re_trace(plaquette_field(gauge, mu, nu)) / 3.0
        s = s + c0 * jnp.sum(1.0 - p)
        r1 = re_trace(rectangle_field(gauge, mu, nu)) / 3.0
        r2 = re_trace(rectangle_field(gauge, nu, mu)) / 3.0
        s = s + c1 * (jnp.sum(1.0 - r1) + jnp.sum(1.0 - r2))
    return beta * s


# -- force via AD ----------------------------------------------------------

def traceless_hermitian(m: jnp.ndarray) -> jnp.ndarray:
    h = 0.5 * (m + dagger(m))
    tr = trace(h) / 3.0
    if is_pairs(m):
        # complex scalar times identity: place the pair scalar on the
        # diagonal (an elementwise product with eye_like would not be a
        # complex multiply)
        return h - tr[..., None, None, :] * jnp.eye(3, dtype=m.dtype)[..., None]
    return h - tr[..., None, None] * jnp.eye(3, dtype=m.dtype)


def gauge_force(action_fn: Callable, gauge: jnp.ndarray) -> jnp.ndarray:
    """F_mu(x) = sum_a T_a dS/dtheta_a for U -> exp(i theta) U.

    JAX's grad g of a real scalar wrt complex U satisfies
    dS = Re sum conj(g) dU with g = dS/dRe(U) + i dS/dIm(U).
    With dU = i Q U:  dS = Re tr(i Q U g^dag), giving the Hermitian
    traceless force F = TA( i (M - M^dag) ) / 2 with M = U g^dag.
    """
    g = jax.grad(lambda u: action_fn(u).real)(gauge)
    # complex: JAX returns conj(dS/dRe + i dS/dIm) for real S, so conj
    # recovers gc with dS = Re<gc, dU>.  Pair: the grad array READ AS
    # COMPLEX already satisfies dS = Re<gc, dU> (and conjugate on a real
    # array is the identity), so one line serves both representations.
    g = jnp.conjugate(g)
    m = mat_mul(gauge, dagger(g))
    k = 0.5 * mat_i(m - dagger(m))
    # with H = tr(P^2) + S and dU/dt = i P U, energy conservation fixes
    # F = TA(K)/2  (dS/dt = tr(P K), dT/dt = -2 tr(P F))
    return 0.5 * traceless_hermitian(k)


# -- momenta / update ------------------------------------------------------

def random_momentum(key, gauge_shape, dtype=jnp.complex128):
    """Gaussian su(3) momenta, <p_a^2> = 1 (gaussGaugeQuda mom mode).
    A floating dtype samples straight into the pair representation."""
    return random_hermitian_traceless(key, gauge_shape, dtype=dtype)


def mom_action(p: jnp.ndarray) -> jnp.ndarray:
    """T = tr(P^2) summed (= 1/2 sum_a p_a^2; momActionQuda analog)."""
    return jnp.sum(re_trace(mat_mul(p, p)))


def update_gauge(gauge: jnp.ndarray, p: jnp.ndarray,
                 eps: float) -> jnp.ndarray:
    """U <- exp(i eps P) U (updateGaugeFieldQuda)."""
    return mat_mul(expm_su3(eps * p), gauge)


def _force_monitor(f: jnp.ndarray, label: str):
    """QUDA_TPU_ENABLE_FORCE_MONITOR: log per-kick force norms
    (reference: QUDA_ENABLE_FORCE_MONITOR in lib/momentum.cu —
    forceRecord prints the max/L2 force per update).  Inactive under
    jit tracing (no host values there)."""
    from ..utils import config as qconf
    from ..utils import logging as qlog
    if not qconf.get("QUDA_TPU_ENABLE_FORCE_MONITOR", fresh=True):
        return
    if isinstance(f, jax.core.Tracer):
        return
    axes = (-3, -2, -1) if is_pairs(f) else (-2, -1)
    site2 = jnp.sum(jnp.abs(f) ** 2, axis=axes)
    qlog.printq(f"force {label}: max {float(jnp.max(site2)) ** 0.5:.6e} "
                f"rms {float(jnp.mean(site2)) ** 0.5:.6e}",
                qlog.SUMMARIZE)


# -- integrators / HMC -----------------------------------------------------

class HMCResult(NamedTuple):
    gauge: jnp.ndarray
    accept: jnp.ndarray
    dH: jnp.ndarray
    plaq: jnp.ndarray


def leapfrog(action_fn, gauge, p, n_steps: int, dt: float):
    """Standard leapfrog: half-kick, n drifts/kicks, half-kick."""
    f = gauge_force(action_fn, gauge)
    _force_monitor(f, "leapfrog kick 0")
    p = p - (0.5 * dt) * f
    for i in range(n_steps):
        gauge = update_gauge(gauge, p, dt)
        f = gauge_force(action_fn, gauge)
        _force_monitor(f, f"leapfrog kick {i + 1}")
        p = p - (dt if i < n_steps - 1 else 0.5 * dt) * f
    return gauge, p


def omf2(action_fn, gauge, p, n_steps: int, dt: float,
         lam: float = 0.1931833275037836):
    """2nd-order Omelyan integrator (QUDA/MILC default flavor)."""
    for _ in range(n_steps):
        p = p - (lam * dt) * gauge_force(action_fn, gauge)
        gauge = update_gauge(gauge, p, 0.5 * dt)
        p = p - ((1.0 - 2.0 * lam) * dt) * gauge_force(action_fn, gauge)
        gauge = update_gauge(gauge, p, 0.5 * dt)
        p = p - (lam * dt) * gauge_force(action_fn, gauge)
    return gauge, p


def hmc_trajectory(key, action_fn, gauge, n_steps: int = 10,
                   dt: float = 0.1, integrator=leapfrog) -> HMCResult:
    """One HMC trajectory with Metropolis accept/reject."""
    from .observables import plaquette
    k_mom, k_acc = jax.random.split(key)
    site_shape = gauge.shape[:-3] if is_pairs(gauge) else gauge.shape[:-2]
    p0 = random_momentum(k_mom, site_shape, gauge.dtype)
    h0 = mom_action(p0) + action_fn(gauge)
    g1, p1 = integrator(action_fn, gauge, p0, n_steps, dt)
    h1 = mom_action(p1) + action_fn(g1)
    dh = h1 - h0
    u = jax.random.uniform(k_acc, ())
    accept = u < jnp.exp(jnp.minimum(-dh, 0.0))
    g_new = jnp.where(accept, g1, gauge)
    # reunitarise drift (QUDA projects after update too)
    g_new = project_su3(g_new)
    return HMCResult(g_new, accept, dh, plaquette(g_new)[0])
