"""Fermion-field smearing: Wuppertal, Gaussian, two-link staggered.

Reference behavior: performWuppertalnStep (lib/interface_quda.cpp:4935),
performTwoLinkGaussianSmearNStep (lib/staggered_quark_smearing.cu),
using the covariant 3-d Laplacian.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops.laplace import covariant_derivative, laplace


def wuppertal_smear(gauge: jnp.ndarray, psi: jnp.ndarray, alpha: float,
                    n_steps: int) -> jnp.ndarray:
    """psi <- (1/(1+6 alpha)) [psi + alpha sum_{spatial} (U psi_+ + U^dag psi_-)]
    iterated n_steps times."""
    norm = 1.0 / (1.0 + 6.0 * alpha)
    for _ in range(n_steps):
        acc = psi
        for mu in range(3):
            acc = acc + alpha * covariant_derivative(gauge, psi, mu, +1)
            acc = acc + alpha * covariant_derivative(gauge, psi, mu, -1)
        psi = norm * acc
    return psi


def gaussian_smear(gauge: jnp.ndarray, psi: jnp.ndarray, omega: float,
                   n_steps: int, ndim: int = 3,
                   two_link_gauge: jnp.ndarray = None) -> jnp.ndarray:
    """exp(-omega^2/4 * Laplacian)-style Gaussian smearing as n_steps of
    (1 - omega^2/(4 n) * (-Delta)) (staggered two-link version passes the
    doubled links and uses 2-hop covariant derivatives).
    """
    eps = omega * omega / (4.0 * n_steps)
    if two_link_gauge is None:
        for _ in range(n_steps):
            psi = psi - eps * laplace(gauge, psi, ndim=ndim)
        return psi
    # two-link version: hops of length 2 with the doubled links
    from ..ops.shift import shift
    from ..ops.su3 import dagger

    def lap2(p):
        acc = 2.0 * ndim * p
        for mu in range(ndim):
            u2 = two_link_gauge[mu]
            fwd = jnp.einsum("...ab,...sb->...sa", u2, shift(p, mu, +1, 2))
            bwd = jnp.einsum("...ab,...sb->...sa",
                             shift(dagger(u2), mu, -1, 2),
                             shift(p, mu, -1, 2))
            acc = acc - fwd - bwd
        return acc

    for _ in range(n_steps):
        psi = psi - eps * lap2(psi)
    return psi
