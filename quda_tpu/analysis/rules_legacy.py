"""The six pre-existing ad-hoc lints, migrated onto the shared engine.

Each of these previously lived as its own test module with its own
``os.walk`` + ``ast.parse`` of the whole package (six full parses per
tier-1 run).  The assertions are preserved — identical or stronger
(findings now carry line numbers; the env-knob scan also covers the
repo-root bench harnesses) — and the old test names survive as thin
wrappers over these passes, so the history of what each lint pins stays
comparable.

Runtime-only halves (the ``_solve_form`` attribute-lattice sweep, the
registry-object hygiene asserts) stay in their original test files:
they execute package code rather than read it, so they gain nothing
from the shared parse.
"""

from __future__ import annotations

import ast
import re

from .engine import package_check, rule

# -- env-knob ---------------------------------------------------------------

_KNOB_RE = re.compile(r"QUDA_TPU_[A-Z0-9_]*[A-Z0-9]")


def _registered_knobs() -> set:
    from ..utils import config as qconf
    return set(qconf.knobs())


@rule("env-knob",
      "every QUDA_TPU_* string referenced in the package (and the "
      "bench harnesses) is registered in utils/config.py — an "
      "unregistered knob read raises only when its path runs; a typoed "
      "one silently never fires")
def check_env_knobs(index, mod):
    registered = _registered_knobs()
    seen = set()
    for i, line in enumerate(mod.lines, 1):
        for m in _KNOB_RE.findall(line):
            if m not in registered and (m, i) not in seen:
                seen.add((m, i))
                yield (i, f"unregistered QUDA_TPU_* knob {m!r} — "
                          "register it in utils/config.py (type, "
                          "default, doc) or fix the typo")


@package_check("env-knob")
def check_knob_registry(index):
    """Registration hygiene rides along (the legacy docs assert, plus
    the round-17 trace_safe field contract)."""
    from ..utils import config as qconf
    rel = "quda_tpu/utils/config.py"
    mod = index.get(rel)
    for name, knob in qconf.knobs().items():
        line = mod.line_of(f'"{name}"') if mod else 1
        if not knob.doc or len(knob.doc) <= 10:
            yield (rel, line,
                   f"{name} registered without a usable doc string — "
                   "invisible in describe()")
        if not isinstance(getattr(knob, "trace_safe", False), bool):
            yield (rel, line,
                   f"{name}.trace_safe must be a bool — the "
                   "trace-safety pass reads its policy from this field")


# -- obs-schema -------------------------------------------------------------

_EVENT_FUNCS = {"event", "_obs_event", "_mirror_row_event"}
_METRIC_FUNCS = {"inc", "set_gauge", "observe", "_obs_metric",
                 "_obs_gauge"}


def _first_str_arg(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _harvest_names(mod, funcs):
    for call in mod.calls():
        if mod.last_name(call.func) in funcs:
            name = _first_str_arg(call)
            if name is not None:
                yield name, call.lineno


@rule("obs-schema",
      "every emitted trace-event / recorded metric name appears in "
      "obs/schema.py, and (package-wide) no registered name is "
      "orphaned — dashboards key on names and break silently")
def check_obs_schema(index, mod):
    from ..obs import schema as osch
    for name, line in _harvest_names(mod, _EVENT_FUNCS):
        if name not in osch.TRACE_EVENTS:
            yield (line, f"trace event {name!r} emitted without a "
                         "schema entry — register it in "
                         "quda_tpu/obs/schema.py TRACE_EVENTS "
                         "(cat + doc)")
    for name, line in _harvest_names(mod, _METRIC_FUNCS):
        if name not in osch.METRICS:
            yield (line, f"metric {name!r} recorded without a schema "
                         "entry — register it in quda_tpu/obs/"
                         "schema.py METRICS (type + help)")


@package_check("obs-schema")
def check_obs_schema_orphans(index):
    from ..obs import schema as osch
    rel = "quda_tpu/obs/schema.py"
    smod = index.get(rel)
    events, metrics = set(), set()
    for mod in index.modules:
        events.update(n for n, _ in _harvest_names(mod, _EVENT_FUNCS))
        metrics.update(n for n, _ in _harvest_names(mod, _METRIC_FUNCS))
    for name in sorted(set(osch.TRACE_EVENTS) - events):
        yield (rel, smod.line_of(f'"{name}"') if smod else 1,
               f"TRACE_EVENTS entry {name!r} nothing emits — schema "
               "rot; delete it or restore the emission site")
    for name in sorted(set(osch.METRICS) - metrics):
        yield (rel, smod.line_of(f'"{name}"') if smod else 1,
               f"METRICS entry {name!r} nothing records — schema rot; "
               "delete it or restore the recording site")


# -- roofline-model ---------------------------------------------------------

_FORM_PREFIXES = ("wilson", "staggered", "generic", "mg_coarse",
                  "clover", "twisted", "dwf")


def _roofline_literals(mod):
    for node in mod.nodes:
        if isinstance(node, ast.Call):
            if mod.last_name(node.func) in ("record", "attribute",
                                            "model"):
                s = _first_str_arg(node)
                if s is not None:
                    yield s, node.lineno
            for kw in node.keywords:
                if kw.arg == "form" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    yield kw.value.value, kw.value.lineno
        elif isinstance(node, ast.Assign):
            if any(getattr(t, "id", "") == "form" for t in node.targets):
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Constant) \
                            and isinstance(c.value, str):
                        yield c.value, c.lineno


def _in_roofline_namespace(s: str) -> bool:
    return any(s == p or s.startswith(p + "_") for p in _FORM_PREFIXES)


@rule("roofline-model",
      "every kernel-form literal recorded/attributed anywhere has a "
      "KERNEL_MODELS entry in obs/roofline.py — a kernel cannot ship "
      "unattributable (the round-9 methodology rule)")
def check_roofline_models(index, mod):
    from ..obs import roofline as orf
    seen = set()
    for lit, line in _roofline_literals(mod):
        if _in_roofline_namespace(lit) and lit not in orf.KERNEL_MODELS \
                and (lit, line) not in seen:
            seen.add((lit, line))
            yield (line, f"form literal {lit!r} recorded without a "
                         "KERNEL_MODELS entry — add the traffic model "
                         "to obs/roofline.py (or None bytes for an "
                         "honest flops-only row)")


# -- comms-ledger -----------------------------------------------------------

def _calls_in(mod, node, names):
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and mod.last_name(n.func) in names]


def _function(mod, name):
    for f in mod.functions():
        if f.name == name:
            return f
    return None


_COMMS_SEAMS = (
    ("quda_tpu/parallel/halo.py", "_permute_slice"),
    ("quda_tpu/parallel/pallas_halo.py", "slab_exchange_bidir"),
    ("quda_tpu/parallel/pallas_halo.py", "wilson_axis_fused_halo"),
    ("quda_tpu/parallel/pallas_halo.py", "wilson_zbwd_fused_halo"),
)

# round 18: the y/x exchange seams.  The strided x column exchange
# (_eo_x_psi_sources) and the two column-face fixes that consume it are
# INTERNAL to parallel/pallas_dslash — every transfer they stage rides
# the exchange() callable built by _make_exchange inside a comms scope,
# which is what labels their ledger rows with (site, policy, axis).
# Calling them from anywhere else bypasses that attribution.
_YX_SEAM_FNS = frozenset({"_eo_x_psi_sources", "_wilson_eo_fix_x",
                          "_stag_eo_fix_x"})

# (function, required callee) wiring pinned inside pallas_dslash: the x
# column exchange must route through the policy seam, both column-face
# fixes must source their halos from it, and the per-axis face plan
# must exist so every new axis seam enumerates through ONE place
_YX_SEAM_WIRING = (
    ("_eo_x_psi_sources", "exchange"),
    ("_wilson_eo_fix_x", "_eo_x_psi_sources"),
    ("_stag_eo_fix_x", "_eo_x_psi_sources"),
    ("_axis_plan", "_FaceIO"),
)


@rule("comms-ledger",
      "ppermute has ONE home (parallel/halo._permute_slice), "
      "slab_exchange_bidir is only called through the _make_exchange "
      "policy seam, and sharded wrappers open a comms scope — an "
      "unledgered transfer ships unattributed")
def check_comms_ledger(index, mod):
    is_halo = mod.rel.endswith("parallel/halo.py")
    is_pallas_halo = mod.rel.endswith("parallel/pallas_halo.py")
    is_dslash = mod.rel.endswith("parallel/pallas_dslash.py")
    for fn in mod.functions():
        # nested defs re-walk their parents' bodies below; attribute
        # each call to its INNERMOST function to avoid duplicates
        own_calls = [n for n in ast.walk(fn)
                     if isinstance(n, ast.Call)
                     and mod.enclosing_function(n) is fn]
        for call in own_calls:
            name = mod.last_name(call.func)
            if name == "ppermute" \
                    and not (is_halo and fn.name == "_permute_slice"):
                yield (call.lineno,
                       f"lax.ppermute called in {fn.name}() outside "
                       "parallel/halo._permute_slice — route the "
                       "transfer through the comms-ledger seam")
            if name == "slab_exchange_bidir" and not is_pallas_halo \
                    and not (is_dslash and fn.name in ("_make_exchange",
                                                       "exchange")):
                yield (call.lineno,
                       f"slab_exchange_bidir called in {fn.name}() "
                       "outside the _make_exchange policy seam")
            if name in _YX_SEAM_FNS and not is_dslash:
                yield (call.lineno,
                       f"{name}() (y/x exchange seam) called in "
                       f"{fn.name}() outside parallel/pallas_dslash — "
                       "the comms scope that labels its ledger rows "
                       "with (site, policy, axis) is bypassed")
        if is_dslash and fn.name != "_make_exchange" \
                and _calls_in(mod, fn, {"_make_exchange"}) \
                and not _calls_in(mod, fn, {"scope"}):
            yield (fn.lineno,
                   f"{fn.name}() builds an exchange via _make_exchange "
                   "without opening a comms scope — its ledger rows "
                   "lose site/policy labels")


@package_check("comms-ledger")
def check_comms_seams(index):
    for rel, fname in _COMMS_SEAMS:
        mod = index.get(rel)
        if mod is None:
            yield (rel, 1, "exchange-seam module missing from the "
                           "package index")
            continue
        fn = _function(mod, fname)
        if fn is None:
            yield (rel, 1, f"exchange seam {fname}() not found — the "
                           "comms ledger pins this name")
        elif not _calls_in(mod, fn, {"record_exchange"}):
            yield (rel, fn.lineno,
                   f"exchange seam {fname}() records nothing into the "
                   "comms ledger (record_exchange missing)")
    rel = "quda_tpu/parallel/pallas_dslash.py"
    mod = index.get(rel)
    if mod is None:
        yield (rel, 1, "y/x exchange-seam module missing from the "
                       "package index")
    else:
        for fname, callee in _YX_SEAM_WIRING:
            fn = _function(mod, fname)
            if fn is None:
                yield (rel, 1, f"y/x exchange seam {fname}() not found "
                               "— the comms ledger pins this name")
            elif not _calls_in(mod, fn, {callee}):
                yield (rel, fn.lineno,
                       f"y/x exchange seam {fname}() does not route "
                       f"through {callee}() — its transfer ships "
                       "outside the ledgered policy seam")
    rel = "quda_tpu/parallel/split.py"
    mod = index.get(rel)
    fn = _function(mod, "split_grid_solve") if mod else None
    if fn is None:
        yield (rel, 1, "split_grid_solve not found — the comms ledger "
                       "pins its replication record")
    elif not _calls_in(mod, fn, {"record_replication"}):
        yield (rel, fn.lineno,
               "split_grid_solve must record its gauge replication "
               "into the comms ledger (lane placement is interconnect "
               "traffic)")


# -- flight-capture ---------------------------------------------------------

_CAPTURE_FUNCS = {"capture", "capture_exception", "_pm_capture"}
_GUARDED_APIS = ("invert_quda", "invert_multishift_quda",
                 "invert_multi_src_quda", "eigensolve_quda",
                 "load_gauge_quda")


@rule("flight-capture",
      "every failure path feeds the postmortem capture hook and the "
      "flight ring has exactly one home (no second bounded deque) — a "
      "failure without a bundle is un-debuggable after the fact")
def check_flight_capture(index, mod):
    # single-ring invariant: file-local, applies everywhere
    if not mod.rel.endswith("obs/flight.py"):
        for call in mod.calls():
            if mod.last_name(call.func) == "deque" \
                    and any(k.arg == "maxlen" for k in call.keywords):
                yield (call.lineno,
                       "bounded deque (ring buffer) outside "
                       "obs/flight.py — the flight recorder is the ONE "
                       "ring; record via obs.flight.record or the "
                       "obs.trace.event tap")
    if mod.rel.endswith("robust/escalate.py"):
        for node in mod.nodes:
            if isinstance(node, ast.ExceptHandler) \
                    and not _calls_in(mod, node, _CAPTURE_FUNCS):
                yield (node.lineno,
                       "except handler without a postmortem capture "
                       "call — a failure that escalates without a "
                       "bundle is un-debuggable")
        fn = _function(mod, "run_ladder")
        if fn is None:
            yield (1, "run_ladder not found — the capture-coverage "
                      "pins target it")
        else:
            calls = _calls_in(mod, fn, _CAPTURE_FUNCS)
            if len(calls) < 3:
                yield (fn.lineno,
                       f"run_ladder has {len(calls)} capture call(s); "
                       "its three failure paths (construct_error / "
                       "ladder_exhausted:failed / ladder_exhausted:"
                       "degraded) must each capture")
            for node in ast.walk(fn):
                if isinstance(node, ast.If) \
                        and any(isinstance(n, ast.Raise)
                                for b in node.body
                                for n in ast.walk(b)) \
                        and not any(_calls_in(mod, b, _CAPTURE_FUNCS)
                                    for b in node.body):
                    yield (node.lineno,
                           "run_ladder raising block does not capture "
                           "before the re-raise")
    if mod.rel.endswith("interfaces/quda_api.py"):
        yield from _check_api_guards(mod)
    if "serve" in mod.rel.split("/")[:-1]:
        yield from _check_serve_request_scope(mod)


def _check_serve_request_scope(mod):
    """Serve-scoped solves must carry request ids into capture: any
    solve-API call made from a ``serve/`` module has SolveTickets
    riding on it, so a postmortem bundle captured inside must be able
    to name them — which requires the call to run lexically inside a
    ``with opm.serve_requests(ids)`` block (obs/postmortem.py pushes
    the ids the manifest writer reads).  A bundle without the ticket's
    request_id strands the operator at 'some request failed'."""
    solve_apis = frozenset(_GUARDED_APIS) - {"load_gauge_quda"}

    def _with_names(w: ast.With) -> set:
        names = set()
        for item in w.items:
            ctx = item.context_expr
            f = ctx.func if isinstance(ctx, ast.Call) else ctx
            names.add(mod.last_name(f))
        return names

    def _walk(node, scoped: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                _walk(child, scoped
                      or "serve_requests" in _with_names(child))
                continue
            if isinstance(child, ast.Call) \
                    and mod.last_name(child.func) in solve_apis \
                    and not scoped:
                found.append(
                    (child.lineno,
                     f"serve-scoped {mod.last_name(child.func)}() call "
                     "outside a serve_requests(...) scope — a "
                     "postmortem bundle captured during this solve "
                     "cannot carry its tickets' request_id (wrap the "
                     "call in obs.postmortem.serve_requests)"))
            _walk(child, scoped)

    found: list = []
    _walk(mod.tree, False)
    yield from found


def _check_api_guards(mod):
    for api in _GUARDED_APIS:
        fn = _function(mod, api)
        if fn is None:
            yield (1, f"API entry point {api}() not found — the "
                      "postmortem boundary-guard pins target it")
            continue
        deco_names = []
        for d in fn.decorator_list:
            f = d.func if isinstance(d, ast.Call) else d
            deco_names.append(mod.last_name(f))
        if "_pm_api" not in deco_names:
            yield (fn.lineno,
                   f"{api}() lacks the _pm_api postmortem boundary "
                   "guard — an uncaught exception crossing this "
                   "boundary must capture a bundle before propagating")
    guard = _function(mod, "_pm_api")
    if guard is None:
        yield (1, "_pm_api guard not found")
    else:
        handlers = [n for n in ast.walk(guard)
                    if isinstance(n, ast.ExceptHandler)]
        if not handlers:
            yield (guard.lineno, "_pm_api has no except handler")
        for h in handlers:
            if not _calls_in(mod, h, _CAPTURE_FUNCS):
                yield (h.lineno, "_pm_api except handler does not call "
                                 "the capture hook")
            if not any(isinstance(n, ast.Raise) for n in ast.walk(h)):
                yield (h.lineno, "_pm_api except handler must re-raise "
                                 "(capture, never swallow)")
    sup = _function(mod, "_solve_supervision")
    if sup is None:
        yield (1, "_solve_supervision not found")
    elif len(_calls_in(mod, sup, {"capture"})) < 2:
        yield (sup.lineno,
               "_solve_supervision must capture on BOTH failure "
               "classifications (breakdown + verify mismatch)")
    lg = _function(mod, "load_gauge_quda")
    if lg is not None and not _calls_in(mod, lg, {"capture"}):
        yield (lg.lineno,
               "load_gauge_quda's rejection site must capture the "
               "rejected gauge before raising")


# -- robust-sentinel --------------------------------------------------------

@rule("robust-sentinel",
      "every solver module threading a lax.while_loop registers the "
      "breakdown sentinel (import robust.sentinel + a make()/active() "
      "gate) — an unguarded compiled loop reintroduces the "
      "NaN-spin-to-maxiter failure mode")
def check_robust_sentinel(index, mod):
    parts = mod.rel.split("/")[:-1]
    if "solvers" not in parts or mod.rel.endswith("__init__.py"):
        return
    first_loop = None
    aliases = set()
    gated = False
    for node in mod.nodes:
        if isinstance(node, ast.Call):
            if getattr(node.func, "attr", None) == "while_loop" \
                    and first_loop is None:
                first_loop = node
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").endswith("robust"):
                for a in node.names:
                    if a.name == "sentinel":
                        aliases.add(a.asname or a.name)
    if first_loop is None:
        return
    for node in mod.nodes:
        if isinstance(node, ast.Call) \
                and getattr(node.func, "attr", None) in ("make",
                                                         "active") \
                and getattr(getattr(node.func, "value", None), "id",
                            None) in aliases:
            gated = True
            break
    if not aliases:
        yield (first_loop.lineno,
               "solver module threads a lax.while_loop with no "
               "robust.sentinel import — thread the sentinel through "
               "the loop carry (make() -> init/step/ok)")
    elif not gated:
        yield (first_loop.lineno,
               "solver module imports robust.sentinel but never calls "
               "make()/active() — the compiled loop runs unguarded")
