"""CLI: ``python -m quda_tpu.analysis``.

Runs the registered passes over the package (or explicit ``--paths``)
and prints every finding; exit status 0 iff zero UNSUPPRESSED findings
remain — the tier-1 contract, callable standalone (pre-commit, CI
without pytest, operator triage).
"""

from __future__ import annotations

import argparse
import sys

from . import RULES, render_json, render_tsv, rule_names, run


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m quda_tpu.analysis",
        description="quda_tpu static analysis: one parse, N passes, "
                    "suppressible typed findings (reason mandatory)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all); "
                    "use --list to see them")
    ap.add_argument("--paths", nargs="*", default=None,
                    help="explicit files to analyze instead of the "
                    "package (file-local checks only)")
    ap.add_argument("--tsv", default=None, metavar="PATH",
                    help="write findings as TSV")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write findings + per-rule counts as JSON")
    ap.add_argument("--list", action="store_true",
                    help="list registered rules and exit")
    ap.add_argument("--suppressed", action="store_true",
                    help="also print suppressed findings")
    args = ap.parse_args(argv)

    if args.list:
        for name in rule_names():
            print(f"{name}: {RULES[name].doc}")
        return 0

    rules = ([r for r in args.rules.split(",") if r]
             if args.rules else None)
    result = run(rules=rules, paths=args.paths)

    for f in result.findings:
        if f.suppressed and not args.suppressed:
            continue
        print(f.render())
    if args.tsv:
        with open(args.tsv, "w") as fh:
            fh.write(render_tsv(result))
    if args.json:
        with open(args.json, "w") as fh:
            fh.write(render_json(result))

    counts = result.counts()
    n_bad = len(result.unsuppressed)
    n_sup = len(result.findings) - n_bad
    summary = ", ".join(
        f"{name}={cnt['unsuppressed']}" for name, cnt in
        sorted(counts.items()) if cnt["unsuppressed"])
    print(f"# {result.n_modules} modules, {len(result.rules)} rules: "
          f"{n_bad} unsuppressed finding(s)"
          + (f" [{summary}]" if summary else "")
          + (f", {n_sup} suppressed" if n_sup else ""))
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
