"""Trace-safety and donation/aliasing passes — the jax-semantics rules.

These encode this repo's hard-won review lessons as rails:

* **trace-safety** — a ``config.get``/``flag``/``intval`` (or ``time.*``,
  ``numpy.random``, ``os.environ``) call lexically inside a function
  handed to ``jax.jit`` / ``lax.while_loop`` / ``lax.scan`` /
  ``shard_map`` executes at TRACE time: its value is frozen into the
  compiled executable, so a changed knob silently serves stale behavior
  (or forces a recompile storm) — knobs must be read at operator
  construction and closed over.  Knobs registered with
  ``trace_safe=True`` in utils/config.py are exempt (policy lives in
  the registry, not in this pass).
* **donation** — a name passed in a donated argument position
  (``donate_argnums``/``donate_argnames``) refers to a buffer the
  runtime may alias into the output; reading it after the donating call
  is use-after-free semantics on TPU (garbage under XLA, correct-looking
  under CPU tests — the worst kind).  The ROADMAP item-2 double-buffer
  headroom lands on top of this rail.
"""

from __future__ import annotations

import ast

from .engine import rule

# terminal names whose function-valued arguments trace (jax transform
# entry points; bases are verified to resolve into jax below)
_TRACE_ENTRIES = {"jit", "pjit", "while_loop", "scan", "fori_loop",
                  "cond", "switch", "shard_map", "pmap", "pallas_call",
                  "remat", "checkpoint", "custom_vjp", "custom_jvp"}
_CONFIG_READS = {"get", "flag", "intval", "floatval", "strval"}


def _trace_safe_knobs() -> set:
    """Knob names the registry marks legal to read under trace."""
    from ..utils import config as qconf
    return {name for name, k in qconf.knobs().items()
            if getattr(k, "trace_safe", False)}


def _is_jax_entry(mod, call: ast.Call) -> bool:
    dn = mod.call_name(call)
    if dn is None:
        return False
    last = dn.rsplit(".", 1)[-1]
    if last not in _TRACE_ENTRIES:
        return False
    head = dn.split(".", 1)[0]
    # resolved through imports ('lax' -> 'jax.lax'); accept unresolved
    # bare aliases only when they are the conventional jax short names
    return head in ("jax", "lax", "jnp", "pl", "pltpu", "pjit", "jit",
                    "shard_map", "pallas_call") or last == dn


def _unwrap_partial(mod, node):
    if isinstance(node, ast.Call):
        dn = mod.call_name(node)
        if dn and dn.rsplit(".", 1)[-1] == "partial" and node.args:
            return node.args[0]
    return node


def _traced_roots(mod):
    """(entry_label, function-node) for every function lexically handed
    to a jax transform: lambda/Name arguments of entry calls, and
    defs decorated with jit (bare or partial-applied)."""
    funcs_by_name = {}
    for f in mod.functions():
        funcs_by_name.setdefault(f.name, []).append(f)
    roots = []
    for call in mod.calls():
        if not _is_jax_entry(mod, call):
            continue
        label = mod.call_name(call).rsplit(".", 1)[-1]
        cands = list(call.args) + [k.value for k in call.keywords]
        for a in cands:
            a = _unwrap_partial(mod, a)
            if isinstance(a, ast.Lambda):
                roots.append((label, a))
            elif isinstance(a, ast.Name):
                for f in funcs_by_name.get(a.id, ()):
                    roots.append((label, f))
    for f in mod.functions():
        for d in f.decorator_list:
            # @partial(jax.jit, ...) unwraps to jax.jit; a plain
            # @jit(...) call-decorator resolves through its func
            target = _unwrap_partial(mod, d)
            if isinstance(target, ast.Call):
                target = target.func
            dn = mod.dotted(target)
            if dn and dn.rsplit(".", 1)[-1] in ("jit", "pjit", "pmap"):
                roots.append(("decorator", f))
    return roots


@rule("trace-safety",
      "no host-state reads (config knobs, time.*, numpy.random, "
      "os.environ) lexically inside functions traced by "
      "jit/while_loop/scan/shard_map — knobs are read at operator "
      "construction (trace_safe=True registry entries exempt)")
def check_trace_safety(index, mod):
    safe_knobs = _trace_safe_knobs()
    seen = set()
    for entry, fn in _traced_roots(mod):
        for node in ast.walk(fn):
            hazard = None
            if isinstance(node, ast.Call):
                dn = mod.call_name(node)
                if dn is None:
                    continue
                base, _, last = dn.rpartition(".")
                if last in _CONFIG_READS and base.endswith("config"):
                    knob = (node.args[0].value
                            if node.args
                            and isinstance(node.args[0], ast.Constant)
                            else None)
                    if knob in safe_knobs:
                        continue
                    hazard = (f"config knob read {dn}({knob!r}) — the "
                              "value freezes into the traced "
                              "executable (stale-knob/recompile "
                              "hazard); read it at operator "
                              "construction or register the knob "
                              "trace_safe=True")
                elif dn == "time" or dn.startswith("time."):
                    hazard = (f"host clock read {dn}() — traces to a "
                              "constant, not a per-call timestamp")
                elif dn.startswith("numpy.random") \
                        or dn.startswith("random."):
                    hazard = (f"host RNG call {dn}() — traces to a "
                              "constant draw; use jax.random with a "
                              "threaded key")
                elif dn == "os.getenv" or dn.startswith("os.environ"):
                    hazard = (f"environment read {dn}() under trace — "
                              "same stale-value hazard as an "
                              "unregistered knob read")
            elif isinstance(node, ast.Attribute):
                if mod.dotted(node) == "os.environ":
                    hazard = ("os.environ access under trace — the "
                              "read freezes at trace time")
            if hazard and (node.lineno, hazard) not in seen:
                seen.add((node.lineno, hazard))
                yield (node.lineno,
                       f"inside a {entry} body: {hazard}")


# -- donation ---------------------------------------------------------------

def _donating_jit_calls(mod):
    """Call nodes constructing a donating jitted function: jit/pjit
    with donate_argnums/donate_argnames keywords.  Returns
    {call-node: (argnums tuple|None, argnames tuple|None)}."""
    out = {}
    for call in mod.calls():
        dn = mod.call_name(call)
        if dn is None or dn.rsplit(".", 1)[-1] not in ("jit", "pjit"):
            continue
        nums = names = None
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                nums = _int_tuple(kw.value)
            elif kw.arg == "donate_argnames":
                names = _str_tuple(kw.value)
        if nums is not None or names is not None:
            out[id(call)] = (call, nums, names)
    return out


def _int_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _pos(node):
    return (node.lineno, node.col_offset)


def _scope_of(mod, node):
    fn = mod.enclosing_function(node)
    return fn if fn is not None else mod.tree


def _donated_names(call: ast.Call, nums, names):
    out = []
    for i in (nums or ()):
        if 0 <= i < len(call.args) \
                and isinstance(call.args[i], ast.Name):
            out.append(call.args[i].id)
    for nm in (names or ()):
        for kw in call.keywords:
            if kw.arg == nm and isinstance(kw.value, ast.Name):
                out.append(kw.value.id)
    return out


@rule("donation",
      "a name passed in a donated argument position "
      "(donate_argnums/donate_argnames) must not be read after the "
      "donating call in the same scope — the buffer may be aliased "
      "into the output (use-after-donation)")
def check_donation(index, mod):
    donors = _donating_jit_calls(mod)
    if not donors:
        return
    # donating-callable bindings: g = jit(f, donate_argnums=...) binds
    # g in its scope; every later g(...) in that scope donates
    bindings = {}           # (scope-id, name) -> (nums, names)
    for _, (call, nums, names) in donors.items():
        parent = mod.parent.get(id(call))
        if isinstance(parent, ast.Assign) and parent.value is call:
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    scope = _scope_of(mod, parent)
                    bindings[(id(scope), t.id)] = (nums, names)
    # donating CALL SITES: bound-name invocations + immediate
    # jit(...)(x) invocations
    sites = []              # (scope-node, call-node, donated-names)
    for call in mod.calls():
        if isinstance(call.func, ast.Name):
            scope = _scope_of(mod, call)
            # a donating callable bound at module level (the common
            # layout) donates at call sites in ANY function scope
            spec = bindings.get((id(scope), call.func.id)) \
                or bindings.get((id(mod.tree), call.func.id))
            if spec is not None:
                donated = _donated_names(call, *spec)
                if donated:
                    sites.append((scope, call, donated))
        elif isinstance(call.func, ast.Call) \
                and id(call.func) in donors:
            _, nums, names = donors[id(call.func)]
            donated = _donated_names(call, nums, names)
            if donated:
                sites.append((_scope_of(mod, call), call, donated))
    for scope, call, donated in sites:
        # linear event scan over the scope: after the donating call,
        # the first event per donated name decides (Store = rebound,
        # fine — the x = g(x) double-buffer idiom; Load = finding).
        call_end = (getattr(call, "end_lineno", call.lineno),
                    getattr(call, "end_col_offset", call.col_offset))
        events = []
        for n in ast.walk(scope):
            if isinstance(n, ast.Name) and n.id in donated \
                    and n is not call.func:
                events.append((_pos(n), n))
        # the assignment receiving the call's result rebinds its
        # targets AFTER the call evaluates, whatever their column —
        # including tuple-unpack targets (x, y = g(x, y), the
        # multi-buffer rebind idiom)
        parent = mod.parent.get(id(call))
        rebound_by_assign = set()
        if isinstance(parent, ast.Assign) and parent.value is call:
            for t in parent.targets:
                for tn in ast.walk(t):
                    if isinstance(tn, ast.Name):
                        rebound_by_assign.add(id(tn))
        pending = set(donated)
        for pos, n in sorted(events, key=lambda e: e[0]):
            if pos <= call_end and id(n) not in rebound_by_assign:
                continue
            if n.id not in pending:
                continue
            if isinstance(n.ctx, ast.Store) or id(n) in rebound_by_assign:
                pending.discard(n.id)
            elif isinstance(n.ctx, ast.Load):
                pending.discard(n.id)
                yield (pos[0],
                       f"{n.id!r} read after being donated at line "
                       f"{call.lineno} — the donated buffer may be "
                       "aliased into the output; rebind the result "
                       "(x = g(x)) or drop the donation")
