"""Rule registry, findings, suppression semantics, and the runner.

One engine, N passes (ISSUE 14 tentpole): every pass registers itself
with :func:`rule` and receives the SHARED parsed index — the package is
parsed once per process however many rules run (the six legacy lints
each paid their own full walk).  Findings are typed, suppressible in
source with a mandatory reason::

    risky_line()  # quda-lint: disable=<rule>  reason=<why it is safe>

and the run exits clean only when zero UNSUPPRESSED findings remain —
the static analog of the reference's check_params.h generated
init/check/print discipline: invariants enforced by tooling, not
review.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, Iterable, List, Optional

from .index import Index, Mod, index_for, package_index


@dataclasses.dataclass
class Finding:
    rule: str
    path: str                  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    reason: str = ""           # the suppression's mandatory reason

    def render(self) -> str:
        tag = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class Rule:
    name: str
    doc: str
    check_module: Optional[Callable[[Index, Mod], Iterable]] = None
    check_package: Optional[Callable[[Index], Iterable]] = None


RULES: Dict[str, Rule] = {}


def rule(name: str, doc: str):
    """Register a pass's per-module checker; attach a whole-package
    checker afterwards via :func:`package_check`.  Checkers yield
    ``(line, message)`` (per-module) or ``(rel, line, message)``
    (package) tuples; the engine owns Finding construction and
    suppression."""
    def deco(fn):
        RULES[name] = Rule(name=name, doc=doc, check_module=fn)
        return fn
    return deco


def package_check(name: str):
    def deco(fn):
        RULES[name].check_package = fn
        return fn
    return deco


@dataclasses.dataclass
class Result:
    findings: List[Finding]
    rules: List[str]
    n_modules: int

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def by_rule(self, name: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == name]

    def counts(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {
            r: {"unsuppressed": 0, "suppressed": 0} for r in self.rules}
        for f in self.findings:
            out.setdefault(f.rule, {"unsuppressed": 0, "suppressed": 0})[
                "suppressed" if f.suppressed else "unsuppressed"] += 1
        return out

    @property
    def ok(self) -> bool:
        return not self.unsuppressed


# suppression-hygiene is itself a pass: a disable without a reason, or
# naming a rule the registry does not know, is the typoed-env-knob
# failure mode (silently doing nothing) applied to the linter itself.
SUPPRESSION_RULE = "suppression-hygiene"


def _check_suppressions(index: Index, mod: Mod):
    for sup in mod.bad_suppressions:
        yield (sup.src_line,
               f"suppression without a reason: disable="
               f"{','.join(sorted(sup.rules))} — the reason is mandatory "
               "(reason=<why this finding is intentional>)")
    for sups in mod.suppressions.values():
        for sup in sups:
            for r in sorted(sup.rules):
                if r not in RULES:
                    yield (sup.src_line,
                           f"suppression names unknown rule {r!r} "
                           f"(known: {sorted(RULES)}) — a typoed "
                           "disable silently suppresses nothing")


def _register_builtin():
    if SUPPRESSION_RULE not in RULES:
        RULES[SUPPRESSION_RULE] = Rule(
            SUPPRESSION_RULE,
            "every quda-lint disable carries a reason and names a "
            "registered rule",
            check_module=_check_suppressions)


def _load_passes():
    """Import the pass modules (registration side effect), once."""
    _register_builtin()
    from . import rules_jax, rules_legacy, rules_locks  # noqa: F401


def _mk_finding(index: Index, name: str, rel: str, line: int,
                msg: str) -> Finding:
    f = Finding(rule=name, path=rel, line=int(line), message=msg)
    mod = index.get(rel)
    if mod is not None and name != SUPPRESSION_RULE:
        sup = mod.suppression_for(name, f.line)
        if sup is not None:
            f.suppressed, f.reason = True, sup.reason
    return f


def run(index: Optional[Index] = None, rules: Optional[List[str]] = None,
        paths: Optional[List[str]] = None) -> Result:
    """Run the selected rules (default: all) over ``index`` /
    ``paths`` (default: the cached package index)."""
    _load_passes()
    if index is None:
        index = index_for(paths) if paths else package_index()
    selected = list(rules) if rules else sorted(RULES)
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        raise KeyError(f"unknown analysis rule(s) {unknown}; "
                       f"known: {sorted(RULES)}")
    findings: List[Finding] = []
    for name in selected:
        r = RULES[name]
        if r.check_module is not None:
            for mod in index.modules:
                for line, msg in r.check_module(index, mod):
                    findings.append(
                        _mk_finding(index, name, mod.rel, line, msg))
        if r.check_package is not None and index.is_package:
            for rel, line, msg in r.check_package(index):
                findings.append(_mk_finding(index, name, rel, line, msg))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return Result(findings=findings, rules=selected,
                  n_modules=len(index.modules))


# -- artifact rendering (bench_suite --artifacts-dir consumers) -------------

def render_tsv(result: Result) -> str:
    rows = ["rule\tpath\tline\tsuppressed\tmessage"]
    for f in result.findings:
        msg = f.message.replace("\t", " ").replace("\n", " ")
        rows.append(f"{f.rule}\t{f.path}\t{f.line}\t"
                    f"{int(f.suppressed)}\t{msg}")
    return "\n".join(rows) + "\n"


def render_json(result: Result) -> str:
    return json.dumps({
        "rules": {name: dict(cnt, doc=RULES[name].doc)
                  for name, cnt in result.counts().items()},
        "n_modules": result.n_modules,
        "ok": result.ok,
        "findings": [dataclasses.asdict(f) for f in result.findings],
    }, indent=1, sort_keys=True)
