"""Unified static analysis over the package's own source (ISSUE 14).

One AST parse, N registered passes, typed findings, mandatory-reason
suppressions — the TPU-native analog of the reference enforcing its
invariants statically (``check_params.h`` generating init/check/print
for every param struct).  Surfaces:

* ``python -m quda_tpu.analysis [--rules ...] [--tsv P] [--json P]`` —
  CLI; exit 0 iff zero unsuppressed findings;
* ``tests/test_analysis.py`` — one parametrized tier-1 test per rule;
* the six legacy lint tests — thin wrappers over the migrated passes,
  sharing this module's single parse;
* ``bench_suite --artifacts-dir`` — ``analysis.tsv``/``analysis.json``
  indexed into ``artifacts_manifest.json``, finding counts per rule on
  the fleet report.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .engine import (Finding, Result, RULES, render_json, render_tsv,
                     run)
from .index import index_for, package_index
from .index import reset_package_index as _reset_index

__all__ = ["Finding", "Result", "RULES", "run", "run_package",
           "render_tsv", "render_json", "rule_names", "save_artifacts",
           "emit_metrics", "index_for", "package_index",
           "reset_package_index"]

_PACKAGE_RESULT: Optional[Result] = None


def run_package(refresh: bool = False) -> Result:
    """The full-rule run over the cached package index, itself cached:
    the parametrized per-rule tests and the six legacy wrappers all
    share ONE parse and ONE pass execution per process."""
    global _PACKAGE_RESULT
    if _PACKAGE_RESULT is None or refresh:
        _PACKAGE_RESULT = run()
    return _PACKAGE_RESULT


def reset_package_index():
    """Drop BOTH caches — the parsed index and the full-run result —
    so a process that edited sources on disk re-analyzes them (the two
    caches are a matched pair; clearing one alone serves stale
    findings)."""
    global _PACKAGE_RESULT
    _PACKAGE_RESULT = None
    _reset_index()


def rule_names() -> List[str]:
    from .engine import _load_passes
    _load_passes()
    return sorted(RULES)


def save_artifacts(result: Result, directory: str,
                   tsv: str = "analysis.tsv",
                   json_name: str = "analysis.json") -> dict:
    """Write analysis.tsv / analysis.json under ``directory`` (the
    bench_suite --artifacts-dir exporter); returns {name: path}."""
    os.makedirs(directory, exist_ok=True)
    tsv_path = os.path.join(directory, tsv)
    json_path = os.path.join(directory, json_name)
    with open(tsv_path, "w") as fh:
        fh.write(render_tsv(result))
    with open(json_path, "w") as fh:
        fh.write(render_json(result))
    return {tsv: tsv_path, json_name: json_path}


def emit_metrics(result: Result):
    """Mirror per-rule finding counts into the metrics registry (no-op
    when metrics are off) — the fleet report's Static analysis line."""
    from ..obs import metrics as omet
    for name, cnt in result.counts().items():
        omet.set_gauge("analysis_findings", cnt["unsuppressed"],
                       rule=name, status="unsuppressed")
        omet.set_gauge("analysis_findings", cnt["suppressed"],
                       rule=name, status="suppressed")
