"""Shared module index: every source file parsed ONCE for all passes.

The six pre-round-17 ad-hoc lints each re-walked the package with their
own ``os.walk`` + ``ast.parse`` loop — six full parses of ~100 files per
tier-1 run, and none of the walkers shared import resolution or source
spans.  This module is the single home for that machinery (the analog of
the reference generating init/check/print once per param struct from one
``check_params.h`` parse): a :class:`Mod` per file carrying the AST, a
flat node list, a parent map, alias-resolved imports, and the per-line
suppression table; an :class:`Index` over all of them; and a cached
:func:`package_index` every pass (and every thin lint-test wrapper)
shares.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

# suppression syntax (reason MANDATORY — enforced by the
# suppression-hygiene rule, engine.py):
#   <statement>  # quda-lint: disable=<rule>[,<rule>...]  reason=<text>
# A comment-only line targets the NEXT physical line instead of its own.
_SUPPRESS_RE = re.compile(
    r"#\s*quda-lint:\s*disable=([A-Za-z0-9_,-]+)"
    r"(?:\s+reason=(.+?))?\s*$")


class Suppression:
    __slots__ = ("rules", "reason", "src_line", "target_line")

    def __init__(self, rules, reason, src_line, target_line):
        self.rules = frozenset(rules)
        self.reason = (reason or "").strip()
        self.src_line = src_line
        self.target_line = target_line


class Mod:
    """One parsed source file + the derived tables every pass shares."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel                      # repo-relative, '/'-separated
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # flat node list + parent map: passes iterate/lookup instead of
        # re-walking (ast.walk allocates a fresh BFS per call)
        self.nodes: List[ast.AST] = list(ast.walk(self.tree))
        self.parent: Dict[int, ast.AST] = {}
        for node in self.nodes:
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node
        # package parts of the CONTAINING package ("quda_tpu/obs/x.py"
        # -> ("quda_tpu", "obs")) for relative-import resolution
        parts = rel.split("/")
        self.pkg_parts: Tuple[str, ...] = tuple(parts[:-1])
        self.imports: Dict[str, str] = self._resolve_imports()
        self.suppressions: Dict[int, List[Suppression]] = {}
        self.bad_suppressions: List[Suppression] = []
        self._scan_suppressions()

    # -- imports ------------------------------------------------------------

    def _resolve_imports(self) -> Dict[str, str]:
        """alias -> fully dotted target ('qconf' ->
        'quda_tpu.utils.config', 'perf_counter' -> 'time.perf_counter')."""
        out: Dict[str, str] = {}
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        out[head] = head
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    out[a.asname or a.name] = target
        return out

    def _from_base(self, node: ast.ImportFrom) -> str:
        if not node.level:
            return node.module or ""
        # relative: strip (level - 1) packages off this module's package
        keep = len(self.pkg_parts) - (node.level - 1)
        parts = list(self.pkg_parts[:max(0, keep)])
        if node.module:
            parts += node.module.split(".")
        return ".".join(parts)

    # -- dotted-name resolution ---------------------------------------------

    def dotted(self, node) -> Optional[str]:
        """Fully-resolved dotted name of a Name/Attribute chain, alias
        expansion applied to the base ('otr.event' ->
        'quda_tpu.obs.trace.event'); None for non-name bases."""
        chain: List[str] = []
        while isinstance(node, ast.Attribute):
            chain.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.imports.get(node.id, node.id)
        return ".".join([base] + list(reversed(chain)))

    def call_name(self, call: ast.Call) -> Optional[str]:
        return self.dotted(call.func)

    @staticmethod
    def last_name(node) -> str:
        """Terminal identifier of a call target (the legacy lints'
        'attr or id' idiom)."""
        return getattr(node, "attr", None) or getattr(node, "id", "")

    # -- structural helpers -------------------------------------------------

    def calls(self) -> Iterable[ast.Call]:
        return (n for n in self.nodes if isinstance(n, ast.Call))

    def functions(self) -> Iterable[ast.FunctionDef]:
        return (n for n in self.nodes
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)))

    def enclosing_function(self, node) -> Optional[ast.FunctionDef]:
        cur = self.parent.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parent.get(id(cur))
        return None

    def ancestors(self, node) -> Iterable[ast.AST]:
        cur = self.parent.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parent.get(id(cur))

    def line_of(self, needle: str, default: int = 1) -> int:
        """1-based line of the first occurrence of ``needle`` (anchor
        for registry-shaped findings: schema names, knob names)."""
        for i, line in enumerate(self.lines, 1):
            if needle in line:
                return i
        return default

    # -- suppressions -------------------------------------------------------

    def _scan_suppressions(self):
        for i, line in enumerate(self.lines, 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = [r for r in m.group(1).split(",") if r]
            comment_only = line.strip().startswith("#")
            target = i + 1 if comment_only else i
            sup = Suppression(rules, m.group(2), i, target)
            self.suppressions.setdefault(target, []).append(sup)
            if not sup.reason:
                self.bad_suppressions.append(sup)

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        for sup in self.suppressions.get(line, ()):
            if rule in sup.rules:
                return sup
        return None


class Index:
    """All modules of one analysis run (the package, or explicit
    files)."""

    def __init__(self, modules: List[Mod], root: str, is_package: bool):
        self.modules = modules
        self.root = root
        self.is_package = is_package
        self.by_rel: Dict[str, Mod] = {m.rel: m for m in modules}

    def get(self, rel: str) -> Optional[Mod]:
        return self.by_rel.get(rel)


def _package_root() -> str:
    import quda_tpu
    return os.path.dirname(os.path.dirname(os.path.abspath(
        quda_tpu.__file__)))


def _load(path: str, root: str) -> Mod:
    rel = os.path.relpath(os.path.abspath(path), root)
    if rel.startswith(".."):
        rel = os.path.abspath(path)
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    return Mod(os.path.abspath(path), rel.replace(os.sep, "/"), text)


def build_package_index() -> Index:
    """Parse the whole surface the legacy lints covered — the package
    plus the repo-root bench harnesses — once."""
    root = _package_root()
    pkg = os.path.join(root, "quda_tpu")
    paths = [os.path.join(root, f) for f in ("bench.py", "bench_suite.py")
             if os.path.exists(os.path.join(root, f))]
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        paths += [os.path.join(dirpath, f) for f in sorted(filenames)
                  if f.endswith(".py")]
    return Index([_load(p, root) for p in sorted(paths)], root,
                 is_package=True)


_PACKAGE_INDEX: Optional[Index] = None


def package_index() -> Index:
    """The cached shared index (ONE parse per process for the engine,
    every registered pass, and every thin lint-test wrapper)."""
    global _PACKAGE_INDEX
    if _PACKAGE_INDEX is None:
        _PACKAGE_INDEX = build_package_index()
    return _PACKAGE_INDEX


def reset_package_index():
    """Drop the cache (tests that edit sources on disk)."""
    global _PACKAGE_INDEX
    _PACKAGE_INDEX = None


def index_for(paths: Iterable[str]) -> Index:
    """An index over explicit files (fixture runs, CLI --paths).  Repo
    pins (seam-coverage, API-guard checks) are skipped: only the
    file-local halves of each pass apply."""
    root = _package_root()
    return Index([_load(p, root) for p in paths], root, is_package=False)
