"""Lock-discipline and off-path-purity passes — the concurrency rules.

* **lock-discipline** — the PR 9 device-high-water race, generalized:
  a module-level mutable container in ``obs/``/``serve/`` is shared
  state (the monitor thread, the solve-service worker thread, and the
  caller all run concurrently); any PUBLIC function mutating one must
  do so under a ``with <lock>`` block.  Private (``_``-prefixed)
  helpers are presumed called under their caller's lock, and
  import-time initialisation is single-threaded — both exempt.
* **off-path-purity** — the static twin of the raising-stub runtime
  tests: every emission entry point of the observability modules
  (``obs/trace``, ``obs/metrics``, ``obs/comms``, ``obs/flight``) must
  follow the documented one-global-load gate (``s = _session`` /
  ``if s is None: return``), and nothing outside those modules may
  reach around the gate via ``<mod>._session`` — otherwise an
  "off means off" knob stops meaning off.
"""

from __future__ import annotations

import ast

from .engine import package_check, rule

_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "popleft", "appendleft", "remove",
             "discard", "clear"}
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}


def _module_containers(mod) -> set:
    """Names bound at module level to mutable containers."""
    out = set()
    for node in mod.tree.body:
        targets, value = (), None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = (node.target,), node.value
        if value is None:
            continue
        is_container = isinstance(value, (ast.Dict, ast.List, ast.Set)) \
            or (isinstance(value, ast.Call)
                and mod.last_name(value.func) in _CONTAINER_CTORS)
        if is_container:
            out.update(t.id for t in targets if isinstance(t, ast.Name))
    return out


def _lockish(expr) -> bool:
    """A with-item that names a lock (module _lock, self.lock, ...)."""
    name = (getattr(expr, "attr", None) or getattr(expr, "id", "") or "")
    return "lock" in name.lower()


def _under_lock(mod, node) -> bool:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            if any(_lockish(item.context_expr) for item in anc.items):
                return True
    return False


def _lock_scope(mod) -> bool:
    parts = mod.rel.split("/")[:-1]
    return "obs" in parts or "serve" in parts


def _mutation_sites(mod, containers):
    """(node, name, how) for each mutation of a module-level
    container."""
    for node in mod.nodes:
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in containers \
                and node.func.attr in _MUTATORS:
            yield node, node.func.value.id, f".{node.func.attr}()"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in containers:
                    yield node, t.value.id, "[...] assignment"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in containers:
                    yield node, t.value.id, "del [...]"


@rule("lock-discipline",
      "module-level mutable containers in obs/ and serve/ mutated by a "
      "public function must be written under a `with <lock>` block "
      "(the PR 9 high-water race class; `_`-helpers and import-time "
      "init exempt)")
def check_lock_discipline(index, mod):
    if not _lock_scope(mod):
        return
    containers = _module_containers(mod)
    if not containers:
        return
    for node, name, how in _mutation_sites(mod, containers):
        # the exemption keys on the OUTERMOST enclosing function: a
        # mutation inside a `_`-named closure nested in a public entry
        # point still runs on the public path (the comms.scope _ctx
        # shape) — only a top-level private helper is presumed called
        # under its caller's lock
        outer = None
        fn = mod.enclosing_function(node)
        cur = fn
        while cur is not None:
            outer = cur
            cur = mod.enclosing_function(cur)
        if outer is None or outer.name.startswith("_"):
            continue
        if _under_lock(mod, node):
            continue
        yield (node.lineno,
               f"module-level container {name!r} mutated ({how}) in "
               f"{fn.name}() outside any `with <lock>` block — "
               "monitor/serve threads share this state; a lost update "
               "here corrupts the fleet report")


# -- off-path purity --------------------------------------------------------

# the gated observability modules and their emission entry points (the
# functions the raising-stub tests pin at runtime)
_GATED = {
    "quda_tpu/obs/trace.py": ("span", "event"),
    "quda_tpu/obs/metrics.py": ("inc", "set_gauge", "observe",
                                "record_execution"),
    "quda_tpu/obs/comms.py": ("scope", "record_exchange",
                              "record_replication", "attribute_solve"),
    "quda_tpu/obs/flight.py": ("record",),
}


def _defines_session(mod) -> bool:
    for node in mod.tree.body:
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "_session"
                   for t in node.targets):
                return True
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.target.id == "_session":
            return True
    return False


def _declares_global_session(fn) -> bool:
    return any(isinstance(n, ast.Global) and "_session" in n.names
               for n in ast.walk(fn))


def _session_locals(fn) -> set:
    """Local names assigned from ``_session`` (or ``<mod>._session``)."""
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name):
            v = n.value
            if (isinstance(v, ast.Name) and v.id == "_session") \
                    or (isinstance(v, ast.Attribute)
                        and v.attr == "_session"):
                out.add(n.targets[0].id)
    return out


def _none_checked(fn, names) -> set:
    """Which of ``names`` are None-compared somewhere in ``fn``."""
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Compare) \
                and isinstance(n.left, ast.Name) \
                and n.left.id in names \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in n.comparators):
            out.add(n.left.id)
    return out


def _session_functions(mod):
    """Top-level functions and methods, innermost-def granularity."""
    return mod.functions()


@rule("off-path-purity",
      "emission sites in session-gated modules follow the "
      "one-global-load gate (s = _session; if s is None: return) and "
      "nothing reaches around it via <mod>._session — the static twin "
      "of the raising-stub 'off means off' tests")
def check_off_path_purity(index, mod):
    in_obs = mod.rel.startswith("quda_tpu/obs/")
    if _defines_session(mod):
        for fn in _session_functions(mod):
            if _declares_global_session(fn):
                continue          # lifecycle (start/stop) owns the global
            # 1) direct use of the global: attribute/subscript/call on
            #    the bare Name `_session` (compare-to-None reads and
            #    plain boolean returns are the allowed predicates)
            for n in ast.walk(fn):
                target = None
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "_session":
                    target = n
                elif isinstance(n, ast.Subscript) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "_session":
                    target = n
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id == "_session":
                    target = n
                if target is not None:
                    yield (target.lineno,
                           f"{fn.name}() uses the module global "
                           "_session directly — load it into a local "
                           "ONCE and None-check it (the one-global-"
                           "load gate); a second read can observe a "
                           "session stopped mid-call")
            # 2) gate completeness: a local loaded from _session that
            #    feeds real work must be None-checked in this function
            locs = _session_locals(fn)
            if not locs:
                continue
            checked = _none_checked(fn, locs)
            unchecked = locs - checked
            if unchecked and any(isinstance(n, ast.Call)
                                 for n in ast.walk(fn)):
                yield (fn.lineno,
                       f"{fn.name}() loads {sorted(unchecked)} from "
                       "_session but never None-checks it — the off "
                       "path would raise AttributeError instead of "
                       "being a no-op (gate incomplete)")
    # 3) nothing outside the gated family reaches around the gate
    if not in_obs:
        for n in mod.nodes:
            if isinstance(n, ast.Attribute) and n.attr == "_session" \
                    and isinstance(n.value, ast.Name):
                yield (n.lineno,
                       "reaching into an observability module's "
                       "_session from outside obs/ bypasses the "
                       "one-global-load gate — call the module's "
                       "public entry points instead")


@package_check("off-path-purity")
def check_purity_pins(index):
    """The named emission entry points exist and read the gate — a
    rename or a gate removal fails here even before the runtime
    raising-stub tests run."""
    for rel, funcs in _GATED.items():
        mod = index.get(rel)
        if mod is None:
            yield (rel, 1, "gated observability module missing from "
                           "the package index")
            continue
        # module-LEVEL functions only: _Registry.inc (a method) must
        # not shadow the gated module function inc()
        by_name = {f.name: f for f in mod.tree.body
                   if isinstance(f, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        for name in funcs:
            fn = by_name.get(name)
            if fn is None:
                yield (rel, 1,
                       f"emission entry point {name}() not found — "
                       "the raising-stub tests and every instrumented "
                       "call site pin this name")
                continue
            reads = any(isinstance(n, ast.Name) and n.id == "_session"
                        for n in ast.walk(fn)) \
                or any(isinstance(n, ast.Attribute)
                       and n.attr == "_session"
                       for n in ast.walk(fn))
            if not reads:
                yield (rel, fn.lineno,
                       f"emission entry point {name}() never reads "
                       "_session — the one-global-load off gate is "
                       "gone")
