"""Verbosity-laddered, process-gated logging with prefix push/pop.

Reference behavior: lib/util_quda.cpp / include/util_quda.h — QudaVerbosity
ladder (SILENT..DEBUG_VERBOSE), rank-0-gated printfQuda, setOutputPrefix /
pushOutputPrefix, errorQuda aborting with file:line.
"""

from __future__ import annotations

import os
import sys
import time
from contextlib import contextmanager

SILENT = 0
SUMMARIZE = 1
VERBOSE = 2
DEBUG_VERBOSE = 3

_LEVELS = {"silent": SILENT, "summarize": SUMMARIZE, "verbose": VERBOSE,
           "debug": DEBUG_VERBOSE}

def _initial_state():
    # read through the central registry (utils/config.py) so the knobs
    # are documented and validated in one place — but never let a bad
    # value break `import quda_tpu`: fall back to defaults here and let
    # config.check_environment() report the problem at init_quda time
    from . import config as qconf

    def safe(name, default):
        try:
            return qconf.get(name)
        except ValueError:
            return default

    return {
        "verbosity": _LEVELS.get(safe("QUDA_TPU_VERBOSITY", "summarize"),
                                 SUMMARIZE),
        "prefix": ["quda_tpu: "],
        "rank": safe("QUDA_TPU_PROCESS_INDEX", 0),
        "rank_verbosity_all":
            safe("QUDA_TPU_RANK_VERBOSITY", "0") == "all",
    }


_state = _initial_state()


def set_verbosity(level):
    _state["verbosity"] = _LEVELS[level] if isinstance(level, str) else level


def get_verbosity() -> int:
    return _state["verbosity"]


@contextmanager
def push_verbosity(level):
    old = _state["verbosity"]
    set_verbosity(level)
    try:
        yield
    finally:
        _state["verbosity"] = old


@contextmanager
def push_prefix(prefix: str):
    _state["prefix"].append(prefix)
    try:
        yield
    finally:
        _state["prefix"].pop()


def _emit(msg: str):
    if _state["rank"] == 0 or _state["rank_verbosity_all"]:
        sys.stderr.write(_state["prefix"][-1] + msg + "\n")


def printq(msg: str, level: int = SUMMARIZE):
    """printfQuda analog: emitted when verbosity >= level on rank 0."""
    if _state["verbosity"] >= level:
        _emit(msg)


def warningq(msg: str):
    if _state["verbosity"] >= SUMMARIZE:
        _emit("WARNING: " + msg)


_warned_once: set = set()


def warn_once(key: str, msg: str):
    """One-time warning per process per key (the unconverged-solve /
    degraded-race notices: loud the first time, not a log flood under
    serving traffic).  Returns True iff the warning was emitted."""
    if key in _warned_once:
        return False
    _warned_once.add(key)
    warningq(msg)
    return True


class QudaError(RuntimeError):
    pass


def errorq(msg: str):
    """errorQuda analog: raise (single-process) instead of comm_abort."""
    _emit("ERROR: " + msg)
    raise QudaError(msg)
