"""Gauge-field site checksums (ILDG-compatible structure).

Reference behavior: lib/checksum.cu — per-site CRC32 of the link data,
combined with site-rank-dependent rotations into two 32-bit sums (the
ILDG scidac-checksum a/b pair).
"""

from __future__ import annotations

import zlib

import numpy as np


def site_crc_pair(site_rows: np.ndarray):
    """QIO/ILDG combination rule over per-site byte rows: (suma, sumb)
    with suma ^= rotl32(crc_r, r % 29), sumb ^= rotl32(crc_r, r % 31),
    r the lexicographic site rank (x fastest).  The single source of the
    rule — lime.py's scidac-checksum records use it too."""
    flat = np.ascontiguousarray(site_rows)
    suma = 0
    sumb = 0
    for rank in range(flat.shape[0]):
        crc = zlib.crc32(flat[rank].tobytes())
        r29 = rank % 29
        r31 = rank % 31
        suma ^= ((crc << r29) | (crc >> (32 - r29))) & 0xFFFFFFFF
        sumb ^= ((crc << r31) | (crc >> (32 - r31))) & 0xFFFFFFFF
    return suma & 0xFFFFFFFF, sumb & 0xFFFFFFFF


def gauge_checksum(gauge) -> dict:
    """ILDG-style (suma, sumb) over per-site CRC32s."""
    g = np.asarray(gauge)
    # site-major copy: (T,Z,Y,X, mu,3,3)
    site = np.ascontiguousarray(np.moveaxis(g, 0, 4))
    T, Z, Y, X = site.shape[:4]
    suma, sumb = site_crc_pair(site.reshape(T * Z * Y * X, -1))
    return {"suma": suma, "sumb": sumb}
