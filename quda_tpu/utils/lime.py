"""LIME / SciDAC / ILDG container I/O — format-true community interop.

Reference behavior: lib/qio_field.cpp:442 (QUDA delegates to the QIO/
c-lime libraries; this module implements the wire formats those libraries
produce so community gauge configurations round-trip):

* LIME record framing (c-lime): 144-byte big-endian header
  {u32 magic 0x456789ab, u16 version 1, u16 flags [bit15=MB, bit14=ME],
  u64 data_length, char type[128]}, data padded to 8 bytes.
* ILDG records: ``ildg-format`` XML (field/precision/lx..lt) +
  ``ildg-binary-data`` (site order t,z,y,x slowest->fastest; per site
  mu = x,y,z,t; row-major 3x3; big-endian IEEE float64/float32).
* SciDAC records: private/file/record XML + ``scidac-binary-data`` +
  ``scidac-checksum`` (QIO crc32 pair: per-site crc32 combined as
  suma ^= rotl(crc, rank % 29), sumb ^= rotl(crc, rank % 31), rank the
  lexicographic site rank, x fastest).
"""

from __future__ import annotations

import re
import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry

LIME_MAGIC = 0x456789AB
_HDR = struct.Struct(">IHHQ128s")


# -- record framing ---------------------------------------------------------

def write_lime(path: str, records: Sequence[Tuple[str, bytes]]):
    """Write (type, data) records; message flags mark the first record MB
    and the last ME (single-message layout, what QIO emits per file)."""
    with open(path, "wb") as fh:
        n = len(records)
        for i, (rtype, data) in enumerate(records):
            flags = 0
            if i == 0:
                flags |= 1 << 15        # MB
            if i == n - 1:
                flags |= 1 << 14        # ME
            fh.write(_HDR.pack(LIME_MAGIC, 1, flags, len(data),
                               rtype.encode()))
            fh.write(data)
            pad = (-len(data)) % 8
            fh.write(b"\0" * pad)


def read_lime(path: str) -> List[Tuple[str, bytes]]:
    out = []
    with open(path, "rb") as fh:
        while True:
            hdr = fh.read(144)
            if len(hdr) < 144:
                break
            magic, version, _flags, length, rtype = _HDR.unpack(hdr)
            if magic != LIME_MAGIC:
                raise IOError(f"bad LIME magic {magic:#x} in {path}")
            data = fh.read(length)
            if len(data) != length:
                raise IOError(f"truncated LIME record in {path}")
            fh.read((-length) % 8)
            out.append((rtype.split(b"\0", 1)[0].decode(), data))
    return out


def find_record(records, rtype: str) -> Optional[bytes]:
    for t, d in records:
        if t == rtype:
            return d
    return None


# -- scidac checksum --------------------------------------------------------

def scidac_checksum(site_major_bytes: np.ndarray) -> Tuple[int, int]:
    """QIO crc32 pair over per-site byte blocks.

    site_major_bytes: (volume, bytes_per_site) uint8, sites in
    lexicographic rank order (x fastest).  Delegates to the shared
    combiner in utils/checksum.py (one source of the rotation rule).
    """
    from .checksum import site_crc_pair
    return site_crc_pair(site_major_bytes)


# -- XML payloads -----------------------------------------------------------

def _ildg_format_xml(geom: LatticeGeometry, precision: int) -> bytes:
    X, Y, Z, T = geom.dims
    return (
        '<?xml version="1.0" encoding="UTF-8"?>'
        '<ildgFormat xmlns="http://www.lqcd.org/ildg" '
        'xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance">'
        "<version>1.0</version><field>su3gauge</field>"
        f"<precision>{precision}</precision>"
        f"<lx>{X}</lx><ly>{Y}</ly><lz>{Z}</lz><lt>{T}</lt>"
        "</ildgFormat>").encode()


def _scidac_private_file_xml(geom: LatticeGeometry) -> bytes:
    X, Y, Z, T = geom.dims
    return (
        '<?xml version="1.0" encoding="UTF-8"?><scidacFile>'
        "<version>1.1</version><spacetime>4</spacetime>"
        f"<dims>{X} {Y} {Z} {T} </dims><volfmt>0</volfmt>"
        "</scidacFile>").encode()


def _scidac_private_record_xml(datatype: str, precision: int, colors: int,
                               spins: int, typesize: int,
                               datacount: int) -> bytes:
    prec = {32: "F", 64: "D"}[precision]
    return (
        '<?xml version="1.0" encoding="UTF-8"?><scidacRecord>'
        "<version>1.1</version><date>now</date><recordtype>0</recordtype>"
        f"<datatype>{datatype}</datatype><precision>{prec}</precision>"
        f"<colors>{colors}</colors><spins>{spins}</spins>"
        f"<typesize>{typesize}</typesize><datacount>{datacount}</datacount>"
        "</scidacRecord>").encode()


def _checksum_xml(suma: int, sumb: int) -> bytes:
    return (
        '<?xml version="1.0" encoding="UTF-8"?><scidacChecksum>'
        f"<version>1.0</version><suma>{suma:x}</suma><sumb>{sumb:x}</sumb>"
        "</scidacChecksum>").encode()


def _xml_field(data: bytes, tag: str) -> Optional[str]:
    m = re.search(rf"<{tag}>\s*([^<]*?)\s*</{tag}>", data.decode())
    return m.group(1) if m else None


# -- gauge fields -----------------------------------------------------------

def _gauge_to_ildg_bytes(gauge, precision: int) -> np.ndarray:
    """(4,T,Z,Y,X,3,3) -> (volume, site_bytes) big-endian site-major."""
    g = np.asarray(gauge)
    site_major = np.moveaxis(g, 0, 4)        # (T,Z,Y,X,mu,3,3)
    dt = ">c16" if precision == 64 else ">c8"
    be = np.ascontiguousarray(site_major.astype(dt))
    vol = be.shape[0] * be.shape[1] * be.shape[2] * be.shape[3]
    return be.view(np.uint8).reshape(vol, -1)


def save_gauge_lime(path: str, gauge, geom: LatticeGeometry,
                    precision: int = 64):
    """Write a SciDAC/ILDG lime gauge file (the layout QIO's singlefile
    format produces: file XMLs, record XMLs, ildg-format, binary data,
    scidac-checksum)."""
    raw = _gauge_to_ildg_bytes(gauge, precision)
    suma, sumb = scidac_checksum(raw)
    typesize = 2 * 9 * (8 if precision == 64 else 4)
    records = [
        ("scidac-private-file-xml", _scidac_private_file_xml(geom)),
        ("scidac-file-xml", b"<?xml version=\"1.0\"?><title>quda_tpu"
         b" gauge configuration</title>"),
        ("scidac-private-record-xml", _scidac_private_record_xml(
            "QDP_D_ColorMatrix", precision, 3, 0, typesize, 4)),
        ("scidac-record-xml", b"<?xml version=\"1.0\"?><info />"),
        ("ildg-format", _ildg_format_xml(geom, precision)),
        ("ildg-binary-data", raw.tobytes()),
        ("scidac-checksum", _checksum_xml(suma, sumb)),
    ]
    write_lime(path, records)


def load_gauge_lime(path: str, verify: bool = True):
    """Read an ILDG/SciDAC lime gauge file -> ((4,T,Z,Y,X,3,3), meta).

    Accepts files written by this module or by QIO-based tools (reads
    ildg-format for geometry/precision; falls back to scidac records)."""
    records = read_lime(path)
    fmt = find_record(records, "ildg-format")
    data = find_record(records, "ildg-binary-data")
    if data is None:
        data = find_record(records, "scidac-binary-data")
    if data is None:
        raise IOError(f"no binary data record in {path}")
    if fmt is not None:
        precision = int(_xml_field(fmt, "precision"))
        dims = tuple(int(_xml_field(fmt, k)) for k in ("lx", "ly", "lz",
                                                       "lt"))
    else:
        pf = find_record(records, "scidac-private-file-xml")
        dims = tuple(int(v) for v in _xml_field(pf, "dims").split())
        pr = find_record(records, "scidac-private-record-xml")
        precision = 64 if (_xml_field(pr, "precision") or "D") == "D" else 32
    geom = LatticeGeometry(dims)
    dt = ">c16" if precision == 64 else ">c8"
    arr = np.frombuffer(data, dtype=dt, count=geom.volume * 4 * 9)
    site_major = arr.reshape(geom.lattice_shape + (4, 3, 3))
    meta = {"dims": dims, "precision": precision}
    if verify:
        ck = find_record(records, "scidac-checksum")
        if ck is not None:
            raw = np.frombuffer(data, np.uint8).reshape(geom.volume, -1)
            suma, sumb = scidac_checksum(raw)
            want_a = int(_xml_field(ck, "suma"), 16)
            want_b = int(_xml_field(ck, "sumb"), 16)
            if (suma, sumb) != (want_a, want_b):
                raise IOError(
                    f"scidac checksum mismatch in {path}: "
                    f"{suma:x}/{sumb:x} != {want_a:x}/{want_b:x}")
            meta["checksum"] = (suma, sumb)
    gauge = jnp.asarray(
        np.moveaxis(site_major.astype(np.complex128), 4, 0))
    return gauge, meta


# -- color-spinor (propagator) fields --------------------------------------

def save_spinor_lime(path: str, psi, geom: LatticeGeometry,
                     precision: int = 64):
    """SciDAC lime file for a (T,Z,Y,X,4,3) Dirac field
    (scidac-binary-data in site-major spin-color order)."""
    a = np.asarray(psi)
    dt = ">c16" if precision == 64 else ">c8"
    be = np.ascontiguousarray(a.astype(dt))
    raw = be.view(np.uint8).reshape(geom.volume, -1)
    suma, sumb = scidac_checksum(raw)
    typesize = 2 * 12 * (8 if precision == 64 else 4)
    records = [
        ("scidac-private-file-xml", _scidac_private_file_xml(geom)),
        ("scidac-file-xml", b"<?xml version=\"1.0\"?><title>quda_tpu"
         b" dirac field</title>"),
        ("scidac-private-record-xml", _scidac_private_record_xml(
            "QDP_D_DiracFermion", precision, 3, 4, typesize, 1)),
        ("scidac-record-xml", b"<?xml version=\"1.0\"?><info />"),
        ("scidac-binary-data", raw.tobytes()),
        ("scidac-checksum", _checksum_xml(suma, sumb)),
    ]
    write_lime(path, records)


def load_spinor_lime(path: str, verify: bool = True):
    records = read_lime(path)
    data = find_record(records, "scidac-binary-data")
    if data is None:
        raise IOError(f"no scidac-binary-data record in {path}")
    pf = find_record(records, "scidac-private-file-xml")
    pr = find_record(records, "scidac-private-record-xml")
    if pf is None or pr is None:
        raise IOError(f"missing scidac file/record XML in {path}")
    dims = tuple(int(v) for v in _xml_field(pf, "dims").split())
    precision = 64 if (_xml_field(pr, "precision") or "D") == "D" else 32
    spins = int(_xml_field(pr, "spins") or 4)
    geom = LatticeGeometry(dims)
    dt = ">c16" if precision == 64 else ">c8"
    arr = np.frombuffer(data, dtype=dt, count=geom.volume * spins * 3)
    psi = arr.reshape(geom.lattice_shape + (spins, 3))
    if verify:
        ck = find_record(records, "scidac-checksum")
        if ck is not None:
            raw = np.frombuffer(data, np.uint8).reshape(geom.volume, -1)
            suma, sumb = scidac_checksum(raw)
            if (suma, sumb) != (int(_xml_field(ck, "suma"), 16),
                                int(_xml_field(ck, "sumb"), 16)):
                raise IOError(f"scidac checksum mismatch in {path}")
    return jnp.asarray(psi.astype(np.complex128)), {
        "dims": dims, "precision": precision, "spins": spins}
