"""TimeProfile: named, categorised, nestable timers with a global stack.

Reference behavior: include/timer.h / lib/timer.cpp — TimeProfile with
~30 QudaProfileType categories, pushProfile RAII, device timers via event
pairs, and the endQuda summary print.  Device timing here wraps
block_until_ready around the timed region (XLA's async dispatch plays the
role of CUDA streams).
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, List, Optional

# QudaProfileType analog
CATEGORIES = (
    "init", "download", "upload", "compute", "comms", "epilogue", "free",
    "io", "chrono", "eigen", "tune", "setup", "preamble", "total",
)


class TimeProfile:
    def __init__(self, name: str):
        self.name = name
        self.seconds: Dict[str, float] = defaultdict(float)
        self.count: Dict[str, int] = defaultdict(int)
        # per-category stack of open start times: nested same-category
        # spans each keep their own interval (a plain dict dropped the
        # outer interval on re-entrant start, losing its time entirely)
        self._open: Dict[str, List[float]] = {}

    def start(self, category: str = "total"):
        self._open.setdefault(category, []).append(time.perf_counter())

    def stop(self, category: str = "total", sync=None):
        if sync is not None:
            sync.block_until_ready()
        stack = self._open.get(category)
        if not stack:
            return          # unmatched stop stays a no-op
        t0 = stack.pop()
        self.seconds[category] += time.perf_counter() - t0
        self.count[category] += 1

    @contextmanager
    def __call__(self, category: str = "total"):
        self.start(category)
        try:
            yield
        finally:
            self.stop(category)

    def summary(self) -> str:
        lines = [f"TimeProfile [{self.name}]"]
        for cat in sorted(self.seconds, key=lambda c: -self.seconds[c]):
            lines.append(f"  {cat:>10}: {self.seconds[cat]:10.4f} s"
                         f"  ({self.count[cat]} calls)")
        return "\n".join(lines)


_profiles: Dict[str, TimeProfile] = {}
_stack: List[TimeProfile] = []


def get_profile(name: str) -> TimeProfile:
    if name not in _profiles:
        _profiles[name] = TimeProfile(name)
    return _profiles[name]


def _profiling_enabled() -> bool:
    from . import config as qconf
    return not qconf.get("QUDA_TPU_DO_NOT_PROFILE", fresh=True)


@contextmanager
def push_profile(name: str, category: str = "total"):
    """pushProfile RAII analog (timer.h:243); a no-op under
    QUDA_TPU_DO_NOT_PROFILE (reference: QUDA_DO_NOT_PROFILE)."""
    if not _profiling_enabled():
        yield None
        return
    prof = get_profile(name)
    _stack.append(prof)
    prof.start(category)
    try:
        yield prof
    finally:
        prof.stop(category)
        _stack.pop()


def current_profile() -> Optional[TimeProfile]:
    return _stack[-1] if _stack else None


def print_summary():
    from .logging import printq
    for prof in _profiles.values():
        printq(prof.summary())
    save_profiles()


def save_profiles():
    """Dump per-profile summaries as <QUDA_TPU_PROFILE_OUTPUT_BASE>.tsv
    under the resource path (reference: QUDA_PROFILE_OUTPUT_BASE tsv
    dumps in lib/tune.cpp)."""
    from . import config as qconf
    path = qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
    if not path or not _profiles:
        return
    base = qconf.get("QUDA_TPU_PROFILE_OUTPUT_BASE", fresh=True)
    import os
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, f"{base}.tsv"), "w") as fh:
        fh.write("profile\tcategory\tseconds\tcount\n")
        for prof in _profiles.values():
            for cat, t in sorted(prof.seconds.items()):
                fh.write(f"{prof.name}\t{cat}\t{t:.6f}\t"
                         f"{prof.count.get(cat, 0)}\n")


# global flop/byte counters (Tunable::flops_global analog, lib/tune.cpp)
_counters = {"flops": 0.0, "bytes": 0.0}


def add_flops(n: float):
    _counters["flops"] += n


def add_bytes(n: float):
    _counters["bytes"] += n


def flops_global() -> float:
    return _counters["flops"]


def bytes_global() -> float:
    return _counters["bytes"]
