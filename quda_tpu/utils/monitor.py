"""Background monitor: periodic device/host sampling to a tsv file.

Reference behavior: lib/monitor.cpp — a host thread samples power, energy,
temperature and clocks every QUDA_ENABLE_MONITOR_PERIOD microseconds into
monitor_n<rank>_<time>.tsv; solvers integrate energy over their window.

TPU analog: no NVML — we sample wall time, device memory stats across
ALL local devices (obs/memory.device_snapshot; sampling only device 0
left a sharded solve's other shards invisible — round-12 fix) and host
RSS.  Snapshots fold their per-device high-water into the HBM ledger
(obs/memory.py), so the end-of-session fleet report carries the peak a
background-monitored run actually reached.  The same
start/stop/integration API shape is kept so solver reports can attach
resource usage.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional


class Monitor:
    def __init__(self, period_s: float = 0.05, path: Optional[str] = None):
        self.period = period_s
        self.path = path
        self.samples: List[dict] = []
        self._thread = None
        self._stop = threading.Event()

    def _device_mem(self):
        """(total, max, n) bytes_in_use over ALL local devices — the
        snapshot also folds per-device high-water into the HBM ledger
        (obs/memory.device_snapshot)."""
        try:
            from ..obs import memory as omem
            rows = omem.device_snapshot()
            if not rows:
                return 0, 0, 0
            vals = [r["bytes_in_use"] for r in rows]
            return sum(vals), max(vals), len(vals)
        except Exception:
            return 0, 0, 0

    def _host_rss(self):
        try:
            with open("/proc/self/statm") as fh:
                return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
        except Exception:
            return 0

    def _loop(self):
        while not self._stop.is_set():
            total, dmax, ndev = self._device_mem()
            self.samples.append({
                "time": time.time(),
                "device_bytes": total,
                "device_bytes_max": dmax,
                "n_devices": ndev,
                "host_rss": self._host_rss(),
            })
            self._stop.wait(self.period)

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self.path:
            with open(self.path, "w") as fh:
                fh.write("time\tdevice_bytes\tdevice_bytes_max\t"
                         "n_devices\thost_rss\n")
                for s in self.samples:
                    fh.write(f"{s['time']:.6f}\t{s['device_bytes']}\t"
                             f"{s.get('device_bytes_max', 0)}\t"
                             f"{s.get('n_devices', 0)}\t"
                             f"{s['host_rss']}\n")

    def window(self, t0: float, t1: float):
        """Samples within [t0, t1] (solver-window integration analog)."""
        return [s for s in self.samples if t0 <= s["time"] <= t1]

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


_default: Optional[Monitor] = None


def start_default():
    """Start the env-configured global monitor (QUDA_TPU_ENABLE_MONITOR
    / QUDA_TPU_MONITOR_PERIOD), writing monitor.tsv under the resource
    path — init_quda calls this, mirroring monitor::init_instance."""
    global _default
    from . import config as qconf
    if _default is not None or not qconf.get("QUDA_TPU_ENABLE_MONITOR",
                                             fresh=True):
        return None
    path = qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
    out = None
    if path:
        os.makedirs(path, exist_ok=True)
        out = os.path.join(path, "monitor.tsv")
    _default = Monitor(qconf.get("QUDA_TPU_MONITOR_PERIOD", fresh=True),
                       out)
    _default.start()
    return _default


def stop_default():
    global _default
    if _default is not None:
        try:
            _default.stop()
        finally:
            _default = None
