"""Central environment-flag registry — the QUDA_* config system analog.

Reference behavior: the reference scatters ~40 ``getenv("QUDA_...")``
calls across tune.cpp, malloc.cpp, monitor.cpp, util_quda.cpp,
milc_interface.cpp, dslash_policy.hpp etc. (e.g. QUDA_ENABLE_TUNING,
QUDA_RESOURCE_PATH, QUDA_ENABLE_MONITOR, QUDA_DETERMINISTIC_REDUCE,
QUDA_MAX_MULTI_RHS, QUDA_ENABLE_DEVICE_MEMORY_POOL).  This module is the
single TPU-native home for that surface:

* every knob is REGISTERED with a type, default, and doc string;
* reads go through typed accessors (`flag`, `intval`, `strval`) with
  caching and validation;
* ``describe()`` prints the full table (the analog of the reference's
  documented env list);
* ``check_environment()`` warns about unrecognised ``QUDA_TPU_*``
  variables — a typoed knob silently doing nothing is the worst failure
  mode of env-var config (fail-fast model, SURVEY §5.6).

CUDA-specific knobs with no TPU meaning (memory pools, MPS, GDR,
NVSHMEM, peer-to-peer) are intentionally NOT accepted: XLA/PJRT owns
allocation and collectives.  They are listed in ``SUBSUMED`` with the
subsystem that replaces them so ``describe()`` can answer "where did
QUDA_ENABLE_DEVICE_MEMORY_POOL go?".
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_PREFIX = "QUDA_TPU_"


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str                 # full env-var name
    kind: str                 # "bool" | "int" | "float" | "str" | "choice"
    default: object
    doc: str
    choices: tuple = ()
    reference: str = ""       # the reference knob this replaces
    # Legal to read inside a traced function (jit/while_loop/scan/
    # shard_map bodies)?  Almost never: a knob read under trace freezes
    # into the compiled executable (stale-knob/recompile hazard), so
    # knobs are read at OPERATOR CONSTRUCTION and closed over.  The
    # static trace-safety pass (quda_tpu/analysis) reads its policy
    # from this field — flipping it to True is a reviewed statement
    # that trace-time freezing is the intended semantics for that knob.
    trace_safe: bool = False


_REGISTRY: dict[str, Knob] = {}


def _register(name, kind, default, doc, choices=(), reference="",
              trace_safe=False):
    _REGISTRY[name] = Knob(name, kind, default, doc, tuple(choices),
                           reference, bool(trace_safe))


# -- logging / verbosity ----------------------------------------------------
_register("QUDA_TPU_VERBOSITY", "choice", "summarize",
          "global log verbosity", ("silent", "summarize", "verbose",
                                   "debug"), "QUDA_VERBOSITY (setVerbosity)")
_register("QUDA_TPU_RANK_VERBOSITY", "str", "0",
          "which process indices print ('all' or a rank number)",
          reference="QUDA_RANK_VERBOSITY")
_register("QUDA_TPU_PROCESS_INDEX", "int", 0,
          "this process's index for rank-gated printing",
          reference="comm rank")

# -- autotuner --------------------------------------------------------------
_register("QUDA_TPU_ENABLE_TUNING", "bool", True,
          "enable the implementation-choice autotuner",
          reference="QUDA_ENABLE_TUNING")
_register("QUDA_TPU_RESOURCE_PATH", "str", "",
          "directory for tunecache.json and profile output",
          reference="QUDA_RESOURCE_PATH")
_register("QUDA_TPU_TUNE_VERSION_CHECK", "bool", True,
          "invalidate tunecache entries recorded by a different "
          "jax/backend version", reference="QUDA_TUNE_VERSION_CHECK")

# -- dslash implementation selection ---------------------------------------
_register("QUDA_TPU_PACKED", "choice", "",
          "force ('1') or forbid ('0') the TPU-native packed device "
          "order in API solves; empty = platform default (on for TPU)",
          ("", "0", "1"),
          reference="native FloatN field orders")
_register("QUDA_TPU_PALLAS", "choice", "",
          "force ('1') or forbid ('0') pallas dslash kernels in API "
          "solves; empty = autotuned choice",
          ("", "0", "1"),
          reference="QUDA_ENABLE_DSLASH_POLICY")
_register("QUDA_TPU_MG_EMBED", "choice", "",
          "apply pair-MG coarse links as single interleaved-embedding "
          "matmuls ('1') instead of 4-einsum pair products; empty/'0' "
          "= pair einsums (flip after chip measurement)",
          ("", "0", "1"),
          reference="coarse-dslash MMA path (lib/dslash_coarse.cu)")
_register("QUDA_TPU_MG_SETUP", "choice", "",
          "MG setup pipeline: ''/'fast' = MRHS null-vector block solve "
          "(one tolerance-stopped batched BiCGStab on the direct "
          "system over all n_vec sources, "
          "solvers/block.batched_bicgstab_pairs; MGLevelParam."
          "setup_solver='cg' selects batched_cg_pairs on MdagM) + "
          "GEMM-built coarse stencil (mg/gemm.py: 9 batched "
          "contractions instead of the ~34*n_vec-dispatch masked "
          "probe loop); 'legacy' = the "
          "pre-round-15 chunked-vmap fixed-iteration CG and probe loop "
          "(kept for the A/B the mg_setup_phase_seconds_total counters "
          "arbitrate)",
          ("", "fast", "legacy"),
          reference="MG::reset setup pipeline (lib/multigrid.cpp:91, "
                    "generateNullVectors :1249, calculateY)")
_register("QUDA_TPU_MG_NULL_CHUNK", "int", 0,
          "cap on simultaneously-batched null-vector solves in MG "
          "setup: 0 = one full-width block solve over all n_vec "
          "sources (the fast-path default; big-HBM chips keep it), "
          "k > 0 = chunk the batch at width k (a full-width batch "
          "holds n_vec concurrent (x, r, p, Ap) Krylov states — an "
          "OOM valve on fine lattices).  The legacy pipeline "
          "(QUDA_TPU_MG_SETUP=legacy) treats 0 as its historical "
          "hard-coded min(n_vec, 4)",
          reference="QUDA_MAX_MULTI_RHS / setup batching "
                    "(lib/multigrid.cpp generateNullVectors)")
_register("QUDA_TPU_MG_COARSE_CHUNK", "int", 0,
          "cap on simultaneously-contracted coarse-stencil columns in "
          "the GEMM coarse build (mg/gemm.py): 0 = all 2*n_vec null-"
          "vector columns in one batch (one fine-field batch of 2*n_vec "
          "resident at once), k > 0 = process k columns per pass — the "
          "HBM valve for fine lattices where 2*n_vec fine fields "
          "exceed residency",
          reference="calculateY batching (lib/coarse_op.in.cu)")
_register("QUDA_TPU_MG_COARSE_FORM", "choice", "auto",
          "pair-MG coarse-operator apply form: 'einsum' = 4-einsum "
          "pair products per link, 'embed' = interleaved-embedding "
          "matmuls, 'pallas' = the fused single-pass coarse stencil "
          "kernel (ops/coarse_pallas.py: diag + 8 hops in one launch, "
          "links read once), 'auto' = race all forms via utils.tune at "
          "hierarchy construction on chip (static einsum/embed default "
          "off-chip, honoring QUDA_TPU_MG_EMBED) — A/B'd, not assumed, "
          "like every other kernel form",
          ("", "auto", "einsum", "embed", "pallas"),
          reference="coarse-dslash MMA/policy selection "
                    "(lib/dslash_coarse.cu + tune.cpp:862)")
_register("QUDA_TPU_RECONSTRUCT", "choice", "18",
          "gauge link storage for v3 pallas kernels: '18' = full, "
          "'12' = two rows + in-kernel third-row reconstruction "
          "(192 B/site instead of 288; SU(3) links only)",
          ("18", "12"),
          reference="QUDA_RECONSTRUCT / gauge_field_order.h "
                    "Reconstruct<12>")
_register("QUDA_TPU_PRECISION_FORM", "choice", "",
          "link storage / precision form for the packed pallas Wilson "
          "operator (PERF.md round 16): 'full' = resident 18-real "
          "links; 'r12' = two rows + in-kernel third-row recon "
          "(192 B/site, both kernel generations and the sharded path); "
          "'r12f' = r12 storage + scatter backward (no resident "
          "backward-link copy — the v3 trick on the v2 gather psi "
          "path); 'fold' = re/im interleaved into sublanes "
          "((...,2,T,Z,YX) -> (...,T,2Z,YX)) so bf16 (16,128) tiles "
          "fill exactly; 'bzfull' = full-Z block admission (single-"
          "buffered under the 16 MB scoped window when the budget knob "
          "rejects double buffering); 'int8' = block-float resident "
          "links (int8 mantissas + one f32 scale per direction/site, "
          "decompressed in-kernel) — changes the operator's floats, so "
          "it must be served under the df64 reliable-update correction "
          "for deep tolerances; 'auto' = race the numerics-preserving "
          "forms via utils.tune (int8 NEVER races); '' = legacy "
          "resolution via QUDA_TPU_RECONSTRUCT.  Read at operator "
          "construction only (storage layout is baked into the "
          "resident arrays), hence NOT trace-safe",
          ("", "auto", "full", "bzfull", "fold", "r12", "r12f", "int8"),
          reference="QUDA_RECONSTRUCT x QUDA_PRECISION link-storage "
                    "matrix (gauge_field_order.h Reconstruct<12> + "
                    "quarter-precision block-float norm arrays)",
          trace_safe=False)
_register("QUDA_TPU_PALLAS_VERSION", "int", 2,
          "pallas kernel generation: 2 = gather kernels with "
          "pre-shifted backward links, 3 = scatter-form backward hops "
          "(no backward-link copies).  Default 2 BY MEASUREMENT "
          "(2026-07-31, TPU v5 lite, 24^4 Wilson full: v2 f32 5673 "
          "GFLOPS vs v3 1768 / v3+recon-12 1919 — the scatter shifts "
          "cost more VPU work than the saved HBM traffic buys; the "
          "autotuner can still select v3 per-shape when it wins)",
          reference="dslash policy selection; tune.cpp:862 — policies "
                    "are timed, never assumed")
_register("QUDA_TPU_SHARDED_POLICY", "str", "auto",
          "multi-chip dslash halo policy, PER MESH AXIS since round "
          "18: 'xla_facefix' = lax.ppermute face fixes around the "
          "pallas interior (GSPMD collective-permute transport, serves "
          "every axis including the strided x column faces); "
          "'fused_halo' = in-kernel RDMA strip exchange, both "
          "directions behind one neighbour barrier (parallel/"
          "pallas_halo.slab_exchange_bidir, the NVSHMEM analog — "
          "contiguous t/z slabs and y row strips only); 'auto' = race "
          "each partitioned axis per (volume, mesh, form, axis) via "
          "utils.tune at construction and cache the winners "
          "(QUDA-policy-engine style).  A per-axis spec pins axes "
          "separately, e.g. 't=fused_halo,z=fused_halo,y=xla_facefix' "
          "(unlisted axes get xla_facefix); a bare policy name is the "
          "LEGACY single-value form — it maps onto all axes (x keeps "
          "xla_facefix under fused_halo) with a one-time deprecation-"
          "style notice.  Read at operator construction only, hence "
          "NOT trace-safe",
          reference="dslash policy engine lib/dslash_policy.hpp:"
                    "365-560,1566-1675 + QUDA_ENABLE_NVSHMEM",
          trace_safe=False)
_register("QUDA_TPU_PALLAS_VMEM_MB", "float", 6.0,
          "single-buffer VMEM budget (MB) for pallas z-block selection "
          "(_pick_bz).  Default 6 leaves half the 16 MB scoped limit "
          "for Mosaic's double buffering; raise it to admit bz=Z "
          "blocks (e.g. the bf16 full-Z 'equal-to-dim' experiment at "
          "Z=24 needs ~12) — measure before pinning",
          reference="tune.cpp shared-bytes tuning axis")
_register("QUDA_TPU_PALLAS_VMEM_MB_STAGGERED", "float", 9.0,
          "per-kernel single-buffer VMEM budget (MB) for the STAGGERED "
          "pallas z-block selection, overriding QUDA_TPU_PALLAS_VMEM_MB "
          "for that family only.  The fused single-pass fat+Naik kernel "
          "keeps both hop sets' link tiles and the t+-1/t+-3 psi tiles "
          "resident (the split-launch form existed only because that "
          "working set busts the 6 MB default at useful block sizes, "
          "PERF.md round 8 lever (a)); the raised default admits it "
          "while the Wilson kernels keep the measured-proven 6 MB",
          reference="tune.cpp shared-bytes tuning axis (per-kernel)")
_register("QUDA_TPU_STAGGERED_FORM", "choice", "auto",
          "staggered/HISQ pallas kernel form: 'fused' = single-pass "
          "fat+Naik (one launch, one psi read, no XLA sum pass), "
          "'two_pass' = separate fat/long gather launches with "
          "pre-shifted backward links (the pre-round-10 form), 'v3' = "
          "two-pass scatter, 'auto' = race all forms via utils.tune at "
          "operator construction and cache the winner per (volume, "
          "dtype, improved) — A/B'd, not assumed: v3 LOST for Wilson "
          "on chip, so no staggered form is presumed either",
          ("", "auto", "fused", "two_pass", "v3"),
          reference="dslash policy selection; tune.cpp:862 — policies "
                    "are timed, never assumed")
_register("QUDA_TPU_CLOVER_FORM", "choice", "auto",
          "clover PC pair-operator form: 'pallas' = the fused v2 "
          "kernel with the resident 2x6x6 chiral clover blocks applied "
          "in the kernel epilogue (ops/clover_pallas — diag+hop one "
          "VMEM pass), 'xla' = the staged hop + einsum composition, "
          "'auto' = race both via utils.tune at operator construction "
          "and cache the winner per (volume, dtype).  Read at operator "
          "construction only, hence NOT trace-safe",
          ("", "auto", "pallas", "xla"),
          reference="dslash policy selection; tune.cpp:862 — policies "
                    "are timed, never assumed "
                    "(dslash_wilson_clover_preconditioned.cu)",
          trace_safe=False)
_register("QUDA_TPU_TWISTED_FORM", "choice", "auto",
          "twisted-mass / twisted-clover PC pair-operator form: "
          "'pallas' = the fused v2 kernel with the in-register i mu "
          "gamma5 twist (plus dense twisted-clover blocks) in the "
          "kernel epilogue, 'xla' = the staged composition, 'auto' = "
          "race and cache per (volume, dtype).  Nondegenerate "
          "flavor-doublet operators always take the XLA composition "
          "(the -b tau1 flavor mixing is not an epilogue term).  Read "
          "at operator construction only, hence NOT trace-safe",
          ("", "auto", "pallas", "xla"),
          reference="dslash policy selection; tune.cpp:862 "
                    "(dslash_twisted_clover_preconditioned.cu)",
          trace_safe=False)
_register("QUDA_TPU_DWF_FORM", "choice", "auto",
          "domain-wall / Möbius 4d-hop form: 'pallas' = the Ls-batched "
          "v2 kernel (ops/dwf_pallas — Ls innermost, gauge tile "
          "fetched once per (t, z-block) while Ls spinor planes stream "
          "through: 576+576/Ls B/site/plane), 'xla' = the vmap-over-s "
          "stencil, 'auto' = race and cache per (volume, dtype, Ls). "
          "The dense (Ls,Ls) m5 algebra stays XLA-batched either way. "
          "Read at operator construction only, hence NOT trace-safe",
          ("", "auto", "pallas", "xla"),
          reference="dslash policy selection; tune.cpp:862 "
                    "(dslash_domain_wall_m5.cuh batches s like rhs)",
          trace_safe=False)
_register("QUDA_TPU_DF64", "choice", "",
          "extended-precision (float32-pair) precise path for deep-tol "
          "Wilson CG: '1' = force, '0' = off, empty = auto (engaged when "
          "tol is below the f32 floor and no f64 backend serves)",
          ("", "0", "1"),
          reference="fp64 matPrecise + dbldbl reductions "
                    "(include/dbldbl.h)")
_register("QUDA_TPU_SLOPPY_PRECISION", "choice", "",
          "override cuda_prec_sloppy='auto' resolution",
          ("", "single", "half", "quarter"),
          reference="QudaInvertParam::cuda_prec_sloppy")

# -- solvers ----------------------------------------------------------------
_register("QUDA_TPU_CG_CHECK_EVERY", "int", 1,
          "fused-iteration CG convergence-check cadence: the while_loop "
          "body fuses this many CG iterations per convergence check, "
          "amortising the cond branch and the heavy-quark reduction over "
          "k dslash applies (solvers/fused_iter.py).  The solve reaches "
          "the same final residual as cadence 1 but may run up to k-1 "
          "iterations past convergence — and past maxiter, which is "
          "also only checked at cadence boundaries",
          reference="lib/inv_cg_quda.cpp per-iteration convergence check")
_register("QUDA_TPU_FUSED_TAIL", "choice", "",
          "route the CG tail (x += a p; r -= a Ap; |r|^2) through the "
          "fused pallas update+reduce kernel (ops/blas_pallas.py): '1' "
          "force, '0'/empty = the XLA-fused jnp path (measure on chip "
          "before pinning).  Covers fused_cg/cg AND the reliable-update "
          "loops of the complex-free pair routes (pair_inplace_codec); "
          "complex-dtype solves always use the jnp path",
          ("", "0", "1"),
          reference="include/kernels/reduce_core.cuh:668 axpyNorm2")
_register("QUDA_TPU_MAX_MULTI_RHS", "int", 32,
          "cap on simultaneously batched right-hand sides in block "
          "solvers", reference="QUDA_MAX_MULTI_RHS")
_register("QUDA_TPU_MULTI_SRC_SPLIT", "choice", "",
          "invert_multi_src_quda routing: '1' = force the split-grid "
          "path (sources sharded over the mesh src axis, gauge "
          "replicated), '0' = force the single-device batched MRHS "
          "pipeline, empty = auto by mesh size (split when >1 device "
          "divides the batch)",
          ("", "0", "1"),
          reference="callMultiSrcQuda split_key "
                    "(lib/interface_quda.cpp:3064)")
_register("QUDA_TPU_MULTI_SRC_BLOCK", "choice", "",
          "batched multi-source solver: '1' = true block CG (shared "
          "Krylov space, real Gram matmuls), empty/'0' = independent "
          "per-RHS lanes (batched CG) — the default matches QUDA's "
          "per-source multi-RHS solves",
          ("", "0", "1"),
          reference="QUDA block-CG solver family (inv_cg_quda.cpp "
                    "block variants)")
_register("QUDA_TPU_DETERMINISTIC_REDUCE", "bool", True,
          "accepted for compatibility: XLA reductions are deterministic "
          "per compiled executable already",
          reference="QUDA_DETERMINISTIC_REDUCE")

# -- monitoring / profiling / tracing ---------------------------------------
_register("QUDA_TPU_TRACE", "bool", False,
          "enable the observability layer (quda_tpu/obs): nestable "
          "span tracing of every API solve (chrome-trace JSON + JSONL "
          "event stream), per-iteration convergence recording surfaced "
          "on InvertParam.res_history, and roofline attribution rows; "
          "off (default) = zero-overhead no-op spans and unmodified "
          "solver loop carries",
          reference="pushProfile spans + profile_N.tsv (lib/tune.cpp:"
                    "450-474)")
_register("QUDA_TPU_TRACE_PATH", "str", "",
          "directory for trace artifacts (trace.json / "
          "trace_events.jsonl); empty = QUDA_TPU_RESOURCE_PATH, else "
          "the working directory",
          reference="QUDA_PROFILE_OUTPUT_BASE")
_register("QUDA_TPU_TRACE_EVENTS_MAX", "int", 200000,
          "cap on buffered trace events per session; events past the "
          "cap are dropped and counted in the flushed trace's "
          "otherData.dropped_events",
          reference="bounded profiling buffers")
_register("QUDA_TPU_METRICS", "bool", False,
          "enable the serving-grade metrics registry (obs/metrics.py): "
          "labeled solve/compile/tuner-cache/retry counters, the HBM "
          "field ledger + all-device memory sampling, and the "
          "end_quda export (metrics.prom Prometheus text, metrics.tsv, "
          "fleet_report.txt under the resource path); off (default) = "
          "zero-overhead no-op recording calls and bit-identical "
          "compiled solves (pinned by raising-stub test)",
          reference="tunecache/profile accounting (lib/tune.cpp:"
                    "450-610) + device_malloc ledger (lib/malloc.cpp)")
_register("QUDA_TPU_ENABLE_MONITOR", "bool", False,
          "periodically sample device/host memory into the monitor log",
          reference="QUDA_ENABLE_MONITOR")
_register("QUDA_TPU_MONITOR_PERIOD", "float", 1.0,
          "monitor sampling period in seconds",
          reference="QUDA_ENABLE_MONITOR_PERIOD")
_register("QUDA_TPU_PROFILE_OUTPUT_BASE", "str", "profile",
          "basename for timer/profile dumps under the resource path",
          reference="QUDA_PROFILE_OUTPUT_BASE")
_register("QUDA_TPU_DO_NOT_PROFILE", "bool", False,
          "disable the global TimeProfile accumulation",
          reference="QUDA_DO_NOT_PROFILE")
_register("QUDA_TPU_ENABLE_FORCE_MONITOR", "bool", False,
          "log per-step force norms during HMC momentum updates",
          reference="QUDA_ENABLE_FORCE_MONITOR")

# -- flight recorder / postmortem bundles (obs/flight.py, obs/postmortem.py)
_register("QUDA_TPU_FLIGHT", "bool", False,
          "enable the in-process flight recorder (obs/flight.py): a "
          "bounded host-side ring buffer of structured events (API "
          "entries/exits, tuner decisions, escalation rungs, sentinel "
          "codes, gauge loads/rejections, exchange-policy picks) whose "
          "tail lands in every postmortem bundle and in flight.jsonl "
          "at end_quda; off (default) = zero-overhead no-op appends "
          "and bit-identical compiled solves (pinned by raising-stub "
          "test)",
          reference="persistent tunecache/profile artifacts "
                    "(lib/tune.cpp:450-610) as the always-on black box")
_register("QUDA_TPU_FLIGHT_EVENTS_MAX", "int", 4096,
          "flight-recorder ring capacity: the newest this many events "
          "are kept; older ones are dropped (counted, reported as a "
          "flight_dropped trace event and in the bundle manifest)",
          reference="bounded profiling buffers")
_register("QUDA_TPU_POSTMORTEM", "choice", "",
          "postmortem bundle capture on solve failure paths "
          "(obs/postmortem.py): '1' = always capture, '0' = never, "
          "empty = follow QUDA_TPU_FLIGHT (a bundle without the ring "
          "tail is half blind, so capture defaults to riding the "
          "recorder).  Triggers: sentinel breakdown, verification "
          "mismatch, exhausted escalation ladder, gauge rejection, and "
          "uncaught exceptions crossing an interfaces/quda_api.py "
          "boundary",
          ("", "0", "1"),
          reference="QUDA_RESOURCE_PATH persistent artifacts as the "
                    "production failure-capture surface")
_register("QUDA_TPU_POSTMORTEM_PATH", "str", "",
          "directory receiving postmortem bundle directories (one "
          "pm_<stamp>_<trigger> dir per capture); empty = "
          "<QUDA_TPU_RESOURCE_PATH>/postmortems, else the working "
          "directory's ./postmortems",
          reference="QUDA_RESOURCE_PATH")
_register("QUDA_TPU_POSTMORTEM_MAX_MB", "float", 64.0,
          "size cap (MB) on the field dumps inside one postmortem "
          "bundle: fields are dumped in replay-priority order (gauge, "
          "source, fat, long) until the budget is spent; fields past "
          "the cap appear in manifest.json as omitted entries with "
          "shape/dtype/sha256 only (a replay then reports what is "
          "missing)",
          reference="bounded artifact size for fleet log collection")
_register("QUDA_TPU_POSTMORTEM_MAX_BUNDLES", "int", 8,
          "cap on postmortem bundles written per session: a repeating "
          "failure (e.g. every solve of a poisoned gauge breaking "
          "down) must not fill the disk; past the cap, captures are "
          "counted (postmortems_total{trigger=suppressed}) but not "
          "written",
          reference="bounded retry: a serving fleet must fail fast, "
                    "not loop")

# -- benchmark harness (bench.py / bench_suite.py) --------------------------
for _n, _k, _d, _doc in (
        ("QUDA_TPU_BENCH_CPU", "bool", False,
         "force the benchmark onto the CPU backend"),
        ("QUDA_TPU_BENCH_L", "int", 0,
         "benchmark lattice extent (0 = platform default)"),
        ("QUDA_TPU_BENCH_N1", "int", 8, "short timing-chain length"),
        ("QUDA_TPU_BENCH_N2", "int", 200, "long timing-chain length"),
        ("QUDA_TPU_BENCH_REPS", "int", 5, "timing repetitions"),
        ("QUDA_TPU_BENCH_PROBE_S", "float", 75.0,
         "TPU probe subprocess timeout (seconds)"),
        ("QUDA_TPU_BENCH_PROBE_RETRIES", "int", 2,
         "TPU probe attempts before CPU fallback"),
        ("QUDA_TPU_BENCH_PROBE_WAIT_S", "float", 30.0,
         "wait between TPU probe attempts (seconds)"),
        ("QUDA_TPU_BENCH_DEADLINE_S", "float", 1200.0,
         "wall-clock budget: on expiry bench.py prints the best record "
         "accumulated so far and exits 0 (0 disables)"),
        ("QUDA_TPU_BENCH_SOLVER_L", "int", 16,
         "solver-suite lattice extent"),
        ("QUDA_TPU_BENCH_SOLVER_L_CHIP", "int", 24,
         "chip-sized solver-suite lattice for the TPU-only end-to-end "
         "rows (pallas-in-solver CG, multishift, bf16-reliable); "
         "0 disables them")):
    _register(_n, _k, _d, _doc, reference="tests/ benchmark CLI flags")

# -- perf-regression gate (bench_suite --compare / obs.regress) --------------
_register("QUDA_TPU_BENCH_COMPARE_TOL", "float", 0.10,
          "throughput tolerance of the bench-history compare gate: a "
          "current gflops/gbps row more than this fraction below its "
          "best-credible committed baseline fails bench_suite "
          "--compare with a rejection row and nonzero exit",
          reference="cross-version perf tracking (arXiv:1408.5925 "
                    "regression discipline)")
_register("QUDA_TPU_BENCH_COMPARE_ITERS_TOL", "float", 0.10,
          "solver-iteration tolerance of the compare gate: an iters "
          "row more than this fraction ABOVE its baseline fails "
          "(convergence regressions hide easily inside a wall-time "
          "budget)",
          reference="invert_test iteration-count reporting")
_register("QUDA_TPU_BENCH_HISTORY_DIR", "str", "",
          "directory holding the committed BENCH_*.json / "
          "MULTICHIP_*.json history the compare gate baselines "
          "against; empty = the repo root (next to bench.py)",
          reference="QUDA_RESOURCE_PATH-style state directory")

_register("QUDA_TPU_FORCE_CPU", "bool", False,
          "pin the CPU backend (and enable x64) in the embedded C-API "
          "interpreter", reference="QUDA_CPU_FIELD_LOCATION-style hosts")

# -- solve supervision (quda_tpu/robust) ------------------------------------
_register("QUDA_TPU_ROBUST", "choice", "off",
          "solve supervision level (quda_tpu/robust): 'off' = the "
          "compiled solves are bit-identical to the unguarded loops "
          "(zero ops added — pinned by test); 'verify' = in-loop "
          "breakdown sentinels (non-finite residual, pivot/Gram "
          "breakdown, stagnation) thread the solver while_loops and "
          "every API solve records verified_res + a solve_status on "
          "InvertParam; 'escalate' = verify plus the bounded retry "
          "ladder (pallas -> XLA stencil form; f32 sloppy -> df64 "
          "reliable; CG -> BiCGStab) on breakdown, verification "
          "mismatch, or operator-construction failure",
          ("off", "verify", "escalate"),
          reference="reliable updates + invert_test true-residual "
                    "checks (arXiv:1408.5925 production discipline)")
_register("QUDA_TPU_ROBUST_STAGNATION", "int", 0,
          "breakdown-sentinel stagnation window: flag a solve whose "
          "residual has not improved for this many consecutive "
          "convergence checks as a 'stagnation' breakdown (0 = "
          "disabled; stagnation is workload-dependent, so it is opt-in "
          "unlike the always-on finiteness/pivot predicates)",
          reference="solver convergence monitoring (lib/solver.cpp "
                    "PrintStats discipline)")
_register("QUDA_TPU_ROBUST_VERIFY_MARGIN", "float", 100.0,
          "verified-exit acceptance margin: a solve whose recomputed "
          "true residual exceeds margin * tol is recorded 'unverified' "
          "(and retried under 'escalate').  The margin absorbs the "
          "legitimate gap between the iterated system's stopping "
          "criterion (e.g. the normal equations) and the direct-system "
          "true residual",
          reference="invert_test residual verification")
_register("QUDA_TPU_ROBUST_MAX_RETRIES", "int", 3,
          "bound on escalation-ladder attempts per API solve "
          "(including the as-requested first attempt)",
          reference="bounded retry: a serving fleet must fail fast, "
                    "not loop")
_register("QUDA_TPU_FAULT", "str", "",
          "deterministic fault injection (quda_tpu/robust/faultinject):"
          " comma-separated <site>:<trigger> arms, e.g. 'dslash:5' "
          "(poison the dslash output at iteration 5 of the next "
          "solve), 'pallas_build:1' (raise on the next pallas operator"
          " construction), 'gauge:1' (poison a link at the next gauge "
          "load), 'residual:1e3' (inflate the next verified residual "
          "by 1e3).  Faults are one-shot: each arm fires once, then "
          "disarms — so an escalation retry sees a healthy system, "
          "modeling a transient fault.  TEST/DRILL KNOB: never set in "
          "production",
          reference="fault-injection testing of the reliable-update/"
                    "autotuner failure paths")
_register("QUDA_TPU_GAUGE_UNITARITY_TOL", "float", 0.0,
          "load_gauge_quda unitarity screen: warn (trace event "
          "gauge_unitarity) when any link's max |U Udag - I| exceeds "
          "this tolerance (0 = disabled).  Non-finite links are "
          "ALWAYS rejected loudly regardless of this knob; a "
          "deviating-but-finite gauge can be repaired with "
          "update_gauge_field_quda's reunitarize (ops/su3.project_su3)",
          reference="checkGauge / unitarize_links_quda tolerance "
                    "(include/svd_quda.h)")

# -- solve service (quda_tpu/serve) -----------------------------------------
_register("QUDA_TPU_SERVE_BATCH_WINDOW_MS", "float", 2.0,
          "solve-service coalescing window (milliseconds): after the "
          "first queued request is picked up, the worker keeps "
          "draining the queue for this long so requests targeting the "
          "same resident gauge coalesce into one MRHS batch "
          "(invert_multi_src_quda).  0 disables waiting — whatever is "
          "already queued still batches",
          reference="invertMultiSrcQuda batching "
                    "(lib/interface_quda.cpp:3064) + PLQCD queue-drain "
                    "overlap (arXiv:1405.0700)")
_register("QUDA_TPU_SERVE_MAX_BATCH", "int", 8,
          "cap on requests coalesced into one solve-service MRHS "
          "batch; also clamped by QUDA_TPU_MAX_MULTI_RHS.  Larger "
          "batches amortise gauge reads further (PERF.md round-7 "
          "curve) at the cost of per-request latency",
          reference="QUDA_MAX_MULTI_RHS")
_register("QUDA_TPU_SERVE_HBM_BUDGET_MB", "float", 0.0,
          "HBM budget (MB) for the solve-service gauge residency "
          "manager: when the obs/memory ledger's 'gauge' family "
          "exceeds it, least-recently-used non-active gauges are "
          "evicted (serve_gauge_evictions_total) until it fits.  "
          "0 = unlimited (single-tenant behavior)",
          reference="device_malloc ledger-driven residency "
                    "(lib/malloc.cpp) for gaugePrecise et al.")
_register("QUDA_TPU_SERVE_COMPILE_CACHE", "choice", "",
          "persistent XLA compilation cache for solve-service workers: "
          "'1' force, '0' off, empty = on when a resource path is "
          "configured.  Points jax_compilation_cache_dir at "
          "<QUDA_TPU_RESOURCE_PATH>/jax_compilation_cache so a fresh "
          "worker process deserialises already-built executables "
          "instead of recompiling (the compile-storm half of ROADMAP "
          "item 2; the tunecache warm start is the race-storm half)",
          ("", "0", "1"),
          reference="QUDA_RESOURCE_PATH persistent tunecache as the "
                    "cross-process warm-start surface")

# -- live telemetry plane (quda_tpu/obs/live.py) ----------------------------
_register("QUDA_TPU_LIVE", "bool", False,
          "serve the live telemetry HTTP plane (obs/live.py): a "
          "loopback ThreadingHTTPServer answering /metrics (Prometheus "
          "text from a lock-consistent registry snapshot, no reset), "
          "/healthz, /readyz, /fleet (live fleet_report.txt render), "
          "and /slo (serve_request_seconds burn rate) while the solve "
          "service keeps draining; off (default) = no server thread, "
          "no socket, and bit-identical compiled solves (pinned by "
          "raising-stub test)",
          reference="NVTX-annotated wrappers + QUDA_RESOURCE_PATH "
                    "artifacts (lib/generate/wrap.py) as the fleet-"
                    "introspection analog")
_register("QUDA_TPU_LIVE_PORT", "int", 0,
          "TCP port for the live telemetry endpoint, bound on "
          "127.0.0.1; 0 (default) = OS-assigned ephemeral port "
          "(obs.live.port() reports the bound one)",
          reference="pull-based Prometheus scrape discipline")
_register("QUDA_TPU_METRICS_FLUSH_SEC", "float", 0.0,
          "interval (seconds) for the live plane's background flusher: "
          "rewrites metrics.prom/metrics.tsv, fleet_report.txt, "
          "flight.jsonl, and roofline.tsv under the resource path "
          "every window so a crashed worker loses at most one "
          "interval of telemetry; 0 (default) disables the flusher "
          "(artifacts export at end_quda only)",
          reference="tunecache.tsv incremental persistence "
                    "(lib/tune.cpp:450-610)")
_register("QUDA_TPU_SLO_TARGET_MS", "float", 1000.0,
          "request-latency SLO target (milliseconds) the /slo endpoint "
          "grades serve_request_seconds against: a request is 'good' "
          "when its histogram bucket's upper bound is within the "
          "target",
          reference="fleet availability accounting (ROADMAP item 2)")
_register("QUDA_TPU_SLO_OBJECTIVE", "float", 0.99,
          "SLO objective: the fraction of requests required under "
          "QUDA_TPU_SLO_TARGET_MS.  /slo reports burn rate = "
          "(1 - compliance) / (1 - objective) — burn > 1 means the "
          "error budget is being spent faster than provisioned",
          reference="fleet availability accounting (ROADMAP item 2)")
_register("QUDA_TPU_SERVE_SLO_BUCKETS", "str", "",
          "comma-separated histogram bucket upper bounds (seconds) for "
          "serve_request_seconds, e.g. '0.05,0.1,0.25,0.5,1'; empty "
          "(default) = the registry-wide HIST_BUCKETS.  Set this when "
          "the SLO target sits inside one default bucket — percentile "
          "upper bounds and the /slo burn rate can only be as sharp "
          "as the bucket grid",
          reference="pull-based Prometheus scrape discipline")

# CUDA-runtime knobs deliberately not carried over: the replacing
# subsystem answers "where did it go".
SUBSUMED = {
    "QUDA_ENABLE_DEVICE_MEMORY_POOL": "XLA/PJRT allocator",
    "QUDA_ENABLE_PINNED_MEMORY_POOL": "XLA/PJRT allocator",
    "QUDA_ENABLE_MANAGED_MEMORY": "XLA/PJRT allocator",
    "QUDA_ENABLE_MANAGED_PREFETCH": "XLA/PJRT allocator",
    "QUDA_ENABLE_P2P": "XLA collectives over ICI",
    "QUDA_ENABLE_GDR": "XLA collectives over ICI",
    "QUDA_ENABLE_GDR_BLACKLIST": "XLA collectives over ICI",
    "QUDA_ENABLE_NVSHMEM": "QUDA_TPU_SHARDED_POLICY=fused_halo "
                           "(in-kernel RDMA halo)",
    "QUDA_ENABLE_MPS": "single-process PJRT runtime",
    "QUDA_ENABLE_ZERO_COPY": "device_put / donation semantics",
    "QUDA_REORDER_LOCATION": "host<->device packing in fields/",
    "QUDA_ENABLE_DSLASH_POLICY": "QUDA_TPU_PALLAS + utils.tune",
    "QUDA_ALLOW_JIT": "jit is the only execution model",
    "QUDA_DEVICE_RESET": "PJRT owns device lifetime",
}

_cache: dict[str, object] = {}

# Scoped override stack (robust/escalate.py retry rungs): each layer maps
# knob name -> raw string value and WINS over os.environ while pushed, so
# a ladder rung can demote e.g. QUDA_TPU_PALLAS without mutating the
# process environment (and without racing other readers of it).
_overrides: list = []


def overrides(**kv):
    """Context manager: push a layer of knob overrides (raw string
    values, validated like env input) that takes precedence over
    os.environ until the context exits.  Unknown knob names raise
    immediately — an override silently doing nothing is the same
    failure mode the registry exists to kill."""
    import contextlib

    for name in kv:
        if name not in _REGISTRY:
            raise KeyError(f"override of unregistered knob {name!r}")

    @contextlib.contextmanager
    def _ctx():
        _overrides.append({k: str(v) for k, v in kv.items()})
        _cache.clear()
        try:
            yield
        finally:
            _overrides.pop()
            _cache.clear()

    return _ctx()


def _parse(knob: Knob, raw: str):
    if knob.kind == "bool":
        if raw.lower() in ("1", "true", "yes", "on"):
            return True
        if raw.lower() in ("0", "false", "no", "off"):
            return False
        raise ValueError(f"{knob.name}={raw!r} is not a boolean "
                         "(use 0/1)")
    if knob.kind == "int":
        return int(raw)
    if knob.kind == "float":
        return float(raw)
    if knob.kind == "choice":
        if raw not in knob.choices:
            raise ValueError(f"{knob.name}={raw!r} not in "
                             f"{knob.choices}")
        return raw
    return raw


def get(name: str, *, fresh: bool = False):
    """Typed value of a registered knob (env override or default)."""
    if name not in _REGISTRY:
        raise KeyError(f"unregistered config knob {name!r}; "
                       f"known: {sorted(_REGISTRY)}")
    if not fresh and name in _cache:
        return _cache[name]
    knob = _REGISTRY[name]
    raw = os.environ.get(name)
    for layer in reversed(_overrides):
        if name in layer:
            raw = layer[name]
            break
    val = knob.default if raw is None or raw == "" else _parse(knob, raw)
    _cache[name] = val
    return val


def flag(name: str) -> bool:
    v = get(name)
    assert isinstance(v, bool), f"{name} is not a bool knob"
    return v


def intval(name: str) -> int:
    return int(get(name))


def floatval(name: str) -> float:
    return float(get(name))


def strval(name: str) -> str:
    return str(get(name))


def reset_cache():
    """Drop cached values (tests mutate os.environ)."""
    _cache.clear()


def knobs() -> dict[str, Knob]:
    return dict(_REGISTRY)


def snapshot_raw() -> dict:
    """Raw-string view of every knob currently steered away from its
    default (env value or scoped-override layer, overrides winning) —
    the replay-facing half of describe(): feeding these back through
    :func:`overrides` reproduces this moment's configuration
    (obs/postmortem.py records it in every bundle manifest)."""
    out = {}
    for name in _REGISTRY:
        raw = os.environ.get(name)
        for layer in reversed(_overrides):
            if name in layer:
                raw = layer[name]
                break
        if raw:
            out[name] = raw
    return out


def snapshot_values() -> dict:
    """Resolved typed value of every registered knob (the human half of
    the postmortem snapshot; a malformed env value reads as None rather
    than aborting a failure capture)."""
    out = {}
    for name in _REGISTRY:
        try:
            out[name] = get(name, fresh=True)
        except ValueError:
            out[name] = None
    return out


def describe() -> str:
    """Human-readable table of every knob (value, default, doc) plus the
    subsumed CUDA-era knobs — the analog of the reference's documented
    environment-variable list."""
    lines = ["# quda_tpu environment configuration"]
    for name in sorted(_REGISTRY):
        k = _REGISTRY[name]
        cur = get(name)
        src = "env" if os.environ.get(name) else "default"
        ref = f"  [ref: {k.reference}]" if k.reference else ""
        lines.append(f"{name} = {cur!r} ({src}; default {k.default!r}) "
                     f"— {k.doc}{ref}")
    lines.append("# subsumed CUDA-era knobs")
    for name in sorted(SUBSUMED):
        lines.append(f"{name} -> {SUBSUMED[name]}")
    return "\n".join(lines)


def check_environment(warn=None) -> list:
    """Return (and warn about) environment variables that LOOK like
    quda_tpu knobs but are not registered — typos silently doing nothing
    are the classic env-config failure."""
    from . import logging as qlog
    warn = warn or qlog.warningq
    unknown = [v for v in os.environ
               if v.startswith(_PREFIX) and v not in _REGISTRY]
    for v in unknown:
        warn(f"warning: unrecognised environment variable {v} "
             "(see quda_tpu.utils.config.describe())")
    legacy = [v for v in os.environ if v in SUBSUMED]
    for v in legacy:
        warn(f"warning: {v} has no effect on TPU — subsumed by "
             f"{SUBSUMED[v]}")
    bad = []
    for name in _REGISTRY:
        if os.environ.get(name):
            try:
                get(name, fresh=True)
            except ValueError as e:
                bad.append(name)
                warn(f"warning: {e}")
    return unknown + legacy + bad
