"""Autotuner with a persistent on-disk cache.

Reference behavior: lib/tune.cpp (1167 LoC) + include/tune_quda.h — every
kernel brute-force times its launch configurations once, caches the winner
in $QUDA_RESOURCE_PATH/tunecache.tsv keyed by {volume, name, aux}, and
doubles as the profiling system (profile_N.tsv).

TPU analog: XLA already schedules fused kernels, so what remains tunable is
the CHOICE among whole implementations (pure-XLA stencil vs Pallas kernel,
Pallas block shapes, halo policies).  `tune` times jitted candidates
(median of inner reps after warmup), persists winners to
$QUDA_TPU_RESOURCE_PATH/tunecache.json, and records per-key call counts and
timings for `save_profile`.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Sequence, Tuple

_cache: Dict[str, dict] = {}
_profile: Dict[str, dict] = {}
_loaded_path = None


def _resource_path():
    from . import config as qconf
    return qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)


def tune_key(name: str, volume, aux: str = "") -> str:
    """TuneKey {volume, name, aux} analog (include/tune_key.h:56)."""
    return f"{volume}|{name}|{aux}"


def load_cache():
    global _loaded_path
    path = _resource_path()
    if not path:
        return
    f = os.path.join(path, "tunecache.json")
    if os.path.exists(f):
        try:
            with open(f) as fh:
                _cache.update(json.load(fh))
        except (json.JSONDecodeError, OSError):
            pass
    _loaded_path = f


def save_cache():
    path = _resource_path()
    if not path:
        return
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "tunecache.json"), "w") as fh:
        json.dump(_cache, fh, indent=1, sort_keys=True)


def tuning_enabled() -> bool:
    from . import config as qconf
    return qconf.get("QUDA_TPU_ENABLE_TUNING", fresh=True)


def _obs_event(name: str, **fields):
    """Mirror tuner decisions into the trace stream (no-op when tracing
    is off) so every cached choice is auditable next to the spans it
    affects — the policy-engine-as-profiler contract."""
    try:
        from ..obs import trace as otr
        otr.event(name, cat="tune", **fields)
    except Exception:
        pass


def tune(name: str, volume, candidates: Dict[str, Callable], args: tuple,
         aux: str = "", reps: int = 3, inner: int = 5) -> str:
    """Return the winning candidate key; time once, cache forever.

    candidates: {param_string: jitted callable}; each is called as f(*args)
    and must return a jax array (block_until_ready used for timing).
    Candidate timings, failures, the winner and cache hits are emitted
    as trace events (obs/trace.py) and the candidate timings accumulate
    into the profiler half (record_launch -> profile_N.tsv).
    """
    key = tune_key(name, volume, aux)
    if key in _cache and _cache[key]["param"] in candidates:
        _obs_event("tune_cached", key=key,
                   param=_cache[key]["param"],
                   seconds=_cache[key].get("time"))
        return _cache[key]["param"]
    if not tuning_enabled():
        return next(iter(candidates))
    best, best_t = None, float("inf")
    for param, fn in candidates.items():
        try:
            out = fn(*args)
            out.block_until_ready()  # compile + warmup
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(inner):
                    out = fn(*args)
                out.block_until_ready()
                times.append((time.perf_counter() - t0) / inner)
            t = min(times)
        except Exception as e:
            _obs_event("tune_candidate_failed", key=key, param=param,
                       error=str(e)[:120])
            continue
        record_launch(name, volume, f"{aux}|{param}", t)
        _obs_event("tune_candidate", key=key, param=param, seconds=t)
        if t < best_t:
            best, best_t = param, t
    if best is None:
        raise RuntimeError(f"no tuning candidate succeeded for {key}")
    _cache[key] = {"param": best, "time": best_t}
    _obs_event("tune_winner", key=key, param=best, seconds=best_t)
    save_cache()
    return best


def record_launch(name: str, volume, aux: str, seconds: float,
                  flops: float = 0.0, bytes_: float = 0.0):
    """Accumulate per-kernel stats (the profiler half of lib/tune.cpp)."""
    key = tune_key(name, volume, aux)
    p = _profile.setdefault(key, {"calls": 0, "seconds": 0.0, "flops": 0.0,
                                  "bytes": 0.0})
    p["calls"] += 1
    p["seconds"] += seconds
    p["flops"] += flops
    p["bytes"] += bytes_


def save_profile(fname: str = "profile_0.tsv"):
    """Write profile_N.tsv like lib/tune.cpp:528-610."""
    path = _resource_path()
    if not path:
        return
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, fname), "w") as fh:
        fh.write("key\tcalls\tseconds\tGFLOPS\tGB/s\n")
        for key, p in sorted(_profile.items()):
            s = max(p["seconds"], 1e-12)
            fh.write(f"{key}\t{p['calls']}\t{p['seconds']:.6f}\t"
                     f"{p['flops'] / s / 1e9:.2f}\t"
                     f"{p['bytes'] / s / 1e9:.2f}\n")


load_cache()
