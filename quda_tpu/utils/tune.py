"""Autotuner with a persistent, chip-keyed on-disk cache.

Reference behavior: lib/tune.cpp (1167 LoC) + include/tune_quda.h — every
kernel brute-force times its launch configurations once, caches the winner
in $QUDA_RESOURCE_PATH/tunecache.tsv keyed by {volume, name, aux}, and
doubles as the profiling system (profile_N.tsv).  The reference cache also
carries the hardware it was measured on and refuses to serve entries from
a different device — a winner timed on one chip is NOISE on another.

TPU analog: XLA already schedules fused kernels, so what remains tunable is
the CHOICE among whole implementations (pure-XLA stencil vs Pallas kernel,
Pallas block shapes, halo policies, staggered kernel forms).  `tune` times
jitted candidates (median of inner reps after warmup), persists winners to
$QUDA_TPU_RESOURCE_PATH/tunecache.json, and records per-key call counts and
timings for `save_profile`.

Cache key schema (v2): ``platform|volume|name|aux`` where ``platform`` is
:func:`platform_key` — backend, device kind, and visible device count — so
a winner raced on CPU interpret is never silently reused on TPU (or vice
versa), and a multi-host mesh does not serve a single-chip race.  Entries
written by the pre-platform schema carry no ``platform`` field and are
dropped at load with a one-time "stale schema, re-racing" notice (the
QUDA_TUNE_VERSION_CHECK analog for the key layout itself).

Warm start: :func:`warm_start` (called by ``init_quda``) re-loads the
persistent cache under the current resource path and mirrors the load —
entry counts, stale drops, platform — into the obs trace stream, so a
fresh worker's first solve hits the raced winners of previous processes
(policy races included: QUDA_TPU_SHARDED_POLICY / QUDA_TPU_STAGGERED_FORM
auto-races go through `tune` and therefore through this store) without a
compile/race storm, and the warm-start behavior is auditable in the
chrome artifact next to the solves it accelerated.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

_cache: Dict[str, dict] = {}
_profile: Dict[str, dict] = {}
_loaded_path = None
_platform_key: Optional[str] = None
_stale_noticed = False


def _resource_path():
    from . import config as qconf
    return qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)


def platform_key() -> str:
    """Stable id of the hardware this process races on: backend platform,
    device kind, and visible device count (the mesh-capacity component),
    e.g. ``tpu:TPU-v5-lite:n8`` or ``cpu:cpu:n1``.  Computed lazily (the
    first call may initialise the jax backend) and cached per process;
    '|' and whitespace are folded so the key splits cleanly."""
    global _platform_key
    if _platform_key is None:
        try:
            import jax
            devs = jax.devices()
            kind = str(getattr(devs[0], "device_kind", "")
                       or devs[0].platform)
            kind = re.sub(r"[\s|]+", "-", kind).strip("-")
            _platform_key = f"{devs[0].platform}:{kind}:n{len(devs)}"
        except Exception:
            _platform_key = "unknown:unknown:n0"
    return _platform_key


def tune_key(name: str, volume, aux: str = "") -> str:
    """TuneKey {volume, name, aux} analog (include/tune_key.h:56) with
    the v2 platform/chip/mesh component prepended — see module docstring."""
    return f"{platform_key()}|{volume}|{name}|{aux}"


def cached_param(name: str, volume, aux: str = "") -> Optional[str]:
    """The cached winner for this (platform, volume, name, aux), or None
    when the race has not run on this hardware yet.  Lets call sites
    report warm-cache-vs-raced provenance without a second race."""
    e = _cache.get(tune_key(name, volume, aux))
    return e.get("param") if isinstance(e, dict) else None


def _notice_stale(n: int, path: str):
    """One-time notice for pre-platform-schema entries: they are not
    attributable to a chip, so they are invalidated (re-raced on first
    use) rather than migrated into a key they were never measured under."""
    global _stale_noticed
    _obs_event("tune_cache_invalidated", count=n, path=path,
               reason="stale schema: entry has no platform key")
    if _stale_noticed:
        return
    _stale_noticed = True
    try:
        from . import logging as qlog
        qlog.warningq(
            f"tunecache {path}: dropped {n} entr"
            f"{'y' if n == 1 else 'ies'} recorded under the pre-platform "
            "key schema (not attributable to this chip); stale schema, "
            "re-racing on first use")
    except Exception:
        pass


def load_cache() -> Optional[dict]:
    """Load tunecache.json under the current resource path into the
    process cache.  Entries without a ``platform`` field (the pre-v2
    un-keyed schema) are dropped with a one-time notice — a winner that
    cannot name the hardware it was timed on must not be served.
    Returns {'path', 'entries', 'stale'} stats (None when no resource
    path is configured)."""
    global _loaded_path
    path = _resource_path()
    if not path:
        return None
    f = os.path.join(path, "tunecache.json")
    loaded = stale = 0
    if os.path.exists(f):
        try:
            with open(f) as fh:
                raw = json.load(fh)
        except (json.JSONDecodeError, OSError):
            raw = {}
        for k, v in raw.items():
            if isinstance(v, dict) and v.get("platform"):
                _cache[k] = v
                loaded += 1
            else:
                stale += 1
        if stale:
            _notice_stale(stale, f)
    _loaded_path = f
    return {"path": f, "entries": loaded, "stale": stale}


def save_cache():
    path = _resource_path()
    if not path:
        return
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "tunecache.json"), "w") as fh:
        json.dump(_cache, fh, indent=1, sort_keys=True)


def warm_start() -> int:
    """init_quda hook: (re)load the persistent cache so this process's
    first solve serves already-raced (platform, volume, form) winners
    with zero re-races, and mirror the load as a ``tune_cache_loaded``
    trace event (counts + platform) so warm-start behavior is auditable
    in the chrome artifact.  Returns the number of entries usable on
    THIS hardware."""
    stats = load_cache() or {"path": "", "entries": 0, "stale": 0}
    here = platform_key()
    usable = sum(1 for k in _cache if k.startswith(here + "|"))
    _obs_event("tune_cache_loaded", path=stats["path"],
               entries=len(_cache), usable_here=usable,
               stale_dropped=stats["stale"], platform=here)
    _obs_gauge("tune_cache_entries", len(_cache), scope="total")
    _obs_gauge("tune_cache_entries", usable, scope="usable_here")
    _obs_gauge("tune_cache_entries", stats["stale"],
               scope="stale_dropped")
    return usable


def cache_snapshot(platform_only: bool = True) -> Dict[str, dict]:
    """Host-side copy of the in-process tunecache — with
    ``platform_only`` restricted to the entries servable on THIS
    hardware (the ones a solve on this chip could have consulted).
    The postmortem bundle writer (obs/postmortem.py) embeds this so a
    replayed solve can be compared against the winners the original
    solve was served."""
    here = platform_key() + "|"
    return {k: dict(v) for k, v in _cache.items()
            if not platform_only or k.startswith(here)}


def tuning_enabled() -> bool:
    from . import config as qconf
    return qconf.get("QUDA_TPU_ENABLE_TUNING", fresh=True)


def _obs_event(name: str, **fields):
    """Mirror tuner decisions into the trace stream (no-op when tracing
    is off) so every cached choice is auditable next to the spans it
    affects — the policy-engine-as-profiler contract."""
    try:
        from ..obs import trace as otr
        otr.event(name, cat="tune", **fields)
    except Exception:
        pass


def _obs_metric(name: str, value: float = 1.0, **labels):
    """Mirror tuner cache behavior into the metrics registry (no-op when
    QUDA_TPU_METRICS is off) — the warm-cache hit/miss/race accounting a
    serving fleet reads before scaling (ROADMAP item 2's compile/race
    storm is diagnosed HERE)."""
    try:
        from ..obs import metrics as omet
        omet.inc(name, value, **labels)
    except Exception:
        pass


def _obs_gauge(name: str, value: float, **labels):
    try:
        from ..obs import metrics as omet
        omet.set_gauge(name, value, **labels)
    except Exception:
        pass


def tune(name: str, volume, candidates: Dict[str, Callable], args: tuple,
         aux: str = "", reps: int = 3, inner: int = 5) -> str:
    """Return the winning candidate key; time once per chip, cache forever.

    candidates: {param_string: jitted callable}; each is called as f(*args)
    and must return a jax array (block_until_ready used for timing).
    Candidate timings, failures, the winner and cache hits are emitted
    as trace events (obs/trace.py) and the candidate timings accumulate
    into the profiler half (record_launch -> profile_N.tsv).
    """
    key = tune_key(name, volume, aux)
    if key in _cache and _cache[key]["param"] in candidates:
        _obs_event("tune_cached", key=key,
                   param=_cache[key]["param"],
                   seconds=_cache[key].get("time"))
        _obs_metric("tune_cache_hits_total", kernel=name)
        return _cache[key]["param"]
    _obs_metric("tune_cache_misses_total", kernel=name)
    if not tuning_enabled():
        return next(iter(candidates))
    _obs_metric("tune_races_total", kernel=name)
    best, best_t = None, float("inf")
    for param, fn in candidates.items():
        try:
            out = fn(*args)
            out.block_until_ready()  # compile + warmup
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(inner):
                    out = fn(*args)
                out.block_until_ready()
                times.append((time.perf_counter() - t0) / inner)
            t = min(times)
        except Exception as e:
            _obs_event("tune_candidate_failed", key=key, param=param,
                       error=str(e)[:120])
            continue
        record_launch(name, volume, f"{aux}|{param}", t)
        _obs_event("tune_candidate", key=key, param=param, seconds=t)
        if t < best_t:
            best, best_t = param, t
    if best is None:
        # every candidate raised (a race mid-chip-window can lose all
        # its entrants to a transient): degrade to the STATIC DEFAULT —
        # the first registered candidate, by the same convention
        # tuning-disabled uses — with a one-time notice, and do NOT
        # cache: the degraded choice was never timed, so the next
        # process re-races (tune.cpp skips failing launches the same
        # way; an all-fail race aborting the solve would turn a tuning
        # hiccup into an outage)
        default = next(iter(candidates))
        _obs_event("tune_race_all_failed", key=key, fallback=default,
                   n_candidates=len(candidates))
        _obs_metric("tune_race_failures_total", kernel=name)
        from . import logging as qlog
        qlog.warn_once(
            f"tune_all_failed:{name}",
            f"tune: every candidate failed for {key}; degrading to "
            f"the static default {default!r} (not cached — re-raced "
            "next time)")
        return default
    _cache[key] = {"param": best, "time": best_t,
                   "platform": platform_key()}
    _obs_event("tune_winner", key=key, param=best, seconds=best_t)
    save_cache()
    return best


def record_launch(name: str, volume, aux: str, seconds: float,
                  flops: float = 0.0, bytes_: float = 0.0):
    """Accumulate per-kernel stats (the profiler half of lib/tune.cpp)."""
    key = tune_key(name, volume, aux)
    p = _profile.setdefault(key, {"calls": 0, "seconds": 0.0, "flops": 0.0,
                                  "bytes": 0.0})
    p["calls"] += 1
    p["seconds"] += seconds
    p["flops"] += flops
    p["bytes"] += bytes_


def save_profile(fname: str = "profile_0.tsv") -> Optional[str]:
    """Write profile_N.tsv like lib/tune.cpp:528-610; returns the path
    (None without a resource path) so end_quda can index it into
    artifacts_manifest.json."""
    path = _resource_path()
    if not path:
        return None
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, fname), "w") as fh:
        fh.write("key\tcalls\tseconds\tGFLOPS\tGB/s\n")
        for key, p in sorted(_profile.items()):
            s = max(p["seconds"], 1e-12)
            fh.write(f"{key}\t{p['calls']}\t{p['seconds']:.6f}\t"
                     f"{p['flops'] / s / 1e9:.2f}\t"
                     f"{p['bytes'] / s / 1e9:.2f}\n")
    return os.path.join(path, fname)


load_cache()
