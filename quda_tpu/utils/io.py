"""Field I/O: gauge/propagator save-load, eigenvector sets, checkpoints.

Reference behavior: lib/qio_field.cpp (SciDAC/ILDG gauge + spinor files,
partition-aware layout lib/layout_hyper.cpp), lib/vector_io.cpp (VectorIO:
MG null spaces / eigenvector sets with optional precision drop on disk),
orbax-style checkpointing for HMC state (SURVEY.md §5.4).

Formats:
* native: .npz with metadata + crc32 site checksums (fast, self-describing)
* ildg: raw big-endian complex128 in ILDG site order (t,z,y,x slowest->
  fastest; mu inner; row-major color) for interop with community tools
* orbax: optional wrapper when orbax-checkpoint is importable
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry
from .checksum import gauge_checksum


def save_field(path: str, arr, meta: Optional[Dict] = None):
    """Save any lattice field with metadata + checksum (native format)."""
    a = np.asarray(arr)
    meta = dict(meta or {})
    meta["dtype"] = str(a.dtype)
    meta["shape"] = list(a.shape)
    meta["crc32"] = int(zlib.crc32(np.ascontiguousarray(a).tobytes()))
    np.savez_compressed(path, data=a, meta=json.dumps(meta))


def load_field(path: str, verify: bool = True):
    if not path.endswith(".npz"):
        path = path + ".npz"
    with np.load(path, allow_pickle=False) as z:
        a = z["data"]
        meta = json.loads(str(z["meta"]))
    if verify:
        crc = int(zlib.crc32(np.ascontiguousarray(a).tobytes()))
        if crc != meta.get("crc32"):
            raise IOError(f"checksum mismatch loading {path}")
    return jnp.asarray(a), meta


# -- ILDG-style raw binary (interop) ---------------------------------------

def save_gauge_ildg(path: str, gauge, geom: LatticeGeometry):
    """(4,T,Z,Y,X,3,3) -> ILDG binary: site-major (t slowest, x fastest),
    per site mu=0..3 (x,y,z,t), row-major 3x3, big-endian complex128."""
    from .lime import _gauge_to_ildg_bytes
    with open(path, "wb") as fh:
        fh.write(_gauge_to_ildg_bytes(gauge, 64).tobytes())
    side = {"dims": list(geom.dims), "checksum": gauge_checksum(gauge)}
    with open(path + ".meta.json", "w") as fh:
        json.dump(side, fh)


def load_gauge_ildg(path: str, geom: LatticeGeometry):
    n = geom.volume * 4 * 9
    raw = np.fromfile(path, dtype=">c16", count=n)
    site_major = raw.reshape(geom.lattice_shape + (4, 3, 3))
    return jnp.asarray(np.moveaxis(site_major.astype(np.complex128), 4, 0))


# -- vector sets (MG null spaces / eigenvectors) ---------------------------

def save_vectors(path: str, vecs, evals=None, save_dtype=None):
    """VectorIO::save analog; save_dtype drops precision on disk."""
    a = np.asarray(vecs)
    if save_dtype is not None:
        a = a.astype(save_dtype)
    meta = {"n_vec": a.shape[0]}
    payload = {"data": a, "meta": json.dumps(meta)}
    if evals is not None:
        payload["evals"] = np.asarray(evals)
    np.savez_compressed(path, **payload)


def load_vectors(path: str, dtype=None):
    if not path.endswith(".npz"):
        path = path + ".npz"
    with np.load(path, allow_pickle=False) as z:
        a = z["data"]
        evals = z["evals"] if "evals" in z else None
    if dtype is not None:
        a = a.astype(dtype)
    return jnp.asarray(a), (jnp.asarray(evals) if evals is not None else None)


# -- HMC / trainer-style checkpoints ---------------------------------------

def save_checkpoint(path: str, state: Dict):
    """Checkpoint a pytree-of-arrays dict (gauge, momenta, rng key, step...).

    Uses orbax when available, else the native npz path per entry.
    """
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), state, force=True)
        return "orbax"
    except Exception:
        os.makedirs(path, exist_ok=True)
        keys = {}
        for k, v in state.items():
            np.save(os.path.join(path, f"{k}.npy"), np.asarray(v))
            keys[k] = str(np.asarray(v).dtype)
        with open(os.path.join(path, "manifest.json"), "w") as fh:
            json.dump(keys, fh)
        return "npz"


def load_checkpoint(path: str) -> Dict:
    manifest = os.path.join(path, "manifest.json")
    if os.path.exists(manifest):
        with open(manifest) as fh:
            keys = json.load(fh)
        return {k: jnp.asarray(np.load(os.path.join(path, f"{k}.npy")))
                for k in keys}
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    return ckptr.restore(os.path.abspath(path))
