"""Lattice RNG: counter-based per-site streams.

Reference behavior: lib/random.cu (RNG class, per-site device-resident
states seeded by comm-offset site index) + the generic MRG32k3a fallback.

TPU-native: JAX's threefry PRNG IS a counter-based generator, so "per-site
states" need no storage at all — a (seed, site-index) fold_in derives each
site's stream deterministically, independent of sharding or device count
(stronger reproducibility than QUDA's stored-state scheme, which depends
on the process grid).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..fields.geometry import LatticeGeometry


class LatticeRNG:
    """Deterministic per-site random streams over a lattice."""

    def __init__(self, seed: int, geom: LatticeGeometry):
        self.geom = geom
        self.key = jax.random.PRNGKey(seed)
        self._draw = 0

    def next_key(self):
        self._draw += 1
        return jax.random.fold_in(self.key, self._draw)

    def gaussian(self, shape_internal, dtype=jnp.complex128):
        """Site-field of Gaussians: (T,Z,Y,X, *internal)."""
        shape = self.geom.lattice_shape + tuple(shape_internal)
        k = self.next_key()
        rdt = jnp.zeros((), dtype).real.dtype
        if jnp.issubdtype(dtype, jnp.complexfloating):
            k1, k2 = jax.random.split(k)
            return (jax.random.normal(k1, shape, rdt)
                    + 1j * jax.random.normal(k2, shape, rdt)).astype(dtype)
        return jax.random.normal(k, shape, dtype)

    def uniform(self, shape_internal, dtype=jnp.float64):
        shape = self.geom.lattice_shape + tuple(shape_internal)
        return jax.random.uniform(self.next_key(), shape, dtype)

    def state(self):
        """Serialisable state (for checkpoint/resume)."""
        return {"key": jnp.asarray(self.key), "draw": self._draw}

    @classmethod
    def from_state(cls, state, geom):
        rng = cls.__new__(cls)
        rng.geom = geom
        rng.key = jnp.asarray(state["key"])
        rng._draw = int(state["draw"])
        return rng
