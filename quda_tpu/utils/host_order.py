"""Host (application) field orders <-> canonical device layout.

Reference behavior: the host-order accessors of
include/gauge_field_order.h (QDPOrder:1852, MILCOrder:1948, CPSOrder:2068)
and include/color_spinor_field_order.h (SpaceSpinorColorOrder:1608 — the
QDP convention, SpaceColorSpinorOrder:1524 — CPS/QLA).  These are what
loadGaugeQuda / invertQuda accept from MILC, Chroma(QDP) and CPS.

Common structure: host fields use EVEN-ODD site ordering — all even
sites then all odd, each ordered lexicographically with x fastest; the
checkerboard index is (((t*Z + z)*Y + y)*X + x) // 2.

Per-site data:
  QDP gauge:   4 separate per-direction arrays, each [2][volCB][3][3]
               row-major (row = "to" color index as in canonical).
  MILC gauge:  one array [2][volCB][4][3][3] (dirs interleaved per site).
  CPS gauge:   like MILC but the 3x3 is TRANSPOSED (column-major) and
               scaled by the anisotropy.
  QDP spinor:  [2][volCB][4 spin][3 color].
  CPS spinor:  [2][volCB][3 color][4 spin].

Canonical layout here: gauge (4,T,Z,Y,X,3,3), spinor (T,Z,Y,X,4,3).
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np

from ..fields.geometry import LatticeGeometry


@lru_cache(maxsize=None)
def _eo_site_perm(geom: LatticeGeometry):
    """Permutation: even-odd host rank -> lexicographic site rank.

    perm[k] = lexicographic rank of the k-th host-ordered site (host
    order = all even sites then all odd, x fastest within each)."""
    T, Z, Y, X = geom.lattice_shape
    t, z, y, x = np.meshgrid(np.arange(T), np.arange(Z), np.arange(Y),
                             np.arange(X), indexing="ij")
    parity = ((t + z + y + x) % 2).reshape(-1)
    lex = np.arange(geom.volume)
    return np.concatenate([lex[parity == 0], lex[parity == 1]])


def _to_host_sites(arr_lex: np.ndarray, geom) -> np.ndarray:
    """(volume, ...) lexicographic -> even-odd host ordering."""
    return arr_lex[_eo_site_perm(geom)]


def _from_host_sites(arr_host: np.ndarray, geom) -> np.ndarray:
    perm = _eo_site_perm(geom)
    out = np.empty_like(arr_host)
    out[perm] = arr_host
    return out


# -- gauge ------------------------------------------------------------------

def gauge_to_qdp(gauge, geom: LatticeGeometry):
    """canonical (4,T,Z,Y,X,3,3) -> list of 4 arrays [2*volCB, 3, 3]."""
    g = np.asarray(gauge)
    out = []
    for mu in range(4):
        lex = g[mu].reshape(geom.volume, 3, 3)
        out.append(_to_host_sites(lex, geom))
    return out


def gauge_from_qdp(arrays, geom: LatticeGeometry):
    g = np.stack([
        _from_host_sites(np.asarray(a).reshape(geom.volume, 3, 3), geom)
        for a in arrays])
    return jnp.asarray(g.reshape((4,) + geom.lattice_shape + (3, 3)))


def gauge_to_milc(gauge, geom: LatticeGeometry):
    """canonical -> [2*volCB, 4, 3, 3] (MILCOrder site-major dirs)."""
    g = np.asarray(gauge)
    lex = np.moveaxis(g, 0, 4).reshape(geom.volume, 4, 3, 3)
    return _to_host_sites(lex, geom)


def gauge_from_milc(array, geom: LatticeGeometry):
    lex = _from_host_sites(
        np.asarray(array).reshape(geom.volume, 4, 3, 3), geom)
    full = lex.reshape(geom.lattice_shape + (4, 3, 3))
    return jnp.asarray(np.moveaxis(full, 4, 0))


def gauge_to_cps(gauge, geom: LatticeGeometry, anisotropy: float = 1.0):
    """canonical -> CPS order: MILC layout with transposed 3x3 scaled by
    the anisotropy (gauge_field_order.h CPSOrder::save)."""
    m = gauge_to_milc(gauge, geom)
    return np.swapaxes(m, -1, -2) * anisotropy


def gauge_from_cps(array, geom: LatticeGeometry, anisotropy: float = 1.0):
    a = np.swapaxes(np.asarray(array), -1, -2) / anisotropy
    return gauge_from_milc(a, geom)


# -- color spinors ----------------------------------------------------------

def spinor_to_qdp(psi, geom: LatticeGeometry):
    """canonical (T,Z,Y,X,4,3) -> [2*volCB, 4, 3] (SpaceSpinorColor)."""
    lex = np.asarray(psi).reshape(geom.volume, 4, 3)
    return _to_host_sites(lex, geom)


def spinor_from_qdp(array, geom: LatticeGeometry):
    lex = _from_host_sites(np.asarray(array).reshape(geom.volume, 4, 3),
                           geom)
    return jnp.asarray(lex.reshape(geom.lattice_shape + (4, 3)))


def spinor_to_cps(psi, geom: LatticeGeometry):
    """canonical -> [2*volCB, 3, 4] (SpaceColorSpinor)."""
    return np.swapaxes(spinor_to_qdp(psi, geom), -1, -2)


def spinor_from_cps(array, geom: LatticeGeometry):
    return spinor_from_qdp(np.swapaxes(np.asarray(array), -1, -2), geom)


# -- BQCD / TIFR gauge orders ----------------------------------------------

def _cb_coords(geom: LatticeGeometry, parity: int):
    """Lexicographic (t, z, y, x) coordinates of the parity's sites in
    checkerboard rank order (x fastest, x-coordinate halved)."""
    T, Z, Y, X = geom.lattice_shape
    t, z, y, x = np.meshgrid(np.arange(T), np.arange(Z), np.arange(Y),
                             np.arange(X), indexing="ij")
    sel = ((t + z + y + x) % 2) == parity
    return (t[sel], z[sel], y[sel], x[sel])


def gauge_to_bqcd(gauge, geom: LatticeGeometry):
    """canonical (4,T,Z,Y,X,3,3) -> BQCD layout (gauge_field_order.h
    BQCDOrder:2137): [dir][parity][extended-cb-site][3][3] with the 3x3
    TRANSPOSED and an extended halo margin of 1 site on every side
    (exVolumeCB = (X/2+2) * (Y+2) * (Z+2) * (T+2)); interior sites sit
    at coordinates + 1, the halo ring is zero-filled (BQCD populates it
    by its own communication)."""
    T, Z, Y, X = geom.lattice_shape
    ex = (X // 2 + 2, Y + 2, Z + 2, T + 2)      # x fastest
    ex_vol = int(np.prod(ex))
    g = np.asarray(gauge)
    out = np.zeros((4, 2, ex_vol, 3, 3), g.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t + 1) * ex[2] + (z + 1)) * ex[1] + (y + 1)) * ex[0] \
            + (x // 2 + 1)
        for mu in range(4):
            out[mu, parity, idx] = np.swapaxes(
                g[mu, t, z, y, x], -1, -2)
    return out


def gauge_from_bqcd(array, geom: LatticeGeometry):
    T, Z, Y, X = geom.lattice_shape
    ex = (X // 2 + 2, Y + 2, Z + 2, T + 2)
    a = np.asarray(array).reshape(4, 2, int(np.prod(ex)), 3, 3)
    g = np.zeros((4,) + geom.lattice_shape + (3, 3), a.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t + 1) * ex[2] + (z + 1)) * ex[1] + (y + 1)) * ex[0] \
            + (x // 2 + 1)
        for mu in range(4):
            g[mu, t, z, y, x] = np.swapaxes(a[mu, parity, idx], -1, -2)
    return jnp.asarray(g)


def gauge_to_tifr(gauge, geom: LatticeGeometry, scale: float = 1.0):
    """canonical -> TIFR layout (TIFROrder:2199):
    [dir][parity][cb-site][3][3] transposed, scaled by ``scale`` — the
    QDP per-direction even-odd order with CPS's transpose+scale twist,
    so it delegates to the one eo-ordering implementation."""
    q = np.stack([a.reshape(2, geom.volume // 2, 3, 3)
                  for a in gauge_to_qdp(gauge, geom)])
    return np.swapaxes(q, -1, -2) * scale


def gauge_from_tifr(array, geom: LatticeGeometry, scale: float = 1.0):
    a = np.swapaxes(
        np.asarray(array).reshape(4, 2, geom.volume // 2, 3, 3),
        -1, -2) / scale
    return gauge_from_qdp(
        [x.reshape(geom.volume, 3, 3) for x in a], geom)


def gauge_to_tifr_padded(gauge, geom: LatticeGeometry, scale: float = 1.0):
    """canonical -> TIFR-padded layout (TIFRPaddedOrder:2263): like TIFR
    but the z dimension is padded by 4 (interior at z+2)."""
    T, Z, Y, X = geom.lattice_shape
    ex_z = Z + 4
    ex_vol_cb = T * ex_z * Y * X // 2
    g = np.asarray(gauge)
    out = np.zeros((4, 2, ex_vol_cb, 3, 3), g.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t * ex_z) + (z + 2)) * Y + y) * (X // 2) + x // 2
        for mu in range(4):
            out[mu, parity, idx] = np.swapaxes(
                g[mu, t, z, y, x], -1, -2) * scale
    return out


def gauge_from_tifr_padded(array, geom: LatticeGeometry,
                           scale: float = 1.0):
    T, Z, Y, X = geom.lattice_shape
    ex_z = Z + 4
    a = np.asarray(array).reshape(4, 2, T * ex_z * Y * X // 2, 3, 3)
    g = np.zeros((4,) + geom.lattice_shape + (3, 3), a.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t * ex_z) + (z + 2)) * Y + y) * (X // 2) + x // 2
        for mu in range(4):
            g[mu, t, z, y, x] = np.swapaxes(a[mu, parity, idx],
                                            -1, -2) / scale
    return jnp.asarray(g)


def spinor_to_tifr_padded(psi, geom: LatticeGeometry):
    """canonical (T,Z,Y,X,4,3) -> TIFR-padded spinor
    (color_spinor_field_order.h PaddedSpaceSpinorColorOrder:1683):
    [2][padded-cb-site][4 spin][3 color], z padded by 4."""
    T, Z, Y, X = geom.lattice_shape
    ex_z = Z + 4
    p = np.asarray(psi)
    out = np.zeros((2, T * ex_z * Y * X // 2, 4, 3), p.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t * ex_z) + (z + 2)) * Y + y) * (X // 2) + x // 2
        out[parity, idx] = p[t, z, y, x]
    return out


def spinor_from_tifr_padded(array, geom: LatticeGeometry):
    T, Z, Y, X = geom.lattice_shape
    ex_z = Z + 4
    a = np.asarray(array).reshape(2, T * ex_z * Y * X // 2, 4, 3)
    p = np.zeros(geom.lattice_shape + (4, 3), a.dtype)
    for parity in (0, 1):
        t, z, y, x = _cb_coords(geom, parity)
        idx = (((t * ex_z) + (z + 2)) * Y + y) * (X // 2) + x // 2
        p[t, z, y, x] = a[parity, idx]
    return jnp.asarray(p)
