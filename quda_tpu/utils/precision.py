"""Precision ladder for TPU (QudaPrecision analog).

QUDA's ladder {double, single, half, quarter} (include/enum_quda.h) maps to
TPU-native dtypes:

| QUDA     | storage                    | compute       | where           |
|----------|----------------------------|---------------|-----------------|
| double   | complex128                 | f64           | CPU only (tests, scalars) |
| single   | complex64                  | f32           | everywhere      |
| half     | bf16 pair (+ site norm)    | f32 on MXU    | sloppy fields   |
| quarter  | int8 block-float (+ norm)  | f32           | planned         |

TPU has no native f64; QUDA's half (fp16 + per-site norm,
include/color_spinor_field_order.h block-float accessors) becomes bf16 —
bf16 has fp32's exponent range so the per-site norm array is unnecessary,
which removes an entire accessor layer.  int8 block-float (quarter) keeps
the norm concept; see ops/blockfloat.py.
"""

from __future__ import annotations

import jax.numpy as jnp

DOUBLE = "double"
SINGLE = "single"
HALF = "half"
QUARTER = "quarter"

COMPLEX_DTYPE = {
    DOUBLE: jnp.complex128,
    SINGLE: jnp.complex64,
    # half/quarter are storage codecs, not complex dtypes; compute at c64
    HALF: jnp.complex64,
    QUARTER: jnp.complex64,
}

REAL_DTYPE = {
    DOUBLE: jnp.float64,
    SINGLE: jnp.float32,
    HALF: jnp.bfloat16,
    QUARTER: jnp.int8,
}


def complex_dtype(prec: str):
    return COMPLEX_DTYPE[prec]


def sloppy_pair(precise: str) -> str:
    """Default sloppy precision for a given precise precision."""
    return {DOUBLE: SINGLE, SINGLE: HALF, HALF: HALF, QUARTER: QUARTER}[precise]
