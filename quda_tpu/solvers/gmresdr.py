"""GMRES-DR: GMRES with deflated restarts (Morgan).

Reference behavior: lib/inv_gmresdr_quda.cpp (562 LoC).  Restarted GMRES
whose restart subspace is augmented with the k lowest Ritz vectors of the
Hessenberg matrix, so the low modes that stall restarted GMRES stay in the
space across cycles.  Small dense work (least squares, eigenvectors, QR)
on the host; basis rotations as jitted einsums.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import blas
from .cg import SolverResult


def gmres_dr(matvec: Callable, b: jnp.ndarray, m: int = 20, k: int = 5,
             x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
             max_cycles: int = 100) -> SolverResult:
    assert 0 < k < m
    mv = jax.jit(matvec)
    rotate = jax.jit(
        lambda V, U: jnp.einsum("ij,i...->j...", jnp.asarray(U, V.dtype), V))
    b2 = float(blas.norm2(b))
    stop = (tol ** 2) * b2

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - mv(x)

    V = jnp.zeros((m + 1,) + b.shape, b.dtype)
    H = np.zeros((m + 1, m), complex)
    beta = float(np.sqrt(float(blas.norm2(r))))
    V = V.at[0].set((r / beta).astype(b.dtype))
    c = np.zeros(m + 1, complex)
    c[0] = beta
    start = 0
    total = 0

    for _ in range(max_cycles):
        # Arnoldi from column `start` to m
        for j in range(start, m):
            w = mv(V[j])
            coef = jnp.einsum("i...,...->i", jnp.conjugate(V[:j + 1]), w)
            w = w - jnp.einsum("i,i...->...", coef, V[:j + 1])
            coef2 = jnp.einsum("i...,...->i", jnp.conjugate(V[:j + 1]), w)
            w = w - jnp.einsum("i,i...->...", coef2, V[:j + 1])
            H[:j + 1, j] += np.asarray(coef + coef2)
            hb = float(np.sqrt(float(blas.norm2(w))))
            H[j + 1, j] = hb
            V = V.at[j + 1].set(w / max(hb, 1e-30))
        total += m - start

        # least squares min ||c - Hbar y||
        y, *_ = np.linalg.lstsq(H, c, rcond=None)
        x = x + rotate(V[:m], y.reshape(m, 1))[0]
        chat = c - H @ y
        r2 = float(np.vdot(chat, chat).real)
        if r2 <= stop:
            r = b - mv(x)
            r2t = float(blas.norm2(r))
            return SolverResult(x, jnp.int32(total), jnp.asarray(r2t),
                                jnp.asarray(r2t <= stop * 1.01 + 0.0) > 0)

        # deflated restart (Morgan): k lowest Ritz vectors of H_m + chat
        theta, G = np.linalg.eig(H[:m, :m])
        order = np.argsort(np.abs(theta))
        P = np.zeros((m + 1, k + 1), complex)
        P[:m, :k] = G[:, order[:k]]
        P[:, k] = chat
        Q, _ = np.linalg.qr(P)
        Hnew = np.zeros((m + 1, m), complex)
        Hnew[:k + 1, :k] = Q.conj().T @ (H @ Q[:m, :k])
        Vnew = rotate(V, Q)                     # (k+1, ...)
        V = V.at[:k + 1].set(Vnew)
        H = Hnew
        c = Q.conj().T @ chat
        c = np.concatenate([c, np.zeros(m - k, complex)])
        start = k

    r = b - mv(x)
    r2t = float(blas.norm2(r))
    return SolverResult(x, jnp.int32(total), jnp.asarray(r2t),
                        jnp.asarray(r2t <= stop))
