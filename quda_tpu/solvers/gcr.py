"""GCR (flexible, restarted), MR, and SD solvers.

Reference behavior: lib/inv_gcr_quda.cpp (433 LoC; the multigrid outer
wrapper and DD-preconditioner host), lib/inv_mr_quda.cpp (171; the MG
smoother), lib/inv_sd_quda.cpp (99).

GCR is FLEXIBLE: the preconditioner K may change between iterations (an MG
V-cycle, a lower-precision inner solve).  One restart cycle of length
``nkrylov`` runs as an unrolled loop storing the (p, Ap) basis in stacked
buffers; cycles iterate in a host-level Python loop (restarts are few and
QUDA also re-orthogonalises on the host side).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def _identity(v):
    return v


@lru_cache(maxsize=64)
def _gcr_cycle(matvec, K, nkrylov: int, dtype_name: str):
    """Cached jitted GCR cycle — keyed on the (hashable) operator
    callables so repeated solves (HMC, resident MG) reuse the compiled
    unrolled cycle instead of re-tracing every call."""

    @jax.jit
    def cycle(x, r):
        ps, aps, ap2s = [], [], []
        dt = x.dtype
        for _ in range(nkrylov):
            z = K(r)
            az = matvec(z)
            # modified Gram-Schmidt of az against previous Ap's
            for p_i, ap_i, ap2_i in zip(ps, aps, ap2s):
                c = blas.cdot(ap_i, az) / ap2_i.astype(dt)
                az = az - c * ap_i
                z = z - c * p_i
            ap2 = blas.norm2(az)
            ps.append(z)
            aps.append(az)
            ap2s.append(ap2)
            alpha = blas.cdot(az, r) / ap2.astype(dt)
            x = x + alpha * z
            r = r - alpha * az
        return x, r, blas.norm2(r)

    return cycle


def gcr(matvec: Callable, b: jnp.ndarray, precond: Optional[Callable] = None,
        x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
        nkrylov: int = 10, max_restarts: int = 50) -> SolverResult:
    import math

    from ..robust import sentinel as rsent
    b2 = blas.norm2(b)
    stop = float((tol ** 2) * b2)
    K = _identity if precond is None else precond
    try:
        cycle = _gcr_cycle(matvec, K, nkrylov, str(b.dtype))
    except TypeError:  # unhashable callables: fall back to per-call jit
        _gcr_cycle.cache_clear()
        cycle = _gcr_cycle.__wrapped__(matvec, K, nkrylov, str(b.dtype))

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    total = 0
    r2 = blas.norm2(r)
    # gcr restarts on the HOST, so the breakdown sentinel is a plain
    # python check between cycles (robust/sentinel.py; off = unchanged)
    guard = rsent.active()
    bk = None
    for _ in range(max_restarts):
        if guard and not math.isfinite(float(r2)):
            break
        if float(r2) <= stop:
            break
        x, r, r2 = cycle(x, r)
        total += nkrylov
    if guard:
        # checked AFTER the loop too: the final cycle (or the
        # max_restarts-th) can be the one that NaNs, and it must not
        # exit classified 'none'
        bk = jnp.int32(rsent.NONFINITE
                       if not math.isfinite(float(r2))
                       else rsent.NONE)
    conv = r2 <= stop
    if bk is not None:
        conv = jnp.logical_and(conv, bk == rsent.NONE)
    return SolverResult(x, jnp.int32(total), r2, conv, None, bk)


def gcr_fixed(matvec: Callable, b: jnp.ndarray, nkrylov: int = 8,
              cycles: int = 1, x0=None) -> jnp.ndarray:
    """Fixed-work GCR (no convergence test) — jit-pure; used as the
    coarsest-level solver inside MG V-cycles."""
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    tiny = 1e-30
    for _ in range(cycles):
        ps, aps, ap2s = [], [], []
        for _ in range(nkrylov):
            z = r
            az = matvec(z)
            for p_i, ap_i, ap2_i in zip(ps, aps, ap2s):
                c = blas.cdot(ap_i, az) / (ap2_i + tiny).astype(b.dtype)
                az = az - c * ap_i
                z = z - c * p_i
            ap2 = blas.norm2(az)
            ps.append(z)
            aps.append(az)
            ap2s.append(ap2)
            alpha = blas.cdot(az, r) / (ap2 + tiny).astype(b.dtype)
            x = x + alpha * z
            r = r - alpha * az
    return x


def mr(matvec: Callable, b: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
       tol: float = 1e-10, maxiter: int = 100,
       omega: float = 1.0) -> SolverResult:
    """Minimal residual iteration (the MG smoother; omega = relaxation)."""
    from ..robust import sentinel as rsent
    sent = rsent.make()
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)

    def cond(c):
        x, r, r2, k = c[:4]
        go = jnp.logical_and(r2 > stop, k < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c[-1]))
        return go

    def body(c):
        x, r, r2, k = c[:4]
        ar = matvec(r)
        alpha = blas.cdot(ar, r) / jnp.maximum(
            blas.norm2(ar), jnp.finfo(r2.dtype).tiny).astype(b.dtype)
        x = x + omega * alpha * r
        r = r - omega * alpha * ar
        r2n = blas.norm2(r)
        out = (x, r, r2n, k + 1)
        if sent is not None:
            out = out + (sent.step(c[-1], r2n),)
        return out

    init = (x, r, blas.norm2(r), jnp.int32(0))
    if sent is not None:
        init = init + (sent.init(init[2]),)
    out = jax.lax.while_loop(cond, body, init)
    x, r, r2, k = out[:4]
    conv, bk = rsent.finalize(sent,
                              out[-1] if sent is not None else None,
                              r2 <= stop)
    return SolverResult(x, k, r2, conv, None, bk)


def mr_fixed(matvec: Callable, b: jnp.ndarray, n_iters: int,
             omega: float = 1.0, x0=None):
    """Fixed-iteration MR via scan — shape-stable smoother for MG cycles."""
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)

    def body(c, _):
        x, r = c
        ar = matvec(r)
        alpha = blas.cdot(ar, r) / jnp.maximum(
            blas.norm2(ar), 1e-30).astype(b.dtype)
        return (x + omega * alpha * r, r - omega * alpha * ar), None

    (x, r), _ = jax.lax.scan(body, (x, r), None, length=n_iters)
    return x


def sd(matvec: Callable, b: jnp.ndarray, x0=None, tol: float = 1e-10,
       maxiter: int = 100) -> SolverResult:
    """Steepest descent for Hermitian positive-definite matvec."""
    from ..robust import sentinel as rsent
    sent = rsent.make()
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)

    def cond(c):
        x, r, r2, k = c[:4]
        go = jnp.logical_and(r2 > stop, k < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c[-1]))
        return go

    def body(c):
        x, r, r2, k = c[:4]
        ar = matvec(r)
        rAr = blas.redot(r, ar)
        alpha = (r2 / rAr).astype(b.dtype)
        x = x + alpha * r
        r = r - alpha * ar
        r2n = blas.norm2(r)
        out = (x, r, r2n, k + 1)
        if sent is not None:
            out = out + (sent.step(c[-1], r2n, denom=rAr),)
        return out

    init = (x, r, blas.norm2(r), jnp.int32(0))
    if sent is not None:
        init = init + (sent.init(init[2]),)
    out = jax.lax.while_loop(cond, body, init)
    x, r, r2, k = out[:4]
    conv, bk = rsent.finalize(sent,
                              out[-1] if sent is not None else None,
                              r2 <= stop)
    return SolverResult(x, k, r2, conv, None, bk)
