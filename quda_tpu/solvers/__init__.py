"""Krylov solver suite (Solver::create analog, lib/solver.cpp:59-155).

All solvers are pure functions over a ``matvec`` closure; ``create``
resolves QUDA's QudaInverterType names onto them.
"""

from .cg import cg, cg_fixed_iters, SolverResult  # noqa: F401
from .fused_iter import fused_cg  # noqa: F401
from .cg3 import cg3, cgne, cgnr  # noqa: F401
from .bicgstab import bicgstab, bicgstab_l  # noqa: F401
from .gcr import gcr, mr, mr_fixed, sd  # noqa: F401
from .ca import ca_cg, ca_gcr  # noqa: F401
from .multishift import multishift_cg  # noqa: F401
from .mixed import (cg_reliable, cg_reliable_df, dtype_codec,  # noqa: F401
                    pair_codec, pair_inplace_codec, solve_refined)
from .block import (batched_bicgstab_pairs, batched_cg,  # noqa: F401
                    batched_cg_pairs, block_cg, block_cg_pairs,
                    BatchedCGResult, BlockCGResult)
from .chrono import ChronoStore, mre_guess  # noqa: F401

_REGISTRY = {
    "cg": cg,
    "cg3": cg3,
    "cgne": cgne,
    "cgnr": cgnr,
    "pcg": cg,            # preconditioner passed via precond=
    "bicgstab": bicgstab,
    "bicgstab-l": bicgstab_l,
    "gcr": gcr,
    "mr": mr,
    "sd": sd,
    "ca-cg": ca_cg,
    "ca-gcr": ca_gcr,
    "multi-shift-cg": multishift_cg,
}


def create(name: str):
    """Look up a solver by (QUDA-style) name."""
    key = name.lower().replace("_", "-")
    if key not in _REGISTRY:
        raise ValueError(
            f"unknown solver '{name}'; available: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
