"""CG3 (three-term recurrence CG) and the normal-equation wrappers
CGNE / CGNR / CG3NE / CG3NR.

Reference behavior: lib/inv_cg3_quda.cpp (304 LoC), lib/inv_cgne.cpp,
lib/inv_cgnr.cpp.  CG3 trades the two-term (x,p) recurrence for a
three-term (x_k, x_{k-1}) one — same Krylov space, different rounding
profile.

  CGNR: solve M^dag M x = M^dag b     (minimises ||b - Mx||)
  CGNE: solve M M^dag y = b, x = M^dag y   (minimises ||x - x*||)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult, cg


def cg3(matvec: Callable, b: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
        tol: float = 1e-10, maxiter: int = 2000) -> SolverResult:
    from ..robust import sentinel as rsent
    sent = rsent.make()
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    rdt = b2.dtype

    state = dict(x=x, x_old=x, r=r, r_old=r, r2=blas.norm2(r),
                 r2_old=jnp.ones((), rdt), rho=jnp.ones((), rdt),
                 k=jnp.int32(0))
    if sent is not None:
        state["sent"] = sent.init(state["r2"])

    def cond(c):
        go = jnp.logical_and(c["r2"] > stop, c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        ar = matvec(c["r"])
        rAr = blas.redot(c["r"], ar)
        gamma = c["r2"] / rAr
        first = c["k"] == 0
        # standard CG3 rho recurrence:
        rho = jnp.where(
            first, jnp.ones((), rdt),
            1.0 / (1.0 - (gamma * c["r2"]) /
                   (c["gamma_old"] * c["r2_old"] * c["rho"])))
        x_new = rho * (c["x"] + gamma.astype(b.dtype) * c["r"]) \
            + (1.0 - rho) * c["x_old"]
        r_new = rho * (c["r"] - gamma.astype(b.dtype) * ar) \
            + (1.0 - rho) * c["r_old"]
        nxt = dict(x=x_new, x_old=c["x"], r=r_new, r_old=c["r"],
                   r2=blas.norm2(r_new), r2_old=c["r2"], rho=rho,
                   gamma_old=gamma, k=c["k"] + 1)
        if sent is not None:
            nxt["sent"] = sent.step(c["sent"], nxt["r2"], denom=rAr)
        return nxt

    state["gamma_old"] = jnp.ones((), rdt)
    out = jax.lax.while_loop(cond, body, state)
    conv, bk = rsent.finalize(sent, out.get("sent"),
                              out["r2"] <= stop)
    return SolverResult(out["x"], out["k"], out["r2"], conv, None, bk)


def cgnr(M: Callable, Mdag: Callable, b: jnp.ndarray, tol: float = 1e-10,
         maxiter: int = 2000, use_cg3: bool = False) -> SolverResult:
    rhs = Mdag(b)
    solver = cg3 if use_cg3 else cg
    mdagm = lambda v: Mdag(M(v))
    # scale tolerance: ||Mdag r|| <= ||Mdag|| ||r||; QUDA also solves the
    # normal system to tol on its own residual
    return solver(mdagm, rhs, tol=tol, maxiter=maxiter)


def cgne(M: Callable, Mdag: Callable, b: jnp.ndarray, tol: float = 1e-10,
         maxiter: int = 2000, use_cg3: bool = False) -> SolverResult:
    solver = cg3 if use_cg3 else cg
    mmdag = lambda v: M(Mdag(v))
    res = solver(mmdag, b, tol=tol, maxiter=maxiter)
    # preserve the inner solve's history/breakdown fields — dropping
    # them here would erase the sentinel's typed reason at the API
    # layer (the supervision epilogue reads res.breakdown)
    return res._replace(x=Mdag(res.x))
