"""MSPCG: Möbius-accelerated Schwarz-preconditioned CG.

Reference behavior: QUDA's MSPCG (inv_pcg_quda.cpp with DiracMobiusPC
MdagMLocal, the comm-free local Möbius normal operator) — the inner
preconditioner applies a few iterations of the LOCAL (halo-free) operator,
trading communication for extra local flops on strong-scaled systems.

Built from existing pieces: parallel/schwarz.py's domain_shift turns any
stencil into its Dirichlet-boundary local version; cg() with precond= is
flexible PCG.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax.numpy as jnp

from ..fields.geometry import LatticeGeometry
from ..parallel.schwarz import make_domain_shift
from .cg import SolverResult, cg, cg_fixed_iters


def make_local_mdagm(geom: LatticeGeometry,
                     domain: Tuple[int, int, int, int],
                     build_mdagm_with_shift: Callable) -> Callable:
    """build_mdagm_with_shift(shift_fn) -> MdagM closure; returns the
    comm-free local MdagM (the MdagMLocal analog)."""
    dshift = make_domain_shift(geom, domain)
    return build_mdagm_with_shift(dshift)


def mspcg(mdagm: Callable, mdagm_local: Callable, b: jnp.ndarray,
          tol: float = 1e-10, maxiter: int = 2000,
          inner_iters: int = 5) -> SolverResult:
    """PCG on mdagm with K = fixed-iteration CG on the local operator."""

    def K(r):
        return cg_fixed_iters(mdagm_local, r, None, inner_iters)[0].x

    return cg(mdagm, b, tol=tol, maxiter=maxiter, precond=K)
