"""Multi-shift CG: solve (A + sigma_i) x_i = b for all shifts at once.

Reference behavior: lib/inv_multi_cg_quda.cpp (493 LoC) — the RHMC
rational-approximation solver for staggered/HISQ.  One Krylov space serves
every shift via the shifted-CG zeta recurrences (a single matvec per
iteration); per-shift convergence is tracked through the analytically known
shifted residual |r_s| = zeta_s |r|.

The shift vector is a static (Python) tuple; the shifted iterates are a
stacked leading axis so the per-shift axpys are one fused broadcast —
QUDA's hand-written multi-shift update kernels (multi_blas) fall out of XLA
fusion for free.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp

from ..ops import blas


class MultiShiftResult(NamedTuple):
    x: jnp.ndarray          # (n_shifts, ...) solutions
    iters: jnp.ndarray
    r2: jnp.ndarray         # base-system final |r|^2
    converged: jnp.ndarray  # (n_shifts,) bool
    # optional per-iteration history (record=True): {"r2": base-system
    # norms, "shift_r2": (slots, n_shifts) analytic shifted residuals}
    history: object = None
    # optional typed breakdown code (robust/sentinel.py; None on
    # unguarded solves — see solvers/cg.SolverResult.breakdown)
    breakdown: object = None


def multishift_cg(matvec: Callable, b: jnp.ndarray,
                  shifts: Sequence[float], tol: float = 1e-10,
                  maxiter: int = 2000,
                  record: bool = False) -> MultiShiftResult:
    """Solve (matvec + shift_i) x_i = b, matvec Hermitian positive
    semi-definite and every shift >= 0 (the RHMC setting).

    Shifts are offset so the BASE system includes the smallest shift (QUDA
    orders shifts ascending and iterates the zeroth); convergence of shift i
    is |r_i|^2 = zeta_i^2 |r|^2 <= tol^2 |b|^2.

    ``record=True`` additionally returns per-iteration base residual
    norms and the analytically-known per-shift residuals
    (|r_s|^2 = zeta_s^2 |r|^2) as ``history`` for obs/convergence.py.
    """
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")
    shifts = tuple(float(s) for s in shifts)
    ns = len(shifts)
    s0 = min(shifts)
    sig = jnp.asarray([s - s0 for s in shifts], b.real.dtype)  # >= 0
    base = lambda v: matvec(v) + (s0 * v if s0 != 0.0 else 0.0 * v)

    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    rdt = b2.dtype

    def expand(a):
        """(ns,) scalars -> broadcastable over stacked fields."""
        return a.reshape((ns,) + (1,) * b.ndim)

    state = dict(
        x=jnp.zeros((ns,) + b.shape, b.dtype),
        p=jnp.broadcast_to(b, (ns,) + b.shape).astype(b.dtype),
        r=b,
        r2=b2,
        zeta=jnp.ones((ns,), rdt),
        zeta_old=jnp.ones((ns,), rdt),
        alpha_old=jnp.ones((), rdt),
        beta_old=jnp.zeros((), rdt),
        k=jnp.int32(0),
    )
    if record:
        state["hist"] = jnp.full((maxiter + 1,), jnp.nan, rdt)
        state["shist"] = jnp.full((maxiter + 1, ns), jnp.nan, rdt)
    if sent is not None:
        state["sent"] = sent.init(b2)

    def shift_r2(c):
        return (c["zeta"] ** 2) * c["r2"]

    def cond(c):
        go = jnp.logical_and(jnp.max(shift_r2(c)) > stop,
                             c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        p0 = c["p"][0]
        Ap = base(p0)
        if fault_k is not None:
            Ap = finj.corrupt(Ap, c["k"], fault_k)
        pAp = blas.redot(p0, Ap).astype(rdt)
        alpha = c["r2"] / pAp

        # zeta recurrence (Frommer/van der Vorst shifted CG)
        zn = c["zeta"] * c["zeta_old"] * c["alpha_old"]
        zd = (alpha * c["beta_old"] * (c["zeta_old"] - c["zeta"])
              + c["zeta_old"] * c["alpha_old"] * (1.0 + sig * alpha))
        zeta_new = jnp.where(zd != 0, zn / jnp.where(zd != 0, zd, 1.0), 0.0)
        alpha_s = alpha * jnp.where(c["zeta"] != 0,
                                    zeta_new / jnp.where(c["zeta"] != 0,
                                                         c["zeta"], 1.0), 0.0)

        x = c["x"] + expand(alpha_s).astype(b.dtype) * c["p"]
        r = c["r"] - alpha.astype(b.dtype) * Ap
        r2_new = blas.norm2(r).astype(rdt)
        beta = r2_new / c["r2"]
        beta_s = beta * jnp.where(
            c["zeta"] != 0,
            (zeta_new / jnp.where(c["zeta"] != 0, c["zeta"], 1.0)) ** 2, 0.0)
        p = (expand(zeta_new).astype(b.dtype) * r[None]
             + expand(beta_s).astype(b.dtype) * c["p"])

        nxt = dict(x=x, p=p, r=r, r2=r2_new, zeta=zeta_new,
                   zeta_old=c["zeta"], alpha_old=alpha, beta_old=beta,
                   k=c["k"] + 1)
        if record:
            nxt["hist"] = c["hist"].at[c["k"]].set(r2_new)
            nxt["shist"] = c["shist"].at[c["k"]].set(
                (zeta_new ** 2) * r2_new)
        if sent is not None:
            nxt["sent"] = sent.step(c["sent"], r2_new, denom=pAp)
        return nxt

    out = jax.lax.while_loop(cond, body, state)
    conv = shift_r2(out) <= stop
    hist = ({"r2": out["hist"], "shift_r2": out["shist"]} if record
            else None)
    conv, bk = rsent.finalize(sent, out.get("sent"), conv)
    return MultiShiftResult(out["x"], out["k"], out["r2"], conv, hist,
                            bk)
