"""BiCGStab and BiCGStab(L) for non-Hermitian systems.

Reference behavior: lib/inv_bicgstab_quda.cpp (384 LoC),
lib/inv_bicgstabl_quda.cpp (760 LoC).  Both run directly on M (no normal
equations), the production solvers for Wilson/clover PC systems.

BiCGStab(L) follows Sleijpen-Fokkema: L BiCG steps building residual/search
histories, then an L-dimensional minimal-residual polynomial update solved
as a small dense least-squares (jnp.linalg.solve on the (L,L) Gram matrix —
host-free, MXU-friendly).  L is static; the inner loops unroll at trace
time the way QUDA's templates instantiate per-L kernels.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def bicgstab(matvec: Callable, b: jnp.ndarray,
             x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
             maxiter: int = 2000, record: bool = False) -> SolverResult:
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    rhat = r
    dt = b.dtype

    one = jnp.ones((), dt)
    state = dict(x=x, r=r, p=jnp.zeros_like(b), v=jnp.zeros_like(b),
                 rho=one, alpha=one, omega=one,
                 r2=blas.norm2(r), k=jnp.int32(0))
    if record:
        state["hist"] = jnp.full((maxiter + 1,), jnp.nan,
                                 state["r2"].dtype)
    if sent is not None:
        state["sent"] = sent.init(state["r2"])

    def cond(c):
        go = jnp.logical_and(c["r2"] > stop, c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        rho_new = blas.cdot(rhat, c["r"])
        beta = (rho_new / c["rho"]) * (c["alpha"] / c["omega"])
        p = c["r"] + beta * (c["p"] - c["omega"] * c["v"])
        v = matvec(p)
        if fault_k is not None:
            v = finj.corrupt(v, c["k"], fault_k)
        alpha = rho_new / blas.cdot(rhat, v)
        s = c["r"] - alpha * v
        t = matvec(s)
        omega = blas.cdot(t, s) / jnp.maximum(
            blas.norm2(t), jnp.finfo(c["r2"].dtype).tiny).astype(dt)
        x = c["x"] + alpha * p + omega * s
        r = s - omega * t
        nxt = dict(x=x, r=r, p=p, v=v, rho=rho_new, alpha=alpha,
                   omega=omega, r2=blas.norm2(r), k=c["k"] + 1)
        if record:
            nxt["hist"] = c["hist"].at[c["k"]].set(nxt["r2"])
        if sent is not None:
            # rho/omega breakdown surfaces as a non-finite r2 within an
            # iteration — the finiteness predicate catches both
            nxt["sent"] = sent.step(c["sent"], nxt["r2"])
        return nxt

    out = jax.lax.while_loop(cond, body, state)
    conv, bk = rsent.finalize(sent, out.get("sent"),
                              out["r2"] <= stop)
    return SolverResult(out["x"], out["k"], out["r2"], conv,
                        out["hist"] if record else None, bk)


def bicgstab_l(matvec: Callable, b: jnp.ndarray, L: int = 4,
               x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
               maxiter: int = 2000, record: bool = False) -> SolverResult:
    """BiCGStab(L); maxiter counts matvec applications (2L per cycle).
    ``record=True`` captures |r|^2 once per cycle (cadence 2L in the
    harvested history — each cycle IS 2L matvec applications)."""
    from ..robust import sentinel as rsent
    sent = rsent.make()
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b) if x0 is None else x0
    r0 = b if x0 is None else b - matvec(x)
    rhat = r0
    dt = b.dtype
    rdt = b2.dtype

    state = dict(x=x,
                 r=jnp.broadcast_to(r0, (L + 1,) + b.shape).astype(dt) * 0,
                 u=jnp.zeros((L + 1,) + b.shape, dt),
                 rho=jnp.ones((), dt), alpha=jnp.zeros((), dt),
                 omega=jnp.ones((), dt),
                 r2=blas.norm2(r0), k=jnp.int32(0))
    state["r"] = state["r"].at[0].set(r0)
    if record:
        state["hist"] = jnp.full((maxiter // (2 * L) + 2,), jnp.nan,
                                 rdt)
    if sent is not None:
        state["sent"] = sent.init(state["r2"])

    def cond(c):
        go = jnp.logical_and(c["r2"] > stop, c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        x, r, u = c["x"], c["r"], c["u"]
        rho, alpha, omega = c["rho"], c["alpha"], c["omega"]
        rho = -omega * rho
        # --- BiCG part (unrolled, j = 0..L-1) ---
        for j in range(L):
            rho_new = blas.cdot(rhat, r[j])
            beta = alpha * rho_new / rho
            rho = rho_new
            for i in range(j + 1):
                u = u.at[i].set(r[i] - beta * u[i])
            u = u.at[j + 1].set(matvec(u[j]))
            gamma = blas.cdot(rhat, u[j + 1])
            alpha = rho / gamma
            for i in range(j + 1):
                r = r.at[i].set(r[i] - alpha * u[i + 1])
            r = r.at[j + 1].set(matvec(r[j]))
            x = x + alpha * u[0]
        # --- MR part: minimise ||r0 - sum_{j=1..L} g_j r_j|| ---
        rs = r[1:]                                  # (L, ...)
        G = jnp.einsum("i...,j...->ij", jnp.conjugate(rs), rs)
        rhs = jnp.einsum("i...,...->i", jnp.conjugate(rs), r[0])
        g = jnp.linalg.solve(G, rhs)                # (L,)
        x = x + jnp.einsum("j,j...->...", g, r[:-1])
        u0 = u[0] - jnp.einsum("j,j...->...", g, u[1:])
        rnew = r[0] - jnp.einsum("j,j...->...", g, rs)
        omega = g[L - 1]
        r = r.at[0].set(rnew)
        u = u.at[0].set(u0)
        nxt = dict(x=x, r=r, u=u, rho=rho, alpha=alpha, omega=omega,
                   r2=blas.norm2(rnew), k=c["k"] + 2 * L)
        if record:
            nxt["hist"] = c["hist"].at[c["k"] // (2 * L)].set(nxt["r2"])
        if sent is not None:
            nxt["sent"] = sent.step(c["sent"], nxt["r2"])
        return nxt

    out = jax.lax.while_loop(cond, body, state)
    conv, bk = rsent.finalize(sent, out.get("sent"),
                              out["r2"] <= stop)
    return SolverResult(out["x"], out["k"], out["r2"], conv,
                        out["hist"] if record else None, bk)
