"""Communication-avoiding (s-step) solvers: CA-CG and CA-GCR.

Reference behavior: lib/inv_ca_cg.cpp (578 LoC), lib/inv_ca_gcr.cpp (398),
QudaCABasis power/Chebyshev basis.

Each outer step builds an s-deep Krylov basis V = [v, A v, ..., A^{s-1} v]
with ONE reduction phase: all Gram-matrix entries are computed as a single
batched einsum (the whole point of CA solvers — QUDA needs one fused
multi-reduce kernel; XLA emits one fused reduction over the stacked basis,
and on a mesh it is one psum instead of s of them).

* ca_gcr: minimises ||r - A V c||_2 each cycle (least squares via the
  normal matrix of the A V basis) — matches QUDA's CA-GCR exactly.
* ca_cg: minimises the A-norm error over span{V, p_prev} (the previous
  outer direction augments the basis, restoring CG-like global convergence).

Chebyshev basis: vectors generated with the shifted-scaled recurrence to
keep the power basis well-conditioned (QUDA QUDA_CHEBYSHEV_BASIS); enabled
via basis="chebyshev" with (lambda_min, lambda_max) estimates.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def _build_basis(matvec, v, s, basis, lam):
    """V = [v, ...] s vectors; power or Chebyshev recurrence."""
    vs = [v]
    if basis == "power":
        for _ in range(s - 1):
            vs.append(matvec(vs[-1]))
    else:
        lo, hi = lam
        a = 2.0 / (hi - lo)
        bshift = -(hi + lo) / (hi - lo)
        # T_0 = v, T_1 = (a A + b) v, T_k = 2 (a A + b) T_{k-1} - T_{k-2}
        def op(u):
            return a * matvec(u) + bshift * u
        if s > 1:
            vs.append(op(v))
        for _ in range(s - 2):
            vs.append(2.0 * op(vs[-1]) - vs[-2])
    return jnp.stack(vs)


@lru_cache(maxsize=64)
def _ca_gcr_cycle(matvec, s, basis, lam):
    @jax.jit
    def cycle(x, r):
        V = _build_basis(matvec, r, s, basis, lam)
        AV = jax.vmap(matvec)(V)
        # one reduction phase: Gram of AV and projections of r
        G = jnp.einsum("i...,j...->ij", jnp.conjugate(AV), AV)
        rhs = jnp.einsum("i...,...->i", jnp.conjugate(AV), r)
        c = jnp.linalg.solve(G, rhs)
        x = x + jnp.einsum("i,i...->...", c, V)
        r = r - jnp.einsum("i,i...->...", c, AV)
        return x, r, blas.norm2(r)

    return cycle


@lru_cache(maxsize=64)
def _ca_cg_cycle(matvec, s, basis, lam):
    @jax.jit
    def cycle(x, r, p_prev, have_prev):
        V = _build_basis(matvec, r, s, basis, lam)
        V = jnp.concatenate([V, p_prev[None]], axis=0)      # (s+1, ...)
        AV = jax.vmap(matvec)(V)
        G = jnp.einsum("i...,j...->ij", jnp.conjugate(V), AV)
        rhs = jnp.einsum("i...,...->i", jnp.conjugate(V), r)
        n = s + 1
        mask = jnp.concatenate([jnp.ones(s), have_prev[None]])
        Gm = G * mask[:, None] * mask[None, :] \
            + jnp.diag(1.0 - mask).astype(G.dtype)
        cvec = jnp.linalg.solve(Gm, rhs * mask.astype(rhs.dtype))
        step = jnp.einsum("i,i...->...", cvec, V)
        x = x + step
        r = r - jnp.einsum("i,i...->...", cvec, AV)
        return x, r, blas.norm2(r), step

    return cycle


def ca_gcr(matvec: Callable, b: jnp.ndarray, s: int = 8,
           x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
           max_cycles: int = 100, basis: str = "power",
           lam: Tuple[float, float] = (0.0, 2.0)) -> SolverResult:
    b2 = blas.norm2(b)
    stop = float((tol ** 2) * b2)
    try:
        cycle = _ca_gcr_cycle(matvec, s, basis, tuple(lam))
    except TypeError:  # unhashable matvec: per-call jit fallback
        cycle = _ca_gcr_cycle.__wrapped__(matvec, s, basis, tuple(lam))

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    r2 = blas.norm2(r)
    it = 0
    for _ in range(max_cycles):
        if float(r2) <= stop:
            break
        x, r, r2 = cycle(x, r)
        it += s
    return SolverResult(x, jnp.int32(it), r2, r2 <= stop)


def ca_cg(matvec: Callable, b: jnp.ndarray, s: int = 8,
          x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
          max_cycles: int = 100, basis: str = "power",
          lam: Tuple[float, float] = (0.0, 2.0)) -> SolverResult:
    """Hermitian positive definite systems; A-norm minimisation per cycle
    over the s-Krylov basis augmented with the previous step direction."""
    b2 = blas.norm2(b)
    stop = float((tol ** 2) * b2)
    try:
        cycle = _ca_cg_cycle(matvec, s, basis, tuple(lam))
    except TypeError:
        cycle = _ca_cg_cycle.__wrapped__(matvec, s, basis, tuple(lam))

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - matvec(x)
    r2 = blas.norm2(r)
    p_prev = jnp.zeros_like(b)
    have = jnp.zeros(())
    it = 0
    for _ in range(max_cycles):
        if float(r2) <= stop:
            break
        x, r, r2, p_prev = cycle(x, r, p_prev, have)
        have = jnp.ones(())
        it += s
    return SolverResult(x, jnp.int32(it), r2, r2 <= stop)
