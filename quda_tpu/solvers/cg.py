"""Conjugate gradient — the workhorse Krylov solver.

Reference behavior: lib/inv_cg_quda.cpp (1736 LoC).  The TPU version is a
`lax.while_loop` so the entire iteration — stencil, fused BLAS, reductions —
compiles to one XLA computation with no host round-trips; QUDA's
heterogeneous-atomic reduction machinery (include/targets/cuda/reduce_helper.h)
exists precisely to hide the device->host sync that XLA never issues here.

Mixed precision with reliable updates (include/reliable_updates.h:33-54)
lives in solvers/mixed.py; this file is the single-precision-domain solver
that runs inside it (and a standalone full-precision solver for tests).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import blas


class SolverResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray      # int32
    r2: jnp.ndarray         # final |r|^2
    converged: jnp.ndarray  # bool
    # optional convergence history (obs/convergence.py): a NaN-padded
    # per-check-point |r|^2 buffer (or a dict of such buffers) when the
    # solver ran with record=True; None (the default) otherwise — the
    # zero-overhead path never allocates it
    history: Optional[object] = None
    # optional typed breakdown code (robust/sentinel.py: 0 = clean exit,
    # else NONFINITE/PIVOT/STAGNATION) when the solve ran with the
    # breakdown sentinel threaded (QUDA_TPU_ROBUST != off); None (the
    # default) on unguarded solves — same discipline as ``history``
    breakdown: Optional[object] = None


def cg(matvec: Callable, b: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
       tol: float = 1e-10, maxiter: int = 1000,
       precond: Optional[Callable] = None,
       tol_hq: float = 0.0,
       check_every: Optional[int] = None,
       record: bool = False) -> SolverResult:
    """Solve matvec(x) = b for Hermitian positive-definite matvec.

    Convergence: |r|^2 <= tol^2 * |b|^2 (QUDA's L2 relative residual,
    lib/solver.cpp stopping condition).  With ``tol_hq > 0`` the
    heavy-quark residual (volume-averaged site-wise |r|/|x|,
    blas.heavy_quark_residual_norm; lib/inv_cg_quda.cpp:80 hq stopping)
    must ALSO drop below tol_hq.  With ``precond`` this is PCG
    (lib/inv_pcg_quda.cpp): K applied each iteration, Polak-Ribiere-free
    standard flexible variant with r.K(r) inner products.

    The iteration body runs on the fused-iteration pipeline
    (solvers/fused_iter.py): the x/r updates and the residual reduction
    share one traversal, and ``check_every`` (default: the
    QUDA_TPU_CG_CHECK_EVERY knob) amortises the convergence check over
    that many dslash applies.
    """
    from .fused_iter import fused_cg
    return fused_cg(matvec, b, x0=x0, tol=tol, maxiter=maxiter,
                    precond=precond, tol_hq=tol_hq,
                    check_every=check_every, record=record)


def cg_fixed_iters(matvec: Callable, b: jnp.ndarray, x0, n_iters: int):
    """Fixed-iteration CG via lax.scan (differentiable, no convergence test).

    Used as an MG setup smoother and inside benchmarks where a static
    iteration count keeps the trace shape-stable.
    """
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b - matvec(x) if x0 is not None else b
    p = r
    r2 = blas.norm2(r)

    def body(carry, _):
        x, r, p, r2 = carry
        Ap = matvec(p)
        # underflow guards: a fixed-iteration scan keeps stepping after
        # the residual hits machine zero (common in f32 MG setup solves,
        # where 100+ iterations converge exactly); unguarded 0/0 here
        # poisons every null vector with NaN
        tiny = jnp.asarray(jnp.finfo(r2.dtype).tiny, r2.dtype)
        alpha = r2 / (blas.redot(p, Ap) + tiny)
        x = x + alpha.astype(x.dtype) * p
        r = r - alpha.astype(x.dtype) * Ap
        r2_new = blas.norm2(r)
        beta = r2_new / (r2 + tiny)
        p = r + beta.astype(x.dtype) * p
        return (x, r, p, r2_new), r2_new

    (x, r, p, r2), hist = jax.lax.scan(body, (x, r, p, r2), None,
                                       length=n_iters)
    return SolverResult(x, jnp.int32(n_iters), r2, r2 >= 0), hist
