"""Conjugate gradient — the workhorse Krylov solver.

Reference behavior: lib/inv_cg_quda.cpp (1736 LoC).  The TPU version is a
`lax.while_loop` so the entire iteration — stencil, fused BLAS, reductions —
compiles to one XLA computation with no host round-trips; QUDA's
heterogeneous-atomic reduction machinery (include/targets/cuda/reduce_helper.h)
exists precisely to hide the device->host sync that XLA never issues here.

Mixed precision with reliable updates (include/reliable_updates.h:33-54)
lives in solvers/mixed.py; this file is the single-precision-domain solver
that runs inside it (and a standalone full-precision solver for tests).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import blas


class SolverResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray      # int32
    r2: jnp.ndarray         # final |r|^2
    converged: jnp.ndarray  # bool


def cg(matvec: Callable, b: jnp.ndarray, x0: Optional[jnp.ndarray] = None,
       tol: float = 1e-10, maxiter: int = 1000,
       precond: Optional[Callable] = None,
       tol_hq: float = 0.0) -> SolverResult:
    """Solve matvec(x) = b for Hermitian positive-definite matvec.

    Convergence: |r|^2 <= tol^2 * |b|^2 (QUDA's L2 relative residual,
    lib/solver.cpp stopping condition).  With ``tol_hq > 0`` the
    heavy-quark residual (volume-averaged site-wise |r|/|x|,
    blas.heavy_quark_residual_norm; lib/inv_cg_quda.cpp:80 hq stopping)
    must ALSO drop below tol_hq.  With ``precond`` this is PCG
    (lib/inv_pcg_quda.cpp): K applied each iteration, Polak-Ribiere-free
    standard flexible variant with r.K(r) inner products.
    """
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2
    use_hq = tol_hq > 0.0
    stop_hq = tol_hq ** 2
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b - matvec(x) if x0 is not None else b

    if precond is None:
        z = r
        rz = blas.norm2(r)
    else:
        z = precond(r)
        rz = blas.redot(r, z)
    p = z
    r2 = blas.norm2(r)

    def hq2(x, r):
        return blas.heavy_quark_residual_norm(x, r)[2]

    def not_done(x, r, r2):
        l2 = r2 > stop
        if not use_hq:
            return l2
        return jnp.logical_or(l2, hq2(x, r) > stop_hq)

    def cond(carry):
        x, r, p, rz, r2, k = carry
        return jnp.logical_and(not_done(x, r, r2), k < maxiter)

    def body(carry):
        x, r, p, rz, r2, k = carry
        Ap = matvec(p)
        pAp = blas.redot(p, Ap)
        alpha = rz / pAp
        x = x + alpha.astype(x.dtype) * p
        r = r - alpha.astype(x.dtype) * Ap
        if precond is None:
            rz_new = blas.norm2(r)
            z = r
        else:
            z = precond(r)
            rz_new = blas.redot(r, z)
        beta = rz_new / rz
        p = z + beta.astype(x.dtype) * p
        r2 = blas.norm2(r)
        return (x, r, p, rz_new, r2, k + 1)

    x, r, p, rz, r2, k = jax.lax.while_loop(
        cond, body, (x, r, p, rz, r2, jnp.int32(0)))
    done = jnp.logical_not(not_done(x, r, r2))
    return SolverResult(x, k, r2, done)


def cg_fixed_iters(matvec: Callable, b: jnp.ndarray, x0, n_iters: int):
    """Fixed-iteration CG via lax.scan (differentiable, no convergence test).

    Used as an MG setup smoother and inside benchmarks where a static
    iteration count keeps the trace shape-stable.
    """
    x = jnp.zeros_like(b) if x0 is None else x0
    r = b - matvec(x) if x0 is not None else b
    p = r
    r2 = blas.norm2(r)

    def body(carry, _):
        x, r, p, r2 = carry
        Ap = matvec(p)
        # underflow guards: a fixed-iteration scan keeps stepping after
        # the residual hits machine zero (common in f32 MG setup solves,
        # where 100+ iterations converge exactly); unguarded 0/0 here
        # poisons every null vector with NaN
        tiny = jnp.asarray(jnp.finfo(r2.dtype).tiny, r2.dtype)
        alpha = r2 / (blas.redot(p, Ap) + tiny)
        x = x + alpha.astype(x.dtype) * p
        r = r - alpha.astype(x.dtype) * Ap
        r2_new = blas.norm2(r)
        beta = r2_new / (r2 + tiny)
        p = r + beta.astype(x.dtype) * p
        return (x, r, p, r2_new), r2_new

    (x, r, p, r2), hist = jax.lax.scan(body, (x, r, p, r2), None,
                                       length=n_iters)
    return SolverResult(x, jnp.int32(n_iters), r2, r2 >= 0), hist
