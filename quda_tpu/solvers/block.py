"""Multi-RHS solving: batched CG (vmap) and true block CG.

Reference behavior: QUDA threads cvector_ref<ColorSpinorField> through
every solver for multi-RHS batching (inv_msrc_cg_quda.cpp, the src_idx
kernel dimension, QUDA_MAX_MULTI_RHS); the MG coarse-dslash MMA path
batches RHS onto tensor cores.

TPU-native: a leading RHS axis + vmap gives the batched solver (XLA turns
the batched stencils into one larger kernel — the MXU sees nrhs x the
work, exactly what the hardware wants), and true block CG shares one
Krylov space across RHS with (nrhs x nrhs) Gram matrices solved on the
fly — communication-optimal for small nrhs.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def _check_nrhs(n: int):
    """QUDA_TPU_MAX_MULTI_RHS advisory cap.  The reference's
    QUDA_MAX_MULTI_RHS is a compile-time instantiation bound, not a
    runtime rejection of user batches — so WARN (the risk is batching
    past device memory) rather than refuse."""
    import warnings

    from ..utils import config as qconf
    cap = qconf.get("QUDA_TPU_MAX_MULTI_RHS", fresh=True)
    if n > cap:
        warnings.warn(
            f"{n} right-hand sides exceeds QUDA_TPU_MAX_MULTI_RHS={cap}; "
            "device memory may not hold the batch — raise the knob to "
            "silence this warning or chunk the sources", stacklevel=3)


def batched_cg(matvec: Callable, B: jnp.ndarray, tol: float = 1e-10,
               maxiter: int = 1000) -> SolverResult:
    """vmapped CG over a leading RHS axis; iterates until ALL converge."""
    from .cg import cg
    _check_nrhs(B.shape[0])
    return jax.vmap(lambda b: cg(matvec, b, tol=tol, maxiter=maxiter))(B)


class BlockCGResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray
    r2: jnp.ndarray          # (nrhs,)
    converged: jnp.ndarray   # (nrhs,)


def block_cg(matvec: Callable, B: jnp.ndarray, tol: float = 1e-10,
             maxiter: int = 1000) -> BlockCGResult:
    """Block CG (O'Leary): solve A X = B sharing one Krylov space.

    B: (nrhs, ...).  Per iteration ONE batched matvec plus two small
    (nrhs, nrhs) Gram solves; RHS with shared spectral content converge in
    fewer iterations than independent CG.
    """
    n = B.shape[0]
    _check_nrhs(n)
    b2 = jax.vmap(blas.norm2)(B)
    stop = (tol ** 2) * b2
    cdt = B.dtype

    def gram(U, V):
        return jnp.einsum("i...,j...->ij", jnp.conjugate(U), V)

    X = jnp.zeros_like(B)
    R = B
    P = R

    def cond(c):
        return jnp.logical_and(jnp.any(c["r2"] > stop),
                               c["k"] < maxiter)

    def body(c):
        X, R, P = c["X"], c["R"], c["P"]
        AP = jax.vmap(matvec)(P)
        pap = gram(P, AP)                       # (n, n)
        rr = gram(R, R)
        # alpha solves (P^H A P) alpha = P^H R
        alpha = jnp.linalg.solve(pap, gram(P, R))
        X = X + jnp.einsum("ij,i...->j...", alpha, P)
        R = R - jnp.einsum("ij,i...->j...", alpha, AP)
        rr_new = gram(R, R)
        beta = jnp.linalg.solve(rr, rr_new)
        P = R + jnp.einsum("ij,i...->j...", beta, P)
        return dict(X=X, R=R, P=P,
                    r2=jnp.real(jnp.einsum("...ii->...i", rr_new[None]))[0],
                    k=c["k"] + 1)

    state = dict(X=X, R=R, P=P, r2=b2, k=jnp.int32(0))
    out = jax.lax.while_loop(cond, body, state)
    return BlockCGResult(out["X"], out["k"], out["r2"],
                         out["r2"] <= stop)
