"""Multi-RHS solving: batched CG (vmap) and true block CG.

Reference behavior: QUDA threads cvector_ref<ColorSpinorField> through
every solver for multi-RHS batching (inv_msrc_cg_quda.cpp, the src_idx
kernel dimension, QUDA_MAX_MULTI_RHS); the MG coarse-dslash MMA path
batches RHS onto tensor cores.

TPU-native: a leading RHS axis + vmap gives the batched solver (XLA turns
the batched stencils into one larger kernel — the MXU sees nrhs x the
work, exactly what the hardware wants), and true block CG shares one
Krylov space across RHS with (nrhs x nrhs) Gram matrices solved on the
fly — communication-optimal for small nrhs.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def _check_nrhs(n: int):
    """QUDA_TPU_MAX_MULTI_RHS advisory cap.  The reference's
    QUDA_MAX_MULTI_RHS is a compile-time instantiation bound, not a
    runtime rejection of user batches — so WARN (the risk is batching
    past device memory) rather than refuse."""
    import warnings

    from ..utils import config as qconf
    cap = qconf.get("QUDA_TPU_MAX_MULTI_RHS", fresh=True)
    if n > cap:
        warnings.warn(
            f"{n} right-hand sides exceeds QUDA_TPU_MAX_MULTI_RHS={cap}; "
            "device memory may not hold the batch — raise the knob to "
            "silence this warning or chunk the sources", stacklevel=3)


def batched_cg(matvec: Callable, B: jnp.ndarray, tol: float = 1e-10,
               maxiter: int = 1000) -> SolverResult:
    """vmapped CG over a leading RHS axis; iterates until ALL converge."""
    from .cg import cg
    _check_nrhs(B.shape[0])
    return jax.vmap(lambda b: cg(matvec, b, tol=tol, maxiter=maxiter))(B)


class BlockCGResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray
    r2: jnp.ndarray          # (nrhs,)
    converged: jnp.ndarray   # (nrhs,)
    # optional (slots, nrhs) per-iteration |r|^2 lanes (record=True)
    history: object = None
    # optional typed breakdown code (robust/sentinel.py; None on
    # unguarded solves — see solvers/cg.SolverResult.breakdown)
    breakdown: object = None


def block_cg(matvec: Callable, B: jnp.ndarray, tol: float = 1e-10,
             maxiter: int = 1000) -> BlockCGResult:
    """Block CG (O'Leary): solve A X = B sharing one Krylov space.

    B: (nrhs, ...).  Per iteration ONE batched matvec plus two small
    (nrhs, nrhs) Gram solves; RHS with shared spectral content converge in
    fewer iterations than independent CG.
    """
    n = B.shape[0]
    _check_nrhs(n)
    b2 = jax.vmap(blas.norm2)(B)
    stop = (tol ** 2) * b2
    cdt = B.dtype

    def gram(U, V):
        return jnp.einsum("i...,j...->ij", jnp.conjugate(U), V)

    X = jnp.zeros_like(B)
    R = B
    P = R

    def cond(c):
        return jnp.logical_and(jnp.any(c["r2"] > stop),
                               c["k"] < maxiter)

    def body(c):
        X, R, P = c["X"], c["R"], c["P"]
        AP = jax.vmap(matvec)(P)
        pap = gram(P, AP)                       # (n, n)
        rr = gram(R, R)
        # alpha solves (P^H A P) alpha = P^H R
        alpha = jnp.linalg.solve(pap, gram(P, R))
        X = X + jnp.einsum("ij,i...->j...", alpha, P)
        R = R - jnp.einsum("ij,i...->j...", alpha, AP)
        rr_new = gram(R, R)
        beta = jnp.linalg.solve(rr, rr_new)
        P = R + jnp.einsum("ij,i...->j...", beta, P)
        return dict(X=X, R=R, P=P,
                    r2=jnp.real(jnp.einsum("...ii->...i", rr_new[None]))[0],
                    k=c["k"] + 1)

    state = dict(X=X, R=R, P=P, r2=b2, k=jnp.int32(0))
    out = jax.lax.while_loop(cond, body, state)
    return BlockCGResult(out["X"], out["k"], out["r2"],
                         out["r2"] <= stop)


# ---------------------------------------------------------------------------
# Pair-form (complex-free) multi-RHS solvers — the packed MRHS pipeline
# ---------------------------------------------------------------------------
#
# The batched invert path (interfaces/quda_api.invert_multi_src_quda)
# keeps every Krylov iterate on packed PAIR arrays (N, 4, 3, 2, T, Z,
# Y*Xh) so the MRHS pallas eo stencil runs INSIDE the compiled batch
# solve.  CG coefficients on the (realified) Hermitian normal operator
# are real, so the pair representation is exact — the same argument as
# the single-RHS pair routes.  Both solvers take a matvec over the FULL
# batch (models/wilson.MdagM_pairs_mrhs or any (N, ...) -> (N, ...)
# callable), not a per-RHS matvec: batching the stencil is the whole
# point (one gauge fetch amortised over N).


class BatchedCGResult(NamedTuple):
    x: jnp.ndarray
    iters: jnp.ndarray       # (nrhs,) iterations to convergence per RHS
    r2: jnp.ndarray          # (nrhs,) final |r|^2
    converged: jnp.ndarray   # (nrhs,)
    # optional (slots, nrhs) per-check-point |r|^2 lanes (record=True)
    history: object = None
    # optional typed breakdown code (robust/sentinel.py; None on
    # unguarded solves — see solvers/cg.SolverResult.breakdown)
    breakdown: object = None


def _per_rhs_dot(u: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    """(N,) real per-RHS inner products — one fused traversal.

    Re<u, v> per RHS: for the real pair arrays every TPU route uses,
    conjugate/real are identity ops (XLA emits the same HLO as the
    plain product — the compiled pair solves are bit-identical); the
    conjugation makes the same lanes serve HERMITIAN COMPLEX batches,
    which is what lets the MG setup run its null-vector inverse
    iterations through this solver on the complex hierarchy too."""
    n = u.shape[0]
    return jnp.sum(jnp.real(jnp.conjugate(u) * v).reshape(n, -1), axis=1)


def _bcast(s: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """(N,) scalars broadcast over the per-RHS field axes."""
    return s.reshape((s.shape[0],) + (1,) * (like.ndim - 1))


def batched_cg_pairs(matvec_batch: Callable, B: jnp.ndarray,
                     tol: float = 1e-10, maxiter: int = 1000,
                     check_every: Optional[int] = None,
                     record: bool = False
                     ) -> BatchedCGResult:
    """Batched CG on pair arrays with the fused-iteration tail.

    Independent CG recurrences in (N,)-vector scalar lanes — each RHS
    follows EXACTLY the trajectory of a solo fused_cg solve — but every
    iteration issues ONE batched matvec, so the MRHS stencil amortises
    the gauge reads.  The fused tail (x += a p; r -= a Ap; per-RHS
    |r|^2 in one traversal) and the ``check_every`` convergence-check
    cadence mirror solvers/fused_iter.py; the loop runs until ALL RHS
    converge (converged lanes keep iterating harmlessly, like
    batched_cg's vmap), and ``iters`` records each RHS's first cadence
    boundary at convergence (unconverged lanes report the total).
    """
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    from .fused_iter import _resolve_check_every
    n = B.shape[0]
    _check_nrhs(n)
    check_every = _resolve_check_every(check_every)
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")
    rdt = jnp.float32 if B.dtype == jnp.bfloat16 else B.dtype
    # scalar-lane dtype: the real counterpart of rdt, so complex
    # batches (the MG setup's null-vector solves on the complex
    # hierarchy) carry real residual lanes; identical to rdt for the
    # real pair arrays
    sdt = jnp.zeros((), rdt).real.dtype
    b2 = _per_rhs_dot(B.astype(rdt), B.astype(rdt))
    stop = (tol ** 2) * b2
    tiny = jnp.asarray(jnp.finfo(sdt).tiny, sdt)

    x = jnp.zeros_like(B)
    r = B
    p = B
    rz = b2

    def one_iter(x, r, p, rz, k):
        Ap = matvec_batch(p)
        if fault_k is not None:
            Ap = finj.corrupt(Ap, k, fault_k)
        pAp = _per_rhs_dot(p.astype(rdt), Ap.astype(rdt))
        alpha = rz / jnp.maximum(pAp, tiny)
        a = _bcast(alpha, x).astype(x.dtype)
        x = x + a * p
        r = r - a * Ap
        r2 = _per_rhs_dot(r.astype(rdt), r.astype(rdt))
        beta = r2 / jnp.maximum(rz, tiny)
        p = r + _bcast(beta, p).astype(p.dtype) * p
        return x, r, p, r2, pAp

    def cond(carry):
        rz, k = carry[3], carry[4]
        go = jnp.logical_and(jnp.any(rz > stop), k < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(carry[-1]))
        return go

    def body(carry):
        x, r, p, rz, k, it_conv = carry[:6]
        pAp = None
        for j in range(check_every):
            x, r, p, rz, pAp = one_iter(x, r, p, rz, k + j)
        k_new = k + check_every
        it_conv = jnp.where((it_conv < 0) & (rz <= stop), k_new, it_conv)
        out = (x, r, p, rz, k_new, it_conv)
        if record:
            out = out + (carry[6].at[k // check_every].set(rz),)
        if sent is not None:
            # aggregate lanes into one scalar per predicate: the sum
            # propagates any lane's NaN, the min pivot flags any
            # non-HPD lane
            out = out + (sent.step(carry[-1], jnp.sum(rz),
                                   denom=jnp.min(pAp)),)
        return out

    it_conv0 = jnp.full((n,), -1, jnp.int32)
    init = (x, r, p, rz, jnp.int32(0), it_conv0)
    if record:
        slots = maxiter // check_every + 2
        init = init + (jnp.full((slots, n), jnp.nan, sdt),)
    if sent is not None:
        init = init + (sent.init(jnp.sum(b2)),)
    out = jax.lax.while_loop(cond, body, init)
    x, r, p, rz, k, it_conv = out[:6]
    it_conv = jnp.where(it_conv < 0, k, it_conv)
    conv, bk = rsent.finalize(sent,
                              out[-1] if sent is not None else None,
                              rz <= stop)
    return BatchedCGResult(x, it_conv, rz, conv,
                           out[6] if record else None, bk)


def batched_bicgstab_pairs(matvec_batch: Callable, B: jnp.ndarray,
                           tol: float = 1e-10, maxiter: int = 1000,
                           ) -> BatchedCGResult:
    """Batched BiCGStab with independent per-RHS scalar lanes.

    The multi-source sibling of solvers/bicgstab.py for DIRECT
    (non-normal) systems: every iteration issues TWO batched matvecs
    (A p and A s) so the MRHS stencil amortises link reads across all
    N lanes, while each lane follows its own BiCGStab recurrence.
    Real arithmetic throughout — pair arrays realify complex systems
    (a real-coefficient Krylov method on the realified operator, the
    same embedding argument as the pair CG routes; the real dots are
    Re<.,.> of the underlying complex vectors).

    This is the MG setup's null-vector solver (mg/mg.py): QUDA's
    generateNullVectors solves M v = r with the setup solver
    (BiCGStab-class) at setup_tol — on kappa-critical Wilson drills
    that converges in ~3-5x fewer dslash applications than CG on the
    squared-condition normal equations, which is where the legacy
    fixed-iteration inverse iteration burned its time.  ``iters``
    reports the iteration of each lane's first converged check
    (2 matvec applies per iteration); converged lanes keep iterating
    harmlessly until all lanes finish."""
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    if jnp.iscomplexobj(B):
        # the scalar lanes are REAL recurrences (Re<.,.> dots — the
        # pair-route embedding): a complex batch fed directly would
        # follow a real-projected BiCGStab that generally stalls for
        # 2*maxiter matvecs.  Realify around the call (as mg/mg.py
        # does) — unlike batched_cg_pairs there is no complex-safe
        # variant of this recurrence to fall through to.
        raise TypeError(
            "batched_bicgstab_pairs needs a REAL (pair/realified) "
            "batch; realify complex systems around the call")
    n = B.shape[0]
    _check_nrhs(n)
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")
    rdt = jnp.float32 if B.dtype == jnp.bfloat16 else B.dtype
    sdt = jnp.zeros((), rdt).real.dtype
    b2 = _per_rhs_dot(B.astype(rdt), B.astype(rdt))
    stop = (tol ** 2) * b2
    tiny = jnp.asarray(jnp.finfo(sdt).tiny, sdt)

    def _safe(d):
        # magnitude-preserving denominator guard: BiCGStab scalars can
        # legitimately be negative (real embedding), so clamp |d| only
        return jnp.where(jnp.abs(d) > tiny, d,
                         jnp.where(d < 0, -tiny, tiny))

    x = jnp.zeros_like(B)
    r = B
    r0 = B
    p = B
    rho = b2

    def body(carry):
        x, r, p, rho, k, it_conv = carry[:6]
        Av = matvec_batch(p)
        if fault_k is not None:
            Av = finj.corrupt(Av, k, fault_k)
        r0v = _per_rhs_dot(r0.astype(rdt), Av.astype(rdt))
        alpha = rho / _safe(r0v)
        s = r - _bcast(alpha, r).astype(r.dtype) * Av
        At = matvec_batch(s)
        tt = _per_rhs_dot(At.astype(rdt), At.astype(rdt))
        ts = _per_rhs_dot(At.astype(rdt), s.astype(rdt))
        omega = ts / jnp.maximum(tt, tiny)
        x = x + _bcast(alpha, x).astype(x.dtype) * p \
            + _bcast(omega, x).astype(x.dtype) * s
        r = s - _bcast(omega, r).astype(r.dtype) * At
        r2 = _per_rhs_dot(r.astype(rdt), r.astype(rdt))
        rho_new = _per_rhs_dot(r0.astype(rdt), r.astype(rdt))
        beta = (rho_new / _safe(rho)) * (alpha / _safe(omega))
        p = r + _bcast(beta, p).astype(p.dtype) * (
            p - _bcast(omega, p).astype(p.dtype) * Av)
        k_new = k + 1
        it_conv = jnp.where((it_conv < 0) & (r2 <= stop), k_new, it_conv)
        out = (x, r, p, rho_new, k_new, it_conv, r2)
        if sent is not None:
            out = out + (sent.step(carry[-1], jnp.sum(r2)),)
        return out

    def cond(carry):
        r2, k = carry[6], carry[4]
        go = jnp.logical_and(
            jnp.logical_and(jnp.any(r2 > stop),
                            jnp.all(jnp.isfinite(r2))),
            k < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(carry[-1]))
        return go

    it_conv0 = jnp.full((n,), -1, jnp.int32)
    init = (x, r, p, rho, jnp.int32(0), it_conv0, b2)
    if sent is not None:
        init = init + (sent.init(jnp.sum(b2)),)
    out = jax.lax.while_loop(cond, body, init)
    x, r2, k, it_conv = out[0], out[6], out[4], out[5]
    it_conv = jnp.where(it_conv < 0, k, it_conv)
    conv, bk = rsent.finalize(sent,
                              out[-1] if sent is not None else None,
                              r2 <= stop)
    return BatchedCGResult(x, it_conv, r2, conv, None, bk)


def block_cg_pairs(matvec_batch: Callable, B: jnp.ndarray,
                   tol: float = 1e-10, maxiter: int = 1000,
                   record: bool = False
                   ) -> BlockCGResult:
    """Block CG (O'Leary) on pair arrays: one shared Krylov space.

    The realified Hermitian system is real SPD, so block CG runs in
    PURE real arithmetic: the (nrhs x nrhs) Gram matrices are real
    matmuls over the flattened site axis — exactly the MXU-friendly
    shape (QUDA's multi_reduce blocks, lib/multi_reduce_quda.cu).  RHS
    sharing spectral content converge in fewer iterations than the
    independent-lane batched solve; the iteration count is shared
    (one Krylov space).

    Breakdown: linearly DEPENDENT sources (e.g. duplicates) make the
    Gram matrices singular — the classic block-CG breakdown, which
    QUDA handles by deflating the block.  Here the loop stops as soon
    as any residual norm goes non-finite and reports those lanes
    unconverged (never garbage-as-success); dedupe the batch or use
    batched_cg_pairs (independent lanes are immune) for such inputs.
    """
    from ..robust import sentinel as rsent
    n = B.shape[0]
    _check_nrhs(n)
    sent = rsent.make()
    rdt = jnp.float32 if B.dtype == jnp.bfloat16 else B.dtype
    b2 = _per_rhs_dot(B.astype(rdt), B.astype(rdt))
    stop = (tol ** 2) * b2

    def gram(U, V):
        # real (N, D) @ (D, N) matmul — the MXU shape
        return jnp.matmul(U.reshape(n, -1).astype(rdt),
                          V.reshape(n, -1).astype(rdt).T)

    def comb(M, U):
        # X_j <- sum_i M[i, j] U_i over the flattened site axis
        return jnp.matmul(M.T.astype(rdt),
                          U.reshape(n, -1).astype(rdt)).reshape(U.shape)

    X = jnp.zeros_like(B)
    R = B
    P = B

    def cond(c):
        # the finiteness guard turns a Gram-breakdown NaN into a clean
        # exit with converged=False instead of silent NaN solutions
        # (always on — it predates the opt-in sentinel and stays as the
        # last line of defense at QUDA_TPU_ROBUST=off)
        go = jnp.logical_and(
            jnp.logical_and(jnp.any(c["r2"] > stop),
                            jnp.all(jnp.isfinite(c["r2"]))),
            c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        X, R, P = c["X"], c["R"], c["P"]
        AP = matvec_batch(P)
        pap = gram(P, AP)
        rr = gram(R, R)
        alpha = jnp.linalg.solve(pap, gram(P, R))
        X = X + comb(alpha, P)
        R = R - comb(alpha, AP)
        rr_new = gram(R, R)
        beta = jnp.linalg.solve(rr, rr_new)
        P = R + comb(beta, P)
        nxt = dict(X=X, R=R, P=P, r2=jnp.diagonal(rr_new),
                   k=c["k"] + 1)
        if record:
            nxt["hist"] = c["hist"].at[c["k"]].set(nxt["r2"])
        if sent is not None:
            # Gram-pivot breakdown: the sum propagates any lane's NaN
            # (a singular Gram solve NaNs the whole block)
            nxt["sent"] = sent.step(c["sent"], jnp.sum(nxt["r2"]))
        return nxt

    state = dict(X=X, R=R, P=P, r2=b2, k=jnp.int32(0))
    if record:
        state["hist"] = jnp.full((maxiter + 1, n), jnp.nan, rdt)
    if sent is not None:
        state["sent"] = sent.init(jnp.sum(b2))
    out = jax.lax.while_loop(cond, body, state)
    conv, bk = rsent.finalize(sent, out.get("sent"),
                              out["r2"] <= stop)
    return BlockCGResult(out["X"], out["k"], out["r2"], conv,
                         out["hist"] if record else None, bk)
