"""Chronological forecasting: minimum-residual extrapolation (MRE).

Reference behavior: lib/inv_mre.cpp (155 LoC) + the chrono store in
lib/solve.cpp:8-19 — past solutions of the same operator seed the next
solve with the min-residual combination, slashing HMC solver iterations.
"""

from __future__ import annotations

from typing import Callable, List

import jax.numpy as jnp

from ..ops import blas


def mre_guess(matvec: Callable, b: jnp.ndarray,
              basis: jnp.ndarray) -> jnp.ndarray:
    """Best initial guess x0 = sum_i c_i basis_i minimising ||b - A x0||.

    basis: (n, ...) stacked past solutions.  One batched matvec + one
    fused reduction (QUDA uses multi-BLAS block dots here).
    """
    Ab = jnp.stack([matvec(basis[i]) for i in range(basis.shape[0])])
    G = jnp.einsum("i...,j...->ij", jnp.conjugate(Ab), Ab)
    rhs = jnp.einsum("i...,...->i", jnp.conjugate(Ab), b)
    # regularised solve (basis vectors can be nearly parallel)
    eps = 1e-12 * jnp.trace(G).real / max(basis.shape[0], 1)
    Gr = G + eps * jnp.eye(G.shape[0], dtype=G.dtype)
    c = jnp.linalg.solve(Gr, rhs)
    return jnp.einsum("i,i...->...", c, basis)


class ChronoStore:
    """Rolling store of past solutions keyed by operator identity
    (flushChronoQuda / QudaInvertParam::chrono_* analog)."""

    def __init__(self, max_dim: int = 8):
        self.max_dim = max_dim
        self._store: List[jnp.ndarray] = []

    def add(self, x: jnp.ndarray):
        self._store.append(x)
        if len(self._store) > self.max_dim:
            self._store.pop(0)

    def guess(self, matvec: Callable, b: jnp.ndarray) -> jnp.ndarray:
        if not self._store:
            return jnp.zeros_like(b)
        return mre_guess(matvec, b, jnp.stack(self._store))

    def flush(self):
        self._store.clear()

    def __len__(self):
        return len(self._store)
