"""eigCG / incremental eigCG: eigenvector harvesting inside CG.

Reference behavior: lib/inv_eigcg_quda.cpp (714 LoC) — Stathopoulos/
Orginos eigCG: while CG iterates, the normalised residuals form a Lanczos
basis whose tridiagonal is known from the CG alpha/beta; when the m-deep
search window fills, it is thick-restarted onto the lowest 2k Ritz vectors.
Incremental eigCG accumulates the harvested eigenvectors across a sequence
of solves (lib/deflation.cpp space) and deflates each subsequent solve.

Host orchestration + jitted lattice work, like the eigensolvers.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..eig.deflation import DeflationSpace, deflated_guess
from ..ops import blas
from .cg import SolverResult


class EigCGResult(NamedTuple):
    x: jnp.ndarray
    iters: int
    r2: float
    converged: bool
    evals: np.ndarray
    evecs: jnp.ndarray


def eigcg(matvec: Callable, b: jnp.ndarray, n_ev: int = 4, m: int = 24,
          x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
          maxiter: int = 2000) -> EigCGResult:
    """CG solve + lowest-eigenpair harvesting (single-rhs eigCG)."""
    assert 2 * n_ev < m
    mv = jax.jit(matvec)
    b2 = float(blas.norm2(b))
    stop = (tol ** 2) * b2

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b if x0 is None else b - mv(x)
    p = r
    r2 = float(blas.norm2(r))

    V = jnp.zeros((m,) + b.shape, b.dtype)
    T = np.zeros((m, m))
    j = 0                       # filled search-space size
    alpha_old, beta_old = 1.0, 0.0
    rotate = jax.jit(
        lambda V, U: jnp.einsum("ij,i...->j...", jnp.asarray(U, V.dtype), V))

    k_iter = 0
    restart_carry = None        # Ritz values on restart (diag of T)
    while r2 > stop and k_iter < maxiter:
        # store normalised residual as Lanczos vector
        v = (r / np.sqrt(r2)).astype(b.dtype)
        if j == m:
            # thick restart: lowest n_ev of T_m and of T_{m-1}, combined
            theta, U = np.linalg.eigh(T)
            theta1, U1 = np.linalg.eigh(T[:m - 1, :m - 1])
            comb = np.zeros((m, 2 * n_ev))
            comb[:, :n_ev] = U[:, :n_ev]
            comb[:m - 1, n_ev:] = U1[:, :n_ev]
            Q, _ = np.linalg.qr(comb)
            Tn = Q.T @ T @ Q
            theta2, U2 = np.linalg.eigh(Tn)
            W = Q @ U2                      # (m, 2k)
            Vk = rotate(V, W)
            V = V.at[:2 * n_ev].set(Vk)
            T = np.zeros((m, m))
            T[np.arange(2 * n_ev), np.arange(2 * n_ev)] = theta2
            j = 2 * n_ev
            restart_carry = True
        V = V.at[j].set(v)
        if restart_carry and j == 2 * n_ev:
            # arrowhead coupling: T[j, :j] = v^T A V[:j] (computed exactly
            # from A v since V[:j] are Ritz vectors)
            av = mv(v)
            coup = np.asarray(
                jnp.einsum("i...,...->i", jnp.conjugate(V[:j]), av)).real
            T[j, :j] = coup
            T[:j, j] = coup
            restart_carry = False

        # one CG step
        Ap = mv(p)
        pAp = float(blas.redot(p, Ap))
        alpha = r2 / pAp
        x = x + alpha * p
        r = r - alpha * Ap
        r2_new = float(blas.norm2(r))
        beta = r2_new / r2

        # Lanczos tridiagonal from CG coefficients
        T[j, j] += 1.0 / alpha + beta_old / alpha_old
        if j + 1 < m:
            T[j + 1, j] = T[j, j + 1] = -np.sqrt(beta) / alpha
        alpha_old, beta_old = alpha, beta
        r2 = r2_new
        p = r + beta * p
        j += 1
        k_iter += 1

    # final eigenpair extraction from the filled part of the space
    jj = max(j, 1)
    theta, U = np.linalg.eigh(T[:jj, :jj])
    nk = min(n_ev, jj)
    Y = rotate(V[:jj], U[:, :nk])
    # Rayleigh quotients on A (refine + orthonormality not enforced)
    evals = []
    for i in range(nk):
        vi = Y[i]
        evals.append(float(blas.cdot(vi, mv(vi)).real
                           / float(blas.norm2(vi))))
    order = np.argsort(evals)
    return EigCGResult(x, k_iter, r2, r2 <= stop,
                       np.asarray(evals)[order], Y[jnp.asarray(order)])


class IncrementalEigCG:
    """inc-eigCG: accumulate a deflation space over a sequence of solves
    (lib/deflation.cpp + the EigCGArgs accumulation loop).

    Accumulation is a Rayleigh–Ritz (Galerkin) pass on the grown space,
    mirroring lib/deflation.cpp's projected-matrix increment: new
    harvested vectors are orthogonalised against the basis (directions
    already represented are DROPPED, not renormalised into noise), the
    projected operator V^dag A V is rediagonalised, and the basis is
    rotated onto its Ritz vectors before truncating to ``max_space``
    lowest.  The rotation is what makes ``deflated_guess``'s diagonal
    spectral inverse valid: plain Gram-Schmidt keeps the SPAN but mixes
    the vectors, so treating (v_i, rayleigh_i) as eigenpairs mis-weights
    the guess and near-duplicate harvests across solves turn into
    amplified noise directions — the pre-round-15 accumulation showed
    zero acceleration because of exactly that.  A·V is carried alongside
    the basis (it rotates with the same U), so each increment costs only
    ``n_ev`` fresh matvecs."""

    def __init__(self, matvec: Callable, n_ev: int = 4, m: int = 24,
                 max_space: int = 32, drop_tol: float = 1e-4):
        self.matvec = matvec
        self.n_ev = n_ev
        self.m = m
        self.max_space = max_space
        self.drop_tol = drop_tol
        self.evecs = None   # (n, ...) Ritz vectors of the space
        self.evals = None   # (n,) Ritz values
        self._av = None     # A @ evecs, rotated in lockstep
        # one jitted wrapper for the life of the accumulator: a fresh
        # jax.jit per solve would retrace the matvec every increment
        self._mv = jax.jit(matvec)

    def _accumulate(self, new_vecs):
        mv = self._mv
        V = [] if self.evecs is None else list(self.evecs)
        W = [] if self._av is None else list(self._av)
        for i in range(new_vecs.shape[0]):
            v = new_vecs[i]
            for u in V:
                v = v - blas.cdot(u, v) * u
            nrm = float(jnp.sqrt(blas.norm2(v)))
            if nrm <= self.drop_tol:
                continue        # already represented: adds no direction
            v = v / nrm
            V.append(v)
            W.append(mv(v))
        Vs, Ws = jnp.stack(V), jnp.stack(W)
        # Rayleigh–Ritz on the accumulated space: G = V^dag (A V) is
        # Hermitian up to rounding; rotate onto its eigenbasis and keep
        # the lowest max_space Ritz pairs (new directions compete with
        # old ones instead of being frozen out by arrival order)
        G = np.asarray(jnp.einsum("i...,j...->ij", jnp.conjugate(Vs), Ws))
        G = 0.5 * (G + G.conj().T)
        theta, U = np.linalg.eigh(G)
        k = min(self.max_space, Vs.shape[0])
        rot = jnp.asarray(U[:, :k], Vs.dtype)
        self.evecs = jnp.einsum("ij,i...->j...", rot, Vs)
        self._av = jnp.einsum("ij,i...->j...", rot, Ws)   # A(VU) = (AV)U
        self.evals = jnp.asarray(theta[:k])

    def solve(self, b: jnp.ndarray, tol: float = 1e-10,
              maxiter: int = 2000) -> EigCGResult:
        x0 = None
        if self.evecs is not None:
            space = DeflationSpace(self.evecs, self.evals)
            x0 = deflated_guess(space, b)
        res = eigcg(self.matvec, b, self.n_ev, self.m, x0=x0, tol=tol,
                    maxiter=maxiter)
        # the Rayleigh–Ritz pass derives its own Ritz values from the
        # projected operator; the per-solve harvested estimates are not
        # consumed here
        self._accumulate(res.evecs)
        return res
