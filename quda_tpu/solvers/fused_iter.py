"""Fused-iteration CG/PCG pipeline — the compiled SOLVE is the artifact.

Round 5 measured the Wilson dslash kernel at 5,673 GFLOPS while the
end-to-end CG solve measured ~89 (VERDICT "What's weak" #1).  QUDA's
whole design tunes the solve, not the kernel in isolation
(lib/inv_cg_quda.cpp, lib/dslash_policy.hpp; PLQCD similarly fuses the
linear-algebra tail with the stencil, arXiv:1405.0700).  This module is
the TPU answer: one place where every CG/PCG iteration body is collapsed
into the smallest number of memory passes, with two levers:

* **Fused tail.**  The iteration tail (x += a p; r -= a Ap; |r|^2) runs
  as ONE traversal — `blas.triple_cg_update` (XLA-fused) or the explicit
  single-VMEM-pass pallas kernel
  (`ops/blas_pallas.cg_update_norm2_pallas`, the reduce_core.cuh:668
  axpyNorm2 analog; `QUDA_TPU_FUSED_TAIL=1` or ``use_pallas_tail``).
  The residual norm that the tail produces is REUSED as the next
  iteration's rz (precond-free CG), so the unfused path's duplicate
  norm2 disappears structurally, not just by compiler CSE.

* **Convergence-check cadence.**  `QUDA_TPU_CG_CHECK_EVERY=k` (or
  ``check_every``) fuses k iterations into each while_loop body, so the
  cond branch — and the heavy-quark reduction when ``tol_hq`` is active —
  runs once per k dslash applies.  The trajectory is IDENTICAL to
  cadence 1 (same update math); the solve merely stops at the first
  multiple of k past convergence, so it reaches the same final residual
  at the cost of up to k-1 extra iterations.  ``iters`` reports the
  iterations actually executed.

Numerical deltas vs the pre-fusion solvers/cg.py loop (documented
bit-tolerance): alpha/beta denominators are guarded with the dtype tiny
(as mixed.cg_reliable always did) — identical results for any convergent
HPD system; the pallas tail's scalar accumulates per-block partials
sequentially, which can differ from jnp.sum's reduction tree in the last
ulp(s) (see ops/blas_pallas.py).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult


def _resolve_check_every(check_every) -> int:
    if check_every is None:
        from ..utils import config as qconf
        check_every = qconf.get("QUDA_TPU_CG_CHECK_EVERY", fresh=True)
    return max(1, int(check_every))


def _resolve_pallas_tail(use_pallas_tail, b) -> bool:
    if use_pallas_tail is None:
        from ..utils import config as qconf
        use_pallas_tail = str(qconf.get("QUDA_TPU_FUSED_TAIL",
                                        fresh=True)) == "1"
    # the pallas kernel serves real (pair-form) fields only; complex
    # solves keep the jnp-fused tail
    return bool(use_pallas_tail) and not jnp.iscomplexobj(b)


def fused_cg(matvec: Callable, b: jnp.ndarray,
             x0: Optional[jnp.ndarray] = None, tol: float = 1e-10,
             maxiter: int = 1000, precond: Optional[Callable] = None,
             tol_hq: float = 0.0, check_every: Optional[int] = None,
             use_pallas_tail: Optional[bool] = None,
             pallas_interpret: Optional[bool] = None,
             record: bool = False) -> SolverResult:
    """CG/PCG with a fused iteration body and check-cadence amortisation.

    Semantics match solvers/cg.cg (which delegates here): convergence at
    |r|^2 <= tol^2 |b|^2, optional heavy-quark residual (tol_hq),
    optional preconditioner (flexible PCG, r.K(r) inner products).
    ``check_every``/``use_pallas_tail`` default to the config knobs
    QUDA_TPU_CG_CHECK_EVERY / QUDA_TPU_FUSED_TAIL;
    ``pallas_interpret=None`` resolves to interpret mode on non-TPU
    backends (so the env knob works on CPU hosts instead of failing to
    lower).  Both the convergence check AND maxiter are evaluated at
    cadence boundaries: with cadence k the solve can run up to k-1
    iterations past convergence or past maxiter — ``iters`` always
    reports the iterations actually executed.

    ``record=True`` threads a NaN-padded |r|^2 history buffer through
    the loop carry, written at every convergence-check point (slot i =
    iteration (i+1)*check_every; intermediate iterations at cadence > 1
    are the documented cadence gaps) and returned as
    ``SolverResult.history`` for obs/convergence.py to harvest.  With
    record=False the carry is unchanged — zero recording overhead.
    """
    check_every = _resolve_check_every(check_every)
    pallas_tail = _resolve_pallas_tail(use_pallas_tail, b)
    if pallas_interpret is None:
        pallas_interpret = jax.default_backend() != "tpu"
    # breakdown sentinel (robust/sentinel.py): None when QUDA_TPU_ROBUST
    # =off — the loop below then traces EXACTLY the unguarded
    # computation (bit-identical compiled solve, pinned by test); the
    # dslash fault site is consumed here at trace time (one-shot)
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")

    b2 = blas.norm2(b)
    rdt = b2.dtype
    stop = (tol ** 2) * b2
    use_hq = tol_hq > 0.0
    stop_hq = tol_hq ** 2
    tiny = jnp.asarray(jnp.finfo(rdt).tiny, rdt)

    x = jnp.zeros_like(b) if x0 is None else x0
    r = b - matvec(x) if x0 is not None else b
    if precond is None:
        z = r
        rz = blas.norm2(r)
    else:
        z = precond(r)
        rz = blas.redot(r, z)
    p = z
    r2 = blas.norm2(r)

    if pallas_tail:
        from ..ops import blas_pallas as bpl

        def tail(alpha, p, Ap, x, r):
            return bpl.cg_update_norm2_pallas(alpha, p, Ap, x, r,
                                              interpret=pallas_interpret)
    else:
        def tail(alpha, p, Ap, x, r):
            return blas.triple_cg_update(alpha.astype(x.dtype), p, Ap,
                                         x, r)

    def one_iter(x, r, p, rz, k):
        Ap = matvec(p)
        if fault_k is not None:
            Ap = finj.corrupt(Ap, k, fault_k)
        pAp = blas.redot(p, Ap).astype(rdt)
        alpha = rz / jnp.maximum(pAp, tiny)
        x, r, r2 = tail(alpha, p, Ap, x, r)
        r2 = r2.astype(rdt)
        if precond is None:
            z, rz_new = r, r2
        else:
            z = precond(r)
            rz_new = blas.redot(r, z).astype(rdt)
        beta = rz_new / jnp.maximum(rz, tiny)
        p = z + beta.astype(x.dtype) * p
        return x, r, p, rz_new, r2, pAp

    def not_done(x, r, r2):
        l2 = r2 > stop
        if not use_hq:
            return l2
        hq2 = blas.heavy_quark_residual_norm(x, r)[2]
        return jnp.logical_or(l2, hq2 > stop_hq)

    def cond(carry):
        x, r, r2, k = carry[0], carry[1], carry[4], carry[5]
        go = jnp.logical_and(not_done(x, r, r2), k < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(carry[-1]))
        return go

    def body(carry):
        x, r, p, rz, r2, k = carry[:6]
        pAp = None
        for j in range(check_every):
            x, r, p, rz, r2, pAp = one_iter(x, r, p, rz, k + j)
        out = (x, r, p, rz, r2, k + check_every)
        if record:
            out = out + (carry[6].at[k // check_every].set(r2),)
        if sent is not None:
            # one sentinel step per convergence check (the amortisation
            # cadence the cond branch already runs at); the pivot check
            # sees the LAST fused iteration's pAp — an earlier
            # breakdown propagates into r2 by then
            out = out + (sent.step(carry[-1], r2, denom=pAp),)
        return out

    init = (x, r, p, rz, r2, jnp.int32(0))
    if record:
        slots = maxiter // check_every + 2
        init = init + (jnp.full((slots,), jnp.nan, rdt),)
    if sent is not None:
        init = init + (sent.init(r2),)
    out = jax.lax.while_loop(cond, body, init)
    x, r, p, rz, r2, k = out[:6]
    done, bk = rsent.finalize(sent, out[-1] if sent is not None else None,
                              jnp.logical_not(not_done(x, r, r2)))
    return SolverResult(x, k, r2, done, out[6] if record else None, bk)
