"""Mixed-precision solving: reliable updates and iterative refinement.

QUDA threads sloppy/precise operator pairs through every solver
(include/invert_quda.h; reliable update logic include/reliable_updates.h:33-54
and lib/inv_cg_quda.cpp).  The TPU precision ladder differs from CUDA's
{double,single,half,quarter}: the compute dtypes are
{float64 (CPU only), float32/complex64, bfloat16-pair} — see
utils/precision.py.  'quarter' drops the LINKS (not the iterates) to
int8 block-float storage — ops/blockfloat.to_int8_links resident gauge,
decompressed at link load inside the kernel, served under the df64
reliable update (interfaces/quda_api._invert_wilson_df64 +
models/wilson precision_form="int8"); spinor iterates stay bf16 pairs,
so the codecs below are unchanged.  Two strategies are provided:

* ``cg_reliable``: QUDA-style in-loop reliable updates — iterate entirely in
  the sloppy precision inside one lax.while_loop; when the sloppy residual
  falls below ``delta`` * (max residual since the last update), recompute the
  true residual with the precise operator and re-inject it (lax.cond keeps
  this branch-free for XLA).  The whole solve is ONE compiled computation.

* ``solve_refined``: outer defect-correction (iterative refinement) driving
  any inner solver — the pattern QUDA calls refinement in multi-shift
  (lib/inv_multi_cg_quda.cpp final refinement phase).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult, cg


class StorageCodec(NamedTuple):
    """How the sloppy iterates are stored and operated on.

    ``down``/``up`` convert between the precise representation (complex
    array) and the sloppy storage; ``norm2``/``redot`` reduce in storage;
    ``axpy(a, x, y) = y + a*x`` for REAL scalar a, computed at f32 and
    rounded back to storage; ``axpy_norm2(a, x, y) = (y + a*x, |..|^2)``
    is the fused update+reduce tail (one traversal — the
    reduce_core.cuh:668 axpyNorm2 analog; optionally the single-pass
    pallas kernel, ops/blas_pallas.py).  Two instances cover the TPU
    ladder: a plain dtype cast (single sloppy) and bf16/int8 pair
    storage (half/quarter — see ops/pair.py).
    """
    down: Callable
    up: Callable
    norm2: Callable
    redot: Callable
    axpy: Callable
    axpy_norm2: Optional[Callable] = None


def dtype_codec(sloppy_dtype, precise_dtype) -> StorageCodec:
    def _axpy_norm2(a, x, y):
        return blas.axpy_norm2(a.astype(sloppy_dtype), x, y)
    return StorageCodec(
        down=lambda x: x.astype(sloppy_dtype),
        up=lambda x: x.astype(precise_dtype),
        norm2=blas.norm2,
        redot=blas.redot,
        axpy=lambda a, x, y: y + a.astype(sloppy_dtype) * x,
        axpy_norm2=_axpy_norm2)


def _make_pair_codec(down, up, store_dtype, use_pallas_tail: bool = False,
                     pallas_interpret: bool = False) -> StorageCodec:
    """Shared reductions/axpy for every pair-storage layout — ONE home
    for the f32-accumulate rounding policy the reliable updates rely on;
    layouts differ only in their down/up converters.  With
    ``use_pallas_tail`` the fused update+reduce runs as the single-pass
    pallas kernel (the norm is taken on the ROUNDED stored value in both
    forms, so the semantics match bit-for-bit up to the documented
    block-accumulation order)."""
    from ..ops import pair as pops
    f32 = jnp.float32

    def axpy(a, x, y):
        return (y.astype(f32) + a.astype(f32) * x.astype(f32)
                ).astype(store_dtype)

    if use_pallas_tail:
        from ..ops import blas_pallas as bpl

        def axpy_norm2(a, x, y):
            out, n2 = bpl.axpy_norm2_pallas(a, x, y,
                                            interpret=pallas_interpret)
            return out, n2
    else:
        def axpy_norm2(a, x, y):
            out = axpy(a, x, y)
            return out, pops.pair_norm2(out)

    return StorageCodec(
        down=down, up=up,
        norm2=pops.pair_norm2,
        redot=pops.pair_redot,
        axpy=axpy,
        axpy_norm2=axpy_norm2)


def pair_codec(store_dtype, precise_dtype) -> StorageCodec:
    from ..ops import pair as pops
    return _make_pair_codec(
        lambda x: pops.to_pairs(x, store_dtype),
        lambda x: pops.from_pairs(x, precise_dtype), store_dtype)


def packed_pair_codec(store_dtype, precise_dtype) -> StorageCodec:
    """Pair storage on the PACKED device layout: re/im as axis 2 of
    (4,3,2,T,Z,YX) (ops/wilson_packed pair stencils)."""
    from ..ops import wilson_packed as wpk
    return _make_pair_codec(
        lambda x: wpk.to_packed_pairs(x, store_dtype),
        lambda x: wpk.from_packed_pairs(x, precise_dtype), store_dtype)


def pair_inplace_codec(store_dtype, use_pallas_tail: Optional[bool] = None,
                       pallas_interpret: Optional[bool] = None
                       ) -> StorageCodec:
    """Codec for when the PRECISE representation is itself an f32 pair
    array on the SAME layout as the sloppy storage — the fully
    complex-free solve path (TPU runtimes without complex64 execution;
    also the zero-conversion native-order path).  down/up are plain
    dtype casts.  ``use_pallas_tail`` routes the fused update+reduce
    through the single-pass pallas kernel (ops/blas_pallas.py);
    ``None`` defers to QUDA_TPU_FUSED_TAIL so the env knob reaches the
    reliable-update loops of the complex-free API solves too (a knob
    silently doing nothing is the failure mode utils/config.py exists
    to kill).  ``pallas_interpret=None`` resolves to interpret mode on
    non-TPU backends."""
    if use_pallas_tail is None:
        from ..utils import config as qconf
        use_pallas_tail = str(qconf.get("QUDA_TPU_FUSED_TAIL",
                                        fresh=True)) == "1"
    if pallas_interpret is None:
        pallas_interpret = jax.default_backend() != "tpu"
    return _make_pair_codec(
        lambda x: x.astype(store_dtype),
        lambda x: x.astype(jnp.float32), store_dtype,
        use_pallas_tail=use_pallas_tail,
        pallas_interpret=pallas_interpret)


def cg_reliable(matvec_hi: Callable, matvec_lo: Callable, b: jnp.ndarray,
                sloppy_dtype=None, tol: float = 1e-10, maxiter: int = 2000,
                delta: float = 0.1,
                codec: Optional[StorageCodec] = None,
                record: bool = False) -> SolverResult:
    """Mixed-precision CG with reliable updates.

    matvec_hi acts on the precise (complex) representation; matvec_lo acts
    on the SLOPPY STORAGE (a complex array for a dtype codec, a (...,2)
    pair array for the bf16/int8 codec).  Convergence is judged on the
    TRUE residual norm maintained through reliable updates, so the
    returned r2 is trustworthy at the precise level.

    ``record=True`` returns ``history={'r2': per-iteration residual
    norms (the true residual at reliable-update iterations, the sloppy
    one otherwise), 'reliable': per-iteration reliable-update flags}``
    for obs/convergence.py; record=False leaves the carry unchanged.
    """
    if codec is None:
        if sloppy_dtype is None:
            raise ValueError("cg_reliable needs sloppy_dtype or codec")
        codec = dtype_codec(sloppy_dtype, b.dtype)
    # breakdown sentinel + dslash fault site (robust/): None/None at
    # QUDA_TPU_ROBUST=off & nothing armed — the loop then traces the
    # exact unguarded computation
    from ..robust import faultinject as finj
    from ..robust import sentinel as rsent
    sent = rsent.make()
    fault_k = finj.iteration_fault("dslash")
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2

    x = jnp.zeros_like(b)          # precise accumulated solution
    r = b                          # precise residual
    r2 = b2
    r_lo = codec.down(r)
    p = r_lo
    x_lo = jnp.zeros_like(r_lo)    # sloppy partial solution since last update
    rdt = jnp.zeros((), b.dtype).real.dtype

    def cond(c):
        go = jnp.logical_and(c["r2"] > stop, c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        Ap = matvec_lo(c["p"])
        if fault_k is not None:
            Ap = finj.corrupt(Ap, c["k"], fault_k)
        pAp = codec.redot(c["p"], Ap).astype(rdt)
        alpha = c["r2_lo"] / jnp.maximum(pAp, jnp.finfo(rdt).tiny)
        x_lo = codec.axpy(alpha, c["p"], c["x_lo"])
        # fused residual update+reduce: one traversal (optionally the
        # single-pass pallas kernel, see StorageCodec.axpy_norm2)
        if codec.axpy_norm2 is not None:
            r_lo, r2_new = codec.axpy_norm2(-alpha, Ap, c["r_lo"])
            r2_new = r2_new.astype(rdt)
        else:
            r_lo = codec.axpy(-alpha, Ap, c["r_lo"])
            r2_new = codec.norm2(r_lo).astype(rdt)
        beta = r2_new / c["r2_lo"]
        p = codec.axpy(beta, c["p"], r_lo)
        r2max = jnp.maximum(c["r2max"], r2_new)
        st_new = (sent.step(c["sent"], r2_new, denom=pAp)
                  if sent is not None else None)

        do_reliable = jnp.logical_or(r2_new < (delta ** 2) * r2max,
                                     r2_new < stop)

        def reliable(_):
            x_new = c["x"] + codec.up(x_lo)
            r_true = c["b"] - matvec_hi(x_new)
            # compensated: the reported residual must be trustworthy
            # below the plain-f32 accumulation floor (dbldbl.h analog)
            r2_true = blas.norm2_comp(r_true).astype(rdt)
            d = dict(
                c, x=x_new, r=r_true, r2=r2_true,
                r_lo=codec.down(r_true),
                # restart the direction at the true residual (QUDA resets
                # beta using the new residual after a reliable update)
                p=codec.down(r_true),
                x_lo=jnp.zeros_like(x_lo),
                r2_lo=r2_true, r2max=r2_true, k=c["k"] + 1)
            if record:
                d["hist"] = c["hist"].at[c["k"]].set(r2_true)
                d["rel"] = c["rel"].at[c["k"]].set(True)
            if sent is not None:
                d["sent"] = st_new
            return d

        def keep(_):
            d = dict(c, p=p, r_lo=r_lo, x_lo=x_lo, r2_lo=r2_new,
                     r2=r2_new.astype(rdt), r2max=r2max, k=c["k"] + 1)
            if record:
                d["hist"] = c["hist"].at[c["k"]].set(r2_new.astype(rdt))
                d["rel"] = c["rel"]
            if sent is not None:
                d["sent"] = st_new
            return d

        return jax.lax.cond(do_reliable, reliable, keep, None)

    init = dict(b=b, x=x, r=r, r2=r2.astype(rdt), r_lo=r_lo, p=p, x_lo=x_lo,
                r2_lo=r2.astype(rdt), r2max=r2.astype(rdt), k=jnp.int32(0))
    if record:
        init["hist"] = jnp.full((maxiter + 1,), jnp.nan, rdt)
        init["rel"] = jnp.zeros((maxiter + 1,), bool)
    if sent is not None:
        init["sent"] = sent.init(r2.astype(rdt))
    out = jax.lax.while_loop(cond, body, init)
    # final fold of any un-injected sloppy contribution
    x_fin = out["x"] + codec.up(out["x_lo"])
    r_fin = b - matvec_hi(x_fin)
    r2_fin = blas.norm2_comp(r_fin)
    hist = ({"r2": out["hist"], "reliable": out["rel"]} if record
            else None)
    conv, bk = rsent.finalize(sent, out.get("sent"), r2_fin <= stop)
    return SolverResult(x_fin, out["k"], r2_fin, conv, hist, bk)


def cg_reliable_df(op_df, matvec_lo: Callable, rhs_df, codec: StorageCodec,
                   tol: float = 1e-10, maxiter: int = 4000,
                   delta: float = 0.1, record: bool = False) -> SolverResult:
    """Extended-precision reliable-update CG on the normal equations.

    The TPU analog of QUDA's double-precise / sloppy-pair solve to 1e-10
    (fp64 matPrecise in lib/inv_cg_quda.cpp:63 + dbldbl accumulators,
    include/dbldbl.h): the precise side runs in df64 (float32-pair,
    ops/df64.py) — no f64, no complex, executable on TPU.

    * ``op_df``: df64 operator bundle (ops/wilson_df64.WilsonPCDF64):
      ``M``/``Mdag`` on df64 fields and ``residual_df``.
    * ``matvec_lo``: the SLOPPY normal operator (MdagM) acting on the
      storage representation (f32/bf16 pair arrays, same layout as the
      df64 hi word).
    * ``rhs_df``: df64 DIRECT rhs (the PC system b).  The loop iterates
      on Mdag M x = Mdag b in sloppy storage; convergence is judged on
      the df64 DIRECT residual |b - M x| recomputed at every reliable
      update, so the returned r2 certifies the direct system at the
      ~1e-14 df64 floor.

    The normal-residual trigger threshold tightens itself (x1/16) when
    the normal system looks converged but the direct residual is not —
    the branch-free analog of QUDA tightening solver tolerances between
    refinement cycles.
    """
    from ..ops import df64 as dfm
    from ..robust import sentinel as rsent
    sent = rsent.make()

    f32 = jnp.float32
    b2d = dfm.to_f32(dfm.norm2(rhs_df)).astype(f32)
    stop_d = (tol ** 2) * b2d

    rn_df = op_df.Mdag(rhs_df)           # normal residual at x = 0
    rn = dfm.to_f32(rn_df)
    bn2 = dfm.to_f32(dfm.norm2_f32(rn)).astype(f32)
    stop_n = (tol ** 2) * bn2

    x = (jnp.zeros_like(rhs_df[0]), jnp.zeros_like(rhs_df[1]))
    r_lo = codec.down(rn)
    x_lo = jnp.zeros_like(r_lo)
    rn2 = codec.norm2(r_lo).astype(f32)

    def cond(c):
        go = jnp.logical_and(c["d2"] > stop_d, c["k"] < maxiter)
        if sent is not None:
            go = jnp.logical_and(go, sent.ok(c["sent"]))
        return go

    def body(c):
        Ap = matvec_lo(c["p"])
        pAp = codec.redot(c["p"], Ap).astype(f32)
        alpha = c["r2_lo"] / jnp.maximum(pAp, jnp.finfo(f32).tiny)
        x_lo = codec.axpy(alpha, c["p"], c["x_lo"])
        if codec.axpy_norm2 is not None:
            r_lo, r2_new = codec.axpy_norm2(-alpha, Ap, c["r_lo"])
            r2_new = r2_new.astype(f32)
        else:
            r_lo = codec.axpy(-alpha, Ap, c["r_lo"])
            r2_new = codec.norm2(r_lo).astype(f32)
        beta = r2_new / c["r2_lo"]
        p = codec.axpy(beta, c["p"], r_lo)
        r2max = jnp.maximum(c["r2max"], r2_new)
        st_new = (sent.step(c["sent"], r2_new, denom=pAp)
                  if sent is not None else None)

        do_reliable = jnp.logical_or(r2_new < (delta ** 2) * r2max,
                                     r2_new < c["stop_n"])

        def reliable(_):
            x_new = dfm.add(c["x"], dfm.promote(codec.up(x_lo)))
            d_df = op_df.residual_df(rhs_df, x_new)
            d2 = dfm.to_f32(dfm.norm2(d_df)).astype(f32)
            rn_df = op_df.Mdag(d_df)
            rn = dfm.to_f32(rn_df)
            rn2_true = dfm.to_f32(dfm.norm2_f32(rn)).astype(f32)
            # not converged on the direct system but the normal target
            # was met -> tighten the inner target
            tighten = jnp.logical_and(d2 > stop_d,
                                      rn2_true <= c["stop_n"])
            stop_n_new = jnp.where(tighten, c["stop_n"] / 16.0,
                                   c["stop_n"])
            d = dict(
                c, x=x_new, d2=d2, stop_n=stop_n_new,
                r_lo=codec.down(rn), p=codec.down(rn),
                x_lo=jnp.zeros_like(x_lo),
                r2_lo=rn2_true, r2max=rn2_true, k=c["k"] + 1)
            if sent is not None:
                d["sent"] = st_new
            if record:
                # record the TRUE normal-equation residual, not d2: the
                # keep branch records sloppy normal-eq norms, and one
                # history must stay one system or the curve is
                # unreadable (the direct-system certificate is the
                # returned r2, judged against stop_d)
                d["hist"] = c["hist"].at[c["k"]].set(rn2_true)
                d["rel"] = c["rel"].at[c["k"]].set(True)
            return d

        def keep(_):
            d = dict(c, p=p, r_lo=r_lo, x_lo=x_lo, r2_lo=r2_new,
                     r2max=r2max, k=c["k"] + 1)
            if record:
                d["hist"] = c["hist"].at[c["k"]].set(r2_new)
                d["rel"] = c["rel"]
            if sent is not None:
                d["sent"] = st_new
            return d

        return jax.lax.cond(do_reliable, reliable, keep, None)

    init = dict(x=x, d2=b2d, stop_n=stop_n, r_lo=r_lo, p=r_lo, x_lo=x_lo,
                r2_lo=rn2, r2max=rn2, k=jnp.int32(0))
    if record:
        init["hist"] = jnp.full((maxiter + 1,), jnp.nan, f32)
        init["rel"] = jnp.zeros((maxiter + 1,), bool)
    if sent is not None:
        init["sent"] = sent.init(rn2)
    out = jax.lax.while_loop(cond, body, init)
    x_fin = dfm.add(out["x"], dfm.promote(codec.up(out["x_lo"])))
    d_df = op_df.residual_df(rhs_df, x_fin)
    d2_fin = dfm.to_f32(dfm.norm2(d_df))
    # the history is the NORMAL-equation residual curve (|Mdag r|^2,
    # sloppy between reliable updates, true at them) — ship its own
    # reference norm |Mdag b|^2 so harvest() normalizes relres in the
    # recorded system instead of the caller's direct-system b2
    hist = ({"r2": out["hist"], "reliable": out["rel"], "b2": bn2}
            if record else None)
    conv, bk = rsent.finalize(sent, out.get("sent"), d2_fin <= stop_d)
    return SolverResult(x_fin, out["k"], d2_fin, conv, hist, bk)


def solve_refined(matvec_hi: Callable, inner_solve: Callable, b: jnp.ndarray,
                  sloppy_dtype, tol: float = 1e-10, max_cycles: int = 10):
    """Defect-correction refinement: repeat { r = b - A x ;  x += solve(r) }.

    ``inner_solve(rhs) -> x`` runs at sloppy_dtype (any solver).  Host-side
    outer loop (few cycles), jitted inner — QUDA's refinement phase pattern.
    """
    b2 = float(blas.norm2(b))
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b)
    r = b
    cycles = 0
    for _ in range(max_cycles):
        y = inner_solve(r.astype(sloppy_dtype))
        x = x + y.astype(x.dtype)
        r = b - matvec_hi(x)
        cycles += 1
        if float(blas.norm2(r)) <= stop:
            break
    r2 = blas.norm2(r)
    return SolverResult(x, jnp.int32(cycles), r2, r2 <= stop)
