"""Mixed-precision solving: reliable updates and iterative refinement.

QUDA threads sloppy/precise operator pairs through every solver
(include/invert_quda.h; reliable update logic include/reliable_updates.h:33-54
and lib/inv_cg_quda.cpp).  The TPU precision ladder differs from CUDA's
{double,single,half,quarter}: the compute dtypes are
{float64 (CPU only), float32/complex64, bfloat16-pair} — see
utils/precision.py.  Two strategies are provided:

* ``cg_reliable``: QUDA-style in-loop reliable updates — iterate entirely in
  the sloppy precision inside one lax.while_loop; when the sloppy residual
  falls below ``delta`` * (max residual since the last update), recompute the
  true residual with the precise operator and re-inject it (lax.cond keeps
  this branch-free for XLA).  The whole solve is ONE compiled computation.

* ``solve_refined``: outer defect-correction (iterative refinement) driving
  any inner solver — the pattern QUDA calls refinement in multi-shift
  (lib/inv_multi_cg_quda.cpp final refinement phase).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ops import blas
from .cg import SolverResult, cg


def cg_reliable(matvec_hi: Callable, matvec_lo: Callable, b: jnp.ndarray,
                sloppy_dtype, tol: float = 1e-10, maxiter: int = 2000,
                delta: float = 0.1) -> SolverResult:
    """Mixed-precision CG with reliable updates.

    matvec_hi acts at b.dtype; matvec_lo at sloppy_dtype.  Convergence is
    judged on the TRUE residual norm maintained through reliable updates,
    so the returned r2 is trustworthy at the precise level.
    """
    b2 = blas.norm2(b)
    stop = (tol ** 2) * b2

    x = jnp.zeros_like(b)          # precise accumulated solution
    r = b                          # precise residual
    r2 = b2
    r_lo = r.astype(sloppy_dtype)
    p = r_lo
    x_lo = jnp.zeros_like(r_lo)    # sloppy partial solution since last update
    rdt = jnp.zeros((), b.dtype).real.dtype

    def cond(c):
        return jnp.logical_and(c["r2"] > stop, c["k"] < maxiter)

    def body(c):
        Ap = matvec_lo(c["p"])
        pAp = blas.redot(c["p"], Ap).astype(rdt)
        alpha = c["r2_lo"] / jnp.maximum(pAp, jnp.finfo(rdt).tiny)
        x_lo = c["x_lo"] + alpha.astype(c["p"].dtype) * c["p"]
        r_lo = c["r_lo"] - alpha.astype(c["p"].dtype) * Ap
        r2_new = blas.norm2(r_lo).astype(rdt)
        beta = r2_new / c["r2_lo"]
        p = r_lo + beta.astype(c["p"].dtype) * c["p"]
        r2max = jnp.maximum(c["r2max"], r2_new)

        do_reliable = jnp.logical_or(r2_new < (delta ** 2) * r2max,
                                     r2_new < stop)

        def reliable(_):
            x_new = c["x"] + x_lo.astype(c["x"].dtype)
            r_true = c["b"] - matvec_hi(x_new)
            r2_true = blas.norm2(r_true).astype(rdt)
            return dict(
                c, x=x_new, r=r_true, r2=r2_true,
                r_lo=r_true.astype(sloppy_dtype),
                # restart the direction at the true residual (QUDA resets
                # beta using the new residual after a reliable update)
                p=r_true.astype(sloppy_dtype),
                x_lo=jnp.zeros_like(x_lo),
                r2_lo=r2_true, r2max=r2_true, k=c["k"] + 1)

        def keep(_):
            return dict(c, p=p, r_lo=r_lo, x_lo=x_lo, r2_lo=r2_new,
                        r2=r2_new.astype(rdt), r2max=r2max, k=c["k"] + 1)

        return jax.lax.cond(do_reliable, reliable, keep, None)

    init = dict(b=b, x=x, r=r, r2=r2.astype(rdt), r_lo=r_lo, p=p, x_lo=x_lo,
                r2_lo=r2.astype(rdt), r2max=r2.astype(rdt), k=jnp.int32(0))
    out = jax.lax.while_loop(cond, body, init)
    # final fold of any un-injected sloppy contribution
    x_fin = out["x"] + out["x_lo"].astype(out["x"].dtype)
    r_fin = b - matvec_hi(x_fin)
    r2_fin = blas.norm2(r_fin)
    return SolverResult(x_fin, out["k"], r2_fin, r2_fin <= stop)


def solve_refined(matvec_hi: Callable, inner_solve: Callable, b: jnp.ndarray,
                  sloppy_dtype, tol: float = 1e-10, max_cycles: int = 10):
    """Defect-correction refinement: repeat { r = b - A x ;  x += solve(r) }.

    ``inner_solve(rhs) -> x`` runs at sloppy_dtype (any solver).  Host-side
    outer loop (few cycles), jitted inner — QUDA's refinement phase pattern.
    """
    b2 = float(blas.norm2(b))
    stop = (tol ** 2) * b2
    x = jnp.zeros_like(b)
    r = b
    cycles = 0
    for _ in range(max_cycles):
        y = inner_solve(r.astype(sloppy_dtype))
        x = x + y.astype(x.dtype)
        r = b - matvec_hi(x)
        cycles += 1
        if float(blas.norm2(r)) <= stop:
            break
    r2 = blas.norm2(r)
    return SolverResult(x, jnp.int32(cycles), r2, r2 <= stop)
