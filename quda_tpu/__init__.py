"""quda_tpu — a TPU-native lattice QCD framework.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of QUDA
(https://github.com/lattice/quda): Dirac stencils, mixed-precision Krylov
solvers, adaptive multigrid, eigensolvers, and the HMC gauge sector —
built on sharded jax.Arrays over a 4-D device mesh with XLA collectives
for halo exchange.

Subpackages
-----------
fields    lattice geometry, ColorSpinorField / GaugeField / CloverField
ops       stencils, BLAS/reductions, SU(3) algebra, gamma algebra
models    Dirac operator classes (Wilson, clover, twisted, staggered, DWF...)
solvers   CG family, BiCGStab(L), GCR, CA solvers, multi-shift, mixed prec
mg        adaptive multigrid (transfer, coarse ops, V-cycle)
eig       TRLM / IRAM eigensolvers, Chebyshev acceleration, deflation
gauge     HMC forces, smearing, gauge fixing, observables, heatbath
parallel  device mesh, sharding layouts, halo exchange
utils     tuning cache, profiling, RNG, I/O, checkpointing
interfaces  C-ABI shim and MILC-style entry points
"""

__version__ = "0.1.0"

from .fields.geometry import EVEN, FULL, ODD, LatticeGeometry  # noqa: F401
