"""Observability layer: span tracing, convergence recording, roofline
attribution.

The reference ships its performance story as instrumentation built INTO
the product — per-API ``TimeProfile`` statics (lib/timer.cpp), the
autotuner doubling as a profiler (profile_N.tsv, lib/tune.cpp:450-474),
and per-solve convergence reporting.  This package is the TPU-native
home for that surface:

* ``obs.trace``       — nestable named spans + instant events, exported
                        as chrome-trace/perfetto JSON and a JSONL event
                        stream (QUDA_TPU_TRACE / QUDA_TPU_TRACE_PATH;
                        off = zero-overhead no-op spans, safe under jit).
* ``obs.convergence`` — per-iteration residual histories and solver
                        events (reliable updates, restarts, breakdowns,
                        per-RHS lanes) harvested from SolverResult
                        histories and surfaced on InvertParam.
* ``obs.roofline``    — the PERF.md per-site flops/bytes models joined
                        with measured wall-times into achieved-GFLOPS /
                        achieved-BW / %-of-demonstrated-peak rows per
                        kernel form, replacing hand arithmetic in the
                        bench harness and the round logs.
* ``obs.history``     — committed BENCH_*/MULTICHIP_* artifacts parsed
                        into canonical (metric, unit, platform, lattice,
                        form, mesh) time series with best-credible
                        (gate_row-passing) baselines and the trends.tsv
                        table PERF.md cites.
* ``obs.regress``     — the ``bench_suite --compare`` perf gate: diffs
                        a run against the history baselines, fails
                        loudly (rejection JSON rows + nonzero exit) on
                        >tol throughput regression or solver-iteration
                        inflation.
* ``obs.metrics``     — serving-grade labeled counter/gauge/histogram
                        registry (QUDA_TPU_METRICS; off = zero-overhead
                        no-op calls): solves by family/status, compile
                        vs warm-executable accounting, tuner warm-cache
                        hit/miss, retry-ladder counters; exported as
                        Prometheus text + metrics.tsv by end_quda.
* ``obs.memory``      — HBM field ledger (every resident field tracked
                        at load/free with per-family bytes + high-water),
                        all-local-device memory_stats sampling around
                        solve phases, and the pallas VMEM budget audit.
* ``obs.report``      — the human-readable end-of-session fleet report
                        (fleet_report.txt) rendered from the two above.
* ``obs.comms``       — the ICI comms ledger (rides QUDA_TPU_TRACE /
                        QUDA_TPU_METRICS): every halo-exchange seam
                        records (site, axis, direction, bytes/device,
                        policy, dtype, mesh); per-solve ICI roofline
                        rows emitted alongside the HBM rows.
* ``obs.costmodel``   — the KERNEL_MODELS cross-check: analytic
                        flops/bytes vs Compiled.cost_analysis() of the
                        XLA reference stencils and the operand-footprint
                        floors; drift lint + per-session cost_drift.tsv.
* ``obs.schema``      — the canonical registry of every trace-event and
                        metric name (linted bidirectionally by
                        tests/test_obs_schema_lint.py; the metrics
                        registry also validates names at record time).
* ``obs.flight``      — the black-box flight recorder
                        (QUDA_TPU_FLIGHT; off = zero-overhead no-op):
                        a bounded ring buffer of structured events —
                        API entries/exits, tuner decisions, escalation
                        rungs, sentinel codes, gauge rejections —
                        tapped off the trace.event emission sites,
                        flushed as flight.jsonl and into every
                        postmortem bundle.
* ``obs.postmortem``  — failure-capture bundles (QUDA_TPU_POSTMORTEM):
                        on breakdown / verify mismatch / ladder
                        exhaustion / gauge rejection / API-boundary
                        exceptions, one self-contained directory —
                        knob + topology snapshot, consulted tunecache,
                        metrics + HBM snapshots, the flight tail, full
                        param provenance, size-capped content-hashed
                        field dumps, manifest.json — plus the
                        session-wide artifacts_manifest.json index.
* ``obs.replay``      — deterministic solve replay from a bundle
                        (``python -m quda_tpu.obs.replay <dir>``):
                        reconstructs fields/params, re-runs through
                        the normal invert_quda path under the recorded
                        knobs, reports reproduced / recovered /
                        diverged and appends replay.json for the fleet
                        report's replay-verified column.
"""

# obs.replay is deliberately NOT imported eagerly: it is the
# ``python -m quda_tpu.obs.replay`` entry point, and runpy warns when a
# -m target is already resident from its package import
from . import (comms, convergence, costmodel, flight,  # noqa: F401
               history, memory, metrics, postmortem, regress,
               report, roofline, schema, trace)
