"""XLA cost-model cross-check: the analytic KERNEL_MODELS vs what the
compiler and the argument footprints actually claim.

The roofline numbers this repo publishes (obs/roofline.py, PERF.md)
rest on hand-derived per-site flops/bytes models.  Hand arithmetic
drifts: a model edited for one kernel form and not its sharded twin, a
traffic table copied with a factor-2 slip, silently corrupts every
achieved-BW percentage downstream.  This module makes the models
checkable against two independent witnesses:

* **flops** — ``Compiled.cost_analysis()`` of the XLA *reference
  stencil* of the same operator family (the jnp forms the pallas
  kernels are bit-matched against).  XLA counts HLO flops on its own;
  the analytic ``flops_per_site`` must agree within ``FLOPS_RTOL``.
  (The pallas call itself is opaque to XLA — and in interpret mode its
  cost analysis reports interpreter machinery — so the reference
  stencil, which computes the identical math, is the honest witness.)
* **bytes** — the operand-footprint floor: the distinct input + output
  array bytes of a real probe invocation of the form, per updated
  site.  An analytic bytes/site below the floor claims less traffic
  than the data touched once (impossible); one above
  ``BYTES_REREAD_MAX`` x the floor claims more re-reading than any
  kernel form in this codebase performs (measured worst case: the
  wilson MRHS model at 2.14x the floor at the n=4 probe point; the
  deliberate-mistake fixtures in tests/test_costmodel.py pin that a
  factor-2 slip in either direction fails).

Surfaces:

* :func:`check_forms` / :func:`lint` — the drift lint over every
  registered pallas form (tests/test_costmodel.py runs it in tier-1;
  the bench ``costmodel`` suite records its ratios as trended rows).
* :func:`note_compile` — called by ``obs.metrics.record_execution`` on
  every first execution, so the session knows WHICH forms actually
  compiled; :func:`save_report` (end_quda, metrics-gated) joins the
  noted keys with the models and any cached probe results into
  ``cost_drift.tsv`` under the resource path.

Probes run on any backend (two tiny 4^4 reference-stencil compiles,
cached per process); footprints are pure shape arithmetic.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .roofline import KERNEL_MODELS

# analytic flops_per_site vs the XLA reference-stencil count: XLA's HLO
# counting runs ~6-12% above the hand models (it charges the projector
# adds the models fold away); measured ratios 1.06-1.13 across families
FLOPS_RTOL = 0.5
# analytic bytes_per_site vs the operand-footprint floor: must be >= 1x
# (cannot move less than the data once) and <= this re-read factor.
# Measured ratios across the registered forms: 1.15 (staggered two-pass)
# to 2.14 (wilson MRHS at the n=4 probe point); 2.5 leaves headroom
# while a factor-2 slip in either direction still fails (the
# tests/test_costmodel.py fixtures pin both directions)
BYTES_REREAD_MAX = 2.5
BYTES_REREAD_MIN = 1.0

# MRHS models are probed at this batch size (their bytes models are
# nrhs-callables)
_PROBE_NRHS = 4
_PROBE_L = 4

_lock = threading.Lock()
_probe_cache: Dict[str, dict] = {}     # form -> drift row
_ref_flops_cache: Dict[str, float] = {}
_noted: List[dict] = []                # record_execution compile keys
_NOTED_MAX = 1000


def reset():
    with _lock:
        _probe_cache.clear()
        _noted.clear()


def note_compile(api: str, form: str, shape, dtype: str, solver: str,
                 seconds: float):
    """Record one first-execution key (obs.metrics.record_execution
    hook): the drift report then covers exactly what compiled this
    session."""
    with _lock:
        if len(_noted) < _NOTED_MAX:
            _noted.append({"api": api, "form": form,
                           "shape": tuple(shape), "dtype": dtype,
                           "solver": solver,
                           "seconds": round(float(seconds), 6)})


def noted_compiles() -> List[dict]:
    with _lock:
        return list(_noted)


def xla_cost(fn, *args) -> dict:
    """{'flops', 'bytes'} from ``jit(fn).lower(*args).compile()
    .cost_analysis()`` (the Compiled cost-analysis capture).  Entries
    the backend does not report come back None."""
    import jax
    ca = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    return {"flops": ca.get("flops"),
            "bytes": ca.get("bytes accessed")}


# -- per-family XLA reference stencils (flops witnesses) --------------------

def _ref_flops_per_site(family: str) -> float:
    """XLA-counted flops/site of the family's reference jnp stencil on a
    4^4 lattice (compiled once per process)."""
    with _lock:
        if family in _ref_flops_cache:
            return _ref_flops_cache[family]
    import numpy as np
    import jax.numpy as jnp
    L = _PROBE_L
    T = Z = Y = X = L
    vol = L ** 4
    rng = np.random.default_rng(0)

    def arr(shape):
        return jnp.asarray(rng.standard_normal(shape).astype(np.float32))

    if family == "wilson":
        from ..ops import wilson_packed as wpk
        g = arr((4, 3, 3, 2, T, Z, Y * X))
        p = arr((4, 3, 2, T, Z, Y * X))
        cost = xla_cost(lambda g, p: wpk.dslash_packed_pairs(g, p, X, Y),
                        g, p)
    elif family == "staggered_fat":
        from ..ops import staggered_packed as spk
        f = arr((4, 3, 3, 2, T, Z, Y * X))
        p = arr((3, 2, T, Z, Y * X))
        cost = xla_cost(
            lambda f, p: spk.dslash_staggered_packed_pairs(f, p, X, Y),
            f, p)
    elif family == "staggered_fat_naik":
        from ..ops import staggered_packed as spk
        f = arr((4, 3, 3, 2, T, Z, Y * X))
        ln = arr((4, 3, 3, 2, T, Z, Y * X))
        p = arr((3, 2, T, Z, Y * X))
        cost = xla_cost(
            lambda f, ln, p: spk.dslash_staggered_packed_pairs(
                f, p, X, Y, long_pp=ln), f, ln, p)
    elif family == "clover":
        # hop + one chiral-block matvec: the staged composition the
        # fused clover/twisted-clover kernels are bit-matched against
        from ..models.clover import apply_clover_pairs
        from ..ops import wilson_packed as wpk
        g = arr((4, 3, 3, 2, T, Z, Y * X))
        blk = arr((2, 6, 6, 2, T, Z, Y * X))
        p = arr((4, 3, 2, T, Z, Y * X))
        cost = xla_cost(
            lambda g, blk, p: apply_clover_pairs(
                blk, wpk.dslash_packed_pairs(g, p, X, Y)), g, blk, p)
    elif family == "twisted_mass":
        # hop + the (1 + i a g5)^{-1} chirality rotation
        from ..models.twisted import _twist_inv_pairs
        from ..ops import wilson_packed as wpk
        g = arr((4, 3, 3, 2, T, Z, Y * X))
        p = arr((4, 3, 2, T, Z, Y * X))
        cost = xla_cost(
            lambda g, p: _twist_inv_pairs(
                wpk.dslash_packed_pairs(g, p, X, Y), 0.25, +1), g, p)
    elif family in ("dwf_ls4", "dwf_ls8"):
        # the Ls-batched 4d hop (the s-diagonal seam the DWF/Möbius
        # fused form accelerates); vol below is 4d sites so the count
        # lands per updated 4d site, matching the Ls x 1320 models
        import jax
        from ..ops import wilson_packed as wpk
        Ls = int(family.rsplit("ls", 1)[1])
        g = arr((4, 3, 3, 2, T, Z, Y * X))
        p = arr((Ls, 4, 3, 2, T, Z, Y * X))
        cost = xla_cost(
            lambda g, p: jax.vmap(
                lambda v: wpk.dslash_packed_pairs(g, v, X, Y))(p), g, p)
    elif family == "mg_coarse":
        # the MG coarse stencil at the canonical probe size (n_vec=4,
        # E=16): the XLA form of the identical stacked contraction the
        # pallas kernel computes (ops/coarse_pallas.coarse_apply_ref)
        # on a 4^4 COARSE lattice — vol below is coarse sites
        from ..ops.coarse_pallas import coarse_apply_ref
        E = 16
        links = arr((9, vol, E, E))
        psi9 = arr((9, vol, E))
        cost = xla_cost(coarse_apply_ref, links, psi9)
    else:
        raise KeyError(f"no reference stencil for family {family!r}")
    fps = float(cost["flops"] or 0.0) / vol
    with _lock:
        _ref_flops_cache[family] = fps
    return fps


# -- per-form operand footprints (bytes floors) -----------------------------
#
# Per-UPDATED-site bytes of the arrays one invocation of the form reads
# and writes ONCE, on the same layout basis the KERNEL_MODELS rows were
# derived (full-lattice pair arrays; gauge 288 B/site full rows, 192
# reconstruct-12, wilson spinor 96, staggered color-spinor 24).  Sharded
# forms alias their single-chip interior (the models exclude the
# O(surface) halo transport — the comms ledger owns it).

_G, _G12, _PSI, _SPSI = 288.0, 192.0, 96.0, 24.0
# packed clover/twisted-clover chiral pair blocks: 2 x 6x6 complex f32
_BLK = 576.0

_FOOTPRINTS: Dict[str, dict] = {
    # v2 gather: forward links + resident pre-shifted backward copy
    "wilson_v2": {"family": "wilson",
                  "floor": lambda n: 2 * _G + 2 * _PSI},
    "wilson_v2_r12": {"family": "wilson",
                      "floor": lambda n: 2 * _G12 + 2 * _PSI},
    # v3 scatter: one link array, no backward copy
    "wilson_v3": {"family": "wilson",
                  "floor": lambda n: _G + 2 * _PSI},
    "wilson_v3_r12": {"family": "wilson",
                      "floor": lambda n: _G12 + 2 * _PSI},
    "wilson_mrhs": {"family": "wilson",
                    "floor": lambda n: 2 * _G / n + 2 * _PSI},
    # precision storage forms (PERF.md round 16).  Floors are the
    # distinct operand bytes of one invocation AT THE FORM'S STORAGE
    # dtype — the bf16 rows halve the f32 basis, the int8 row charges
    # 1-byte mantissas + the f32 scale planes (4 dirs x 4 B = 16/site
    # per array).  r12f/int8 read here+there link arrays (no resident
    # backward copy); fold keeps the v2 operand set in folded layout.
    "wilson_v2_r12f": {"family": "wilson",
                       "floor": lambda n: 2 * _G12 + 2 * _PSI},
    "wilson_v2_fold": {"family": "wilson",
                       "floor": lambda n: 2 * _G + 2 * _PSI},
    "wilson_v2_bf16_fold": {"family": "wilson",
                            "floor": lambda n: (2 * _G + 2 * _PSI) / 2},
    "wilson_v2_bf16_bzfull": {"family": "wilson",
                              "floor": lambda n:
                              (2 * _G + 2 * _PSI) / 2},
    "wilson_v2_int8": {"family": "wilson",
                       "floor": lambda n: 2 * (_G / 4 + 16.0)
                       + 2 * _PSI},
    "wilson_sharded_v2": {"alias": "wilson_v2"},
    "wilson_sharded_v2_r12": {"alias": "wilson_v2_r12"},
    "wilson_sharded_v3": {"alias": "wilson_v3"},
    "wilson_sharded_v3_r12": {"alias": "wilson_v3_r12"},
    "staggered_fat": {"family": "staggered_fat",
                      "floor": lambda n: 2 * _G + 2 * _SPSI},
    "staggered_fat_naik": {"family": "staggered_fat_naik",
                           "floor": lambda n: 4 * _G + 2 * _SPSI},
    "staggered_fat_v3": {"family": "staggered_fat",
                         "floor": lambda n: _G + 2 * _SPSI},
    "staggered_fat_naik_v3": {"family": "staggered_fat_naik",
                              "floor": lambda n: 2 * _G + 2 * _SPSI},
    "staggered_fat_naik_fused": {"family": "staggered_fat_naik",
                                 "floor": lambda n: 2 * _G + 2 * _SPSI},
    # fused precision forms: non-eo operand basis like the fused row
    # (fat + long link arrays + psi + out).  r12 swaps the long array
    # for its R=2 storage + the streamed f32 sign plane (16 B/site);
    # fold is a layout change at unchanged byte count
    "staggered_fat_naik_fused_r12": {
        "family": "staggered_fat_naik",
        "floor": lambda n: _G + _G12 + 16.0 + 2 * _SPSI},
    "staggered_fat_naik_fused_fold": {
        "family": "staggered_fat_naik",
        "floor": lambda n: 2 * _G + 2 * _SPSI},
    "staggered_mrhs": {"family": "staggered_fat_naik",
                       "floor": lambda n: 4 * _G / n + 2 * _SPSI},
    "staggered_fat_mrhs": {"family": "staggered_fat",
                           "floor": lambda n: 2 * _G / n + 2 * _SPSI},
    "staggered_sharded_fat": {"alias": "staggered_fat"},
    "staggered_sharded_fat_naik": {"alias": "staggered_fat_naik"},
    # operator-zoo fused forms (PERF.md round 18): hop operand set +
    # the resident diagonal term's storage.  The clover/twisted-clover
    # rows read the packed chiral blocks once per pass; the twisted-mass
    # twist is two compiled-in scalars (zero bytes); the MRHS rows
    # amortize links AND blocks over the RHS stream.  The r12 floors
    # charge the reconstruct-12 link storage at the FORM's dtype basis
    "clover_pallas": {"family": "clover",
                      "floor": lambda n: 2 * _PSI + 2 * _G + _BLK},
    "clover_pallas_r12": {"family": "clover",
                          "floor": lambda n: 2 * _PSI + 2 * _G12
                          + _BLK},
    "clover_pallas_mrhs": {"family": "clover",
                           "floor": lambda n: 2 * _PSI
                           + (2 * _G + _BLK) / n},
    "twisted_mass_pallas": {"family": "twisted_mass",
                            "floor": lambda n: 2 * _PSI + 2 * _G},
    "twisted_mass_pallas_r12": {"family": "twisted_mass",
                                "floor": lambda n: 2 * _PSI + 2 * _G12},
    "twisted_mass_pallas_mrhs": {"family": "twisted_mass",
                                 "floor": lambda n: 2 * _PSI
                                 + 2 * _G / n},
    # twisted clover runs the clover operand set (twist folded into the
    # inverse blocks / an in-register rotation)
    "twisted_clover_pallas": {"alias": "clover_pallas"},
    "twisted_clover_pallas_r12": {"alias": "clover_pallas_r12"},
    "twisted_clover_pallas_mrhs": {"alias": "clover_pallas_mrhs"},
    # Ls-batched DWF hop: Ls spinor planes in+out, ONE gauge fetch
    "dwf_ls4_pallas": {"family": "dwf_ls4",
                       "floor": lambda n: 4 * 2 * _PSI + 2 * _G},
    "dwf_ls8_pallas": {"family": "dwf_ls8",
                       "floor": lambda n: 8 * 2 * _PSI + 2 * _G},
    # fused MG coarse stencil at the canonical probe size (E=16): the
    # distinct operands of one invocation are the 9 embedded link
    # matrices (36*E^2 B/site), the input vector read once (4*E) and
    # the output (4*E); the model's 9 psi stream reads (pre-rolled
    # neighbour copies) are re-reads over this floor
    "mg_coarse_pallas": {"family": "mg_coarse",
                         "floor": lambda n: 36.0 * 256 + 8 * 16.0},
}


def checkable_forms() -> List[str]:
    """Every KERNEL_MODELS form the drift lint covers: pallas forms with
    a traffic model.  Forms with ``bytes_per_site`` None (the XLA
    stencils, 'generic') are honest flops-only rows — nothing to
    cross-check."""
    return [f for f, m in KERNEL_MODELS.items()
            if m["bytes_per_site"] is not None]


def drift_row(form: str, probe: bool = True) -> dict:
    """One model-drift verdict: analytic flops vs the XLA reference
    count, analytic bytes vs the operand-footprint floor.  With
    ``probe=False`` a form not already probed this process comes back
    ``checked=False`` (no compile is triggered)."""
    with _lock:
        cached = _probe_cache.get(form)
    if cached is not None:
        return cached
    spec = _FOOTPRINTS.get(form)
    if spec is None:
        return {"form": form, "checked": False, "ok": False,
                "reasons": ["no footprint spec registered in "
                            "obs/costmodel.py — a pallas form shipped "
                            "without its drift check"]}
    base = form
    while "alias" in spec:
        base = spec["alias"]
        spec = _FOOTPRINTS[base]
    if not probe:
        return {"form": form, "checked": False, "ok": None,
                "reasons": []}
    m = KERNEL_MODELS[form]
    nrhs = _PROBE_NRHS if callable(m["bytes_per_site"]) else 1
    bps = m["bytes_per_site"](nrhs) if callable(m["bytes_per_site"]) \
        else float(m["bytes_per_site"])
    fps = float(m["flops_per_site"])
    floor = float(spec["floor"](nrhs))
    ref_fps = _ref_flops_per_site(spec["family"])
    flops_ratio = ref_fps / fps if fps else float("inf")
    bytes_ratio = bps / floor if floor else float("inf")
    reasons = []
    if not (1.0 - FLOPS_RTOL <= flops_ratio <= 1.0 + FLOPS_RTOL):
        reasons.append(
            f"flops drift: XLA counts {ref_fps:g} flops/site for the "
            f"{spec['family']} reference stencil but the model claims "
            f"{fps:g} (ratio {flops_ratio:.2f}, tolerance "
            f"±{FLOPS_RTOL:.0%})")
    if not (BYTES_REREAD_MIN <= bytes_ratio <= BYTES_REREAD_MAX):
        reasons.append(
            f"bytes drift: model claims {bps:g} B/site but the operand "
            f"footprint floor is {floor:g} (ratio {bytes_ratio:.2f}, "
            f"allowed [{BYTES_REREAD_MIN:g}, {BYTES_REREAD_MAX:g}]x)")
    row = {"form": form, "checked": True, "ok": not reasons,
           "nrhs": nrhs, "analytic_flops_per_site": fps,
           "xla_ref_flops_per_site": round(ref_fps, 1),
           "flops_ratio": round(flops_ratio, 4),
           "analytic_bytes_per_site": bps,
           "footprint_floor_bytes_per_site": floor,
           "bytes_ratio": round(bytes_ratio, 4),
           "reasons": reasons}
    with _lock:
        _probe_cache[form] = row
    from . import trace as otr
    otr.event("cost_drift", cat="costmodel", form=form, ok=row["ok"],
              flops_ratio=row["flops_ratio"],
              bytes_ratio=row["bytes_ratio"])
    return row


def check_forms(forms=None) -> List[dict]:
    """Drift rows for every checkable (or named) form — the model-drift
    report body."""
    return [drift_row(f) for f in (forms or checkable_forms())]


def lint(forms=None) -> List[dict]:
    """The drift LINT: raises with every failing form's reasons; returns
    the rows when all pass.  Run by tests/test_costmodel.py so a
    KERNEL_MODELS edit that disagrees with XLA's claim beyond tolerance
    cannot ship."""
    rows = check_forms(forms)
    bad = [r for r in rows if not r["ok"]]
    if bad:
        msg = "; ".join(f"{r['form']}: {'; '.join(r['reasons'])}"
                        for r in bad)
        raise AssertionError(f"cost-model drift lint failed: {msg}")
    return rows


def save_report(path: Optional[str] = None,
                fname: str = "cost_drift.tsv") -> Optional[str]:
    """The session's model-drift report: one row per form that COMPILED
    this session (note_compile keys), joined with its analytic model
    and any probe verdict already computed (``probe=False`` here — the
    shutdown path never triggers fresh compiles; the lint/bench own
    exhaustive probing).  None when nothing compiled or no output
    path."""
    import os

    from ..utils import config as qconf
    path = path or qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
    noted = noted_compiles()
    if not path or not noted:
        return None
    os.makedirs(path, exist_ok=True)
    cols = ("api", "form", "solver", "dtype", "compile_seconds",
            "analytic_flops_per_site", "analytic_bytes_per_site",
            "checked", "ok", "flops_ratio", "bytes_ratio")
    out = os.path.join(path, fname)

    def cell(v):
        # unprobed verdicts are None — render as EMPTY like the ratio
        # columns, not the string 'None'
        return "" if v is None else str(v)

    with open(out, "w") as fh:
        fh.write("\t".join(cols) + "\n")
        for n in noted:
            m = KERNEL_MODELS.get(n["form"], KERNEL_MODELS["generic"])
            bps = m["bytes_per_site"]
            d = drift_row(n["form"], probe=False) \
                if n["form"] in _FOOTPRINTS else None

            fh.write("\t".join(cell(v) for v in (
                n["api"], n["form"], n["solver"], n["dtype"],
                n["seconds"], m["flops_per_site"],
                bps(_PROBE_NRHS) if callable(bps) else bps,
                d["checked"] if d else None,
                d.get("ok") if d else None,
                d.get("flops_ratio") if d else None,
                d.get("bytes_ratio") if d else None)) + "\n")
    return out
