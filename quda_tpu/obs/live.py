"""Live telemetry plane: HTTP scrape endpoint + periodic exporter.

Every other observability leg (trace, metrics, flight, postmortems)
exports at ``end_quda`` — but a production solve-service worker is
long-lived and never reaches ``end_quda``, so without this module the
fleet runs blind.  The reference's answer to live introspection is its
NVTX-annotated wrappers and persistent QUDA_RESOURCE_PATH artifacts
(lib/generate/wrap.py, lib/tune.cpp:450-610); ours is the pull-based
Prometheus discipline the metrics registry was shaped for, with
PLQCD-style always-draining semantics (arXiv:1405.0700): the queue
keeps serving while the telemetry plane observes it.

A stdlib ``ThreadingHTTPServer`` bound on 127.0.0.1 serves:

* ``/metrics``  — Prometheus text from a lock-consistent live snapshot
  of the registry (obs/metrics.py ``snapshot``; NO reset — scrapes are
  idempotent reads);
* ``/healthz``  — process liveness + the attached solve-service
  worker-thread liveness;
* ``/readyz``   — 200 only when the attached service can serve: worker
  draining, warm start complete, a gauge registered/resident;
* ``/fleet``    — the live ``fleet_report.txt`` render (obs/report.py);
* ``/slo``      — ``serve_request_seconds`` error-budget burn rate
  against QUDA_TPU_SLO_TARGET_MS / QUDA_TPU_SLO_OBJECTIVE.

A background flusher (``QUDA_TPU_METRICS_FLUSH_SEC`` > 0) rewrites the
metrics/fleet/flight/roofline artifacts every interval so a crashed
worker loses at most one window of telemetry.

Activation: ``QUDA_TPU_LIVE=1`` (read by ``init_quda`` via
:func:`maybe_start`) or an explicit :func:`start`.  **Off means off**
— the obs discipline: every entry point returns after one
module-global load, no server/socket/thread exists, and no op is ever
added to a compiled solve either way (pinned by a raising-stub test
like every other leg).  The server holds its mutable state on the
session instance behind ``self.lock``; scrape handlers only READ the
other obs modules' lock-consistent snapshots.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class _Handler(BaseHTTPRequestHandler):
    """Routes one GET to the session's endpoint methods via
    :func:`_respond` (which owns the off-path gate); request logging
    to stderr is silenced — the scrape cadence is not operator news."""

    server_version = "quda-tpu-live"

    def do_GET(self):  # noqa: N802 — http.server API name
        status, ctype, body = _respond(self.path)
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 — API name
        pass


class _Live:
    """One live-telemetry session: the HTTP server, its worker thread,
    the optional periodic flusher, and the attached solve service."""

    def __init__(self, port: int, flush_sec: float):
        self.lock = threading.Lock()
        self.service = None          # attached SolveService (or None)
        self.flush_sec = float(flush_sec)
        self.t0 = time.time()
        self.shutdown = threading.Event()
        self.server = ThreadingHTTPServer(("127.0.0.1", int(port)),
                                          _Handler)
        self.server.daemon_threads = True
        self.port = int(self.server.server_address[1])
        self.thread = threading.Thread(target=self.server.serve_forever,
                                       name="quda-live", daemon=True)
        self.flusher: Optional[threading.Thread] = None
        if self.flush_sec > 0:
            self.flusher = threading.Thread(target=self._flush_loop,
                                            name="quda-live-flush",
                                            daemon=True)

    # -- lifecycle ----------------------------------------------------------

    def open(self):
        from . import trace as otr
        self.thread.start()
        if self.flusher is not None:
            self.flusher.start()
        otr.event("live_started", cat="live", port=self.port,
                  flush_sec=self.flush_sec)

    def close(self):
        self.shutdown.set()
        self.server.shutdown()
        self.thread.join(timeout=5.0)
        self.server.server_close()
        if self.flusher is not None:
            self.flusher.join(timeout=5.0)

    # -- periodic exporter --------------------------------------------------

    def _flush_loop(self):
        while not self.shutdown.wait(self.flush_sec):
            self.flush_window()

    def flush_window(self) -> dict:
        """One flush window: rewrite every incremental artifact.  Each
        leg is isolated — a full disk on one file must not stop the
        others (the end_quda epilogue contract)."""
        from ..utils import logging as qlog
        from . import flight as ofl
        from . import metrics as omet
        from . import roofline as orf
        from . import trace as otr
        written: dict = {}
        for name, step in (("metrics", omet.flush),
                           ("flight", ofl.flush),
                           ("roofline", orf.save)):
            try:
                written[name] = step()
            except Exception as e:   # noqa: BLE001 — keep flushing
                written[name] = None
                qlog.warn_once(
                    f"live_flush_{name}",
                    f"live flusher: {name} flush failed "
                    f"({type(e).__name__}: {str(e)[:120]})")
        omet.inc("live_flushes_total")
        otr.event("live_flush", cat="live",
                  artifacts=sorted(k for k, v in written.items() if v))
        return written

    # -- endpoints ----------------------------------------------------------

    def metrics(self):
        from . import metrics as omet
        body = omet.render_prometheus(omet.snapshot())
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                body.encode())

    def fleet(self):
        from . import metrics as omet
        from . import report as orep
        return (200, "text/plain; charset=utf-8",
                orep.render(omet.snapshot()).encode())

    def healthz(self):
        with self.lock:
            svc = self.service
        doc = {"uptime_s": round(time.time() - self.t0, 3),
               "service_attached": svc is not None}
        if svc is not None:
            h = svc.health()
            doc["worker_alive"] = h["worker_alive"]
            doc["stopped"] = h["stopped"]
        # liveness: the process answers; a dead worker thread behind a
        # live socket is exactly the zombie /healthz exists to expose
        ok = doc.get("worker_alive", True) or doc.get("stopped", False)
        return (200 if ok else 503, "application/json",
                (json.dumps(doc, sort_keys=True) + "\n").encode())

    def readyz(self):
        with self.lock:
            svc = self.service
        checks = {"service_attached": svc is not None}
        if svc is not None:
            h = svc.health()
            checks["worker_alive"] = h["worker_alive"]
            checks["queue_draining"] = (h["worker_alive"]
                                        and not h["stopped"])
            checks["warm_start_complete"] = h["warm_start_complete"]
            checks["gauge_present"] = h["gauge_present"]
        ready = bool(checks["service_attached"]
                     and all(checks.values()))
        doc = {"ready": ready, "checks": checks}
        return (200 if ready else 503, "application/json",
                (json.dumps(doc, sort_keys=True) + "\n").encode())

    def slo(self):
        from . import metrics as omet
        summary = slo_summary()
        for row in summary["families"]:
            omet.set_gauge("slo_burn_rate", row["burn_rate"],
                           family=row["family"])
        omet.set_gauge("slo_burn_rate",
                       summary["overall"]["burn_rate"], family="all")
        return (200, "application/json",
                (json.dumps(summary, sort_keys=True) + "\n").encode())


_session: Optional[_Live] = None


def enabled() -> bool:
    return _session is not None


def start(port: Optional[int] = None,
          flush_sec: Optional[float] = None) -> _Live:
    """Bind the telemetry server (idempotent: an active session and
    its port win).  ``port`` 0 = OS-assigned ephemeral; :func:`port`
    reports the bound one."""
    global _session
    if _session is not None:
        return _session
    from ..utils import config as qconf
    if port is None:
        port = int(qconf.get("QUDA_TPU_LIVE_PORT", fresh=True))
    if flush_sec is None:
        flush_sec = float(qconf.get("QUDA_TPU_METRICS_FLUSH_SEC",
                                    fresh=True))
    s = _Live(port, flush_sec)
    _session = s
    s.open()
    return s


def maybe_start() -> Optional[_Live]:
    """Start the plane iff QUDA_TPU_LIVE is set (init_quda hook).  A
    bind failure warns instead of raising — telemetry must never stop
    a solve session from opening."""
    from ..utils import config as qconf
    if not qconf.get("QUDA_TPU_LIVE", fresh=True):
        return None
    try:
        return start()
    except OSError as e:
        from ..utils import logging as qlog
        qlog.warningq(f"live telemetry disabled: cannot bind "
                      f"QUDA_TPU_LIVE_PORT ({e})")
        return None


def stop() -> Optional[int]:
    """Tear the server down (end_quda hook; returns the port it held).
    Runs BEFORE the other obs legs flush so no scrape can race their
    teardown."""
    global _session
    s = _session
    if s is None:
        return None
    _session = None
    s.close()
    return s.port


def port() -> Optional[int]:
    """The bound TCP port (None when the plane is off)."""
    s = _session
    if s is None:
        return None
    return s.port


def attach(service):
    """Point /healthz //readyz at a solve service (SolveService.start
    hook; one global load when the plane is off)."""
    s = _session
    if s is None:
        return
    with s.lock:
        s.service = service


def detach(service):
    """Drop the service reference at SolveService.stop — but only the
    one that attached; a replacement service must not be detached by
    its predecessor's teardown."""
    s = _session
    if s is None:
        return
    with s.lock:
        if s.service is service:
            s.service = None


def flush_now() -> Optional[dict]:
    """Run one flush window on the caller's thread (tests / operator
    tooling; None when the plane is off)."""
    s = _session
    if s is None:
        return None
    return s.flush_window()


def _respond(path: str):
    """Route one request; the single off-path gate for every endpoint.
    Returns (status, content-type, body-bytes)."""
    s = _session
    if s is None:
        return (503, "text/plain; charset=utf-8",
                b"no live telemetry session\n")
    route = path.split("?", 1)[0].rstrip("/") or "/"
    fn = {"/metrics": s.metrics, "/healthz": s.healthz,
          "/readyz": s.readyz, "/fleet": s.fleet,
          "/slo": s.slo}.get(route)
    if fn is None:
        out = (404, "text/plain; charset=utf-8",
               b"endpoints: /metrics /healthz /readyz /fleet /slo\n")
    else:
        try:
            out = fn()
        except Exception as e:   # noqa: BLE001 — a scrape must never
            # kill the server thread pool; the error IS the payload
            out = (500, "text/plain; charset=utf-8",
                   f"{type(e).__name__}: {e}\n".encode())
    from . import metrics as omet
    omet.inc("live_scrapes_total", endpoint=route.lstrip("/") or "root",
             code=f"{out[0] // 100}xx")
    return out


def slo_summary(snap: Optional[dict] = None) -> dict:
    """Burn-rate read of ``serve_request_seconds`` against the SLO
    knobs.  A request counts as good when its bucket's upper bound is
    within the target (the conservative read — bucketed data cannot
    place a sample more precisely); burn rate =
    (1 - compliance) / (1 - objective), so burn > 1 means the error
    budget is being spent faster than provisioned."""
    from ..utils import config as qconf
    from . import metrics as omet
    snap = snap or omet.snapshot()
    target_s = float(qconf.get("QUDA_TPU_SLO_TARGET_MS",
                               fresh=True)) / 1e3
    objective = float(qconf.get("QUDA_TPU_SLO_OBJECTIVE", fresh=True))
    budget = max(1e-9, 1.0 - objective)

    def _grade(h) -> dict:
        bounds = h.get("buckets", omet.HIST_BUCKETS)
        good = sum(h["counts"][i] for i, ub in enumerate(bounds)
                   if ub <= target_s)
        n = h["n"]
        compliance = (good / n) if n else 1.0
        return {"n": n, "good": good,
                "compliance": round(compliance, 6),
                "burn_rate": round((1.0 - compliance) / budget, 6)}

    families = []
    pooled_n = pooled_good = 0
    for (name, labels), h in sorted(snap["histograms"].items()):
        if name != "serve_request_seconds":
            continue
        row = _grade(h)
        row["family"] = dict(labels).get("family", "?")
        families.append(row)
        pooled_n += row["n"]
        pooled_good += row["good"]
    pooled = (pooled_good / pooled_n) if pooled_n else 1.0
    return {"target_ms": target_s * 1e3,
            "objective": objective,
            "families": families,
            "overall": {"n": pooled_n, "good": pooled_good,
                        "compliance": round(pooled, 6),
                        "burn_rate": round((1.0 - pooled) / budget, 6)}}
