"""Flight recorder: an always-cheap in-process ring buffer of events.

The trace session (obs/trace.py) is the *profiling* surface: opt-in,
unbounded-ish, flushed as chrome artifacts for humans studying a run
they planned to study.  A serving fleet needs the *black-box* half: when
a solve goes wrong on chip N hours into a run, the operator wants the
last few thousand structured events — API entries/exits, tuner
decisions, escalation rungs, sentinel codes, gauge loads/rejections,
exchange-policy picks — attached to the failure, without having paid
for full tracing all along.  That is this module: a bounded
``collections.deque`` ring (``QUDA_TPU_FLIGHT_EVENTS_MAX``, oldest
dropped and counted) fed by host-side appends only.

Feeds:

* every ``obs.trace.event(...)`` call site in the package taps into the
  ring when the recorder is on (the tap lives in trace.event, so tuner/
  robust/gauge/comms events arrive here with zero new call sites), even
  when the trace session itself is off;
* ``obs.trace.api_span`` records ``api_enter`` / ``api_exit`` markers;
* subsystems may call :func:`record` directly for ring-only events
  (names here are NOT part of the obs schema — the ring mirrors
  schema'd events, it does not mint dashboard names).

Activation: ``QUDA_TPU_FLIGHT=1`` (read by init_quda via
:func:`maybe_start`) or an explicit :func:`start`.  **Off means off**
(the obs no-op discipline): :func:`record` returns after one global
load, no ring exists, no clock is read, and no op is ever added to a
compiled solve either way — pinned by a raising-stub test
(tests/test_flight.py).

``end_quda`` flushes the ring tail to ``flight.jsonl`` under the
resource path (and the postmortem writer snapshots it into every
bundle); drops are surfaced as a ``flight_dropped`` trace event and on
the flush return so a truncated black box is never mistaken for a
complete one.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, Optional


class _Ring:
    """The live recorder: a maxlen deque + drop accounting.  Appends
    are host-side only (dict build + deque append under a lock) — the
    recorder never touches device values, so instrumented sites are
    safe around jit boundaries."""

    __slots__ = ("events", "maxlen", "dropped", "seq", "t0", "wall0",
                 "lock")

    def __init__(self, maxlen: int):
        self.maxlen = int(maxlen)
        self.events: collections.deque = collections.deque(
            maxlen=self.maxlen)
        self.dropped = 0
        self.seq = 0
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.lock = threading.Lock()

    def append(self, name: str, cat: str, fields: dict):
        with self.lock:
            if len(self.events) >= self.maxlen:
                self.dropped += 1
            self.seq += 1
            self.events.append({
                "seq": self.seq,
                "t_us": round((time.perf_counter() - self.t0) * 1e6, 3),
                "name": name, "cat": cat, **fields})


_session: Optional[_Ring] = None


def enabled() -> bool:
    return _session is not None


def start(maxlen: Optional[int] = None) -> _Ring:
    """Open a recorder session (idempotent: an active ring is kept —
    trace.start semantics; an explicit maxlen that conflicts with the
    live ring is discarded, the black box must not lose its tail
    mid-session)."""
    global _session
    if _session is None:
        if maxlen is None:
            from ..utils import config as qconf
            maxlen = int(qconf.get("QUDA_TPU_FLIGHT_EVENTS_MAX",
                                   fresh=True))
        _session = _Ring(max(1, int(maxlen)))
    return _session


def maybe_start() -> Optional[_Ring]:
    """Start a session iff QUDA_TPU_FLIGHT is set (init_quda hook)."""
    from ..utils import config as qconf
    if qconf.get("QUDA_TPU_FLIGHT", fresh=True):
        return start()
    return None


def record(name: str, cat: str = "event", **fields):
    """Append one event to the ring — the module no-op when the
    recorder is off (one global load, nothing else; the zero-overhead
    contract shared with obs.trace.event)."""
    r = _session
    if r is None:
        return
    r.append(name, cat, fields)


def dropped() -> int:
    r = _session
    return r.dropped if r is not None else 0


def tail(n: Optional[int] = None) -> List[dict]:
    """The newest ``n`` ring events (all when n is None), oldest first
    — the postmortem writer's snapshot hook.  Host-side copies; the
    ring keeps running."""
    r = _session
    if r is None:
        return []
    with r.lock:
        evs = list(r.events)
    return evs if n is None else evs[-int(n):]


def _json_safe(obj):
    """Ring fields arrive as whatever the call site passed (ints,
    floats, lists, the odd numpy scalar); render everything else via
    str so one exotic field can never eat the flush."""
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return str(obj)


def flush(path: Optional[str] = None,
          fname: str = "flight.jsonl") -> Optional[dict]:
    """Write the ring tail as JSONL under ``path`` (default: the
    resource path, else cwd); returns {'flight': file, 'events': n,
    'dropped': d} or None when the recorder is off.  The session stays
    active (incremental flushes overwrite)."""
    r = _session
    if r is None:
        return None
    if path is None:
        from ..utils import config as qconf
        path = qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True) or "."
    os.makedirs(path, exist_ok=True)
    fpath = os.path.join(path, fname)
    evs = tail()
    with open(fpath, "w") as fh:
        for e in evs:
            fh.write(json.dumps({k: _json_safe(v) for k, v in e.items()})
                     + "\n")
    return {"flight": fpath, "events": len(evs), "dropped": r.dropped}


def stop(flush_files: bool = True) -> Optional[dict]:
    """Close the recorder (end_quda hook); flushes flight.jsonl and —
    when the ring wrapped — emits the ``flight_dropped`` trace event so
    a truncated black box is auditable next to the artifacts it
    truncated."""
    global _session
    r = _session
    if r is None:
        return None
    # snapshot BEFORE the event: the trace tap appends the event to
    # this very ring, which on a full ring would inflate its own count
    n_dropped, n_kept = r.dropped, len(r.events)
    try:
        if n_dropped:
            from . import trace as otr
            otr.event("flight_dropped", cat="flight",
                      dropped=n_dropped, kept=n_kept)
        out = flush() if flush_files else None
        if out is not None:
            out["dropped"] = n_dropped
        return out
    finally:
        _session = None
