"""Bench-history layer: committed BENCH_*/MULTICHIP_* files as one
canonical, gate-credible time series.

PR 4 made every solve and bench row emit telemetry; this module is the
half that CONSUMES it across runs (ROADMAP open item 5, the arXiv:
1408.5925 cross-version performance-tracking discipline).  It parses
every committed ``BENCH_*.json`` / ``MULTICHIP_*.json`` — the driver's
per-round wrapper format ({"n", "rc", "tail", "parsed"}), bare bench.py
records (BENCH_TPU_LAST.json), and raw bench_suite JSON-line streams —
into canonical rows keyed by (metric, unit, platform, lattice, form,
mesh), and computes the best-credible baseline per series from rows
that pass ``bench.gate_row`` ONLY: round-5's 1.27e11-GFLOPS garbage can
never become a baseline someone "regresses" against, and a CPU row can
never set the bar for a TPU run (the PLQCD arXiv:1405.0700 lesson —
perf state is only meaningful keyed to the hardware that measured it).

Pure Python (no jax): tier-1 safe, and usable by the CI lint that keeps
committed history consumable forever (tests/test_bench_json_lint.py).

Consumers: ``obs.regress`` (the ``bench_suite --compare`` perf gate)
and the trends.tsv table PERF.md rounds cite instead of hand-copied
numbers.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

# units where larger is better; everything else regresses upward
THROUGHPUT_UNITS = ("gflops", "gbps", "msites_per_s")

# units tracked as TREND LINES only — never gated in either direction:
# ici_gb (analytic interconnect bytes per dslash apply, obs/comms.py)
# moves with the decomposition, not with performance, and drift_ratio
# (obs/costmodel.py analytic-vs-footprint) is a consistency check whose
# pass/fail lives in the drift lint, not the perf gate
TRENDED_ONLY_UNITS = ("ici_gb", "drift_ratio")

# suite-row fields that become canonical observations: (field, unit).
# ordered — for the secs family only the FIRST present field is taken
# (secs_per_call and secs are the same observable at different call
# sites, and double-recording would duplicate the series)
_VALUE_FIELDS = (("gflops", "gflops"), ("gbps", "gbps"),
                 ("msites_per_s", "msites_per_s"), ("iters", "iters"),
                 ("ici_gb", "ici_gb"),
                 ("cost_drift_ratio", "drift_ratio"))
_SECS_FIELDS = (("secs_per_call", "secs"), ("secs", "secs"),
                ("apply_secs", "apply_secs"))

_ROUND_RE = re.compile(r"_r(\d+)\.json$")


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def series_key(row: dict) -> tuple:
    """Canonical identity of one time series: what must match for two
    observations to be comparable across rounds."""
    return (row["metric"], row["unit"], row["platform"],
            row.get("lattice") or "", row.get("form") or "",
            row.get("mesh") or "")


def _fmt_list(v) -> str:
    if isinstance(v, (list, tuple)):
        return "x".join(str(x) for x in v)
    return str(v) if v is not None else ""


def _mk_row(metric, unit, value, platform, *, lattice=None, form=None,
            mesh=None, suite="", source="", round_no=None, carried=False,
            measured_at=None) -> dict:
    return {"metric": metric, "unit": unit, "value": value,
            "platform": platform, "lattice": _fmt_list(lattice),
            "form": form or "", "mesh": _fmt_list(mesh), "suite": suite,
            "source": source, "round": round_no, "carried": carried,
            "measured_at": measured_at}


def _gate(suite: str, row: dict) -> Tuple[bool, str]:
    """bench.gate_row against the row's OWN platform banner: the secs
    floor and roofline bounds still apply, and a row without a platform
    fails the banner check — un-attributable rows are never credible.
    (bench.py lives at the repo root next to the committed history; when
    the package is imported without it, a minimal finite/positive check
    stands in so the library layer stays importable.)"""
    try:
        import bench
    except ImportError:
        import math
        for k in ("gflops", "gbps"):
            v = row.get(k)
            if v is not None and not (isinstance(v, (int, float))
                                      and math.isfinite(v) and v >= 0):
                return False, f"{k}={v!r} is not a finite throughput"
        return bool(row.get("platform")), "no platform"
    return bench.gate_row(suite, row,
                          banner_platform=row.get("platform") or "?")


def rows_from_record(rec: dict, source: str = "",
                     round_no: Optional[int] = None,
                     carried: bool = False,
                     stats: Optional[dict] = None) -> List[dict]:
    """Canonical rows from one bench.py headline record (including the
    nested carried ``last_tpu`` measurement and the per-path GFLOPS
    table).  Records without a ``platform`` are legacy (pre-gate
    schema): counted, never recorded."""
    stats = stats if stats is not None else {}
    out: List[dict] = []
    plat = rec.get("platform")
    if not plat:
        if _num(rec.get("value")):
            stats["legacy"] = stats.get("legacy", 0) + 1
        else:
            stats["empty"] = stats.get("empty", 0) + 1
    else:
        lat = rec.get("lattice")
        at = rec.get("measured_at")
        v = _num(rec.get("value"))
        if v is not None and v > 0:
            cand = _mk_row(str(rec.get("metric",
                                       "wilson_dslash_gflops_chip")),
                           str(rec.get("unit", "GFLOPS")).lower(), v,
                           plat, lattice=lat, form=rec.get("path"),
                           suite="headline", source=source,
                           round_no=round_no, carried=carried,
                           measured_at=at)
            ok, _ = _gate("dslash", {"name": cand["metric"],
                                     "gflops": v, "platform": plat})
            if ok:
                out.append(cand)
            else:
                stats["ungated"] = stats.get("ungated", 0) + 1
        for pname, pv in (rec.get("paths") or {}).items():
            pv = _num(pv)
            if pname.endswith("_error") or pv is None:
                continue
            ok, _ = _gate("dslash", {"name": pname, "gflops": pv,
                                     "platform": plat})
            if not ok:
                stats["ungated"] = stats.get("ungated", 0) + 1
                continue
            out.append(_mk_row(f"dslash_path/{pname}", "gflops", pv,
                               plat, lattice=lat, form=pname,
                               suite="dslash", source=source,
                               round_no=round_no, carried=carried,
                               measured_at=at))
    sub = rec.get("last_tpu")
    if isinstance(sub, dict):
        out.extend(rows_from_record(sub, source, round_no, carried=True,
                                    stats=stats))
    return out


def rows_from_suite_row(row: dict, source: str = "",
                        round_no: Optional[int] = None,
                        stats: Optional[dict] = None) -> List[dict]:
    """Canonical rows from one bench_suite JSON line.  Rejection/error/
    skip rows are counted (they are part of the record, not data);
    recorded rows must carry a platform and re-pass ``gate_row`` to
    become baseline-eligible."""
    stats = stats if stats is not None else {}

    def bump(k):
        stats[k] = stats.get(k, 0) + 1

    if row.get("skipped"):
        bump("skipped")
        return []
    if "rejected" in row:
        bump("rejected")
        return []
    if "error" in row:
        bump("error")
        return []
    suite, name = row.get("suite"), row.get("name")
    if not suite or not name or suite == "harness":
        bump("other")
        return []
    if not row.get("platform"):
        bump("legacy")
        return []
    ok, _reason = _gate(suite, row)
    if not ok:
        bump("ungated")
        return []
    out = []
    fields = list(_VALUE_FIELDS)
    for f, u in _SECS_FIELDS:
        if _num(row.get(f)) is not None:
            fields.append((f, u))
            break
    for field, unit in fields:
        v = _num(row.get(field))
        if v is None:
            continue
        out.append(_mk_row(f"{suite}/{name}", unit, v, row["platform"],
                           lattice=row.get("lattice"),
                           form=row.get("form"), mesh=row.get("mesh"),
                           suite=suite, source=source,
                           round_no=round_no,
                           measured_at=row.get("measured_at")))
    if out:
        bump("recorded")
    return out


def _json_objects_from_tail(tail: str) -> Iterable[dict]:
    """JSON objects embedded in a captured-stdout tail: one per line,
    tolerating log-prefix junk before the first '{' (the round-1 tail
    carries a jax platform WARNING on the same stream)."""
    for line in (tail or "").splitlines():
        i = line.find("{")
        if i < 0:
            continue
        try:
            obj = json.loads(line[i:])
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            yield obj


def _eat_obj(obj: dict, out: List[dict], source: str,
             round_no: Optional[int], stats: dict):
    if "suite" in obj:
        out.extend(rows_from_suite_row(obj, source, round_no, stats))
    elif "metric" in obj:
        out.extend(rows_from_record(obj, source, round_no, stats=stats))
    elif "tail" in obj or "parsed" in obj or "n_devices" in obj:
        # driver wrapper (BENCH_rNN / MULTICHIP_rNN): rows live in the
        # tail stream; "parsed" duplicates the tail's last JSON line,
        # so it is only consulted when the tail yielded nothing (the
        # History seen-set dedupes the overlap otherwise)
        before = len(out)
        for sub in _json_objects_from_tail(obj.get("tail") or ""):
            _eat_obj(sub, out, source, round_no, stats)
        parsed = obj.get("parsed")
        if len(out) == before and isinstance(parsed, dict):
            _eat_obj(parsed, out, source, round_no, stats)
    else:
        stats["other"] = stats.get("other", 0) + 1


def parse_file(path: str) -> Tuple[List[dict], dict]:
    """All canonical rows in one committed bench artifact, plus a stats
    dict ({'recorded', 'legacy', 'ungated', 'rejected', 'error',
    'skipped', 'empty', 'unparseable', ...}) describing what was seen
    but not recorded."""
    source = os.path.basename(path)
    m = _ROUND_RE.search(source)
    round_no = int(m.group(1)) if m else None
    stats: dict = {}
    out: List[dict] = []
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return [], {"unparseable": 1}
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict):
        _eat_obj(doc, out, source, round_no, stats)
    elif doc is None:
        # JSON-lines stream (a bench_suite run teed to a file)
        parsed_any = False
        for obj in _json_objects_from_tail(text):
            parsed_any = True
            _eat_obj(obj, out, source, round_no, stats)
        if not parsed_any:
            stats["unparseable"] = stats.get("unparseable", 0) + 1
    else:
        stats["unparseable"] = stats.get("unparseable", 0) + 1
    return out, stats


class History:
    """The canonical time series: series_key -> observations sorted by
    round, with exact-duplicate suppression (the carried ``last_tpu``
    record repeats verbatim across rounds until a fresh chip number
    lands; the wrapper's ``parsed`` duplicates its tail line)."""

    def __init__(self):
        self.series: Dict[tuple, List[dict]] = {}
        self.stats: dict = {}
        self.files: List[str] = []
        self._seen: set = set()

    def add(self, row: dict):
        key = series_key(row)
        # carried rows (last_tpu) repeat verbatim across ROUNDS until a
        # fresh measurement lands: their identity is the measurement
        # itself, not the round that re-printed it
        sig = (key, None if row.get("carried") else row.get("round"),
               row["value"], row.get("measured_at"), row.get("carried"))
        if sig in self._seen:
            self.stats["duplicate"] = self.stats.get("duplicate", 0) + 1
            return
        self._seen.add(sig)
        self.series.setdefault(key, []).append(row)

    def without_round(self, round_no: int) -> "History":
        """A copy of this history with one round's own (non-carried)
        observations removed — the baseline the --latest dry mode diffs
        that round against, built without re-parsing any files."""
        h = History()
        h.files = list(self.files)
        h.stats = dict(self.stats)
        for rows in self.series.values():
            for r in rows:
                if r.get("round") == round_no and not r.get("carried"):
                    continue
                h.add(r)
        return h.finish()

    def add_stats(self, stats: dict):
        for k, v in stats.items():
            self.stats[k] = self.stats.get(k, 0) + v

    def finish(self):
        for rows in self.series.values():
            rows.sort(key=lambda r: (r.get("round") is not None,
                                     r.get("round") or 0))
        return self

    def best(self, key: tuple) -> Optional[dict]:
        """Best-credible observation for a series (gating already
        happened at parse time): max for throughput units, min for
        secs/iters — the baseline the compare gate diffs against."""
        rows = self.series.get(key)
        if not rows:
            return None
        if key[1] in THROUGHPUT_UNITS:
            return max(rows, key=lambda r: r["value"])
        return min(rows, key=lambda r: r["value"])

    def latest(self, key: tuple) -> Optional[dict]:
        rows = self.series.get(key)
        return rows[-1] if rows else None

    def max_round(self) -> Optional[int]:
        rounds = [r.get("round") for rows in self.series.values()
                  for r in rows if r.get("round") is not None]
        return max(rounds) if rounds else None


def history_files(dirpath: str) -> List[str]:
    return sorted(glob.glob(os.path.join(dirpath, "BENCH_*.json"))
                  + glob.glob(os.path.join(dirpath, "MULTICHIP_*.json")))


def load_history(dirpath: str,
                 exclude_rounds: Iterable[int] = ()) -> History:
    """Parse every committed bench artifact under ``dirpath`` into one
    History.  ``exclude_rounds`` drops whole rounds (the --latest dry
    mode compares the newest round against the rest)."""
    h = History()
    excl = set(exclude_rounds)
    for path in history_files(dirpath):
        rows, stats = parse_file(path)
        h.files.append(os.path.basename(path))
        h.add_stats(stats)
        for r in rows:
            if r.get("round") in excl:
                continue
            h.add(r)
    return h.finish()


def trend_table(history: History,
                current: Optional[List[dict]] = None) -> str:
    """The TSV trend table PERF.md rounds cite instead of hand-copied
    numbers: one line per series with its best-credible baseline, the
    latest observation, and the compact per-round history."""
    lines = ["metric\tunit\tplatform\tlattice\tform\tmesh\tn\t"
             "best\tbest_src\tlatest\tlatest_src\tcurrent\thistory"]
    cur_by_key: Dict[tuple, dict] = {}
    for row in current or []:
        cur_by_key[series_key(row)] = row
    keys = set(history.series) | set(cur_by_key)
    for key in sorted(keys, key=lambda k: tuple(str(x) for x in k)):
        rows = history.series.get(key, [])
        best = history.best(key)
        latest = history.latest(key)
        cur = cur_by_key.get(key)

        def _src(r):
            if r is None:
                return ""
            return (f"r{r['round']:02d}" if r.get("round") is not None
                    else (r.get("source") or "?"))

        hist = " ".join(f"{_src(r)}:{r['value']:g}" for r in rows)
        metric, unit, platform, lattice, form, mesh = key
        lines.append("\t".join([
            metric, unit, platform, str(lattice), str(form), str(mesh),
            str(len(rows)),
            f"{best['value']:g}" if best else "", _src(best),
            f"{latest['value']:g}" if latest else "", _src(latest),
            f"{cur['value']:g}" if cur else "", hist]))
    return "\n".join(lines) + "\n"
