"""Per-solve convergence recording: residual histories + solver events.

Reference behavior: the reference prints per-iteration residuals at
VERBOSE verbosity from every solver (PrintStats, lib/solver.cpp) and
reports reliable-update/restart events; convergence history is the
first thing a failing production solve needs and the one thing a
compiled lax.while_loop hides.

TPU mechanics: solvers cannot append to host lists from inside a
while_loop, so each solver (solvers/cg.py, fused_iter.py, mixed.py,
multishift.py, bicgstab.py, block.py) takes an opt-in ``record=True``
that threads a preallocated NaN-filled history buffer through the loop
carry — written at convergence-check points, i.e. every iteration at
cadence 1 and every k-th at QUDA_TPU_CG_CHECK_EVERY=k — and returns it
as ``SolverResult.history``.  ``harvest`` turns that device buffer into
a host-side :class:`ConvergenceRecord` (cadence inferred, gaps marked,
reliable-update/breakdown/per-shift/per-RHS events extracted) and
``publish`` surfaces it on InvertParam (``res_history`` / ``events``)
and as per-iteration ``residual`` events in the trace JSONL stream.

With ``record=False`` (the default, and always when QUDA_TPU_TRACE is
off) the history buffer is never allocated and the loop carry is
byte-identical to the unrecorded solver — zero overhead.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class ConvergenceRecord:
    """One solve's convergence story, host-side and dumpable."""
    solver: str
    tol: float
    cadence: int                      # check cadence the history was
                                      # recorded at (1 = every iteration)
    iters: int                        # iterations actually executed
    b2: float                         # |b|^2 of the recorded system
    history: List[dict]               # [{"iter", "r2", "relres"}, ...]
    events: List[dict]                # reliable_update / restart /
                                      # breakdown / shift_converged /
                                      # cadence markers
    lanes: Optional[dict] = None      # per-RHS/per-shift histories:
                                      # {label: [{"iter","r2","relres"}]}

    def dump(self, path: str):
        """Write the record as JSON (per-solve dump)."""
        with open(path, "w") as fh:
            json.dump(dataclasses.asdict(self), fh, indent=1)

    def relres_final(self) -> Optional[float]:
        return self.history[-1]["relres"] if self.history else None


def _relres(r2: float, b2: float) -> float:
    if not (b2 > 0.0) or not math.isfinite(r2):
        return float("nan")
    return math.sqrt(max(r2, 0.0) / b2)


def _entries(r2_slots: np.ndarray, cadence: int, b2: float) -> List[dict]:
    out = []
    for i, v in enumerate(r2_slots):
        v = float(v)
        if math.isnan(v):
            break
        out.append({"iter": (i + 1) * cadence, "r2": v,
                    "relres": _relres(v, b2)})
    return out


def _infer_cadence(r2_slots: np.ndarray, iters: int) -> int:
    n_valid = 0
    for v in np.asarray(r2_slots, dtype=np.float64):
        if math.isnan(float(v)):
            break
        n_valid += 1
    if n_valid <= 0 or iters <= 0:
        return 1
    return max(1, int(round(iters / n_valid)))


def harvest(solver: str, res, tol: float, b2
            ) -> Optional[ConvergenceRecord]:
    """SolverResult-with-history -> ConvergenceRecord (None when the
    solve recorded nothing — the zero-overhead path).

    ``b2`` is the reference norm relres is judged against: a scalar, or
    — for per-RHS (2-D) histories — an (nrhs,) vector so every lane is
    normalized against ITS OWN |b_i|^2 (a single worst-lane scalar
    under-reports every other lane's relative residual).  A dict
    history that carries its own ``b2`` key (a solver that recorded a
    different system than the caller's, e.g. cg_reliable_df's
    normal-equation curve) overrides the argument."""
    h = getattr(res, "history", None)
    if h is None:
        return None
    # per-RHS solvers report an (nrhs,) iteration vector; the executed
    # lockstep iteration count is the slowest lane's
    iters = int(np.max(np.asarray(res.iters)))
    b2_vec = np.asarray(b2, dtype=np.float64).reshape(-1)
    b2 = float(np.max(b2_vec))
    events: List[dict] = []
    lanes = None

    if isinstance(h, dict):
        if h.get("b2") is not None:
            b2 = float(np.asarray(h["b2"], dtype=np.float64))
        r2 = np.asarray(h["r2"], dtype=np.float64)
        cadence = _infer_cadence(r2, iters)
        history = _entries(r2, cadence, b2)
        rel = h.get("reliable")
        if rel is not None:
            rel = np.asarray(rel)
            for i in range(min(len(rel), len(history))):
                if bool(rel[i]):
                    events.append({"type": "reliable_update",
                                   "iter": (i + 1) * cadence})
        sh = h.get("shift_r2")
        if sh is not None:
            sh = np.asarray(sh, dtype=np.float64)
            lanes = {}
            stop = (tol ** 2) * b2
            for s in range(sh.shape[1]):
                lane = _entries(sh[:, s], cadence, b2)
                lanes[f"shift{s}"] = lane
                conv_at = next((e["iter"] for e in lane
                                if e["r2"] <= stop), None)
                if conv_at is not None:
                    events.append({"type": "shift_converged",
                                   "shift": s, "iter": conv_at})
    else:
        a = np.asarray(h, dtype=np.float64)
        if a.ndim == 2:
            # per-RHS lanes (block solvers): each lane is normalized
            # against its own b2 (scalar b2 broadcasts), and the
            # headline history is the worst RELATIVE lane per slot —
            # the lane-picking must happen in relres units or a
            # big-norm RHS masks a stalled small-norm one (-inf fill
            # keeps fully-unwritten slots NaN without a nanmax warning)
            nl = a.shape[1]
            lane_b2 = (np.full(nl, b2_vec[0]) if b2_vec.size == 1
                       else b2_vec[:nl])
            rel_a = a / np.where(lane_b2 > 0.0, lane_b2, np.nan)[None, :]
            filled = np.where(np.isnan(rel_a), -np.inf, rel_a)
            idx = (filled.argmax(axis=1) if a.size
                   else np.zeros(len(a), np.intp))
            worst = a[np.arange(len(a)), idx]
            worst = np.where(np.isneginf(filled.max(axis=1)),
                             np.nan, worst)
            worst_b2 = lane_b2[idx]
            cadence = _infer_cadence(worst, iters)
            history = []
            for i, v in enumerate(worst):
                v = float(v)
                if math.isnan(v):
                    break
                history.append({"iter": (i + 1) * cadence, "r2": v,
                                "relres": _relres(v,
                                                  float(worst_b2[i]))})
            lanes = {f"rhs{i}": _entries(a[:, i], cadence,
                                         float(lane_b2[i]))
                     for i in range(nl)}
        else:
            cadence = _infer_cadence(a, iters)
            history = _entries(a, cadence, b2)

    if cadence > 1:
        # the cadence gap marker the check-cadence contract requires:
        # residuals between check points were computed but not observed
        events.insert(0, {"type": "check_cadence", "every": cadence,
                          "note": f"residuals recorded every {cadence} "
                                  "iterations; intermediate iterations "
                                  "are cadence gaps"})
    if history and not math.isnan(history[-1]["r2"]):
        if not np.asarray(res.converged).all():
            events.append({"type": "unconverged", "iter": iters,
                           "r2": history[-1]["r2"]})
    if any(math.isinf(e["r2"]) or math.isnan(e["r2"]) for e in history):
        events.append({"type": "breakdown",
                       "note": "non-finite residual in history"})
    return ConvergenceRecord(solver=solver, tol=float(tol),
                             cadence=cadence, iters=iters, b2=b2,
                             history=history, events=events, lanes=lanes)


def publish(rec: Optional[ConvergenceRecord], param=None):
    """Surface a record on an InvertParam (res_history/events) and emit
    per-iteration ``residual`` events into the trace stream (one per
    history entry; per-lane entries carry their lane label)."""
    if rec is None:
        return None
    if param is not None:
        param.res_history = list(rec.history)
        param.events = list(rec.events)
    from . import trace as otr
    if otr.enabled():
        for e in rec.history:
            otr.event("residual", cat="convergence", solver=rec.solver,
                      iter=e["iter"], r2=e["r2"], relres=e["relres"])
        if rec.lanes:
            for label, lane in rec.lanes.items():
                for e in lane:
                    otr.event("residual_lane", cat="convergence",
                              solver=rec.solver, lane=label,
                              iter=e["iter"], r2=e["r2"],
                              relres=e["relres"])
        for ev in rec.events:
            otr.event(ev.get("type", "solver_event"), cat="convergence",
                      solver=rec.solver,
                      **{k: v for k, v in ev.items() if k != "type"})
    return rec
