"""Postmortem bundles: one self-contained directory per solve failure.

Reference behavior: the reference's production posture stores
self-describing artifacts under $QUDA_RESOURCE_PATH (tunecache.tsv,
profile_N.tsv) so a run can be understood after the fact
(lib/tune.cpp:450-610); arXiv:1408.5925's framework keeps every solver
decision resident and inspectable.  A serving fleet needs the black-box
half of that discipline: when a solve goes wrong on chip N hours into a
run, an operator pulls ONE bundle and re-runs that exact solve on a
workstation (obs/replay.py).  This module writes the bundle.

Capture triggers (the ISSUE-11 failure-path inventory):

* sentinel breakdown / verification mismatch — the classification
  branches of ``interfaces/quda_api._solve_supervision``;
* construction failure and ladder exhaustion — every failure path of
  ``robust/escalate.run_ladder`` (``_pm_capture`` sites, linted by
  tests/test_flight_lint.py);
* gauge rejection — ``load_gauge_quda``'s non-finite screen;
* any uncaught exception crossing an ``interfaces/quda_api.py`` API
  boundary (the ``_pm_api`` guard's except-to-status site).

Bundle layout (``<postmortem dir>/pm_<stamp>_p<pid>_<seq>_<trigger>/``)::

    manifest.json     trigger, api, platform/topology, knob snapshot
                      (raw strings — the replay input), param
                      provenance incl. solve_attempts, field index
    flight.jsonl      the flight-recorder ring tail (obs/flight.py)
    metrics.json      metrics-registry snapshot (obs/metrics.py)
    hbm.json          HBM field ledger + device high-water (obs/memory)
    tunecache.json    the tunecache entries consulted on this platform
    fields/*.npy      content-hashed gauge/fat/long/source dumps,
                      size-capped by QUDA_TPU_POSTMORTEM_MAX_MB
                      (fields past the cap stay in the manifest as
                      omitted entries with shape/dtype/sha256)

Activation: ``QUDA_TPU_POSTMORTEM`` ('1' always / '0' never / empty =
follow the flight recorder).  **Off means off**: :func:`capture`
returns after one knob read, no directory is ever created, and no op
is added to a compiled solve either way — pinned by the raising-stub
test next to the flight recorder's.  Bundle writes are bounded per
session (``QUDA_TPU_POSTMORTEM_MAX_BUNDLES``); a capture that fails
internally warns and returns None — the postmortem writer must never
turn a recoverable failure into a crash (AssertionError propagates so
the raising-stub pins stay effective).

``end_quda`` indexes every bundle (with everything else it flushed)
into ``artifacts_manifest.json`` via :func:`write_artifacts_manifest`;
the fleet report renders the session's bundles in its "Postmortems"
section with their replay-verified status (obs/replay.py writes
``replay.json`` into a bundle it has re-run).
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import sys
import threading
import time
from typing import List, Optional

# field-dump priority under the size cap: replay needs gauge + source;
# links before derived/auxiliary fields
_PRIORITY = {"gauge": 0, "source": 1, "fat": 2, "long": 3, "clover": 4}

_bundles: List[dict] = []
_suppressed = 0
_seq = 0
# captures can arrive from the solve-service worker thread and the
# caller concurrently; the session bundle index must not lose entries
# (the obs/memory lock discipline).  _inflight counts cap slots
# reserved by captures still writing their bundle, so two concurrent
# captures at len == cap-1 cannot both pass the cap check
_inflight = 0
_bundles_lock = threading.Lock()

# Serve-request scope stack (pushed by the solve-service worker around
# each executed batch, serve/service.py _solve): the ids of the
# SolveTickets whose batch is currently on the API — read by the API
# spans (request_ids attribute) and by _write_bundle, which lands them
# in manifest.json so an operator goes from a failed ticket to its
# bundle in one grep.  Unlike _scopes this is NOT gated on capture
# being enabled: a list push per batch is host-side noise, and the ids
# must be present whenever a capture fires mid-batch.
_serve_requests: List[tuple] = []

# Per-API-call scope stack (pushed by quda_api's _pm_api guard): gives
# capture sites deep in the call tree the API name, the caller's
# source/param, and the knob snapshot AS OF API ENTRY (an escalation
# rung's scoped overrides must not leak into the replay input — the
# replay re-runs the WHOLE solve, ladder included).  The ``captured``
# flag lets the boundary exception guard skip a failure that already
# captured a more specific trigger.
_scopes: List[dict] = []


def enabled() -> bool:
    """'1' = always, '0' = never, empty = ride the flight recorder (a
    bundle without the ring tail is half blind, so capture defaults to
    following QUDA_TPU_FLIGHT's live session)."""
    from ..utils import config as qconf
    v = str(qconf.get("QUDA_TPU_POSTMORTEM", fresh=True))
    if v == "1":
        return True
    if v == "0":
        return False
    from . import flight as ofl
    return ofl.enabled()


def bundle_root() -> str:
    """The directory receiving bundle dirs: QUDA_TPU_POSTMORTEM_PATH,
    else <resource path>/postmortems (cwd-relative when no resource
    path is configured)."""
    from ..utils import config as qconf
    path = qconf.get("QUDA_TPU_POSTMORTEM_PATH", fresh=True)
    if path:
        return path
    rp = qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
    return os.path.join(rp or ".", "postmortems")


def bundles() -> List[dict]:
    """Bundles written this session: [{'path', 'trigger', 'api',
    'wall'}] (fleet report + artifacts manifest consumers)."""
    with _bundles_lock:
        return list(_bundles)


def suppressed() -> int:
    return _suppressed


def reset_session():
    """Forget this session's bundle list (init/end_quda hook; the
    bundle DIRECTORIES persist on disk — only the in-process index
    resets)."""
    global _suppressed, _inflight
    with _bundles_lock:
        _bundles.clear()
        _suppressed = 0
        _inflight = 0
    # init/end teardown runs on the owning thread before/after any
    # capture can be in flight; the scope stack is per-call LIFO state
    # a lock cannot meaningfully serialize
    _scopes.clear()  # quda-lint: disable=lock-discipline  reason=session teardown; no capture is in flight across init/end boundaries
    _serve_requests.clear()  # quda-lint: disable=lock-discipline  reason=session teardown; the solve-service worker is stopped before end_quda runs


def current_scope() -> Optional[dict]:
    return _scopes[-1] if _scopes else None


@contextlib.contextmanager
def serve_requests(ids):
    """Mark the solve-service request ids riding the current API call
    (see the stack comment above).  The worker wraps each executed
    batch; nesting is the worker's own call nesting, single-threaded
    by the service's one-worker contract."""
    _serve_requests.append(tuple(str(i) for i in ids))  # quda-lint: disable=lock-discipline  reason=per-batch LIFO context stack, push/pop ordering is the worker thread's own nesting
    try:
        yield
    finally:
        _serve_requests.pop()  # quda-lint: disable=lock-discipline  reason=per-batch LIFO context stack, push/pop ordering is the worker thread's own nesting


def current_request_ids() -> tuple:
    """The innermost serve-request ids (() outside the service)."""
    return _serve_requests[-1] if _serve_requests else ()


@contextlib.contextmanager
def solve_scope(api: str, param=None, source=None,
                source_name: str = "source"):
    """Per-API-call capture context (see the stack comment above).
    Entered by the ``_pm_api`` guard only when capture is enabled —
    the disabled path never builds the knob snapshot."""
    from ..utils import config as qconf
    # the scope stack is LIFO state tied to context-manager nesting on
    # the calling thread; a lock cannot make cross-thread push/pop
    # interleavings meaningful (concurrent API calls each need their
    # own capture context — a thread-local stack is the round-18+
    # upgrade if multi-threaded serving outgrows the single worker)
    _scopes.append({"api": api, "param": param, "source": source,  # quda-lint: disable=lock-discipline  reason=per-call LIFO context stack, push/pop ordering is the calling thread's own nesting
                    "source_name": source_name, "captured": False,
                    "knobs_raw": qconf.snapshot_raw()})
    try:
        yield _scopes[-1]
    finally:
        popped = _scopes.pop()  # quda-lint: disable=lock-discipline  reason=per-call LIFO context stack, push/pop ordering is the calling thread's own nesting
        # one failure, one bundle — across NESTED boundaries too: an
        # exception captured inside (e.g. invert_quda called from the
        # invert_multi_src_quda fallback loop) must not re-capture at
        # the outer boundary, so the flag propagates outward on exit
        if popped.get("captured") and _scopes:
            _scopes[-1]["captured"] = True


def capture(trigger: str, api: Optional[str] = None, param=None,
            fields: Optional[dict] = None, exc: Optional[BaseException]
            = None, note: Optional[str] = None) -> Optional[str]:
    """Write one postmortem bundle for a failure; returns its directory
    (None when capture is off, suppressed past the session cap, or the
    writer itself failed).  ``fields`` overrides the default dump set
    (resident gauge/fat/long from the API context + the scope's
    source); ``param`` defaults to the scope's InvertParam — pass the
    attempt copy at attempt-level sites so the bundle records the
    provenance of the failing attempt, not the caller's final view.

    One bundle per API call: the FIRST capture inside a solve scope
    wins; later triggers of the same call (every subsequent rung of an
    exhausting ladder re-classifying the same failure) are skipped —
    without this, one persistently-failing solve under 'escalate'
    would burn MAX_RETRIES near-identical bundles off the session cap
    and starve the next, distinct failure of its bundle."""
    if not enabled():
        return None
    global _suppressed, _inflight
    from ..utils import config as qconf
    from ..utils import logging as qlog
    from . import metrics as omet
    from . import trace as otr
    scope = current_scope()
    if scope is not None and scope.get("captured"):
        return None
    if api is None:
        api = scope["api"] if scope else "unknown"
    if param is None and scope is not None:
        param = scope["param"]
    cap = int(qconf.get("QUDA_TPU_POSTMORTEM_MAX_BUNDLES", fresh=True))
    with _bundles_lock:
        over_cap = len(_bundles) + _inflight >= max(1, cap)
        if over_cap:
            _suppressed += 1
        else:
            _inflight += 1
    if over_cap:
        if scope is not None:
            scope["captured"] = True
        omet.inc("postmortems_total", trigger="suppressed")
        qlog.warn_once(
            "postmortem_suppressed",
            f"postmortem: session bundle cap "
            f"(QUDA_TPU_POSTMORTEM_MAX_BUNDLES={cap}) reached; further "
            "captures are counted but not written")
        return None
    try:
        path = _write_bundle(trigger, api, param, fields, exc, note,
                             scope)
    except AssertionError:
        with _bundles_lock:
            _inflight -= 1     # release the reserved cap slot
        raise                  # raising-stub pins must stay effective
    except Exception as e:     # noqa: BLE001 — never worsen a failure
        with _bundles_lock:
            _inflight -= 1
        qlog.warningq(
            f"postmortem capture failed ({type(e).__name__}: "
            f"{str(e)[:120]}); the original failure is unaffected")
        return None
    if scope is not None:
        scope["captured"] = True
    with _bundles_lock:
        _inflight -= 1         # reservation becomes the real entry
        _bundles.append({"path": path, "trigger": trigger, "api": api,
                         "wall": time.time(),
                         "request_ids": list(current_request_ids())})
    omet.inc("postmortems_total", trigger=trigger)
    otr.event("postmortem_written", cat="postmortem", trigger=trigger,
              api=api, path=path)
    qlog.warningq(f"postmortem bundle written: {path} "
                  f"(trigger {trigger}; replay with `python -m "
                  "quda_tpu.obs.replay <bundle>`)")
    return path


def capture_exception(api: str, exc: BaseException) -> Optional[str]:
    """The API-boundary guard's except-to-status hook: capture an
    uncaught exception UNLESS a more specific trigger already captured
    during this API call (scope ``captured`` flag) — one failure, one
    bundle."""
    s = current_scope()
    if s is not None and s.get("captured"):
        return None
    return capture(f"exception:{type(exc).__name__}", api=api, exc=exc)


# -- bundle writing ----------------------------------------------------------

def _json_default(obj):
    return str(obj)


def _write_json(bdir: str, rel: str, doc, files: dict):
    fpath = os.path.join(bdir, rel)
    with open(fpath, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True,
                  default=_json_default)
    files[rel] = {"bytes": os.path.getsize(fpath)}


def _param_dict(param) -> Optional[dict]:
    """Every dataclass field of an InvertParam/GaugeParam as plain
    data (sequences listed, exotic values stringified at dump time)."""
    import dataclasses
    if param is None:
        return None
    if not dataclasses.is_dataclass(param):
        return {"repr": repr(param)}
    out = {}
    for f in dataclasses.fields(param):
        v = getattr(param, f.name)
        out[f.name] = list(v) if isinstance(v, tuple) else v
    return out


def _platform_info() -> dict:
    info = {"python": sys.version.split()[0]}
    try:
        import jax
        info["jax"] = jax.__version__
        info["backend"] = jax.default_backend()
        devs = jax.devices()
        info["n_devices"] = len(devs)
        info["device_kind"] = str(getattr(devs[0], "device_kind", "")
                                  or devs[0].platform)
        info["process_index"] = jax.process_index()
    except Exception as e:     # noqa: BLE001 — capture must not crash
        info["error"] = f"{type(e).__name__}: {e}"
    return info


def _metrics_snapshot() -> dict:
    """The registry snapshot with its tuple keys flattened to rows."""
    from . import metrics as omet
    snap = omet.snapshot()
    return {kind: [{"name": name, "labels": dict(labels), "value": v}
                   for (name, labels), v in sorted(snap[kind].items())]
            for kind in snap}


def _default_fields(scope: Optional[dict]) -> dict:
    """The dump set when the capture site passed none: the resident
    device fields of the API context + the scope's source."""
    out = {}
    try:
        from ..interfaces import quda_api as qapi
        for k in ("gauge", "fat", "long"):
            if qapi._ctx.get(k) is not None:
                out[k] = qapi._ctx[k]
    except Exception:          # noqa: BLE001 — partial dump beats none
        pass
    if scope is not None and scope.get("source") is not None:
        out[scope.get("source_name") or "source"] = scope["source"]
    return out


def _dump_fields(bdir: str, fields: dict, cap_mb: float,
                 files: dict) -> dict:
    """Content-hashed .npy dumps in priority order until the size cap
    is spent; capped-out fields keep manifest entries (shape/dtype/
    sha256, omitted='size_cap') so replay can say what is missing."""
    import hashlib

    import numpy as np
    budget = int(cap_mb * 2 ** 20)
    index = {}
    os.makedirs(os.path.join(bdir, "fields"), exist_ok=True)
    for name in sorted(fields, key=lambda n: (_PRIORITY.get(n, 99), n)):
        arr = np.ascontiguousarray(np.asarray(fields[name]))
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "nbytes": int(arr.nbytes),
                 "sha256": hashlib.sha256(arr.tobytes()).hexdigest()}
        if arr.nbytes <= budget:
            rel = f"fields/{name}.npy"
            np.save(os.path.join(bdir, rel), arr)
            budget -= arr.nbytes
            entry["file"] = rel
            files[rel] = {"bytes": os.path.getsize(
                os.path.join(bdir, rel))}
        else:
            entry["omitted"] = "size_cap"
        index[name] = entry
    return index


def _write_bundle(trigger: str, api: str, param, fields, exc, note,
                  scope) -> str:
    global _seq
    from ..utils import config as qconf
    from ..utils import tune as qtune
    from . import flight as ofl
    from . import memory as omem
    slug = re.sub(r"[^A-Za-z0-9_.-]+", "-", trigger)[:48]
    root = bundle_root()
    os.makedirs(root, exist_ok=True)
    # pid in the name + exist_ok=False retry: workers sharing one
    # resource path (the supported fleet setup) capturing in the same
    # wall-clock second must never merge two failures into one
    # corrupted bundle dir
    stamp = time.strftime("%Y%m%d_%H%M%S")
    while True:
        _seq += 1
        bdir = os.path.join(
            root, f"pm_{stamp}_p{os.getpid()}_{_seq:03d}_{slug}")
        try:
            os.makedirs(bdir, exist_ok=False)
            break
        except FileExistsError:
            continue
    files: dict = {}

    flight_tail = ofl.tail()
    if flight_tail or ofl.enabled():
        fpath = os.path.join(bdir, "flight.jsonl")
        with open(fpath, "w") as fh:
            for e in flight_tail:
                fh.write(json.dumps(e, default=_json_default) + "\n")
        files["flight.jsonl"] = {"bytes": os.path.getsize(fpath),
                                 "events": len(flight_tail),
                                 "dropped": ofl.dropped()}
    _write_json(bdir, "metrics.json", _metrics_snapshot(), files)
    _write_json(bdir, "hbm.json", {
        "ledger": omem.ledger(),
        "family_bytes": omem.family_bytes(),
        "high_water": omem.high_water(),
        "device_high_water": omem.device_high_water()}, files)
    _write_json(bdir, "tunecache.json",
                qtune.cache_snapshot(platform_only=True), files)

    if fields is None:
        fields = _default_fields(scope)
    cap_mb = float(qconf.get("QUDA_TPU_POSTMORTEM_MAX_MB", fresh=True))
    field_index = _dump_fields(bdir, fields, cap_mb, files) \
        if fields else {}

    # a load_gauge_quda capture's scope param IS the (rejected) load's
    # GaugeParam — record it as such; solve captures record the
    # RESIDENT gauge's param from the API context
    is_gauge_param = type(param).__name__ == "GaugeParam"
    gauge_param = _param_dict(param) if is_gauge_param else None
    if gauge_param is None:
        try:
            from ..interfaces import quda_api as qapi
            gauge_param = _param_dict(qapi._ctx.get("gauge_param"))
        except Exception:      # noqa: BLE001
            pass

    # request-id correlation: the solve-service ids riding this API
    # call (serve_requests scope).  request_id is the one-grep key for
    # the single-request case; batched captures keep the full list
    rids = current_request_ids()

    # manifest LAST: its presence marks the bundle complete
    manifest = {
        "schema": 1,
        "trigger": trigger,
        "api": api,
        "request_id": rids[0] if len(rids) == 1 else None,
        "request_ids": list(rids),
        "wall_time": time.time(),
        "written": time.strftime("%Y-%m-%d %H:%M:%S"),
        "note": note,
        "exception": (None if exc is None else
                      {"type": type(exc).__name__,
                       "message": str(exc)[:500]}),
        "platform": _platform_info(),
        # raw-string knob snapshot AS OF API ENTRY (scope) — the
        # replay input; resolved values ride along for humans
        "knobs": ((scope or {}).get("knobs_raw")
                  or qconf.snapshot_raw()),
        "knobs_resolved": qconf.snapshot_values(),
        "invert_param": None if is_gauge_param else _param_dict(param),
        "gauge_param": gauge_param,
        "fields": field_index,
        "files": files,
        "flight": {"events": len(flight_tail),
                   "dropped": ofl.dropped(),
                   "enabled": ofl.enabled()},
    }
    with open(os.path.join(bdir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True,
                  default=_json_default)
    return bdir


# -- session artifact indexing (end_quda / bench_suite) ----------------------

def _tree_bytes(path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(path):
        for f in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, f))
            except OSError:
                pass
    return total


def write_artifacts_manifest(artifacts: dict,
                             path: Optional[str] = None) -> \
        Optional[str]:
    """Index every artifact a session flushed — trace, metrics.prom/
    tsv, fleet_report.txt, roofline.tsv, cost_drift.tsv, tune
    profiles, flight.jsonl, postmortem bundles — into ONE
    ``artifacts_manifest.json`` (name -> path + size, plus the knob
    snapshot), so operators and CI collect one file to find
    everything.  ``artifacts`` maps artifact name -> written path.

    Directory: explicit ``path`` (bench_suite --artifacts-dir) >
    resource path > the first artifact's directory.  Nothing to index
    and no explicit path -> None (a bare test session must not drop
    manifests into the cwd)."""
    from ..utils import config as qconf
    arts = {k: v for k, v in (artifacts or {}).items() if v}
    explicit = path is not None
    if path is None:
        path = qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True) or ""
    if not path and arts:
        path = os.path.dirname(next(iter(arts.values()))) or "."
    if not path or (not arts and not _bundles and not explicit):
        return None
    os.makedirs(path, exist_ok=True)

    def _size(p):
        try:
            return os.path.getsize(p)
        except OSError:
            return None

    doc = {
        "schema": 1,
        "generated": time.strftime("%Y-%m-%d %H:%M:%S"),
        "knobs": qconf.snapshot_raw(),
        "artifacts": {name: {"path": p, "bytes": _size(p)}
                      for name, p in sorted(arts.items())},
        "postmortems": [
            {"path": b["path"], "trigger": b["trigger"],
             "api": b["api"],
             "request_ids": b.get("request_ids", []),
             "manifest": os.path.join(b["path"], "manifest.json"),
             "bytes": _tree_bytes(b["path"])}
            for b in _bundles],
        "postmortems_suppressed": _suppressed,
    }
    fpath = os.path.join(path, "artifacts_manifest.json")
    with open(fpath, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True,
                  default=_json_default)
    return fpath


def replay_status(bundle_path: str) -> str:
    """Fleet-report cell: has this bundle been replay-verified?
    Reads the ``replay.json`` obs/replay.py writes into a bundle it
    re-ran; 'no' when no replay has run."""
    try:
        with open(os.path.join(bundle_path, "replay.json")) as fh:
            verdict = json.load(fh).get("verdict", "")
    except (OSError, json.JSONDecodeError):
        return "no"
    if verdict in ("reproduced", "recovered"):
        return f"yes ({verdict})"
    return verdict or "no"
