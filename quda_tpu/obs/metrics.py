"""Serving-grade metrics: a labeled counter/gauge/histogram registry.

Reference behavior: the reference's production accounting lives in its
persistent tunecache + per-kernel profile tsv (lib/tune.cpp:450-610)
and per-solve convergence reporting — counts of what compiled, what was
served warm, and what every solve did.  A serving fleet reads exactly
this before it scales (ROADMAP item 2: "serves its first solve without
a compile/race storm"); this module is the TPU-native home for it.

Activation: ``QUDA_TPU_METRICS=1`` (read by ``init_quda`` via
:func:`maybe_start`) or an explicit :func:`start` (bench_suite's
``--metrics``).  **Off means off** — the trace-module discipline
(obs/trace.py): every recording entry point (:func:`inc`,
:func:`set_gauge`, :func:`observe`, :func:`record_execution`) returns
after one module-global load, no registry object exists, and no device
op is ever added either way, so instrumented call sites are safe in
hot host paths and the compiled solves stay bit-identical (pinned by a
raising-stub test like the tracer's).

Every metric NAME must be registered in obs/schema.py (type + help);
the registry validates at record time, and the schema lint
(tests/test_obs_schema_lint.py) validates every call site statically —
dashboards never break silently.

``end_quda`` exports the session as Prometheus text (``metrics.prom``,
scrapeable after copy/serve) and a flat ``metrics.tsv``, plus the
human-readable fleet report (obs/report.py), under the resource path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from . import schema

# histogram bucket upper bounds in seconds (+Inf is implicit); chosen
# for solve/compile wall times: sub-10ms CI toys through minute-class
# chip compiles
HIST_BUCKETS = (0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0)


def _hist_bounds(name: str) -> tuple:
    """Bucket upper bounds for one histogram.  serve_request_seconds
    honors QUDA_TPU_SERVE_SLO_BUCKETS (comma-separated seconds) so a
    sub-second SLO is not quantized into one default bucket; a
    malformed value warns once and falls back — a typoed knob must
    never take down the recording path."""
    if name != "serve_request_seconds":
        return HIST_BUCKETS
    from ..utils import config as qconf
    raw = str(qconf.get("QUDA_TPU_SERVE_SLO_BUCKETS", fresh=True) or "")
    if not raw.strip():
        return HIST_BUCKETS
    try:
        bounds = tuple(sorted({float(t) for t in raw.split(",")
                               if t.strip()}))
    except ValueError:
        from ..utils import logging as qlog
        qlog.warn_once(
            "serve_slo_buckets",
            f"QUDA_TPU_SERVE_SLO_BUCKETS={raw!r} is not a comma-"
            "separated list of seconds; using the default buckets")
        return HIST_BUCKETS
    return bounds or HIST_BUCKETS

# export file prefix: quda_tpu_solves_total etc.
_PROM_PREFIX = "quda_tpu_"


class _Registry:
    """The live session store.  All methods validate the metric name
    against obs/schema.py — an unregistered name raises the first time
    its code path runs (the runtime half of the schema lint)."""

    def __init__(self, path: str):
        self.path = path
        self.wall0 = time.time()
        self.counters: dict = {}      # (name, labels) -> float
        self.gauges: dict = {}        # (name, labels) -> float
        self.hists: dict = {}         # (name, labels) -> {counts,sum,n}
        self.seen_keys: set = set()   # compile-accounting keys
        self.lock = threading.Lock()

    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted((k, str(v))
                                   for k, v in labels.items())))

    @staticmethod
    def _check(name: str, kind: str):
        m = schema.METRICS.get(name)
        if m is None:
            raise KeyError(
                f"unregistered metric {name!r}; register it in "
                "quda_tpu/obs/schema.py (type + help) — an ad-hoc "
                "name breaks dashboards silently")
        if m["type"] != kind:
            raise TypeError(
                f"metric {name!r} is registered as {m['type']}, "
                f"recorded as {kind}")

    def inc(self, name: str, value: float, labels: dict):
        self._check(name, schema.COUNTER)
        k = self._key(name, labels)
        with self.lock:
            self.counters[k] = self.counters.get(k, 0.0) + float(value)

    def set(self, name: str, value: float, labels: dict):
        self._check(name, schema.GAUGE)
        with self.lock:
            self.gauges[self._key(name, labels)] = float(value)

    def observe(self, name: str, value: float, labels: dict):
        self._check(name, schema.HISTOGRAM)
        k = self._key(name, labels)
        with self.lock:
            h = self.hists.get(k)
            if h is None:
                bounds = _hist_bounds(name)
                h = self.hists[k] = {
                    "counts": [0] * (len(bounds) + 1),
                    "sum": 0.0, "n": 0, "buckets": bounds}
            for i, ub in enumerate(h["buckets"]):
                if value <= ub:
                    h["counts"][i] += 1
                    break
            else:
                h["counts"][-1] += 1
            h["sum"] += float(value)
            h["n"] += 1


_session: Optional[_Registry] = None


def enabled() -> bool:
    return _session is not None


def _metrics_dir() -> str:
    from ..utils import config as qconf
    return qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True) or "."


def start(path: Optional[str] = None) -> _Registry:
    """Open a metrics session (idempotent: an active session and its
    path win, trace.start semantics)."""
    global _session
    if _session is None:
        _session = _Registry(path or _metrics_dir())
    elif path is not None and path != _session.path:
        from ..utils import logging as qlog
        qlog.warningq(
            f"obs.metrics.start({path!r}): a session is already "
            f"active, keeping its artifacts at {_session.path}")
    return _session


def maybe_start() -> Optional[_Registry]:
    """Start a session iff QUDA_TPU_METRICS is set (init_quda hook)."""
    from ..utils import config as qconf
    if qconf.get("QUDA_TPU_METRICS", fresh=True):
        return start()
    return None


def stop(flush_files: bool = True) -> Optional[dict]:
    """Close the session; returns {'prom', 'tsv', 'report'} paths when
    artifacts were written (end_quda hook).  The session is cleared
    even when the flush raises (unwritable resource path): a later
    init/solve cycle must start a FRESH registry, not silently reuse
    the stale counters and seen-compile keys of the failed one."""
    global _session
    if _session is None:
        return None
    try:
        return flush() if flush_files else None
    finally:
        _session = None


# -- recording entry points (one global load when off) ----------------------

def inc(name: str, value: float = 1.0, **labels):
    """Add ``value`` to a labeled counter (no-op when metrics are off)."""
    r = _session
    if r is None:
        return
    r.inc(name, value, labels)


def set_gauge(name: str, value: float, **labels):
    """Set a labeled gauge (no-op when metrics are off)."""
    r = _session
    if r is None:
        return
    r.set(name, value, labels)


def observe(name: str, value: float, **labels):
    """Observe a value into a labeled histogram (no-op when off)."""
    r = _session
    if r is None:
        return
    r.observe(name, value, labels)


def record_execution(api: str, form: str, shape, dtype: str,
                     solver: str, seconds: float) -> bool:
    """Compile/executable-cache accounting for one compute phase.

    The first execution of a distinct (api, operator form, shape,
    dtype, solver) key in this process pays the XLA compile inside its
    wall time — count it as a compile (``compiles_total`` +
    ``compile_seconds`` + a ``compile`` trace event); later executions
    of the same key ran the cached executable (``executions_total``
    only).  Returns True iff this was a first execution."""
    r = _session
    if r is None:
        return False
    key = f"{api}|{form}|{tuple(shape)}|{dtype}|{solver}"
    with r.lock:
        first = key not in r.seen_keys
        r.seen_keys.add(key)
    if first:
        r.inc("compiles_total", 1.0, {"api": api, "form": form})
        r.observe("compile_seconds", seconds, {"api": api})
        from . import trace as otr
        otr.event("compile", cat="metrics", api=api, form=form,
                  shape=list(shape), dtype=dtype, solver=solver,
                  seconds=round(float(seconds), 6))
        # cost-model cross-check capture: the session's drift report
        # (obs/costmodel.py, cost_drift.tsv at end_quda) covers exactly
        # the forms that compiled here
        from . import costmodel as ocost
        ocost.note_compile(api, form, shape, dtype, solver, seconds)
    r.inc("executions_total", 1.0, {"api": api, "form": form})
    return first


def executable_keys() -> set:
    """Snapshot of the (api, form, shape, dtype, solver) keys executed
    this session (the rendered-string form ``record_execution`` keys
    on).  serve/persist.py writes these to the resource path at worker
    shutdown so the NEXT process knows which executables the persisted
    XLA compilation cache already holds."""
    r = _session
    if r is None:
        return set()
    with r.lock:
        return set(r.seen_keys)


def seed_executable_keys(keys) -> int:
    """Pre-seed the compile-accounting key set (serve/persist.py warm
    start): a key seeded here was compiled by a PREVIOUS process whose
    executable the persisted compilation cache serves, so its first
    execution in THIS process must count as a warm execution, not a
    compile — ``compiles_total == 0`` for already-keyed executables is
    the ROADMAP item-2 acceptance instrument.  Returns the number of
    keys newly seeded (0 when no session is active)."""
    r = _session
    if r is None:
        return 0
    with r.lock:
        fresh = {str(k) for k in keys} - r.seen_keys
        r.seen_keys |= fresh
    return len(fresh)


# -- snapshot / export ------------------------------------------------------

def snapshot() -> dict:
    """Host-side copy of the live registry: {'counters', 'gauges',
    'histograms'} keyed by (name, ((label, value), ...)).  Empty dicts
    when no session is active (report renders 'no metrics session')."""
    r = _session
    if r is None:
        return {"counters": {}, "gauges": {}, "histograms": {}}
    with r.lock:
        return {"counters": dict(r.counters),
                "gauges": dict(r.gauges),
                "histograms": {k: {"counts": list(h["counts"]),
                                   "sum": h["sum"], "n": h["n"],
                                   "buckets": tuple(
                                       h.get("buckets", HIST_BUCKETS))}
                               for k, h in r.hists.items()}}


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _num(v: float) -> str:
    """Full-precision sample rendering: '%g' truncates to 6 significant
    digits, which corrupts any counter/gauge >= 1e6 (a session easily
    accumulates more solver iterations or ledger bytes than that, and a
    rounded counter can read as zero/negative under rate()).  Integral
    values print as integers, others as repr (round-trip exact)."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def _prom_labels(labels: tuple, extra: str = "") -> str:
    parts = [f'{k}="{_esc(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(snap: Optional[dict] = None) -> str:
    """The session as Prometheus text-format exposition."""
    snap = snap or snapshot()
    by_name: dict = {}
    for kind in ("counters", "gauges", "histograms"):
        for (name, labels), v in snap[kind].items():
            by_name.setdefault(name, []).append((labels, v))
    lines = []
    for name in sorted(by_name):
        meta = schema.METRICS[name]
        full = _PROM_PREFIX + name
        lines.append(f"# HELP {full} {meta['help']}")
        lines.append(f"# TYPE {full} {meta['type']}")
        for labels, v in sorted(by_name[name]):
            if meta["type"] == schema.HISTOGRAM:
                cum = 0
                for i, ub in enumerate(v.get("buckets", HIST_BUCKETS)):
                    cum += v["counts"][i]
                    le = f'le="{ub}"'
                    lines.append(
                        f"{full}_bucket{_prom_labels(labels, le)} {cum}")
                cum += v["counts"][-1]
                inf = 'le="+Inf"'
                lines.append(
                    f"{full}_bucket{_prom_labels(labels, inf)} {cum}")
                lines.append(f"{full}_sum{_prom_labels(labels)}"
                             f" {v['sum']:.6f}")
                lines.append(f"{full}_count{_prom_labels(labels)} {cum}")
            else:
                lines.append(f"{full}{_prom_labels(labels)} {_num(v)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_tsv(snap: Optional[dict] = None) -> str:
    """Flat name/labels/value tsv (the profile_N.tsv sibling)."""
    snap = snap or snapshot()
    rows = ["metric\ttype\tlabels\tvalue"]
    for kind, tname in (("counters", schema.COUNTER),
                        ("gauges", schema.GAUGE)):
        for (name, labels), v in sorted(snap[kind].items()):
            lab = ",".join(f"{k}={v2}" for k, v2 in labels)
            rows.append(f"{name}\t{tname}\t{lab}\t{_num(v)}")
    for (name, labels), h in sorted(snap["histograms"].items()):
        lab = ",".join(f"{k}={v2}" for k, v2 in labels)
        rows.append(f"{name}\thistogram\t{lab}\t"
                    f"n={h['n']},sum={h['sum']:.6f}")
    return "\n".join(rows) + "\n"


def flush() -> Optional[dict]:
    """Write metrics.prom + metrics.tsv + the fleet report under the
    session path; the session stays active (incremental overwrites)."""
    r = _session
    if r is None:
        return None
    os.makedirs(r.path, exist_ok=True)
    snap = snapshot()
    prom_path = os.path.join(r.path, "metrics.prom")
    tsv_path = os.path.join(r.path, "metrics.tsv")
    with open(prom_path, "w") as fh:
        fh.write(render_prometheus(snap))
    with open(tsv_path, "w") as fh:
        fh.write(render_tsv(snap))
    from . import report as orep
    report_path = orep.save(os.path.join(r.path, "fleet_report.txt"),
                            snap=snap)
    return {"prom": prom_path, "tsv": tsv_path, "report": report_path}
