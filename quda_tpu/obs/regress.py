"""Perf-regression gate over the committed bench history.

The missing half of "measurement is part of the product" (ROADMAP open
item 5): every bench row the repo ever committed is a baseline
candidate (obs/history.py), and every new run is diffed against the
best-credible baseline per (metric, unit, platform, lattice, form,
mesh) series.  A current row more than ``tol`` below its throughput
baseline — or a solver whose iteration count inflates past the same
tolerance (a convergence regression hides easily inside a wall-time
budget) — fails the gate LOUDLY: a rejection-style JSON row on stdout
(the same grep surface as ``bench.record_row`` rejections) and a
nonzero exit.  The regression discipline of "A Framework for Lattice
QCD Calculations on GPUs" (arXiv:1408.5925), institutionalized.

Entry points:
* ``compare(current_rows, hist, ...)`` — the pure engine (tier-1 safe).
* ``main(argv)``  — the CLI ``bench_suite.py --compare`` delegates to;
  also runnable directly: ``python -m quda_tpu.obs.regress --latest``.

Every invocation writes ``trends.tsv`` (under the resource path, else
the history dir) so PERF.md rounds cite generated trend tables instead
of hand-copied numbers.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional, Tuple

from . import history as qhist


def _conf(name):
    from ..utils import config as qconf
    return qconf.get(name, fresh=True)


def default_history_dir() -> str:
    """QUDA_TPU_BENCH_HISTORY_DIR, else the repo root (where the driver
    commits BENCH_rNN.json / MULTICHIP_rNN.json)."""
    d = _conf("QUDA_TPU_BENCH_HISTORY_DIR")
    if d:
        return d
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def compare(current_rows: List[dict], hist: qhist.History,
            tol: Optional[float] = None,
            iters_tol: Optional[float] = None) -> Tuple[int, List[dict]]:
    """Diff canonical current rows against the history's best-credible
    baselines.  Returns (n_failures, verdicts); each verdict dict
    carries ``compare`` in {'ok', 'improved', 'regression',
    'iteration_inflation', 'slowdown', 'no_baseline'} and failing ones
    also carry a ``rejected`` reason string (record_row style)."""
    tol = float(_conf("QUDA_TPU_BENCH_COMPARE_TOL")
                if tol is None else tol)
    iters_tol = float(_conf("QUDA_TPU_BENCH_COMPARE_ITERS_TOL")
                      if iters_tol is None else iters_tol)
    verdicts: List[dict] = []
    failures = 0
    for row in current_rows:
        key = qhist.series_key(row)
        base = hist.best(key)
        v = {"compare": "ok", "metric": row["metric"],
             "unit": row["unit"], "platform": row["platform"],
             "lattice": row.get("lattice"), "form": row.get("form"),
             "mesh": row.get("mesh"), "current": row["value"]}
        if base is None:
            v["compare"] = "no_baseline"
            verdicts.append(v)
            continue
        bv = base["value"]
        v["baseline"] = bv
        v["baseline_source"] = base.get("source")
        v["ratio"] = round(row["value"] / bv, 4) if bv else None
        if row["unit"] in qhist.TRENDED_ONLY_UNITS:
            # comms volume / cost-drift ratio: a trend line the first
            # chip window starts, never a gate (the drift LINT owns
            # pass/fail for the ratio; ici bytes change with the
            # decomposition, not the code's speed)
            v["compare"] = "trended"
        elif row["unit"] in qhist.THROUGHPUT_UNITS:
            lim = bv * (1.0 - tol)
            if row["value"] < lim:
                v["compare"] = "regression"
                v["tol"] = tol
                v["rejected"] = (
                    f"throughput regression: {row['metric']} "
                    f"[{row['unit']}] {row['value']:g} is "
                    f"{(1 - row['value'] / bv) * 100:.1f}% below the "
                    f"best-credible baseline {bv:g} "
                    f"({base.get('source')}); tolerance {tol:.0%}")
                failures += 1
            elif row["value"] > bv:
                v["compare"] = "improved"
        elif row["unit"] == "iters":
            lim = bv * (1.0 + iters_tol)
            if row["value"] > lim:
                v["compare"] = "iteration_inflation"
                v["tol"] = iters_tol
                v["rejected"] = (
                    f"solver-iteration inflation: {row['metric']} took "
                    f"{row['value']:g} iterations vs the baseline "
                    f"{bv:g} ({base.get('source')}) — "
                    f"{(row['value'] / bv - 1) * 100:.1f}% more; "
                    f"tolerance {iters_tol:.0%}")
                failures += 1
            elif row["value"] < bv:
                v["compare"] = "improved"
        else:
            # secs-family: slower-than-baseline is a slowdown, reported
            # but NOT failing — wall-times on shared CI hosts are too
            # noisy to gate on, and the throughput/iters gates already
            # cover the attributable regressions
            if row["value"] > bv * (1.0 + tol):
                v["compare"] = "slowdown"
            elif row["value"] < bv:
                v["compare"] = "improved"
        verdicts.append(v)
    return failures, verdicts


def canonicalize_recorded(recorded, stats: Optional[dict] = None
                          ) -> List[dict]:
    """(suite, row) pairs from bench.recorded_rows() -> canonical rows
    for compare()."""
    out: List[dict] = []
    for suite, row in recorded:
        out.extend(qhist.rows_from_suite_row(
            dict(row, suite=suite), source="current", stats=stats))
    return out


def write_trends(hist: qhist.History, current: List[dict],
                 path: Optional[str] = None) -> Optional[str]:
    """trends.tsv: the citable trend table.  Destination: explicit
    ``path`` (--trends / bench_suite --artifacts-dir) > the resource
    path > an EXPLICITLY configured QUDA_TPU_BENCH_HISTORY_DIR.  With
    none of those, returns None without writing — the history-dir
    fallback is the repo root, and a bare compare run must not drop
    artifacts into the working tree (the write_artifacts_manifest
    contract)."""
    if not path:
        base = (_conf("QUDA_TPU_RESOURCE_PATH")
                or _conf("QUDA_TPU_BENCH_HISTORY_DIR"))
        if not base:
            return None
        path = os.path.join(base, "trends.tsv")
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        fh.write(qhist.trend_table(hist, current))
    return path


def run_compare(current_rows: List[dict], history_dir: str,
                tol: Optional[float] = None,
                iters_tol: Optional[float] = None,
                trends_path: Optional[str] = None,
                exclude_rounds=(), log=None,
                hist: Optional[qhist.History] = None) -> int:
    """The whole gate: load history (unless an already-built ``hist``
    is passed), diff, print verdict JSON rows (failures carry
    ``rejected``), write trends.tsv, return the exit code (number of
    failing rows, capped at process-exit range)."""
    if log is None:
        log = lambda s: print(s, flush=True)
    if hist is None:
        hist = qhist.load_history(history_dir,
                                  exclude_rounds=exclude_rounds)
    failures, verdicts = compare(current_rows, hist, tol, iters_tol)
    for v in verdicts:
        if v["compare"] not in ("ok",):      # quiet on unremarkable rows
            log(json.dumps(dict({"suite": "compare"}, **v)))
    trends = write_trends(hist, current_rows, trends_path)
    summary = {"suite": "compare", "history_files": len(hist.files),
               "series": len(hist.series),
               "current_rows": len(current_rows),
               "failures": failures, "trends": trends,
               "history_stats": hist.stats}
    log(json.dumps(summary))
    return min(failures, 120)


def pop_opt(argv: List[str], flag: str, default=None):
    """Pop ``--flag VALUE`` or ``--flag=VALUE`` from ``argv`` in place;
    ``default`` when absent.  The ONE value-flag parser for this CLI
    and bench_suite's passthrough — a flag with no value raises
    ValueError instead of swallowing the next flag (or crashing)."""
    if flag in argv:
        i = argv.index(flag)
        argv.pop(i)
        if i >= len(argv) or argv[i].startswith("--"):
            raise ValueError(f"{flag} needs a value")
        return argv.pop(i)
    for a in argv:
        if a.startswith(flag + "="):
            argv.remove(a)
            return a.split("=", 1)[1]
    return default


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``python -m quda_tpu.obs.regress [--history DIR]
    [--current FILE | --latest] [--tol X] [--iters-tol Y]
    [--trends PATH]``.

    --current FILE: canonical rows come from FILE (a driver wrapper, a
      bare bench record, or a bench_suite JSON-lines stream).
    --latest: the newest committed round plays "current" and is diffed
      against the baseline built from every OTHER round — the dry mode
      that gates already-committed history with zero measurements.
    """
    argv = list(sys.argv[1:] if argv is None else argv)

    def _usage_error(msg: str) -> int:
        print(json.dumps({"suite": "compare", "error": msg}),
              flush=True)
        return 2

    try:
        history_dir = pop_opt(argv, "--history") or default_history_dir()
        current_file = pop_opt(argv, "--current")
        tol = pop_opt(argv, "--tol")
        iters_tol = pop_opt(argv, "--iters-tol")
        trends_path = pop_opt(argv, "--trends")
    except ValueError as e:
        return _usage_error(str(e))
    latest = "--latest" in argv
    if latest:
        argv.remove("--latest")
    if argv:
        return _usage_error(f"unknown arguments {argv}")
    tol = float(tol) if tol is not None else None
    iters_tol = float(iters_tol) if iters_tol is not None else None

    hist = None
    if current_file:
        current_rows, stats = qhist.parse_file(current_file)
        if stats.get("unparseable"):
            return _usage_error(f"cannot parse {current_file}")
    elif latest:
        full = qhist.load_history(history_dir)
        mr = full.max_round()
        if mr is None:
            return _usage_error(
                f"no round-numbered history under {history_dir}")
        current_rows = [r for rows in full.series.values() for r in rows
                        if r.get("round") == mr and not r.get("carried")]
        hist = full.without_round(mr)
    else:
        return _usage_error("need --current FILE or --latest")
    return run_compare(current_rows, history_dir, tol, iters_tol,
                       trends_path, hist=hist)


if __name__ == "__main__":
    sys.exit(main())
