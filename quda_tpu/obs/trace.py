"""Span tracer: nestable named spans + instant events, chrome-trace out.

Reference behavior: the reference's profiling surface is pushProfile
RAII spans (include/timer.h:243) + the tunecache profiler tsv
(lib/tune.cpp:450-474).  This module adds the modern export formats on
top of the same span discipline: a chrome-trace/perfetto JSON
(``trace.json``) and a flat JSONL event stream
(``trace_events.jsonl``), written under QUDA_TPU_TRACE_PATH (default:
the resource path) when tracing is active.

Activation: ``QUDA_TPU_TRACE=1`` (read by init_quda via
``maybe_start``) or an explicit ``start()`` (the bench harness's
``--trace``).  **Off means off**: ``span()`` returns a module-level
no-op singleton whose __enter__/__exit__ do nothing and ``event()``
returns after one global load — no buffers, no clocks, no allocation —
so instrumented code is safe to leave in hot host paths and around jit
boundaries.  (Spans time HOST regions; device work inside a span is
attributed to it only up to XLA's async dispatch, so callers that need
device-accurate spans must pass a fetched/blocked result the way the
bench harness does.)

When jax.profiler.TraceAnnotation is available each span also opens a
matching annotation, so quda_tpu spans show up inside a jax/XLA
profiler capture (StartTraceRegion analog).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

# the flight recorder taps this module's event stream (obs/flight.py
# imports nothing from here at module level, so the edge is acyclic)
from . import flight as _flight


class _NoopSpan:
    """Zero-overhead disabled span (the QUDA_DO_NOT_PROFILE analog)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Session:
    def __init__(self, path: str, prefix: str, max_events: int):
        self.path = path
        self.prefix = prefix
        self.max_events = max_events
        self.t0 = time.perf_counter()
        self.wall0 = time.time()
        self.chrome: list = []     # chrome traceEvents dicts
        self.jsonl: list = []      # flat event-stream dicts
        self.dropped = 0
        self.lock = threading.Lock()
        self.depth: dict = {}      # thread ident -> current span depth
        self.device_pids: dict = {}  # device label -> chrome pid
        try:
            import jax.profiler
            self.annotation_cls = getattr(jax.profiler, "TraceAnnotation",
                                          None)
        except Exception:
            self.annotation_cls = None


_session: Optional[_Session] = None


def enabled() -> bool:
    return _session is not None


def _trace_dir() -> str:
    from ..utils import config as qconf
    return (qconf.get("QUDA_TPU_TRACE_PATH", fresh=True)
            or qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
            or ".")


def start(path: Optional[str] = None, prefix: str = "trace") -> _Session:
    """Open a trace session (idempotent: an active session is kept —
    and its path/prefix WIN; explicit arguments that conflict with the
    active session are discarded with a warning, so a driver that
    init_quda'd with QUDA_TPU_TRACE=1 and then asks for bench_trace
    artifacts learns where its events actually went).
    Artifacts land in ``path`` (default: QUDA_TPU_TRACE_PATH, else the
    resource path, else cwd) as <prefix>.json / <prefix>_events.jsonl."""
    global _session
    if _session is None:
        from ..utils import config as qconf
        _session = _Session(path or _trace_dir(), prefix,
                            qconf.get("QUDA_TPU_TRACE_EVENTS_MAX",
                                      fresh=True))
    elif ((path is not None and path != _session.path)
          or prefix != _session.prefix):
        from ..utils import logging as qlog
        qlog.warningq(
            f"obs.trace.start({path!r}, prefix={prefix!r}): a session "
            f"is already active, keeping its artifacts at "
            f"{_session.path}/{_session.prefix}.json")
    return _session


def maybe_start() -> Optional[_Session]:
    """Start a session iff QUDA_TPU_TRACE is set (init_quda hook)."""
    from ..utils import config as qconf
    if qconf.get("QUDA_TPU_TRACE", fresh=True):
        return start()
    return None


def stop(flush_files: bool = True) -> Optional[dict]:
    """Close the session; returns {'chrome': path, 'jsonl': path} when
    artifacts were written (end_quda hook)."""
    global _session
    if _session is None:
        return None
    paths = flush() if flush_files else None
    _session = None
    return paths


def _now_us(s: _Session) -> float:
    return (time.perf_counter() - s.t0) * 1e6


def _push(s: _Session, chrome_ev: dict, jsonl_ev: Optional[dict]):
    with s.lock:
        if len(s.chrome) >= s.max_events:
            s.dropped += 1
            return
        s.chrome.append(chrome_ev)
        if jsonl_ev is not None:
            s.jsonl.append(jsonl_ev)


def _device_pid(s: _Session, label: str, desc: str) -> int:
    """Chrome pid for one device track; first use emits the perfetto
    process metadata naming it (mesh coordinates in the track name) —
    metadata rows bypass the event cap (bounded by device count) and
    pid 0 stays the host track."""
    with s.lock:
        pid = s.device_pids.get(label)
        if pid is not None:
            return pid
        if not s.device_pids:
            s.chrome.append({"name": "process_name", "ph": "M",
                             "pid": 0, "tid": 0,
                             "args": {"name": "host (api spans)"}})
        pid = 1 + len(s.device_pids)
        s.device_pids[label] = pid
        s.chrome.append({"name": "process_name", "ph": "M", "pid": pid,
                         "tid": 0, "args": {"name": desc}})
        s.chrome.append({"name": "process_sort_index", "ph": "M",
                         "pid": pid, "tid": 0,
                         "args": {"sort_index": pid}})
        return pid


def _mirror_span_per_device(s: _Session, name: str, cat: str, ts: float,
                            dur: float, mesh, args: dict) -> int:
    """One chrome span row per LOCAL device of ``mesh``, on that
    device's own pid track (mesh coordinates in the track name), so
    perfetto shows a sharded solve as parallel device rows instead of
    one collapsed host track.  The duration is the host-measured span
    (per-device device timelines need a profiler capture); what the
    rows add is the device/mesh-coordinate attribution."""
    import numpy as np
    try:
        import jax
        my_proc = jax.process_index()
    except Exception:
        return 0
    n = 0
    # partitioned axes only in the track names (a size-1 axis carries
    # no placement information); all axes when nothing is partitioned
    parted = [ax for ax in mesh.axis_names if mesh.shape[ax] > 1] \
        or list(mesh.axis_names)
    for idx, dev in np.ndenumerate(mesh.devices):
        if getattr(dev, "process_index", 0) != my_proc:
            continue
        label = f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"
        coords = ",".join(f"{ax}={i}" for ax, i
                          in zip(mesh.axis_names, idx) if ax in parted)
        pid = _device_pid(s, label, f"device {label} [{coords}]")
        _push(s, {"name": name, "cat": cat, "ph": "X",
                  "ts": round(ts, 3), "dur": round(dur, 3),
                  "pid": pid, "tid": 0,
                  "args": dict(args, device=label, mesh_coords=coords)},
              None)
        n += 1
    return n


class _Span:
    __slots__ = ("name", "cat", "args", "_ts", "_ann", "_depth", "_tid",
                 "_mesh")

    def __init__(self, name: str, cat: str, args: dict, mesh=None):
        self.name = name
        self.cat = cat
        self.args = args
        self._ann = None
        self._ts = 0.0
        self._depth = 0
        self._tid = 0
        self._mesh = mesh

    def __enter__(self):
        s = _session
        if s is None:            # stopped between creation and entry
            return self
        self._tid = threading.get_ident()
        self._depth = s.depth.get(self._tid, 0) + 1
        s.depth[self._tid] = self._depth
        if s.annotation_cls is not None:
            try:
                self._ann = s.annotation_cls(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._ts = _now_us(s)
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        s = _session
        if s is None or self._depth == 0:
            return False
        dur = _now_us(s) - self._ts
        s.depth[self._tid] = self._depth - 1
        args = dict(self.args, depth=self._depth)
        n_dev = 0
        if self._mesh is not None:
            n_dev = _mirror_span_per_device(s, self.name, self.cat,
                                            self._ts, dur, self._mesh,
                                            dict(self.args))
        jsonl = {"kind": "span", "name": self.name, "cat": self.cat,
                 "ts_us": round(self._ts, 3), "dur_us": round(dur, 3),
                 "depth": self._depth, **self.args}
        if n_dev:
            jsonl["devices"] = n_dev
        _push(s, {"name": self.name, "cat": self.cat, "ph": "X",
                  "ts": round(self._ts, 3), "dur": round(dur, 3),
                  "pid": 0, "tid": 0, "args": args}, jsonl)
        return False


def span(name: str, cat: str = "api", mesh=None, **args):
    """A nestable named span; the module no-op singleton when tracing is
    off (so call sites stay branch-cheap on the disabled path).  With
    ``mesh`` (a jax.sharding.Mesh) the span is additionally mirrored
    onto one chrome track per local mesh device, mesh coordinates in
    the track names — a sharded solve renders as parallel device rows
    in perfetto instead of one collapsed host track."""
    if _session is None:
        return _NOOP
    return _Span(name, cat, args, mesh=mesh)


def event(name: str, cat: str = "event", **fields):
    """Instant event into both the chrome trace and the JSONL stream.

    Every call here also lands in the flight-recorder ring when
    QUDA_TPU_FLIGHT is on — the recorder rides the SAME emission sites
    (tuner decisions, escalation rungs, sentinel codes, gauge loads/
    rejections, exchange-policy picks) independently of whether a
    trace session is active, so the black box costs zero new
    instrumentation.  Both disabled paths stay one-global-load
    no-ops."""
    fl = _flight._session
    if fl is not None:
        fl.append(name, cat, fields)
    s = _session
    if s is None:
        return
    ts = _now_us(s)
    _push(s, {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": round(ts, 3), "pid": 0, "tid": 0, "args": fields},
          {"kind": "event", "name": name, "cat": cat,
           "ts_us": round(ts, 3), **fields})


def flush() -> Optional[dict]:
    """Write the chrome-trace JSON + JSONL stream; returns their paths.
    The session stays active (incremental flushes overwrite)."""
    s = _session
    if s is None:
        return None
    os.makedirs(s.path, exist_ok=True)
    chrome_path = os.path.join(s.path, f"{s.prefix}.json")
    jsonl_path = os.path.join(s.path, f"{s.prefix}_events.jsonl")
    with s.lock:
        doc = {"traceEvents": list(s.chrome),
               "displayTimeUnit": "ms",
               "otherData": {"source": "quda_tpu.obs.trace",
                             "wall_start": s.wall0,
                             "dropped_events": s.dropped}}
        lines = [json.dumps(e) for e in s.jsonl]
    with open(chrome_path, "w") as fh:
        json.dump(doc, fh)
    with open(jsonl_path, "w") as fh:
        fh.write("\n".join(lines) + ("\n" if lines else ""))
    return {"chrome": chrome_path, "jsonl": jsonl_path}


# -- TimeProfile-bridged helpers for the API layer --------------------------

@contextmanager
def api_span(name: str, **args):
    """Top-level API span: a pushProfile interval (category 'total' on
    the named TimeProfile) + a trace span — one context for every
    interface entry point (invert_quda, eigensolve_quda, ...).  API
    entries/exits are also marked into the flight-recorder ring
    (host-side, no-op when QUDA_TPU_FLIGHT is off) so a postmortem
    bundle's tail shows what the worker was serving when it failed."""
    from ..utils.timer import push_profile
    _flight.record("api_enter", cat="api", api=name, **args)
    try:
        with push_profile(name):
            with span(name, cat="api", **args):
                yield
    finally:
        _flight.record("api_exit", cat="api", api=name)


@contextmanager
def phase(category: str, profile: Optional[str] = None, mesh=None,
          **args):
    """One category interval on ``profile``'s TimeProfile + a trace span
    — the setup/compute/comms/epilogue breakdown inside an api_span.
    ``mesh`` mirrors the span onto per-device chrome tracks (see
    :func:`span`)."""
    from ..utils import timer as qtimer
    prof = (qtimer.get_profile(profile)
            if profile is not None and qtimer._profiling_enabled()
            else None)
    if prof is not None:
        prof.start(category)
    try:
        with span(category, cat=category, mesh=mesh, **args):
            yield
    finally:
        if prof is not None:
            prof.stop(category)
