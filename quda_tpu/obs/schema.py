"""Canonical observability schema: every trace-event and metric name.

Dashboards, scrape configs and trend queries key on NAMES.  A renamed
or ad-hoc event/metric breaks them silently — the exact failure mode
the env-knob registry (utils/config.py) exists to kill for knobs.  This
module is the same discipline for the telemetry surface:

* ``TRACE_EVENTS`` — every instant-event name the package may emit into
  the JSONL/chrome stream (obs/trace.event), with the category it
  belongs to and a one-line meaning;
* ``METRICS``      — every metric the registry (obs/metrics.py) may
  record, with its type (counter | gauge | histogram) and help string
  (exported verbatim into the Prometheus ``# HELP`` lines).

``tests/test_obs_schema_lint.py`` AST-harvests every emission site in
the package and asserts BOTH directions: no emitted name missing here,
and no registered name that nothing emits (schema rot).  The metrics
registry additionally validates at record time, so an unregistered
name fails the first time its code path runs even outside CI.
"""

from __future__ import annotations

# -- trace events (obs/trace.event instant events) --------------------------

TRACE_EVENTS: dict[str, dict] = {
    # convergence recording (obs/convergence.py)
    "residual": {"cat": "residual",
                 "doc": "per-iteration solver residual (headline lane)"},
    "residual_lane": {"cat": "residual",
                      "doc": "per-RHS/per-shift lane residual"},
    # roofline attribution (obs/roofline.py)
    "roofline": {"cat": "roofline",
                 "doc": "one achieved-GFLOPS/BW attribution row"},
    # bench harness (bench.py record_row)
    "bench_row": {"cat": "bench", "doc": "gate-passing bench row"},
    "bench_row_rejected": {"cat": "bench",
                           "doc": "bench row refused by gate_row"},
    # autotuner (utils/tune.py)
    "tune_cached": {"cat": "tune", "doc": "race served from the cache"},
    "tune_candidate": {"cat": "tune", "doc": "one candidate timing"},
    "tune_candidate_failed": {"cat": "tune",
                              "doc": "candidate raised mid-race"},
    "tune_winner": {"cat": "tune", "doc": "race winner cached"},
    "tune_race_all_failed": {"cat": "tune",
                             "doc": "every candidate raised; static "
                                    "default served uncached"},
    "tune_cache_invalidated": {"cat": "tune",
                               "doc": "stale-schema entries dropped at "
                                      "load"},
    "tune_cache_loaded": {"cat": "tune",
                          "doc": "warm-start load stats (init_quda)"},
    # solve supervision (quda_tpu/robust + interfaces/quda_api)
    "solve_retry": {"cat": "robust",
                    "doc": "escalation-ladder rung transition"},
    "solve_degraded": {"cat": "robust",
                       "doc": "solve served from a fallback rung"},
    "breakdown_detected": {"cat": "robust",
                           "doc": "in-loop breakdown sentinel tripped"},
    "verify_mismatch": {"cat": "robust",
                        "doc": "claimed convergence failed the "
                               "recomputed-residual check"},
    "gauge_rejected": {"cat": "robust",
                       "doc": "non-finite gauge refused at load"},
    "gauge_unitarity": {"cat": "robust",
                        "doc": "unitarity screen exceeded tolerance"},
    "fault_injected": {"cat": "robust",
                       "doc": "QUDA_TPU_FAULT arm fired (drill)"},
    # ICI comms ledger (obs/comms.py)
    "ici_exchange": {"cat": "comms",
                     "doc": "one halo-exchange seam recorded into the "
                            "ledger (per trace, bytes from the traced "
                            "slab shapes)"},
    "ici_solve": {"cat": "comms",
                  "doc": "per-solve ICI attribution row (ledger model "
                         "x measured applies, vs nominal link BW)"},
    # cost-model cross-check (obs/costmodel.py)
    "cost_drift": {"cat": "costmodel",
                   "doc": "one KERNEL_MODELS drift verdict (analytic "
                          "vs XLA reference flops + footprint floor)"},
    # serving-grade accounting (obs/metrics.py / obs/memory.py)
    "compile": {"cat": "metrics",
                "doc": "first execution of a (api, form, shape, dtype, "
                       "solver) key — compile time included in seconds"},
    "hbm_field_tracked": {"cat": "memory",
                          "doc": "resident field (re)registered in the "
                                 "HBM ledger"},
    "hbm_field_released": {"cat": "memory",
                           "doc": "resident field freed from the HBM "
                                  "ledger"},
    # solve service (quda_tpu/serve)
    "serve_batch": {"cat": "serve",
                    "doc": "one coalesced batch executed by the solve-"
                           "service worker (gauge, size, route, queue "
                           "depth at collection)"},
    "serve_gauge_evicted": {"cat": "serve",
                            "doc": "residency manager evicted an LRU "
                                   "gauge to fit the HBM budget"},
    "serve_availability": {"cat": "serve",
                           "doc": "a request finished degraded/"
                                  "unverified/failed — the availability "
                                  "event a fleet pages on instead of a "
                                  "stack trace"},
    "serve_warm_start": {"cat": "serve",
                         "doc": "worker warm start: persisted "
                                "compilation-cache dir + executable-key "
                                "index load stats"},
    # live telemetry plane (obs/live.py)
    "live_started": {"cat": "live",
                     "doc": "telemetry HTTP server bound (port + "
                            "flusher interval) — the scrape plane is "
                            "answering while the worker drains"},
    "live_flush": {"cat": "live",
                   "doc": "one periodic artifact flush window "
                          "completed (QUDA_TPU_METRICS_FLUSH_SEC): "
                          "metrics/fleet/flight/roofline rewritten "
                          "under the resource path"},
    # failure capture (obs/postmortem.py / obs/flight.py)
    "postmortem_written": {"cat": "postmortem",
                           "doc": "one failure-capture bundle written "
                                  "under the postmortem path (trigger "
                                  "+ api + bundle dir)"},
    "flight_dropped": {"cat": "flight",
                       "doc": "the flight-recorder ring wrapped: "
                              "oldest events were dropped (count "
                              "reported at session stop)"},
}

# -- metrics (obs/metrics.py registry) --------------------------------------

COUNTER, GAUGE, HISTOGRAM = "counter", "gauge", "histogram"

METRICS: dict[str, dict] = {
    # fleet solve accounting (interfaces/quda_api._solve_supervision;
    # under 'escalate' every ladder ATTEMPT counts — retries are visible
    # as extra attempts next to solve_retries_total)
    "solves_total": {
        "type": COUNTER,
        "help": "API solve attempts by api/family/status"},
    "solve_iterations_total": {
        "type": COUNTER,
        "help": "solver iterations executed, by api/family"},
    "solve_seconds": {
        "type": HISTOGRAM,
        "help": "wall seconds per API solve attempt, by api/family"},
    "eigensolves_total": {
        "type": COUNTER,
        "help": "eigensolve_quda calls by family/eig_type"},
    # compile / executable-cache accounting
    "compiles_total": {
        "type": COUNTER,
        "help": "first executions (compile included) per distinct "
                "(api, operator form, shape, dtype, solver) key, "
                "by api/form"},
    "compile_seconds": {
        "type": HISTOGRAM,
        "help": "first-execution wall seconds (compile + run), by api"},
    "executions_total": {
        "type": COUNTER,
        "help": "compute-phase executions per api/form (warm "
                "executable after the first)"},
    # tuner warm-cache accounting (utils/tune.py)
    "tune_cache_hits_total": {
        "type": COUNTER,
        "help": "tune() decisions served from the warm cache, by kernel"},
    "tune_cache_misses_total": {
        "type": COUNTER,
        "help": "tune() keys not in the warm cache, by kernel"},
    "tune_races_total": {
        "type": COUNTER,
        "help": "candidate races actually timed, by kernel"},
    "tune_race_failures_total": {
        "type": COUNTER,
        "help": "races whose every candidate raised (static default "
                "served), by kernel"},
    "tune_cache_entries": {
        "type": GAUGE,
        "help": "persistent tunecache entries at warm start, by scope "
                "(total | usable_here | stale_dropped)"},
    # robust subsystem (robust/escalate.py + _solve_supervision)
    "solve_retries_total": {
        "type": COUNTER,
        "help": "escalation-ladder rung transitions, by api/reason"},
    "solve_degraded_total": {
        "type": COUNTER,
        "help": "solves served from a fallback rung (or best-effort "
                "after ladder exhaustion), by api"},
    "breakdowns_total": {
        "type": COUNTER,
        "help": "breakdown-sentinel exits, by api/reason"},
    # HBM field ledger (obs/memory.py)
    "hbm_field_bytes": {
        "type": GAUGE,
        "help": "resident bytes of one registered field, by family/field"},
    "hbm_family_bytes": {
        "type": GAUGE,
        "help": "resident bytes per field family"},
    "hbm_family_high_water_bytes": {
        "type": GAUGE,
        "help": "session high-water resident bytes per field family"},
    "hbm_device_bytes_in_use": {
        "type": GAUGE,
        "help": "backend bytes_in_use per local device (last sample)"},
    "hbm_device_high_water_bytes": {
        "type": GAUGE,
        "help": "session high-water bytes_in_use per local device"},
    # VMEM budget audit (obs/memory.py vs QUDA_TPU_PALLAS_VMEM_MB*)
    "vmem_budget_bytes": {
        "type": GAUGE,
        "help": "configured single-buffer pallas VMEM budget, by knob"},
    "vmem_block_bytes": {
        "type": GAUGE,
        "help": "selected z-block working-set bytes (last _pick_bz "
                "decision), by knob"},
    # ICI comms ledger (obs/comms.py)
    "ici_bytes_total": {
        "type": COUNTER,
        "help": "interconnect bytes attributed to solves (halo model x "
                "applies) and split-grid replications, by axis/policy"},
    # MG setup attribution (mg/mg.py _setup phase breakdown)
    "mg_setup_phase_seconds_total": {
        "type": COUNTER,
        "help": "MG setup wall seconds per hierarchy level and phase "
                "(null_vectors | transfer_build | coarse_probe), by "
                "level/phase"},
    "mg_setup_seconds_total": {
        "type": COUNTER,
        "help": "total MG setup wall seconds per hierarchy build, by "
                "levels"},
    # failure capture (obs/postmortem.py)
    "postmortems_total": {
        "type": COUNTER,
        "help": "postmortem bundles captured, by trigger (breakdown:*, "
                "verify_mismatch, construct_error:*, ladder_exhausted:"
                "*, gauge_rejected, exception:*; 'suppressed' counts "
                "captures past the per-session bundle cap)"},
    # solve service (quda_tpu/serve)
    "serve_requests_total": {
        "type": COUNTER,
        "help": "solve-service requests completed, by family/status "
                "(status is the supervised solve_status, or 'failed' "
                "for requests whose execution raised)"},
    "serve_batches_total": {
        "type": COUNTER,
        "help": "coalesced MRHS batches executed by the solve-service "
                "worker, by batch size — the batch-size histogram of "
                "the fleet report's Service section"},
    "serve_request_seconds": {
        "type": HISTOGRAM,
        "help": "wall seconds from request submission to result "
                "delivery (queue wait + batch solve), by family — the "
                "solve_seconds SLO surface of the Service section"},
    "serve_queue_depth": {
        "type": GAUGE,
        "help": "solve-service queue depth, by scope (last = at the "
                "most recent batch collection, peak = session maximum)"},
    "serve_gauge_hits_total": {
        "type": COUNTER,
        "help": "requests served with their gauge already the active "
                "resident one (no residency switch), by gauge"},
    "serve_gauge_activations_total": {
        "type": COUNTER,
        "help": "residency switches: a cached gauge installed as the "
                "active resident one for a batch, by gauge"},
    "serve_gauge_evictions_total": {
        "type": COUNTER,
        "help": "gauges evicted by the residency manager to fit the "
                "HBM budget (LRU order, never the active one), by "
                "gauge"},
    "serve_availability_events_total": {
        "type": COUNTER,
        "help": "requests that finished degraded / unverified / "
                "breakdown / unconverged / failed, by kind — the "
                "Service section's availability row"},
    "serve_warm_keys": {
        "type": GAUGE,
        "help": "persisted executable-key index at worker warm start, "
                "by scope (loaded = keys seeded into compile "
                "accounting, saved = keys written at shutdown)"},
    # live telemetry plane (obs/live.py)
    "live_scrapes_total": {
        "type": COUNTER,
        "help": "telemetry-endpoint requests answered, by endpoint "
                "(metrics | healthz | readyz | fleet | slo) and HTTP "
                "status class"},
    "live_flushes_total": {
        "type": COUNTER,
        "help": "periodic background artifact flushes completed by "
                "the live plane (QUDA_TPU_METRICS_FLUSH_SEC windows)"},
    "slo_burn_rate": {
        "type": GAUGE,
        "help": "serve_request_seconds error-budget burn rate at the "
                "last /slo evaluation, by family ('all' = every "
                "family pooled): (1 - compliance) / "
                "(1 - QUDA_TPU_SLO_OBJECTIVE) against "
                "QUDA_TPU_SLO_TARGET_MS"},
    # bench harness (bench_suite.py)
    "bench_rows_total": {
        "type": COUNTER,
        "help": "bench rows emitted, by suite"},
    # static analysis (quda_tpu/analysis; bench_suite --artifacts-dir
    # runs the engine and mirrors per-rule counts here for the fleet
    # report's Static analysis section)
    "analysis_findings": {
        "type": GAUGE,
        "help": "static-analysis findings at the last engine run, by "
                "rule/status (unsuppressed findings fail tier-1 and "
                "the CLI; suppressed ones carry a mandatory reason)"},
}


def metric_type(name: str) -> str:
    return METRICS[name]["type"]
