"""Deterministic solve replay from a postmortem bundle.

``python -m quda_tpu.obs.replay <bundle-dir>`` reconstructs the fields
and params a postmortem bundle (obs/postmortem.py) recorded, re-runs
the solve through the NORMAL ``invert_quda`` path under the recorded
knob snapshot, and reports whether the replay agrees with the original:

* **reproduced** — the replay exits with the recorded ``solve_status``
  and a bit-for-bit identical verified residual (XLA reductions are
  deterministic per executable, so same fields + same knobs + same
  code revision reproduce the failure exactly — QUDA_TPU_FAULT drills
  included, because the fault spec is part of the knob snapshot and
  re-arms under the replay overrides);
* **recovered** — the bundle recorded a failing attempt (breakdown,
  construct error, verification mismatch) and the replay, running the
  FULL solve under the recorded knobs (escalation ladder included),
  exits verified-converged: the failure was transient or the ladder
  absorbed it;
* **diverged** — anything else: the bundle no longer reproduces on
  this build/host, which is itself the finding (environment drift,
  nondeterminism, or a fix).

The replay never writes new telemetry: QUDA_TPU_POSTMORTEM /
QUDA_TPU_FLIGHT / QUDA_TPU_TRACE / QUDA_TPU_METRICS are forced off on
top of the recorded knobs (none of the four adds device ops, so the
solve itself is unchanged — pinned by the obs raising-stub tests), so
re-running a bundle cannot clobber the artifacts of the session that
wrote it.  The verdict is appended to the bundle as ``replay.json``,
which the fleet report's "Postmortems" section quotes as
replay-verified yes/no.

In-process use (:func:`replay_bundle`) re-initialises the API context
(init_quda / load_gauge_quda): run it after ``end_quda``, or from a
fresh process (the CLI).
"""

from __future__ import annotations

import json
import math
import os
import struct
import sys
from typing import Optional

# InvertParam result fields must NOT be seeded from the recorded
# (post-failure) param — the replay recomputes them; the recorded
# values are the comparison baseline
_RESULT_FIELDS = frozenset({
    "true_res", "iter_count", "secs", "gflops", "true_res_multi",
    "iter_count_multi", "res_history", "events", "converged",
    "converged_multi", "verified_res", "solve_status",
    "solve_attempts"})

# telemetry knobs forced off during replay (see module docstring)
_QUIET = {"QUDA_TPU_POSTMORTEM": "0", "QUDA_TPU_FLIGHT": "0",
          "QUDA_TPU_TRACE": "0", "QUDA_TPU_METRICS": "0"}

_REPLAYABLE = ("invert_quda", "invert_multishift_quda",
               "invert_multi_src_quda", "load_gauge_quda")


def load_manifest(bundle: str) -> dict:
    with open(os.path.join(bundle, "manifest.json")) as fh:
        return json.load(fh)


def _load_field(bundle: str, manifest: dict, name: str):
    import numpy as np
    entry = (manifest.get("fields") or {}).get(name)
    if entry is None:
        raise ValueError(f"bundle has no recorded {name!r} field")
    if "file" not in entry:
        raise ValueError(
            f"bundle field {name!r} was omitted at capture "
            f"({entry.get('omitted')}; {entry.get('nbytes')} bytes over "
            "QUDA_TPU_POSTMORTEM_MAX_MB) — cannot replay without it")
    return np.load(os.path.join(bundle, entry["file"]))


def _bits(x: float) -> bytes:
    return struct.pack("<d", float(x))


def bits_equal(a, b) -> bool:
    """Bit-for-bit float64 agreement; both-NaN counts as agreement
    regardless of payload (a NaN residual round-trips through the
    manifest JSON as the canonical quiet NaN)."""
    fa, fb = float(a), float(b)
    if math.isnan(fa) and math.isnan(fb):
        return True
    return _bits(fa) == _bits(fb)


def _rebuild_invert_param(recorded: dict):
    import dataclasses

    from ..interfaces.params import InvertParam
    p = InvertParam()
    names = {f.name for f in dataclasses.fields(InvertParam)}
    for k, v in (recorded or {}).items():
        if k in names and k not in _RESULT_FIELDS:
            setattr(p, k, tuple(v) if isinstance(v, list) else v)
    return p


def _rebuild_gauge_param(recorded: dict):
    import dataclasses

    from ..interfaces.params import GaugeParam
    gp = GaugeParam()
    names = {f.name for f in dataclasses.fields(GaugeParam)}
    for k, v in (recorded or {}).items():
        if k in names:
            setattr(gp, k, tuple(v) if isinstance(v, list) else v)
    # the dumped gauge is the RESIDENT field: already order-converted
    # and anisotropy-folded at the original load — never fold twice
    gp.gauge_order = "canonical"
    gp.anisotropy = 1.0
    return gp


def _verdict(rec_status: str, rec_vres, rep_status: str,
             rep_vres, rep_converged: bool,
             rec_exc_type: Optional[str] = None) -> str:
    # an exception-trigger bundle reproduces when the replay raises
    # the SAME exception type (its recorded solve_status/verified_res
    # are just the pre-failure param defaults — not the failure)
    if rec_exc_type and rep_status == f"raised:{rec_exc_type}":
        return "reproduced"
    status_ok = (rep_status == rec_status)
    vres_ok = (rec_vres is None
               or (rep_vres is not None
                   and bits_equal(rec_vres, rep_vres)))
    if status_ok and vres_ok:
        return "reproduced"
    if rec_status != "converged" and rep_status == "converged" \
            and rep_converged:
        return "recovered"
    return "diverged"


def replay_bundle(bundle: str, save: bool = True) -> dict:
    """Re-run the solve a bundle recorded; returns the replay report
    (and appends it to the bundle as replay.json when ``save``)."""
    from ..utils import config as qconf
    manifest = load_manifest(bundle)
    api = manifest.get("api")
    if api not in _REPLAYABLE:
        raise ValueError(f"bundle api {api!r} is not replayable "
                         f"(supported: {_REPLAYABLE})")
    # a bundle from a build with knobs this checkout has never heard
    # of must still replay (environment drift is a finding, not a
    # crash): unknown names are dropped from the overrides and
    # reported, not fed to qconf.overrides' unregistered-knob raise
    known = set(qconf.knobs())
    recorded_knobs = dict(manifest.get("knobs") or {})
    skipped_knobs = sorted(k for k in recorded_knobs if k not in known)
    overrides = {k: v for k, v in recorded_knobs.items() if k in known}
    overrides.update(_QUIET)

    from ..interfaces import quda_api as qapi
    from ..robust import faultinject as finj

    rec_param = manifest.get("invert_param") or {}
    rec_exc_type = (manifest.get("exception") or {}).get("type")
    report = {
        "bundle": os.path.abspath(bundle),
        "api": api,
        "trigger": manifest.get("trigger"),
        "recorded": {"solve_status": rec_param.get("solve_status"),
                     "verified_res": rec_param.get("verified_res"),
                     "converged": rec_param.get("converged"),
                     "iter_count": rec_param.get("iter_count"),
                     "exception_type": rec_exc_type},
    }
    if skipped_knobs:
        report["skipped_knobs"] = skipped_knobs
    with qconf.overrides(**overrides):
        # the recorded QUDA_TPU_FAULT spec re-arms under the override
        # stack — the drill that captured this bundle replays too
        finj.reset()
        try:
            qapi.init_quda()
            if api == "load_gauge_quda":
                return _replay_gauge_load(bundle, manifest, report,
                                          save, qapi, finj)
            gp = _rebuild_gauge_param(manifest.get("gauge_param"))
            qapi.load_gauge_quda(_load_field(bundle, manifest, "gauge"),
                                 gp)
            if (manifest.get("fields") or {}).get("fat"):
                try:
                    qapi.load_fat_long_quda(
                        _load_field(bundle, manifest, "fat"),
                        _load_field(bundle, manifest, "long"))
                except ValueError:
                    pass       # fat recorded, long capped out
            p = _rebuild_invert_param(rec_param)
            src = _load_field(bundle, manifest, "source")
            fn = getattr(qapi, api)
            try:
                fn(src, p)
                replayed = {
                    "solve_status": p.solve_status,
                    "verified_res": p.verified_res,
                    "converged": bool(p.converged),
                    "iter_count": int(p.iter_count),
                    "solve_attempts": list(p.solve_attempts)}
            except Exception as e:  # noqa: BLE001 — exception IS data
                replayed = {
                    "solve_status": f"raised:{type(e).__name__}",
                    "verified_res": None, "converged": False,
                    "error": str(e)[:300]}
        finally:
            finj.reset()       # never leak replay arms to the caller
    report["replayed"] = replayed
    rec = report["recorded"]
    report["verdict"] = _verdict(
        rec.get("solve_status"), rec.get("verified_res"),
        replayed.get("solve_status"), replayed.get("verified_res"),
        bool(replayed.get("converged")), rec_exc_type=rec_exc_type)
    report["status_match"] = (replayed.get("solve_status")
                              == rec.get("solve_status"))
    report["verified_res_bits_match"] = (
        rec.get("verified_res") is not None
        and replayed.get("verified_res") is not None
        and bits_equal(rec["verified_res"], replayed["verified_res"]))
    if save:
        _save_report(bundle, report)
    return report


def _replay_gauge_load(bundle, manifest, report, save, qapi, finj):
    """Gauge-rejection bundles replay the load itself: the dumped
    gauge (poisoned as captured) must be rejected again."""
    from ..utils.logging import QudaError
    gp = _rebuild_gauge_param(manifest.get("gauge_param"))
    try:
        qapi.load_gauge_quda(_load_field(bundle, manifest, "gauge"), gp)
        replayed = {"solve_status": "accepted"}
    except QudaError as e:
        replayed = {"solve_status": "rejected", "error": str(e)[:300]}
    finally:
        finj.reset()
    report["replayed"] = replayed
    report["verdict"] = ("reproduced"
                         if replayed["solve_status"] == "rejected"
                         else "diverged")
    if save:
        _save_report(bundle, report)
    return report


def _save_report(bundle: str, report: dict):
    import time
    with open(os.path.join(bundle, "replay.json"), "w") as fh:
        json.dump(dict(report,
                       replayed_at=time.strftime("%Y-%m-%d %H:%M:%S")),
                  fh, indent=1, sort_keys=True, default=str)


def render(report: dict) -> str:
    rec, rep = report["recorded"], report["replayed"]
    lines = [
        f"# postmortem replay — {report['bundle']}",
        f"api:      {report['api']}   trigger: {report['trigger']}",
        f"recorded: status={rec.get('solve_status')!r} "
        f"verified_res={rec.get('verified_res')} "
        f"iters={rec.get('iter_count')}",
        f"replayed: status={rep.get('solve_status')!r} "
        f"verified_res={rep.get('verified_res')} "
        f"iters={rep.get('iter_count')}",
        f"verdict:  {report['verdict'].upper()}",
    ]
    if rep.get("error"):
        lines.append(f"replay error: {rep['error']}")
    if report.get("skipped_knobs"):
        lines.append("skipped knobs (unknown to this build): "
                     + ", ".join(report["skipped_knobs"]))
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    as_json = "--json" in argv
    args = [a for a in argv if not a.startswith("-")]
    if len(args) != 1:
        print("usage: python -m quda_tpu.obs.replay [--json] "
              "<bundle-dir>", file=sys.stderr)
        return 2
    report = replay_bundle(args[0])
    print(json.dumps(report, indent=1, default=str) if as_json
          else render(report))
    return 0 if report["verdict"] in ("reproduced", "recovered") else 1


if __name__ == "__main__":
    sys.exit(main())
