"""HBM field ledger + device-memory sampling + VMEM budget audit.

Reference behavior: the reference's device_malloc ledger (lib/malloc.cpp)
tracks every allocation with a label and reports the high-water mark at
shutdown; QUDA_ENABLE_MONITOR samples device state periodically.  On
TPU, XLA/PJRT owns allocation, so what a serving fleet needs instead is
*attribution*: which resident FIELDS (gauge, clover, fat/Naik links, MG
hierarchy levels, eig workspaces) account for the HBM a worker holds,
what the per-device ``memory_stats()`` high-water was around solves,
and whether the pallas kernels' VMEM budgets
(``QUDA_TPU_PALLAS_VMEM_MB*``) are sane against the 16 MB scoped limit.

Three surfaces:

* the **field ledger** — :func:`track` / :func:`release` called at every
  resident-field load/free site (interfaces/quda_api.py, models/).
  Host-side dict bookkeeping (nanoseconds, no device ops), ALWAYS
  maintained; mirrored into the metrics registry (``hbm_field_bytes``,
  family totals, high-water gauges) and the trace stream only when
  those sessions are active.
* **device snapshots** — :func:`device_snapshot` reads
  ``memory_stats()`` from **all** local devices (not just device 0 —
  the round-12 monitor fix) and folds per-device high-water into the
  ledger; :func:`sample` is the solve-phase hook quda_api calls when
  metrics are on.
* the **VMEM audit** — :func:`vmem_audit` records each ``_pick_bz``
  block decision against its budget knob, and
  :func:`audit_vmem_budgets` checks every registered budget against
  the 16 MB Mosaic scoped limit (single-buffer budget must leave room
  for double buffering) for the fleet report.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# Mosaic scoped-VMEM limit the budgets are carved from (see
# QUDA_TPU_PALLAS_VMEM_MB's registration doc: 6 MB default = < half of
# 16 MB so double buffering fits)
SCOPED_VMEM_MB = 16.0

# the per-form single-buffer budget knobs (utils/config.py)
VMEM_KNOBS = ("QUDA_TPU_PALLAS_VMEM_MB", "QUDA_TPU_PALLAS_VMEM_MB_STAGGERED")

_fields: Dict[tuple, dict] = {}        # (family, name) -> {bytes, since}
_family_high: Dict[str, int] = {}      # family -> high-water bytes
_device_last: Dict[str, int] = {}      # device label -> last bytes_in_use
_device_high: Dict[str, int] = {}      # device label -> high-water
_vmem_last: Dict[str, dict] = {}       # knob -> last _pick_bz decision
# the monitor's background thread and the solve-phase sampling hook
# both read-modify-write the device high-water dicts — a lost update
# would under-report the peak the fleet report quotes
_lock = threading.Lock()


def reset():
    """Drop all ledger state (end_quda epilogue / test isolation)."""
    with _lock:
        _fields.clear()
        _family_high.clear()
        _device_last.clear()
        _device_high.clear()
        _vmem_last.clear()


def nbytes_of(obj, _seen: Optional[set] = None, _depth: int = 0) -> int:
    """Total array bytes reachable from ``obj``: jax/numpy arrays count
    ``.nbytes``; containers and plain objects (MG hierarchies, pair
    operators) are walked recursively with cycle/depth guards.  Host
    bookkeeping only — never forces device transfers."""
    if _seen is None:
        _seen = set()
    if _depth > 8 or id(obj) in _seen:
        return 0
    _seen.add(id(obj))
    nb = getattr(obj, "nbytes", None)
    if isinstance(nb, int) and hasattr(obj, "dtype"):
        return nb
    if isinstance(obj, (int, float, complex, str, bytes, bool,
                        type(None))):
        return 0
    if isinstance(obj, dict):
        return sum(nbytes_of(v, _seen, _depth + 1) for v in obj.values())
    if isinstance(obj, (list, tuple, set, frozenset)):
        return sum(nbytes_of(v, _seen, _depth + 1) for v in obj)
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        return sum(nbytes_of(v, _seen, _depth + 1) for v in d.values())
    return 0


def _family_total_locked(family: str) -> int:
    """Resident bytes of one family; caller holds ``_lock``."""
    return sum(e["bytes"] for (f, _), e in _fields.items()
               if f == family)


def _mirror_family(family: str, total: int, high: int):
    from . import metrics as omet
    omet.set_gauge("hbm_family_bytes", total, family=family)
    omet.set_gauge("hbm_family_high_water_bytes", high, family=family)


def track(family: str, name: str, obj) -> int:
    """(Re)register a resident field: ``obj`` is an array/pytree/object
    (bytes computed via :func:`nbytes_of`) or an int byte count.
    Re-tracking the same (family, name) replaces the entry — resident
    mutations (smearing, HMC updates) keep one row, not a leak."""
    nbytes = obj if isinstance(obj, int) else nbytes_of(obj)
    with _lock:
        _fields[(family, name)] = {"bytes": int(nbytes),
                                   "since": time.time()}
        fam_total = _family_total_locked(family)
        if fam_total > _family_high.get(family, 0):
            _family_high[family] = fam_total
        high = _family_high.get(family, 0)
    from . import metrics as omet
    from . import trace as otr
    omet.set_gauge("hbm_field_bytes", nbytes, family=family, field=name)
    _mirror_family(family, fam_total, high)
    otr.event("hbm_field_tracked", cat="memory", family=family,
              field=name, bytes=int(nbytes))
    return int(nbytes)


def release_family(family: str) -> int:
    """Release every field of a family (the per-API-call transient
    families — clover terms, eig workspaces — whose arrays die with the
    call; family high-water is retained as the peak signal).  Returns
    the number of entries released."""
    with _lock:
        names = [n for (f, n) in _fields if f == family]
    for n in names:
        release(family, n)
    return len(names)


def release(family: str, name: str) -> bool:
    """Unregister a resident field (free/end_quda site); True iff it
    was tracked."""
    with _lock:
        entry = _fields.pop((family, name), None)
        if entry is None:
            return False
        fam_total = _family_total_locked(family)
        high = _family_high.get(family, 0)
    from . import metrics as omet
    from . import trace as otr
    omet.set_gauge("hbm_field_bytes", 0, family=family, field=name)
    _mirror_family(family, fam_total, high)
    otr.event("hbm_field_released", cat="memory", family=family,
              field=name, bytes=entry["bytes"])
    return True


def ledger() -> List[dict]:
    """Current ledger rows, largest first."""
    with _lock:
        rows = [{"family": f, "field": n, "bytes": e["bytes"]}
                for (f, n), e in _fields.items()]
    return sorted(rows, key=lambda r: -r["bytes"])


def family_bytes() -> Dict[str, int]:
    out: Dict[str, int] = {}
    with _lock:
        for (family, _), e in _fields.items():
            out[family] = out.get(family, 0) + e["bytes"]
    return out


def high_water() -> Dict[str, int]:
    with _lock:
        return dict(_family_high)


def device_high_water() -> Dict[str, int]:
    with _lock:
        return dict(_device_high)


def device_snapshot() -> List[dict]:
    """``memory_stats()`` across ALL local devices (the monitor
    previously sampled only ``jax.local_devices()[0]`` — a sharded
    solve's other shards were invisible).  Folds per-device high-water
    into the ledger.  Backends without memory_stats (CPU) yield
    bytes_in_use 0 rows, one per device, so consumers always see the
    device count."""
    rows: List[dict] = []
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return rows
    for d in devices:
        label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        in_use = int(stats.get("bytes_in_use", 0))
        peak = int(stats.get("peak_bytes_in_use", in_use))
        with _lock:
            _device_last[label] = in_use
            if max(in_use, peak) > _device_high.get(label, 0):
                _device_high[label] = max(in_use, peak)
        rows.append({"device": label, "bytes_in_use": in_use,
                     "peak_bytes_in_use": peak})
    return rows


def sample(phase: str = "") -> List[dict]:
    """Solve-phase device sampling hook (quda_api, metrics-gated at the
    call sites): snapshot all local devices and mirror the per-device
    gauges.  ``phase`` is advisory (kept for call-site readability)."""
    rows = device_snapshot()
    from . import metrics as omet
    for r in rows:
        omet.set_gauge("hbm_device_bytes_in_use", r["bytes_in_use"],
                       device=r["device"])
        omet.set_gauge("hbm_device_high_water_bytes",
                       _device_high.get(r["device"], 0),
                       device=r["device"])
    return rows


# -- VMEM budget audit ------------------------------------------------------

def vmem_audit(knob: str, block_bytes: int, budget_bytes: int,
               bz: Optional[int] = None, single_buffered: bool = False):
    """Record one ``_pick_bz`` decision: selected single-buffer working
    set vs the knob's budget (ops/wilson_pallas_packed.py call sites).
    ``block_bytes`` is the PADDED tile working set — sublane rows at the
    dtype's tile height (8 f32 / 16 bf16 / 32 int8), lanes padded to
    128 — so the audit charges what the block really occupies.
    ``single_buffered`` marks a full-block admission that only fits the
    scoped window once (the bf16/int8 bz=Z fallback): Mosaic cannot
    double-buffer it, so the pipeline serialises."""
    with _lock:
        _vmem_last[knob] = {"block_bytes": int(block_bytes),
                            "budget_bytes": int(budget_bytes), "bz": bz,
                            "single_buffered": bool(single_buffered)}
    from . import metrics as omet
    omet.set_gauge("vmem_block_bytes", block_bytes, knob=knob)
    omet.set_gauge("vmem_budget_bytes", budget_bytes, knob=knob)


def audit_vmem_budgets() -> List[dict]:
    """Every registered per-form VMEM budget vs the scoped limit: a
    single-buffer budget above SCOPED_VMEM_MB/2 leaves Mosaic no room
    to double-buffer (legal but measure-before-pinning territory —
    flagged, not rejected).  Fleet-report consumable."""
    from ..utils import config as qconf
    out = []
    for knob in VMEM_KNOBS:
        mb = float(qconf.get(knob, fresh=True))
        with _lock:
            last = dict(_vmem_last.get(knob, {}))
        out.append({
            "knob": knob, "budget_mb": mb,
            "double_buffer_ok": mb <= SCOPED_VMEM_MB / 2,
            "last_block_bytes": last.get("block_bytes"),
            "last_bz": last.get("bz"),
            "last_single_buffered": last.get("single_buffered", False),
        })
    return out
