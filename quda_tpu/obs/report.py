"""End-of-session fleet report: the one page an operator reads.

Renders the metrics registry (obs/metrics.py) + HBM ledger
(obs/memory.py) into a human-readable summary — solves by family and
status, resident-field bytes with high-water marks, compile count vs
warm-executable and tuner warm-cache hits, retry-ladder usage, and the
VMEM budget audit.  ``end_quda`` writes it as ``fleet_report.txt``
next to ``metrics.prom`` when QUDA_TPU_METRICS is on; the same text is
what a serving fleet's rollout review quotes before scaling a worker
image (ROADMAP item 2's "first solve without a compile/race storm" is
checked HERE: compiles_total vs executions_total vs tune cache hits).
"""

from __future__ import annotations

import time
from typing import Optional

from . import memory as omem
from . import metrics as omet
from . import postmortem as opm


def _mb(nbytes) -> str:
    return f"{nbytes / 2 ** 20:.2f} MB"


def _by_name(snap: dict, kind: str, name: str) -> list:
    """[(labels_dict, value)] for one metric name, label-sorted."""
    return sorted(((dict(labels), v)
                   for (n, labels), v in snap[kind].items()
                   if n == name),
                  key=lambda x: sorted(x[0].items()))


def _counter_total(snap: dict, name: str, **match) -> float:
    tot = 0.0
    for labels, v in _by_name(snap, "counters", name):
        if all(labels.get(k) == v2 for k, v2 in match.items()):
            tot += v
    return tot


def render(snap: Optional[dict] = None) -> str:
    """The fleet report as text.  Works from a snapshot so a single
    flush renders exactly what it exported."""
    snap = snap or omet.snapshot()
    lines = ["# quda_tpu fleet report",
             f"# generated {time.strftime('%Y-%m-%d %H:%M:%S')}", ""]

    # -- solves by family / status --
    lines.append("## Solves (by api / family / status)")
    solves = _by_name(snap, "counters", "solves_total")
    if solves:
        for labels, v in solves:
            lines.append(f"  {labels.get('api', '?'):28s} "
                         f"{labels.get('family', '?'):16s} "
                         f"{labels.get('status', '?'):24s} {v:g}")
        iters = _counter_total(snap, "solve_iterations_total")
        lines.append(f"  total solver iterations: {iters:g}")
    else:
        lines.append("  (no API solves recorded)")
    eig = _by_name(snap, "counters", "eigensolves_total")
    for labels, v in eig:
        lines.append(f"  eigensolve {labels.get('family', '?')}/"
                     f"{labels.get('eig_type', '?')}: {v:g}")
    lines.append("")

    # -- HBM ledger --
    lines.append("## HBM field ledger (resident now / session "
                 "high-water)")
    fam = omem.family_bytes()
    high = omem.high_water()
    if fam or high:
        for family in sorted(set(fam) | set(high)):
            lines.append(f"  {family:12s} {_mb(fam.get(family, 0)):>12s}"
                         f"  high-water {_mb(high.get(family, 0))}")
        for row in omem.ledger():
            lines.append(f"    {row['family']}/{row['field']}: "
                         f"{_mb(row['bytes'])}")
    else:
        lines.append("  (no resident fields tracked)")
    dev_high = omem.device_high_water()
    for dev in sorted(dev_high):
        lines.append(f"  device {dev}: high-water "
                     f"{_mb(dev_high[dev])} (memory_stats)")
    lines.append("")

    # -- compile / cache accounting --
    lines.append("## Compile & cache accounting")
    compiles = _counter_total(snap, "compiles_total")
    execs = _counter_total(snap, "executions_total")
    lines.append(f"  first-execution compiles: {compiles:g} distinct "
                 f"(api, form, shape, dtype, solver) keys")
    for labels, v in _by_name(snap, "counters", "compiles_total"):
        lines.append(f"    {labels.get('api', '?')}/"
                     f"{labels.get('form', '?')}: {v:g}")
    lines.append(f"  compute-phase executions: {execs:g} "
                 f"(warm-executable after the first: "
                 f"{max(0.0, execs - compiles):g})")
    hits = _counter_total(snap, "tune_cache_hits_total")
    misses = _counter_total(snap, "tune_cache_misses_total")
    races = _counter_total(snap, "tune_races_total")
    race_fail = _counter_total(snap, "tune_race_failures_total")
    lines.append(f"  tuner warm-cache: {hits:g} hits / {misses:g} "
                 f"misses ({races:g} races timed, {race_fail:g} "
                 "all-candidates-failed)")
    for labels, v in _by_name(snap, "gauges", "tune_cache_entries"):
        lines.append(f"    warm-start entries [{labels.get('scope')}]: "
                     f"{v:g}")
    lines.append("")

    # -- retry ladder / robustness --
    lines.append("## Retry ladder (QUDA_TPU_ROBUST)")
    retries = _counter_total(snap, "solve_retries_total")
    degraded = _counter_total(snap, "solve_degraded_total")
    breakdowns = _counter_total(snap, "breakdowns_total")
    if retries or degraded or breakdowns:
        for labels, v in _by_name(snap, "counters",
                                  "solve_retries_total"):
            lines.append(f"  retry {labels.get('api', '?')} "
                         f"[{labels.get('reason', '?')}]: {v:g}")
        lines.append(f"  degraded solves: {degraded:g}; breakdown "
                     f"exits: {breakdowns:g}")
    else:
        lines.append("  (no retries, degradations, or breakdowns)")
    lines.append("")

    # -- postmortem bundles (obs/postmortem.py) --
    lines.append("## Postmortems (failure-capture bundles)")
    pm_bundles = opm.bundles()
    if pm_bundles:
        by_trigger: dict = {}
        for b in pm_bundles:
            by_trigger[b["trigger"]] = by_trigger.get(b["trigger"],
                                                      0) + 1
        for trig in sorted(by_trigger):
            lines.append(f"  {trig}: {by_trigger[trig]}")
        for b in pm_bundles:
            lines.append(f"    {b['path']}  replay-verified: "
                         f"{opm.replay_status(b['path'])}")
        if opm.suppressed():
            lines.append(f"  ({opm.suppressed()} further capture(s) "
                         "suppressed past the session bundle cap)")
        lines.append("  replay: python -m quda_tpu.obs.replay "
                     "<bundle>")
    else:
        lines.append("  (no postmortem bundles this session)")
    lines.append("")

    # -- solve service (quda_tpu/serve) --
    _render_service(snap, lines)

    # -- MG setup attribution --
    mg_phases = _by_name(snap, "counters", "mg_setup_phase_seconds_total")
    if mg_phases:
        lines.append("## MG setup breakdown (per level / phase, "
                     "wall seconds)")
        total = _counter_total(snap, "mg_setup_seconds_total")
        for labels, v in mg_phases:
            lines.append(f"  level {labels.get('level', '?')} "
                         f"{labels.get('phase', '?'):16s} {v:.3f} s")
        phase_sum = _counter_total(snap, "mg_setup_phase_seconds_total")
        lines.append(f"  phases {phase_sum:.3f} s of {total:.3f} s "
                     "setup wall")
        lines.append("")

    # -- ICI comms attribution --
    ici = _by_name(snap, "counters", "ici_bytes_total")
    if ici:
        lines.append("## ICI comms (ledger-attributed bytes)")
        for labels, v in ici:
            lines.append(f"  axes {labels.get('axis', '?'):8s} "
                         f"policy {labels.get('policy', '?'):16s} "
                         f"{_mb(v)}")
        lines.append("")

    # -- static analysis (quda_tpu/analysis, when an engine run
    #    mirrored its counts this session) --
    sa = _by_name(snap, "gauges", "analysis_findings")
    if sa:
        lines.append("## Static analysis (quda_tpu/analysis, per rule)")
        per_rule: dict = {}
        for labels, v in sa:
            per_rule.setdefault(labels.get("rule", "?"), {})[
                labels.get("status", "?")] = v
        for rname in sorted(per_rule):
            c = per_rule[rname]
            bad = c.get("unsuppressed", 0)
            sup = c.get("suppressed", 0)
            note = "CLEAN" if not bad else "FINDINGS — fix or suppress"
            lines.append(f"  {rname:22s} unsuppressed {bad:g}, "
                         f"suppressed {sup:g}  [{note}]")
        lines.append("")

    # -- VMEM budget audit --
    lines.append("## Pallas VMEM budgets (single-buffer, vs "
                 f"{omem.SCOPED_VMEM_MB:g} MB scoped limit)")
    for row in omem.audit_vmem_budgets():
        note = ("ok" if row["double_buffer_ok"]
                else "leaves < half the scoped limit for Mosaic's "
                     "double buffering — measured-knob territory")
        last = ""
        if row["last_block_bytes"] is not None:
            last = (f"; last block {_mb(row['last_block_bytes'])} "
                    f"(bz={row['last_bz']})")
        lines.append(f"  {row['knob']}: {row['budget_mb']:g} MB "
                     f"[{note}]{last}")
    return "\n".join(lines) + "\n"


def _hist_percentile_bounds(h, qs=(0.5, 0.9, 0.99)):
    """Upper-bound percentile estimates from the cumulative histogram
    buckets: the tightest bucket bound covering each quantile (the
    standard Prometheus-histogram read; exact values are not retained
    by design, so every estimate is an UPPER bound and is rendered as
    one — p50≤, never p50=).  Buckets come from the histogram itself
    (QUDA_TPU_SERVE_SLO_BUCKETS may have reshaped them).  Returns
    {q: bound-or-None}, None meaning the +Inf bucket."""
    bounds = {}
    for q in qs:
        target = q * h["n"]
        cum = 0
        val = None
        for i, ub in enumerate(h.get("buckets", omet.HIST_BUCKETS)):
            cum += h["counts"][i]
            if cum >= target:
                val = ub
                break
        bounds[q] = val
    return bounds


def _render_service(snap: dict, lines: list):
    """The Service section: rendered only when the solve service
    recorded anything — queue depth, the batch-size histogram,
    solve_seconds SLO percentiles, per-gauge residency traffic, and
    the availability-event roll-up ROADMAP item 2 asks the fleet to
    page on."""
    reqs = _by_name(snap, "counters", "serve_requests_total")
    batches = _by_name(snap, "counters", "serve_batches_total")
    if not reqs and not batches:
        return
    lines.append("## Service (solve-service worker)")
    for labels, v in reqs:
        lines.append(f"  requests {labels.get('family', '?'):14s} "
                     f"{labels.get('status', '?'):24s} {v:g}")
    depth = {labels.get("scope"): v for labels, v in
             _by_name(snap, "gauges", "serve_queue_depth")}
    lines.append(f"  queue depth: last {depth.get('last', 0):g}, "
                 f"peak {depth.get('peak', 0):g}")
    if batches:
        sizes = " ".join(
            f"n={labels.get('size', '?')} x{v:g}"
            for labels, v in sorted(
                batches, key=lambda x: int(x[0].get("size", 0))))
        lines.append(f"  coalesced batches: {sizes}")
    for labels, h in _by_name(snap, "histograms",
                              "serve_request_seconds"):
        b = _hist_percentile_bounds(h)
        last = h.get("buckets", omet.HIST_BUCKETS)[-1]
        pct = ", ".join(
            (f"p{int(q * 100)}≤ {ub:g} s" if ub is not None
             else f"p{int(q * 100)}> {last:g} s")
            for q, ub in b.items())
        mean = h["sum"] / max(1, h["n"])
        lines.append(f"  solve_seconds SLO "
                     f"[{labels.get('family', '?')}]: {pct} "
                     f"(bucket upper bounds; n={h['n']}, "
                     f"mean {mean:.3f} s)")
    gauges_seen = {}
    for metric, col in (("serve_gauge_hits_total", "hits"),
                        ("serve_gauge_activations_total",
                         "activations"),
                        ("serve_gauge_evictions_total", "evictions")):
        for labels, v in _by_name(snap, "counters", metric):
            gauges_seen.setdefault(labels.get("gauge", "?"),
                                   {})[col] = v
    for gid in sorted(gauges_seen):
        g = gauges_seen[gid]
        lines.append(f"  gauge {gid}: hits {g.get('hits', 0):g}, "
                     f"activations {g.get('activations', 0):g}, "
                     f"evictions {g.get('evictions', 0):g}")
    avail = _by_name(snap, "counters", "serve_availability_events_total")
    if avail:
        for labels, v in avail:
            lines.append(f"  availability events "
                         f"[{labels.get('kind', '?')}]: {v:g}")
    else:
        lines.append("  availability events: none")
    warm = {labels.get("scope"): v for labels, v in
            _by_name(snap, "gauges", "serve_warm_keys")}
    if warm:
        lines.append(f"  warm executable keys: "
                     f"loaded {warm.get('loaded', 0):g}, "
                     f"saved {warm.get('saved', 0):g}")
    lines.append("")


def save(path: str, snap: Optional[dict] = None) -> str:
    """Write the report to ``path`` (metrics.flush hook)."""
    with open(path, "w") as fh:
        fh.write(render(snap))
    return path
