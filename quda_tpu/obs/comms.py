"""ICI comms ledger: bytes-over-interconnect attribution per solve.

Reference behavior: the only Python in the entire reference is
``lib/generate/wrap.py`` — a code generator for an NVTX-annotated MPI
wrapper, built because comms attribution matters enough to tool.  PLQCD
(arXiv:1405.0700) makes the point quantitatively: the comms-overlap
fraction is *the* number that decides pod-scale viability.  This module
is the TPU-native home for that number's numerator: every halo-exchange
seam in the package (``lax.ppermute`` via
``parallel/halo._permute_slice``, the in-kernel RDMA policies of
``parallel/pallas_halo``, the split-grid gauge replication of
``parallel/split.py``) records (axis, direction, bytes/device, mesh,
policy, dtype) into one ledger, and the solve epilogue joins those rows
with measured seconds into an ICI roofline row emitted alongside the
HBM roofline in ``roofline.tsv``.

Semantics — a MODEL ledger, recorded at trace time: the exchange seams
execute inside ``jit``/``shard_map`` *tracing*, so each distinct
compiled stencil contributes its rows ONCE (per trace), with the bytes
computed from the actual traced slab shapes.  That is the point: the
ledger rows ARE the analytic halo model, harvested from the real seams
instead of hand arithmetic, and the per-solve total is rows x measured
operator applications (``attribute_solve``).  Entry ``count`` is the
number of traces that recorded the row, not an execution count.  The
split-grid replication row is the exception: it records at the actual
``device_put`` call, so its bytes are real per-call transfer volume.

Activation: rides the existing observability knobs — ``init_quda``
starts the ledger iff ``QUDA_TPU_TRACE`` or ``QUDA_TPU_METRICS`` is set
(:func:`maybe_start`); the bench harness and tests call :func:`start`
directly.  **Off means off**: every recording entry point returns after
one module-global load and ``scope()`` hands back a no-op singleton, so
the seams stay branch-cheap on the disabled path and compiled solves
are bit-identical (pinned by a raising-stub test, the trace/metrics
discipline).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# Nominal per-chip aggregate ICI bandwidth used for the percent column
# of the ICI roofline rows.  This is the published v5e interconnect spec
# (1600 Gbps/chip), NOT a demonstrated number — no multi-chip window has
# measured a sustained link rate yet, so the column answers "how close
# would this solve's comms volume alone come to saturating the nominal
# link" (the PLQCD overlap-fraction numerator).  Replace with a measured
# peak the first time a chip window times a saturating exchange; on CPU
# meshes the percentage is computed but physically meaningless.
ICI_NOMINAL_GBPS = 200.0


class _NoopScope:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


class _Ledger:
    """Live ledger session.  Exchange ENTRIES live at module level (see
    ``_entries``): they are recorded at trace time and model the jit
    cache, which outlives any one init/end session — a second session
    reusing cached executables would otherwise silently lose all ICI
    attribution.  The session holds only the per-session solve rows and
    gates whether recording happens at all."""

    def __init__(self):
        self.solve_rows: List[dict] = []      # attribute_solve output
        self.lock = threading.Lock()

    def record(self, site: str, axis: str, direction: str, nbytes: int,
               policy: str, dtype: str, mesh: str, n_slabs: int):
        key = (site, axis, direction, int(nbytes), policy, dtype, mesh,
               int(n_slabs))
        with _entries_lock:
            _entries[key] = _entries.get(key, 0) + 1
        from . import trace as otr
        otr.event("ici_exchange", cat="comms", site=site, axis=axis,
                  direction=direction, bytes=int(nbytes), policy=policy,
                  dtype=dtype, mesh=mesh, n_slabs=int(n_slabs))


_session: Optional[_Ledger] = None

# (site, axis, direction, bytes, policy, dtype, mesh, n_slabs) -> trace
# count.  Module-level (NOT per session): entries record what each
# compiled stencil's trace exchanged, and compiled executables persist
# across init/end cycles in one process — the entries must too, or a
# later session attributes nothing because nothing re-traces.
_entries: Dict[tuple, int] = {}
_entries_lock = threading.Lock()

# Scope stack: the sharded dslash wrappers push (site, policy) while
# their face-fix tracing runs, so the primitive seams (_permute_slice,
# slab_exchange_bidir) can label their rows without threading arguments
# through every call chain.  Host-side list, touched only at trace time.
_scopes: List[dict] = []


def enabled() -> bool:
    return _session is not None


def start() -> _Ledger:
    """Open a ledger session (idempotent — an active session is kept)."""
    global _session
    if _session is None:
        _session = _Ledger()
    return _session


def maybe_start() -> Optional[_Ledger]:
    """Start iff QUDA_TPU_TRACE or QUDA_TPU_METRICS is set (init_quda
    hook — the ledger rides the existing observability knobs, no knob
    of its own)."""
    from ..utils import config as qconf
    if (qconf.get("QUDA_TPU_TRACE", fresh=True)
            or qconf.get("QUDA_TPU_METRICS", fresh=True)):
        return start()
    return None


def stop():
    """Drop the session and its solve rows (end_quda epilogue).  The
    exchange ENTRIES survive on purpose: they mirror the process's jit
    cache, which a later init/end cycle reuses without re-tracing."""
    global _session
    _session = None
    # the scope stack is trace-time LIFO state owned by the tracing
    # thread's context nesting; end_quda teardown runs after tracing
    _scopes.clear()  # quda-lint: disable=lock-discipline  reason=trace-time LIFO scope stack; teardown runs on the owning thread after tracing


def reset():
    """Full reset — session, solve rows AND the process-lifetime
    exchange entries (test isolation only; production uses stop())."""
    stop()
    with _entries_lock:
        _entries.clear()


def scope(site: str, policy: Optional[str] = None, mesh_axes=()):
    """Context manager labeling exchanges recorded inside it (pushed by
    the sharded dslash wrappers around their face-fix construction);
    ``mesh_axes`` are the partitioned ring sizes, inherited by seams
    that cannot see the mesh themselves (slab_exchange_bidir).  The
    no-op singleton when the ledger is off."""
    if _session is None:
        return _NOOP_SCOPE

    import contextlib

    @contextlib.contextmanager
    def _ctx():
        # the scope stack is per-trace LIFO state owned by the tracing
        # thread's context nesting (the postmortem._scopes rationale);
        # a lock cannot linearize cross-thread push/pop meaningfully
        _scopes.append({"site": site, "policy": policy,  # quda-lint: disable=lock-discipline  reason=trace-time LIFO scope stack, push/pop ordering is the tracing thread's own nesting
                        "mesh_axes": tuple(mesh_axes)})
        try:
            yield
        finally:
            _scopes.pop()  # quda-lint: disable=lock-discipline  reason=trace-time LIFO scope stack, push/pop ordering is the tracing thread's own nesting

    return _ctx()


def _tracer_nbytes(arr) -> int:
    """Bytes of an array OR tracer (tracers carry size/dtype, not
    nbytes)."""
    nb = getattr(arr, "nbytes", None)
    if isinstance(nb, int):
        return nb
    import numpy as np
    return int(arr.size) * int(np.dtype(arr.dtype).itemsize)


def record_exchange(arrs=None, axis: str = "?",
                    direction: str = "bidir",
                    policy: Optional[str] = None, mesh_axes=(),
                    nbytes: Optional[int] = None, n_slabs: int = 1,
                    dtype: str = "float32") -> None:
    """One halo exchange at a primitive seam: ``arrs`` is the slab (or
    tuple of slabs) a device sends per invocation — per-device bytes
    come from the traced shapes — or pass ``nbytes``/``n_slabs``/
    ``dtype`` explicitly where the slabs are kernel-internal VMEM
    buffers (the fused-halo entry points).  No-op (one global load)
    when the ledger is off."""
    s = _session
    if s is None:
        return
    if nbytes is None:
        if not isinstance(arrs, (tuple, list)):
            arrs = (arrs,)
        nbytes = sum(_tracer_nbytes(a) for a in arrs)
        n_slabs = len(arrs)
        import numpy as np
        dtype = str(np.dtype(arrs[0].dtype).name)
    top = _scopes[-1] if _scopes else {}
    # the scope's mesh sizes WIN over a seam-supplied single ring: the
    # sharded wrappers know the full (n_t, n_z) partition while
    # _permute_slice sees only its own axis — attribution's device
    # count needs the full product
    mesh_axes = tuple(top.get("mesh_axes") or ()) or tuple(mesh_axes)
    s.record(site=top.get("site") or "unscoped",
             axis=axis, direction=direction, nbytes=int(nbytes),
             policy=policy or top.get("policy") or "ppermute",
             dtype=dtype, mesh="x".join(str(a) for a in mesh_axes),
             n_slabs=n_slabs)


def record_replication(obj, axis: str, n_devices: int,
                       what: str = "gauge") -> None:
    """Split-grid lane placement: ``obj`` (array/pytree) is replicated
    onto every sub-grid — (n_devices - 1) x its bytes travel the
    interconnect at the actual ``device_put``.  Unlike the exchange
    rows this is a per-CALL record (it runs host-side, not in a
    trace)."""
    s = _session
    if s is None:
        return
    from . import memory as omem
    from . import metrics as omet
    nbytes = omem.nbytes_of(obj) * max(0, int(n_devices) - 1)
    s.record(site=f"split_grid:{what}", axis=axis,
             direction="replicate", nbytes=nbytes, policy="split_grid",
             dtype="", mesh=str(n_devices), n_slabs=1)
    omet.inc("ici_bytes_total", float(nbytes), axis=axis,
             policy="split_grid")


def _ledger_rows() -> List[dict]:
    """Ledger rows in TRACE (insertion) order — the order the
    invocation grouping's latest-wins rule depends on."""
    with _entries_lock:
        items = list(_entries.items())
    return [{"site": k[0], "axis": k[1], "direction": k[2],
             "bytes": k[3], "policy": k[4], "dtype": k[5], "mesh": k[6],
             "n_slabs": k[7], "traces": c} for k, c in items]


def ledger() -> List[dict]:
    """Current ledger rows (largest first; process-lifetime entries)."""
    return sorted(_ledger_rows(), key=lambda r: -r["bytes"])


def _invocation_rows(site_prefix: str = "") -> List[dict]:
    """Ledger exchange rows eligible for per-invocation attribution, in
    trace order (latest-wins grouping depends on it): replication rows
    excluded (per-call, not per-invocation), sites filtered by
    prefix."""
    return [r for r in _ledger_rows()
            if r["direction"] != "replicate"
            and (not site_prefix or r["site"].startswith(site_prefix))]


def _invocation_groups(site_prefix: str = "") -> Dict[tuple, dict]:
    """Ledger exchange rows grouped by (site, policy, dtype, mesh) —
    the identity of ONE traced stencil configuration.  Within a group,
    one invocation performs at most one exchange per (axis, direction,
    n_slabs); a second entry under the same slot means the site was
    re-traced at a DIFFERENT lattice shape (the entries are process-
    lifetime, like the jit cache), and the LATEST one wins — summing
    shapes would bill one invocation for every size the worker ever
    served.  The surviving slots sum into the invocation's bytes.
    Rows across groups are ALTERNATIVES, never additive: the parity
    stencils are symmetric, an auto race traces both policies, a
    mixed-precision solve traces both dtypes — each invocation runs
    exactly one of them."""
    groups: Dict[tuple, dict] = {}
    for r in _invocation_rows(site_prefix):
        key = (r["site"], r["policy"], r["dtype"], r["mesh"])
        slot = (r["axis"], r["direction"], r["n_slabs"])
        # _entries is insertion-ordered, so a later-traced shape's row
        # replaces the earlier one here
        groups.setdefault(key, {})[slot] = r
    return {key: {"bytes": sum(r["bytes"] for r in slots.values()),
                  "rows": list(slots.values())}
            for key, slots in groups.items()}


def per_invocation_bytes(site_prefix: str = "") -> int:
    """Per-device ICI bytes of ONE stencil invocation: the max
    (site, policy, dtype) group total (see _invocation_groups for why
    max, not sum).  ``site_prefix`` confines the model to one operator
    family's stencils."""
    groups = _invocation_groups(site_prefix)
    return max((g["bytes"] for g in groups.values()), default=0)


def attribute_solve(form: str, applies: float, dslash_per_apply: float,
                    seconds: float, label: str = "",
                    site_prefix: str = "") -> Optional[dict]:
    """Join the ledger's per-invocation model with a solve's measured
    applies/seconds into one ICI roofline row (the HBM-roofline sibling
    obs/roofline.py records): total bytes = per-invocation bytes x
    applies x dslash_per_apply x mesh devices, ``gbps`` = aggregate
    bytes/seconds, and ``pct_nominal_ici`` = the PER-DEVICE rate vs
    ICI_NOMINAL_GBPS (devices send concurrently — the per-chip link
    saturates on per-device traffic).  Appended to the session rows
    (dumped into roofline.tsv by its save()) + an ``ici_solve`` trace
    event + the ``ici_bytes_total`` counter.  None when the ledger is
    off or holds no exchange rows."""
    s = _session
    if s is None:
        return None
    groups = _invocation_groups(site_prefix)
    if not groups:
        return None
    # the solve executed ONE stencil configuration per invocation; take
    # the max-bytes group(s).  Racing candidates move identical slabs,
    # so ties across policies are expected — the label then names all
    # tied policies (the ledger cannot know the race winner), but the
    # TOTAL is counted once, never split across policies a solve may
    # not have executed.
    per_inv = max(g["bytes"] for g in groups.values())
    win_rows = [r for g in groups.values()
                if g["bytes"] == per_inv for r in g["rows"]]
    policies = sorted({r["policy"] for r in win_rows})
    axes = sorted({r["axis"] for r in win_rows})
    # devices participating: every exchange row is per-device; the mesh
    # column carries the partition sizes — total ICI traffic is the
    # per-device bytes summed over devices
    n_dev = 1
    for r in win_rows:
        try:
            n = 1
            for p in r["mesh"].split("x"):
                if p:
                    n *= int(p)
            n_dev = max(n_dev, n)
        except ValueError:
            pass
    total = per_inv * float(applies) * float(dslash_per_apply) * n_dev
    gbps = (total / seconds / 1e9) if seconds > 0 else 0.0
    # saturation percentage is PER DEVICE: every device sends its
    # per_inv bytes concurrently, so the per-chip nominal link compares
    # against the per-device rate — dividing the mesh-aggregate total
    # by one chip's nominal would overstate saturation n_dev-fold
    gbps_dev = gbps / n_dev
    pol_label = "+".join(policies)
    row = {"form": f"ici:{form}", "label": label,
           "ici_bytes": int(total),
           "bytes_per_invocation_per_device": int(per_inv),
           "applies": float(applies),
           "dslash_per_apply": float(dslash_per_apply),
           "devices": n_dev, "seconds": round(float(seconds), 6),
           "gbps": round(gbps, 3),
           "gbps_per_device": round(gbps_dev, 3),
           "pct_nominal_ici": round(100.0 * gbps_dev
                                    / ICI_NOMINAL_GBPS, 2),
           "policy": pol_label,
           "axes": "+".join(axes)}
    # per-axis breakdown from ONE representative max group (the tied
    # groups are alternatives moving identical slabs, so any one of
    # them carries the per-axis split; summing the union would
    # double-count ties).  Multi-axis meshes additionally get one
    # ici:{form}:{axis} sub-row per partitioned axis so the roofline
    # dump shows where the bytes go.
    rep = next(g for g in groups.values() if g["bytes"] == per_inv)
    axis_bytes: Dict[str, int] = {}
    for r in rep["rows"]:
        axis_bytes[r["axis"]] = axis_bytes.get(r["axis"], 0) + r["bytes"]
    sub_rows = []
    if len(axis_bytes) > 1:
        for ax in sorted(axis_bytes):
            b_ax = axis_bytes[ax]
            t_ax = b_ax * float(applies) * float(dslash_per_apply) * n_dev
            g_ax = (t_ax / seconds / 1e9) if seconds > 0 else 0.0
            sub_rows.append({
                "form": f"ici:{form}:{ax}", "label": label,
                "ici_bytes": int(t_ax),
                "bytes_per_invocation_per_device": int(b_ax),
                "applies": float(applies),
                "dslash_per_apply": float(dslash_per_apply),
                "devices": n_dev, "seconds": round(float(seconds), 6),
                "gbps": round(g_ax, 3),
                "gbps_per_device": round(g_ax / n_dev, 3),
                "pct_nominal_ici": round(100.0 * g_ax / n_dev
                                         / ICI_NOMINAL_GBPS, 2),
                "policy": pol_label, "axes": ax})
    with s.lock:
        s.solve_rows.append(row)
        s.solve_rows.extend(sub_rows)
    from . import metrics as omet
    from . import trace as otr
    otr.event("ici_solve", cat="comms", **row)
    # the counter splits per axis (ici_bytes_total{axis, policy}); the
    # per-axis totals sum exactly to the row's mesh-aggregate bytes
    for ax in sorted(axis_bytes):
        t_ax = (axis_bytes[ax] * float(applies)
                * float(dslash_per_apply) * n_dev)
        omet.inc("ici_bytes_total", float(t_ax), axis=ax,
                 policy=pol_label)
    return row


def solve_rows() -> List[dict]:
    s = _session
    if s is None:
        return []
    with s.lock:
        return list(s.solve_rows)


def reset_rows():
    """Drop the accumulated SOLVE rows but keep the session and the
    process-lifetime exchange entries (an incremental dump-then-reset
    for harnesses that flush roofline.tsv mid-session)."""
    s = _session
    if s is None:
        return
    with s.lock:
        s.solve_rows.clear()


# -- analytic halo models (notice/bench consumers) --------------------------

def wilson_eo_halo_model(dims, mesh_shape, itemsize: int = 4) -> dict:
    """Per-dslash-invocation ICI bytes of the sharded eo Wilson policies
    from first principles — the number the ledger must reproduce from
    the seams, and what the QUDA_TPU_SHARDED_POLICY race notice quotes
    next to its timing winner.  ``dims`` = global (T, Z, Y, X),
    ``mesh_shape`` = (n_t, n_z) or the full (n_t, n_z, n_y, n_x).  Both
    v2 and v3 exchange exactly two psi-shaped faces per partitioned
    direction (one ``exchange`` call), so the model is form-independent:
    2 x face bytes per axis.  t/z faces are whole planes, the y face is
    one local row strip, and the x face is one local COLUMN stack of xh
    slots (the eo slot-select reaches one column, w=1) — strided, which
    is why x is the cheapest axis per device but ppermute-only."""
    T, Z, Y, X = dims
    n_t, n_z, n_y, n_x = tuple(mesh_shape) + (1,) * (4 - len(mesh_shape))
    t_l, z_l = T // n_t, Z // n_z
    y_l, xh_l = Y // n_y, (X // 2) // n_x
    axes = {}
    per_device = 0
    for name, n, face_elems in (("t", n_t, 4 * 3 * 2 * z_l * y_l * xh_l),
                                ("z", n_z, 4 * 3 * 2 * t_l * y_l * xh_l),
                                ("y", n_y, 4 * 3 * 2 * t_l * z_l * xh_l),
                                ("x", n_x, 4 * 3 * 2 * t_l * z_l * y_l)):
        if n <= 1:
            continue
        b = 2 * face_elems * itemsize
        axes[name] = b
        per_device += b
    return {"per_device": per_device,
            "total": per_device * n_t * n_z * n_y * n_x, "axes": axes}
