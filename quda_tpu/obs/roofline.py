"""Roofline attribution: PERF.md traffic models joined with wall-times.

Reference behavior: QPhiX/QUDA performance work reports every kernel as
achieved-vs-roofline (arXiv:1510.08879; QUDA's per-kernel GFLOPS+GB/s
profiler tsv, lib/tune.cpp:528-610).  PERF.md rounds 2-8 derived those
numbers BY HAND from ad-hoc bench prints; this module is the single
home for (a) the per-site flops/bytes models of every kernel form and
(b) the arithmetic joining them with measured seconds into
achieved-GFLOPS / achieved-BW / %-of-demonstrated-peak rows — the bench
harness and the API solves consume these helpers instead of private
math, so a model update lands everywhere at once.

Demonstrated peaks (NOT theoretical): the best single-chip numbers this
codebase has measured (PERF.md round 5, TPU v5 lite, 24^4 Wilson v2
f32): 5,673 GFLOPS kernel rate and ~4.8 TB/s effective bandwidth.  The
percent-of-peak columns answer "how much of what this hardware has
already demonstrated does this measurement reach" — on other platforms
(CPU CI) they are still computed but meaningless, and callers should
gate on platform before quoting them.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

# best demonstrated single-chip rates (PERF.md round 5 measurement)
DEMONSTRATED_PEAK_GFLOPS = 5673.0
DEMONSTRATED_PEAK_GBPS = 4800.0

# Per-site flops / bytes models (f32 pairs, per UPDATED site, one
# operator application).  Sources: PERF.md round 2 (v2 traffic table),
# round 3 (v3 scatter table), round 4 (reconstruct-12), round 7 (MRHS
# 576 + 576/N), round 8 (staggered fat+Naik 1512 B).  ``bytes_per_site``
# None = no credible traffic model for the form (no BW attribution).
KERNEL_MODELS: Dict[str, dict] = {
    # gather-form v2: psi 5x96 + out 96 + gauge 288 fwd + 288 bw copy
    "wilson_v2": {"flops_per_site": 1320, "bytes_per_site": 1152},
    # v2 with reconstruct-12 links: BOTH resident link arrays (forward
    # and the pre-shifted backward copy, built from the compressed
    # arrays) shrink 288 -> 192 B/site, so 1152 - 2*96
    "wilson_v2_r12": {"flops_per_site": 1320, "bytes_per_site": 960},
    # scatter-form v3: psi ~312 + gauge 288 + U_t plane ~81 + out 96
    "wilson_v3": {"flops_per_site": 1320, "bytes_per_site": 777},
    # v3 + in-kernel reconstruct-12 link decompression
    "wilson_v3_r12": {"flops_per_site": 1320, "bytes_per_site": 684},
    # MRHS v2: psi 480 + out 96 + gauge 576/N per RHS (nrhs-dependent)
    "wilson_mrhs": {"flops_per_site": 1320,
                    "bytes_per_site": lambda nrhs: 576.0 + 576.0 / nrhs},
    # precision storage forms (PERF.md round 16).  r12f = r12 storage
    # + copy-free scatter backward on the gather psi path: gauge reads
    # are g_here 192 + g_there xyz 144 + g_t plane 48 = 384 — exactly
    # the r12 forward+backward-copy 2x192, so traffic EQUALS wilson_v2
    # _r12; the win is residency (no 192 B/site backward array), not
    # bandwidth.  684 B/site remains wilson_v3_r12's number.
    "wilson_v2_r12f": {"flops_per_site": 1320, "bytes_per_site": 960},
    # fold: re/im interleaved into sublane rows — same logical bytes as
    # v2 at f32 (the fold changes tile SHAPE, not byte count)...
    "wilson_v2_fold": {"flops_per_site": 1320, "bytes_per_site": 1152},
    # ...but at bf16 storage the fold makes every (16,128) tile FULL
    # (no half-empty sublane pads), so the moved bytes finally match
    # the logical 2-byte element count: 1152/2
    "wilson_v2_bf16_fold": {"flops_per_site": 1320,
                            "bytes_per_site": 576},
    # bf16 bz=Z full-block admission: same logical bf16 bytes; the row
    # exists because the block schedule (one z-block, single-buffered
    # when the budget rejects double buffering) is a distinct kernel
    # configuration whose measured point must not silently drift into
    # the blocked-bf16 attribution
    "wilson_v2_bf16_bzfull": {"flops_per_site": 1320,
                              "bytes_per_site": 576},
    # int8 block-float links (r12f-style here+there reads, no resident
    # backward copy): mantissas 4 dirs x 9 complex x 2 x 1 B = 72 for
    # EACH of the here/there arrays + one f32 scale per (dir, site) x2
    # arrays = 2x16 + psi 5x96 + out 96 -> 72+72+16+16+480+96 = 752
    "wilson_v2_int8": {"flops_per_site": 1320, "bytes_per_site": 752},
    # sharded v2 interior (halo transport excluded from the model: it is
    # policy-dependent and O(surface); the trace carries the policy);
    # r12 variants mirror the single-chip subtraction
    "wilson_sharded_v2": {"flops_per_site": 1320, "bytes_per_site": 1152},
    "wilson_sharded_v2_r12": {"flops_per_site": 1320,
                              "bytes_per_site": 960},
    "wilson_sharded_v3": {"flops_per_site": 1320, "bytes_per_site": 777},
    "wilson_sharded_v3_r12": {"flops_per_site": 1320,
                              "bytes_per_site": 684},
    # XLA pair stencil: flop model only (XLA's fusion choices make a
    # static traffic model dishonest)
    "wilson_xla": {"flops_per_site": 1320, "bytes_per_site": None},
    # improved staggered fat+Naik two-pass gather kernel (PERF.md round
    # 8): per pass psi 5x24 + fwd links 288 + resident backward copy 288
    # + out 24 = 720, two passes + the XLA sum pass (2x24 read + 24
    # write)
    "staggered_fat_naik": {"flops_per_site": 1146,
                           "bytes_per_site": 1512},
    # plain staggered (fat hop set only): ONE gather pass, no sum pass
    "staggered_fat": {"flops_per_site": 570, "bytes_per_site": 720},
    # scatter-form (v3) staggered: no backward-link copies; per pass
    # psi 3x24 + links 288 + U_t plane 72 + out 24 = 456 (+ the sum
    # pass for the improved two-pass form)
    "staggered_fat_v3": {"flops_per_site": 570, "bytes_per_site": 456},
    "staggered_fat_naik_v3": {"flops_per_site": 1146,
                              "bytes_per_site": 984},
    # FUSED single-pass fat+Naik (round 10 tentpole): one launch, one
    # psi read, no XLA sum pass, no backward-link arrays — psi 5x24 +
    # fat/long fwd links 2x288 + U_t planes at t-1/t-3 2x72 + out 24
    # (z boundary rows are O(1/bz)).  1.75x less traffic than two-pass
    "staggered_fat_naik_fused": {"flops_per_site": 1146,
                                 "bytes_per_site": 864},
    # fused + Naik-link recon-12 (PERF.md round 16): the LONG links are
    # ±SU(3) after KS-phase folding, so only that hop set compresses
    # (fat links are smeared sums — not unitary, no reconstruction):
    # long fwd 288 -> 192 (-96), long t-plane 72 -> 48 (-24), plus the
    # streamed f32 sign plane 4x4 B = 16 and its t-plane 4:
    # 864 - 96 - 24 + 16 + 4 = 764
    "staggered_fat_naik_fused_r12": {"flops_per_site": 1146,
                                     "bytes_per_site": 764},
    # fused + re/im sublane fold: full R=3 rows, same logical bytes —
    # the row exists for the bf16 full-tile A/B (tile shape, not byte
    # count, is what changes; measured points must not alias the
    # unfolded fused attribution)
    "staggered_fat_naik_fused_fold": {"flops_per_site": 1146,
                                      "bytes_per_site": 864},
    # MRHS staggered (gather two-pass body, links amortized over N):
    # improved = 2 passes x (psi 120 + out 24) + sum 72 + 1152/N links;
    # fat-only = one pass, no sum
    "staggered_mrhs": {"flops_per_site": 1146,
                       "bytes_per_site": lambda nrhs: 360.0
                       + 1152.0 / nrhs},
    "staggered_fat_mrhs": {"flops_per_site": 570,
                           "bytes_per_site": lambda nrhs: 144.0
                           + 576.0 / nrhs},
    # sharded staggered eo interiors (two-pass gather form — the mesh
    # default, models/staggered.py; halo transport excluded as for the
    # Wilson sharded rows: policy-dependent and O(surface))
    "staggered_sharded_fat": {"flops_per_site": 570,
                              "bytes_per_site": 720},
    "staggered_sharded_fat_naik": {"flops_per_site": 1146,
                                   "bytes_per_site": 1512},
    # XLA pair stencil: flop model only (same honesty rule as wilson_xla)
    "staggered_xla": {"flops_per_site": 1146, "bytes_per_site": None},
    # fused MG coarse-stencil kernel (ops/coarse_pallas.py) at the
    # CANONICAL probe size n_vec=4 (Nc=8, embedding dim E=16): 9 real
    # ExE matvecs = 18*E^2 flops/site; links once (36*E^2 B) + the
    # input and its 8 pre-rolled neighbour copies (36*E B) + out (4*E).
    # Nc-parametric attribution goes through
    # ops/coarse_pallas.coarse_model(nc) — this row is the drift-lint
    # anchor (obs/costmodel.py family 'mg_coarse')
    "mg_coarse_pallas": {"flops_per_site": 4608, "bytes_per_site": 9856},
    # -- operator-zoo fused forms (PERF.md round 18) --------------------
    # Clover PC fused kernel (ops/clover_pallas): per fused pass the v2
    # hop operand set (psi 5x96 + out 96 + fwd/bw links 2x288) plus the
    # resident chiral pair blocks streamed per tile — 2x6x6 complex f32
    # = 576 B/site (288 at bf16).  flops: hop 1320 + one 2x(6x6)
    # complex block matvec 504
    "clover_pallas": {"flops_per_site": 1824, "bytes_per_site": 1728},
    "clover_pallas_r12": {"flops_per_site": 1824,
                          "bytes_per_site": 1536},
    # MRHS fused clover: links AND blocks amortize over the RHS stream
    # (both index maps ignore n) — psi 480 + out 96 + (576+576)/N
    "clover_pallas_mrhs": {
        "flops_per_site": 1824,
        "bytes_per_site": lambda nrhs: 576.0 + 1152.0 / nrhs},
    # twisted mass: the twist is two STATIC scalars compiled into the
    # epilogue — zero extra traffic over the v2 hop; flops: hop 1320 +
    # twist rotate/combine 96
    "twisted_mass_pallas": {"flops_per_site": 1416,
                            "bytes_per_site": 1152},
    "twisted_mass_pallas_r12": {"flops_per_site": 1416,
                                "bytes_per_site": 960},
    "twisted_mass_pallas_mrhs": {
        "flops_per_site": 1416,
        "bytes_per_site": lambda nrhs: 576.0 + 576.0 / nrhs},
    # twisted clover: dense block term (the twist is folded into the
    # inverse blocks / added in-register) — clover traffic and flops
    "twisted_clover_pallas": {"flops_per_site": 1824,
                              "bytes_per_site": 1728},
    "twisted_clover_pallas_r12": {"flops_per_site": 1824,
                                  "bytes_per_site": 1536},
    "twisted_clover_pallas_mrhs": {
        "flops_per_site": 1824,
        "bytes_per_site": lambda nrhs: 576.0 + 1152.0 / nrhs},
    # Ls-batched DWF/Möbius 4d hop (ops/dwf_pallas): per UPDATED 4d
    # site per dslash invocation with Ls baked in — Ls spinor planes
    # (Ls x 576) stream through ONE gauge-tile fetch (576), i.e.
    # 576 + 576/Ls per plane.  flops Ls x 1320.  Only Ls in {4, 8} get
    # traffic rows: at Ls >= 12 the honest model (psi still read 5x per
    # plane) exceeds the BYTES_REREAD_MAX re-read ceiling over the
    # operand floor, so larger Ls report flops-only via 'dwf_pallas'
    "dwf_ls4_pallas": {"flops_per_site": 5280, "bytes_per_site": 2880},
    "dwf_ls8_pallas": {"flops_per_site": 10560,
                       "bytes_per_site": 5184},
    # Ls outside the registered set: flops come from the operator
    # (flops_per_site override), no static traffic claim
    "dwf_pallas": {"flops_per_site": None, "bytes_per_site": None},
    # multi-source Möbius: N sources x Ls planes share one gauge tile;
    # bytes honesty as above (amortization shown by the bench row, not
    # a static model)
    "dwf_ls8_pallas_mrhs": {"flops_per_site": 10560,
                            "bytes_per_site": None},
    # staged XLA compositions: flop models only (same honesty rule as
    # wilson_xla — XLA's fusion choices make a traffic claim dishonest)
    "clover_xla": {"flops_per_site": 1824, "bytes_per_site": None},
    "twisted_xla": {"flops_per_site": 1416, "bytes_per_site": None},
    "twisted_clover_xla": {"flops_per_site": 1824,
                           "bytes_per_site": None},
    "dwf_xla": {"flops_per_site": None, "bytes_per_site": None},
    # operator-supplied flop count, no traffic model
    "generic": {"flops_per_site": None, "bytes_per_site": None},
}


def model(form: str, nrhs: int = 1, flops_per_site: Optional[float] = None
          ) -> tuple:
    """(flops_per_site, bytes_per_site or None) for a kernel form; a
    caller-supplied flops_per_site overrides (the 'generic' route)."""
    m = KERNEL_MODELS.get(form, KERNEL_MODELS["generic"])
    fps = m["flops_per_site"] if flops_per_site is None else flops_per_site
    bps = m["bytes_per_site"]
    if callable(bps):
        bps = bps(max(1, int(nrhs)))
    return fps, bps


def achieved(flops: float, bytes_: float, secs: float) -> dict:
    """Total flops/bytes + seconds -> {'gflops', 'gbps'} (rounded the
    way bench rows record them).  Non-positive seconds -> zeros: the
    bench gate rejects such rows; this helper must not divide by it."""
    if not (secs > 0):
        return {"gflops": 0.0, "gbps": 0.0}
    return {"gflops": round(flops / secs / 1e9, 2),
            "gbps": round(bytes_ / secs / 1e9, 2)}


def attribute(form: str, sites: int, applies: float, seconds: float,
              nrhs: int = 1, flops_per_site: Optional[float] = None,
              dslash_per_apply: float = 1.0, **extra) -> dict:
    """One roofline row: a kernel form applied ``applies`` times over
    ``sites`` updated sites (per RHS) in ``seconds`` wall.

    Units: ``flops_per_site`` (caller-supplied or the model's) is per
    APPLY per site, but ``bytes_per_site`` in KERNEL_MODELS is per
    DSLASH INVOCATION per site — a composite operator that runs several
    dslash per apply (the even/odd-preconditioned M is two) must pass
    ``dslash_per_apply`` so the traffic side is charged once per
    invocation; leaving it at 1 under-reports achieved BW by that
    factor.

    Returns {form, sites, applies, nrhs, seconds, flops, bytes,
    gflops, gbps, pct_peak_gflops, pct_peak_bw, **extra}; the bytes/BW
    columns are None for forms without a traffic model."""
    fps, bps = model(form, nrhs, flops_per_site)
    fps = float(fps or 0.0)
    flops = fps * sites * applies * max(1, int(nrhs))
    bts = (bps * sites * applies * dslash_per_apply * max(1, int(nrhs))
           if bps is not None else None)
    th = achieved(flops, bts or 0.0, seconds)
    row = {"form": form, "sites": int(sites), "applies": float(applies),
           "nrhs": int(nrhs),
           "dslash_per_apply": float(dslash_per_apply),
           "seconds": round(float(seconds), 6),
           "flops_per_site": fps, "bytes_per_site": bps,
           "gflops": th["gflops"],
           "gbps": th["gbps"] if bts is not None else None,
           "pct_peak_gflops": round(100.0 * th["gflops"]
                                    / DEMONSTRATED_PEAK_GFLOPS, 2),
           "pct_peak_bw": (round(100.0 * th["gbps"]
                                 / DEMONSTRATED_PEAK_GBPS, 2)
                           if bts is not None else None)}
    row.update(extra)
    return row


# -- per-process accumulation (flushed by end_quda) -------------------------

_rows: List[dict] = []
_dropped = 0
_MAX_ROWS = 10000
# the solve-service worker thread and the calling thread both record
# rows (the obs/memory lock discipline; a lost append is a silently
# thinner roofline.tsv)
_rows_lock = threading.Lock()


def record(form: str, sites: int, applies: float, seconds: float,
           nrhs: int = 1, flops_per_site: Optional[float] = None,
           dslash_per_apply: float = 1.0, **extra) -> dict:
    """attribute() + accumulate for the end_quda roofline.tsv dump +
    mirror as a trace event (auditable next to the spans it times)."""
    global _dropped
    row = attribute(form, sites, applies, seconds, nrhs=nrhs,
                    flops_per_site=flops_per_site,
                    dslash_per_apply=dslash_per_apply, **extra)
    with _rows_lock:
        if len(_rows) < _MAX_ROWS:
            _rows.append(row)
        else:
            # no silent caps (PERF.md round-9 rule): count what the tsv
            # will be missing so save() can mark the truncation
            _dropped += 1
    from . import trace as otr
    otr.event("roofline", cat="roofline", **row)
    return row


def rows() -> List[dict]:
    with _rows_lock:
        return list(_rows)


def reset():
    global _dropped
    with _rows_lock:
        _rows.clear()
        _dropped = 0


def save(fname: str = "roofline.tsv",
         path: Optional[str] = None) -> Optional[str]:
    """Dump accumulated rows as a tsv under ``path`` (default: the
    resource path — the profile_N.tsv sibling); None when no path or no
    rows.  The ICI attribution rows of the comms ledger (obs/comms.py
    ``attribute_solve``) are appended alongside the HBM rows: same
    form/seconds/gbps columns, percent column against the nominal ICI
    link bandwidth instead of the HBM demonstrated peak."""
    import os

    from . import comms as ocomms
    from ..utils import config as qconf
    path = path or qconf.get("QUDA_TPU_RESOURCE_PATH", fresh=True)
    ici_rows = ocomms.solve_rows()
    with _rows_lock:
        hbm_rows = list(_rows)
        dropped = _dropped
    if not path or not (hbm_rows or ici_rows):
        return None
    os.makedirs(path, exist_ok=True)
    cols = ("form", "sites", "applies", "nrhs", "seconds", "gflops",
            "gbps", "pct_peak_gflops", "pct_peak_bw", "label")
    out = os.path.join(path, fname)
    with open(out, "w") as fh:
        fh.write("\t".join(cols) + "\n")
        for r in hbm_rows:
            fh.write("\t".join(str(r.get(c, "")) for c in cols) + "\n")
        if dropped:
            fh.write(f"# TRUNCATED: {dropped} rows past the "
                     f"{_MAX_ROWS}-row cap were dropped\n")
        if ici_rows:
            fh.write(f"# ICI attribution (comms ledger; gbps = mesh-"
                     f"aggregate, pct = PER-DEVICE rate vs the nominal "
                     f"{ocomms.ICI_NOMINAL_GBPS:g} GB/s per-chip link, "
                     "NOT the HBM peak)\n")
            for r in ici_rows:
                fh.write("\t".join(str(v) for v in (
                    r["form"], r["ici_bytes"], r["applies"], "",
                    r["seconds"], "", r["gbps"], "",
                    r["pct_nominal_ici"],
                    f"{r['label']}|{r['policy']}|axes={r['axes']}"
                    f"|devices={r['devices']}")) + "\n")
    return out
