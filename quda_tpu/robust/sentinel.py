"""Shared in-loop breakdown sentinel for the Krylov solvers.

Reference behavior: the reference's solvers guard their compiled hot
loops against numerical breakdown — reliable updates recompute the true
residual (include/reliable_updates.h), the CG family checks pivots, and
the block solvers deflate singular Gram systems — so a solve that goes
non-finite exits with a diagnosable state instead of spinning NaN
arithmetic to maxiter ("A Framework for Lattice QCD Calculations on
GPUs", arXiv:1408.5925, production posture).  Before this module only
``solvers/block.block_cg_pairs`` had a finiteness guard; every other
while_loop would happily burn maxiter dslash applies on NaNs.

This module generalises that guard into ONE predicate threaded through
the loop carries of cg/fused_iter, mixed.cg_reliable[_df], bicgstab,
multishift, block and the small gcr-family loops:

* **non-finite residual** — |r|^2 is NaN/Inf (SDC, overflow, a poisoned
  operand);
* **pivot breakdown** — a CG-family denominator (pAp) non-finite or
  <= 0: the operator is not behaving HPD on this Krylov space;
* **stagnation** — the residual has not improved for
  QUDA_TPU_ROBUST_STAGNATION consecutive convergence checks (opt-in,
  0 = disabled: plateaus are workload-dependent).

Zero-overhead contract (the obs no-op-span discipline): with
``QUDA_TPU_ROBUST=off`` :func:`make` returns ``None`` and the solvers
build EXACTLY the loop they build today — same carry structure, same
ops, bit-identical compiled solve (pinned by tests/test_robust.py's
raising-stub test).  When active, the carry gains a three-scalar state
``(code, best_r2, checks_since_improvement)`` and the loop cond gains
one ``code == 0`` conjunct; the first breakdown is sticky and is
surfaced as ``SolverResult.breakdown`` for the API layer's verified
exits and escalation ladder (robust/escalate.py).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

# breakdown reason codes (static ints so they compile into the loop)
NONE = 0
NONFINITE = 1          # |r|^2 went NaN/Inf
PIVOT = 2              # CG denominator (pAp) non-finite or <= 0
STAGNATION = 3         # no residual improvement for N checks

REASONS = {NONE: "none", NONFINITE: "nonfinite", PIVOT: "pivot",
           STAGNATION: "stagnation"}


def mode() -> str:
    """Current QUDA_TPU_ROBUST level: 'off' | 'verify' | 'escalate'."""
    from ..utils import config as qconf
    return str(qconf.get("QUDA_TPU_ROBUST", fresh=True)) or "off"


def active() -> bool:
    return mode() != "off"


def reason(code) -> str:
    """Host-side name of a breakdown code (unknown codes stringify)."""
    return REASONS.get(int(code), f"code{int(code)}")


def make(stagnation_checks: Optional[int] = None) -> Optional["Sentinel"]:
    """The per-solve sentinel, or ``None`` when QUDA_TPU_ROBUST=off —
    the None path is the zero-overhead contract: callers guard every
    sentinel touch with ``if sent is not None`` so the disabled solve
    traces exactly the pre-sentinel computation."""
    if not active():
        return None
    if stagnation_checks is None:
        from ..utils import config as qconf
        stagnation_checks = int(qconf.get("QUDA_TPU_ROBUST_STAGNATION",
                                          fresh=True))
    # flight-recorder marker (host-side, no-op when QUDA_TPU_FLIGHT is
    # off): the ring shows which solves ran sentinel-guarded, so a
    # postmortem tail distinguishes "breakdown detected" from "nothing
    # was watching" — the trip itself arrives via the
    # breakdown_detected trace-event tap
    from ..obs import flight as ofl
    ofl.record("sentinel_armed", cat="robust", mode=mode(),
               stagnation=stagnation_checks)
    return Sentinel(stagnation_checks)


def finalize(sent, state, conv):
    """Shared solver-exit epilogue: returns ``(converged, breakdown)``
    where a tripped sentinel masks the convergence claim (a NaN
    residual compares False against the CONTINUE criterion ``r2 >
    stop``, so the naive not-not-done exit would report a poisoned
    solve as converged) and exposes the typed code.  ``sent is None``
    (QUDA_TPU_ROBUST=off) passes ``conv`` through untouched with
    ``breakdown=None`` — zero ops added."""
    if sent is None:
        return conv, None
    code = sent.code(state)
    return jnp.logical_and(conv, code == NONE), code


class Sentinel:
    """In-loop breakdown predicate over a (code, best_r2, since) state
    tuple.  ``init`` seeds the state from the initial residual norm,
    ``step`` runs once per convergence check inside the loop body, and
    ``ok`` is the extra while_loop cond conjunct.  The first non-NONE
    code is sticky so the exit state names the ORIGINAL failure, not a
    downstream symptom."""

    __slots__ = ("stagnation_checks",)

    def __init__(self, stagnation_checks: int = 0):
        self.stagnation_checks = int(stagnation_checks)

    def init(self, r2):
        r2 = jnp.asarray(r2)
        return (jnp.int32(NONE), r2, jnp.int32(0))

    def step(self, state, r2, denom=None):
        """Advance the state with this check point's residual norm (a
        scalar; batched solvers pass an aggregate that propagates any
        lane's NaN, e.g. the sum) and optionally the CG pivot
        denominator pAp (HPD solves only — it must be finite and
        positive there)."""
        code, best, since = state
        r2 = jnp.asarray(r2)
        nonfin = jnp.logical_not(jnp.isfinite(r2))
        if denom is not None:
            # a FINITE non-positive pivot is the PIVOT class (the
            # operator is not behaving HPD — the original cause, which
            # this same step's r2 overflow would otherwise mask); a
            # non-finite denominator is just more non-finiteness
            d = jnp.asarray(denom)
            d_fin = jnp.isfinite(d)
            pivot = jnp.logical_and(d_fin, d <= 0)
            nonfin = jnp.logical_or(nonfin, jnp.logical_not(d_fin))
            new = jnp.where(pivot, PIVOT,
                            jnp.where(nonfin, NONFINITE, NONE))
        else:
            new = jnp.where(nonfin, NONFINITE, NONE)
        improved = r2 < best
        best = jnp.where(improved, r2, best)
        since = jnp.where(improved, 0, since + 1).astype(jnp.int32)
        if self.stagnation_checks > 0:
            stalled = since >= self.stagnation_checks
            new = jnp.where(jnp.logical_and(new == NONE, stalled),
                            STAGNATION, new)
        code = jnp.where(code == NONE, new, code).astype(jnp.int32)
        return (code, best, since)

    def ok(self, state):
        return state[0] == NONE

    @staticmethod
    def code(state):
        """The int32 breakdown code of an exited state (NONE = clean)."""
        return state[0]
