"""Bounded escalation ladder for API solves.

Reference behavior: the reference's production posture is that a failing
kernel candidate or an unconverged sloppy solve is an EVENT TO RECOVER
FROM, not a crash — the autotuner skips throwing launches (lib/tune.cpp)
and the mixed-precision solvers re-anchor on the precise operator when
the sloppy system drifts (include/reliable_updates.h).  This module is
the solve-level generalisation: when an attempt breaks down (sentinel),
fails verification (verified exit), or cannot even construct its
operator (pallas compile error, VMEM budget overflow, sharded-policy
race crash), retry through a bounded, configurable ladder of
progressively safer configurations:

1. **as-requested** — whatever the knobs/param selected;
2. **xla** — demote QUDA_TPU_PALLAS to '0': the XLA stencil form, no
   hand-written kernels, no pallas construction;
3. **df64-reliable** (Wilson CG) — force the extended-precision
   reliable route (QUDA_TPU_DF64=1): the deepest-precision rung; or
   **bicgstab** (other non-Hermitian families) — swap the solver.

Knob demotion uses utils/config.py's scoped override stack, so a rung
never mutates os.environ and the requested configuration is restored
the moment the attempt exits.  Per-attempt provenance lands on
``InvertParam.solve_attempts`` and the final ``solve_status``; every
transition emits ``solve_retry`` / ``solve_degraded`` trace events
(obs/trace.py) next to the solve spans they explain.

Active only at ``QUDA_TPU_ROBUST=escalate``; at 'verify' the statuses
are recorded but nothing retries; at 'off' this module is never called
(invert_quda's dispatch bypasses it entirely).
"""

from __future__ import annotations

import copy
from typing import Callable, List

from . import sentinel as rsent

# InvertParam result fields an attempt produces and the winning attempt
# must publish back onto the caller's param (x_df64_lo is set
# dynamically by the df64 route, hence the getattr guard in _publish)
_RESULT_FIELDS = ("true_res", "iter_count", "secs", "gflops",
                  "true_res_multi", "iter_count_multi", "res_history",
                  "events", "verified_res", "solve_status", "converged",
                  "converged_multi", "x_df64_lo")


def enabled() -> bool:
    return rsent.mode() == "escalate"


def _pm_capture(trigger: str, api: str, param, exc=None):
    """Postmortem hook for the ladder's failure paths (construct
    errors, ladder exhaustion): one bounded bundle per failure under
    the resource path (obs/postmortem.py; no-op when capture is off).
    tests/test_flight_lint.py pins that every failure path in this
    module calls it."""
    from ..obs import postmortem as opm
    opm.capture(trigger, api=api, param=param, exc=exc)


def ladder(param) -> List[dict]:
    """The rung list for this solve: label + knob overrides (+ optional
    solver swap), bounded by QUDA_TPU_ROBUST_MAX_RETRIES.  Rung 0 is
    always the as-requested configuration."""
    from ..utils import config as qconf
    rungs = [{"label": "as-requested", "overrides": {}}]
    # the XLA stencil form: no pallas kernels to construct or compile —
    # the safe form for every operator family
    rungs.append({"label": "xla",
                  "overrides": {"QUDA_TPU_PALLAS": "0"}})
    cg_family = param.inv_type in ("cg", "pcg", "cgnr", "cgne")
    if (param.dslash_type == "wilson" and cg_family
            and not param.num_offset):
        # precision escalation: the df64 (float32-pair) reliable route —
        # certifies the residual below the f32 floor with no pallas
        rungs.append({"label": "df64-reliable",
                      "overrides": {"QUDA_TPU_PALLAS": "0",
                                    "QUDA_TPU_DF64": "1"}})
    elif (cg_family and not param.num_offset
          and param.dslash_type not in ("staggered", "asqtad", "hisq",
                                        "laplace")):
        # solver escalation for the non-Hermitian families: BiCGStab
        # attacks the direct system with a different recurrence (the
        # classic CG-breakdown fallback).  Multishift solves
        # (num_offset) are excluded: their body has no per-inv_type
        # dispatch, so the rung would re-run the identical solve under
        # a false 'bicgstab' provenance
        rungs.append({"label": "bicgstab",
                      "overrides": {"QUDA_TPU_PALLAS": "0"},
                      "inv_type": "bicgstab"})
    cap = max(1, int(qconf.get("QUDA_TPU_ROBUST_MAX_RETRIES",
                               fresh=True)))
    return rungs[:cap]


def _publish(param, attempt_param, attempts):
    for f in _RESULT_FIELDS:
        if hasattr(attempt_param, f):
            setattr(param, f, getattr(attempt_param, f))
    param.solve_attempts = list(attempts)


def run_ladder(body: Callable, source, param, api: str = "invert_quda"):
    """Drive ``body(source, param_copy)`` down the ladder until an
    attempt verifies converged; publish the winner (or the best failed
    attempt, status 'degraded') onto ``param``.  Construction/compile
    exceptions fail the attempt; if EVERY rung raised, the last
    exception propagates (there is no solution to degrade to)."""
    from ..obs import metrics as omet
    from ..obs import trace as otr
    from ..utils import config as qconf
    from ..utils import logging as qlog

    import math

    rungs = ladder(param)
    attempts: List[dict] = []
    # best completed-but-unconverged attempt so far, scored by the
    # VERIFIED residual (smaller wins; non-finite scores worst) — the
    # exhausted-ladder path must publish the best effort, not simply
    # the last rung tried
    best = None          # (score, rung_label, x, attempt_param)
    last_exc = None
    for i, rung in enumerate(rungs):
        p_i = copy.copy(param)
        p_i.solve_attempts = ()
        if rung.get("inv_type"):
            p_i.inv_type = rung["inv_type"]
        try:
            with qconf.overrides(**rung["overrides"]):
                x = body(source, p_i)
        except Exception as e:      # noqa: BLE001 — construction class
            last_exc = e
            attempts.append({"attempt": i, "rung": rung["label"],
                             "status":
                                 f"construct_error:{type(e).__name__}",
                             "error": str(e)[:200]})
            _pm_capture(f"construct_error:{type(e).__name__}", api,
                        p_i, exc=e)
            if i + 1 < len(rungs):
                otr.event("solve_retry", cat="robust", api=api,
                          from_rung=rung["label"],
                          to_rung=rungs[i + 1]["label"],
                          reason=f"construct_error:{type(e).__name__}")
                omet.inc("solve_retries_total", api=api,
                         reason="construct_error")
                qlog.warningq(
                    f"{api}: attempt {i} ({rung['label']}) failed to "
                    f"construct ({type(e).__name__}: {str(e)[:120]}); "
                    f"escalating to {rungs[i + 1]['label']}")
            continue
        status = p_i.solve_status or ("converged" if p_i.converged
                                      else "unconverged")
        attempts.append({"attempt": i, "rung": rung["label"],
                         "status": status, "iters": p_i.iter_count,
                         "verified_res": p_i.verified_res})
        score = (p_i.verified_res
                 if math.isfinite(p_i.verified_res or float("nan"))
                 else float("inf"))
        if best is None or score < best[0]:
            best = (score, rung["label"], x, p_i)
        if status == "converged":
            _publish(param, p_i, attempts)
            if i > 0:
                # served from a fallback rung: the request is answered
                # but the configured fast path is not — say so
                otr.event("solve_degraded", cat="robust", api=api,
                          rung=rung["label"], attempts=i + 1,
                          status=status)
                omet.inc("solve_degraded_total", api=api)
                qlog.warningq(
                    f"{api}: served from escalation rung "
                    f"'{rung['label']}' after {i} failed attempt(s) "
                    "(see InvertParam.solve_attempts)")
            return x
        if i + 1 < len(rungs):
            otr.event("solve_retry", cat="robust", api=api,
                      from_rung=rung["label"],
                      to_rung=rungs[i + 1]["label"], reason=status)
            omet.inc("solve_retries_total", api=api, reason=status)
            qlog.warningq(
                f"{api}: attempt {i} ({rung['label']}) exited "
                f"{status}; escalating to {rungs[i + 1]['label']}")
    if best is None:
        param.solve_attempts = list(attempts)
        param.solve_status = "failed"
        _pm_capture("ladder_exhausted:failed", api, param,
                    exc=last_exc)
        raise last_exc
    _, best_rung, x, p_i = best
    _publish(param, p_i, attempts)
    param.solve_status = f"degraded:{p_i.solve_status}"
    param.converged = False
    _pm_capture(f"ladder_exhausted:{param.solve_status}", api, param)
    otr.event("solve_degraded", cat="robust", api=api, rung=best_rung,
              attempts=len(attempts), status=param.solve_status)
    omet.inc("solve_degraded_total", api=api)
    qlog.warningq(
        f"{api}: escalation ladder exhausted ({len(attempts)} "
        f"attempts); returning the best effort (rung '{best_rung}') "
        f"with status {param.solve_status} — see "
        "InvertParam.solve_attempts")
    return x
