"""Deterministic fault injection: every robustness path testable on CPU.

A breakdown sentinel, verified exit, or escalation rung that is only
exercised when a real chip corrupts a solve is dead code until the worst
possible moment.  This registry arms deterministic faults at the exact
seams the robust/ subsystem guards, so tests/test_robust.py drives every
recovery path end to end on the CPU backend — the QUDA analog is the
autotuner surviving failing kernel candidates by construction, not by
hoping (lib/tune.cpp skips throwing launches).

Sites (``QUDA_TPU_FAULT=<site>:<trigger>[,<site>:<trigger>...]`` or the
programmatic :func:`arm`):

* ``dslash:<k>``       — poison the operator-apply output at iteration k
                         of the next solve (the mid-solve SDC / NaN-spin
                         scenario; consumed at solver trace time);
* ``gauge:<1>``        — poison one link of the next load_gauge_quda
                         input (exercises the gauge-load validation);
* ``pallas_build:<n>`` — raise InjectedFault from the next n pallas
                         operator constructions (the pallas-compile /
                         VMEM-budget / sharded-race failure class);
* ``residual:<f>``     — inflate the next verified residual by factor f
                         (the verification-mismatch escalation trigger).

Every arm is ONE-SHOT (``pallas_build`` counts down its n): after firing
it disarms, so an escalation retry sees a healthy system — transient
faults are the scenario the ladder exists for.  Firings are recorded
(:func:`fired`) and mirrored as ``fault_injected`` trace events so a
drill is auditable in the chrome artifact.

Zero-overhead: with nothing armed every probe is a dict lookup on an
empty dict — no jax ops are ever built.  NEVER set QUDA_TPU_FAULT in
production.
"""

from __future__ import annotations

from typing import List, Optional

SITES = ("dslash", "gauge", "pallas_build", "residual")


class InjectedFault(RuntimeError):
    """Raised by an armed construction-site fault (pallas_build)."""


_armed: dict = {}
_fired: List[dict] = []
_env_parsed = False


def _ensure_env():
    """Parse QUDA_TPU_FAULT once per reset (one-shot consumption is
    stateful; re-parsing per probe would re-arm consumed faults)."""
    global _env_parsed
    if _env_parsed:
        return
    _env_parsed = True
    from ..utils import config as qconf
    spec = str(qconf.get("QUDA_TPU_FAULT", fresh=True))
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, trig = part.partition(":")
        arm(site.strip(), trig.strip() or "1")


def arm(site: str, trigger: str = "1"):
    """Arm one site programmatically (tests).  Unknown sites raise —
    a typoed fault spec silently doing nothing would defeat the drill."""
    if site not in SITES:
        raise ValueError(f"unknown fault site {site!r}; known: {SITES}")
    _armed[site] = str(trigger)


def reset():
    """Disarm everything and forget firings (test isolation).  The env
    spec re-parses on the next probe."""
    global _env_parsed
    _armed.clear()
    _fired.clear()
    _env_parsed = False


def armed(site: str) -> Optional[str]:
    _ensure_env()
    return _armed.get(site)


def fired(site: Optional[str] = None) -> List[dict]:
    """Record of fired faults (for test assertions)."""
    if site is None:
        return list(_fired)
    return [f for f in _fired if f["site"] == site]


def _record(site: str, trigger: str):
    _fired.append({"site": site, "trigger": trigger})
    try:
        from ..obs import trace as otr
        otr.event("fault_injected", cat="robust", site=site,
                  trigger=trigger)
    except Exception:
        pass


def iteration_fault(site: str = "dslash") -> Optional[int]:
    """Consume an iteration-indexed arm at solver TRACE time: returns
    the target iteration k (and disarms) when the site is armed, else
    None.  The solver bakes :func:`corrupt` into this attempt's
    computation; the next attempt traces clean — the one-shot transient
    semantics the escalation ladder recovers from."""
    if not _armed and _env_parsed:
        return None
    _ensure_env()
    trig = _armed.pop(site, None)
    if trig is None:
        return None
    k = int(float(trig))
    _record(site, trig)
    return k


def corrupt(x, k, k_fault: int):
    """Traced poison: the whole array goes NaN when the loop counter k
    equals the armed iteration (jnp.where on a scalar predicate — the
    deterministic, compiled form of a mid-solve SDC)."""
    import jax.numpy as jnp
    bad = jnp.full_like(x, float("nan"))
    return jnp.where(jnp.equal(jnp.asarray(k, jnp.int32),
                               jnp.int32(k_fault)), bad, x)


def maybe_raise(site: str = "pallas_build"):
    """Raise InjectedFault if the construction site is armed; the
    trigger is a countdown (``pallas_build:2`` raises twice)."""
    if not _armed and _env_parsed:
        return
    _ensure_env()
    trig = _armed.get(site)
    if trig is None:
        return
    n = int(float(trig))
    if n <= 1:
        _armed.pop(site, None)
    else:
        _armed[site] = str(n - 1)
    _record(site, trig)
    raise InjectedFault(
        f"injected {site} failure (QUDA_TPU_FAULT drill)")


def maybe_poison_gauge(g):
    """One-shot link poison for the gauge-load validation drill: sets
    the (0,0,...,0) matrix entry of the first direction to NaN."""
    if not _armed and _env_parsed:
        return g
    _ensure_env()
    trig = _armed.pop("gauge", None)
    if trig is None:
        return g
    _record("gauge", trig)
    idx = (0,) * (g.ndim - 2) + (0, 0)
    return g.at[idx].set(float("nan"))


def inflated_residual(value: float, site: str = "residual") -> float:
    """One-shot verified-residual inflation (host-side float) — makes
    the verification step disagree with the solver's own convergence
    claim, driving the 'unverified' escalation path."""
    if not _armed and _env_parsed:
        return value
    _ensure_env()
    trig = _armed.pop(site, None)
    if trig is None:
        return value
    _record(site, trig)
    return float(value) * float(trig)
