"""Solve supervision: breakdown sentinels, verified exits, an
escalation ladder, and deterministic fault injection.

The serving-fleet failure modes this subsystem closes (ROADMAP north
star; the reference's production discipline per arXiv:1408.5925):

* a solve NaN-spinning to maxiter          -> robust/sentinel.py
* a silently-unconverged/wrong answer      -> verified exits
  (interfaces/quda_api.py records verified_res + solve_status)
* a worker crash on pallas construction    -> robust/escalate.py
* all of the above untestable off-chip     -> robust/faultinject.py

One knob drives it: ``QUDA_TPU_ROBUST`` in {off, verify, escalate}
(utils/config.py).  'off' is the default and adds ZERO ops to the
compiled solves (pinned by tests/test_robust.py raising stubs).
"""

from . import escalate, faultinject, sentinel  # noqa: F401

__all__ = ["sentinel", "faultinject", "escalate"]
