#!/bin/bash
# First-TPU-window measurement queue (PERF.md round-4 checklist).
# Probe first; run ONE phase at a time (never two TPU processes); every
# phase is timeout-bounded and appends to measurements_tpu.log.
set -u
cd "$(dirname "$0")"
LOG=measurements_tpu.log
probe=$(timeout 90 python -c "import jax; print(jax.devices()[0].platform)" 2>/dev/null | tail -1)
echo "[$(date -u +%FT%TZ)] probe: ${probe:-none}" | tee -a "$LOG"
if [ "$probe" != "tpu" ]; then
  echo "tunnel down; aborting" | tee -a "$LOG"
  exit 1
fi
run() {
  echo "[$(date -u +%FT%TZ)] == $*" | tee -a "$LOG"
  timeout 2400 "$@" 2>&1 | tail -20 | tee -a "$LOG"
}
run python bench.py
run python bench_suite.py dslash
run python bench_suite.py solver
run python bench_suite.py mg
run python bench_suite.py gauge
run python bench_suite.py blas
echo "[$(date -u +%FT%TZ)] queue complete" | tee -a "$LOG"
