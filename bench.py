"""Headline benchmark: Wilson dslash GFLOPS on one chip.

Prints ONE JSON line, e.g.:
  {"metric": "wilson_dslash_gflops_chip", "value": N, "unit": "GFLOPS",
   "vs_baseline": N, "platform": "tpu", "lattice": [24,24,24,24],
   "path": "pallas_packed", "correctness_rel_err": E, "method": {...},
   "paths": {...per-path GFLOPS...}}

Baseline: 1400 GFLOPS — the order of public A100 single-precision Wilson
dslash results (BASELINE.md: target is "within 2x of A100", so
vs_baseline >= 0.5 meets the target).

Flop model: 1320 flops/site (Dslash::flops(), reference include/dslash.h:475).

Measurement honesty (hard-won on the axon TPU tunnel):
  * complex64 does not EXECUTE on some TPU runtimes; worse, the failure
    only surfaces at host-transfer time while block_until_ready returns
    success without running anything — timing a no-op.  The headline
    paths are therefore the all-f32 pair-form stencils (which are also
    the honest "single precision" numbers to compare against GPU f32
    dslash results), complex support is probed in a SUBPROCESS (a failed
    complex op can wedge the backend for the whole process), and every
    timed call fetches an f32 scalar checksum to the host — transfer
    completion is the only reliable execution barrier.
  * A fixed per-call RPC overhead (tens of ms over the tunnel) would
    swamp a naive time/chain number, so the per-application time is the
    MARGINAL cost between two chain lengths: (t(n2)-t(n1))/(n2-n1).
  * Inputs are varied per repetition (an eps scalar folded into the
    chain) so a result-memoising runtime cannot serve cached outputs.
  * Correctness is asserted in-run: the TPU pair path is compared
    against the complex stencil on the CPU backend at 8^4 and the
    relative error is reported in the JSON line.

Paths benchmarked (best f32 path wins; bf16-storage sloppy reported too):
  xla_pairs     — packed pair-form (4,3,2,T,Z,YX) f32 stencil
                  (ops/wilson_packed.dslash_packed_pairs)
  pallas_packed — hand-blocked pallas kernel, grid (T, Z/BZ)
                  (ops/wilson_pallas_packed); TPU only
  pallas_bf16 / xla_pairs_bf16 — same with bf16 storage (f32 compute):
                  the half-precision sloppy-operator number
  xla_canonical — complex (T,Z,Y,X,4,3) roll+einsum stencil; only where
                  complex executes (CPU; GPU; full TPU runtimes)
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import threading
import time

BASELINE_GFLOPS = 1400.0


# -- roofline / noise gating ------------------------------------------------
# Round 5 recorded physically impossible rows into the measurement log
# (triple_update_norm2 at 1.27e11 GFLOPS / secs 0.0, xpay_redot at
# 31.8 TB/s — measurements_tpu.log), and the mg suite silently fell back
# to CPU under a TPU banner.  Every recorded row now passes ``gate_row``:
# a marginal-seconds floor, a per-suite roofline bound, and a
# platform==banner assertion.  Rejections are printed LOUDLY into the log
# (an error row), never silently recorded as data.  The bounds are pure
# numbers unit-tested in tests/test_bench_gate.py.

MIN_MARGINAL_SECS = 1e-6      # below this a marginal is noise, not data

SUITE_ROOFLINES = {
    # {"gflops", "gbps"} upper bounds per suite, deliberately generous
    # (~10x the best credible chip measurement) — they reject the
    # impossible, not the surprising:
    #  * dslash/solver: best measured 5,673 GFLOPS (PERF.md round 5); an
    #    order of magnitude above sits far past the v5p VPU envelope for
    #    a stencil, and effective bandwidth beyond ~25 TB/s exceeds even
    #    the VMEM-resident regime (<= 23 TB/s measured).
    #  * blas: bandwidth-bound bundles at ~0.67 flops/byte against the
    #    same <= 23 TB/s VMEM ceiling -> < 16 TFLOPS real.
    "dslash": {"gflops": 60.0e3, "gbps": 25.0e3},
    "solver": {"gflops": 60.0e3, "gbps": 25.0e3},
    "blas": {"gflops": 30.0e3, "gbps": 25.0e3},
    "mg": {"gflops": 60.0e3, "gbps": 25.0e3},
    "gauge": {"gflops": 60.0e3, "gbps": 25.0e3},
}
_DEFAULT_ROOFLINE = {"gflops": 60.0e3, "gbps": 25.0e3}


def gate_row(suite: str, row: dict, banner_platform: str = None):
    """(ok, reason) for a measurement row.

    Pure function (no jax) so the round-5 failure modes are unit-testable:
    rejects rows whose platform does not match the banner they would be
    recorded under, rows with a ~zero/negative time, and rows whose
    gflops/gbps exceed the per-suite roofline bound."""
    if banner_platform is not None and row.get("platform") != banner_platform:
        return False, (f"platform mismatch: row measured on "
                       f"{row.get('platform')!r} cannot be recorded "
                       f"under a {banner_platform!r} banner")
    secs = row.get("secs_per_call", row.get("secs"))
    if secs is not None and not (isinstance(secs, (int, float))
                                 and math.isfinite(secs)
                                 and secs > MIN_MARGINAL_SECS):
        return False, (f"secs={secs!r} at/below the {MIN_MARGINAL_SECS:g}s "
                       "floor: a zero/negative marginal is noise, not a "
                       "measurement")
    if row.get("converged") is False:
        return False, ("unconverged solve: the row carries "
                       "converged=False — a timing whose solve missed "
                       "tol is not recordable throughput (quda_tpu/"
                       "robust unconverged-flag contract)")
    lim = SUITE_ROOFLINES.get(suite, _DEFAULT_ROOFLINE)
    for key, unit in (("gflops", "GFLOPS"), ("gbps", "GB/s")):
        v = row.get(key)
        if v is None:
            continue
        if not (isinstance(v, (int, float)) and math.isfinite(v)
                and v >= 0):
            return False, f"{key}={v!r} is not a finite throughput"
        if v > lim[key]:
            return False, (f"{key}={v:g} exceeds the {suite} roofline "
                           f"bound {lim[key]:g} {unit} — physically "
                           "impossible; rejected")
    return True, ""


# Rows accepted by record_row in this process, in order — the compare
# gate's "current run" input (bench_suite --compare).  Rejected rows are
# kept too so the gate summary can say how many died at the gate.
_RECORDED_ROWS: list = []
_REJECTED_ROWS: list = []


def recorded_rows() -> list:
    """(suite, row) pairs accepted by record_row this process."""
    return list(_RECORDED_ROWS)


def rejected_rows() -> list:
    """(suite, row, reason) triples refused by record_row this process."""
    return list(_REJECTED_ROWS)


def reset_recorded_rows():
    _RECORDED_ROWS.clear()
    _REJECTED_ROWS.clear()


def _mirror_row_event(name: str, suite: str, row: dict, **extra):
    """Mirror a bench row into the obs trace stream (bench_suite
    --trace) so the chrome artifact carries the measurements next to
    the spans/tuner events; scalars only, and never let observability
    break a measurement run."""
    try:
        from quda_tpu.obs import trace as _otr
        if _otr.enabled():
            # row keys that collide with event()'s own parameters are
            # prefixed
            taken = ("name", "cat", "suite") + tuple(extra)
            fields = {("row_" + k if k in taken else k): v
                      for k, v in row.items()
                      if isinstance(v, (str, int, float, bool))
                      or v is None}
            _otr.event(name, cat="bench", suite=suite, **fields,
                       **extra)
    except Exception:
        pass


def record_row(suite: str, row: dict, banner_platform: str = None,
               log=None):
    """Print ``row`` as one JSON line iff it passes ``gate_row``;
    otherwise print a loud rejection row so the failure lands IN the log
    instead of being silently recorded as data.  Returns True iff the
    row was recorded."""
    if log is None:
        log = lambda s: print(s, flush=True)
    ok, reason = gate_row(suite, row, banner_platform)
    if ok:
        log(json.dumps(dict({"suite": suite}, **row)))
        _RECORDED_ROWS.append((suite, dict(row)))
        _mirror_row_event("bench_row", suite, row)
    else:
        log(json.dumps({"suite": suite, "name": row.get("name"),
                        "rejected": reason,
                        "platform": row.get("platform")}))
        _REJECTED_ROWS.append((suite, dict(row), reason))
        # rejections mirror too (bench_row_rejected): a gate failure
        # must be visible in the chrome artifact, not just the text log
        _mirror_row_event("bench_row_rejected", suite, row,
                          rejected=reason)
    return ok


LAST_TPU_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_TPU_LAST.json")

# The record is built INCREMENTALLY (skeleton first, each timed path folded
# in as it completes) so that the deadline watchdog below can always emit a
# parseable line.  Round 3 was lost to the opposite design: a wedged tunnel
# stalled the probe loop past the driver's window and the run was killed
# having printed nothing (BENCH_r03.json rc:124, empty tail).
_RECORD: dict = {}
_DONE = threading.Event()


def _arm_deadline(seconds: float):
    """Watchdog thread: on expiry, print the record accumulated so far and
    hard-exit.  A thread (not SIGALRM) because the failure mode being
    defended against is the main thread wedged inside a backend RPC that
    never returns to the bytecode loop."""
    if seconds <= 0:
        return None

    def fire():
        if _DONE.is_set():
            return
        # snapshot before serializing: the main thread may be mutating the
        # record concurrently, and ANY exception here must still reach the
        # os._exit — a dead watchdog with no output is the rc:124 failure
        # all over again
        out = ('{"metric": "wilson_dslash_gflops_chip", "value": 0.0, '
               '"unit": "GFLOPS", "vs_baseline": 0.0, '
               '"error": "deadline hit; record serialization failed"}')
        import copy
        for _ in range(3):
            try:
                rec = copy.deepcopy(_RECORD)
                rec.setdefault("note", "deadline hit; partial record")
                out = json.dumps(rec)
                break
            except Exception:
                continue
        try:
            print(out, flush=True)
        finally:
            os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _conf(name):
    """Benchmark knobs go through the central registry
    (quda_tpu.utils.config) — one source of truth for defaults/docs."""
    from quda_tpu.utils import config as qconf
    return qconf.get(name, fresh=True)


def _probe_subprocess() -> dict:
    """Probe platform + complex64 execution support in a child process
    (a failed complex op can wedge the backend, and device init can hang
    — neither must take down the benchmark)."""
    code = r"""
import json, sys
import jax, jax.numpy as jnp
import numpy as np
out = {}
try:
    out["platform"] = jax.devices()[0].platform
except Exception as e:
    out["error"] = str(e)[:100]
    print(json.dumps(out)); sys.exit(0)
try:
    x = jnp.ones((8, 128), jnp.complex64) * (1 + 1j)
    s = float(jnp.sum(jnp.real(x * jnp.conj(x))))
    out["complex_ok"] = abs(s - 2 * 8 * 128) < 1e-3
except Exception:
    out["complex_ok"] = False
print(json.dumps(out))
"""
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=float(
                               _conf("QUDA_TPU_BENCH_PROBE_S")))
        for line in r.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except Exception:
        pass
    return {"error": "probe failed/hung"}


def _fetch(x) -> float:
    """Host-fetch an f32 scalar — the only reliable execution barrier."""
    import numpy as np
    return float(np.asarray(x))


def _time_marginal(make_chain, args, n1: int, n2: int, reps: int):
    """Marginal per-application seconds between chain lengths n1 < n2.

    make_chain(n) -> jitted f(*args, eps) returning an f32 scalar.
    Returns (seconds_per_apply, checksum).

    A marginal that is not clearly positive means the measurement is
    NOISE (a contended host can inflate the short-chain total past the
    long one — observed 2026-07-31: blas rows claiming 0.0 s/call and
    1e11 "GFLOPS" while another process shared the chip).  On a
    degenerate marginal BOTH chains are re-measured, keeping the min of
    each (the consistent estimator); if the marginal is still
    indistinguishable from zero the result is NaN so no caller can
    mistake it for a throughput."""
    import jax.numpy as jnp

    totals = {}
    checksum = None

    def measure(n):
        f = make_chain(n)
        nonlocal checksum
        checksum = _fetch(f(*args, jnp.float32(0.01)))  # compile + warm
        best = float("inf")
        for i in range(reps):
            eps = jnp.float32(0.01 + 1e-4 * (i + 1))
            t0 = time.perf_counter()
            checksum = _fetch(f(*args, eps))
            best = min(best, time.perf_counter() - t0)
        return best

    for n in (n1, n2):
        totals[n] = measure(n)
    if totals[n2] - totals[n1] <= 0.02 * totals[n1]:
        # degenerate marginal — usually a contention spike inflating the
        # SHORT chain's best.  Re-measure BOTH chains and keep the min
        # (the consistent estimator); never keep a slower sample.
        for n in (n1, n2):
            totals[n] = min(totals[n], measure(n))
    sec = (totals[n2] - totals[n1]) / (n2 - n1)
    if sec <= 0.02 * totals[n1] / (n2 - n1):
        return float("nan"), checksum
    return sec, checksum


def main():
    force_cpu = _conf("QUDA_TPU_BENCH_CPU")
    if force_cpu:
        # everything below runs on the CPU backend; don't probe the TPU
        # (its answer would misattribute the platform of the timings)
        probe = {"platform": "cpu", "complex_ok": True}
    else:
        # The tunnel to the chip goes down for stretches of minutes; a
        # single failed probe must not condemn the round's number to the
        # CPU fallback.  Retry — but the TOTAL probe budget must stay well
        # under the driver's window (round 3 died stalling here for ~31
        # minutes): defaults are 2 attempts x 75 s timeout + 30 s wait
        # = 180 s worst case.  A probe that ANSWERS (even with "cpu") is a
        # healthy host resolving to CPU and costs only seconds per retry;
        # only a hung/failed probe pays the full timeout.
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            attempts = 1
        else:
            attempts = _conf("QUDA_TPU_BENCH_PROBE_RETRIES")
        wait_s = _conf("QUDA_TPU_BENCH_PROBE_WAIT_S")
        probe = {}
        for i in range(max(attempts, 1)):
            probe = _probe_subprocess()
            if probe.get("platform") not in (None, "cpu"):
                break
            if i + 1 < attempts:
                time.sleep(wait_s)
        if "platform" not in probe:
            # device init hung or failed: fall back to CPU via re-exec
            os.environ["QUDA_TPU_BENCH_CPU"] = "1"
            os.execv(sys.executable, [sys.executable] + sys.argv)

    platform = probe.get("platform", "cpu")
    complex_ok = bool(probe.get("complex_ok", False))

    # Skeleton record + deadline watchdog BEFORE any backend work in this
    # process (device_put can wedge on a dying tunnel even after a clean
    # probe).  Carry the last attributable TPU measurement from the start;
    # it is dropped again once a fresh TPU number lands.
    _RECORD.update({
        "metric": "wilson_dslash_gflops_chip", "value": 0.0,
        "unit": "GFLOPS", "vs_baseline": 0.0, "platform": platform,
        "path": "none", "paths": {},
    })
    try:
        if os.path.exists(LAST_TPU_FILE):
            with open(LAST_TPU_FILE) as f:
                _RECORD["last_tpu"] = json.load(f)
    except Exception:
        pass
    deadline = _arm_deadline(float(_conf("QUDA_TPU_BENCH_DEADLINE_S")))

    import numpy as np
    import jax
    import jax.numpy as jnp

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")

    # banner honesty: the probe's platform answer and THIS process's
    # backend can disagree (the tunnel drops between probe and init, and
    # jax then falls back to CPU silently).  A CPU measurement must never
    # be recorded under a TPU banner — re-derive the platform from the
    # process that actually runs the timings.
    actual = jax.default_backend()
    if platform != actual:
        _RECORD["platform_note"] = (
            f"probe reported {platform!r} but the benchmark process "
            f"initialised {actual!r}; recording under the actual platform")
        print(json.dumps({"error": _RECORD["platform_note"]}), flush=True)
        platform = actual
        _RECORD["platform"] = platform

    from quda_tpu.ops import wilson as wops
    from quda_tpu.ops import wilson_packed as wpk

    L = _conf("QUDA_TPU_BENCH_L") or (24 if platform != "cpu" else 8)
    T = Z = Y = X = L
    rng = np.random.default_rng(0)

    # Build fields on the host (keeps complex off backends that lack it);
    # antiperiodic-t phases folded into the links like the solve path.
    gauge = (rng.standard_normal((4, T, Z, Y, X, 3, 3))
             + 1j * rng.standard_normal((4, T, Z, Y, X, 3, 3))
             ).astype(np.complex64) * 0.3
    gauge[3, -1] *= -1.0
    psi = (rng.standard_normal((T, Z, Y, X, 4, 3))
           + 1j * rng.standard_normal((T, Z, Y, X, 4, 3))
           ).astype(np.complex64)
    gp = np.transpose(gauge, (0, 5, 6, 1, 2, 3, 4)).reshape(
        4, 3, 3, T, Z, Y * X)
    pp = np.transpose(psi, (4, 5, 0, 1, 2, 3)).reshape(4, 3, T, Z, Y * X)
    g_pairs = np.stack([gp.real, gp.imag], axis=3).astype(np.float32)
    p_pairs = np.stack([pp.real, pp.imag], axis=2).astype(np.float32)

    g_d = jax.device_put(jnp.asarray(g_pairs))
    p_d = jax.device_put(jnp.asarray(p_pairs))
    g_d.block_until_ready(), p_d.block_until_ready()

    # ---- correctness gate: pair path on this backend vs complex stencil
    # on the CPU backend, at 8^4 ------------------------------------------
    Lc = 8
    gs = gauge[:, :Lc, :Lc, :Lc, :Lc]
    ps = psi[:Lc, :Lc, :Lc, :Lc]
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        ref = np.asarray(jax.jit(wops.dslash_full)(
            jax.device_put(gs, cpu), jax.device_put(ps, cpu)))
    refp = np.transpose(ref, (4, 5, 0, 1, 2, 3)).reshape(
        4, 3, Lc, Lc, Lc * Lc)
    gps = np.transpose(gs, (0, 5, 6, 1, 2, 3, 4)).reshape(
        4, 3, 3, Lc, Lc, Lc * Lc)
    pps = np.transpose(ps, (4, 5, 0, 1, 2, 3)).reshape(
        4, 3, Lc, Lc, Lc * Lc)
    gsd = jax.device_put(jnp.asarray(
        np.stack([gps.real, gps.imag], axis=3).astype(np.float32)))
    psd = jax.device_put(jnp.asarray(
        np.stack([pps.real, pps.imag], axis=2).astype(np.float32)))
    out_h = np.asarray(jax.jit(
        lambda g, p: wpk.dslash_packed_pairs(g, p, Lc, Lc))(gsd, psd))
    got = out_h[:, :, 0] + 1j * out_h[:, :, 1]
    rel_err = float(np.max(np.abs(got - refp)) / np.max(np.abs(refp)))
    if rel_err > 1e-4:
        _DONE.set()
        _RECORD["error"] = f"correctness gate failed: {rel_err}"
        print(json.dumps(_RECORD))
        return
    _RECORD["correctness_rel_err"] = rel_err
    _RECORD["lattice"] = [L, L, L, L]

    # ---- timed paths -----------------------------------------------------
    # chain spread sets the timing SNR: the marginal difference must be
    # large against the tunnel's per-call RPC noise (~5-10 ms), so the
    # long chain is ~200 applications (~50 ms of real dslash work).
    n1 = _conf("QUDA_TPU_BENCH_N1")
    n2 = _conf("QUDA_TPU_BENCH_N2")
    reps = _conf("QUDA_TPU_BENCH_REPS")
    flops = 1320 * (L ** 4)

    def chain_of(fn):
        def make(n):
            @jax.jit
            def f(g, p, eps):
                def body(v, _):
                    o = fn(g, v) * 0.125 + eps * v
                    return o.astype(p.dtype), None
                out, _ = jax.lax.scan(body, p, None, length=n)
                return jnp.sum(out.astype(jnp.float32) ** 2)
            return f
        return make

    paths = _RECORD["paths"]
    secs = {}

    def _refresh_headline():
        # fold the best f32 path into the record after EVERY measurement,
        # so a deadline fire mid-run still reports what has been measured
        f32 = {k: v for k, v in secs.items() if "bf16" not in k}
        if f32:
            best = min(f32, key=f32.get)
            _RECORD["path"] = best
            _RECORD["value"] = round(flops / f32[best] / 1e9, 1)
            _RECORD["vs_baseline"] = round(
                _RECORD["value"] / BASELINE_GFLOPS, 3)
            # a fresh TPU number supersedes the carried measurement and
            # must be persisted NOW — a deadline fire later in the run
            # must not lose it
            if platform == "tpu" and _RECORD["value"] > 0:
                _RECORD.pop("last_tpu", None)
                try:
                    with open(LAST_TPU_FILE, "w") as f:
                        json.dump(dict(_RECORD, measured_at=time.strftime(
                            "%Y-%m-%d %H:%M:%S")), f, indent=1)
                except Exception:
                    pass

    def run_path(name, fn, args):
        try:
            s, _ = _time_marginal(chain_of(fn), args, n1, n2, reps)
            ok, reason = gate_row("dslash", {
                "name": name, "secs_per_call": s,
                "gflops": flops / s / 1e9 if s and s > 0 else float("nan"),
                "platform": platform})
            if not (s > 0):              # NaN marginal — noise, not data
                paths[name + "_error"] = ("non-positive marginal "
                                          "(contended host?)")
            elif not ok:                 # roofline-gated: impossible rate
                paths[name + "_error"] = reason
            else:
                secs[name] = s
                paths[name] = round(flops / s / 1e9, 1)
        except Exception as e:
            paths[name + "_error"] = str(e)[:160]
        _refresh_headline()

    if platform != "tpu":
        run_path("xla_pairs",
                 lambda g, v: wpk.dslash_packed_pairs(g, v, X, Y),
                 (g_d, p_d))

    pallas_rel_err = None
    if platform == "tpu":
        # most-important-first: if the deadline watchdog fires mid-run,
        # the v3-vs-v2 answer (the round's open question) must already be
        # in the record; stencil + bf16 variants follow
        from quda_tpu.ops import wilson_pallas_packed as wpp
        # gate the pallas kernel ON DEVICE against the (CPU-gated) pair
        # stencil at the headline size — this exercises the multi-z-block
        # splice configuration the headline number is measured with
        try:
            # pre-shifted backward gauge: computed once per gauge load in
            # real use, so keep the rolls OUT of the timed chain (inside
            # the scan body XLA re-rolls the whole field per application)
            gbw = jax.jit(lambda g: wpp.backward_gauge(g, X))(g_d)
            gbw.block_until_ready()

            @jax.jit
            def _gate(g, p):
                # gate the EXACT timed variant (explicit gauge_bw)
                a = wpp.dslash_pallas_packed(g, p, X, gauge_bw=gbw)
                b = wpk.dslash_packed_pairs(g, p, X, Y)
                return (jnp.max(jnp.abs(a - b)), jnp.max(jnp.abs(b)))
            d, m = _gate(g_d, p_d)
            pallas_rel_err = _fetch(d) / _fetch(m)
            if pallas_rel_err < 1e-4:
                run_path("pallas_packed",
                         lambda g, v: wpp.dslash_pallas_packed(
                             g, v, X, gauge_bw=gbw),
                         (g_d, p_d))
            else:
                paths["pallas_packed_error"] = (
                    f"gate failed: rel err {pallas_rel_err:.3e}")
        except Exception as e:
            paths["pallas_packed_error"] = str(e)[:160]
        # v3 kernel: scatter-form backward hops, no backward-gauge copy
        try:
            @jax.jit
            def _gate3(g, p):
                a = wpp.dslash_pallas_packed_v3(g, p, X)
                b = wpk.dslash_packed_pairs(g, p, X, Y)
                return (jnp.max(jnp.abs(a - b)), jnp.max(jnp.abs(b)))
            d3, m3 = _gate3(g_d, p_d)
            v3_rel_err = _fetch(d3) / _fetch(m3)
            if v3_rel_err < 1e-4:
                run_path("pallas_v3",
                         lambda g, v: wpp.dslash_pallas_packed_v3(g, v, X),
                         (g_d, p_d))
            else:
                paths["pallas_v3_error"] = (
                    f"gate failed: rel err {v3_rel_err:.3e}")
        except Exception as e:
            paths["pallas_v3_error"] = str(e)[:160]
        # reconstruct-12 v3: in-kernel third-row reconstruction needs
        # genuine SU(3) links, so gate + time on a projected gauge
        # (det-fixed QR) with the antiperiodic-t phase folded the same
        # way the solve path folds it
        try:
            graw = (rng.standard_normal((4, T, Z, Y, X, 3, 3))
                    + 1j * rng.standard_normal((4, T, Z, Y, X, 3, 3))
                    ).astype(np.complex64)
            qm, rm = np.linalg.qr(graw)
            dg = np.diagonal(rm, axis1=-2, axis2=-1)
            qm = qm * (dg / np.abs(dg))[..., None, :]
            qm = qm * np.linalg.det(qm)[..., None, None] ** (-1.0 / 3.0)
            qm[3, -1] *= -1.0
            gsu = np.transpose(qm, (0, 5, 6, 1, 2, 3, 4)).reshape(
                4, 3, 3, T, Z, Y * X)
            gsu_d = jax.device_put(jnp.asarray(
                np.stack([gsu.real, gsu.imag], axis=3).astype(np.float32)))
            gsu_d.block_until_ready()
            g12 = jax.jit(wpp.to_recon12)(gsu_d)
            g12.block_until_ready()

            @jax.jit
            def _gate12(gf, gc, p):
                a = wpp.dslash_pallas_packed_v3(gc, p, X)
                b = wpp.dslash_pallas_packed_v3(gf, p, X)
                return (jnp.max(jnp.abs(a - b)), jnp.max(jnp.abs(b)))
            d12, m12 = _gate12(gsu_d, g12, p_d)
            r12_rel_err = _fetch(d12) / _fetch(m12)
            if r12_rel_err < 1e-4:
                run_path("pallas_v3_r12",
                         lambda g, v: wpp.dslash_pallas_packed_v3(
                             g, v, X),
                         (g12, p_d))
                g12_bf = g12.astype(jnp.bfloat16)
                p_bf0 = p_d.astype(jnp.bfloat16)
                g12_bf.block_until_ready(), p_bf0.block_until_ready()
                run_path("pallas_v3_r12_bf16",
                         lambda g, v: wpp.dslash_pallas_packed_v3(
                             g, v, X),
                         (g12_bf, p_bf0))
            else:
                paths["pallas_v3_r12_error"] = (
                    f"gate failed: rel err {r12_rel_err:.3e}")
        except Exception as e:
            paths["pallas_v3_r12_error"] = str(e)[:160]
        # f32 stencil next: if both pallas gates failed, the record still
        # gets a headline-eligible f32 number before the bf16 variants
        run_path("xla_pairs",
                 lambda g, v: wpk.dslash_packed_pairs(g, v, X, Y),
                 (g_d, p_d))
        # bf16-storage sloppy variants (f32 compute) — the half-precision
        # operator number; pallas reads bf16 blocks if given bf16 arrays
        g_bf = g_d.astype(jnp.bfloat16)
        p_bf = p_d.astype(jnp.bfloat16)
        g_bf.block_until_ready(), p_bf.block_until_ready()
        run_path("pallas_v3_bf16",
                 lambda g, v: wpp.dslash_pallas_packed_v3(g, v, X),
                 (g_bf, p_bf))
        gbw_bf = jax.jit(lambda g: wpp.backward_gauge(g, X))(g_bf)
        gbw_bf.block_until_ready()
        run_path("pallas_bf16",
                 lambda g, v: wpp.dslash_pallas_packed(
                     g, v, X, gauge_bw=gbw_bf),
                 (g_bf, p_bf))
        run_path("xla_pairs_bf16",
                 lambda g, v: wpk.dslash_packed_pairs(g, v, X, Y,
                                                      out_dtype=jnp.bfloat16),
                 (g_bf, p_bf))
        # multi-RHS amortization (the round-7 tentpole): 8 RHS streamed
        # through one gauge-tile fetch per (t, z-block).  NOT headline-
        # eligible (the headline is per-application single-RHS); the
        # aggregate and per-RHS rates land in "paths" through the same
        # roofline gate.  Gate: lane 0 of the batch must BIT-match the
        # single-RHS v2 kernel (same kernel body by construction).
        try:
            p8 = jnp.stack([jnp.roll(p_d, i, axis=-1) for i in range(8)])
            p8.block_until_ready()

            @jax.jit
            def _gate_mrhs(g, pb):
                a = wpp.dslash_pallas_packed_mrhs(g, pb, X, gauge_bw=gbw)
                b = wpp.dslash_pallas_packed(g, pb[0], X, gauge_bw=gbw)
                return (jnp.max(jnp.abs(a[0] - b)), jnp.max(jnp.abs(b)))
            dm, mm = _gate_mrhs(g_d, p8)
            mrhs_rel = _fetch(dm) / _fetch(mm)
            if mrhs_rel < 1e-6:
                s8, _ = _time_marginal(
                    chain_of(lambda g, v: wpp.dslash_pallas_packed_mrhs(
                        g, v, X, gauge_bw=gbw)), (g_d, p8), n1, n2, reps)
                row = {"name": "pallas_mrhs_n8", "secs_per_call": s8,
                       "gflops": (8 * flops / s8 / 1e9
                                  if s8 and s8 > 0 else float("nan")),
                       "platform": platform}
                ok, reason = gate_row("dslash", row)
                if not (s8 > 0):
                    paths["pallas_mrhs_n8_error"] = (
                        "non-positive marginal (contended host?)")
                elif not ok:
                    paths["pallas_mrhs_n8_error"] = reason
                else:
                    paths["pallas_mrhs_n8"] = round(8 * flops / s8 / 1e9,
                                                    1)
                    paths["pallas_mrhs_n8_per_rhs"] = round(
                        flops / s8 / 1e9, 1)
            else:
                paths["pallas_mrhs_n8_error"] = (
                    f"gate failed: rel err {mrhs_rel:.3e}")
        except Exception as e:
            paths["pallas_mrhs_n8_error"] = str(e)[:160]
        _refresh_headline()

    if complex_ok or platform == "cpu":
        gauge_d = jax.device_put(jnp.asarray(gauge))
        psi_d = jax.device_put(jnp.asarray(psi))

        def canon(g, v):
            return wops.dslash_full(g, v)

        def make_canon(n):
            @jax.jit
            def f(g, p, eps):
                def body(v, _):
                    return canon(g, v) * 0.125 + eps * v, None
                out, _ = jax.lax.scan(body, p, None, length=n)
                return jnp.sum(jnp.real(out * jnp.conj(out)))
            return f
        try:
            s, _ = _time_marginal(make_canon, (gauge_d, psi_d), n1, n2,
                                  reps)
            ok, reason = gate_row("dslash", {
                "name": "xla_canonical", "secs_per_call": s,
                "gflops": flops / s / 1e9 if s and s > 0 else float("nan"),
                "platform": platform})
            if not (s > 0):          # NaN marginal — noise, not data
                paths["xla_canonical_error"] = ("non-positive marginal "
                                                "(contended host?)")
            elif not ok:
                paths["xla_canonical_error"] = reason
            else:
                secs["xla_canonical"] = s
                paths["xla_canonical"] = round(flops / s / 1e9, 1)
        except Exception as e:
            paths["xla_canonical_error"] = str(e)[:160]
        _refresh_headline()

    # headline (best f32 path; bf16 storage reported but not headline) has
    # been folded in by _refresh_headline after each path
    _RECORD["pallas_vs_xla_rel_err"] = pallas_rel_err
    _RECORD["method"] = {
        "timing": "marginal cost between scan chains",
        "chains": [n1, n2],
        "reps": reps,
        "execution_barrier": "host fetch of f32 checksum",
        "inputs_varied_per_rep": True,
        "complex_ok": complex_ok,
    }
    # Persist good TPU runs; if this run had to fall back to CPU (the
    # tunnel drops for stretches), the last attributable TPU measurement
    # stays carried in "last_tpu" so the round still records a chip number.
    try:
        if platform == "tpu" and _RECORD["value"] > 0:
            _RECORD.pop("last_tpu", None)
            with open(LAST_TPU_FILE, "w") as f:
                json.dump(dict(_RECORD, measured_at=time.strftime(
                    "%Y-%m-%d %H:%M:%S")), f, indent=1)
    except Exception:
        pass
    _DONE.set()
    if deadline is not None:
        deadline.cancel()
    print(json.dumps(_RECORD))


if __name__ == "__main__":
    main()
