"""Headline benchmark: Wilson dslash GFLOPS on one chip.

Prints ONE JSON line, e.g.:
  {"metric": "wilson_dslash_gflops_chip", "value": N, "unit": "GFLOPS",
   "vs_baseline": N, "platform": "axon", "lattice": [24,24,24,24],
   "path": "xla_packed", "chain": 30, "reps": 5, "dispatch_ms": M,
   "paths": {...per-path GFLOPS...}}

Baseline: 1400 GFLOPS — the order of public A100 single-precision Wilson
dslash results (BASELINE.md: target is "within 2x of A100", so
vs_baseline >= 0.5 meets the target).

Flop model: 1320 flops/site (Dslash::flops(), reference include/dslash.h:475).
Runs complex64 (TPU has no f64); the dslash is HBM-bandwidth bound so c64 is
the honest precision to compare against single-precision GPU numbers.

Paths benchmarked (best wins):
  xla_canonical — host-order (T,Z,Y,X,4,3) roll+einsum stencil (ops/wilson.py)
  xla_packed    — TPU-native packed order (4,3,T,Z,Y*X) unrolled stencil
                  (ops/wilson_packed.py); pack/unpack excluded from timing,
                  as fields stay packed across a whole solve
  pallas_packed — hand-blocked pallas kernel on the packed pair layout
                  (ops/wilson_pallas_packed.py); TPU only
"""

from __future__ import annotations

import json
import sys
import time


def _time_chain(fn, args, chain: int, reps: int) -> float:
    """Best per-application seconds for a scan-chained fn."""
    import jax

    @jax.jit
    def apply_chain(*a):
        def body(v, _):
            return fn(*a[:-1], v), None
        out, _ = jax.lax.scan(body, a[-1], None, length=chain)
        return out

    out = apply_chain(*args)
    out.block_until_ready()  # compile + warmup
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = apply_chain(*args)
        out.block_until_ready()
        best = min(best, (time.perf_counter() - t0) / chain)
    return best


def main():
    import os

    import jax
    import jax.numpy as jnp

    if os.environ.get("QUDA_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    # The axon TPU tunnel can wedge (device init hangs instead of failing).
    # Probe device init in a watchdog thread; fall back to CPU rather than
    # hang the whole benchmark run.
    import threading

    probe = {}

    def _probe():
        try:
            devs = jax.devices()
            probe["platform"] = devs[0].platform
        except Exception as e:
            probe["error"] = str(e)

    th = threading.Thread(target=_probe, daemon=True)
    th.start()
    th.join(timeout=float(os.environ.get("QUDA_TPU_BENCH_PROBE_S", "240")))
    if "platform" in probe:
        platform = probe["platform"]
    else:
        # hung or failed: a hung backend cannot be recovered in-process;
        # re-exec ourselves with the CPU override so the run completes
        if not os.environ.get("QUDA_TPU_BENCH_CPU"):
            os.environ["QUDA_TPU_BENCH_CPU"] = "1"
            os.execv(sys.executable, [sys.executable] + sys.argv)
        platform = "cpu"

    from quda_tpu.fields.geometry import LatticeGeometry
    from quda_tpu.fields.gauge import GaugeField
    from quda_tpu.fields.spinor import ColorSpinorField
    from quda_tpu.ops import wilson as wops
    from quda_tpu.ops import wilson_packed as wpk
    from quda_tpu.ops.boundary import apply_t_boundary

    # 24^4: ~64 MB spinor + 96 MB gauge at c64 — big enough to be
    # bandwidth-bound, small enough to compile fast over the tunnel.
    L = int(os.environ.get("QUDA_TPU_BENCH_L",
                           "24" if platform != "cpu" else "8"))
    geom = LatticeGeometry((L, L, L, L))
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    gauge = apply_t_boundary(
        GaugeField.random(k1, geom, dtype=jnp.complex64).data, geom, -1)
    psi = ColorSpinorField.gaussian(k2, geom, dtype=jnp.complex64).data
    gauge_p = wpk.pack_gauge(gauge)
    psi_p = wpk.pack_spinor(psi)
    for a in (gauge, psi, gauge_p, psi_p):
        a.block_until_ready()

    # dispatch latency: a trivial jitted op, timed round-trip (attributes
    # how much of any slow number is tunnel/executable launch overhead)
    tiny = jax.jit(lambda x: x + 1.0)
    t = jnp.zeros((8, 128), jnp.float32)
    tiny(t).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(10):
        tiny(t).block_until_ready()
    dispatch_ms = (time.perf_counter() - t0) / 10 * 1e3

    chain = int(os.environ.get("QUDA_TPU_BENCH_CHAIN", "30"))
    reps = int(os.environ.get("QUDA_TPU_BENCH_REPS", "5"))
    flops = 1320 * geom.volume

    paths = {}
    secs = {}
    secs["xla_canonical"] = _time_chain(
        wops.dslash_full, (gauge, psi), chain, reps)
    secs["xla_packed"] = _time_chain(
        lambda g, p: wpk.dslash_packed(g, p, L, L), (gauge_p, psi_p),
        chain, reps)
    if platform == "tpu":
        # pallas kernel (compiled mode needs real TPU; interpret-only
        # correctness is covered in tests)
        try:
            from quda_tpu.ops import wilson_pallas_packed as wpp
            g_pl = wpp.to_pallas_layout(gauge_p)
            p_pl = wpp.to_pallas_layout(psi_p)
            g_pl.block_until_ready()
            secs["pallas_packed"] = _time_chain(
                lambda g, p: wpp.dslash_pallas_packed(g, p, L),
                (g_pl, p_pl), chain, reps)
        except Exception as e:
            paths["pallas_packed_error"] = str(e)[:120]
    for name, s in secs.items():
        paths[name] = round(flops / s / 1e9, 1)

    best_path = min(secs, key=secs.get)
    gflops = flops / secs[best_path] / 1e9
    baseline = 1400.0
    print(json.dumps({
        "metric": "wilson_dslash_gflops_chip",
        "value": round(gflops, 1),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / baseline, 3),
        "platform": platform,
        "lattice": [L, L, L, L],
        "path": best_path,
        "chain": chain,
        "reps": reps,
        "dispatch_ms": round(dispatch_ms, 2),
        "paths": paths,
    }))


if __name__ == "__main__":
    main()
