"""Headline benchmark: Wilson dslash GFLOPS on one chip.

Prints ONE JSON line:
  {"metric": "wilson_dslash_gflops_chip", "value": N, "unit": "GFLOPS",
   "vs_baseline": N}

Baseline: 1400 GFLOPS — the order of public A100 single-precision Wilson
dslash results (BASELINE.md: target is "within 2x of A100", so
vs_baseline >= 0.5 meets the target).

Flop model: 1320 flops/site (Dslash::flops(), reference include/dslash.h:475).
Runs complex64 (TPU has no f64); the dslash is HBM-bandwidth bound so c64 is
the honest precision to compare against single-precision GPU numbers.
"""

from __future__ import annotations

import json
import sys
import time


def main():
    import os

    import jax
    import jax.numpy as jnp

    if os.environ.get("QUDA_TPU_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    # The axon TPU tunnel can wedge (device init hangs instead of failing).
    # Probe device init in a watchdog thread; fall back to CPU rather than
    # hang the whole benchmark run.
    import threading

    probe = {}

    def _probe():
        try:
            devs = jax.devices()
            probe["platform"] = devs[0].platform
        except Exception as e:
            probe["error"] = str(e)

    th = threading.Thread(target=_probe, daemon=True)
    th.start()
    th.join(timeout=float(os.environ.get("QUDA_TPU_BENCH_PROBE_S", "120")))
    if "platform" in probe:
        platform = probe["platform"]
    else:
        # hung or failed: a hung backend cannot be recovered in-process;
        # re-exec ourselves with the CPU override so the run completes
        if not os.environ.get("QUDA_TPU_BENCH_CPU"):
            os.environ["QUDA_TPU_BENCH_CPU"] = "1"
            os.execv(sys.executable, [sys.executable] + sys.argv)
        platform = "cpu"

    from quda_tpu.fields.geometry import LatticeGeometry
    from quda_tpu.fields.gauge import GaugeField
    from quda_tpu.fields.spinor import ColorSpinorField
    from quda_tpu.ops import wilson as wops
    from quda_tpu.ops.boundary import apply_t_boundary

    # 24^4: ~64 MB spinor + 96 MB gauge at c64 — big enough to be
    # bandwidth-bound, small enough to compile fast over the tunnel.
    L = 24 if platform != "cpu" else 8
    geom = LatticeGeometry((L, L, L, L))
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    gauge = apply_t_boundary(
        GaugeField.random(k1, geom, dtype=jnp.complex64).data, geom, -1)
    psi = ColorSpinorField.gaussian(k2, geom, dtype=jnp.complex64).data

    # autotune the stencil implementation (XLA fusion vs Pallas kernel)
    # once; the winner is cached in $QUDA_TPU_RESOURCE_PATH
    from quda_tpu.ops.wilson_pallas import dslash_pallas
    from quda_tpu.utils import tune as qtune

    stencil = wops.dslash_full
    if platform not in ("cpu",):
        candidates = {
            "xla": jax.jit(wops.dslash_full),
            "pallas": jax.jit(lambda g, p: dslash_pallas(g, p)),
        }
        try:
            winner = qtune.tune("wilson_dslash", (L, L, L, L), candidates,
                                (gauge, psi), aux="c64")
            stencil = {"xla": wops.dslash_full,
                       "pallas": dslash_pallas}[winner]
        except Exception:
            stencil = wops.dslash_full

    # steady-state form: chain dslash applications so timing covers the
    # fused stencil, not dispatch
    CHAIN = 10

    @jax.jit
    def apply_chain(g, p):
        def body(v, _):
            return stencil(g, v), None
        out, _ = jax.lax.scan(body, p, None, length=CHAIN)
        return out

    out = apply_chain(gauge, psi)
    out.block_until_ready()  # compile + warmup

    reps = 5
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = apply_chain(gauge, psi)
        out.block_until_ready()
        best = min(best, (time.perf_counter() - t0) / CHAIN)

    flops = 1320 * geom.volume
    gflops = flops / best / 1e9
    baseline = 1400.0
    print(json.dumps({
        "metric": "wilson_dslash_gflops_chip",
        "value": round(gflops, 1),
        "unit": "GFLOPS",
        "vs_baseline": round(gflops / baseline, 3),
    }))


if __name__ == "__main__":
    main()
