"""Production-volume multigrid measurement on the virtual device mesh.

VERDICT r4 weak #7 / next #6: all MG evidence was 8^4-class while the
reference's BASELINE config 5 is a 3-level solve on 48^3x96
(lib/multigrid.cpp:91-358 setup; tests/multigrid_benchmark_test.cpp).
This harness runs ONE 3-level Wilson-clover setup+solve at >=32^3x64 on
the 8-device virtual CPU mesh (the same GSPMD path a TPU pod would use)
and reports the numbers the reference's MG users actually budget:

  * setup seconds (null vectors + block QR + Galerkin probing, per level)
  * resident memory (host RSS delta; device = host on the CPU backend)
  * per-V-cycle seconds, and the share spent on each level's operator
  * outer GCR iterations + wall seconds vs plain CG on the same system

Writes one JSON line per record (same convention as bench_suite.py);
run:  python bench_mg_scale.py [--lat 32 32 32 64] [--nvec 12]
The slow-marked test (tests/test_mg_scale.py) drives the same entry at a
reduced volume so the path stays exercised in CI.
"""

import argparse
import json
import os
import sys
import time

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _configure():
    """CLI-entry config (NOT run on import: pytest owns these globals).

    Single-core hosts: async dispatch lets two collective programs
    interleave across the 8 virtual devices' threads, which deadlocks
    the XLA:CPU rendezvous (observed: collective-permute termination
    timeout, 7/8 threads arrived).  Synchronous dispatch serialises
    programs."""
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)


def _rss_mb():
    with open("/proc/self/statm") as fh:
        return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE") / 2**20


def run(lat, n_vec, kappa, csw, tol, setup_iters, emit=print,
        gauge_scale=None, nkrylov=16):
    from quda_tpu.fields.geometry import LatticeGeometry
    from quda_tpu.fields.gauge import GaugeField
    from quda_tpu.mg.mg import MG, MGLevelParam, mg_solve
    from quda_tpu.models.clover import DiracClover
    from quda_tpu.ops import blas
    from quda_tpu.parallel.mesh import make_lattice_mesh, shard_spinor
    from quda_tpu.solvers.cg import cg

    geom = LatticeGeometry(tuple(lat))
    rss0 = _rss_mb()

    t0 = time.perf_counter()
    # gauge_scale < full disorder gives a SMOOTH configuration — the
    # regime MG is for (coherent near-null modes; physical ensembles are
    # smooth).  Fully random links destroy the low-mode structure and
    # make plain CG artificially easy AND MG setup useless.
    gkw = {} if gauge_scale is None else {"scale": gauge_scale}
    U = GaugeField.random(jax.random.PRNGKey(11), geom, **gkw).data.astype(
        jnp.complex64)
    d = DiracClover(U, geom, kappa=kappa, csw=csw)
    b = jax.random.normal(
        jax.random.PRNGKey(12), geom.lattice_shape + (4, 3), jnp.float32
    ).astype(jnp.complex64)
    jax.block_until_ready(b)
    t_fields = time.perf_counter() - t0

    # 3 levels: 32^3x64 -> (4,4,4,4) blocks -> 8^3x16 -> (2,2,2,2) -> 4^3x8
    params = [
        MGLevelParam(block=(4, 4, 4, 4), n_vec=n_vec,
                     setup_iters=setup_iters, post_smooth=4,
                     smoother="ca-gcr", coarse_solver_iters=8),
        MGLevelParam(block=(2, 2, 2, 2), n_vec=n_vec,
                     setup_iters=max(20, setup_iters // 2), post_smooth=4,
                     smoother="ca-gcr", coarse_solver_iters=16,
                     coarse_solver_cycles=2, coarse_replicate=True),
    ]

    t0 = time.perf_counter()
    mg = MG(d, geom, params, key=jax.random.PRNGKey(13))
    jax.block_until_ready(mg.levels[-1]["coarse"].x_diag)
    setup_s = time.perf_counter() - t0
    rss_setup = _rss_mb()

    shapes = [tuple(lv["transfer"].coarse_shape) for lv in mg.levels]
    emit(json.dumps({
        "suite": "mg_scale", "name": "setup",
        "lattice": list(lat), "n_vec": n_vec, "levels": 3,
        "coarse_shapes": [list(s) for s in shapes],
        "field_init_secs": round(t_fields, 2),
        "setup_secs": round(setup_s, 2),
        "rss_mb_after_setup": round(rss_setup - rss0, 1),
        "platform": "cpu"}), flush=True)

    # V-cycle cost (jitted apply, averaged over 3 warm calls);
    # precondition takes/returns STANDARD layout
    pre = jax.jit(mg.precondition)
    jax.block_until_ready(pre(b))
    t0 = time.perf_counter()
    for _ in range(3):
        out = pre(b)
    jax.block_until_ready(out)
    vcycle_s = (time.perf_counter() - t0) / 3
    emit(json.dumps({
        "suite": "mg_scale", "name": "vcycle",
        "apply_secs": round(vcycle_s, 3),
        "platform": "cpu"}), flush=True)

    # outer MG-GCR solve
    t0 = time.perf_counter()
    res_mg, _ = mg_solve(d, geom, b, None, tol=tol, nkrylov=nkrylov,
                         max_restarts=80, mg=mg)
    jax.block_until_ready(res_mg.x)
    mg_solve_s = time.perf_counter() - t0
    r = b - d.M(res_mg.x)
    true_res = float(jnp.sqrt(blas.norm2(r) / blas.norm2(b)))

    # plain CG on the same system (CGNR)
    t0 = time.perf_counter()
    res_cg = cg(d.MdagM, d.Mdag(b), tol=tol, maxiter=4000)
    jax.block_until_ready(res_cg.x)
    cg_s = time.perf_counter() - t0

    emit(json.dumps({
        "suite": "mg_scale", "name": "solve_vs_cg",
        "mg_outer_iters": int(res_mg.iters),
        "mg_converged": bool(res_mg.converged),
        "mg_secs": round(mg_solve_s, 1), "mg_true_res": true_res,
        "cg_iters": int(res_cg.iters),
        "cg_converged": bool(res_cg.converged),
        "cg_secs": round(cg_s, 1),
        "rss_mb_total": round(_rss_mb() - rss0, 1),
        "platform": "cpu"}), flush=True)

    # Sharded V-cycle at volume LAST (records above are already flushed):
    # the GSPMD path a TPU pod runs, exercised like __graft_entry__'s
    # dryrun.  On 1-core hosts XLA:CPU's 40 s collective-rendezvous
    # watchdog can abort the process under load — that is a property of
    # the emulation host, not of the sharding, so it must not take the
    # measured records with it.
    try:
        mesh = make_lattice_mesh()        # 8 virtual devices over t/z/y/x
        b_sh = shard_spinor(b, mesh)
        pre_sh = jax.jit(mg.precondition)
        with mesh:
            jax.block_until_ready(pre_sh(b_sh))      # compile + warm
            t0 = time.perf_counter()
            out = pre_sh(b_sh)
            jax.block_until_ready(out)
            sharded_s = time.perf_counter() - t0
        emit(json.dumps({
            "suite": "mg_scale", "name": "vcycle_sharded_mesh8",
            "apply_secs": round(sharded_s, 3),
            "platform": "cpu-mesh8"}), flush=True)
    except Exception as e:                      # pragma: no cover
        emit(json.dumps({
            "suite": "mg_scale", "name": "vcycle_sharded_mesh8",
            "error": str(e)[:160]}), flush=True)
    return res_mg, res_cg


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--lat", type=int, nargs=4, default=[32, 32, 32, 64],
                    help="X Y Z T — LatticeGeometry dims order "
                         "(default 32^3 spatial, T=64)")
    ap.add_argument("--nvec", type=int, default=12)
    ap.add_argument("--kappa", type=float, default=0.124)
    ap.add_argument("--csw", type=float, default=1.0)
    ap.add_argument("--tol", type=float, default=1e-8)
    ap.add_argument("--setup-iters", type=int, default=60)
    ap.add_argument("--scale", type=float, default=None,
                    help="gauge disorder scale (None = fully random; "
                         "~0.15 = smooth, the MG regime)")
    ap.add_argument("--nkrylov", type=int, default=16)
    a = ap.parse_args()
    _configure()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    run(a.lat, a.nvec, a.kappa, a.csw, a.tol, a.setup_iters,
        gauge_scale=a.scale, nkrylov=a.nkrylov)
