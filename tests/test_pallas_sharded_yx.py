"""Round 18: y/x-sharded pallas dslash on 3D/4D virtual meshes.

The v2-form sharded stencils generalize beyond t/z — the y axis rides
pre-rotated row strips on the fused y*x array axis, the x axis rides
block-contiguous relayout (parallel/mesh.fuse_block_layout) + strided
column gathers — and every new seam must bit-match the single-device
stencil and land its bytes in the ICI ledger.  Heavy mesh shapes are
slow-marked; the fast tier keeps one 2-device witness per new axis
plus the pure-python policy-engine contracts."""

import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from quda_tpu.parallel import compat

pytestmark = pytest.mark.skipif(
    not compat.has_shard_map(),
    reason="no shard_map API in this jax version")

from quda_tpu.fields.geometry import LatticeGeometry
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.fields.spinor import ColorSpinorField, even_odd_split
from quda_tpu.ops import blas
from quda_tpu.ops import wilson_packed as wpk
from quda_tpu.ops import wilson_pallas_packed as wpp
from quda_tpu.parallel.mesh import (fuse_block_layout, make_lattice_mesh,
                                    unfuse_block_layout)
from quda_tpu.parallel.pallas_dslash import (AXIS_NAMES, FUSED_HALO_AXES,
                                             SHARDED_POLICIES,
                                             _policy_label,
                                             resolve_axis_policies)

PSI_SPEC = P(None, None, None, "t", "z", ("y", "x"))
G_SPEC = P(None, None, None, None, "t", "z", ("y", "x"))
STAG_PSI_SPEC = P(None, None, "t", "z", ("y", "x"))


# -- the per-axis policy engine (pure python, fast tier) --------------------

def test_resolve_axis_policies_forms():
    """Bare name maps onto every axis (fused_halo keeps facefix on x),
    spec strings pin axes individually with facefix defaults, dicts
    pass through normalized."""
    assert resolve_axis_policies("xla_facefix") == {
        a: "xla_facefix" for a in AXIS_NAMES}
    fh = resolve_axis_policies("fused_halo")
    assert fh == {"t": "fused_halo", "z": "fused_halo",
                  "y": "fused_halo", "x": "xla_facefix"}
    spec = resolve_axis_policies("t=fused_halo, y=xla_facefix")
    assert spec == {"t": "fused_halo", "z": "xla_facefix",
                    "y": "xla_facefix", "x": "xla_facefix"}
    assert resolve_axis_policies(spec) == spec


def test_resolve_axis_policies_rejects():
    with pytest.raises(ValueError, match="unknown sharded halo policy"):
        resolve_axis_policies("bogus")
    with pytest.raises(ValueError, match="unknown sharded halo policy"):
        resolve_axis_policies("t=bogus")
    with pytest.raises(ValueError, match="unknown mesh axis"):
        resolve_axis_policies("w=fused_halo")
    # an EXPLICIT x=fused_halo is an error (strided column face), while
    # the bare legacy name silently keeps facefix there
    with pytest.raises(ValueError, match="strided column"):
        resolve_axis_policies("x=fused_halo")


def test_policy_label_is_joint():
    """The ledger scope carries ONE label: the plain name when every
    partitioned axis agrees, else the per-axis spec (obs/comms groups
    within a scope are alternatives — a per-axis label split would
    fracture the invocation model)."""
    pols = resolve_axis_policies("t=fused_halo,z=fused_halo")
    assert _policy_label(pols, ("t", "z")) == "fused_halo"
    assert _policy_label(pols, ("t", "z", "y")) == \
        "t=fused_halo,z=fused_halo,y=xla_facefix"
    assert _policy_label(resolve_axis_policies("xla_facefix"), ()) == \
        "xla_facefix"


# -- fixtures ---------------------------------------------------------------

def _eo_fixture(key1=51, key2=52, fold_t=True, shape=(4, 4, 8, 16)):
    """(dims, g_eo_pp, (pe, po)) — the test_pallas_sharded eo fixture
    (ctor order x,y,z,t; folded antiperiodic t so shard-edge signs are
    exercised), duplicated here because test modules are not a
    package."""
    from quda_tpu.ops.boundary import apply_t_boundary
    from quda_tpu.ops.wilson import split_gauge_eo
    geom = LatticeGeometry(shape)
    dims = geom.lattice_shape
    gauge = GaugeField.random(jax.random.PRNGKey(key1), geom
                              ).data.astype(jnp.complex64)
    if fold_t:
        gauge = apply_t_boundary(gauge, geom, -1)
    psi = ColorSpinorField.gaussian(jax.random.PRNGKey(key2), geom
                                    ).data.astype(jnp.complex64)
    g_eo = split_gauge_eo(gauge, geom)
    g_eo_pp = tuple(wpk.to_packed_pairs(wpk.pack_gauge(g), jnp.float32)
                    for g in g_eo)
    return dims, g_eo_pp, even_odd_split(psi, geom)


def _run_sharded_eo(dims, g_eo_pp, parity, src_pp, grid, policy,
                    recon12=False):
    """Shard the eo v2 stencil over ``grid`` (any axes, x included via
    block-contiguous relayout) and return the output in NATURAL
    layout."""
    from quda_tpu.parallel.pallas_dslash import dslash_eo_pallas_sharded
    T, Z, Y, X = dims
    n_dev = int(np.prod(grid))
    mesh = make_lattice_mesh(grid=grid, n_src=1,
                             devices=jax.devices()[:n_dev])
    n_y, n_x = grid[2], grid[3]
    uh, ut = g_eo_pp[parity], g_eo_pp[1 - parity]
    if recon12:
        uh, ut = wpp.to_recon12(uh), wpp.to_recon12(ut)
    # GLOBAL pre-shift on the NATURAL layout, THEN block-relayout, THEN
    # shard (the v2 design, x-generalized)
    u_bw = wpp.backward_gauge_eo(ut, dims, parity)
    rl = lambda a: fuse_block_layout(a, n_y, n_x, Y, X // 2)
    fn = compat.shard_map(
        lambda a, b, p: dslash_eo_pallas_sharded(
            a, b, p, dims, parity, mesh, interpret=True, policy=policy),
        mesh=mesh, in_specs=(G_SPEC, G_SPEC, PSI_SPEC),
        out_specs=PSI_SPEC)
    uh_s = jax.device_put(rl(uh), NamedSharding(mesh, G_SPEC))
    ub_s = jax.device_put(rl(u_bw), NamedSharding(mesh, G_SPEC))
    src_s = jax.device_put(rl(src_pp), NamedSharding(mesh, PSI_SPEC))
    out = jax.jit(fn)(uh_s, ub_s, src_s)
    return unfuse_block_layout(out, n_y, n_x, Y, X // 2)


# -- fast witnesses: one per new axis ---------------------------------------

@pytest.mark.slow
def test_sharded_wilson_full_y_matches_single_device():
    """y-partitioned full-lattice Wilson: the fused y*x axis splits into
    contiguous row strips (n_x=1 needs no relayout) and the y face fix
    exchanges one row strip per direction — must bit-match the
    single-device pair stencil on a 2-device mesh.  (Slow: interpret
    -mode kernel compiles push it past the 30s fast budget; the fast
    tier keeps the x-sharded eo bit-match which covers the same
    wrapper seam.)"""
    from quda_tpu.parallel.pallas_dslash import dslash_pallas_sharded
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual devices")
    geom = LatticeGeometry((4, 4, 4, 4))
    T, Z, Y, X = geom.lattice_shape
    gauge = GaugeField.random(jax.random.PRNGKey(21), geom
                              ).data.astype(jnp.complex64)
    psi = ColorSpinorField.gaussian(jax.random.PRNGKey(22), geom
                                    ).data.astype(jnp.complex64)
    gp = wpp.to_pallas_layout(wpk.pack_gauge(gauge))
    pp = wpp.to_pallas_layout(wpk.pack_spinor(psi))
    gbw = wpp.backward_gauge(gp, X)
    ref = wpk.dslash_packed_pairs(gp, pp, X, Y)

    mesh = make_lattice_mesh(grid=(1, 1, 2, 1), n_src=1,
                             devices=jax.devices()[:2])
    fn = compat.shard_map(
        lambda g, gb, p: dslash_pallas_sharded(g, gb, p, X, mesh,
                                               interpret=True),
        mesh=mesh, in_specs=(G_SPEC, G_SPEC, PSI_SPEC),
        out_specs=PSI_SPEC)
    out = jax.jit(fn)(jax.device_put(gp, NamedSharding(mesh, G_SPEC)),
                      jax.device_put(gbw, NamedSharding(mesh, G_SPEC)),
                      jax.device_put(pp, NamedSharding(mesh, PSI_SPEC)))
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


def test_sharded_wilson_eo_x_matches_single_device():
    """x-partitioned eo Wilson: block-contiguous relayout makes each
    shard a (Y x Xh_loc) rectangle and the strided column faces ride
    the exchange — the odd-hop slot-select seam of the checkerboard,
    on a 2-device mesh."""
    dims, g_eo_pp, (pe, po) = _eo_fixture(shape=(8, 4, 4, 4))
    parity = 0
    src_pp = wpk.to_packed_pairs(wpk.pack_spinor(po), jnp.float32)
    ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual devices")
    out = _run_sharded_eo(dims, g_eo_pp, parity, src_pp,
                          grid=(1, 1, 1, 2), policy="xla_facefix")
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


def test_psum_free_on_size1_mesh_axes():
    """Satellite: parallel/halo.psum_scalar psums over all four lattice
    axes unconditionally, claiming size-1 axes are free.  Pin it: on a
    t/z-only mesh the compiled all-reduce replica groups are IDENTICAL
    to a psum over just the live axes (the y/x names add no collective),
    and the ICI ledger records no exchange rows for it (reductions are
    not halo traffic)."""
    from quda_tpu.obs import comms as ocomms
    from quda_tpu.parallel.halo import psum_scalar
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    mesh = make_lattice_mesh(grid=(2, 2, 1, 1), n_src=1,
                             devices=jax.devices()[:4])
    spec = P("t", "z", "y", "x")
    x = jnp.arange(16, dtype=jnp.float32).reshape(2, 2, 2, 2)

    def compiled_allreduce_groups(body):
        fn = jax.jit(compat.shard_map(body, mesh=mesh, in_specs=(spec,),
                                      out_specs=P(None, None, None,
                                                  None)))
        txt = fn.lower(x).compile().as_text()
        groups = [ln.split("replica_groups=")[1].split(",")[0]
                  for ln in txt.splitlines()
                  if "all-reduce" in ln and "replica_groups=" in ln]
        return fn, groups

    f_all, g_all = compiled_allreduce_groups(
        lambda a: psum_scalar(jnp.sum(a), mesh))
    f_live, g_live = compiled_allreduce_groups(
        lambda a: jax.lax.psum(jnp.sum(a), ("t", "z")))
    assert g_all, "no all-reduce in the compiled psum"
    assert g_all == g_live          # size-1 y/x axes add no collective
    ocomms.reset()
    ocomms.start()
    try:
        total = f_all(jax.device_put(x, NamedSharding(mesh, spec)))
        assert float(total) == float(jnp.sum(x))
        assert ocomms.ledger() == []   # no halo bytes attributed
    finally:
        ocomms.reset()


@pytest.mark.slow
def test_operator_x_sharded_mesh_roundtrip():
    """Model-level x sharding: DiracWilsonPC.pairs(mesh=...) with an
    x-partitioned mesh block-relayouts its links and pair fields
    (_yx_block_pairs) and MdagM_pairs matches the unsharded operator
    after the inverse relayout.  (Slow: four interpret-mode kernel
    compiles — the fast tier keeps the wrapper-level x bit-match.)"""
    from quda_tpu.models.wilson import DiracWilsonPC
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 virtual devices")
    geom = LatticeGeometry((8, 4, 4, 4))     # (T,Z,Y,X) = (4,4,4,8)
    gauge = GaugeField.random(jax.random.PRNGKey(23), geom
                              ).data.astype(jnp.complex64)
    psi = ColorSpinorField.gaussian(jax.random.PRNGKey(24), geom
                                    ).data.astype(jnp.complex64)
    pe, po = even_odd_split(psi, geom)
    dpk = DiracWilsonPC(gauge, geom, kappa=0.11).packed()
    ref_op = dpk.pairs(jnp.float32)
    ref = ref_op.MdagM_pairs(ref_op.prepare_pairs(pe, po))

    mesh = make_lattice_mesh(grid=(1, 1, 1, 2), n_src=1,
                             devices=jax.devices()[:2])
    op = dpk.pairs(jnp.float32, use_pallas=True, pallas_interpret=True,
                   mesh=mesh, sharded_policy="xla_facefix")
    assert op._mesh_yx == (1, 2)
    out = op.MdagM_pairs(op.prepare_pairs(pe, po))
    out_nat = op._yx_block_pairs(out, inverse=True)
    err = float(jnp.sqrt(blas.norm2(ref - out_nat) / blas.norm2(ref)))
    assert err < 1e-5


def test_operator_accepts_per_axis_policy_spec():
    """QUDA_TPU_SHARDED_POLICY accepts the per-axis spec string at the
    operator seam and resolves it into the full {axis: policy} map."""
    from quda_tpu.models.wilson import DiracWilsonPC
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 virtual devices")
    geom = LatticeGeometry((4, 4, 4, 4))
    gauge = GaugeField.random(jax.random.PRNGKey(25), geom
                              ).data.astype(jnp.complex64)
    mesh = make_lattice_mesh(grid=(2, 2, 1, 1), n_src=1,
                             devices=jax.devices()[:4])
    op = DiracWilsonPC(gauge, geom, kappa=0.1).packed().pairs(
        jnp.float32, use_pallas=True, pallas_interpret=True, mesh=mesh,
        sharded_policy="t=xla_facefix,z=xla_facefix")
    assert op._sharded_policy == {a: "xla_facefix" for a in AXIS_NAMES}


# -- slow: 3D/4D mesh bit-match sweeps --------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("parity", [0, 1])
def test_sharded_wilson_eo_3d_matches_single_device(parity):
    """Acceptance: eo Wilson v2 on a 3D (2,2,2,1) mesh — t, z AND y
    partitioned — bit-matches the single-device stencil, both
    parities."""
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    dims, g_eo_pp, (pe, po) = _eo_fixture()
    src = pe if parity == 1 else po
    src_pp = wpk.to_packed_pairs(wpk.pack_spinor(src), jnp.float32)
    ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
    out = _run_sharded_eo(dims, g_eo_pp, parity, src_pp,
                          grid=(2, 2, 2, 1), policy="xla_facefix")
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


@pytest.mark.slow
@pytest.mark.parametrize("parity", [0, 1])
def test_sharded_wilson_eo_3d_recon12_matches_single_device(parity):
    """reconstruct-12 on the 3D mesh: the y/x face slabs rebuild row 2
    exactly like the t/z slabs (folded antiperiodic-t signs included via
    the fixture's fold)."""
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    dims, g_eo_pp, (pe, po) = _eo_fixture()
    src = pe if parity == 1 else po
    src_pp = wpk.to_packed_pairs(wpk.pack_spinor(src), jnp.float32)
    ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
    out = _run_sharded_eo(dims, g_eo_pp, parity, src_pp,
                          grid=(2, 2, 2, 1), policy="xla_facefix",
                          recon12=True)
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-5          # f32 third-row reconstruction floor


@pytest.mark.slow
def test_sharded_wilson_eo_3axes_with_x_matches_single_device():
    """t+y+x partitioned together: the block-contiguous relayout and
    the strided x column exchange compose with the y row strips and the
    t plane slabs on one mesh."""
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    dims, g_eo_pp, (pe, po) = _eo_fixture(shape=(8, 4, 8, 16))
    parity = 1
    src_pp = wpk.to_packed_pairs(wpk.pack_spinor(pe), jnp.float32)
    ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
    out = _run_sharded_eo(dims, g_eo_pp, parity, src_pp,
                          grid=(2, 1, 2, 2), policy="xla_facefix")
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


@pytest.mark.slow
@pytest.mark.skipif(not compat.has_dist_interpret(),
                    reason="fused_halo needs the distributed Mosaic "
                           "interpreter (pltpu.InterpretParams)")
@pytest.mark.parametrize("parity", [0, 1])
def test_sharded_wilson_eo_fused_halo_y_matches_facefix(parity):
    """Per-axis policy A/B on the 3D mesh: fused RDMA on the contiguous
    y row strip (t/z on facefix) is bit-identical to all-facefix."""
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    dims, g_eo_pp, (pe, po) = _eo_fixture()
    src = pe if parity == 1 else po
    src_pp = wpk.to_packed_pairs(wpk.pack_spinor(src), jnp.float32)
    ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
    out = _run_sharded_eo(
        dims, g_eo_pp, parity, src_pp, grid=(2, 2, 2, 1),
        policy="t=xla_facefix,z=xla_facefix,y=fused_halo")
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


@pytest.mark.slow
@pytest.mark.parametrize("parity", [0, 1])
def test_sharded_staggered_eo_3d_matches_single_device(parity):
    """Checkerboarded staggered fat+Naik on a 3D (2,2,2,1) mesh: the
    y row-strip exchange carries the 2-row Naik window (w=2) and the
    eo slot select holds on every partitioned axis."""
    from quda_tpu.ops import staggered_packed as spk
    from quda_tpu.ops import staggered_pallas as stp
    from quda_tpu.ops.wilson import split_gauge_eo
    from quda_tpu.parallel.pallas_dslash import (
        dslash_staggered_eo_pallas_sharded)
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    # local extents must be >= 3 on every partitioned axis (Naik
    # 3-hop crosses at most one shard boundary) and even (eo masks):
    # 8/2 = 4 on t, z, and y
    geom = LatticeGeometry((8, 8, 8, 8))     # (T,Z,Y,X) = (8,8,8,8)
    T, Z, Y, X = geom.lattice_shape
    dims = (T, Z, Y, X)
    fat_c = GaugeField.random(jax.random.PRNGKey(71), geom
                              ).data.astype(jnp.complex64)
    long_c = GaugeField.random(jax.random.PRNGKey(72), geom
                               ).data.astype(jnp.complex64)
    psi = ColorSpinorField.gaussian(jax.random.PRNGKey(73), geom
                                    ).data.astype(jnp.complex64)[..., :1, :]
    fat_eo = split_gauge_eo(fat_c, geom)
    long_eo = split_gauge_eo(long_c, geom)
    pe, po = even_odd_split(psi, geom)
    src = pe if parity == 1 else po
    fat_eo_pp = tuple(wpk.to_packed_pairs(spk.pack_links(g), jnp.float32)
                      for g in fat_eo)
    long_eo_pp = tuple(wpk.to_packed_pairs(spk.pack_links(g),
                                           jnp.float32)
                       for g in long_eo)
    src_pp = wpk.to_packed_pairs(spk.pack_staggered(src), jnp.float32)
    ref = spk.dslash_staggered_eo_packed_pairs(
        fat_eo_pp, src_pp, dims, parity, long_eo_pp)
    fat_bw = stp.backward_links_eo(fat_eo_pp[1 - parity], dims, parity,
                                   1)
    long_bw = stp.backward_links_eo(long_eo_pp[1 - parity], dims,
                                    parity, 3)
    mesh = make_lattice_mesh(grid=(2, 2, 2, 1), n_src=1)
    fn = compat.shard_map(
        lambda fh, fb, lh, lb, p: dslash_staggered_eo_pallas_sharded(
            fh, fb, p, dims, parity, mesh, long_here_pl=lh,
            long_bw_pl=lb, interpret=True),
        mesh=mesh, in_specs=(G_SPEC,) * 4 + (STAG_PSI_SPEC,),
        out_specs=STAG_PSI_SPEC)
    args = [jax.device_put(a, NamedSharding(mesh, G_SPEC))
            for a in (fat_eo_pp[parity], fat_bw, long_eo_pp[parity],
                      long_bw)]
    src_s = jax.device_put(src_pp, NamedSharding(mesh, STAG_PSI_SPEC))
    out = jax.jit(fn)(*args, src_s)
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


@pytest.mark.slow
def test_sharded_staggered_full_yx_matches_single_device():
    """Full-lattice staggered fat+Naik with y AND x partitioned
    (2,1,2,2): the 3-hop Naik slabs cross the y strip seam and the x
    wrap masks hold at the block-relayout shard edges."""
    from quda_tpu.ops import staggered_packed as spk
    from quda_tpu.ops import staggered_pallas as stp
    from quda_tpu.parallel.pallas_dslash import (
        dslash_staggered_pallas_sharded)
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    geom = LatticeGeometry((16, 8, 4, 8))    # (T,Z,Y,X) = (8,4,8,16)
    T, Z, Y, X = geom.lattice_shape
    fat_pp = wpk.to_packed_pairs(spk.pack_links(
        GaugeField.random(jax.random.PRNGKey(74), geom
                          ).data.astype(jnp.complex64)), jnp.float32)
    long_pp = wpk.to_packed_pairs(spk.pack_links(
        GaugeField.random(jax.random.PRNGKey(75), geom
                          ).data.astype(jnp.complex64)), jnp.float32)
    psi_pp = wpk.to_packed_pairs(spk.pack_staggered(
        ColorSpinorField.gaussian(jax.random.PRNGKey(76), geom
                                  ).data.astype(jnp.complex64)[..., :1, :]
    ), jnp.float32)
    ref = spk.dslash_staggered_packed_pairs(fat_pp, psi_pp, X, Y,
                                            long_pp)
    fat_bw = stp.backward_links(fat_pp, X, 1)
    long_bw = stp.backward_links(long_pp, X, 3)
    grid = (2, 1, 2, 2)
    mesh = make_lattice_mesh(grid=grid, n_src=1)
    n_y, n_x = grid[2], grid[3]
    rl = lambda a: fuse_block_layout(a, n_y, n_x, Y, X)
    fn = compat.shard_map(
        lambda f, fb, l, lb, p: dslash_staggered_pallas_sharded(
            f, fb, p, X, mesh, long_pl=l, long_bw_pl=lb,
            interpret=True),
        mesh=mesh, in_specs=(G_SPEC,) * 4 + (STAG_PSI_SPEC,),
        out_specs=STAG_PSI_SPEC)
    args = [jax.device_put(rl(a), NamedSharding(mesh, G_SPEC))
            for a in (fat_pp, fat_bw, long_pp, long_bw)]
    psi_s = jax.device_put(rl(psi_pp),
                           NamedSharding(mesh, STAG_PSI_SPEC))
    out = unfuse_block_layout(jax.jit(fn)(*args, psi_s), n_y, n_x, Y, X)
    err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert err < 1e-6


@pytest.mark.slow
def test_sharded_wilson_eo_4d_mesh_subprocess():
    """True 4D decomposition — all four lattice axes partitioned on a
    (2,2,2,2) mesh — needs 16 virtual devices, so it runs in a
    subprocess with its own XLA_FLAGS (the in-process runtime is pinned
    to 8)."""
    code = r"""
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from quda_tpu.fields.geometry import LatticeGeometry
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.fields.spinor import ColorSpinorField, even_odd_split
from quda_tpu.ops import blas
from quda_tpu.ops import wilson_packed as wpk
from quda_tpu.ops import wilson_pallas_packed as wpp
from quda_tpu.ops.wilson import split_gauge_eo
from quda_tpu.parallel import compat
from quda_tpu.parallel.mesh import (fuse_block_layout, make_lattice_mesh,
                                    unfuse_block_layout)
from quda_tpu.parallel.pallas_dslash import dslash_eo_pallas_sharded
assert len(jax.devices()) == 16, len(jax.devices())
geom = LatticeGeometry((8, 4, 4, 4))        # (T,Z,Y,X) = (4,4,4,8)
dims = geom.lattice_shape
T, Z, Y, X = dims
gauge = GaugeField.random(jax.random.PRNGKey(81), geom
                          ).data.astype(jnp.complex64)
psi = ColorSpinorField.gaussian(jax.random.PRNGKey(82), geom
                                ).data.astype(jnp.complex64)
g_eo = split_gauge_eo(gauge, geom)
g_eo_pp = tuple(wpk.to_packed_pairs(wpk.pack_gauge(g), jnp.float32)
                for g in g_eo)
pe, po = even_odd_split(psi, geom)
parity = 0
src_pp = wpk.to_packed_pairs(wpk.pack_spinor(po), jnp.float32)
ref = wpk.dslash_eo_packed_pairs(g_eo_pp, src_pp, dims, parity)
grid = (2, 2, 2, 2)
mesh = make_lattice_mesh(grid=grid, n_src=1)
u_bw = wpp.backward_gauge_eo(g_eo_pp[1 - parity], dims, parity)
rl = lambda a: fuse_block_layout(a, 2, 2, Y, X // 2)
psi_spec = P(None, None, None, "t", "z", ("y", "x"))
g_spec = P(None, None, None, None, "t", "z", ("y", "x"))
fn = compat.shard_map(
    lambda a, b, p: dslash_eo_pallas_sharded(
        a, b, p, dims, parity, mesh, interpret=True,
        policy="xla_facefix"),
    mesh=mesh, in_specs=(g_spec, g_spec, psi_spec),
    out_specs=psi_spec)
out = jax.jit(fn)(
    jax.device_put(rl(g_eo_pp[parity]), NamedSharding(mesh, g_spec)),
    jax.device_put(rl(u_bw), NamedSharding(mesh, g_spec)),
    jax.device_put(rl(src_pp), NamedSharding(mesh, psi_spec)))
out = unfuse_block_layout(out, 2, 2, Y, X // 2)
err = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
assert err < 1e-6, err
print("4D_OK", err)
"""
    import os
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=16")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=560)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "4D_OK" in res.stdout


# -- slow: ICI attribution on the 3D mesh -----------------------------------

@pytest.mark.slow
def test_halo_model_matches_ledger_on_3d_mesh(monkeypatch):
    """Acceptance: the analytic per-axis halo model is pinned BIT-EQUAL
    to the ledger rows on a 3D mesh — per-parity site totals equal the
    model's per-device bytes, the per-axis split equals model["axes"],
    and the solve attribution emits one ici sub-row per partitioned
    axis."""
    from quda_tpu.models.wilson import DiracWilsonPC
    from quda_tpu.obs import comms as ocomms
    from quda_tpu.utils import config as qconf
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    monkeypatch.setenv("QUDA_TPU_TRACE", "1")
    qconf.reset_cache()
    ocomms.reset()
    assert ocomms.maybe_start() is not None
    try:
        geom = LatticeGeometry((4, 4, 4, 8))   # (T,Z,Y,X) = (8,4,4,4)
        dims = geom.lattice_shape
        gauge = GaugeField.random(jax.random.PRNGKey(91), geom
                                  ).data.astype(jnp.complex64)
        psi = ColorSpinorField.gaussian(jax.random.PRNGKey(92), geom
                                        ).data.astype(jnp.complex64)
        pe, po = even_odd_split(psi, geom)
        mesh = make_lattice_mesh(grid=(2, 2, 2, 1), n_src=1)
        op = DiracWilsonPC(gauge, geom, kappa=0.1).packed().pairs(
            jnp.float32, use_pallas=True, pallas_interpret=True,
            mesh=mesh, sharded_policy="xla_facefix")
        rhs = op.prepare_pairs(pe, po)
        out = jax.jit(op.MdagM_pairs)(rhs)
        out.block_until_ready()

        model = ocomms.wilson_eo_halo_model(dims, (2, 2, 2, 1))
        assert set(model["axes"]) == {"t", "z", "y"}
        rows = ocomms.ledger()
        assert rows, "sharded apply recorded no ledger rows"
        per_site = {}
        per_site_axis = {}
        for r in rows:
            assert r["policy"] == "xla_facefix"
            assert r["axis"] in ("t", "z", "y")
            assert r["mesh"] == "2x2x2x1"
            per_site[r["site"]] = per_site.get(r["site"], 0) + r["bytes"]
            k = (r["site"], r["axis"])
            per_site_axis[k] = per_site_axis.get(k, 0) + r["bytes"]
        assert set(per_site) == {"wilson_eo_sharded_v2:p0",
                                 "wilson_eo_sharded_v2:p1"}
        for site, total in per_site.items():
            assert total == model["per_device"], (site, total, model)
            for ax, b in model["axes"].items():
                assert per_site_axis[(site, ax)] == b, (site, ax)
        assert ocomms.per_invocation_bytes() == model["per_device"]
        row = ocomms.attribute_solve("wilson_sharded_v2", 1, 1.0, 1.0)
        assert row["devices"] == 8
        assert row["axes"] == "t+y+z"
        subs = [r for r in ocomms.solve_rows()
                if r["form"].startswith("ici:wilson_sharded_v2:")]
        assert {r["form"] for r in subs} == {
            "ici:wilson_sharded_v2:t", "ici:wilson_sharded_v2:z",
            "ici:wilson_sharded_v2:y"}
        for r in subs:
            ax = r["form"].rsplit(":", 1)[1]
            assert r["bytes_per_invocation_per_device"] == \
                model["axes"][ax]
    finally:
        ocomms.reset()


@pytest.mark.slow
def test_split_grid_composes_with_mesh_sharding(monkeypatch):
    """Satellite: split-grid x mesh-sharding on one (src=2, t=2, z=2)
    mesh — the multi-src solve matches the single-chip batched solve
    (to f32 roundoff: GSPMD partitioning reorders the CG reductions
    vs the eager vmap reference), the mesh-sharded operator runs on
    the same mesh (src axis replicated), and the ICI ledger attributes
    the src gauge replication and the t/z halo exchanges as SEPARATE
    rows."""
    from quda_tpu.models.wilson import DiracWilsonPC
    from quda_tpu.obs import comms as ocomms
    from quda_tpu.ops import wilson as wops
    from quda_tpu.parallel.split import split_grid_solve
    from quda_tpu.solvers.cg import cg_fixed_iters
    from quda_tpu.utils import config as qconf
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    monkeypatch.setenv("QUDA_TPU_TRACE", "1")
    qconf.reset_cache()
    ocomms.reset()
    assert ocomms.maybe_start() is not None
    try:
        geom = LatticeGeometry((8, 4, 4, 4))   # (T,Z,Y,X) = (4,4,4,8)
        mesh = make_lattice_mesh(grid=(2, 2, 1, 1), n_src=2)
        assert dict(mesh.shape)["src"] == 2
        gauge = GaugeField.random(jax.random.PRNGKey(93), geom
                                  ).data.astype(jnp.complex64)
        key = jax.random.PRNGKey(94)
        B = jnp.stack([ColorSpinorField.gaussian(
            jax.random.fold_in(key, i), geom
        ).data.astype(jnp.complex64) for i in range(2)])

        def solve_one(g, b):
            mv = lambda v: wops.matvec_full(g, v, 0.1)
            from quda_tpu.models.dirac import apply_gamma5
            mdag = lambda v: apply_gamma5(mv(apply_gamma5(v)))
            rhs = mdag(b)
            return cg_fixed_iters(lambda v: mdag(mv(v)), rhs, None,
                                  12)[0].x
        out = split_grid_solve(solve_one, gauge, B, mesh)
        want = jax.vmap(lambda b: solve_one(gauge, b))(B)
        err_b = float(jnp.sqrt(blas.norm2(out - want)
                               / blas.norm2(want)))
        assert err_b < 1e-5, err_b

        # mesh-sharded pairs operator ON THE SAME MESH: the src axis is
        # simply replicated by the PartitionSpecs — split-grid and
        # lattice decomposition compose on one device grid
        psi = ColorSpinorField.gaussian(jax.random.PRNGKey(95), geom
                                        ).data.astype(jnp.complex64)
        pe, po = even_odd_split(psi, geom)
        dpk = DiracWilsonPC(gauge, geom, kappa=0.1).packed()
        ref_op = dpk.pairs(jnp.float32)
        ref = ref_op.MdagM_pairs(ref_op.prepare_pairs(pe, po))
        op = dpk.pairs(jnp.float32, use_pallas=True,
                       pallas_interpret=True, mesh=mesh,
                       sharded_policy="xla_facefix")
        out_pp = jax.jit(op.MdagM_pairs)(op.prepare_pairs(pe, po))
        err = float(jnp.sqrt(blas.norm2(ref - out_pp)
                             / blas.norm2(ref)))
        assert err < 1e-5

        rows = ocomms.ledger()
        rep = [r for r in rows if r["direction"] == "replicate"]
        exch = [r for r in rows if r["direction"] != "replicate"]
        assert len(rep) == 1 and rep[0]["site"] == "split_grid:gauge"
        assert rep[0]["axis"] == "src"
        assert exch and {r["axis"] for r in exch} == {"t", "z"}
        assert all(r["site"].startswith("wilson_eo_sharded_v2")
                   for r in exch)
    finally:
        ocomms.reset()
