"""Mixed-precision solver tests: reliable updates + iterative refinement.

Sloppy = complex64, precise = complex128 (the CPU analog of the TPU's
f32-precise / bf16-sloppy pairing).  Plain single-precision CG stalls well
above 1e-10; the mixed schemes must reach it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quda_tpu.fields.geometry import EVEN, LatticeGeometry
from quda_tpu.fields.spinor import ColorSpinorField, even_odd_split
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.models.wilson import DiracWilsonPC
from quda_tpu.ops import blas
from quda_tpu.solvers.cg import cg
from quda_tpu.solvers.mixed import cg_reliable, solve_refined

GEOM = LatticeGeometry((8, 8, 8, 8))
KAPPA = 0.125
TOL = 1e-10


@pytest.fixture(scope="module")
def problem():
    key = jax.random.PRNGKey(21)
    k1, k2 = jax.random.split(key)
    gauge = GaugeField.random(k1, GEOM).data
    b_full = ColorSpinorField.gaussian(k2, GEOM).data
    dpc = DiracWilsonPC(gauge, GEOM, KAPPA)
    be, bo = even_odd_split(b_full, GEOM)
    rhs = dpc.Mdag(dpc.prepare(be, bo))
    dpc_lo = DiracWilsonPC(gauge.astype(jnp.complex64), GEOM, KAPPA)
    return dpc, dpc_lo, rhs


def test_pure_single_stalls(problem):
    """Sanity: single-precision CG cannot reach a TRUE residual of 1e-10
    (its recursive residual under-reports) — motivates mixing."""
    dpc, dpc_lo, rhs = problem
    res = cg(dpc_lo.MdagM, rhs.astype(jnp.complex64), tol=TOL, maxiter=500)
    true_r2 = blas.norm2(rhs - dpc.MdagM(res.x.astype(jnp.complex128)))
    assert float(jnp.sqrt(true_r2 / blas.norm2(rhs))) > 10 * TOL


def test_cg_reliable_reaches_double_tol(problem):
    dpc, dpc_lo, rhs = problem
    res = jax.jit(lambda b: cg_reliable(
        dpc.MdagM, dpc_lo.MdagM, b, jnp.complex64, tol=TOL,
        maxiter=2000))(rhs)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL


def test_refinement_reaches_double_tol(problem):
    dpc, dpc_lo, rhs = problem
    inner = jax.jit(lambda r: cg(dpc_lo.MdagM, r, tol=1e-5, maxiter=500).x)
    res = solve_refined(dpc.MdagM, inner, rhs, jnp.complex64, tol=TOL)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL


def test_reliable_iters_comparable_to_pure_double(problem):
    """Reliable-update CG shouldn't need dramatically more iterations."""
    dpc, dpc_lo, rhs = problem
    res_d = cg(dpc.MdagM, rhs, tol=TOL, maxiter=2000)
    res_m = cg_reliable(dpc.MdagM, dpc_lo.MdagM, rhs, jnp.complex64,
                        tol=TOL, maxiter=2000)
    assert int(res_m.iters) < 3 * int(res_d.iters)


# -- bf16/int8 pair-storage sloppy path (ops/pair.py) ----------------------

def test_pair_stencil_matches_complex(problem):
    """bf16 pair-form PC Wilson matvec tracks the exact operator to the
    bf16 rounding level (and int8 block-float to its scale)."""
    dpc, _, rhs = problem
    v = rhs.astype(jnp.complex64)
    exact = dpc.M(rhs)
    for prec, bound in (("half", 0.02), ("quarter", 0.05)):
        sl = dpc.sloppy(prec)
        err = blas.norm2(exact - sl.M(v).astype(rhs.dtype))
        assert float(jnp.sqrt(err / blas.norm2(exact))) < bound


def test_cg_reliable_bf16_pairs_reaches_double_tol(problem):
    """The whole sloppy loop runs on bf16 pair storage (QUDA half) and
    still reaches a precise-level 1e-10 true residual, at a comparable
    iteration count to pure precise CG."""
    from quda_tpu.solvers.mixed import pair_codec
    dpc, _, rhs = problem
    sl = dpc.sloppy("half")
    codec = pair_codec(jnp.bfloat16, rhs.dtype)
    res = cg_reliable(dpc.MdagM, sl.MdagM_pairs, rhs, tol=TOL,
                      maxiter=2000, codec=codec)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL
    res_d = cg(dpc.MdagM, rhs, tol=TOL, maxiter=2000)
    assert int(res.iters) < 2 * int(res_d.iters)


def test_cg_reliable_int8_pairs_converges(problem):
    """Quarter (int8 block-float gauge) sloppy operator still converges
    under reliable updates."""
    from quda_tpu.solvers.mixed import pair_codec
    dpc, _, rhs = problem
    sl = dpc.sloppy("quarter")
    codec = pair_codec(jnp.bfloat16, rhs.dtype)
    res = cg_reliable(dpc.MdagM, sl.MdagM_pairs, rhs, tol=TOL,
                      maxiter=4000, codec=codec)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL


def test_api_mixed_bicgstab_refined(problem):
    """BiCGStab with bf16-internal inner solves through the API-level
    defect-correction path converges on the non-Hermitian PC system."""
    from quda_tpu.solvers.bicgstab import bicgstab
    from quda_tpu.solvers.mixed import solve_refined
    dpc, _, _ = problem
    key = jax.random.PRNGKey(5)
    b = even_odd_split(ColorSpinorField.gaussian(key, GEOM).data, GEOM)[0]
    sl = dpc.sloppy("half")
    inner = jax.jit(lambda r: bicgstab(sl.M, r, tol=1e-3, maxiter=500).x)
    res = solve_refined(dpc.M, inner, b, jnp.complex64, tol=1e-9)
    assert bool(res.converged)
    r2 = blas.norm2(b - dpc.M(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(b))) < 2e-9


def test_pair_complex_algebra_and_full_stencil(problem):
    """pair_cdot / pair_caxpy match the complex BLAS, and the full-lattice
    pair stencil matches the canonical full dslash at bf16 accuracy."""
    from quda_tpu.models.wilson import DiracWilson
    from quda_tpu.ops import pair as pops
    from quda_tpu.ops import wilson as wops
    key = jax.random.PRNGKey(9)
    k1, k2, k3 = jax.random.split(key, 3)
    x = (jax.random.normal(k1, (5, 7)) + 1j * jax.random.normal(k2, (5, 7))
         ).astype(jnp.complex64)
    y = (jax.random.normal(k3, (5, 7)) + 0.5j).astype(jnp.complex64)
    xp = pops.to_pairs(x, jnp.float32)
    yp = pops.to_pairs(y, jnp.float32)
    assert np.allclose(complex(pops.pair_cdot(xp, yp)),
                       complex(blas.cdot(x, y)), rtol=1e-5)
    a = 0.3 - 1.7j
    got = pops.from_pairs(pops.pair_caxpy(a, xp, yp), jnp.complex64)
    assert np.allclose(np.asarray(got), np.asarray(y + a * x), rtol=1e-5)

    geom = GEOM
    gauge = GaugeField.random(jax.random.PRNGKey(1), geom).data
    d = DiracWilson(gauge, geom, KAPPA)
    psi = ColorSpinorField.gaussian(jax.random.PRNGKey(2), geom).data
    ref = wops.dslash_full(d.gauge, psi.astype(jnp.complex64))
    gst = pops.encode_gauge(d.gauge.astype(jnp.complex64), "half")
    out = pops.from_pairs(
        pops.dslash_full_pairs(gst, pops.to_pairs(psi, jnp.bfloat16)),
        jnp.complex64)
    rel = float(jnp.sqrt(blas.norm2(ref - out) / blas.norm2(ref)))
    assert rel < 0.02


@pytest.fixture(scope="module")
def api_ctx():
    from quda_tpu.interfaces.params import GaugeParam
    from quda_tpu.interfaces.quda_api import init_quda, load_gauge_quda
    key = jax.random.PRNGKey(21)
    k1, k2 = jax.random.split(key)
    gauge = GaugeField.random(k1, GEOM).data
    b = ColorSpinorField.gaussian(k2, GEOM).data
    init_quda()
    load_gauge_quda(gauge, GaugeParam(X=GEOM.lattice_shape,
                                      cuda_prec="double"))
    return gauge, b


def test_invert_multishift_half_sloppy(api_ctx):
    """Multishift with bf16 sloppy + per-shift precise polish (the TPU
    default path via cuda_prec_sloppy='auto') reaches the tolerance on
    every shifted system."""
    from quda_tpu.fields.spinor import even_odd_split
    from quda_tpu.interfaces.params import InvertParam
    from quda_tpu.interfaces.quda_api import invert_multishift_quda
    from quda_tpu.models.wilson import DiracWilsonPC
    gauge, b = api_ctx
    shifts = (0.01, 0.05, 0.2)
    p = InvertParam(dslash_type="wilson", kappa=KAPPA, inv_type="cg",
                    solve_type="normop-pc", tol=1e-9, maxiter=2000,
                    cuda_prec="double", cuda_prec_sloppy="half",
                    num_offset=len(shifts), offset=shifts)
    xs = invert_multishift_quda(b, p)
    dpc = DiracWilsonPC(gauge, GEOM, KAPPA)
    be, bo = even_odd_split(b, GEOM)
    rhs = dpc.Mdag(dpc.prepare(be, bo))
    for i, s in enumerate(shifts):
        r = rhs - (dpc.MdagM(xs[i]) + s * xs[i])
        assert float(jnp.sqrt(blas.norm2(r) / blas.norm2(rhs))) < 1e-8
    assert p.iter_count > 0


@pytest.mark.parametrize("inv,solve", [
    ("bicgstab", "direct-pc"),
    ("gcr", "normop-pc"),        # inner operator must be MdagM here
    ("cg", "normop-pc"),
])
def test_invert_quda_half_sloppy_branches(api_ctx, inv, solve):
    """invert_quda with cuda_prec_sloppy='half' exercises the pair-sloppy
    branches (cg_reliable codec path / defect-correction bicgstab+gcr),
    including the normop case where the inner operator is MdagM."""
    from quda_tpu.interfaces.params import InvertParam
    from quda_tpu.interfaces.quda_api import invert_quda
    from quda_tpu.models.wilson import DiracWilson
    gauge, b = api_ctx
    tol = 1e-9
    p = InvertParam(dslash_type="wilson", kappa=KAPPA, inv_type=inv,
                    solve_type=solve, tol=tol, maxiter=2000,
                    cuda_prec="double", cuda_prec_sloppy="half")
    x = invert_quda(b, p)
    d = DiracWilson(gauge, GEOM, KAPPA)
    r2 = blas.norm2(b - d.M(jnp.asarray(x)))
    assert float(jnp.sqrt(r2 / blas.norm2(b))) < 10 * tol
    assert p.true_res < 10 * tol


@pytest.mark.parametrize("dslash", ["clover", "twisted-mass", "mobius"])
def test_pair_families_bf16_sloppy_api(api_ctx, dslash, monkeypatch):
    """cuda_prec_sloppy='half' on the new pair families: the mixed CG
    runs the bf16 pair-storage sloppy operator inside cg_reliable and
    still converges to the precise tolerance."""
    from quda_tpu.interfaces import quda_api as api
    from quda_tpu.interfaces.params import InvertParam

    monkeypatch.setenv("QUDA_TPU_PACKED", "1")
    geom = GEOM
    key = jax.random.PRNGKey(91)
    if dslash == "mobius":
        ls = 4
        b = np.asarray(jnp.stack([
            ColorSpinorField.gaussian(jax.random.fold_in(key, s),
                                      geom).data
            for s in range(ls)])).astype(np.complex64)
        p = InvertParam(dslash_type="mobius", kappa=0.0, mass=0.04,
                        m5=-1.4, Ls=ls, b5=1.5, c5=0.5, inv_type="cg",
                        solve_type="direct-pc", cuda_prec="single",
                        cuda_prec_sloppy="half", tol=1e-6, maxiter=4000)
    else:
        b = np.asarray(ColorSpinorField.gaussian(key, geom).data
                       ).astype(np.complex64)
        kw = dict(kappa=0.12, inv_type="cg", solve_type="direct-pc",
                  cuda_prec="single", cuda_prec_sloppy="half",
                  tol=1e-6, maxiter=4000)
        if dslash == "clover":
            kw["csw"] = 1.0
        else:
            kw["mu"] = 0.2
        p = InvertParam(dslash_type=dslash, **kw)
    api.invert_quda(b, p)
    assert p.true_res < 1e-5, p.true_res
