"""Mixed-precision solver tests: reliable updates + iterative refinement.

Sloppy = complex64, precise = complex128 (the CPU analog of the TPU's
f32-precise / bf16-sloppy pairing).  Plain single-precision CG stalls well
above 1e-10; the mixed schemes must reach it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quda_tpu.fields.geometry import EVEN, LatticeGeometry
from quda_tpu.fields.spinor import ColorSpinorField, even_odd_split
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.models.wilson import DiracWilsonPC
from quda_tpu.ops import blas
from quda_tpu.solvers.cg import cg
from quda_tpu.solvers.mixed import cg_reliable, solve_refined

GEOM = LatticeGeometry((8, 8, 8, 8))
KAPPA = 0.125
TOL = 1e-10


@pytest.fixture(scope="module")
def problem():
    key = jax.random.PRNGKey(21)
    k1, k2 = jax.random.split(key)
    gauge = GaugeField.random(k1, GEOM).data
    b_full = ColorSpinorField.gaussian(k2, GEOM).data
    dpc = DiracWilsonPC(gauge, GEOM, KAPPA)
    be, bo = even_odd_split(b_full, GEOM)
    rhs = dpc.Mdag(dpc.prepare(be, bo))
    dpc_lo = DiracWilsonPC(gauge.astype(jnp.complex64), GEOM, KAPPA)
    return dpc, dpc_lo, rhs


def test_pure_single_stalls(problem):
    """Sanity: single-precision CG cannot reach a TRUE residual of 1e-10
    (its recursive residual under-reports) — motivates mixing."""
    dpc, dpc_lo, rhs = problem
    res = cg(dpc_lo.MdagM, rhs.astype(jnp.complex64), tol=TOL, maxiter=500)
    true_r2 = blas.norm2(rhs - dpc.MdagM(res.x.astype(jnp.complex128)))
    assert float(jnp.sqrt(true_r2 / blas.norm2(rhs))) > 10 * TOL


def test_cg_reliable_reaches_double_tol(problem):
    dpc, dpc_lo, rhs = problem
    res = jax.jit(lambda b: cg_reliable(
        dpc.MdagM, dpc_lo.MdagM, b, jnp.complex64, tol=TOL,
        maxiter=2000))(rhs)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL


def test_refinement_reaches_double_tol(problem):
    dpc, dpc_lo, rhs = problem
    inner = jax.jit(lambda r: cg(dpc_lo.MdagM, r, tol=1e-5, maxiter=500).x)
    res = solve_refined(dpc.MdagM, inner, rhs, jnp.complex64, tol=TOL)
    assert bool(res.converged)
    r2 = blas.norm2(rhs - dpc.MdagM(res.x))
    assert float(jnp.sqrt(r2 / blas.norm2(rhs))) < 2 * TOL


def test_reliable_iters_comparable_to_pure_double(problem):
    """Reliable-update CG shouldn't need dramatically more iterations."""
    dpc, dpc_lo, rhs = problem
    res_d = cg(dpc.MdagM, rhs, tol=TOL, maxiter=2000)
    res_m = cg_reliable(dpc.MdagM, dpc_lo.MdagM, rhs, jnp.complex64,
                        tol=TOL, maxiter=2000)
    assert int(res_m.iters) < 3 * int(res_d.iters)
