"""Chip-keyed persistent tuner warm cache (utils/tune.py v2 schema):
the platform/chip/mesh key component, stale un-keyed-entry
invalidation with its one-time notice, cross-platform isolation (the
CPU-interpret-poisons-TPU bug), warm_start's zero-re-race contract for
a fresh process, and the trace-event audit trail through init_quda."""

import json

import jax
import jax.numpy as jnp
import pytest

from quda_tpu.obs import trace as otr
from quda_tpu.utils import config as qconf
from quda_tpu.utils import tune


@pytest.fixture(autouse=True)
def _iso(monkeypatch):
    """Fresh in-process cache + closed trace session around each test
    (the module cache is process-global by design)."""
    otr.stop(flush_files=False)
    qconf.reset_cache()
    monkeypatch.setattr(tune, "_cache", {})
    monkeypatch.setattr(tune, "_stale_noticed", False)
    yield
    otr.stop(flush_files=False)
    qconf.reset_cache()


def test_platform_key_shape_and_stability():
    k = tune.platform_key()
    assert k == tune.platform_key()              # cached per process
    parts = k.split(":")
    assert len(parts) == 3 and parts[2].startswith("n")
    assert "|" not in k and " " not in k         # splits cleanly


def test_tune_key_carries_platform_component():
    key = tune.tune_key("op", (4, 4), "aux")
    assert key.startswith(tune.platform_key() + "|")
    assert key.endswith("|(4, 4)|op|aux")


def test_stale_unkeyed_entries_invalidated(tmp_path, monkeypatch,
                                           capsys):
    """Entries written by the pre-platform schema (tunecache poisoning
    bug: a CPU-interpret winner silently served on TPU) are dropped at
    load with a one-time 'stale schema, re-racing' notice, and the next
    save purges them from disk."""
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    (tmp_path / "tunecache.json").write_text(json.dumps({
        "(24, 24, 24, 24)|wilson_eo_sharded_policy|v2":
            {"param": "fused_halo", "time": 0.001}}))
    stats = tune.load_cache()
    assert stats["stale"] == 1 and stats["entries"] == 0
    assert tune._cache == {}
    err = capsys.readouterr().err
    assert "stale schema" in err and "re-racing" in err
    tune.load_cache()                            # one-time notice only
    assert "stale schema" not in capsys.readouterr().err
    tune.save_cache()
    assert json.loads((tmp_path / "tunecache.json").read_text()) == {}


def test_other_platform_entry_is_not_served(tmp_path, monkeypatch):
    """A winner raced on DIFFERENT hardware stays in the store (it is
    valid there) but never satisfies this platform's lookup."""
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    alien = "tpu:TPU-v9:n4|(4, 4)|xplat_op|"
    (tmp_path / "tunecache.json").write_text(json.dumps({
        alien: {"param": "alien_win", "time": 1e-9,
                "platform": "tpu:TPU-v9:n4"}}))
    stats = tune.load_cache()
    assert stats["entries"] == 1
    assert tune.cached_param("xplat_op", (4, 4)) is None
    x = jnp.ones((8, 8))
    won = tune.tune("xplat_op", (4, 4),
                    {"alien_win": jax.jit(lambda a: (a @ a) @ (a @ a)),
                     "local": jax.jit(lambda a: a + 1.0)}, (x,))
    # re-raced HERE; both the alien and the fresh local entry coexist
    assert alien in tune._cache
    local_key = tune.tune_key("xplat_op", (4, 4))
    assert local_key in tune._cache and local_key != alien
    assert tune._cache[local_key]["platform"] == tune.platform_key()
    assert won == tune._cache[local_key]["param"]


def test_warm_start_serves_with_zero_reraces(tmp_path, monkeypatch):
    """The acceptance contract: a second process with a warmed resource
    path emits tune_cache_loaded/tune_cached events and performs ZERO
    re-races for already-keyed (platform, volume, form) entries —
    candidates that would raise if timed prove it."""
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    x = jnp.ones((8, 8))
    won = tune.tune("warm_op", (8, 8),
                    {"slow": jax.jit(lambda a: (a @ a) @ (a @ a)),
                     "fast": jax.jit(lambda a: a + 1.0)}, (x,), aux="k")
    # ---- fresh-process simulation: empty in-memory cache ----
    monkeypatch.setattr(tune, "_cache", {})
    otr.start(str(tmp_path))
    assert tune.warm_start() == 1

    def boom(*a):
        raise AssertionError("re-raced after warm start")

    won2 = tune.tune("warm_op", (8, 8), {"slow": boom, "fast": boom},
                     (x,), aux="k")
    assert won2 == won
    assert tune.cached_param("warm_op", (8, 8), aux="k") == won
    paths = otr.stop()
    lines = [json.loads(ln) for ln in open(paths["jsonl"])]
    loaded = [ln for ln in lines if ln["name"] == "tune_cache_loaded"]
    assert loaded and loaded[0]["usable_here"] == 1
    assert loaded[0]["platform"] == tune.platform_key()
    assert any(ln["name"] == "tune_cached" for ln in lines)


def test_init_quda_preloads_warm_cache(tmp_path, monkeypatch):
    """init_quda is the warm-start hook: the load event lands in the
    QUDA_TPU_TRACE session and the first tune() after init is a cache
    hit, not a race."""
    from quda_tpu.interfaces.quda_api import end_quda, init_quda
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    monkeypatch.setenv("QUDA_TPU_TRACE", "1")
    qconf.reset_cache()
    x = jnp.ones((8, 8))
    won = tune.tune("api_warm_op", (8, 8),
                    {"fast": jax.jit(lambda a: a + 1.0)}, (x,))
    monkeypatch.setattr(tune, "_cache", {})      # "new worker"
    init_quda()

    def boom(*a):
        raise AssertionError("re-raced after init_quda warm start")

    assert tune.tune("api_warm_op", (8, 8), {"fast": boom}, (x,)) == won
    end_quda()
    lines = [json.loads(ln) for ln in
             open(tmp_path / "trace_events.jsonl")]
    names = [ln["name"] for ln in lines]
    assert "tune_cache_loaded" in names and "tune_cached" in names


# -- race resilience (robust round: failing candidates never win) ------------

def test_raising_candidate_is_skipped_and_never_cached(tmp_path,
                                                       monkeypatch):
    """A candidate that raises ON-CHIP mid-race is marked failed
    (tune_candidate_failed event) and the race still returns a usable
    winner; the failed candidate must never be the cached param."""
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    qconf.reset_cache()
    otr.start(str(tmp_path))
    x = jnp.ones((8, 8))

    calls = {"n": 0}

    def mid_race_boom(a):
        # raises AFTER a successful warmup call — the mid-race (not
        # at-construction) failure mode: the timing loop itself throws
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("device raised mid-race")
        return a + 1.0

    won = tune.tune("race_op", (8, 8),
                    {"breaks": mid_race_boom,
                     "works": jax.jit(lambda a: a * 2.0)}, (x,),
                    aux="resil")
    assert won == "works"
    assert tune.cached_param("race_op", (8, 8), aux="resil") == "works"
    paths = otr.stop()
    lines = [json.loads(ln) for ln in open(paths["jsonl"])]
    failed = [ln for ln in lines if ln["name"] == "tune_candidate_failed"]
    assert failed and failed[0]["param"] == "breaks"
    winner = [ln for ln in lines if ln["name"] == "tune_winner"]
    assert winner and winner[0]["param"] == "works"


def test_all_candidates_fail_degrades_to_static_default(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """An all-candidates-fail race must DEGRADE to the static default
    (the first registered candidate — the tuning-disabled convention)
    with a one-time notice instead of raising, and must NOT cache the
    untimed fallback (the next process re-races)."""
    from quda_tpu.utils import logging as qlog
    monkeypatch.setenv("QUDA_TPU_RESOURCE_PATH", str(tmp_path))
    qconf.reset_cache()
    monkeypatch.setattr(qlog, "_warned_once", set())
    otr.start(str(tmp_path))
    x = jnp.ones((8, 8))

    def boom_a(a):
        raise RuntimeError("a failed")

    def boom_b(a):
        raise RuntimeError("b failed")

    won = tune.tune("allfail_op", (8, 8),
                    {"default": boom_a, "other": boom_b}, (x,),
                    aux="af")
    assert won == "default"
    # the degraded choice was never timed -> not cached, re-raced later
    assert tune.cached_param("allfail_op", (8, 8), aux="af") is None
    err = capsys.readouterr().err
    assert "every candidate failed" in err
    assert "static default" in err
    # one-time: a second all-fail race stays quiet on stderr
    tune.tune("allfail_op", (8, 8), {"default": boom_a}, (x,), aux="af2")
    assert "every candidate failed" not in capsys.readouterr().err
    paths = otr.stop()
    lines = [json.loads(ln) for ln in open(paths["jsonl"])]
    allfail = [ln for ln in lines if ln["name"] == "tune_race_all_failed"]
    assert len(allfail) == 2 and allfail[0]["fallback"] == "default"
    assert len([ln for ln in lines
                if ln["name"] == "tune_candidate_failed"]) == 3
