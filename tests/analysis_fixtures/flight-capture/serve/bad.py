"""Seeded violation: a serve-scoped worker runs the solve API outside
a serve_requests(...) scope — a postmortem bundle captured during the
solve cannot carry the tickets' request_id."""


def execute_batch(api, grp, param):
    import jax.numpy as jnp
    B = jnp.stack([r.source for r in grp])
    return api.invert_multi_src_quda(B, param)         # finding
