"""Clean twin: the batch runs inside the serve-request scope, so any
capture lands the ticket ids in its manifest."""

from quda_tpu.obs import postmortem as opm


def execute_batch(api, grp, param):
    import jax.numpy as jnp
    with opm.serve_requests([r.request_id for r in grp]):
        B = jnp.stack([r.source for r in grp])
        return api.invert_multi_src_quda(B, param)
