"""Suppressed twin: the unscoped solve call is reasoned."""


def execute_batch(api, grp, param):
    import jax.numpy as jnp
    B = jnp.stack([r.source for r in grp])
    return api.invert_multi_src_quda(B, param)  # quda-lint: disable=flight-capture  reason=fixture pin: replay harness re-running a recorded batch whose manifest already carries the original request ids
