"""Suppressed twin: the second ring is reasoned."""

import collections

_events = collections.deque(maxlen=256)  # quda-lint: disable=flight-capture  reason=fixture pin: host-only scratch history, contents mirrored into the flight ring by note()


def note(event):
    _events.append(event)
