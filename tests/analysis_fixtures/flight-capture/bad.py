"""Seeded violation: a second bounded ring buffer outside
obs/flight.py — a black box the postmortem bundles never snapshot."""

import collections

_events = collections.deque(maxlen=256)           # finding


def note(event):
    _events.append(event)
