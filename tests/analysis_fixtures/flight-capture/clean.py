"""Clean twin: events go to THE ring via the public tap."""

from quda_tpu.obs import flight


def note(event):
    flight.record("fixture_event", cat="fixture", detail=event)
