"""Seeded violation: config knob + host clock read inside a traced
while_loop body (the stale-knob/recompile hazard class)."""

import time

from jax import lax

from quda_tpu.utils import config as qconf


def _cond(carry):
    return carry[1] < 10


def _body(carry):
    k = qconf.intval("QUDA_TPU_CG_CHECK_EVERY")      # finding: knob read
    t = time.perf_counter()                          # finding: host clock
    return (carry[0] + k + t, carry[1] + 1)


def run():
    return lax.while_loop(_cond, _body, (0.0, 0))


# the dominant jit idiom in the package: partial-applied decorator
from functools import partial  # noqa: E402

import jax  # noqa: E402


@partial(jax.jit, static_argnums=0)
def kernel(n, x):
    if qconf.flag("QUDA_TPU_TRACE"):                 # finding: knob read
        x = x + 1.0
    return x * n

