"""Clean twin: the knob is read at construction and closed over — the
package discipline the pass enforces."""

from jax import lax

from quda_tpu.utils import config as qconf


def run():
    k = qconf.intval("QUDA_TPU_CG_CHECK_EVERY")   # construction-time read

    def _cond(carry):
        return carry[1] < 10

    def _body(carry):
        return (carry[0] + k, carry[1] + 1)       # closed-over value

    return lax.while_loop(_cond, _body, (0, 0))
