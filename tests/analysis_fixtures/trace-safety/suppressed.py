"""Suppressed twin: same trace-time knob read, under a reasoned
disable (e.g. a fixture-pinned intentional freeze)."""

from jax import lax

from quda_tpu.utils import config as qconf


def _cond(carry):
    return carry[1] < 10


def _body(carry):
    k = qconf.intval("QUDA_TPU_CG_CHECK_EVERY")  # quda-lint: disable=trace-safety  reason=fixture pin: freezing the cadence into this trace is intended
    return (carry[0] + k, carry[1] + 1)


def run():
    return lax.while_loop(_cond, _body, (0, 0))
