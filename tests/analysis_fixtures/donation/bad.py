"""Seeded violation: a donated buffer read after the donating call
(use-after-donation — garbage on TPU, correct-looking on CPU)."""

import jax


def f(x):
    return x * 2.0


def run(x):
    g = jax.jit(f, donate_argnums=(0,))
    y = g(x)
    return y + x          # finding: x was donated at the g(x) call


# the common layout: the donating callable bound at MODULE level,
# called from inside a function scope
g2 = jax.jit(f, donate_argnums=(0,))


def run_module_bound(x):
    out = g2(x)
    return out + x        # finding: x was donated at the g2(x) call
