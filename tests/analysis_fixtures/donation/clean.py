"""Clean twin: the double-buffer idiom — the result rebinds the
donated name, nothing reads the dead buffer."""

import jax


def f(x):
    return x * 2.0


def run(x):
    g = jax.jit(f, donate_argnums=(0,))
    for _ in range(4):
        x = g(x)          # rebind: the donated buffer is never re-read
    return x


def f2(x, y):
    return y, x


def run_tuple(x, y):
    g = jax.jit(f2, donate_argnums=(0, 1))
    x, y = g(x, y)        # tuple-unpack rebind: both names rebound
    return x + y
