"""Suppressed twin: the post-donation read is intentional (e.g. a test
asserting the runtime did NOT alias on this backend)."""

import jax


def f(x):
    return x * 2.0


def run(x):
    g = jax.jit(f, donate_argnums=(0,))
    y = g(x)
    return y + x  # quda-lint: disable=donation  reason=fixture pin: CPU backend never aliases, the read is the assertion
