"""Seeded violation: a kernel-form literal in the roofline namespace
with no KERNEL_MODELS traffic model — an unattributable kernel."""

from quda_tpu.obs import roofline as orf


def attribute(seconds):
    form = "wilson_totally_unmodeled_form"        # finding
    return orf.record(form, 16, 1.0, seconds)
