"""Clean twin: a modeled form from KERNEL_MODELS."""

from quda_tpu.obs import roofline as orf


def attribute(seconds):
    form = "wilson_v2"
    return orf.record(form, 16, 1.0, seconds)
