"""Suppressed twin: the unmodeled form literal is reasoned."""

from quda_tpu.obs import roofline as orf


def attribute(seconds):
    form = "wilson_totally_unmodeled_form"  # quda-lint: disable=roofline-model  reason=fixture pin: prototype form, model lands with the first measured row
    return orf.record(form, 16, 1.0, seconds)
