"""Suppressed twin: the unguarded loop is reasoned."""

from jax import lax


def solve(cond, body, carry):
    return lax.while_loop(cond, body, carry)  # quda-lint: disable=robust-sentinel  reason=fixture pin: bounded fixed-trip helper loop, cannot spin past its trip count
