"""Clean twin: the sentinel threads the loop carry (make() gate)."""

from jax import lax

from quda_tpu.robust import sentinel


def solve(cond, body, carry):
    guard = sentinel.make("fixture")
    return lax.while_loop(cond, body, (carry, guard))
