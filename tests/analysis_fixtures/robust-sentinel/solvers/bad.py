"""Seeded violation: a solver module threading a lax.while_loop with
no breakdown sentinel — the NaN-spin-to-maxiter failure mode."""

from jax import lax


def solve(cond, body, carry):
    return lax.while_loop(cond, body, carry)      # finding
