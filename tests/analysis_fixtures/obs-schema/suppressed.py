"""Suppressed twin: the off-schema name is reasoned."""

from quda_tpu.obs import trace as otr


def emit():
    otr.event("totally_unregistered_event", cat="fixture")  # quda-lint: disable=obs-schema  reason=fixture pin: name scoped to an external consumer, never scraped
