"""Seeded violations: a trace event and a metric emitted under names
the canonical schema does not know — the silent-dashboard-break
class."""

from quda_tpu.obs import metrics as omet
from quda_tpu.obs import trace as otr


def emit():
    otr.event("totally_unregistered_event", cat="fixture")   # finding
    omet.inc("totally_unregistered_metric_total")            # finding
