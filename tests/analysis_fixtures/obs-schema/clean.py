"""Clean twin: schema-registered names only."""

from quda_tpu.obs import metrics as omet
from quda_tpu.obs import trace as otr


def emit():
    otr.event("compile", cat="metrics")
    omet.inc("solves_total", api="fixture", family="f", status="ok")
