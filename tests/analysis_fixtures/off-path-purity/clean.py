"""Clean twin: the documented one-global-load gate — one read into a
local, None-check, early return; lifecycle owns the global."""

_session = None


def record(name):
    s = _session
    if s is None:
        return
    s.events.append(name)


def start():
    global _session
    if _session is None:
        _session = object()
    return _session


def enabled():
    return _session is not None
