"""Seeded violations: an emission function using the session global
directly (double read — can observe a mid-call stop), and a gate
loaded but never None-checked."""

_session = None


def record(name):
    _session.events.append(name)      # finding: ungated direct use


def observe(value):
    s = _session
    s.observe(value)                  # finding: local never None-checked
