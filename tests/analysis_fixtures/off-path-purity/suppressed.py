"""Suppressed twin: the direct use is reasoned (e.g. an interactive
debug helper that may legitimately crash when off)."""

_session = None


def record(name):
    _session.events.append(name)  # quda-lint: disable=off-path-purity  reason=fixture pin: debug-only helper, crashing when off is the desired loud failure
