"""Seeded violation, live-telemetry shape: scrape handlers run on the
HTTP server's thread pool, so module-level scrape accounting mutated
without a lock races across concurrent scrapes."""

_scrape_counts = {}


def handle(path):
    _scrape_counts[path] = _scrape_counts.get(path, 0) + 1   # finding
    return 200
