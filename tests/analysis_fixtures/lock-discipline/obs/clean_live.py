"""Clean twin, live-telemetry shape: mutable scrape state lives on the
session instance behind its lock (the obs/live.py pattern) — handler
threads mutate under `with s.lock`, module level holds only the
session slot."""

import threading

_session = None


class _Live:
    def __init__(self):
        self.lock = threading.Lock()
        self.scrape_counts = {}


def handle(path):
    s = _session
    if s is None:
        return 503
    with s.lock:
        s.scrape_counts[path] = s.scrape_counts.get(path, 0) + 1
    return 200
