"""Suppressed twin: the unlocked scrape accounting is reasoned."""

_scrape_counts = {}


def handle(path):
    _scrape_counts[path] = _scrape_counts.get(path, 0) + 1  # quda-lint: disable=lock-discipline  reason=fixture pin: single-threaded test server, handler concurrency is 1 by construction
    return 200
