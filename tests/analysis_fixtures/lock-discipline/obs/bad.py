"""Seeded violation: a public function mutating a module-level
container with no lock (the PR 9 high-water race class)."""

import threading

_lock = threading.Lock()
_cache = {}


def put(key, value):
    _cache[key] = value       # finding: unlocked shared-state write


def forget(key):
    _cache.pop(key, None)     # finding: unlocked mutator call


def batch_put(items):
    def _store(k, v):
        _cache[k] = v         # finding: closure on the public path —
        # a _-named nested helper inside a public entry point is NOT
        # the private-top-level-helper exemption
    for k, v in items:
        _store(k, v)
