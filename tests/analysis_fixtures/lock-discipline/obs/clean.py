"""Clean twin: the mutation sits under the module lock; import-time
initialisation and _private helpers are exempt by design."""

import threading

_lock = threading.Lock()
_cache = {}
_cache["seeded"] = True       # import-time init: exempt


def put(key, value):
    with _lock:
        _cache[key] = value


def _install(key, value):
    _cache[key] = value       # _helper: presumed under the caller's lock
