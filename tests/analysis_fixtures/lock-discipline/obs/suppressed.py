"""Suppressed twin: the unlocked write is intentional and reasoned."""

import threading

_lock = threading.Lock()
_cache = {}


def put(key, value):
    _cache[key] = value  # quda-lint: disable=lock-discipline  reason=fixture pin: single-threaded import-shim, no concurrent writers exist
