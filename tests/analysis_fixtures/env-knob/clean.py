"""Clean twin: only registered knobs, read through the typed
accessors."""

from quda_tpu.utils import config as qconf


def read():
    return qconf.intval("QUDA_TPU_MAX_MULTI_RHS")
