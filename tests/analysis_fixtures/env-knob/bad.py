"""Seeded violation: a QUDA_TPU_* name the registry does not know — a
typoed knob read silently never fires."""

import os


def read():
    return os.environ.get("QUDA_TPU_TOTALLY_UNREGISTERED_KNOB")  # finding
