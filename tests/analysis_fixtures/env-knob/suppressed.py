"""Suppressed twin: the unregistered reference is reasoned (e.g. a
doc mentioning a knob another tool owns)."""

import os


def read():
    return os.environ.get("QUDA_TPU_TOTALLY_UNREGISTERED_KNOB")  # quda-lint: disable=env-knob  reason=fixture pin: name owned by an external harness, not this registry
