"""Seeded violation: a disable naming an unregistered rule."""

from jax import lax


def rogue(slab, perm):
    return lax.ppermute(slab, "z", perm)  # quda-lint: disable=comms-legder  reason=typo in the rule name means this suppresses nothing
