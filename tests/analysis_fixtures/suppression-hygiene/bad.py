"""Seeded violations: a disable without the mandatory reason, and a
disable naming a rule the registry does not know (it would silently
suppress nothing)."""

from jax import lax


def rogue(slab, perm):
    return lax.ppermute(slab, "z", perm)  # quda-lint: disable=comms-ledger
