"""Clean twin: a well-formed suppression — known rule, reason given."""

from jax import lax


def rogue(slab, perm):
    return lax.ppermute(slab, "z", perm)  # quda-lint: disable=comms-ledger  reason=fixture pin: microbenchmark harness, bytes accounted by hand
