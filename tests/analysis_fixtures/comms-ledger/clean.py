"""Clean twin: transfers go through the ledgered exchange seam."""

from quda_tpu.parallel.halo import exchange_boundaries


def proper_exchange(field, mesh):
    return exchange_boundaries(field, mesh)
