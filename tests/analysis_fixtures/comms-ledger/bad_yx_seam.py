"""Seeded violation (round 18): a y/x exchange seam called outside
parallel/pallas_dslash — the comms scope that labels its ledger rows
with (site, policy, axis) never opens, so the transfer ships
unattributed."""

from quda_tpu.parallel.pallas_dslash import _eo_x_psi_sources


def rogue_x_face_exchange(psi_pl, xh_loc, r0):
    raw = lambda lo, hi, name, n: (hi, lo)     # unledgered transport
    return _eo_x_psi_sources(psi_pl, xh_loc, raw, "x", 1, 1, r0)  # finding
