"""Suppressed twin: the out-of-home ppermute is reasoned."""

from jax import lax


def rogue_exchange(slab, perm):
    return lax.ppermute(slab, "z", perm)  # quda-lint: disable=comms-ledger  reason=fixture pin: microbenchmark harness, bytes accounted by hand in its row
