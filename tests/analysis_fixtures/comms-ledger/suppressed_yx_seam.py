"""Suppressed twin: the y/x seam call is acknowledged with a reason
(e.g. a migration shim that opens its own comms scope)."""

from quda_tpu.parallel.pallas_dslash import _eo_x_psi_sources


def shimmed_x_face_exchange(psi_pl, xh_loc, exchange, r0):
    return _eo_x_psi_sources(  # quda-lint: disable=comms-ledger  reason=migration shim opens its own comms scope upstream
        psi_pl, xh_loc, exchange, "x", 1, 1, r0)
