"""Clean twin: y/x-partitioned transfers go through the public sharded
wrapper, which opens the comms scope and routes every face through the
ledgered exchange seam."""

from quda_tpu.parallel.pallas_dslash import dslash_eo_pallas_sharded


def proper_x_face_exchange(u_here, u_bw, psi, dims, parity, mesh):
    return dslash_eo_pallas_sharded(u_here, u_bw, psi, dims, parity,
                                    mesh)
