"""Seeded violation: a lax.ppermute outside the single ledgered home
(parallel/halo._permute_slice) — an unattributed ICI transfer."""

from jax import lax


def rogue_exchange(slab, perm):
    return lax.ppermute(slab, "z", perm)          # finding
