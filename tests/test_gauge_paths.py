"""Generic path-table evaluator tests (computeGaugeForceQuda /
gaugeLoopTraceQuda analogs, gauge_force.cuh:100, gauge_loop_trace.cu:74)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quda_tpu.fields.geometry import LatticeGeometry
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.gauge.action import gauge_force, wilson_action
from quda_tpu.gauge.observables import plaquette_field
from quda_tpu.gauge.paths import (gauge_loop_trace, gauge_path_action,
                                  gauge_path_force, plaquette_paths,
                                  wilson_line)
from quda_tpu.ops.su3 import trace

GEOM = LatticeGeometry((4, 4, 4, 4))


@pytest.fixture(scope="module")
def gauge():
    return GaugeField.random(jax.random.PRNGKey(31), GEOM).data


def test_wilson_line_plaquette(gauge):
    """Path [mu, nu, 7-mu, 7-nu] reproduces plaquette_field."""
    for mu, nu in ((0, 1), (1, 3), (2, 3)):
        W, disp = wilson_line(gauge, [mu, nu, 7 - mu, 7 - nu])
        assert disp == (0, 0, 0, 0)
        ref = plaquette_field(gauge, mu, nu)
        assert np.allclose(np.asarray(W), np.asarray(ref), atol=1e-12)


def test_loop_trace_matches_wilson_action(gauge):
    """Sum of plaquette-loop traces reproduces the Wilson action."""
    paths = [[mu, nu, 7 - mu, 7 - nu]
             for mu in range(4) for nu in range(4) if mu < nu]
    beta = 5.5
    tr_sum = jnp.sum(gauge_loop_trace(gauge, paths, [1.0] * len(paths)))
    n_plaq = 6 * GEOM.volume
    s_from_trace = beta * (n_plaq - float(tr_sum.real) / 3.0)
    s_ref = float(wilson_action(gauge, beta))
    assert np.isclose(s_from_trace, s_ref, rtol=1e-12)


def test_loop_trace_rejects_open_path(gauge):
    with pytest.raises(ValueError):
        gauge_loop_trace(gauge, [[0, 1, 7]], [1.0])


def test_plaquette_path_force_matches_action_force(gauge):
    """The generic path-table force with the 6-staple table equals the AD
    force of the Wilson action (coeff -beta/3 makes the actions equal up
    to a constant, and constants don't change forces)."""
    beta = 5.5
    buf = plaquette_paths()
    # the 6-staple table counts each unordered plaquette 4x (fwd+bwd
    # staples from both of its directions)
    coeffs = [-beta / 3.0 / 4.0] * 6
    f_paths = gauge_path_force(gauge, buf, coeffs)
    f_ref = gauge_force(lambda g: wilson_action(g, beta), gauge)
    assert np.allclose(np.asarray(f_paths), np.asarray(f_ref), atol=1e-10)


def test_random_path_force_matches_finite_difference(gauge):
    """FD check of the AD force on an arbitrary (user-style) path table."""
    from quda_tpu.ops.su3 import random_hermitian_traceless
    buf = []
    for mu in range(4):
        nu = (mu + 1) % 4
        rho = (mu + 2) % 4
        buf.append([
            [nu, 7 - mu, 7 - nu],                       # standard staple
            [nu, rho, 7 - mu, 7 - rho, 7 - nu],         # chair
        ])
    coeffs = [0.7, -0.3]
    act = lambda g: gauge_path_action(g, buf, coeffs)
    f = gauge_path_force(gauge, buf, coeffs)

    key = jax.random.PRNGKey(4)
    q = random_hermitian_traceless(key, gauge.shape[:-2],
                                   dtype=gauge.dtype)
    from quda_tpu.ops.su3 import expm_su3, mat_mul as mm
    eps = 1e-5
    def s_at(t):
        u = mm(expm_su3(t * q), gauge)
        return float(act(u))
    ds_fd = (s_at(eps) - s_at(-eps)) / (2 * eps)
    # dS/dt = 2 tr(Q F) summed (force convention of gauge/action.py)
    ds_ad = 2.0 * float(jnp.sum(trace(mm(q, f)).real))
    assert np.isclose(ds_fd, ds_ad, rtol=1e-5, atol=1e-7)


def test_polyakov_loop_closes_through_torus(gauge):
    """Straight T-direction line of full extent is a valid loop
    (closure via periodicity, gaugeLoopTraceQuda computes it)."""
    T = gauge.shape[1]
    tr = gauge_loop_trace(gauge, [[3] * T], [1.0])
    assert np.isfinite(complex(tr[0]).real)


def test_path_coeff_length_mismatch_raises(gauge):
    with pytest.raises(ValueError):
        gauge_loop_trace(gauge, [[0, 1, 7, 6], [0, 3, 7, 4]], [1.0])
    with pytest.raises(ValueError):
        gauge_path_action(gauge, plaquette_paths(), [1.0] * 5)
