"""Precision storage forms (PERF.md round 16): model-level dispatch of
the bf16 full-tile fold / bz=Z admission, the fused in-kernel recon-12
forms (Wilson r12f + staggered Naik r12), and the int8 block-float
links — interpreter bit-match against the resident-full-links reference
through the SAME operator surface the solvers drive (``_d_to`` /
``D_to_pairs``), both parities, MRHS, and the sharded downgrade path.

Bitwise claims are exact by construction and asserted exactly:

* ``fold`` is a storage-layout permutation of the same f32/bf16
  elements — identical arithmetic, identical result bits;
* ``bzfull`` changes only the pallas grid blocking — same kernel body;
* ``r12f`` runs the identical reconstruction arithmetic as resident
  r12 storage (shared ``_recon12_wrap``) — r12 and r12f must agree
  BITWISE with each other, and to f32 roundoff with full links;
* ``int8`` is bounded-error vs full (block-float quantisation), and
  the pallas in-kernel decompression must bit-match the XLA
  decompress-at-setup route built from the same (q, scale) pair.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quda_tpu.fields.gauge import GaugeField
from quda_tpu.fields.geometry import LatticeGeometry
from quda_tpu.models.staggered import DiracStaggeredPC
from quda_tpu.models.wilson import DiracWilsonPC
from quda_tpu.utils import config as qconf

GEOM = LatticeGeometry((4, 4, 4, 4))


@pytest.fixture(autouse=True)
def _fresh_config():
    qconf.reset_cache()
    yield
    qconf.reset_cache()


def _wilson_dpk():
    gauge = GaugeField.random(jax.random.PRNGKey(21), GEOM).data.astype(
        jnp.complex64)
    return DiracWilsonPC(gauge, GEOM, kappa=0.11).packed()


def _staggered_dpc():
    fat = GaugeField.random(jax.random.PRNGKey(22), GEOM).data.astype(
        jnp.complex64)
    lng = GaugeField.random(jax.random.PRNGKey(23), GEOM).data.astype(
        jnp.complex64)
    return DiracStaggeredPC(fat, GEOM, mass=0.05, improved=True,
                            long_links=lng)


def _psi(shape, seed=1):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _wilson_out(dpk, form, parity, store=jnp.float32, psi=None):
    sl = dpk.pairs(store, use_pallas=True, pallas_interpret=True,
                   precision_form=form)
    T, Z, Y, X = GEOM.lattice_shape
    p = psi if psi is not None else _psi((4, 3, 2, T, Z, Y * X // 2))
    return np.asarray(sl._d_to(p.astype(store), parity, jnp.float32)), sl


@pytest.mark.parametrize(
    "parity", [0, pytest.param(1, marks=pytest.mark.slow)])
@pytest.mark.parametrize(
    "form", [pytest.param("r12", marks=pytest.mark.slow),
             "r12f", "fold",
             pytest.param("bzfull", marks=pytest.mark.slow)])
def test_wilson_precision_forms_match_full(form, parity):
    dpk = _wilson_dpk()
    ref, _ = _wilson_out(dpk, "full", parity)
    out, sl = _wilson_out(dpk, form, parity)
    assert sl._precision_form == form
    if form in ("fold", "bzfull"):
        # layout/blocking changes only: identical arithmetic -> bits
        assert np.array_equal(out, ref)
    else:
        err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
        assert err < 3e-5, (form, err)


@pytest.mark.slow
@pytest.mark.parametrize("parity", [0, 1])
def test_wilson_r12f_bitmatches_resident_r12(parity):
    """r12f shares r12's stored rows and reconstruction arithmetic —
    only the backward-hop data movement differs (scatter reads of the
    unshifted opposite-parity links vs the resident pre-shifted copy).
    Same inputs, same arithmetic: the results must agree bitwise."""
    dpk = _wilson_dpk()
    a, _ = _wilson_out(dpk, "r12", parity)
    b, _ = _wilson_out(dpk, "r12f", parity)
    assert np.array_equal(a, b)


def test_wilson_bf16_fold_bitmatches_bf16_full():
    """The re/im-into-sublane fold at bf16 storage is the round-16
    full-tile form: same bf16 elements, permuted rows — the hop must
    reproduce the unfolded bf16 kernel bit for bit."""
    dpk = _wilson_dpk()
    ref, _ = _wilson_out(dpk, "full", 0, store=jnp.bfloat16)
    out, sl = _wilson_out(dpk, "fold", 0, store=jnp.bfloat16)
    assert sl._precision_form == "fold"
    assert np.array_equal(out, ref)


def test_wilson_int8_links_bounded_error_and_xla_bitmatch():
    """int8 block-float links: bounded quantisation error vs full
    links, and the in-kernel decompression bit-matches the XLA route
    decompressed at setup from the same (q, scale) arrays."""
    dpk = _wilson_dpk()
    ref, _ = _wilson_out(dpk, "full", 0)
    out, sl = _wilson_out(dpk, "int8", 0)
    assert sl._precision_form == "int8"
    assert sl.gauge_eo_pp is None and sl._gauge_q[0].dtype == jnp.int8
    err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert err < 2e-2, err
    T, Z, Y, X = GEOM.lattice_shape
    psi = _psi((4, 3, 2, T, Z, Y * X // 2))
    xla = dpk.pairs(jnp.float32, use_pallas=False,
                    precision_form="int8")
    assert xla._precision_form == "int8"
    x_out = np.asarray(xla._d_to(psi, 0, jnp.float32))
    p_out = np.asarray(sl._d_to(psi, 0, jnp.float32))
    assert np.max(np.abs(x_out - p_out)) < 1e-5


@pytest.mark.parametrize(
    "n", [pytest.param(1, marks=pytest.mark.slow), 3])
@pytest.mark.parametrize(
    "form", [pytest.param("r12f", marks=pytest.mark.slow), "fold",
             pytest.param("bzfull", marks=pytest.mark.slow),
             pytest.param("int8", marks=pytest.mark.slow)])
def test_wilson_precision_mrhs_matches_single(form, n):
    """The batched hop of every precision form equals the single-RHS
    hop per column (N=1 and N=3 — the MRHS kernels where they exist,
    the vmap fallback where they don't)."""
    dpk = _wilson_dpk()
    sl = dpk.pairs(jnp.float32, use_pallas=True, pallas_interpret=True,
                   precision_form=form)
    T, Z, Y, X = GEOM.lattice_shape
    pb = jnp.stack([_psi((4, 3, 2, T, Z, Y * X // 2), seed=5 + i)
                    for i in range(n)])
    ob = np.asarray(sl._d_to_mrhs(pb, 0, jnp.float32))
    for i in range(n):
        oi = np.asarray(sl._d_to(pb[i], 0, jnp.float32))
        assert np.array_equal(ob[i], oi), (form, n, i)


@pytest.mark.parametrize(
    "parity", [0, pytest.param(1, marks=pytest.mark.slow)])
@pytest.mark.parametrize("pform", ["r12", "fold"])
def test_staggered_fused_precision_forms_match_full(pform, parity):
    dpc = _staggered_dpc()
    T, Z, Y, X = GEOM.lattice_shape
    psi = _psi((3, 2, T, Z, Y * X // 2), seed=7)
    ref_op = dpc.pairs(jnp.float32, use_pallas=True,
                       pallas_interpret=True, form="fused",
                       precision_form="full")
    ref = np.asarray(ref_op.D_to_pairs(psi, parity, jnp.float32))
    op = dpc.pairs(jnp.float32, use_pallas=True, pallas_interpret=True,
                   form="fused", precision_form=pform)
    assert op._precision_form == pform
    out = np.asarray(op.D_to_pairs(psi, parity, jnp.float32))
    if pform == "fold":
        assert np.array_equal(out, ref)
    else:
        # long links are +-SU(3) after KS-phase folding; the recon-12
        # sign plane must re-apply the folded phase exactly
        err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
        assert err < 3e-5, err
        assert op.long_eo_pp[0].shape[1] == 2
        assert op._long_sign is not None


def test_staggered_wilson_only_forms_downgrade():
    """r12f/bzfull/int8 are Wilson forms: the staggered family serves
    'full' (with a notice) instead of failing or mislabeling."""
    dpc = _staggered_dpc()
    for pform in ("r12f", "bzfull", "int8"):
        op = dpc.pairs(jnp.float32, use_pallas=True,
                       pallas_interpret=True, form="fused",
                       precision_form=pform)
        assert op._precision_form == "full", pform


def test_env_knob_resolution(monkeypatch):
    """QUDA_TPU_PRECISION_FORM drives construction when no explicit
    kwarg pins the form; the explicit kwarg wins over the env."""
    dpk = _wilson_dpk()
    monkeypatch.setenv("QUDA_TPU_PRECISION_FORM", "r12f")
    qconf.reset_cache()
    sl = dpk.pairs(jnp.float32, use_pallas=True, pallas_interpret=True)
    assert sl._precision_form == "r12f"
    sl2 = dpk.pairs(jnp.float32, use_pallas=True,
                    pallas_interpret=True, precision_form="fold")
    assert sl2._precision_form == "fold"


def test_legacy_reconstruct_env_still_resolves(monkeypatch):
    """QUDA_TPU_RECONSTRUCT=12 with no precision form remains the r12
    route (the pre-round-16 contract must not break)."""
    dpk = _wilson_dpk()
    monkeypatch.setenv("QUDA_TPU_RECONSTRUCT", "12")
    monkeypatch.delenv("QUDA_TPU_PRECISION_FORM", raising=False)
    qconf.reset_cache()
    sl = dpk.pairs(jnp.float32, use_pallas=True, pallas_interpret=True)
    assert sl._precision_form == "r12"
    assert sl.gauge_eo_pp[0].shape[1] == 2


def test_xla_path_serves_int8_full_only(monkeypatch):
    """The XLA stencil has no in-kernel decompression: pallas-only
    forms downgrade to full (with a notice); int8 decompresses at
    setup and keeps its label."""
    dpk = _wilson_dpk()
    for pform, served in (("fold", "full"), ("bzfull", "full"),
                          ("r12f", "full"), ("int8", "int8")):
        sl = dpk.pairs(jnp.float32, use_pallas=False,
                       precision_form=pform)
        assert sl._precision_form == served, pform


def test_bzfull_audits_single_buffer_admission(monkeypatch):
    """The bz=Z full-block admission must leave an audit trail: a block
    admitted single-buffered (double-buffering would bust the scoped
    16 MB window) is flagged in obs.memory's VMEM audit with the
    PADDED tile byte count."""
    from quda_tpu.obs import memory as omem
    from quda_tpu.ops import wilson_pallas_packed as wpp
    omem.reset()
    # budget small enough that double-buffering Z=8 f32 blocks fails
    # but one copy fits inside the scoped window
    monkeypatch.setenv("QUDA_TPU_PALLAS_VMEM_MB", "1.0")
    qconf.reset_cache()
    bz = wpp._pick_bz(8, 1024, jnp.float32, planes=288, min_bz=8,
                      allow_bzfull=True)
    assert bz == 8
    rows = {r["knob"]: r for r in omem.audit_vmem_budgets()}
    row = rows["QUDA_TPU_PALLAS_VMEM_MB"]
    assert row["last_bz"] == 8
    assert row["last_single_buffered"] is True
    assert row["last_block_bytes"] > 0


def test_pick_bz_dtype_sublane_padding():
    """_pick_bz charges PADDED tile bytes per dtype: sublane tiles are
    8 rows f32, 16 bf16, 32 int8 — a z-block of 2 rows costs a full
    tile's rows, and the bf16/int8 tiles must not be charged at the
    f32 pad."""
    from quda_tpu.obs import memory as omem
    from quda_tpu.ops import wilson_pallas_packed as wpp
    omem.reset()
    wpp._pick_bz(8, 128, jnp.float32, planes=1)
    f32_bytes = omem.audit_vmem_budgets()[0]["last_block_bytes"]
    wpp._pick_bz(8, 128, jnp.bfloat16, planes=1)
    bf16_bytes = omem.audit_vmem_budgets()[0]["last_block_bytes"]
    # same logical elements; bf16 halves the element size but pads to
    # 16 sublane rows — the PADDED charge is what VMEM really holds
    assert f32_bytes == 8 * 128 * 4
    assert bf16_bytes == 16 * 128 * 2


@pytest.mark.slow
def test_sharded_mesh_downgrades_precision_forms():
    """Mesh-sharded kernels speak full/r12 only: r12f and int8
    downgrade to r12, fold/bzfull to full — and the downgraded sharded
    operator still matches the unsharded reference (the round-8
    sharded-r12 path, exterior face fixes included)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quda_tpu.parallel import compat
    from quda_tpu.parallel.mesh import make_lattice_mesh
    if not compat.has_shard_map():
        pytest.skip("no shard_map API in this jax version")
    if len(jax.devices()) != 8:
        pytest.skip("needs the 8-device virtual mesh")
    geom = LatticeGeometry((4, 4, 8, 16))
    gauge = GaugeField.random(jax.random.PRNGKey(31), geom).data.astype(
        jnp.complex64)
    dpk = DiracWilsonPC(gauge, geom, kappa=0.12).packed()
    T, Z, Y, X = geom.lattice_shape
    psi = _psi((4, 3, 2, T, Z, Y * X // 2), seed=9)
    ref_op = dpk.pairs(jnp.float32, use_pallas=True,
                       pallas_interpret=True, precision_form="r12")
    ref = np.asarray(ref_op._d_to(psi, 0, jnp.float32))

    mesh = make_lattice_mesh(grid=(4, 2, 1, 1), n_src=1)
    sh = dpk.pairs(jnp.float32, use_pallas=True, pallas_interpret=True,
                   mesh=mesh, sharded_policy="xla_facefix",
                   precision_form="r12f")
    assert sh._precision_form == "r12"       # mesh downgrade
    assert sh.gauge_eo_pp[0].shape[1] == 2   # compressed storage kept
    x_s = jax.device_put(
        psi, NamedSharding(mesh, P(None, None, None, "t", "z", None)))
    out = np.asarray(jax.jit(lambda q: sh._d_to(q, 0, jnp.float32))(x_s))
    err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
    assert err < 3e-5, err

    for pform, served in (("fold", "full"), ("int8", "r12")):
        op = dpk.pairs(jnp.float32, use_pallas=True,
                       pallas_interpret=True, mesh=mesh,
                       sharded_policy="xla_facefix",
                       precision_form=pform)
        assert op._precision_form == served, pform
