"""Eigensolver tests: TRLM/IRAM vs dense/ARPACK references, deflation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse.linalg as ssl

from quda_tpu.fields.geometry import EVEN, LatticeGeometry
from quda_tpu.fields.spinor import ColorSpinorField, even_odd_split
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.models.dirac import apply_gamma5
from quda_tpu.models.wilson import DiracWilson, DiracWilsonPC
from quda_tpu.ops import blas
from quda_tpu.eig.deflation import DeflationSpace, deflated_guess
from quda_tpu.eig.iram import iram
from quda_tpu.eig.lanczos import EigParam, chebyshev_op, trlm
from quda_tpu.solvers.cg import cg

GEOM = LatticeGeometry((4, 4, 4, 4))
KAPPA = 0.125


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(101)
    gauge = GaugeField.random(key, GEOM).data
    dpc = DiracWilsonPC(gauge, GEOM, KAPPA)
    example = even_odd_split(
        ColorSpinorField.zeros(GEOM).data, GEOM)[0]
    shape = example.shape
    dim = int(np.prod(shape))

    def to_flat(v):
        return np.asarray(v).reshape(dim)

    def from_flat(a):
        return jnp.asarray(a.reshape(shape))

    mv = jax.jit(dpc.MdagM)
    linop = ssl.LinearOperator(
        (dim, dim),
        matvec=lambda a: to_flat(mv(from_flat(a.astype(np.complex128)))),
        dtype=np.complex128)
    return dpc, example, linop, from_flat


def test_trlm_smallest_vs_arpack(setup):
    dpc, example, linop, _ = setup
    k = 6
    want = np.sort(ssl.eigsh(linop, k=k, which="SA",
                             return_eigenvectors=False))
    param = EigParam(n_ev=k, n_kr=32, tol=1e-8, max_restarts=200)
    res = trlm(dpc.MdagM, example, param)
    assert res.converged
    assert np.allclose(res.evals[:k], want, rtol=1e-6)
    assert np.all(res.residua < 1e-6)


def test_trlm_chebyshev_accelerated(setup):
    dpc, example, linop, _ = setup
    k = 4
    want = np.sort(ssl.eigsh(linop, k=k, which="SA",
                             return_eigenvectors=False))
    # spectrum upper edge estimate for the filter window
    lmax = float(ssl.eigsh(linop, k=1, which="LA",
                           return_eigenvectors=False)[0])
    param = EigParam(n_ev=k, n_kr=24, tol=1e-8, max_restarts=100,
                     use_poly_acc=True, poly_deg=12,
                     a_min=float(want[-1]) * 2.0, a_max=1.05 * lmax)
    res = trlm(dpc.MdagM, example, param)
    assert res.converged
    assert np.allclose(res.evals[:k], want, rtol=1e-6)


def test_chebyshev_op_amplifies_low_modes(setup):
    dpc, example, _, _ = setup
    op = chebyshev_op(dpc.MdagM, 10, 1.0, 4.0)
    v = ColorSpinorField.gaussian(jax.random.PRNGKey(3), GEOM).data
    ve, _ = even_odd_split(v, GEOM)
    out = op(ve)
    assert np.isfinite(float(blas.norm2(out)))


def test_iram_nonhermitian(setup):
    """Restarted Arnoldi on the non-Hermitian PC Wilson operator: the
    largest-real-part eigenvalues (complex-conjugate pairs) must match
    ARPACK."""
    dpc, example, _, from_flat = setup
    shape = example.shape
    dim = int(np.prod(shape))
    mv = jax.jit(dpc.M)
    linop = ssl.LinearOperator(
        (dim, dim),
        matvec=lambda a: np.asarray(
            mv(jnp.asarray(a.astype(np.complex128).reshape(shape)))
        ).reshape(dim),
        dtype=np.complex128)
    k = 4
    # Oracle: ask ARPACK for 3x the wanted pairs with a fixed start vector
    # and keep the top k.  With k=4 exactly and a random v0, ARPACK itself
    # intermittently misses the leading conjugate pair on this clustered
    # spectrum (observed in round 1); the over-request makes it reliable.
    v0 = np.full(dim, 1.0 + 0.5j, dtype=np.complex128)
    want = ssl.eigs(linop, k=3 * k, which="LR", v0=v0,
                    return_eigenvectors=False)
    want = np.sort(want.real)[::-1][:k]
    param = EigParam(n_ev=k, n_kr=30, tol=1e-7, max_restarts=300,
                     spectrum="LR")
    res = iram(dpc.M, example, param)
    assert res.converged
    got = np.sort(np.asarray(res.evals).real)[::-1]
    assert np.allclose(got, want, rtol=1e-6)
    assert np.all(res.residua < 1e-5)


def test_iram_clustered_nonnormal():
    """IRAM on a deliberately non-normal dense operator with a clustered
    leading spectrum (the regime where naive restarting mis-routes pairs:
    reference lib/eig_iram.cpp keeps locked pairs through restarts)."""
    rng = np.random.default_rng(7)
    n = 192
    lam = np.concatenate([
        [2.0, 1.9995, 1.999, 1.9985],              # tight lead cluster
        rng.uniform(-1.0, 1.5, n - 4)])            # bulk
    S = np.eye(n) + 0.3 * rng.standard_normal((n, n)) / np.sqrt(n)
    A = jnp.asarray(S @ np.diag(lam) @ np.linalg.inv(S),
                    dtype=jnp.complex128)
    example = jnp.zeros((n,), jnp.complex128)
    param = EigParam(n_ev=4, n_kr=40, tol=1e-9, max_restarts=400,
                     spectrum="LR")
    res = iram(lambda v: A @ v, example, param)
    assert res.converged
    got = np.sort(np.asarray(res.evals).real)[::-1]
    assert np.allclose(got, np.sort(lam)[::-1][:4], rtol=1e-7)
    assert np.all(res.residua < 1e-6)


def test_deflation_cuts_iterations(setup):
    dpc, example, _, _ = setup
    param = EigParam(n_ev=8, n_kr=32, tol=1e-10, max_restarts=200)
    res = trlm(dpc.MdagM, example, param)
    assert res.converged
    b = even_odd_split(
        ColorSpinorField.gaussian(jax.random.PRNGKey(5), GEOM).data, GEOM)[0]
    space = DeflationSpace(res.evecs, jnp.asarray(res.evals))
    cold = cg(dpc.MdagM, b, tol=1e-10, maxiter=2000)
    x0 = deflated_guess(space, b)
    warm = cg(dpc.MdagM, b, x0=x0, tol=1e-10, maxiter=2000)
    assert int(warm.iters) < int(cold.iters)
    r2 = blas.norm2(b - dpc.MdagM(warm.x))
    assert float(jnp.sqrt(r2 / blas.norm2(b))) < 2e-10


def test_arpack_bridge_through_api():
    """eig_type='arpack' (the lib/arpack_interface.cpp analog) matches
    TRLM through the public eigensolve_quda entry point."""
    from quda_tpu.interfaces.params import (EigParamAPI, GaugeParam,
                                            InvertParam)
    from quda_tpu.interfaces.quda_api import (eigensolve_quda, init_quda,
                                              load_gauge_quda)
    geom = LatticeGeometry((4, 4, 4, 4))
    gauge = GaugeField.random(jax.random.PRNGKey(1), geom).data
    init_quda()
    load_gauge_quda(gauge, GaugeParam(X=geom.dims, cuda_prec="double"))
    ip = InvertParam(dslash_type="wilson", kappa=0.12,
                     solve_type="normop-pc", cuda_prec="double",
                     cuda_prec_sloppy="double")
    ep_a = EigParamAPI(eig_type="arpack", n_ev=4, spectrum="SR", tol=1e-8)
    vals_a, vecs_a = eigensolve_quda(ep_a, ip)
    ep_t = EigParamAPI(eig_type="trlm", n_ev=4, n_kr=32, spectrum="SR",
                       tol=1e-9, max_restarts=200)
    vals_t, _ = eigensolve_quda(ep_t, ip)
    assert np.allclose(np.sort(np.asarray(vals_a).real),
                       np.sort(np.asarray(vals_t).real)[:4], rtol=1e-6)
