"""Gauge observables, AD force correctness, HMC energy conservation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from quda_tpu.fields.geometry import LatticeGeometry
from quda_tpu.fields.gauge import GaugeField
from quda_tpu.gauge.action import (gauge_force, hmc_trajectory, improved_action,
                                   leapfrog, mom_action, omf2, random_momentum,
                                   traceless_hermitian, update_gauge,
                                   wilson_action)
from quda_tpu.gauge.observables import (energy, plaquette, polyakov_loop,
                                        qcharge, qcharge_density)
from quda_tpu.ops.su3 import dagger, expm_su3, mat_mul, trace

GEOM = LatticeGeometry((4, 4, 4, 4))


@pytest.fixture(scope="module")
def cfg():
    key = jax.random.PRNGKey(500)
    return GaugeField.random(key, GEOM, scale=0.5).data


def test_plaquette_unit_gauge():
    u = GaugeField.unit(GEOM).data
    mean, sp, tm = plaquette(u)
    assert np.allclose([float(mean), float(sp), float(tm)], 1.0)
    assert np.isclose(complex(polyakov_loop(u)).real, 1.0)


def test_plaquette_random_range(cfg):
    mean, sp, tm = plaquette(cfg)
    assert 0.0 < float(mean) < 1.0
    assert np.isclose(float(mean), (float(sp) + float(tm)) / 2.0)


def test_plaquette_gauge_invariance(cfg):
    """Plaquette must be invariant under random gauge transformations."""
    from quda_tpu.ops.shift import shift
    from quda_tpu.ops.su3 import random_su3
    g = random_su3(jax.random.PRNGKey(7), GEOM.lattice_shape)
    transformed = jnp.stack([
        mat_mul(mat_mul(g, cfg[mu]), dagger(shift(g, mu, +1)))
        for mu in range(4)])
    assert np.isclose(float(plaquette(transformed)[0]),
                      float(plaquette(cfg)[0]), atol=1e-12)


def test_qcharge_properties(cfg):
    q = float(qcharge(cfg))
    assert np.isfinite(q)
    dens = qcharge_density(cfg)
    assert dens.dtype in (jnp.float64, jnp.float32)
    # unit gauge: zero topological charge
    assert np.isclose(float(qcharge(GaugeField.unit(GEOM).data)), 0.0)


def test_force_matches_finite_difference(cfg):
    """dS/dtheta along a random su(3) direction vs finite differences."""
    beta = 5.5
    act = lambda u: wilson_action(u, beta)
    f = gauge_force(act, cfg)
    # force must be traceless Hermitian
    assert np.allclose(np.asarray(trace(f)), 0.0, atol=1e-10)
    assert np.allclose(np.asarray(f), np.asarray(dagger(f)), atol=1e-12)

    from quda_tpu.ops.su3 import random_hermitian_traceless
    q = random_hermitian_traceless(jax.random.PRNGKey(3), cfg.shape[:-2],
                                   dtype=cfg.dtype)
    eps = 1e-5
    up = mat_mul(expm_su3(eps * q), cfg)
    dn = mat_mul(expm_su3(-eps * q), cfg)
    fd = (float(act(up)) - float(act(dn))) / (2 * eps)
    # analytic: dS/dt = sum_a q_a f_a = 2 sum tr(Q F)
    ana = 2.0 * float(jnp.sum(trace(mat_mul(q, f)).real))
    assert np.isclose(fd, ana, rtol=1e-6), (fd, ana)


def test_improved_action_force_fd(cfg):
    act = lambda u: improved_action(u, 5.0, -1.0 / 12.0)
    f = gauge_force(act, cfg)
    from quda_tpu.ops.su3 import random_hermitian_traceless
    q = random_hermitian_traceless(jax.random.PRNGKey(9), cfg.shape[:-2],
                                   dtype=cfg.dtype)
    eps = 1e-5
    fd = (float(act(mat_mul(expm_su3(eps * q), cfg)))
          - float(act(mat_mul(expm_su3(-eps * q), cfg)))) / (2 * eps)
    ana = 2.0 * float(jnp.sum(trace(mat_mul(q, f)).real))
    assert np.isclose(fd, ana, rtol=1e-6)


def test_leapfrog_energy_scaling(cfg):
    """dH ~ O(dt^2): halving dt must cut |dH| by ~4 (reversible,
    symplectic integrator + correct force)."""
    beta = 5.5
    act = lambda u: wilson_action(u, beta)
    p0 = random_momentum(jax.random.PRNGKey(1), cfg.shape[:-2], cfg.dtype)

    def dh(dt, n):
        g1, p1 = leapfrog(act, cfg, p0, n, dt)
        return float(mom_action(p1) + act(g1) - mom_action(p0) - act(cfg))

    d1 = dh(0.0125, 32)
    d2 = dh(0.00625, 64)
    # second-order symplectic: ratio must approach 4
    assert 3.0 < abs(d1) / abs(d2) < 5.0
    assert abs(d2) < 0.1


def test_leapfrog_reversibility(cfg):
    act = lambda u: wilson_action(u, 5.5)
    p0 = random_momentum(jax.random.PRNGKey(2), cfg.shape[:-2], cfg.dtype)
    g1, p1 = leapfrog(act, cfg, p0, 6, 0.05)
    g2, p2 = leapfrog(act, g1, -p1, 6, 0.05)
    assert np.allclose(np.asarray(g2), np.asarray(cfg), atol=1e-9)
    assert np.allclose(np.asarray(p2), np.asarray(-p0), atol=1e-9)


def test_omf2_more_accurate_than_leapfrog(cfg):
    act = lambda u: wilson_action(u, 5.5)
    p0 = random_momentum(jax.random.PRNGKey(4), cfg.shape[:-2], cfg.dtype)
    g1, p1 = leapfrog(act, cfg, p0, 10, 0.05)
    dh_lf = abs(float(mom_action(p1) + act(g1) - mom_action(p0) - act(cfg)))
    g2, p2 = omf2(act, cfg, p0, 10, 0.05)
    dh_om = abs(float(mom_action(p2) + act(g2) - mom_action(p0) - act(cfg)))
    assert dh_om < dh_lf


def test_hmc_trajectory_runs(cfg):
    act = lambda u: wilson_action(u, 5.5)
    res = hmc_trajectory(jax.random.PRNGKey(10), act, cfg, n_steps=8,
                         dt=0.05, integrator=omf2)
    assert np.isfinite(float(res.dH))
    assert abs(float(res.dH)) < 1.0
    assert 0.0 < float(res.plaq) < 1.0
