"""Tier-1 surface of the unified static-analysis engine (ISSUE 14).

Three layers:

* **package cleanliness** — one parametrized test per registered rule:
  the repo itself must carry zero UNSUPPRESSED findings (suppressions
  carry their mandatory reasons).  All rules share ONE parse and ONE
  engine run per process (`analysis.run_package` is cached), which is
  the whole point of migrating the six ad-hoc lints onto the engine.
* **seeded fixtures** — per rule: `bad.py` must produce at least one
  unsuppressed finding (a pass that stops DETECTING fails here, not
  just a pass that stops running), `suppressed.py` must produce only
  suppressed findings, `clean.py` none.
* **engine mechanics** — the single-parse cache, suppression-line
  semantics, mandatory-reason enforcement, CLI exit codes.
"""

import os
import subprocess
import sys

import pytest

from quda_tpu import analysis

FIXDIR = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

RULES = analysis.rule_names()


@pytest.fixture(scope="module")
def package_result():
    return analysis.run_package()


# -- package cleanliness ----------------------------------------------------

@pytest.mark.parametrize("rule", RULES)
def test_rule_clean_on_package(package_result, rule):
    bad = [f for f in package_result.findings
           if f.rule == rule and not f.suppressed]
    assert not bad, (
        f"unsuppressed {rule} findings in the package:\n  "
        + "\n  ".join(f.render() for f in bad)
        + "\nfix the violation or suppress it in source with "
          "`# quda-lint: disable=" + rule + "  reason=<why>`")


def test_package_suppressions_all_carry_reasons(package_result):
    """Every suppressed finding surfaced a non-empty reason (the
    engine refuses reasonless disables via suppression-hygiene; this
    checks the carried-through reason text)."""
    for f in package_result.findings:
        if f.suppressed:
            assert f.reason and len(f.reason) > 10, f.render()


def test_engine_is_single_parse():
    """The shared index and the full-run result are process-cached:
    the per-rule tests above and the six legacy lint wrappers all
    reuse ONE parse (the speed contract of the migration)."""
    assert analysis.package_index() is analysis.package_index()
    assert analysis.run_package() is analysis.run_package()


# -- seeded fixtures --------------------------------------------------------

def _fixture_files(rule, prefix):
    d = os.path.join(FIXDIR, rule)
    if not os.path.isdir(d):
        return []
    out = []
    for dirpath, dirnames, filenames in os.walk(d):
        out += [os.path.join(dirpath, f) for f in filenames
                if f.startswith(prefix) and f.endswith(".py")]
    return sorted(out)


@pytest.mark.parametrize("rule", RULES)
def test_bad_fixture_detected(rule):
    paths = _fixture_files(rule, "bad")
    assert paths, f"no bad fixture for rule {rule} — every rule ships "\
                  "with a seeded violation that must fail"
    for path in paths:
        res = analysis.run(rules=[rule], paths=[path])
        bad = [f for f in res.findings if not f.suppressed]
        assert bad, (f"{rule} did not detect its seeded violation in "
                     f"{os.path.relpath(path, FIXDIR)} — the pass "
                     "runs but no longer detects")


@pytest.mark.parametrize("rule", RULES)
def test_suppressed_fixture_is_clean_but_found(rule):
    paths = _fixture_files(rule, "suppressed")
    if rule == "suppression-hygiene":
        pytest.skip("hygiene findings are deliberately unsuppressible")
    assert paths, f"no suppressed fixture for rule {rule}"
    for path in paths:
        res = analysis.run(rules=[rule], paths=[path])
        assert not res.unsuppressed, (
            f"suppression did not apply in {path}:\n"
            + "\n".join(f.render() for f in res.unsuppressed))
        sup = [f for f in res.findings if f.suppressed]
        assert sup, (f"{rule} found nothing at all in {path} — the "
                     "suppressed twin must still DETECT (suppressed) "
                     "findings")
        assert all(f.reason for f in sup)


@pytest.mark.parametrize("rule", RULES)
def test_clean_fixture_produces_nothing(rule):
    paths = _fixture_files(rule, "clean")
    assert paths, f"no clean fixture for rule {rule}"
    for path in paths:
        res = analysis.run(rules=[rule], paths=[path])
        assert not res.findings, (
            f"{rule} false-positives on its clean twin {path}:\n"
            + "\n".join(f.render() for f in res.findings))


# -- engine mechanics -------------------------------------------------------

def test_reasonless_suppression_is_a_finding():
    path = os.path.join(FIXDIR, "suppression-hygiene", "bad.py")
    res = analysis.run(rules=["suppression-hygiene"], paths=[path])
    assert any("reason is mandatory" in f.message
               for f in res.unsuppressed), res.findings


def test_unknown_rule_suppression_is_a_finding():
    path = os.path.join(FIXDIR, "suppression-hygiene",
                        "bad_unknown_rule.py")
    res = analysis.run(rules=["suppression-hygiene"], paths=[path])
    assert any("unknown rule" in f.message
               for f in res.unsuppressed), res.findings


def test_reasonless_suppression_does_not_suppress():
    """A disable without a reason must NOT silence the underlying
    finding — otherwise the mandatory-reason rule would be advisory."""
    path = os.path.join(FIXDIR, "suppression-hygiene", "bad.py")
    res = analysis.run(rules=["comms-ledger", "suppression-hygiene"],
                       paths=[path])
    rules_hit = {f.rule for f in res.unsuppressed}
    assert "suppression-hygiene" in rules_hit
    # the ppermute finding itself: the reasonless disable still names
    # the rule, so engine policy decides; we pin that AT LEAST the
    # hygiene finding keeps the file failing
    assert res.unsuppressed


def test_unknown_rule_selection_raises():
    with pytest.raises(KeyError):
        analysis.run(rules=["no-such-rule"])


def test_comment_only_suppression_targets_next_line(tmp_path):
    src = ("from jax import lax\n"
           "def f(x, p):\n"
           "    # quda-lint: disable=comms-ledger  reason=own-line "
           "comment covers the next line\n"
           "    return lax.ppermute(x, 'z', p)\n")
    p = tmp_path / "own_line.py"
    p.write_text(src)
    res = analysis.run(rules=["comms-ledger"], paths=[str(p)])
    assert res.findings and not res.unsuppressed


# -- CLI --------------------------------------------------------------------

def _cli(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "quda_tpu.analysis", *argv],
        capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_cli_package_exits_zero_and_writes_artifacts(tmp_path):
    tsv = tmp_path / "analysis.tsv"
    jsn = tmp_path / "analysis.json"
    r = _cli("--tsv", str(tsv), "--json", str(jsn))
    assert r.returncode == 0, r.stdout + r.stderr
    assert tsv.exists() and jsn.exists()
    import json
    doc = json.loads(jsn.read_text())
    assert doc["ok"] is True
    assert set(RULES) <= set(doc["rules"])


@pytest.mark.slow
def test_cli_exits_nonzero_on_each_seeded_violation():
    for rule in RULES:
        for path in _fixture_files(rule, "bad"):
            r = _cli("--rules", rule, "--paths", path)
            assert r.returncode == 1, (
                f"CLI passed on seeded violation {path}:\n{r.stdout}")


def test_cli_inprocess_exit_codes(capsys):
    """The CLI main() contract without subprocess cost: nonzero on a
    seeded violation, zero on its clean twin."""
    from quda_tpu.analysis.__main__ import main
    bad = os.path.join(FIXDIR, "comms-ledger", "bad.py")
    clean = os.path.join(FIXDIR, "comms-ledger", "clean.py")
    assert main(["--rules", "comms-ledger", "--paths", bad]) == 1
    assert main(["--rules", "comms-ledger", "--paths", clean]) == 0
    capsys.readouterr()


# -- artifacts + metrics wiring --------------------------------------------

def test_artifacts_and_metrics_surface(tmp_path, package_result):
    paths = analysis.save_artifacts(package_result, str(tmp_path))
    assert os.path.exists(paths["analysis.tsv"])
    assert os.path.exists(paths["analysis.json"])
    with open(paths["analysis.tsv"]) as fh:
        header = fh.readline()
    assert header.startswith("rule\tpath\tline")
    # metric mirroring (fleet-report Static analysis line)
    from quda_tpu.obs import metrics as omet
    omet.stop(flush_files=False)
    omet.start(str(tmp_path))
    try:
        analysis.emit_metrics(package_result)
        snap = omet.snapshot()
        rules_seen = {dict(labels).get("rule")
                      for (name, labels) in snap["gauges"]
                      if name == "analysis_findings"}
        assert set(RULES) <= rules_seen
        from quda_tpu.obs import report as orep
        text = orep.render(snap)
        assert "Static analysis" in text
    finally:
        omet.stop(flush_files=False)


def test_trace_safe_field_exists_on_every_knob():
    """The rode-along contract: trace-safety policy lives in the knob
    registry (utils/config.Knob.trace_safe), not in a pass-local
    allowlist."""
    from quda_tpu.utils import config as qconf
    for name, knob in qconf.knobs().items():
        assert isinstance(knob.trace_safe, bool), name
